"""Headline benchmark: fused-ABFT huge kernel at M=N=K=4096 on real TPU.

Prints ONE JSON line:
  metric      abft_kernel_huge GFLOPS at 4096 with reference-like injection
  vs_baseline ratio vs the reference's abft_kernel_huge on sm_80
              (4005 GFLOPS, reference README.md:53 / BASELINE.md)

Also embeds context fields: XLA f32 dot GFLOPS on the same chip and the
fraction of it we reach (north-star target >= 0.80, BASELINE.json), the
plain (non-FT) kernel GFLOPS, and the fused-ABFT overhead.

``--serve [--smoke]`` runs the fault-tolerant SERVING goodput bench
instead (``serve_main`` — no supervisor/worker split): the
``ft_sgemm_tpu.serve`` engine prewarms a shape bucket set, a load
generator drives ragged requests with SDC injection through the
continuous-batching queue, and the JSON line reports
goodput-under-injection (correct results/second) with p50/p99 latency,
throughput, and the retry/fault counters in context. SIGTERM drains and
emits a ``partial`` artifact; the streamed timeline carries per-batch
spans and progress points for harder kills.

``--chaos [--smoke]`` runs the fault-model coverage CAMPAIGN instead
(``chaos_main``): every declared fault model (``contracts.FAULT_MODELS``)
compiled onto the existing actuators, swept across the serve / block /
train / pool workloads, and the JSON line reports overall detection rate
with the per-model coverage matrix + MTBF-derived policy picks in
``context.chaos`` (the ledger ingests them as ``chaos.*`` measurements
for ``cli trend`` gating).

``--tuned`` adds an ``ft_tuned`` stage: the same injected headline kernel
dispatched through the autotuner's tile cache (``ft_sgemm_tpu.tuner`` —
seed it with ``python -m ft_sgemm_tpu.cli tune 4096`` in a prior window),
so the artifact reports heuristic-vs-tuned GFLOPS side by side
(``context.abft_tuned_gflops`` / ``context.tuned_block``). Fails soft:
with no cache entry the stage records why and the headline is untouched.

Architecture (round-3 rework): a SUPERVISOR / WORKER split.

Rounds 1 and 2 both lost their number to the axon TPU tunnel:
``BENCH_r01.json`` rc=1 (backend init raised), ``BENCH_r02.json`` rc=124
(backend init HUNG — two xla_bridge warnings 25 minutes apart, then the
driver's SIGKILL).  A hang inside ``jax.devices()`` blocks in C and cannot
be interrupted from Python in-process, so no amount of in-process retry or
deadline checking protects the JSON line.  Therefore:

* The supervisor (this file's ``main``) never imports jax.  It launches the
  measurement as a child subprocess in its own process group, enforces a
  hard per-attempt budget (SIGTERM, then SIGKILL), relaunches while the
  headline is missing and budget remains, and ALWAYS prints the JSON line
  assembled from whatever stage records landed on disk.
* The worker (``--worker RECORDS``) appends one JSON record per completed
  stage to the records file (fsync'd), headline FIRST, so a kill at any
  moment loses at most the stage in flight.  A fresh worker resumes: it
  reads the records file and skips completed stages.
* The supervisor handles SIGTERM/SIGINT by killing the worker group and
  flushing the JSON line before exiting — so even a driver that times the
  whole script out gets a parseable artifact as long as it sends SIGTERM
  before SIGKILL.

Budget knobs (env): ``FT_SGEMM_BENCH_DEADLINE`` total seconds (default 900,
well under any plausible driver window), ``FT_SGEMM_BENCH_WORKER_MAX`` per
attempt (default 480), ``FT_SGEMM_BENCH_MARGIN`` reserved for final
assembly (default 30), ``FT_SGEMM_BENCH_GRACE`` SIGTERM->SIGKILL (default
5), ``FT_SGEMM_BENCH_MIN_ATTEMPT`` smallest budget worth launching a
worker for (default 90), ``FT_SGEMM_BENCH_TIMELINE`` span-timeline path
(default ``<records>.timeline.jsonl`` — the worker streams
stage/attempt/compile spans there, flushed per event, and the supervisor
both appends kill markers and SALVAGES completed stage values from it
when a deadline kill would otherwise null the artifact; render with
``python -m ft_sgemm_tpu.cli timeline``), ``FT_SGEMM_BENCH_RECORDS``
records path (default:
a repo-local ``.bench/`` file keyed by the code version, so runs of the
same code share measurements — an earlier monitoring run's stages resume
into the scoring run; an flock serializes concurrent runs, and different
code can never inherit stale numbers), ``FT_SGEMM_COMPILE_CACHE``
persistent XLA compile-cache location (default: the shared
``~/.cache/ft_sgemm_tpu/jaxcache`` alongside the tuner cache — XLA keys
entries by module content, so sharing across code versions is safe;
``0``/``off`` disables; see ``ft_sgemm_tpu/perf/compile_cache.py``),
``FT_SGEMM_LEDGER`` run-ledger path — every emitted artifact line
(headline, ``--smoke``, ``--serve``; null and partial ones included)
also appends one distilled row to the longitudinal run ledger
(``ft_sgemm_tpu/perf/ledger.py``; ``FT_SGEMM_LEDGER_RUN_ID`` overrides
the timestamp-derived run id), feeding ``cli history`` /
``cli trend --gate``.
The worker records the cache's enable status and end-of-run
hit/miss/bytes-written stats (``context.compile_cache``), every stage
span carries a compile/execute wall split, and the RunReport embeds the
per-run phase attribution (``ft_sgemm_tpu/perf/wallclock.py``) — so a
deadline-killed artifact now says how much of its budget went to XLA
compile and whether a relaunch would resume warm. Warm the cache ahead
of a window with ``python -m ft_sgemm_tpu.cli prewarm``.

Attempt budgeting (round-4 rework): BENCH_r03 lost its number because two
fixed 480 s attempts were each killed while the backend was SLOWLY
initializing (~9 min — the two xla_bridge warnings in the artifact tail
are 8 minutes apart: progress, not a dead hang).  Two counters now:

* The worker runs a daemon HEARTBEAT thread (started before any jax
  import) that touches ``<records>.hb`` every few seconds.  The
  supervisor, once an attempt exceeds its nominal budget, keeps extending
  it while the heartbeat file stays fresh — a schedulable worker mid-init
  is better odds than a fresh relaunch that re-pays init against the
  same tunnel.  Extension is bounded: stale heartbeat (GIL wedged /
  process dead) kills immediately, and a cap of ``EXTEND_MAX`` (default
  one extra nominal budget) bounds how long mere liveness can hold an
  attempt — a DEAD tunnel hang blocks in a GIL-releasing C read and
  heartbeats forever, and must not forfeit every relaunch a large
  deadline could still afford.  The hard deadline bounds everything.
* When the remaining budget cannot fit two nominal attempts, the
  supervisor sizes ONE attempt to all of it instead of launching two
  doomed fixed-budget ones (900 s deadline => a single ~870 s attempt,
  which survives a ~9-minute init with time to measure).
"""

import contextlib
import json
import os
import signal
import subprocess
import sys
import tempfile
import time
import traceback

SIZE = 4096
REFERENCE_ABFT_HUGE_GFLOPS = 4005.0  # sm_80, reference README.md:53
_T0 = time.monotonic()
_DEADLINE = float(os.environ.get("FT_SGEMM_BENCH_DEADLINE", 900.0))
_WORKER_MAX = float(os.environ.get("FT_SGEMM_BENCH_WORKER_MAX", 480.0))
_MARGIN = float(os.environ.get("FT_SGEMM_BENCH_MARGIN", 30.0))
_GRACE = float(os.environ.get("FT_SGEMM_BENCH_GRACE", 5.0))
_MIN_ATTEMPT = float(os.environ.get("FT_SGEMM_BENCH_MIN_ATTEMPT", 90.0))
# An attempt past its nominal budget survives while the worker's heartbeat
# file is younger than this (3+ missed beats = stale).
_HB_FRESH = float(os.environ.get("FT_SGEMM_BENCH_HB_FRESH", 45.0))
# ...but extension is CAPPED: a heartbeat proves the worker is
# schedulable, not that init progresses — a dead tunnel hang in a
# GIL-releasing C read beats forever. Capping extension at one extra
# nominal budget keeps the slow-init fix (480 s + 480 s covers a ~9-min
# init with time to measure) without letting one wedged worker forfeit
# every relaunch a large deadline could still afford. (Under the default
# 900 s deadline the single-long-attempt sizing governs instead.)
_EXTEND_MAX = float(os.environ.get("FT_SGEMM_BENCH_EXTEND_MAX",
                                   _WORKER_MAX))


def _time_left() -> float:
    return _DEADLINE - (time.monotonic() - _T0)


# --------------------------------------------------------------------------
# Run timeline: streamed span log (telemetry/timeline.py), loaded by FILE
# PATH so the supervisor keeps its never-imports-jax guarantee (importing
# the ft_sgemm_tpu package root would pull jax in). Everything here is
# best-effort: a missing/unwritable timeline degrades observability, never
# the JSON line.
# --------------------------------------------------------------------------

_TIMELINE_MOD = None


def _load_timeline_mod():
    """The telemetry.timeline module loaded standalone (stdlib-only by
    contract — see its docstring). None when unloadable."""
    global _TIMELINE_MOD
    if _TIMELINE_MOD is not None:
        return _TIMELINE_MOD
    try:
        import importlib.util

        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "ft_sgemm_tpu", "telemetry", "timeline.py")
        spec = importlib.util.spec_from_file_location("_ft_timeline", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _TIMELINE_MOD = mod
    except Exception:  # noqa: BLE001 — observability must not kill the run
        _TIMELINE_MOD = None
    return _TIMELINE_MOD


def _timeline_path(records_path):
    env = os.environ.get("FT_SGEMM_BENCH_TIMELINE")
    if env:
        return env
    return (records_path + ".timeline.jsonl") if records_path else None


_LEDGER_MOD = None


def _load_ledger_mod():
    """perf/ledger.py loaded standalone (stdlib-only by contract, same
    file-path discipline as the timeline module). None when unloadable."""
    global _LEDGER_MOD
    if _LEDGER_MOD is not None:
        return _LEDGER_MOD
    try:
        import importlib.util

        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "ft_sgemm_tpu", "perf", "ledger.py")
        spec = importlib.util.spec_from_file_location("_ft_ledger", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _LEDGER_MOD = mod
    except Exception:  # noqa: BLE001 — observability must not kill the run
        _LEDGER_MOD = None
    return _LEDGER_MOD


_TREND_MOD = None


def _load_trend_mod():
    """perf/trend.py loaded standalone (stdlib-only by contract, same
    file-path discipline as the timeline/ledger modules). None when
    unloadable."""
    global _TREND_MOD
    if _TREND_MOD is not None:
        return _TREND_MOD
    try:
        import importlib.util

        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "ft_sgemm_tpu", "perf", "trend.py")
        spec = importlib.util.spec_from_file_location("_ft_trend", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _TREND_MOD = mod
    except Exception:  # noqa: BLE001 — observability must not kill the run
        _TREND_MOD = None
    return _TREND_MOD


# Flat per-rung wall margin (seconds) used when the ledger holds no
# history for a rung — the pre-ISSUE-13 behavior, kept as the floor.
_RUNG_BUDGET_FLOOR = 30.0


def _headline_rung_budgets(live, labels, default=_RUNG_BUDGET_FLOOR):
    """Per-rung wall budgets from the run ledger's per-stage history.

    For each ladder rung, predict its wall as ``mean + 2*std`` of the
    ``stage[ft_headline[<label>]].seconds`` series on THIS platform
    (``perf/trend.py::stage_wall_budget``), falling back to the
    aggregate ``stage[ft_headline].seconds`` series, then to the flat
    ``default`` — which also FLOORS every prediction, so a freak
    0.2 s history can never admit a rung into a 1 s remainder. Best
    effort by construction: no ledger / no history = the historical
    flat margin.
    """
    out = {label: float(default) for label in labels}
    path = os.environ.get("FT_SGEMM_LEDGER")
    lmod, tmod = _load_ledger_mod(), _load_trend_mod()
    if not path or not os.path.exists(path) or lmod is None \
            or tmod is None:
        return out
    try:
        entries = lmod.dedup_entries(lmod.read_ledger(path))
        platform = (live.get("device_kind") or live.get("platform_used")
                    or "?")
        for label in labels:
            for stage in (f"ft_headline[{label}]", "ft_headline"):
                b = tmod.stage_wall_budget(entries, stage, platform)
                if b is not None:
                    out[label] = max(float(default), float(b))
                    break
    except Exception:  # noqa: BLE001 — budgeting is an accelerant only
        pass
    return out


def _order_headline_ladder(ladder, rec):
    """Highest-value-missing-rung-first ordering of the headline ladder.

    The ladder list is already value-ordered (flagship first); rungs a
    previous attempt (or the ledger resume) already banked under their
    ``ft_headline[<label>]`` record move to the BACK, preserving value
    order within each group — so the single highest-value rung still
    missing always runs first against the warm compile cache, and a
    banked rung is only consulted as a promotion fallback (ROADMAP
    item 1: an attempt cannot die null while any rung is measurable or
    banked).
    """
    missing = [r for r in ladder
               if not rec.done(f"ft_headline[{r[0]}]")]
    banked = [r for r in ladder if rec.done(f"ft_headline[{r[0]}]")]
    return missing + banked


_LINT_FACTS = False  # False = not yet run; None = unavailable


def _lint_facts():
    """``{"findings", "seconds"}`` from one run of the static contract
    checker (ft_sgemm_tpu/lint/core.py, path-loaded — stdlib-only by
    contract, same discipline as the timeline/ledger modules), memoized
    per process. Rides the RunReport manifest so the ledger's
    ``lint.findings`` / ``lint.seconds`` series track checker health
    longitudinally like any other measurement. None when the source
    tree is not alongside this file (an installed wheel) or the checker
    fails — observability must not fail the run."""
    global _LINT_FACTS
    if _LINT_FACTS is not False:
        return _LINT_FACTS
    try:
        import importlib.util

        root = os.path.dirname(os.path.abspath(__file__))
        path = os.path.join(root, "ft_sgemm_tpu", "lint", "core.py")
        spec = importlib.util.spec_from_file_location("_ft_lint", path)
        mod = importlib.util.module_from_spec(spec)
        # Registered before exec: dataclasses (py3.10, PEP 563 strings)
        # resolves the defining module through sys.modules.
        sys.modules[spec.name] = mod
        spec.loader.exec_module(mod)
        facts = mod.lint_facts(root)
        _LINT_FACTS = {"findings": facts["findings"],
                       "seconds": facts["seconds"]}
    except Exception:  # noqa: BLE001 — observability must not kill the run
        _LINT_FACTS = None
    return _LINT_FACTS


# Ledger measurement keys <-> worker stage names for the headline rung
# set: the abft_kernel_huge measurements a fresh worker may RESUME from
# the run ledger instead of re-measuring. Keys are the artifact-context
# spellings perf/ledger.py::extract_measurements banks (the metric key
# itself carries the headline).
LEDGER_RESUME_STAGES = {
    "abft_kernel_huge_gflops_4096": "ft_headline",
    "xla_dot_gflops": "xla_dot",
    "kernel_sgemm_huge_gflops": "plain_huge",
    "abft_rowcol_gflops": "ft_rowcol",
    "abft_rowcol_mxu_gflops": "ft_rowcol_mxu",
    "abft_fused_gflops": "ft_fused",
    "bf16_abft_huge_gflops": "bf16_abft",
    "bf16_abft_fused_gflops": "bf16_fused",
    "bf16_sgemm_huge_gflops": "bf16_plain",
    "bf16_xla_dot_gflops": "bf16_xla",
}


def _ledger_fresh_values(git_rev, platform_used, device_kind,
                         ledger_path=None):
    """Headline-rung values already banked in the run ledger for THIS
    exact identity: ``{stage: {"value", "run_id"}}`` from the freshest
    (latest-appended, deduped) ledger rows whose (git rev, platform
    used, device kind) all match. A killed run's completed rungs reach
    the ledger via ``_ledger_append`` even when the records file is
    gone, so a relaunch resumes them instead of forfeiting them
    (ROADMAP item 1). Identity-strict by construction: a different rev,
    a dirty tree (``-dirty`` rev), or another device kind never
    matches. Best-effort: any failure returns {}."""
    path = ledger_path or os.environ.get("FT_SGEMM_LEDGER")
    if not path or not git_rev or not os.path.exists(path):
        return {}
    mod = _load_ledger_mod()
    if mod is None:
        return {}
    try:
        entries = mod.dedup_entries(mod.read_ledger(path))
    except Exception:  # noqa: BLE001 — resume is an accelerant only
        return {}
    out = {}
    for e in entries:  # append order: later rows supersede earlier
        if e.get("kind") != "bench" or e.get("git_rev") != git_rev:
            continue
        p = e.get("platform") or {}
        if p.get("used") != platform_used \
                or p.get("device_kind") != device_kind:
            continue
        meas = e.get("measurements") or {}
        for key, stage in LEDGER_RESUME_STAGES.items():
            m = meas.get(key)
            v = m.get("value") if isinstance(m, dict) else None
            if isinstance(v, (int, float)):
                out[stage] = {"value": float(v),
                              "run_id": e.get("run_id")}
    return out


def _ledger_resume_stages(rec, tl, live):
    """Seed the records with ledger-banked rungs (see
    :func:`_ledger_fresh_values`); each skipped rung logs the NAMED
    ``skipped_fresh_in_ledger`` reason — in the records (so the emit's
    resumed-stage provenance sees it) and as a timeline point."""
    try:
        from ft_sgemm_tpu.perf.report import _git_rev

        rev = _git_rev()
    except Exception:  # noqa: BLE001
        rev = None
    fresh = _ledger_fresh_values(rev, live.get("platform_used"),
                                 live.get("device_kind"))
    if not fresh:
        return None
    skipped = []
    for stage, rec_val in sorted(fresh.items()):
        if rec.done(stage):
            continue
        value = rec_val["value"]
        if stage == "ft_headline":
            value = {"gflops": value,
                     "strategy": f"ledger:{rec_val['run_id']}"}
        rec.ok(stage, value)
        skipped.append(stage)
        tl.point("stage", stage, note="skipped_fresh_in_ledger",
                 run_id=rec_val["run_id"])
        sys.stderr.write(
            f"bench worker: {stage}: skipped_fresh_in_ledger "
            f"(run {rec_val['run_id']}, rev {rev})\n")
    if skipped:
        rec.ok("ledger_resume", {"reason": "skipped_fresh_in_ledger",
                                 "git_rev": rev, "stages": skipped})
    return {"stages": skipped, "git_rev": rev}


def _ledger_append(artifact):
    """Append the just-emitted artifact line to the run ledger when
    ``FT_SGEMM_LEDGER=`` names one. Best-effort by construction: the
    ledger row is observability, the printed JSON line is the contract —
    nothing here may fail the run. ``FT_SGEMM_LEDGER_RUN_ID`` overrides
    the timestamp-derived run id (CI sets it to the workflow run)."""
    path = os.environ.get("FT_SGEMM_LEDGER")
    if not path:
        return
    try:
        mod = _load_ledger_mod()
        if mod is None or not isinstance(artifact, dict):
            return
        run_id = (os.environ.get("FT_SGEMM_LEDGER_RUN_ID")
                  or f"{artifact.get('metric') or 'run'}-"
                     f"{time.strftime('%Y%m%d-%H%M%S')}")
        mod.append(path, mod.ingest(artifact, run_id=run_id,
                                    source="bench.py"))
    except Exception:  # noqa: BLE001
        pass


class _NoTimeline:
    """Recorder stand-in when the timeline module failed to load."""

    def point(self, *a, **k):
        pass

    @contextlib.contextmanager
    def span(self, *a, **k):
        yield {}

    path = None


def _make_timeline(records_path):
    mod = _load_timeline_mod()
    path = _timeline_path(records_path)
    if mod is None or path is None:
        return _NoTimeline()
    try:
        return mod.TimelineRecorder(path)
    except Exception:  # noqa: BLE001
        return _NoTimeline()


def _tl_point(kind, name, **fields):
    """Supervisor-side point event (kill markers): opened per write so a
    signal handler can emit without any shared recorder state."""
    mod = _load_timeline_mod()
    path = _timeline_path(_RECORDS_PATH)
    if mod is None or path is None:
        return
    try:
        mod.TimelineRecorder(path).point(kind, name, **fields)
    except Exception:  # noqa: BLE001
        pass


def _read_timeline_summary():
    """Summarize the run's streamed timeline, or None."""
    mod = _load_timeline_mod()
    path = _timeline_path(_RECORDS_PATH)
    if mod is None or path is None:
        return None
    try:
        records = mod.read_timeline(path)
        return mod.summarize_timeline(records) if records else None
    except Exception:  # noqa: BLE001
        return None


def _attempt_budget(remaining):
    """Nominal per-attempt budget given the remaining run budget.

    When the remainder can't fit two nominal attempts, give ONE attempt
    everything: two fixed 480 s attempts under a 900 s deadline guarantee
    neither survives a ~9-minute backend init (the BENCH_r03 failure),
    while one 870 s attempt does."""
    if remaining < 2 * _WORKER_MAX:
        return remaining
    return _WORKER_MAX


# --------------------------------------------------------------------------
# Stage records: one JSON object per line, later lines win.
# {"name": str, "ok": true, "value": any} | {"name": str, "ok": false,
#  "error": str}
# --------------------------------------------------------------------------

def _read_records(path):
    values, errors = {}, {}
    try:
        # errors="replace": a SIGKILL mid-write can tear a multi-byte UTF-8
        # sequence; decoding must never take down the emit path.
        with open(path, errors="replace") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # torn write from a killed worker
                if not isinstance(rec, dict):
                    continue  # stray scalar/array line in a resumed file
                name = rec.get("name")
                if rec.get("ok"):
                    values[name] = rec.get("value")
                    errors.pop(name, None)
                else:
                    errors[name] = rec.get("error", "unknown")
    except (OSError, ValueError):
        pass
    return values, errors


# Context stages the worker wants beyond the headline; _worker_rc derives
# the supervisor-facing exit status from the records alone.
WANTED_STAGES = ("backend", "xla_dot", "plain_huge", "ft_rowcol",
                 "ft_rowcol_mxu", "ft_fused", "bf16_abft", "bf16_fused",
                 "bf16_plain", "bf16_xla")


def _worker_rc(rec):
    """rc protocol: 0 = every stage recorded (supervisor stops
    relaunching), 3 = headline safe but context stages missing (supervisor
    may relaunch a resuming worker while budget remains), 1 = no
    headline."""
    if not rec.done("ft_headline"):
        return 1
    return 0 if all(rec.done(w) for w in WANTED_STAGES) else 3


class Recorder:
    """Append-only, fsync'd stage log shared across worker attempts."""

    def __init__(self, path):
        self.path = path
        self.values, self.errors = _read_records(path)

    def done(self, name):
        return name in self.values

    def _write(self, rec):
        # Best-effort: an unwritable records file (disk full, bad
        # user-supplied path) must degrade to losing persistence, never
        # raise into a crash handler that is itself trying to record.
        try:
            # A SIGKILLed predecessor can leave a torn, newline-less
            # tail; appending directly would glue this record onto the
            # unparseable line and lose it. Start fresh in that case.
            lead = ""
            try:
                with open(self.path, "rb") as f:
                    f.seek(0, os.SEEK_END)
                    if f.tell() > 0:
                        f.seek(-1, os.SEEK_END)
                        if f.read(1) != b"\n":
                            lead = "\n"
            except OSError:
                pass
            with open(self.path, "a") as f:
                f.write(lead + json.dumps(rec) + "\n")
                f.flush()
                os.fsync(f.fileno())
        except OSError as e:
            sys.stderr.write(f"bench: records write failed ({e}); "
                             f"record kept in memory only: {rec}\n")

    def ok(self, name, value):
        self.values[name] = value
        self.errors.pop(name, None)
        self._write({"name": name, "ok": True, "value": value})

    def fail(self, name, error):
        self.errors[name] = error
        self._write({"name": name, "ok": False, "error": str(error)})

    def reset(self):
        """Discard all records (truncate the file, clear state) — used
        when existing records are invalid for this run (wrong backend).
        Writes a fresh _reset_token: the supervisor treats a token it did
        NOT see in its pre-run snapshot as proof that nothing resumed,
        even for stages whose remeasured values happen to coincide (e.g.
        backend-independent constants)."""
        self.values, self.errors = {}, {}
        try:
            with open(self.path, "w"):
                pass
        except OSError:
            pass
        self.ok("_reset_token", os.urandom(8).hex())


# --------------------------------------------------------------------------
# Supervisor
# --------------------------------------------------------------------------

_CHILD = None
_EMITTED = False
_FINAL_RC = None
_RECORDS_PATH = None
_ATTEMPTS = 0
_PRE_VALUES = {}     # stage records that pre-dated this run (transparency)
_LOCK_FH = None      # held for process lifetime (see _acquire_run_lock)


def _worker_output():
    """A real fd for worker stdout/stderr (keeps the supervisor's stdout
    clean for the JSON line; worker chatter lands in the artifact tail)."""
    try:
        sys.stderr.fileno()
        return sys.stderr
    except Exception:  # noqa: BLE001 — pytest capture objects lack fileno
        return subprocess.DEVNULL


class _HbTracker:
    """Heartbeat freshness from mtime CHANGE against the monotonic clock.

    Comparing mtime to time.time() directly would let a forward NTP step
    larger than _HB_FRESH make a live worker look stale — re-creating the
    mid-init kill this machinery exists to prevent. Instead: fresh iff
    the mtime advanced within the last _HB_FRESH monotonic seconds."""

    ABSENT, FRESH, STALE = "absent", "fresh", "stale"

    def __init__(self, hb_path):
        self.hb_path = hb_path
        self.mtime = None
        self.seen = None
        self.start = time.monotonic()

    def status(self):
        now = time.monotonic()
        try:
            mt = os.path.getmtime(self.hb_path)
        except OSError:
            if self.mtime is not None:
                return self.STALE  # was beating, file vanished
            # Startup grace: a loaded machine can take seconds to exec
            # the worker before its first beat lands — absence only
            # counts against the worker after a full freshness window.
            return (self.FRESH if now - self.start < _HB_FRESH
                    else self.ABSENT)
        if mt != self.mtime:
            self.mtime, self.seen = mt, now
            return self.FRESH
        return self.FRESH if now - self.seen < _HB_FRESH else self.STALE


def _wait_with_heartbeat(attempt_t0, budget, hb_path):
    """Wait out one worker attempt; returns its rc or a kill reason.

    Past the nominal budget the attempt EXTENDS while the heartbeat file
    stays fresh: a slowly-initializing backend is progress enough
    (heartbeat proves the worker is at least schedulable), and
    relaunching against the same tunnel only re-pays init. Extension is
    bounded three ways: stale heartbeat, the _EXTEND_MAX cap (liveness
    is not progress — a dead tunnel hang heartbeats forever and must not
    forfeit every relaunch), and the supervisor's hard deadline."""
    hb = _HbTracker(hb_path)
    while True:
        try:
            return _CHILD.wait(timeout=2.0)
        except subprocess.TimeoutExpired:
            pass
        # Poll every tick (not only past budget): freshness is defined by
        # mtime CHANGE, so the tracker needs observations to change from.
        status = hb.status()
        if _time_left() <= _MARGIN:
            _kill_child()
            reason = "killed (supervisor deadline reached)"
            _tl_point("kill", reason)
            return reason
        over = time.monotonic() - attempt_t0 - budget
        if over < 0:
            continue
        if over >= _EXTEND_MAX:
            _kill_child()
            reason = ("killed (per-attempt budget and heartbeat-extension "
                      "cap exhausted)")
            _tl_point("kill", reason)
            return reason
        if status == hb.FRESH:
            continue  # worker alive past budget: extend the attempt
        _kill_child()
        reason = f"killed (per-attempt budget exhausted, heartbeat {status})"
        _tl_point("kill", reason)
        return reason


def _kill_child():
    global _CHILD
    proc = _CHILD
    if proc is None or proc.poll() is not None:
        return
    try:
        os.killpg(proc.pid, signal.SIGTERM)
    except (ProcessLookupError, PermissionError):
        return
    try:
        proc.wait(timeout=_GRACE)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
        try:
            proc.wait(timeout=5)
        except subprocess.TimeoutExpired:
            pass


def _emit(values, errors, extra_errors=None):
    """Assemble and print THE json line from stage records. Returns rc.

    Signal-safe: SIGTERM/SIGINT are masked during assembly+print so the
    handler (which also funnels here) cannot interrupt mid-emit and
    os._exit before the line lands; a second call after a completed emit
    returns the latched rc instead of clobbering the contract.
    """
    global _EMITTED, _FINAL_RC
    if _EMITTED:
        return _FINAL_RC if _FINAL_RC is not None else 1
    try:
        old_mask = signal.pthread_sigmask(
            signal.SIG_BLOCK, {signal.SIGTERM, signal.SIGINT})
    except (AttributeError, ValueError, OSError):
        old_mask = None
    try:
        if _EMITTED:
            return _FINAL_RC if _FINAL_RC is not None else 1
        _EMITTED = True
        try:
            _FINAL_RC = _emit_locked(values, errors, extra_errors)
        except Exception as e:  # noqa: BLE001 — a line MUST still print
            print(json.dumps({
                "metric": "abft_kernel_huge_gflops_4096", "value": None,
                "unit": "GFLOPS", "vs_baseline": None,
                "context": {"errors": {
                    "emit": f"{type(e).__name__}: {e}"}},
            }), flush=True)
            sys.stderr.write(traceback.format_exc())
            _FINAL_RC = 1
        return _FINAL_RC
    finally:
        if old_mask is not None:
            signal.pthread_sigmask(signal.SIG_SETMASK, old_mask)


def _emit_locked(values, errors, extra_errors=None):
    errors = dict(errors)
    errors.update(extra_errors or {})

    ft_rec = values.get("ft_headline")
    # What the weighted ladder itself measured (pre-override, for context).
    ladder_gflops = ft_rec.get("gflops") if isinstance(ft_rec, dict) else ft_rec
    ladder_strategy = (ft_rec.get("strategy") if isinstance(ft_rec, dict)
                       else None)
    # The headline is the BEST measured correcting fused-ABFT variant —
    # rowcol and fused qualify as "abft_kernel_huge" exactly as the
    # weighted ladder does (all correct injected faults in-kernel; the
    # reference's flagship row is likewise its best FT kernel). Every
    # per-variant number stays visible in context.
    ft, strategy = _best_measurement(values)
    # Kill-safe salvage (the BENCH_r05 null-artifact class): when this
    # run's records hold no promotable measurement, read the worker's
    # STREAMED timeline partials — every completed stage's value landed
    # on disk before the next stage began — and emit the best completed
    # measurement instead of null, marked ``context.partial`` below.
    tl_summary = _read_timeline_summary()
    salvaged = False
    if ft is None and tl_summary:
        merged = dict(values)
        for name, v in (tl_summary.get("stage_values") or {}).items():
            merged.setdefault(name, v)
        ft_s, strat_s = _best_measurement(merged)
        if ft_s is not None:
            ft, strategy = ft_s, strat_s
            salvaged = True
            values = merged  # salvaged stages join the context rows
    context = {}
    if strategy:
        context["strategy"] = strategy
    if ladder_gflops is not None and ladder_gflops != ft:
        # The overridden ladder measurement stays visible too.
        context["abft_weighted_gflops"] = round(ladder_gflops, 1)
        if ladder_strategy:
            context["abft_weighted_strategy"] = ladder_strategy
    backend = values.get("backend")
    if isinstance(backend, dict):
        context.update(backend)
    # Compile-cache observability: the worker's setup status (superseded
    # by end-of-run hit/miss/bytes stats — later record lines win), with
    # the enabled/reason pair flattened so a reader never has to guess
    # why caching was off.
    cc = values.get("compile_cache")
    if isinstance(cc, dict):
        context["compile_cache"] = cc
        context["compile_cache_enabled"] = bool(cc.get("enabled"))
        if cc.get("reason"):
            context["compile_cache_reason"] = cc["reason"]

    key_map = {
        "xla_dot": "xla_dot_gflops",
        "plain_huge": "kernel_sgemm_huge_gflops",
        "ft_rowcol": "abft_rowcol_gflops",
        "ft_rowcol_mxu": "abft_rowcol_mxu_gflops",
        "ft_fused": "abft_fused_gflops",
        "bf16_abft": "bf16_abft_huge_gflops",
        "bf16_fused": "bf16_abft_fused_gflops",
        "bf16_plain": "bf16_sgemm_huge_gflops",
        "bf16_xla": "bf16_xla_dot_gflops",
        "injected_faults_per_tile": "injected_faults_per_tile",
        # Fault-telemetry embed: the injected headline run's materialized
        # detected/uncorrectable counters ride the artifact so SDC
        # activity is auditable from the JSON alone.
        "fault_counters": "fault_counters",
        # Autotuner comparison (--tuned): cache-dispatched kernel GFLOPS
        # plus the tile the cache served, next to the heuristic rows.
        "ft_tuned": "abft_tuned",
        # Ledger-driven resume provenance: which rungs this run seeded
        # from the run ledger (reason: skipped_fresh_in_ledger) instead
        # of re-measuring.
        "ledger_resume": "ledger_resume",
        # Performance observability: the RunReport manifest + per-stage
        # roofline rows the worker banked (ft_sgemm_tpu.perf).
        "run_report": "run_report",
    }
    for src, dst in key_map.items():
        if src in values and values[src] is not None:
            v = values[src]
            context[dst] = round(v, 1) if isinstance(v, float) else v

    # VPU-vs-MXU encode comparison: the same strategy measured under both
    # checksum-encode modes at this size, so the artifact answers "did the
    # augmented-operand encode pay off?" without cross-referencing stages.
    # rowcol pairs its two stages directly; the weighted pair is the
    # ladder's weighted measurement (VPU/precomp) vs the fused stage
    # (weighted's MXU encode under its historical strategy name).
    enc_cmp = {}
    rc_pair = {}
    if isinstance(values.get("ft_rowcol"), (int, float)):
        rc_pair["vpu"] = round(values["ft_rowcol"], 1)
    if isinstance(values.get("ft_rowcol_mxu"), (int, float)):
        rc_pair["mxu"] = round(values["ft_rowcol_mxu"], 1)
    if rc_pair:
        enc_cmp["rowcol"] = rc_pair
    w_pair = {}
    if isinstance(ladder_gflops, (int, float)) and (
            ladder_strategy is None or "rowcol" not in ladder_strategy):
        w_pair["vpu"] = round(ladder_gflops, 1)
    if isinstance(values.get("ft_fused"), (int, float)):
        w_pair["mxu"] = round(values["ft_fused"], 1)
    if w_pair:
        enc_cmp["weighted"] = w_pair
    if enc_cmp:
        context["encode_comparison"] = {"size": SIZE, **enc_cmp}

    xla = values.get("xla_dot")
    plain = values.get("plain_huge")
    if ft is not None and xla:
        context["ft_vs_xla"] = round(ft / xla, 3)
    if ft is not None and plain:
        context["abft_overhead"] = round(1.0 - ft / plain, 3)
    bf_ft, bf_xla = values.get("bf16_abft"), values.get("bf16_xla")
    bf_plain = values.get("bf16_plain")
    bf_fused = values.get("bf16_fused")
    if bf_ft and bf_xla:
        context["bf16_ft_vs_xla"] = round(bf_ft / bf_xla, 3)
    if bf_fused and bf_xla:
        context["bf16_fused_vs_xla"] = round(bf_fused / bf_xla, 3)
    if bf_plain and bf_xla:
        context["bf16_plain_vs_xla"] = round(bf_plain / bf_xla, 3)

    # Backend-fallback artifact (the empty-bench satellite): the TPU
    # headline was unmeasurable, but the worker measured the CPU-feasible
    # smoke set instead of dying — surface it (and its embedded
    # RunReport) and treat the run as successful observability output.
    fallback_ok = False
    fb = values.get("fallback_smoke")
    if isinstance(fb, dict):
        fb = dict(fb)
        rr = fb.pop("run_report", None)
        if rr is not None and "run_report" not in context:
            context["run_report"] = rr
        fallback_ok = bool(fb.get("ok"))
        context["fallback_smoke"] = fb

    context["bench_attempts"] = _ATTEMPTS
    # Honest provenance: count pre-existing stage records whose values
    # survived unchanged into the final set — i.e. stages this run
    # actually inherited rather than measured. A _reset_token that was
    # not in the pre-run snapshot proves the worker discarded the old
    # records mid-run: nothing resumed, coincidentally-equal remeasured
    # values notwithstanding.
    reset_this_run = (values.get("_reset_token") is not None
                      and values.get("_reset_token")
                      != _PRE_VALUES.get("_reset_token"))
    # "backend" is always re-probed live (never served from cache), so
    # it's excluded like the token; "backend_guard"/"worker_crash" are
    # diagnostic tombstones whose cleared-values are identical across runs
    # and would inflate the count: only MEASURED stages count.
    resumed = 0 if reset_this_run else sum(
        1 for k, v in _PRE_VALUES.items()
        if k not in ("_reset_token", "backend", "backend_guard",
                     "worker_crash")
        and values.get(k) == v)
    if resumed:
        context["resumed_stages"] = resumed
    if ft is None:
        # Honest pointer, not a substitute: value stays null (this run
        # measured nothing), but the artifact names the newest banked
        # measurement from ANY code version so the reader knows a
        # driver-protocol number exists and where its provenance lives.
        stale = _newest_stale_headline()
        if stale:
            context["last_measured_other_code_version"] = stale
    killed = ("signal" in errors
              or any(isinstance(v, str) and "killed (" in v
                     for v in errors.values()))
    complete = ("ft_headline" in values
                and all(w in values for w in WANTED_STAGES))
    if ft is not None and (salvaged or (killed and not complete)):
        # Real but PARTIAL: a deadline kill (or a lost record salvaged
        # from the streamed timeline) means later stages never ran —
        # say so, and list exactly which stages completed, so readers
        # and gates (bench-compare, summarize_bench) never mistake a
        # salvaged artifact for a full sweep.
        context["partial"] = True
        context["completed_stages"] = sorted(
            k for k in values
            if not k.startswith("_")
            and k not in ("backend_guard", "worker_crash",
                          "compile_cache"))
    if tl_summary:
        if tl_summary.get("killed_at_stage"):
            context["killed_at_stage"] = tl_summary["killed_at_stage"]
        tpath = _timeline_path(_RECORDS_PATH)
        if tpath:
            context["timeline"] = os.path.basename(tpath)
    context["errors"] = errors
    metric = "abft_kernel_huge_gflops_4096"
    value = None if ft is None else round(ft, 1)
    vs_baseline = (None if ft is None
                   else round(ft / REFERENCE_ABFT_HUGE_GFLOPS, 3))
    if ft is None and isinstance(fb, dict):
        # Platform-honest CPU headline (ROADMAP item 1): the TPU 4096
        # headline cannot exist on this host, but the VERIFIED fallback
        # smoke did measure the injected-and-corrected FT kernel —
        # promote its warm-path GFLOPS under a metric that says exactly
        # what it measured (smoke tile at SMOKE_SIZE) instead of
        # emitting another value:null artifact. bench-compare reads the
        # differing metric vs the TPU baseline as incomparable (exit 0,
        # never a fake ratio — vs_baseline stays null), and the trend
        # plane gates the new (metric, platform) series against its own
        # history.
        row = (fb.get("encode_modes") or {}).get("vpu") or {}
        warm = row.get("warm_seconds")
        if isinstance(warm, (int, float)) and warm > 0 \
                and row.get("corrected_ok") \
                and not row.get("uncorrectable"):
            value = round(2.0 * SMOKE_SIZE**3 / 1e9 / warm, 3)
            metric = f"abft_kernel_smoke_gflops_{SMOKE_SIZE}"
            context["headline_fallback"] = {
                "reason": "no TPU backend: smoke-tile headline on "
                          + str(context.get("platform_used")
                                or context.get("backend") or "unknown"),
                "size": SMOKE_SIZE,
                "warm_seconds": warm,
                "strategy": "rowcol",
                "encode": "vpu",
            }
    artifact = {
        "metric": metric,
        "value": value,
        "unit": "GFLOPS",
        "vs_baseline": vs_baseline,
        "context": context,
    }
    print(json.dumps(artifact), flush=True)
    _ledger_append(artifact)
    if ft is not None:
        return 0
    # No TPU headline, but a completed backend-fallback measurement is a
    # successful run of what this host could measure — not the rc=1
    # "parsed: null" failure the round-1..5 artifacts recorded.
    return 0 if fallback_ok else 1


def _best_measurement(vals):
    """Best measured correcting variant in a records dict: the weighted
    ladder's own headline, overridden by a faster rowcol/fused stage.
    Returns ``(gflops_or_None, strategy_label)`` — one vocabulary for
    both the live emit and the stale-provenance scan.

    Completed LADDER RUNGS count too: the worker streams each rung's
    measurement under ``ft_headline[<label>]`` before attempting the
    next, so a deadline kill between rungs (the headline-first salvage
    path) still promotes the finished rung's number even though the
    outer ``ft_headline`` record never landed."""
    rec = vals.get("ft_headline")
    ft = rec.get("gflops") if isinstance(rec, dict) else rec
    strategy = rec.get("strategy") if isinstance(rec, dict) else None
    for stage, label in (("ft_rowcol", "rowcol"),
                         ("ft_rowcol_mxu", "rowcol (MXU-augmented encode)"),
                         ("ft_fused", "fused (MXU-augmented)")):
        v = vals.get(stage)
        if isinstance(v, (int, float)) and (ft is None or v > ft):
            ft, strategy = v, label
    for name, v in vals.items():
        if (isinstance(name, str) and name.startswith("ft_headline[")
                and name.endswith("]") and isinstance(v, (int, float))
                and (ft is None or v > ft)):
            ft, strategy = v, name[len("ft_headline["):-1]
    return ft, strategy


def _newest_stale_headline():
    """Newest same-SIZE records file (any code version) with a measured
    headline.

    Returns ``{"file", "gflops", "strategy"}`` or None. Provenance only —
    the caller must NOT promote it into ``value`` (it was measured under
    different code; RESULTS.md carries the full story). The current run's
    own records file is excluded: its values are already the emit's
    input, and labeling them "other code version" would be false."""
    try:
        base = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            ".bench")
        current = os.path.basename(_RECORDS_PATH) if _RECORDS_PATH else None
        stamped = []
        for name in os.listdir(base):
            if (not name.startswith("records_")
                    or not name.endswith(f"_{SIZE}.jsonl")
                    or name == current):
                continue
            try:  # a concurrent prune may unlink between listdir and stat
                stamped.append((os.path.getmtime(os.path.join(base, name)),
                                name))
            except OSError:
                continue
        for _, name in sorted(stamped, reverse=True):
            vals, _ = _read_records(os.path.join(base, name))
            g, strategy = _best_measurement(vals)
            if isinstance(g, (int, float)):
                return {"gflops": round(float(g), 1),
                        "strategy": strategy, "file": name}
    except OSError:
        pass
    return None


def _emit_from_disk(extra_errors=None):
    values, errors = _read_records(_RECORDS_PATH) if _RECORDS_PATH else ({}, {})
    return _emit(values, errors, extra_errors)


def _on_signal(signum, frame):
    """Driver timeout path: flush the JSON line, kill the worker, exit.

    Emit FIRST: the records are already on disk and the worker never
    writes to stdout, while killing a tunnel-hung worker can block up to
    ~2x grace — a driver with a short SIGTERM->SIGKILL window must not be
    able to SIGKILL us before the line lands. The worker is then reaped
    here or, failing even that, by its PR_SET_PDEATHSIG when we exit."""
    rc = _emit_from_disk({"signal": f"supervisor received signal {signum}"})
    _tl_point("kill", f"killed (supervisor received signal {signum})")
    _kill_child()
    os._exit(rc)


def _worker_preexec():
    """Runs in the forked child: die with the supervisor.

    start_new_session detaches the worker from the driver's process group,
    so a driver that SIGKILLs the supervisor directly (no SIGTERM) would
    otherwise orphan a jax-hung worker holding the TPU tunnel forever.
    PR_SET_PDEATHSIG delivers SIGKILL to the worker the moment the
    supervisor dies, whatever killed it."""
    try:
        import ctypes
        libc = ctypes.CDLL("libc.so.6", use_errno=True)
        libc.prctl(1, signal.SIGKILL, 0, 0, 0)  # 1 = PR_SET_PDEATHSIG
    except Exception:  # noqa: BLE001 — best-effort; non-Linux fallback
        pass


# Every path whose content can change a measured number. MUST cover every
# repo-local module the worker imports (the package, the native helpers it
# dlopens, the bench protocol, build metadata) — a measurement-relevant
# code location outside this list would let stale banked records be
# resumed after the code changed. tests/test_bench.py enforces the
# coverage by importing everything the worker reaches in a subprocess and
# asserting each repo-local module file lands under one of these paths.
CODE_VERSION_PATHS = ["bench.py", "pyproject.toml", "ft_sgemm_tpu", "csrc"]


def _code_version_key():
    """Content key of the code under measurement.

    Only code that can change the measured numbers participates: the bench
    protocol itself, the package it measures, the native helpers, and the
    build metadata. Tests, scripts, examples, and docs cannot alter a
    GFLOPS reading, so editing (or committing) them must not discard
    banked hardware stages — tunnel windows are too scarce to re-measure
    after every cosmetic commit. The key is a digest of the tracked blobs
    under those paths (``git ls-tree``, independent of which commit they
    came from) plus the dirty tracked diff and untracked code files'
    (path, size, mtime) — distinct code states map to distinct keys;
    mtime+size for untracked content is a cheap proxy that can over-split
    keys, never under-split in practice."""
    import hashlib

    base = os.path.dirname(os.path.abspath(__file__))

    code_paths = CODE_VERSION_PATHS
    code_exts = (".py", ".cpp", ".cc", ".c", ".h", ".sh", ".toml")

    def git(*args):
        # check=True: a failed git call (e.g. another process holding
        # .git/index.lock) must invalidate the key entirely, never
        # silently collapse a dirty tree onto the clean-HEAD key.
        return subprocess.run(["git", "-C", base, *args],
                              capture_output=True, text=True,
                              timeout=10, check=True).stdout

    try:
        tree = git("ls-tree", "-r", "HEAD", "--", *code_paths)
        if not tree.strip():
            return None
        state = git("diff", "HEAD", "--", *code_paths)
        for rel in git("ls-files", "--others", "--exclude-standard",
                       "--", *code_paths).splitlines():
            if not rel.endswith(code_exts):
                continue
            try:
                st = os.stat(os.path.join(base, rel))
                state += f"\n{rel} {st.st_size} {st.st_mtime_ns}"
            except OSError:
                state += f"\n{rel} gone"
        return hashlib.sha1(
            (tree + "\0" + state).encode()).hexdigest()[:12]
    except Exception:  # noqa: BLE001 — any git failure means "no key"
        return None


def _default_records_path():
    """A stable, code-version-keyed records path.

    Keyed by :func:`_code_version_key` so independent bench runs of the
    SAME code share stage records: a measurement window captured by a
    monitoring run earlier in the round resumes — rather than re-pays or,
    worse, loses — when the final scoring run executes after the tunnel
    has died again. Different code gets a fresh file, so stale numbers can
    never attach to changed kernels. Records live in a repo-local
    ``.bench/`` directory (gitignored), NOT world-writable /tmp, so no
    other user can pre-seed or lock out the records. Falls back to a
    private mkstemp file when git is unavailable.
    """
    key = _code_version_key()
    if key:
        d = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         ".bench")
        try:
            os.makedirs(d, mode=0o700, exist_ok=True)
            # Prune RECORDS of old code states (every edit mints a new
            # key; without this the directory grows without bound). Never
            # touch .lock files — another live run may hold a flock on an
            # old-mtime lock inode, and unlinking it would let two runs
            # acquire "the" lock on different inodes — and never touch
            # the current key's own records.
            mine = f"records_{key}_{SIZE}.jsonl"
            cutoff = time.time() - 3 * 86400
            for name in os.listdir(d):
                # Spare the current key's records AND its streamed
                # timeline (the salvage input must survive startup).
                if (not name.endswith(".jsonl")
                        or name in (mine, mine + ".timeline.jsonl")):
                    continue
                fp = os.path.join(d, name)
                try:
                    if os.path.getmtime(fp) < cutoff:
                        os.unlink(fp)
                except OSError:
                    pass
            return os.path.join(d, mine)
        except OSError:
            pass
    fd, path = tempfile.mkstemp(prefix="ft_sgemm_bench_", suffix=".jsonl")
    os.close(fd)
    return path


def _acquire_run_lock():
    """One live bench per records file.

    Concurrent runs of the same code (e.g. a monitoring run overlapping
    the scoring run) would both contend for the TPU and interleave record
    appends; an exclusive flock makes the later run wait for the earlier
    one (whose results it then inherits via resume). If the lock cannot
    be had within a bounded wait, fall back to a private mkstemp records
    file — isolated, measurement proceeds. The fd is held for process
    lifetime; the OS releases it on ANY exit path including os._exit."""
    global _RECORDS_PATH, _LOCK_FH
    import fcntl

    def isolate():
        # Private mkstemp file seeded with a snapshot of the shared
        # records: isolation must not discard stages (possibly the
        # headline) already landed there — reading needs no lock, and
        # _read_records skips torn lines. The global swaps LAST: a signal
        # arriving mid-copy must still see a records path that holds the
        # headline (the shared one), never a half-seeded empty file.
        global _RECORDS_PATH
        shared = _RECORDS_PATH
        fd, private = tempfile.mkstemp(
            prefix="ft_sgemm_bench_", suffix=".jsonl")
        os.close(fd)
        try:
            with open(shared, "rb") as src, open(private, "wb") as dst:
                dst.write(src.read())
        except OSError:
            pass
        _RECORDS_PATH = private

    try:
        _LOCK_FH = open(_RECORDS_PATH + ".lock", "a")
    except OSError:
        # Can't create the lock: sharing WITHOUT a lock is the one unsafe
        # option (interleaved appends + TPU contention) — isolate instead.
        isolate()
        return
    t0 = time.monotonic()
    limit = min(240.0, _DEADLINE / 3.0)
    while True:
        try:
            fcntl.flock(_LOCK_FH, fcntl.LOCK_EX | fcntl.LOCK_NB)
            return
        except OSError as e:
            import errno

            if e.errno not in (errno.EWOULDBLOCK, errno.EAGAIN,
                               errno.EACCES):
                # flock unsupported here (e.g. ENOLCK): waiting is
                # pointless — isolate immediately instead of burning up
                # to limit seconds of the measurement budget.
                limit = -1.0
            if time.monotonic() - t0 > limit:
                isolate()
                return
            time.sleep(min(5.0, max(0.1, limit / 4.0)))


def main():
    global _CHILD, _RECORDS_PATH, _ATTEMPTS, _PRE_VALUES
    # Handlers FIRST — before the git-keyed path computation (up to ~30s
    # of git subprocesses) and the lock wait (up to ~4 min): a driver
    # SIGTERM at ANY point must flush a JSON line assembled from whatever
    # records are readable (reading needs no lock; a None records path
    # emits an empty-context line).
    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)
    _RECORDS_PATH = (os.environ.get("FT_SGEMM_BENCH_RECORDS")
                     or _default_records_path())
    # Provenance snapshot before the lock wait: even an emit from the
    # SIGTERM handler during the wait must know which stages predate us.
    _PRE_VALUES = _read_records(_RECORDS_PATH)[0]
    _acquire_run_lock()
    # Re-snapshot: the previous lock holder may have appended stages while
    # we waited — those are resumed too (the worker never re-measures
    # them), and isolate() may have swapped the records path.
    _PRE_VALUES = _read_records(_RECORDS_PATH)[0]

    worker_rc = None
    extra = {}
    completed_partials = 0
    while True:
        values, _ = _read_records(_RECORDS_PATH)
        remaining = _time_left() - _MARGIN
        if remaining < _MIN_ATTEMPT:
            break
        if worker_rc == 0:
            break  # worker finished everything it wanted
        if worker_rc == 4:
            break  # deterministic environment failure (wrong backend)
        if worker_rc == 5:
            break  # backend fell back; smoke set measured — relaunching
            #        cannot change the platform
        if "ft_headline" in values and remaining < 2 * _MIN_ATTEMPT:
            break  # headline safe; not enough budget to chase context stages
        if worker_rc == 3:
            # A worker RAN TO COMPLETION with the headline safe but some
            # context stages failed. One fresh-process relaunch covers
            # transient tunnel errors; beyond that the failures are
            # deterministic and relaunching just re-pays backend init.
            completed_partials += 1
            if completed_partials >= 2:
                break
        if _ATTEMPTS >= 8:
            break
        budget = _attempt_budget(remaining)
        attempt_t0 = time.monotonic()
        env = dict(os.environ)
        # The worker plans its stages against the attempt's TRUE wall
        # allowance — nominal budget plus the heartbeat extension it can
        # earn, clipped to the supervisor's hard remaining time — so a
        # long init neither starves measurement (the allowance already
        # prices extension in) nor lets the worker schedule past the
        # deadline kill and lose the stage in flight. Minus a slack: the
        # supervisor's kill timers start HERE (pre-exec) while the
        # worker's clock starts post-exec, so without slack a loaded
        # machine's exec lag would put the kill BEFORE the worker's own
        # expiry — mid-stage, losing the record in flight. (Relative
        # floor keeps tiny test budgets positive.)
        alw = min(budget + _EXTEND_MAX, remaining)
        env["FT_SGEMM_WORKER_DEADLINE"] = str(max(alw - 10.0, alw * 0.75))
        hb_path = _RECORDS_PATH + ".hb"
        try:
            os.unlink(hb_path)  # a stale file must not extend this attempt
        except OSError:
            pass
        out = _worker_output()
        try:
            _CHILD = subprocess.Popen(
                [sys.executable, os.path.abspath(__file__), "--worker",
                 _RECORDS_PATH],
                stdout=out, stderr=out, start_new_session=True,
                preexec_fn=_worker_preexec, env=env)
        except Exception as e:  # noqa: BLE001 — the JSON line must survive
            extra["worker_launch"] = f"{type(e).__name__}: {e}"
            sys.stderr.write(traceback.format_exc())
            break
        _ATTEMPTS += 1
        worker_rc = _wait_with_heartbeat(attempt_t0, budget, hb_path)
        _CHILD = None
        if (worker_rc not in (0, 3, 4)
                and time.monotonic() - attempt_t0 < 60):
            # A fast failure is a tunnel outage, not a slow measurement:
            # pace relaunches across the remaining budget (outages last
            # seconds to minutes) instead of burning the attempt cap in
            # the first minutes and idling away the rest of the deadline.
            pause = min(45.0, 5.0 * (2 ** (_ATTEMPTS - 1)))
            pause = min(pause,
                        max(0.0, _time_left() - _MARGIN - _MIN_ATTEMPT))
            if pause > 0:
                time.sleep(pause)  # SIGTERM still handled during sleep

    # rc 3 is the protocol's "headline safe, context incomplete" status —
    # not an error, and rc 5 is the backend-fallback success path; the
    # individual skipped stages carry their own records.
    if worker_rc not in (0, 3, 5, None):
        extra["worker_rc"] = str(worker_rc)
    values, _ = _read_records(_RECORDS_PATH)
    if (_ATTEMPTS == 0 and worker_rc is None
            and "worker_launch" not in extra
            and "ft_headline" not in values):
        extra["no_attempts"] = (
            f"budget never allowed a worker launch (deadline "
            f"{_DEADLINE:.0f}s, margin {_MARGIN:.0f}s, min attempt "
            f"{_MIN_ATTEMPT:.0f}s)")
    return _emit_from_disk(extra)


# --------------------------------------------------------------------------
# Worker
# --------------------------------------------------------------------------

def _stage_need(est_seconds, stage_max):
    """Wall-clock budget a new stage must fit before it launches.

    1.5x the largest completed stage's wall time (headroom for variance),
    floored at the historical 20 s guard, capped by
    ``FT_SGEMM_BENCH_STAGE_MAX`` so one pathologically slow stage cannot
    make the guard refuse every later stage.
    """
    return min(max(20.0, 1.5 * est_seconds), stage_max)


def _retry(what, fn, errors, attempts=4, base=3.0):
    """Run fn() with exponential-backoff retries; record failure and return
    None instead of raising (transient axon tunnel errors: compile-helper
    HTTP 500s, backend-init UNAVAILABLE)."""
    last_tb = None
    last = None
    for i in range(attempts):
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 — must never kill the worker
            last = e
            last_tb = traceback.format_exc()
            if i < attempts - 1:
                time.sleep(min(base * (2 ** i), 60.0))
    errors[what] = f"{type(last).__name__}: {last}"
    sys.stderr.write(f"bench worker: stage {what!r} failed after {attempts}"
                     f" attempts:\n{last_tb}")
    return None


def _start_heartbeat(records_path, tl=None):
    """Touch ``<records>.hb`` every few seconds from a daemon thread.

    Started BEFORE any jax import: the supervisor's budget-extension
    policy reads this file's mtime. A slowly-initializing backend keeps
    beating (init releases the GIL between steps — the BENCH_r03 tail
    shows log lines landing mid-init); a wedged GIL or dead process goes
    stale and the supervisor's nominal-budget kill fires. Each beat also
    lands as a timeline point so ``cli timeline`` can render heartbeat
    gaps post hoc."""
    if (os.environ.get("PYTEST_CURRENT_TEST")
            and os.environ.get("FT_SGEMM_BENCH_FAKE_NO_HB")):
        return  # test hook: simulate a worker whose beats never start
    import threading

    hb = records_path + ".hb"

    def beat():
        while True:
            try:
                with open(hb, "w") as f:
                    f.write(f"{os.getpid()} {time.time():.1f}\n")
            except OSError:
                pass
            if tl is not None:
                tl.point("heartbeat", "beat")
            time.sleep(10.0)

    threading.Thread(target=beat, daemon=True,
                     name="bench-heartbeat").start()


def _setup_compile_cache():
    """Enable the persistent compile cache via perf.compile_cache.

    Returns the status dict (``{"enabled", "path", "reason"}``) that is
    banked as the ``compile_cache`` stage record — a failure is a named
    reason in the artifact, never an anonymous swallow and never a dead
    worker. The default location is the shared cache alongside the tuner
    cache (XLA keys entries by module content, so cross-code-version
    sharing is safe); ``FT_SGEMM_COMPILE_CACHE`` overrides or disables.
    """
    try:
        from ft_sgemm_tpu.perf import compile_cache

        return compile_cache.enable()
    except Exception as e:  # noqa: BLE001 — caching is never worth a crash
        return {"enabled": False, "path": None,
                "reason": f"{type(e).__name__}: {e}"}


def _compile_cache_stats():
    """Current compile-cache stats dict, or None when unavailable."""
    try:
        from ft_sgemm_tpu.perf import compile_cache

        return compile_cache.stats()
    except Exception:  # noqa: BLE001
        return None


def worker_main(records_path):
    tl = _make_timeline(records_path)
    _start_heartbeat(records_path, tl)
    rec = Recorder(records_path)
    try:
        # The attempt span's start record lands before any jax import:
        # even a worker that hangs in backend init leaves a timeline
        # saying when the attempt began and (from the supervisor's kill
        # marker) when it died.
        with tl.span("worker", kind="attempt", pid=os.getpid()) as info:
            rc = _worker_stages(rec, tl)
            info["value"] = rc
            return rc
    except Exception as e:  # noqa: BLE001 — a crash must leave a record
        # Deterministic failures outside any _retry wrapper (imports,
        # kernel factories) land here so the artifact says WHAT died
        # instead of just worker_rc=1 (the round-1 failure mode).
        rec.fail("worker_crash", f"{type(e).__name__}: {e}")
        sys.stderr.write(traceback.format_exc())
        return _worker_rc(rec)


def _worker_stages(rec, tl=None):
    tl = _NoTimeline() if tl is None else tl
    # The supervisor passes the attempt's full wall allowance (nominal
    # budget + earnable heartbeat extension, clipped to its deadline), so
    # stage skip thresholds track the REAL kill time — finish gracefully
    # (rc=3 partial at worst) just before it, never mid-stage.
    deadline = float(os.environ.get("FT_SGEMM_WORKER_DEADLINE", _WORKER_MAX))
    t0 = time.monotonic()

    def left():
        return deadline - (time.monotonic() - t0)

    # Test hooks: exercise the supervisor's kill / assemble paths without a
    # TPU or a jax import (tests/test_bench.py). Honored ONLY under pytest
    # so a leftover env var can never fabricate a scored artifact.
    if os.environ.get("PYTEST_CURRENT_TEST"):
        fake = os.environ.get("FT_SGEMM_BENCH_FAKE_VALUE")
        if fake:
            slow = os.environ.get("FT_SGEMM_BENCH_FAKE_SLOW")
            if slow:
                # Simulated slow backend init: sleeps past the nominal
                # attempt budget while the heartbeat thread keeps beating.
                time.sleep(float(slow))
            rec.ok("backend", {"backend": "fake", "device": "fake",
                               "num_devices": 1})
            rec.ok("ft_headline", {"gflops": float(fake),
                                   "strategy": "fake"})
            rec.ok("xla_dot", float(fake) * 1.05)
            return 0
        fake_partial = os.environ.get("FT_SGEMM_BENCH_FAKE_PARTIAL")
        if fake_partial:
            # Simulated deadline-kill mid-sweep (the salvage-path test
            # harness): one context stage completes — records AND
            # streamed timeline — then the next stage hangs in flight
            # until the supervisor's kill. No headline ever lands, so
            # the emit must salvage the completed stage.
            rec.ok("backend", {"backend": "fake", "device": "fake",
                               "num_devices": 1})
            with tl.span("ft_rowcol", kind="stage") as info:
                info["value"] = float(fake_partial)
            rec.ok("ft_rowcol", float(fake_partial))
            with tl.span("ft_fused", kind="stage"):
                time.sleep(100000)
        if os.environ.get("FT_SGEMM_BENCH_FAKE_HANG"):
            time.sleep(100000)

    # TPU-only metric: records measured on a fallback backend (e.g. a
    # CPU-only dev box) must never resume into — or short-circuit — a real
    # scoring run of the same code version. (The supervisor's provenance
    # field survives this: resumed stages are counted by value-comparing
    # against the pre-run snapshot, so discarded-then-remeasured stages
    # don't count as resumed.)
    backend_rec = rec.values.get("backend")
    if (isinstance(backend_rec, dict)
            and backend_rec.get("backend") != "tpu"):
        sys.stderr.write(
            f"bench worker: discarding records measured on backend "
            f"{backend_rec.get('backend')!r} (metric is TPU-only)\n")
        rec.reset()

    if _worker_rc(rec) == 0:
        return 0  # resume of a finished run: skip jax init entirely

    errors = {}

    # Per-stage wall-clock budget (graceful early-stop): BENCH_r05 lost
    # its number because the supervisor's deadline landed MID-stage —
    # the kill discarded the whole attempt's in-flight work and the
    # artifact read null. A stage that probably cannot finish in the
    # remaining budget is now SKIPPED WITH A RECORD instead of started:
    # each completed stage's wall time updates a running estimate, and a
    # new stage only launches when ~1.5x that estimate (floored at the
    # old 20 s guard, capped by FT_SGEMM_BENCH_STAGE_MAX) still fits.
    # Every completed record is fsync'd immediately (Recorder), so a
    # slow stage degrades the artifact to "skipped: ..." rows rather
    # than nulling it.
    stage_max = float(os.environ.get("FT_SGEMM_BENCH_STAGE_MAX", 300.0))
    stage_est = {"seconds": 20.0}  # prior: the old flat guard

    # Wall-phase split holder: gf() clears and refills it per measurement
    # (bench_seconds_per_call's phase_info), and the enclosing stage span
    # copies the lower/compile/execute decomposition into its end record —
    # the per-stage compile-vs-execute attribution perf/wallclock.py
    # rolls up. Worker is single-threaded; one shared dict suffices.
    phase_holder = {}

    def _merge_phase_split(span_info):
        for key in ("lower_seconds", "compile_seconds", "execute_seconds"):
            v = phase_holder.get(key)
            if isinstance(v, (int, float)):
                span_info[key] = v

    def record_retry(name, fn, attempts=3, base=2.0):
        if rec.done(name):
            return rec.values[name]
        need = _stage_need(stage_est["seconds"], stage_max)
        if left() < need:
            rec.fail(name, f"skipped: worker deadline within ~{need:.0f}s"
                           " stage budget (graceful early-stop)")
            tl.point("stage", name, note="skipped: graceful early-stop")
            return None
        t_stage = time.monotonic()
        with tl.span(name, kind="stage") as span_info:
            out = _retry(name, fn, errors, attempts=attempts, base=base)
            if out is None:
                span_info["status"] = "fail"
                span_info["error"] = errors.get(name, "unknown")
            else:
                span_info["value"] = out
                _merge_phase_split(span_info)
        elapsed = time.monotonic() - t_stage
        if out is not None:
            # Only successful stages update the estimate: a failed stage's
            # wall time is retry backoff, not measurement cost.
            stage_est["seconds"] = max(stage_est["seconds"], elapsed)
        if out is None:
            rec.fail(name, errors.get(name, "unknown"))
        else:
            rec.ok(name, out)
        return out

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    with tl.span("import_jax", kind="compile"):
        import numpy as np

        import jax

    # Persistent executable cache: tunnel windows are ~20 min; a relaunch
    # or a later stage must not respend them recompiling the same
    # kernels. Promoted from a silent best-effort block to the observable
    # perf.compile_cache module: the enable status (a NAMED failure
    # reason instead of an anonymous swallow) is banked as a stage record
    # and surfaces in the artifact context as compile_cache_enabled /
    # compile_cache_reason; hit/miss/bytes-written stats supersede the
    # record at report time. FT_SGEMM_COMPILE_CACHE overrides the
    # location (or pins it off — the tuner cache's hermetic test/CI
    # pattern).
    with tl.span("compile_cache_setup", kind="compile"):
        rec.ok("compile_cache", _setup_compile_cache())

    def probe():
        devs = jax.devices()
        x = jax.device_put(np.zeros((8, 128), np.float32))
        jax.block_until_ready(x)
        kind = getattr(devs[0], "device_kind", devs[0].platform)
        return {"backend": jax.default_backend(), "device": str(devs[0]),
                "device_kind": str(kind), "num_devices": len(devs),
                "platform_requested": (os.environ.get("JAX_PLATFORMS")
                                       or "default")}

    # Short in-process retries only: a HANG here is bounded by the
    # supervisor (nominal budget + the heartbeat-extension cap), and a
    # fresh worker process is the better retry for tunnel outages.
    # ALWAYS probe live — never serve the backend stage from cache: a
    # resume on a different machine must not measure under a stale
    # recorded identity (TPU-recorded cache on a CPU box would otherwise
    # merge CPU stage numbers into a TPU-claiming artifact).
    with tl.span("backend_init", kind="compile") as bi_info:
        live = _retry("backend", probe, errors, attempts=3, base=2.0)
        if live is None:
            bi_info["status"] = "fail"
            bi_info["error"] = errors.get("backend", "unknown")
    if live is None:
        # Backend init raised every retry (the BENCH_r01 failure class).
        # Instead of dying with a null artifact, fall back to whatever
        # platform still works (ultimately cpu) and record the fallback
        # triple — the artifact then says WHAT was requested, what ran,
        # and why (the empty-bench satellite of the perf-observability
        # rework).
        live, fb_err = _backend_with_fallback(
            initial_error=errors.get("backend", "unknown"))
        if live is None:
            rec.fail("backend", fb_err)
            return _worker_rc(rec)
        live.setdefault("fallback_reason", errors.get("backend", "unknown"))
    else:
        live.setdefault("platform_used", live.get("backend"))
    if live.get("backend") != "tpu":
        # The 4096 headline is TPU-only (interpret-mode Pallas at this
        # size would never finish), but the run must still produce a
        # useful artifact: record the backend facts and the CPU-feasible
        # smoke measurement set + RunReport, then stop — relaunching
        # cannot change the platform.
        rec.ok("backend", live)
        if left() < 60:
            # A slow plugin init (libtpu's metadata retries run ~8 min
            # before jax gives up) can eat the attempt; the platform
            # triple is already banked — record the skip rather than be
            # killed mid-measurement.
            rec.fail("fallback_smoke",
                     "skipped: worker deadline within 60s after backend "
                     "fallback")
            return 5

        def fallback_fn():
            ctx = {}
            ok = _smoke_measure(ctx, device_kind=live.get("device_kind"),
                                facts=live, tl=tl)
            ctx["ok"] = bool(ok)
            return ctx

        out = _retry("fallback_smoke", fallback_fn, errors, attempts=2)
        if out is None:
            rec.fail("fallback_smoke",
                     errors.get("fallback_smoke", "unknown"))
        else:
            rec.ok("fallback_smoke", out)
        return 5  # deterministic: fallback measured, stop relaunching
    # A live TPU probe supersedes one-shot diagnostics from earlier runs
    # that shared this records file (e.g. a CPU monitoring box's
    # backend_guard): an ok tombstone clears the stale error so it cannot
    # pollute this run's final artifact.
    for stale in ("backend_guard", "worker_crash"):
        if stale in rec.errors:
            rec.ok(stale, "cleared: superseded by a successful TPU probe")
    cached = rec.values.get("backend")
    if isinstance(cached, dict) and cached != live:
        # Same backend kind but a different device/topology (e.g. the
        # tunnel reattached another chip): numbers measured there must
        # not resume here under this device's identity.
        sys.stderr.write(
            f"bench worker: discarding records measured on {cached!r}; "
            f"live device is {live!r}\n")
        rec.reset()
    rec.ok("backend", live)

    # Ledger-driven headline resume (ROADMAP item 1 slice): rungs this
    # exact (git rev, platform) already measured are seeded from the run
    # ledger instead of re-measured — a killed run's completed rungs
    # reach the ledger via the supervisor's _ledger_append even when the
    # records file was lost, so relaunches stop forfeiting them. Each
    # skipped rung logs the named ``skipped_fresh_in_ledger`` reason.
    _ledger_resume_stages(rec, tl, live)

    import jax.numpy as jnp

    from ft_sgemm_tpu import InjectionSpec, SHAPES, make_ft_sgemm, make_sgemm
    from ft_sgemm_tpu.ops.reference import sgemm_reference
    from ft_sgemm_tpu.utils.matrices import generate_random_matrix
    from ft_sgemm_tpu.utils.timing import bench_seconds_per_call

    flop = 2.0 * SIZE**3

    def put_inputs():
        rng = np.random.default_rng(10)
        return tuple(
            jax.device_put(generate_random_matrix(SIZE, SIZE, rng=rng))
            for _ in range(3))

    with tl.span("device_put_inputs", kind="stage") as dp_info:
        inputs = _retry("device_put_inputs", put_inputs, errors, attempts=3)
        if inputs is None:
            dp_info["status"] = "fail"
            dp_info["error"] = errors.get("device_put_inputs", "unknown")
    if inputs is None:
        rec.fail("device_put_inputs", errors["device_put_inputs"])
        return _worker_rc(rec)
    a, b, c = inputs

    def gf(fn, *args):
        # Tight remaining budget: trade a little timing variance (shorter
        # device-time floor) for finishing the stage inside the deadline —
        # a slightly noisier measured row beats a killed-mid-stage null.
        mdt = 2.0 if left() > 180.0 else 1.0
        phase_holder.clear()
        sec = bench_seconds_per_call(fn, *args, min_device_time=mdt,
                                     phase_info=phase_holder)
        return flop / 1e9 / sec

    inj = InjectionSpec.reference_like(SIZE, SHAPES["huge"].bk)
    if not rec.done("injected_faults_per_tile"):
        rec.ok("injected_faults_per_tile",
               inj.expected_faults(SIZE, SHAPES["huge"].bk))

    # Automatic headline prewarm (ROADMAP item 1): with the persistent
    # compile cache live, AOT-compile the headline ladder's EXACT
    # rep-loop executables (compile_bench_loop shares the timing path's
    # HLO by construction) before the timed pass. Each compile is
    # fsync'd into the cache as it lands, so even an attempt killed
    # mid-prewarm leaves the NEXT attempt warmer — the property that
    # turns a deadline-killed 4096 run into a resumable one instead of a
    # null BENCH_r06. Skipped when the cache is off (nothing would
    # persist, and the ladder's own lower/compile pays the same wall),
    # or once the headline is already banked.
    cc_rec = rec.values.get("compile_cache")
    if (not rec.done("ft_headline") and not rec.done("prewarm_headline")
            and isinstance(cc_rec, dict) and cc_rec.get("enabled")):

        def prewarm_fn():
            from ft_sgemm_tpu.utils.timing import compile_bench_loop

            f32 = jax.ShapeDtypeStruct((SIZE, SIZE), jnp.float32)
            compiled, skipped = [], []
            for label, kwargs in _headline_prewarm_plan(
                    SIZE, SHAPES["huge"].bk):
                # Leave room for at least one timed rung after prewarm:
                # banking executables is pointless if it eats the whole
                # attempt.
                if left() < 120:
                    skipped.append(label)
                    continue
                kern = make_ft_sgemm("huge", alpha=1.0, beta=-1.5,
                                     **kwargs)
                compile_bench_loop(
                    lambda a, b, x, _k=kern: _k(a, b, x, inj).c,
                    f32, f32, f32)
                compiled.append(label)
            return {"compiled": compiled, "skipped": skipped}

        with tl.span("prewarm_headline", kind="compile") as pw_info:
            out = _retry("prewarm_headline", prewarm_fn, errors,
                         attempts=1)
            if out is None:
                pw_info["status"] = "fail"
                pw_info["error"] = errors.get("prewarm_headline",
                                              "unknown")
            else:
                pw_info["value"] = out
        if out is not None:
            rec.ok("prewarm_headline", out)
        else:
            # Prewarm is an accelerant, never a gate: record the failure
            # and measure anyway.
            rec.fail("prewarm_headline",
                     errors.get("prewarm_headline", "unknown"))

    # Headline FIRST so later-stage failures can't cost the round's number.
    # Fallback ladder: weighted precomp -> weighted in-kernel encode (only
    # meaningful when nk >= 2; ADVICE.md r2) -> rowcol. Any rung is a valid
    # fused-ABFT headline; context records which one landed.
    if not rec.done("ft_headline"):
        nk = SIZE // SHAPES["huge"].bk
        ladder = [("weighted (deferred single-check localization)",
                   dict(strategy="weighted"))]
        if nk >= 2:
            ladder.append(("weighted (in-kernel encode fallback, 2 checks)",
                           dict(strategy="weighted", check_every=nk // 2)))
        ladder.append(("rowcol", dict(strategy="rowcol")))
        # ISSUE 13 satellite (ROADMAP item 1 slice): highest-value-
        # missing-rung-first — rungs a previous attempt already banked
        # move behind the still-missing ones (promotion fallback) — and
        # each rung is budgeted from the ledger's per-stage wall history
        # instead of the flat 30 s margin, so a rung that history says
        # cannot finish is SKIPPED (named reason) in favor of a cheaper
        # one rather than dying mid-measurement.
        ladder = _order_headline_ladder(ladder, rec)
        budgets = _headline_rung_budgets(live, [lb for lb, _ in ladder])
        with tl.span("ft_headline", kind="stage") as head_info:
            for label, kwargs in ladder:
                rung = f"ft_headline[{label}]"
                if rec.done(rung):
                    # Banked by an earlier attempt sharing this records
                    # file: promote without burning wall on re-measuring.
                    val = rec.values[rung]
                    if isinstance(val, (int, float)):
                        rec.ok("ft_headline",
                               {"gflops": float(val), "strategy": label})
                        head_info["value"] = {"gflops": float(val),
                                              "strategy": label,
                                              "promoted_from": rung}
                        break
                    continue
                need = budgets.get(label, _RUNG_BUDGET_FLOOR)
                if left() < need:
                    reason = (f"skipped: predicted ~{need:.0f}s wall"
                              f" (ledger stage history) exceeds remaining"
                              f" {left():.0f}s budget")
                    rec.fail(rung, reason)
                    tl.point("stage", rung, note="skipped_over_budget",
                             predicted_seconds=round(need, 1))
                    continue

                def rung_fn(kwargs=kwargs):
                    # Factory inside the retry scope: a factory-time
                    # failure on one rung must fall through to the next,
                    # not abort the ladder.
                    ft = make_ft_sgemm("huge", alpha=1.0, beta=-1.5,
                                       **kwargs)
                    return gf(lambda a, b, x: ft(a, b, x, inj).c, a, b, c)

                with tl.span(rung, kind="stage") as rung_info:
                    val = _retry(rung, rung_fn, errors, attempts=2)
                    if val is None:
                        rung_info["status"] = "fail"
                        rung_info["error"] = errors.get(rung, "unknown")
                    else:
                        rung_info["value"] = val
                        _merge_phase_split(rung_info)
                if val is not None:
                    # Bank the rung ITSELF too: a relaunch resuming this
                    # records file promotes it instead of re-measuring.
                    rec.ok(rung, val)
                    rec.ok("ft_headline",
                           {"gflops": val, "strategy": label})
                    head_info["value"] = {"gflops": val, "strategy": label}
                    break
                # Land the rung's error on disk even when a later rung
                # rescues the headline, so the artifact says WHAT died.
                rec.fail(rung, errors.get(rung, "unknown"))
            else:
                rec.fail("ft_headline", json.dumps(errors))
            if "value" not in head_info:
                head_info["status"] = "fail"

    if not rec.done("ft_headline"):
        # No number, no point burning budget on context stages: return so
        # the supervisor can relaunch a fresh worker whose FIRST job is
        # the headline ladder again.
        return _worker_rc(rec)

    # Headline-first stage order (ROADMAP item 1): from here on, every
    # stage is a COMPARISON stage — none may run before the headline
    # ladder above, so a deadline kill anywhere below still leaves the
    # round's number banked (records + streamed timeline salvage). Even
    # the cheap fault-counters audit runs AFTER the GFLOPS comparison
    # rows: it compiles its own kernel variant, and compile wall before
    # the comparisons is exactly what killed rounds 2-5.
    record_retry("xla_dot",
                 lambda: gf(lambda a, b, x: sgemm_reference(a, b, x, 1.0,
                                                            -1.5), a, b, c),
                 attempts=2)
    # Factories stay inside the retry scopes: a deterministic factory
    # failure must cost one stage, not crash the worker.
    record_retry("plain_huge",
                 lambda: gf(make_sgemm("huge", alpha=1.0, beta=-1.5),
                            a, b, c), attempts=2)

    def rowcol_fn():
        ft_rc = make_ft_sgemm("huge", alpha=1.0, beta=-1.5,
                              strategy="rowcol")
        return gf(lambda a, b, x: ft_rc(a, b, x, inj).c, a, b, c)

    record_retry("ft_rowcol", rowcol_fn, attempts=2)

    def rowcol_mxu_fn():
        # The VPU-vs-MXU encode comparison row (emit pairs it with
        # ft_rowcol): same strategy, same injection, expected checksums
        # riding the augmented dot instead of per-step VPU reductions.
        ft_rm = make_ft_sgemm("huge", alpha=1.0, beta=-1.5,
                              strategy="rowcol", encode="mxu")
        return gf(lambda a, b, x: ft_rm(a, b, x, inj).c, a, b, c)

    record_retry("ft_rowcol_mxu", rowcol_mxu_fn, attempts=2)

    def fused_fn():
        ft_fu = make_ft_sgemm("huge", alpha=1.0, beta=-1.5,
                              strategy="fused")
        return gf(lambda a, b, x: ft_fu(a, b, x, inj).c, a, b, c)

    record_retry("ft_fused", fused_fn, attempts=2)

    def fault_counters_fn():
        # Telemetry for the artifact: one injected headline-kernel run's
        # materialized FtSgemmResult counters — detections must equal the
        # schedule (tiles * per-tile), uncorrectable must be 0, and a
        # reader of the JSON can check both without rerunning anything.
        ft = make_ft_sgemm("huge", alpha=1.0, beta=-1.5)
        res = ft(a, b, c, inj)
        jax.block_until_ready(res.c)
        return {"detections": int(res.num_detected),
                "uncorrectable": int(res.num_uncorrectable)}

    record_retry("fault_counters", fault_counters_fn, attempts=2)

    if os.environ.get("FT_SGEMM_BENCH_TUNED"):
        # --tuned: the headline kernel dispatched through the autotuner's
        # persisted tile cache, side by side with the heuristic rows. The
        # named-shape factory consults the cache by itself; the explicit
        # lookup here is to (a) skip the stage honestly when there is no
        # entry (re-measuring the heuristic would be a lie labeled
        # "tuned") and (b) record WHICH tile the cache served.
        def tuned_fn():
            from ft_sgemm_tpu import tuner

            tile = tuner.lookup_tile(SIZE, SIZE, SIZE, strategy="weighted",
                                     in_dtype="float32",
                                     injection_enabled=True)
            if tile is None:
                raise RuntimeError(
                    "no tuned cache entry for "
                    + tuner.make_key(SIZE, SIZE, SIZE, strategy="weighted",
                                     in_dtype="float32",
                                     injection_enabled=True)
                    + f" in {tuner.cache_path()}; run `python -m"
                    f" ft_sgemm_tpu.cli tune {SIZE} --inject` first")
            ft_t = make_ft_sgemm("huge", alpha=1.0, beta=-1.5)
            val = gf(lambda a, b, x: ft_t(a, b, x, inj).c, a, b, c)
            return {"gflops": round(val, 1), "tuned_block": list(tile.block)}

        record_retry("ft_tuned", tuned_fn, attempts=2)

    # TPU-native bf16 input mode (f32 accumulation + checksums): the MXU's
    # full-rate path — context only; the headline stays f32 for reference
    # parity (the reference is SGEMM).
    def bf16_inputs():
        a16 = jax.device_put(jnp.asarray(a, jnp.bfloat16))
        b16 = jax.device_put(jnp.asarray(b, jnp.bfloat16))
        return a16, b16

    bf16_names = ("bf16_abft", "bf16_fused", "bf16_plain", "bf16_xla")
    if not all(rec.done(n) for n in bf16_names):
        if left() <= 60:
            for n in bf16_names:
                if not rec.done(n):
                    rec.fail(n, "skipped: worker deadline reached")
            pair = None
        else:
            pair = _retry("bf16_inputs", bf16_inputs, errors, attempts=2)
            if pair is None:
                for n in bf16_names:
                    if not rec.done(n):
                        rec.fail(n, "bf16_inputs: "
                                 + errors.get("bf16_inputs", "unknown"))
        if pair is not None:
            a16, b16 = pair

            def bf16_abft_fn():
                ft16 = make_ft_sgemm("huge", alpha=1.0, beta=-1.5,
                                     strategy="weighted",
                                     in_dtype="bfloat16")
                # The bf16 override tile has a different bk: rebuild the
                # reference-like schedule so fault density matches the
                # f32 row.
                inj16 = InjectionSpec.reference_like(
                    SIZE, ft16.shape_config.bk)
                return gf(lambda a, b, x: ft16(a, b, x, inj16).c,
                          a16, b16, c)

            record_retry("bf16_abft", bf16_abft_fn, attempts=2)

            def bf16_fused_fn():
                # The fused strategy's DESIGN POINT (VERDICT r4 #4): bf16
                # is where in-kernel VPU encode hurts most (the MXU runs
                # 4x faster, the VPU doesn't), so riding the checksum
                # moments through the same bf16 MXU dot should close the
                # measured 69.6%-of-dot gap. Measured at the bf16-FT
                # override tile like the weighted row.
                ft16f = make_ft_sgemm("huge", alpha=1.0, beta=-1.5,
                                      strategy="fused",
                                      in_dtype="bfloat16")
                inj16f = InjectionSpec.reference_like(
                    SIZE, ft16f.shape_config.bk)
                return gf(lambda a, b, x: ft16f(a, b, x, inj16f).c,
                          a16, b16, c)

            record_retry("bf16_fused", bf16_fused_fn, attempts=2)
            record_retry(
                "bf16_plain",
                lambda: gf(make_sgemm("huge", alpha=1.0, beta=-1.5,
                                      in_dtype="bfloat16"), a16, b16, c),
                attempts=2)
            record_retry(
                "bf16_xla",
                lambda: gf(lambda a, b, x: sgemm_reference(
                    a, b, x, 1.0, -1.5, in_dtype="bfloat16"), a16, b16, c),
                attempts=2)

    _record_run_report(rec, live, tl=tl)
    return _worker_rc(rec)


def _headline_prewarm_plan(size, bk=512):
    """The headline ladder's kernel recipes, in ladder order — the stage
    set the worker AOT-compiles into the persistent cache before timing
    (and what ``cli prewarm`` covers in its larger variant set). One
    source so the prewarmed executables are exactly the timed ones.
    ``bk`` is the flagship K-depth (``SHAPES["huge"].bk`` — passed in so
    this helper stays importable without jax, the supervisor contract)."""
    nk = size // bk
    plan = [("weighted", dict(strategy="weighted"))]
    if nk >= 2:
        plan.append(("weighted_inkernel",
                     dict(strategy="weighted", check_every=nk // 2)))
    plan.append(("rowcol", dict(strategy="rowcol")))
    return plan


# Stage name -> roofline-row recipe: (strategy, encode, dtype). The cost
# decomposition follows the kernel body each stage actually ran; plain
# and vendor rows carry no FT terms. bf16 FT rows are costed at the f32
# flagship block (the bf16 override tile differs; the block only enters
# the small epilogue byte terms, so the roofline row stays honest to
# within a rounding of bytes).
_REPORT_STAGES = (
    ("xla_dot", None, "vpu", "float32"),
    ("plain_huge", None, "vpu", "float32"),
    ("ft_rowcol", "rowcol", "vpu", "float32"),
    ("ft_rowcol_mxu", "rowcol", "mxu", "float32"),
    ("ft_fused", "fused", "mxu", "float32"),
    ("bf16_xla", None, "vpu", "bfloat16"),
    ("bf16_plain", None, "vpu", "bfloat16"),
    ("bf16_abft", "weighted", "vpu", "bfloat16"),
    ("bf16_fused", "fused", "mxu", "bfloat16"),
)


def _tl_summary_for_report(tl):
    """The run's timeline summary for RunReport embedding, or None.

    ``stage_values`` is dropped — redundant with the stage records that
    feed the roofline rows — keeping the artifact lean."""
    try:
        mod = _load_timeline_mod()
        path = getattr(tl, "path", None)
        if mod is None or not path or not os.path.exists(path):
            return None
        summary = mod.summarize_timeline(mod.read_timeline(path))
        summary.pop("stage_values", None)
        return summary
    except Exception:  # noqa: BLE001 — observability never kills a run
        return None


def _record_run_report(rec, live, tl=None):
    """Assemble the RunReport (manifest + per-stage roofline rows) from
    this run's stage records and bank it as the ``run_report`` record.

    Re-recorded on every attempt (later lines win) so a resumed worker's
    report covers the stages that landed since. Seconds are recovered
    from each stage's recorded GFLOPS via the bench convention
    ``gflops = 2*SIZE^3/1e9/sec`` — exact inversion, no re-measurement —
    while the row's flops/bytes come from the kernel's own cost model,
    so %-of-peak reflects the work the FT kernel actually does. Never
    raises: a report failure is a record, not a dead artifact."""
    try:
        from ft_sgemm_tpu import SHAPES, perf

        kind = live.get("device_kind") if isinstance(live, dict) else None
        blk = SHAPES["huge"].block
        rows = []

        def seconds_of(gflops_val):
            if not isinstance(gflops_val, (int, float)) or gflops_val <= 0:
                return None
            return (2.0 * SIZE**3) / 1e9 / float(gflops_val)

        def add(name, gflops_val, strategy, encode, dtype,
                block=blk, check_every=None):
            sec = seconds_of(gflops_val)
            if sec is None:
                return
            rows.append(perf.stage_row(
                name, sec, m=SIZE, n=SIZE, k=SIZE,
                in_itemsize=2 if dtype == "bfloat16" else 4, dtype=dtype,
                block=block, strategy=strategy, encode=encode,
                check_every=check_every, device_kind=kind))

        head = rec.values.get("ft_headline")
        if isinstance(head, dict):
            label = head.get("strategy") or ""
            strategy = "rowcol" if "rowcol" in label else "weighted"
            nk = SIZE // SHAPES["huge"].bk
            ce = nk // 2 if "in-kernel encode fallback" in label else None
            add("ft_headline", head.get("gflops"), strategy, "vpu",
                "float32", check_every=ce)
        for name, strategy, encode, dtype in _REPORT_STAGES:
            add(name, rec.values.get(name), strategy, encode, dtype)
        tuned = rec.values.get("ft_tuned")
        if isinstance(tuned, dict):
            tb = tuned.get("tuned_block")
            add("ft_tuned", tuned.get("gflops"), "weighted", "vpu",
                "float32", block=tuple(tb) if tb else blk)
        # The backend-fallback triple rides the manifest (not just the
        # bench context): a report rendered from the artifact alone says
        # what platform was ASKED for, what ran, and why they differ.
        extra = {k: live[k] for k in ("platform_requested",
                                      "platform_used", "fallback_reason")
                 if isinstance(live, dict) and live.get(k) is not None}
        # End-of-run compile-cache traffic supersedes the setup-time
        # status record (later lines win) and rides the manifest too.
        cc_stats = _compile_cache_stats()
        if cc_stats is not None:
            rec.ok("compile_cache", cc_stats)
            extra["compile_cache"] = cc_stats
        lint = _lint_facts()
        if lint is not None:
            extra["lint"] = lint
        tl_summary = _tl_summary_for_report(tl)
        wall = None
        if tl_summary:
            try:
                from ft_sgemm_tpu.perf import wallclock

                wall = wallclock.attribute_wall(tl_summary)
                wallclock.record_wall(wall)
            except Exception:  # noqa: BLE001 — attribution is best-effort
                wall = None
        manifest = perf.build_manifest(
            device_kind=kind,
            platform=live.get("backend") if isinstance(live, dict)
            else None,
            extra=extra or None)
        rec.ok("run_report",
               perf.RunReport(manifest=manifest, stages=rows,
                              timeline=tl_summary, wall=wall).to_dict())
    except Exception as e:  # noqa: BLE001 — observability never kills a run
        rec.fail("run_report", f"{type(e).__name__}: {e}")
        sys.stderr.write(traceback.format_exc())


def _backend_with_fallback(initial_error=None):
    """``(facts, error)``: probe the jax backend, falling back to CPU.

    The empty-bench root cause (BENCH_r01..r05): a configured backend
    whose init raises (or hangs — the supervisor handles that case) used
    to kill the process before anything was measured. Here a backend-init
    ``RuntimeError`` is caught, the platform is re-pointed at ``cpu``
    (always compiled into jaxlib), and the artifact records
    ``platform_requested`` / ``platform_used`` / ``fallback_reason``
    instead of dying with a null artifact. ``initial_error`` (the worker
    path, whose retry loop already proved the configured backend dead)
    skips the initial probe — a failing TPU plugin can burn minutes per
    init attempt, and re-paying one here would eat the measurement
    budget. Returns ``(None, error)`` only when even the CPU fallback
    failed."""
    import jax

    requested = os.environ.get("JAX_PLATFORMS") or "default"

    def probe():
        devs = jax.devices()
        kind = getattr(devs[0], "device_kind", devs[0].platform)
        return {"backend": jax.default_backend(),
                "device": str(devs[0]), "device_kind": str(kind),
                "num_devices": len(devs),
                "platform_requested": requested}

    reason = initial_error
    if reason is None:
        try:
            facts = probe()
            facts["platform_used"] = facts["backend"]
            return facts, None
        except RuntimeError as e:
            reason = f"{type(e).__name__}: {e}"
    sys.stderr.write(f"bench: backend init failed ({reason}); "
                     "falling back to cpu\n")
    try:
        jax.config.update("jax_platforms", "cpu")
        facts = probe()
        facts["platform_used"] = facts["backend"]
        facts["fallback_reason"] = reason
        return facts, None
    except Exception as e:  # noqa: BLE001 — record, let the caller emit
        return None, f"{reason}; cpu fallback also failed: " \
                     f"{type(e).__name__}: {e}"


SMOKE_SIZE = 256
SMOKE_BLOCK = (128, 128, 128)


def _smoke_measure(context, *, device_kind=None, facts=None, tl=None):
    """The smoke measurement set: one tiny size, both encode modes, plus
    the RunReport manifest with per-stage roofline rows and a guarded
    compiled-HLO introspection. Shared by ``--smoke`` and the worker's
    backend-fallback path (which records the same facts under the full
    bench artifact instead of dying null). ``facts`` (the backend probe
    dict) threads the ``platform_requested`` / ``platform_used`` /
    ``fallback_reason`` triple into the RunReport manifest; ``tl`` (a
    TimelineRecorder) streams per-stage spans and lands the timeline
    summary in the report. Returns ok_all."""
    import numpy as np

    import jax

    from ft_sgemm_tpu import InjectionSpec, make_ft_sgemm, perf
    from ft_sgemm_tpu.configs import KernelShape
    from ft_sgemm_tpu.ops.reference import sgemm_reference
    from ft_sgemm_tpu.utils.matrices import (
        generate_random_matrix, verify_matrix)

    size = SMOKE_SIZE
    tile = KernelShape("smoke", *SMOKE_BLOCK, (0,) * 7)
    rng = np.random.default_rng(10)
    a = generate_random_matrix(size, size, rng=rng)
    b = generate_random_matrix(size, size, rng=rng)
    c = generate_random_matrix(size, size, rng=rng)
    want = np.asarray(sgemm_reference(a, b, c, 1.0, -1.5))
    inj = InjectionSpec(enabled=True, every=1, magnitude=10000.0)
    context.setdefault("encode_modes", {})
    context.setdefault("errors", {})
    tl = _NoTimeline() if tl is None else tl
    stages = []
    ok_all = True
    for enc in ("vpu", "mxu"):
        try:
            with tl.span(f"ft_rowcol[{enc}]", kind="stage") as span_info:
                ft = make_ft_sgemm(tile, alpha=1.0, beta=-1.5,
                                   strategy="rowcol", encode=enc)
                t1 = time.monotonic()
                res = ft(a, b, c, inj)
                jax.block_until_ready(res.c)
                first = time.monotonic() - t1
                # Second call is warm: its wall is pure execute, and
                # first-minus-warm is the trace+compile share — the
                # smoke-grade compile/execute split (the 4096 path gets
                # the exact lower()/compile() split from
                # bench_seconds_per_call instead). With the persistent
                # compile cache warm, the first call's compile share
                # collapses to cache retrieval — the warm-start signal
                # CI's double-smoke job asserts on.
                t2 = time.monotonic()
                jax.block_until_ready(ft(a, b, c, inj).c)
                dt = time.monotonic() - t2
                ok, nbad, _ = verify_matrix(want, np.asarray(res.c),
                                            verbose=False)
                unc = int(res.num_uncorrectable)
                # "seconds" keeps its historical first-call meaning (the
                # committed baseline and the CI noise gate compare it;
                # at smoke size the warm wall is single-digit ms — far
                # too noisy to gate on). The warm call rides along as
                # warm_seconds, and the span split carries the
                # compile-vs-execute attribution.
                row = {
                    "corrected_ok": bool(ok),
                    "detections": int(res.num_detected),
                    "uncorrectable": unc, "seconds": round(first, 3),
                    "warm_seconds": round(dt, 3)}
                context["encode_modes"][enc] = row
                span_info["value"] = row
                span_info["compile_seconds"] = round(max(first - dt, 0.0),
                                                     6)
                span_info["execute_seconds"] = round(min(first, dt) + dt,
                                                     6)
            ok_all &= bool(ok) and unc == 0
            stages.append(perf.stage_row(
                f"ft_rowcol[{enc}]", first, m=size, n=size, k=size,
                block=SMOKE_BLOCK, strategy="rowcol", encode=enc,
                device_kind=device_kind))
        except Exception as e:  # noqa: BLE001 — record per-mode, keep going
            context["errors"][enc] = f"{type(e).__name__}: {e}"
            sys.stderr.write(traceback.format_exc())
            ok_all = False
    # Low-precision stages (ISSUE 7): one bf16-adaptive row (the V-ABFT
    # per-tile thresholds riding the in-kernel encode) and one int8 row
    # (int32-exact accumulation) — CI's proof that BOTH new axes
    # (threshold mode x dtype) run end to end on any backend, with
    # dtype-correct roofline rows (stage peak picked by dtype).
    context.setdefault("low_precision", {})
    lp_stages = [
        ("ft_rowcol[bf16-adaptive]", "bfloat16", "adaptive", a, b,
         np.asarray(sgemm_reference(a, b, c, 1.0, -1.5,
                                    in_dtype="bfloat16"))),
        ("ft_rowcol[int8]", "int8", "adaptive", np.round(a * 10.0),
         np.round(b * 10.0), None),
    ]
    for lp_name, lp_dtype, lp_thr, lp_a, lp_b, lp_want in lp_stages:
        try:
            if lp_want is None:
                lp_want = np.asarray(sgemm_reference(
                    lp_a, lp_b, c, 1.0, -1.5, in_dtype=lp_dtype))
            with tl.span(lp_name, kind="stage") as span_info:
                ft = make_ft_sgemm(tile, alpha=1.0, beta=-1.5,
                                   strategy="rowcol", threshold=lp_thr,
                                   in_dtype=lp_dtype)
                t1 = time.monotonic()
                res = ft(lp_a, lp_b, c, inj)
                jax.block_until_ready(res.c)
                lp_first = time.monotonic() - t1
                # Same smoke-grade compile/execute split as the encode
                # stages above: warm second call's wall is pure execute,
                # first-minus-warm is the trace+compile share.
                t2 = time.monotonic()
                jax.block_until_ready(ft(lp_a, lp_b, c, inj).c)
                lp_warm = time.monotonic() - t2
                ok, nbad, _ = verify_matrix(lp_want, np.asarray(res.c),
                                            verbose=False)
                unc = int(res.num_uncorrectable)
                row = {
                    "corrected_ok": bool(ok),
                    "detections": int(res.num_detected),
                    "uncorrectable": unc,
                    "seconds": round(lp_first, 3),
                    "warm_seconds": round(lp_warm, 3)}
                context["low_precision"][lp_name] = row
                span_info["value"] = row
                span_info["compile_seconds"] = round(
                    max(lp_first - lp_warm, 0.0), 6)
                span_info["execute_seconds"] = round(
                    min(lp_first, lp_warm) + lp_warm, 6)
            ok_all &= bool(ok) and unc == 0
            stages.append(perf.stage_row(
                lp_name, lp_first, m=size, n=size, k=size,
                block=SMOKE_BLOCK, strategy="rowcol", encode="vpu",
                dtype=lp_dtype,
                in_itemsize=1 if lp_dtype == "int8" else 2,
                device_kind=device_kind))
        except Exception as e:  # noqa: BLE001 — record per-stage, keep going
            context["errors"][lp_name] = f"{type(e).__name__}: {e}"
            sys.stderr.write(traceback.format_exc())
            ok_all = False
    # Compiled-artifact introspection of the vendor-path dot at this size
    # (guarded per backend: cost/memory analysis may be unavailable —
    # the dict then names what's missing instead of raising).
    try:
        from ft_sgemm_tpu.perf import hlo as perf_hlo

        with tl.span("hlo_introspect", kind="compile"):
            context["hlo"] = perf_hlo.introspect_jitted(
                lambda a, b, c: sgemm_reference(a, b, c, 1.0, -1.5),
                a, b, c, label="xla_dot_smoke")
    except Exception as e:  # noqa: BLE001
        context["errors"]["hlo"] = f"{type(e).__name__}: {e}"
    try:
        extra = {k: facts[k] for k in ("platform_requested",
                                       "platform_used", "fallback_reason")
                 if isinstance(facts, dict) and facts.get(k) is not None}
        cc_stats = _compile_cache_stats()
        if cc_stats is not None:
            context["compile_cache"] = cc_stats
            context["compile_cache_enabled"] = bool(cc_stats.get("enabled"))
            if cc_stats.get("reason"):
                context["compile_cache_reason"] = cc_stats["reason"]
            extra["compile_cache"] = cc_stats
        lint = _lint_facts()
        if lint is not None:
            extra["lint"] = lint
        tl_summary = _tl_summary_for_report(tl)
        wall = None
        if tl_summary:
            try:
                from ft_sgemm_tpu.perf import wallclock

                wall = wallclock.attribute_wall(tl_summary)
                wallclock.record_wall(wall)
            except Exception:  # noqa: BLE001 — attribution is best-effort
                wall = None
        manifest = perf.build_manifest(device_kind=device_kind,
                                       extra=extra or None)
        context["run_report"] = perf.RunReport(
            manifest=manifest, stages=stages,
            timeline=tl_summary, wall=wall).to_dict()
    except Exception as e:  # noqa: BLE001
        context["errors"]["run_report"] = f"{type(e).__name__}: {e}"
    return ok_all


def _serve_steady_state_compile_spans(tl_path):
    """Count compile records streamed AFTER the engine's ``prewarm_done``
    point — the warm-path purity number the serve artifact reports and
    CI pins at zero. None when the timeline is unavailable."""
    mod = _load_timeline_mod()
    if mod is None or not tl_path or not os.path.exists(tl_path):
        return None
    try:
        records = mod.read_timeline(tl_path)
    except OSError:
        return None
    t_done = None
    for rec in records:
        if rec.get("name") == "prewarm_done":
            t_done = rec.get("t")
    if t_done is None:
        return None
    return sum(1 for rec in records
               if rec.get("kind") == "compile"
               and rec.get("phase") == "start"
               and isinstance(rec.get("t"), (int, float))
               and rec["t"] > t_done)


def serve_main(argv):
    """``--serve [--smoke]``: the fault-tolerant serving goodput bench.

    Drives the ``ft_sgemm_tpu.serve`` layer — shape-bucketed continuous
    batching over an AOT-prewarmed bucket set with SDC injection — and
    prints ONE JSON line: goodput-under-injection (correct results per
    second) as the metric, with p50/p99 latency, throughput, and the
    retry/fault counters in context. No supervisor/worker split (the
    serve engine is its own scheduler): instead SIGTERM/SIGINT set a
    stop flag the load generator polls, so a deadline-killed run drains
    what it already accepted and emits a ``partial`` artifact — and the
    engine's streamed timeline (``FT_SGEMM_BENCH_TIMELINE``) holds
    per-batch spans and running ``serve_progress`` points for anything
    harder-killed than that. ``--workload=block`` serves TRANSFORMER
    BLOCKS instead of bare GEMMs (``serve/blocks.py``): ragged
    prefill/decode attention through the FT attention executors over an
    ABFT-checked paged KV cache, goodput reported as
    tokens-correct-per-second (metric ``serve_block_goodput_tps``) with
    stored-state fault counters (``kv_faults`` /
    ``kv_corrected_in_place`` / ``kv_page_restores``) in context;
    ``--decode-ratio=R`` and ``--kv-corrupt-rate=R`` shape the mix.
    ``--pool`` runs the MULTI-DEVICE pool stage
    (``serve/pool.py``): the same load drives the single-device engine
    and then a health-steered device pool over every local device —
    per-device AOT replicas, bounded async in-flight, a marked-sick
    device drained (``--sick-device=N``, default 1, ``none`` disables;
    GEMM workload only) — and the artifact reports goodput scaling
    (``context.scaling``), per-device placement
    (``context.pool.per_device``), and the drain outcome; rc!=0 unless
    placement spread over >1 device and the sick device was drained.
    ``--pool --workload=block`` dispatches the transformer-block engine
    through the same pool (per-device block replicas, ring executors
    off). ``--pool --evict-device=N`` runs the elastic-recovery FIRE
    DRILL instead (``ft_sgemm_tpu.resilience.run_eviction_drill``,
    DESIGN.md §18): persistent faults on device N under live load →
    EVICTION (placement permanently stops naming it, queued batches
    migrate, survivors re-confirmed in the re-AOT window) → recovery
    load + one rehearsal of every checksum tier and ladder rung; the
    artifact's ``context.recovery`` section (MTTR, tier-of-detection
    counts, panel-recompute flops ratio, goodput recovery ratio) is
    what the run ledger ingests as ``recovery.*`` measurements; rc!=0
    unless evicted with zero incorrect responses and recovered goodput.
    Flags: ``--smoke`` (the CPU/CI scenario),
    ``--requests=N``, ``--inject-rate=R``, ``--adversarial-rate=R``,
    ``--rate=RPS``, ``--buckets=256,512`` (block: padded SEQ sizes),
    ``--monitor-port=N`` (start
    the live /metrics-/healthz-/events exporter for the run — 0 binds an
    ephemeral port, URL streamed to stderr; ``cli top URL`` renders it).
    The artifact context embeds the final SLO/error-budget and
    device-health snapshot (``context.slo`` / ``context.device_health``)
    plus a RunReport whose SLO section ``cli report`` renders.
    """
    smoke = "--smoke" in argv
    pool = "--pool" in argv
    workload = "gemm"
    kw = {}
    bad = None
    sizes = None
    for f in argv:
        try:
            if f.startswith("--evict-device="):
                # Elastic-recovery fire-drill knob (resilience/
                # elastic.py): which pool device receives the
                # persistent fault stream and must be EVICTED.
                kw["evict_device"] = int(f.split("=", 1)[1])
            elif f.startswith("--sick-device="):
                # Pool drain self-test knob (serve/pool.py mark_sick):
                # which pool device is marked sick before the load;
                # "none" disables the marking.
                val = f.split("=", 1)[1]
                kw["sick_device"] = None if val == "none" else int(val)
            elif f.startswith("--workload="):
                workload = f.split("=", 1)[1]
                if workload not in ("gemm", "block"):
                    raise ValueError(
                        f"unknown workload {workload!r} (gemm|block)")
            elif f.startswith("--requests="):
                kw["num_requests"] = int(f.split("=", 1)[1])
            elif f.startswith("--inject-rate="):
                kw["inject_rate"] = float(f.split("=", 1)[1])
            elif f.startswith("--adversarial-rate="):
                kw["adversarial_rate"] = float(f.split("=", 1)[1])
            elif f.startswith("--rate="):
                kw["rate"] = float(f.split("=", 1)[1])
            elif f.startswith("--decode-ratio="):
                kw["decode_ratio"] = float(f.split("=", 1)[1])
            elif f.startswith("--kv-corrupt-rate="):
                kw["kv_corrupt_rate"] = float(f.split("=", 1)[1])
            elif f.startswith("--buckets="):
                sizes = tuple(
                    int(v) for v in f.split("=", 1)[1].split(",") if v)
            elif f.startswith("--monitor-port="):
                kw["monitor_port"] = int(f.split("=", 1)[1])
            elif f.startswith("--epilogue="):
                # Fused-epilogue bucket set (configs.EpilogueSpec
                # spelling, e.g. bias+relu) — GEMM workload only.
                kw["epilogue"] = f.split("=", 1)[1]
        except ValueError as e:
            bad = f"{f}: {e}"
    block = workload == "block"
    # One goodput vocabulary per workload: requests-correct/sec for bare
    # GEMMs, tokens-correct/sec for transformer blocks.
    metric = "serve_block_goodput_tps" if block else "serve_goodput_rps"
    unit = "tokens/s" if block else "requests/s"
    if sizes is not None:
        kw["seq_sizes" if block else "bucket_sizes"] = sizes
    if not block:
        for flag in ("decode_ratio", "kv_corrupt_rate"):
            if flag in kw:
                bad = f"--{flag.replace('_', '-')}= needs" \
                    " --workload=block"
    elif "epilogue" in kw:
        bad = "--epilogue= needs --workload=gemm"
    drill = "evict_device" in kw
    if "sick_device" in kw and (not pool or block or drill):
        bad = "--sick-device= needs --pool with the gemm workload"
    if drill and (not pool or block):
        bad = "--evict-device= needs --pool with the gemm workload"
    if bad:
        print(json.dumps({"metric": metric, "value": None,
                          "unit": unit, "vs_baseline": None,
                          "context": {"errors": {"argv": bad}}}),
              flush=True)
        return 2

    import threading

    stop = threading.Event()

    def on_signal(signum, frame):
        # First signal: stop accepting, drain, emit partial. The load
        # generator polls the flag between arrivals.
        stop.set()

    signal.signal(signal.SIGTERM, on_signal)
    signal.signal(signal.SIGINT, on_signal)

    context = {"serve": True, "smoke": smoke, "workload": workload,
               "pool": pool, "drill": drill, "errors": {}}
    tl = (_make_timeline(None)
          if os.environ.get("FT_SGEMM_BENCH_TIMELINE") else _NoTimeline())
    try:
        import jax  # noqa: F401
    except Exception as e:  # noqa: BLE001 — the line must still print
        context["errors"]["import"] = f"{type(e).__name__}: {e}"
        print(json.dumps({"metric": metric, "value": None,
                          "unit": unit, "vs_baseline": None,
                          "context": context}), flush=True)
        sys.stderr.write(traceback.format_exc())
        return 1
    with tl.span("compile_cache_setup", kind="compile"):
        cc = _setup_compile_cache()
        context["compile_cache"] = cc
        context["compile_cache_enabled"] = bool(cc.get("enabled"))
        if cc.get("reason"):
            context["compile_cache_reason"] = cc["reason"]
    with tl.span("backend_init", kind="compile"):
        facts, err = _backend_with_fallback()
    if facts is None:
        context["errors"]["backend"] = err
        print(json.dumps({"metric": metric, "value": None,
                          "unit": unit, "vs_baseline": None,
                          "context": context}), flush=True)
        return 1
    context.update(facts)
    value = None
    try:
        if block:
            from ft_sgemm_tpu.serve import run_block_serve_bench

            stats = run_block_serve_bench(smoke=smoke, timeline=tl,
                                          pool=pool,
                                          should_stop=stop.is_set,
                                          progress_out=sys.stderr, **kw)
            value = stats.get("goodput_tps")
        elif drill:
            from ft_sgemm_tpu.resilience import run_eviction_drill

            drill_kw = {k: v for k, v in kw.items()
                        if k in ("evict_device", "bucket_sizes")}
            if "num_requests" in kw:
                drill_kw["requests_per_phase"] = kw["num_requests"]
            stats = run_eviction_drill(smoke=smoke, timeline=tl,
                                       progress_out=sys.stderr,
                                       **drill_kw)
            value = stats.get("goodput_rps")
        elif pool:
            from ft_sgemm_tpu.serve import run_pool_serve_bench

            stats = run_pool_serve_bench(smoke=smoke, timeline=tl,
                                         should_stop=stop.is_set,
                                         progress_out=sys.stderr, **kw)
            value = stats.get("goodput_rps")
        else:
            from ft_sgemm_tpu.serve import run_serve_bench

            stats = run_serve_bench(smoke=smoke, timeline=tl,
                                    should_stop=stop.is_set,
                                    progress_out=sys.stderr, **kw)
            value = stats.get("goodput_rps")
        context.update(stats)
        if stop.is_set():
            context["partial"] = True
    except Exception as e:  # noqa: BLE001 — the line must still print
        context["errors"]["serve"] = f"{type(e).__name__}: {e}"
        sys.stderr.write(traceback.format_exc())
    spans = _serve_steady_state_compile_spans(
        os.environ.get("FT_SGEMM_BENCH_TIMELINE"))
    if spans is not None:
        context["steady_state_compile_spans"] = spans
    cc_stats = _compile_cache_stats()
    if cc_stats is not None:
        context["compile_cache"] = cc_stats
    try:
        # The serve artifact carries a RunReport too, so `cli report`
        # renders the run's environment + the final SLO/health section
        # (ISSUE 9: the artifact embeds the SLO/budget snapshot).
        from ft_sgemm_tpu.perf.report import RunReport, build_manifest

        serve_extra = {"serve": True, "workload": workload, "pool": pool}
        lint = _lint_facts()
        if lint is not None:
            serve_extra["lint"] = lint
        # PR 20: the cost plane rides the monitor snapshot (engines push
        # CostLedger snapshots into it); lift it to a first-class
        # context key so the ledger ingests economics.* measurements
        # and the RunReport renders its own section.
        econ = context.get("economics")
        if econ is None and isinstance(context.get("slo"), dict):
            econ = context["slo"].get("economics")
        if isinstance(econ, dict):
            context["economics"] = econ
        context["run_report"] = RunReport(
            manifest=build_manifest(extra=serve_extra),
            stages=[], slo=context.get("slo"),
            economics=econ if isinstance(econ, dict) else None).to_dict()
    except Exception as e:  # noqa: BLE001 — the line must still print
        context["errors"]["run_report"] = f"{type(e).__name__}: {e}"
    artifact = {"metric": metric,
                "value": value,
                "unit": unit, "vs_baseline": None,
                "context": context}
    print(json.dumps(artifact), flush=True)
    _ledger_append(artifact)
    ok = (value is not None and value > 0
          and context.get("completed", 0) > 0
          and (drill or context.get("correct")
               == context.get("completed"))
          and context.get("whole_queue_retries", 0) == 0)
    if ok and drill:
        # The drill's own acceptance verdict: evicted (not just
        # drained), zero incorrect/lost responses, nothing placed on
        # the evicted device afterward, goodput recovered.
        ok = bool(context.get("ok"))
    elif ok and pool:
        # The pool stage's own acceptance facts: placement actually
        # spread over the mesh, and a marked-sick device was drained.
        pool_stats = context.get("pool")
        pool_stats = pool_stats if isinstance(pool_stats, dict) else {}
        ok = (pool_stats.get("devices_used", 0) > 1
              and (context.get("sick_device") is None
                   or bool(context.get("sick_device_drained"))))
    return 0 if ok else 1


def smoke_main():
    """``--smoke``: one tiny size, both encode modes, any backend.

    A CI-runnable liveness check for the bench entrypoint: no supervisor,
    no TPU requirement, no records file — just the import path, the FT
    kernel factories under BOTH checksum-encode modes (injected faults
    must be corrected), and one JSON line on stdout carrying a full
    RunReport manifest (``ft_sgemm_tpu.perf``) with per-stage roofline
    rows. Keeps the bench entrypoint from silently rotting between
    hardware windows, and gives CI's ``bench-compare`` gate its
    artifact. A backend whose init fails falls back to CPU and records
    the fallback instead of dying (``_backend_with_fallback``).
    """
    t0 = time.monotonic()
    try:
        import jax  # noqa: F401 — the import itself is under test
    except Exception as e:  # noqa: BLE001 — the line must still print
        print(json.dumps({"metric": "bench_smoke", "value": 0, "unit": "ok",
                          "vs_baseline": None,
                          "context": {"smoke": True, "errors": {
                              "import": f"{type(e).__name__}: {e}"}}}),
              flush=True)
        sys.stderr.write(traceback.format_exc())
        return 1

    context = {"smoke": True, "size": SMOKE_SIZE, "errors": {}}
    # --smoke streams a timeline when FT_SGEMM_BENCH_TIMELINE names a
    # path (CI sets it, uploads the JSONL, and renders it with
    # ``cli timeline``); without the env var this is a no-op recorder.
    tl = (_make_timeline(None)
          if os.environ.get("FT_SGEMM_BENCH_TIMELINE") else _NoTimeline())
    # Same warm-start setup as the full worker: smoke is the CI probe of
    # the compile-cache contract (two runs sharing FT_SGEMM_COMPILE_CACHE
    # must show hits > 0 and a lower compile fraction on the second).
    with tl.span("compile_cache_setup", kind="compile"):
        cc_status = _setup_compile_cache()
        context["compile_cache"] = cc_status
        context["compile_cache_enabled"] = bool(cc_status.get("enabled"))
        if cc_status.get("reason"):
            context["compile_cache_reason"] = cc_status["reason"]
    with tl.span("backend_init", kind="compile"):
        facts, err = _backend_with_fallback()
    if facts is None:
        context["errors"]["backend"] = err
        print(json.dumps({"metric": "bench_smoke", "value": 0, "unit": "ok",
                          "vs_baseline": None, "context": context}),
              flush=True)
        return 1
    context.update(facts)
    try:
        ok_all = _smoke_measure(context,
                                device_kind=facts.get("device_kind"),
                                facts=facts, tl=tl)
    except Exception as e:  # noqa: BLE001 — the line must still print
        context["errors"]["smoke"] = f"{type(e).__name__}: {e}"
        sys.stderr.write(traceback.format_exc())
        ok_all = False
    context["seconds_total"] = round(time.monotonic() - t0, 3)
    artifact = {"metric": "bench_smoke", "value": 1 if ok_all else 0,
                "unit": "ok", "vs_baseline": None, "context": context}
    print(json.dumps(artifact), flush=True)
    _ledger_append(artifact)
    return 0 if ok_all else 1


def fleet_main(argv):
    """``--fleet [--smoke]``: the multi-process fleet smoke.

    Jax-free on the supervisor side by construction: this function
    path-loads ``ft_sgemm_tpu/fleet/launch.py`` (stdlib-only by
    contract) and drives ``launch_fleet`` — N REAL processes, each a
    jax.distributed rank with its own virtual CPU devices, running the
    worker's DCN-honesty phases plus the cross-host serve acts
    (``ft_sgemm_tpu/fleet/worker.py``). Prints ONE JSON line whose
    ``context.fleet`` block the run ledger ingests as ``fleet.*``
    measurements. rc 0 iff every rank reported ok AND the acceptance
    facts hold: a fault injected on a non-coordinator rank detected at
    the ``global`` checksum tier and attributed to the right
    (host, device) in the merged fleet view; that host EVICTED (not
    drained) under load with goodput recovered >= 0.7x baseline and
    zero incorrect results. Flags: ``--procs=N`` (default 2),
    ``--vdevs=M`` (default 4), ``--program=NAME`` (default smoke),
    ``--deadline=SECONDS``, ``--workdir=DIR`` (default: a fresh temp
    dir; rank logs/timelines/result.json land there either way).
    """
    import tempfile

    procs, vdevs = 2, 4
    program = "smoke"
    deadline = 540.0
    workdir = None
    bad = None
    for f in argv:
        try:
            if f.startswith("--procs="):
                procs = int(f.split("=", 1)[1])
            elif f.startswith("--vdevs="):
                vdevs = int(f.split("=", 1)[1])
            elif f.startswith("--program="):
                program = f.split("=", 1)[1]
            elif f.startswith("--deadline="):
                deadline = float(f.split("=", 1)[1])
            elif f.startswith("--workdir="):
                workdir = f.split("=", 1)[1]
        except ValueError as e:
            bad = f"{f}: {e}"
    if bad:
        sys.stderr.write(f"bench --fleet: bad flag {bad}\n")
        return 2
    if workdir is None:
        workdir = tempfile.mkdtemp(prefix="ft_sgemm_fleet_")

    import importlib.util

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "ft_sgemm_tpu", "fleet", "launch.py")
    spec = importlib.util.spec_from_file_location("_ft_fleet_launch", path)
    launch = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = launch
    spec.loader.exec_module(launch)

    t0 = time.monotonic()
    report = launch.launch_fleet(launch.FleetSpec(
        procs=procs, vdevs=vdevs, program=program, workdir=workdir,
        deadline_seconds=deadline, wedge_after=max(120.0, deadline / 3)))
    fleet = ((report.get("result") or {}).get("fleet")
             if isinstance(report.get("result"), dict) else None) or {}
    serve = ((report.get("result") or {}).get("serve")
             if isinstance(report.get("result"), dict) else None) or {}
    if fleet and isinstance(serve.get("dispatcher"), dict):
        # Per-slot request counts, hop-latency percentiles, and the
        # last measured clock skew (FleetDispatcher.stats()) ride the
        # artifact so summarize_bench can render the hop decomposition.
        fleet = dict(fleet, dispatcher=serve["dispatcher"])
    # PR 20: stitch supervisor + per-rank timelines into ONE
    # skew-corrected multi-process Perfetto trace — still jax-free
    # (traceview is stdlib-only/path-loadable, same as launch.py).
    trace_meta = None
    try:
        tv_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "ft_sgemm_tpu", "telemetry", "traceview.py")
        tv_spec = importlib.util.spec_from_file_location(
            "_ft_traceview", tv_path)
        tv = importlib.util.module_from_spec(tv_spec)
        sys.modules[tv_spec.name] = tv
        tv_spec.loader.exec_module(tv)
        trace, trace_path = tv.merge_fleet(workdir)
        meta = trace["otherData"]
        trace_meta = {
            "path": trace_path,
            "spans": meta.get("spans"),
            "points": meta.get("points"),
            "flows": meta.get("flows"),
            "cross_process_flows": meta.get("cross_process_flows"),
            "processes": meta.get("processes"),
            "ranks": meta.get("ranks"),
            "clock_skew_seconds": meta.get("clock_skew_seconds"),
        }
    except Exception as e:  # noqa: BLE001 — stitching never kills the smoke
        trace_meta = {"error": str(e)}
    localized = fleet.get("localized") or {}
    checks = {
        "ranks_ok": report.get("ok", False),
        "global_tier_detected": fleet.get("global_tier") == "global",
        "attributed_cross_host": (
            localized.get("host") is not None
            and localized.get("host") != 0
            and localized.get("device") is not None),
        "host_evicted_not_drained": (
            fleet.get("eviction_action") == "evicted"),
        "goodput_recovered": (
            (fleet.get("goodput_recovery_ratio") or 0) >= 0.7),
        "zero_incorrect": fleet.get("incorrect_responses") == 0,
        # PR 20: one trace_id must flow ACROSS the process boundary in
        # the merged trace, and the cost plane must have accounted the
        # run (useful + overhead fractions share one denominator).
        "trace_cross_process": bool(
            (trace_meta or {}).get("cross_process_flows")),
        "economics_accounted": (
            isinstance(fleet.get("economics"), dict)
            and fleet["economics"].get("useful_flops_fraction")
            is not None),
    }
    if program != "smoke":
        # Non-smoke programs (noop/counters/wedge) only promise their
        # own phases; acceptance is the rank statuses.
        checks = {"ranks_ok": report.get("ok", False)}
    ok_all = all(checks.values())
    context = {
        "procs": procs, "vdevs": vdevs, "program": program,
        "workdir": workdir,
        "coordinator": report.get("coordinator"),
        "rank_statuses": {r: info.get("status")
                          for r, info in (report.get("ranks")
                                          or {}).items()},
        "checks": checks,
        "fleet": fleet or None,
        "merged_trace": trace_meta,
        "economics": fleet.get("economics"),
        "clock_skew_seconds": fleet.get("clock_skew_seconds"),
        "wall_seconds": round(time.monotonic() - t0, 3),
    }
    artifact = {"metric": "fleet_goodput_recovery_ratio",
                "value": fleet.get("goodput_recovery_ratio"),
                "unit": "ratio", "vs_baseline": None, "context": context}
    print(json.dumps(artifact), flush=True)
    _ledger_append(artifact)
    if not ok_all:
        failed = sorted(k for k, v in checks.items() if not v)
        sys.stderr.write(f"bench --fleet: FAILED checks: {failed}\n")
    return 0 if ok_all else 1


def chaos_main(argv):
    """``--chaos [--smoke]``: the fault-model coverage campaign.

    Sweeps every declared fault model (``contracts.FAULT_MODELS``)
    across its workloads via :class:`ft_sgemm_tpu.chaos.ChaosCampaign`
    and prints ONE JSON line: the ``chaos_coverage`` artifact (overall
    detection rate as the metric, the full per-model matrix + policy
    recommendations in ``context.chaos``). The run ledger ingests the
    per-model ``chaos.*`` measurements, so ``cli trend --gate``
    thereafter fails a model whose detection rate regresses. The
    human-readable coverage table goes to stderr; ``--coverage-out=``
    additionally writes COVERAGE.json. rc per
    :func:`ft_sgemm_tpu.cli.chaos_verdict` — every model measured,
    correctable models at detection 1.0, zero incorrect results, zero
    clean-twin false positives.
    """
    from ft_sgemm_tpu.chaos.campaign import (
        ChaosCampaign,
        render_coverage,
    )
    from ft_sgemm_tpu.cli import chaos_verdict

    kw = {}
    coverage_path = None
    for f in argv:
        try:
            if f.startswith("--models="):
                kw["models"] = tuple(
                    v for v in f.split("=", 1)[1].split(",") if v)
            elif f.startswith("--episodes="):
                kw["episodes"] = int(f.split("=", 1)[1])
            elif f.startswith("--clean-episodes="):
                kw["clean_episodes"] = int(f.split("=", 1)[1])
            elif f.startswith("--seed="):
                kw["seed"] = int(f.split("=", 1)[1])
            elif f.startswith("--coverage-out="):
                coverage_path = f.split("=", 1)[1]
        except ValueError as e:
            sys.stderr.write(f"bench --chaos: {e}\n")
            return 2
    if "--smoke" in argv:
        kw.setdefault("episodes", 2)
        kw.setdefault("clean_episodes", 1)
    try:
        doc = ChaosCampaign(**kw).run()
    except ValueError as e:
        sys.stderr.write(f"bench --chaos: {e}\n")
        return 2
    sys.stderr.write(render_coverage(doc) + "\n")
    if coverage_path:
        with open(coverage_path, "w", encoding="utf-8") as fh:
            json.dump(doc, fh, indent=1)
            fh.write("\n")
    print(json.dumps(doc), flush=True)
    _ledger_append(doc)
    return 0 if chaos_verdict(doc) else 1


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--worker":
        sys.exit(worker_main(sys.argv[2]))
    if "--fleet" in sys.argv[1:]:
        sys.exit(fleet_main(sys.argv[1:]))
    if "--serve" in sys.argv[1:]:
        sys.exit(serve_main(sys.argv[1:]))
    if "--chaos" in sys.argv[1:]:
        sys.exit(chaos_main(sys.argv[1:]))
    if "--smoke" in sys.argv[1:]:
        sys.exit(smoke_main())
    if "--tuned" in sys.argv[1:]:
        # The worker inherits the supervisor's env (attempt launches build
        # env from os.environ), so one flag covers every relaunch.
        os.environ["FT_SGEMM_BENCH_TUNED"] = "1"
    sys.exit(main())
