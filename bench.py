"""Headline benchmark: fused-ABFT huge kernel at M=N=K=4096 on real TPU.

Prints ONE JSON line:
  metric      abft_kernel_huge GFLOPS at 4096 with reference-like injection
  vs_baseline ratio vs the reference's abft_kernel_huge on sm_80
              (4005 GFLOPS, reference README.md:53 / BASELINE.md)

Also embeds context fields: XLA f32 dot GFLOPS on the same chip and the
fraction of it we reach (north-star target >= 0.80, BASELINE.json), the
plain (non-FT) kernel GFLOPS, and the fused-ABFT overhead.

Resilience: the axon TPU tunnel occasionally fails backend init or a
compile with a transient error (round-1 postmortem: BENCH_r01.json died in
the first ``jax.device_put``). Backend bring-up is retried with exponential
backoff (~2 min budget), every measurement stage is independently retried,
a wall-clock deadline (``FT_SGEMM_BENCH_DEADLINE`` seconds, default 1500)
skips remaining context stages when the tunnel crawls, and the JSON line
is ALWAYS emitted — with whatever stages succeeded and the per-stage
errors recorded in ``context.errors``. Exit code is 0 iff the headline
value was measured.
"""

import json
import os
import sys
import time
import traceback

import numpy as np

sys.path.insert(0, ".")

SIZE = 4096
REFERENCE_ABFT_HUGE_GFLOPS = 4005.0  # sm_80, reference README.md:53
_T0 = time.monotonic()
_DEADLINE = float(os.environ.get("FT_SGEMM_BENCH_DEADLINE", 1500.0))


def _time_left() -> float:
    return _DEADLINE - (time.monotonic() - _T0)


def _retry(what, fn, errors, attempts=4, base=3.0):
    """Run fn() with exponential-backoff retries; record failure and return
    None instead of raising (transient axon tunnel errors: compile-helper
    HTTP 500s, backend-init UNAVAILABLE)."""
    last_tb = None
    last = None
    for i in range(attempts):
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 — must never kill the JSON line
            last = e
            last_tb = traceback.format_exc()
            if i < attempts - 1:
                time.sleep(min(base * (2 ** i), 60.0))
    errors[what] = f"{type(last).__name__}: {last}"
    sys.stderr.write(f"bench: stage {what!r} failed after {attempts}"
                     f" attempts:\n{last_tb}")
    return None


def _init_backend(errors):
    """Bring up the JAX backend (retrying, ~4-5 min budget) and return
    device info."""
    import jax

    def probe():
        devs = jax.devices()
        x = jax.device_put(np.zeros((8, 128), np.float32))
        jax.block_until_ready(x)
        return devs

    # Backoff sleeps 5+10+20+40+60x3 = 255s (~4.3 min) across 8 attempts,
    # plus probe time: axon tunnel outages observed live range from seconds
    # to hours; this covers the short tail without eating the whole
    # FT_SGEMM_BENCH_DEADLINE budget.
    devs = _retry("backend_init", probe, errors, attempts=8, base=5.0)
    if devs is None:
        return None
    return {"backend": jax.default_backend(),
            "device": str(devs[0]), "num_devices": len(devs)}


def main():
    errors = {}
    context = {"strategy": "weighted (deferred single-check localization)"}
    ft_gflops = None

    dev_info = _init_backend(errors)
    if dev_info is not None:
        context.update(dev_info)
        try:
            ft_gflops = _measure(context, errors)
        except Exception as e:  # noqa: BLE001 — the JSON line must survive
            errors["measure"] = f"{type(e).__name__}: {e}"
            sys.stderr.write(traceback.format_exc())

    context["errors"] = errors
    print(json.dumps({
        "metric": "abft_kernel_huge_gflops_4096",
        "value": None if ft_gflops is None else round(ft_gflops, 1),
        "unit": "GFLOPS",
        "vs_baseline": (None if ft_gflops is None
                        else round(ft_gflops / REFERENCE_ABFT_HUGE_GFLOPS, 3)),
        "context": context,
    }), flush=True)
    return 0 if ft_gflops is not None else 1


def _measure(context, errors):
    """All measurement stages; returns the headline GFLOPS (or None)."""
    import jax
    import jax.numpy as jnp

    from ft_sgemm_tpu import InjectionSpec, SHAPES, make_ft_sgemm, make_sgemm
    from ft_sgemm_tpu.ops.reference import sgemm_reference
    from ft_sgemm_tpu.utils.matrices import generate_random_matrix
    from ft_sgemm_tpu.utils.timing import bench_seconds_per_call

    flop = 2.0 * SIZE**3

    def put_inputs():
        rng = np.random.default_rng(10)
        return tuple(
            jax.device_put(generate_random_matrix(SIZE, SIZE, rng=rng))
            for _ in range(3))

    inputs = _retry("device_put_inputs", put_inputs, errors, attempts=4)
    if inputs is None:
        return None
    a, b, c = inputs

    def stage(name, fn, *args, attempts=2):
        if _time_left() <= 0:
            errors[name] = "skipped: bench deadline reached"
            return None
        sec = _retry(name, lambda: bench_seconds_per_call(
            fn, *args, min_device_time=2.0), errors, attempts=attempts)
        return None if sec is None else flop / 1e9 / sec

    # Headline FIRST so later-stage failures can't cost the round's number.
    inj = InjectionSpec.reference_like(SIZE, SHAPES["huge"].bk)
    ft = make_ft_sgemm("huge", alpha=1.0, beta=-1.5, strategy="weighted")
    ft_gflops = stage("ft_weighted", lambda a, b, x: ft(a, b, x, inj).c,
                      a, b, c, attempts=3)
    if ft_gflops is None:
        # The default cadence routes to the precomputed-expectation kernel;
        # if that path fails on this backend, fall back to the in-kernel
        # encode variant (any check_every < nk) so the round still gets a
        # valid FT headline. Same strategy, same correction guarantees.
        nk = SIZE // ft.shape_config.bk
        ft_fb = make_ft_sgemm("huge", alpha=1.0, beta=-1.5,
                              strategy="weighted",
                              check_every=max(1, nk // 2))
        ft_gflops = stage("ft_weighted_inkernel",
                          lambda a, b, x: ft_fb(a, b, x, inj).c,
                          a, b, c, attempts=2)
        if ft_gflops is not None:
            context["strategy"] = ("weighted (in-kernel encode fallback,"
                                   " 2 checks)")

    xla = stage("xla_dot", lambda a, b, x: sgemm_reference(a, b, x, 1.0, -1.5),
                a, b, c)
    if xla is not None:
        context["xla_dot_gflops"] = round(xla, 1)

    plain_fn = make_sgemm("huge", alpha=1.0, beta=-1.5)
    plain = stage("plain_huge", plain_fn, a, b, c)
    if plain is not None:
        context["kernel_sgemm_huge_gflops"] = round(plain, 1)

    ft_rc = make_ft_sgemm("huge", alpha=1.0, beta=-1.5, strategy="rowcol")
    rowcol = stage("ft_rowcol", lambda a, b, x: ft_rc(a, b, x, inj).c, a, b, c)
    if rowcol is not None:
        context["abft_rowcol_gflops"] = round(rowcol, 1)

    if ft_gflops is not None:
        if xla is not None:
            context["ft_vs_xla"] = round(ft_gflops / xla, 3)
        if plain is not None:
            context["abft_overhead"] = round(1.0 - ft_gflops / plain, 3)

    # TPU-native bf16 input mode (f32 accumulation + checksums): the MXU's
    # full-rate path — context only; the headline stays f32 for reference
    # parity (the reference is SGEMM).
    def bf16_stages():
        a16 = jax.device_put(jnp.asarray(a, jnp.bfloat16))
        b16 = jax.device_put(jnp.asarray(b, jnp.bfloat16))
        ft16 = make_ft_sgemm("huge", alpha=1.0, beta=-1.5,
                             strategy="weighted", in_dtype="bfloat16")
        # The bf16 override tile has a different bk: rebuild the
        # reference-like schedule so fault density matches the f32 row.
        inj16 = InjectionSpec.reference_like(SIZE, ft16.shape_config.bk)
        sec_ft = bench_seconds_per_call(
            lambda a, b, x: ft16(a, b, x, inj16).c, a16, b16, c,
            min_device_time=2.0)
        plain16 = make_sgemm("huge", alpha=1.0, beta=-1.5,
                             in_dtype="bfloat16")
        sec_plain = bench_seconds_per_call(plain16, a16, b16, c,
                                           min_device_time=2.0)
        xla16 = lambda a, b, x: sgemm_reference(  # noqa: E731
            a, b, x, 1.0, -1.5, in_dtype="bfloat16")
        sec_xla = bench_seconds_per_call(xla16, a16, b16, c,
                                         min_device_time=2.0)
        return flop / 1e9 / sec_ft, flop / 1e9 / sec_plain, flop / 1e9 / sec_xla

    if _time_left() <= 0:
        errors["bf16"] = "skipped: bench deadline reached"
        bf16 = None
    else:
        bf16 = _retry("bf16", bf16_stages, errors, attempts=2)
    if bf16 is not None:
        context["bf16_abft_huge_gflops"] = round(bf16[0], 1)
        context["bf16_sgemm_huge_gflops"] = round(bf16[1], 1)
        context["bf16_xla_dot_gflops"] = round(bf16[2], 1)
        context["bf16_ft_vs_xla"] = round(bf16[0] / bf16[2], 3)
        context["bf16_plain_vs_xla"] = round(bf16[1] / bf16[2], 3)

    context["injected_faults_per_tile"] = inj.expected_faults(
        SIZE, SHAPES["huge"].bk)
    return ft_gflops


if __name__ == "__main__":
    sys.exit(main())
