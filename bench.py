"""Headline benchmark: fused-ABFT huge kernel at M=N=K=4096 on real TPU.

Prints ONE JSON line:
  metric      abft_kernel_huge GFLOPS at 4096 with reference-like injection
  vs_baseline ratio vs the reference's abft_kernel_huge on sm_80
              (4005 GFLOPS, reference README.md:53 / BASELINE.md)

Also embeds context fields: XLA f32 dot GFLOPS on the same chip and the
fraction of it we reach (north-star target >= 0.80, BASELINE.json), the
plain (non-FT) kernel GFLOPS, and the fused-ABFT overhead.
"""

import json
import sys

import numpy as np

import jax

sys.path.insert(0, ".")

from ft_sgemm_tpu import InjectionSpec, SHAPES, make_ft_sgemm, make_sgemm  # noqa: E402
from ft_sgemm_tpu.ops.reference import sgemm_reference  # noqa: E402
from ft_sgemm_tpu.utils.matrices import generate_random_matrix  # noqa: E402
from ft_sgemm_tpu.utils.timing import bench_seconds_per_call  # noqa: E402

SIZE = 4096
REFERENCE_ABFT_HUGE_GFLOPS = 4005.0  # sm_80, reference README.md:53


def time_chained(fn, a, b, c):
    return bench_seconds_per_call(fn, a, b, c, min_device_time=2.0)


def main():
    rng = np.random.default_rng(10)
    a = jax.device_put(generate_random_matrix(SIZE, SIZE, rng=rng))
    b = jax.device_put(generate_random_matrix(SIZE, SIZE, rng=rng))
    c = jax.device_put(generate_random_matrix(SIZE, SIZE, rng=rng))
    flop = 2.0 * SIZE**3

    xla = lambda a, b, x: sgemm_reference(a, b, x, 1.0, -1.5)  # noqa: E731
    xla_gflops = flop / 1e9 / time_chained(xla, a, b, c)

    plain = make_sgemm("huge", alpha=1.0, beta=-1.5)
    plain_gflops = flop / 1e9 / time_chained(plain, a, b, c)

    inj = InjectionSpec.reference_like(SIZE, SHAPES["huge"].bk)
    # Headline: the weighted-checksum fused kernel (deferred single-check
    # localization — our fastest design that still *corrects* every fault).
    ft = make_ft_sgemm("huge", alpha=1.0, beta=-1.5, strategy="weighted")
    ft_fn = lambda a, b, x: ft(a, b, x, inj).c  # noqa: E731
    ft_gflops = flop / 1e9 / time_chained(ft_fn, a, b, c)

    ft_rc = make_ft_sgemm("huge", alpha=1.0, beta=-1.5, strategy="rowcol")
    ft_rc_fn = lambda a, b, x: ft_rc(a, b, x, inj).c  # noqa: E731
    rowcol_gflops = flop / 1e9 / time_chained(ft_rc_fn, a, b, c)

    # TPU-native bf16 input mode (f32 accumulation + checksums): the MXU's
    # full-rate path — context only; the headline stays f32 for reference
    # parity (the reference is SGEMM).
    ft16 = make_ft_sgemm("huge", alpha=1.0, beta=-1.5, strategy="weighted",
                         in_dtype="bfloat16")
    # The bf16 override tile has a different bk: rebuild the reference-like
    # schedule for it so fault density matches the f32 headline row.
    inj16 = InjectionSpec.reference_like(SIZE, ft16.shape_config.bk)
    ft16_fn = lambda a, b, x: ft16(a, b, x, inj16).c  # noqa: E731
    # Pre-cast so the wrappers' bf16 casts trace to no-ops in the rep loop.
    import jax.numpy as jnp
    a16 = jax.device_put(jnp.asarray(a, jnp.bfloat16))
    b16 = jax.device_put(jnp.asarray(b, jnp.bfloat16))
    bf16_ft_gflops = flop / 1e9 / time_chained(ft16_fn, a16, b16, c)
    plain16 = make_sgemm("huge", alpha=1.0, beta=-1.5, in_dtype="bfloat16")
    bf16_plain_gflops = flop / 1e9 / time_chained(plain16, a16, b16, c)

    print(json.dumps({
        "metric": "abft_kernel_huge_gflops_4096",
        "value": round(ft_gflops, 1),
        "unit": "GFLOPS",
        "vs_baseline": round(ft_gflops / REFERENCE_ABFT_HUGE_GFLOPS, 3),
        "context": {
            "strategy": "weighted (deferred single-check localization)",
            "xla_dot_gflops": round(xla_gflops, 1),
            "kernel_sgemm_huge_gflops": round(plain_gflops, 1),
            "abft_rowcol_gflops": round(rowcol_gflops, 1),
            "ft_vs_xla": round(ft_gflops / xla_gflops, 3),
            "abft_overhead": round(1.0 - ft_gflops / plain_gflops, 3),
            "bf16_abft_huge_gflops": round(bf16_ft_gflops, 1),
            "bf16_sgemm_huge_gflops": round(bf16_plain_gflops, 1),
            "backend": jax.default_backend(),
            "injected_faults_per_tile": inj.expected_faults(
                SIZE, SHAPES["huge"].bk),
        },
    }))


if __name__ == "__main__":
    main()
