"""Detection-rate and threshold-calibration analysis.

The reference fixes one operating point — fault magnitude 1e4 against
threshold 9.5e3 (``include_code_gen/ft_sgemm_huge.cuh:49-51``) — chosen so
that f32 checksum noise from its quantized ±{0,…,0.9} inputs stays far
below the threshold (SURVEY.md §4 "Determinism"). The paper behind it
(arXiv:2305.01024) evaluates the scheme by sweeping fault magnitudes and
measuring detection rates; the repo itself ships no such tooling.

This module makes that evaluation a first-class capability:

  - :func:`measure_noise_floor` — the largest |checksum residual| a clean
    (fault-free) run produces, measured through the two-pass baseline's
    residual outputs. Any detection threshold must sit above this.
  - :func:`calibrate_threshold` — noise floor × safety margin: the smallest
    threshold that cannot false-positive on the given data, and with it the
    smallest fault magnitude the kernels can reliably see.
  - :func:`detection_rate_sweep` — fraction of injected faults detected (and
    corrected, for correcting strategies) as the fault magnitude sweeps
    across the threshold, plus output correctness at each point.

Together they answer the two questions the reference hardcodes: "what
threshold is safe for THIS data?" and "how small a fault can we catch?".
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import numpy as np

from ft_sgemm_tpu.configs import KernelShape
from ft_sgemm_tpu.injection import InjectionSpec, REFERENCE_THRESHOLD
from ft_sgemm_tpu.ops.abft_baseline import abft_baseline_sgemm
from ft_sgemm_tpu.ops.ft_sgemm import make_ft_sgemm
from ft_sgemm_tpu.ops.reference import sgemm_reference
from ft_sgemm_tpu.utils.matrices import verify_matrix


def measure_noise_floor(a, b, c, *, alpha: float = 1.0, beta: float = -1.5,
                        panel_k: int = 256, precision: str = "highest",
                        in_dtype: str = "float32") -> float:
    """Max |checksum residual| of a clean run on the given inputs.

    Uses the two-pass baseline (its residuals are observable outputs;
    the fused kernels keep theirs in scratch). Checksum math is identical
    across designs — full row/col sums accumulated in f32 — so this bounds
    the fused kernels' clean residuals too (the baseline accumulates
    full-matrix sums, the worst case; per-tile residuals are smaller).
    """
    res = abft_baseline_sgemm(
        a, b, c, alpha, beta, panel_k=panel_k, precision=precision,
        in_dtype=in_dtype, threshold=np.inf,
    )
    return float(max(res.max_row_residual, res.max_col_residual))


# Empirically calibrated constants for estimate_noise_floor (see its
# docstring). Largest implied C_RAND measured: ~14 (CPU f32 pairwise
# reductions; sizes 256-2048; quantized, unit-gaussian, and 10x-gaussian
# inputs; implied values 10-14, stable across the grid). 32 is ~2.3x that
# worst case; hardware validation happens live in
# scripts/detection_study.py, which prints bound/measured each run.
# Defined in ops.common (single source shared with the traced estimator
# behind make_ft_sgemm(threshold="auto")).
from ft_sgemm_tpu.ops.common import (  # noqa: E402  (placed for context)
    NOISE_C_BIAS as _NOISE_C_BIAS,
    NOISE_C_RAND as _NOISE_C_RAND,
)


def estimate_noise_floor(a, b, c=None, *, alpha: float = 1.0,
                         beta: float = -1.5) -> float:
    """Closed-form bound on the clean checksum-residual noise — no GEMM run.

    The residual of a fault-free run is pure f32 rounding noise: the same
    T-term sum accumulated in two different orders (the checksum path vs
    the accumulator path), both tree/pairwise reductions in practice (XLA
    reductions, the kernels' VPU tile sums, the MXU's K accumulation). Two
    regimes, summed per term:

      - zero-mean (cancelling) data: partial sums random-walk at
        ~sqrt(t)*sigma, so the accumulated rounding error is
        ~C_rand * eps * sqrt(T) * sigma with sigma the per-term RMS;
      - biased (same-sign) data: partial sums grow linearly and tree
        summation error is bounded by ~C_bias * eps * log2(T) * T * |mu|
        with mu the per-term mean.

        product term: T = Tab = K * max(M, N), sigma = rms(a) * rms(b),
                      mu = mean(a) * mean(b), scaled by |alpha|
        beta*C term:  T = Tc = max(M, N), sigma = rms(c), mu = mean(c),
                      scaled by |beta|

    (the checksums seed from the row/col sums of beta*C — the C term
    dominates when |C| >> |A@B.T|, e.g. tiny inputs against a large
    pre-existing C). Pass ``c=None`` only when beta is 0.

    The constants are CALIBRATED, not folklore: measured noise floors
    (via :func:`measure_noise_floor`) across sizes 256-2048 and three
    input distributions imply C_rand in 10-14 under this model — the
    round-2 formula's random-walk ``T^1.5`` scaling overestimated by 4-6
    orders of magnitude AND with the wrong exponent (measured floors grow
    ~linearly in size, i.e. ~sqrt(T), not T^1.5). ``C_rand = 32`` keeps
    ~2.3x headroom over the worst implied value; the live detection study
    (``scripts/detection_study.py``) re-validates the bound against the
    hardware-measured floor every run.

    Useful when the data is too large to afford :func:`measure_noise_floor`
    (which costs a full two-pass GEMM): moments are O(n^2). For the
    reference's quantized +-{0..0.9} inputs at 4096 this lands orders of
    magnitude under the 9500 operating threshold, matching measurement.
    """
    # Pure-numpy evaluation of the SAME formula (constants shared from
    # ops.common; tests/test_analysis.py pins twin agreement against the
    # traced estimate_noise_floor_jnp that threshold="auto" evaluates).
    # Numpy on purpose: this is documented as a cheap estimator needing
    # no GEMM run, and a jnp delegate would trigger JAX backend init —
    # on the axon-tunnel machines, the exact hang mode the bench
    # supervisor exists to avoid (ADVICE.md r3).
    a = np.asarray(a)
    b = np.asarray(b)
    (m, k), n = a.shape, b.shape[0]
    tmax = float(max(m, n))
    eps = float(np.finfo(np.float32).eps)

    def rms(x):
        # Scale-invariant, mirroring the traced twin: normalize by max|x|
        # before squaring so near-f32-max inputs can't overflow to inf.
        xf = np.asarray(x, np.float32)
        scale = max(float(np.max(np.abs(xf))), 1e-30)
        return scale * float(np.sqrt(np.mean(np.square(xf / scale))))

    def term(t, sigma, mu):
        return eps * (_NOISE_C_RAND * np.sqrt(t) * sigma
                      + _NOISE_C_BIAS * np.log2(max(t, 2.0)) * t * abs(mu))

    noise = abs(alpha) * term(
        float(k) * tmax, rms(a) * rms(b),
        float(np.mean(a, dtype=np.float64)) *
        float(np.mean(b, dtype=np.float64)))
    if c is not None and beta != 0.0:
        cf = np.asarray(c, np.float32)
        noise += abs(beta) * term(tmax, rms(cf),
                                  float(np.mean(cf, dtype=np.float64)))
    elif beta != 0.0:
        raise ValueError(
            "estimate_noise_floor: pass c (or beta=0) — the beta*C term"
            " contributes residual noise the bound must include")
    # Saturate instead of inf (inf would silently disable detection when
    # used as a threshold) — same clamp as the traced twin.
    return float(min(noise, float(np.finfo(np.float32).max) / 16.0))


def adaptive_threshold_estimate(a, b, *, bm: int, bn: int,
                                margin: float = 8.0,
                                tile: Optional[tuple] = None):
    """Host twin of the in-kernel ``threshold="adaptive"`` derivation.

    Evaluates the SAME variance-bound formula
    (``ops.common.variance_bound_threshold`` — one implementation, two
    array modules) that the kernels evaluate per tile per check, at the
    full-K final-check point: moments over one (bm, K) row tile of A and
    one (bn, K) row tile of B (``tile=(i, j)`` picks which; default the
    whole operands — the moment-averaged view telemetry records).
    Returns ``(threshold, variance)`` where ``variance`` is the
    mean-square product statistic ``E[a^2] * E[b^2]`` the bound's random
    term scales by. Pure numpy — no jax import, callable from the
    bench supervisor and offline tooling.

    The brute-force-moment unit tests pin this twin against directly
    computed ``sum``/``sum(x^2)`` statistics, which transitively pins the
    kernels' in-kernel math (same shared formula, same inputs).
    """
    from ft_sgemm_tpu.ops.common import variance_bound_threshold

    a = np.asarray(a, np.float32)
    b = np.asarray(b, np.float32)
    if tile is not None:
        i, j = tile
        a = a[i * bm:(i + 1) * bm]
        b = b[j * bn:(j + 1) * bn]
    k = a.shape[1]
    rows_a = min(bm, a.shape[0])
    rows_b = min(bn, b.shape[0])
    n_a = float(rows_a * k)
    n_b = float(rows_b * k)
    t_ab = float(k) * float(max(bm, bn))
    thr = variance_bound_threshold(
        float(np.sum(a, dtype=np.float64)),
        float(np.sum(np.square(a, dtype=np.float64))),
        float(np.sum(b, dtype=np.float64)),
        float(np.sum(np.square(b, dtype=np.float64))),
        n_a=n_a, n_b=n_b, t_ab=t_ab,
        log2_t=float(np.log2(max(t_ab, 2.0))), margin=margin, xp=np)
    variance = float(
        (np.sum(np.square(a, dtype=np.float64)) / n_a)
        * (np.sum(np.square(b, dtype=np.float64)) / n_b))
    return float(thr), variance


@dataclasses.dataclass(frozen=True)
class ThresholdCalibration:
    noise_floor: float        # max clean residual observed
    threshold: float          # noise_floor * margin
    min_detectable: float     # smallest reliably-detectable |fault|:
                              # |fault| - noise > threshold  =>  2x threshold
    margin: float

    def spec_like(self, K: int, bk: int, magnitude: Optional[float] = None,
                  **kw) -> InjectionSpec:
        """Reference-style schedule at (default) the minimum detectable
        magnitude — the hardest faults this calibration still catches."""
        return InjectionSpec.reference_like(
            K, bk, magnitude=self.min_detectable if magnitude is None
            else magnitude, **kw)


def calibrate_threshold(a, b, c, *, alpha: float = 1.0, beta: float = -1.5,
                        margin: float = 8.0, precision: str = "highest",
                        in_dtype: str = "float32") -> ThresholdCalibration:
    """Pick the smallest safe threshold for the given inputs.

    ``threshold = noise_floor * margin`` guards against run-to-run reduction
    -order variance (XLA may re-tile reductions between compiles; the margin
    absorbs it). A fault is then *reliably* detectable when its residual
    contribution exceeds ``threshold + noise_floor``; ``min_detectable``
    rounds that up to ``2 * threshold``.

    The reference's fixed point sits far inside this: its noise floor at
    K=6144 is O(1) while err_bound1=9500 (margin ~1e3).
    """
    floor = measure_noise_floor(a, b, c, alpha=alpha, beta=beta,
                                precision=precision, in_dtype=in_dtype)
    thr = float(max(floor, np.finfo(np.float32).tiny) * margin)
    return ThresholdCalibration(
        noise_floor=floor, threshold=thr, min_detectable=2.0 * thr,
        margin=margin,
    )


@dataclasses.dataclass(frozen=True)
class DetectionPoint:
    magnitude: float
    expected_faults: int      # faults injected over the whole run
    detected: int             # faults the kernel reported
    detection_rate: float     # detected / expected
    output_correct: bool      # corrected C passes the reference tolerance
                              # (for "global": C untouched => False once
                              # magnitude breaks the verify tolerance)


def detection_rate_sweep(
    a, b, c,
    magnitudes: Sequence[float],
    shape: KernelShape | str = "huge",
    *,
    strategy: str = "rowcol",
    threshold: float | str = REFERENCE_THRESHOLD,
    alpha: float = 1.0,
    beta: float = -1.5,
    num_faults: int = 4,
    precision: str = "highest",
    in_dtype: str = "float32",
    interpret: Optional[bool] = None,
) -> list[DetectionPoint]:
    """Detection/correction behavior as fault magnitude sweeps the threshold.

    For each magnitude: inject a reference-style rotating schedule of
    ``num_faults`` faults per C tile, count in-kernel detections, and verify
    the output against the XLA oracle. Magnitudes below the threshold are
    *designed* misses (the scheme's blind spot — also quantifies it);
    magnitudes above it must all be caught.
    """
    # String shapes stay names: make_ft_sgemm resolves them through the
    # per-dtype tile overrides (configs.BF16_TILE_OVERRIDES).
    a = np.asarray(a, np.float32)
    b = np.asarray(b, np.float32)
    c = np.asarray(c, np.float32)
    k = a.shape[1]
    # Oracle matches the kernel's input mode (bf16-rounded for bf16).
    want = np.asarray(sgemm_reference(a, b, c, alpha, beta,
                                      in_dtype=in_dtype))
    ft = make_ft_sgemm(shape, alpha=alpha, beta=beta, strategy=strategy,
                       threshold=threshold, precision=precision,
                       in_dtype=in_dtype, interpret=interpret)
    # Fault accounting must follow the tile the kernel ACTUALLY runs: named
    # shapes may swap to a dtype-tuned tile (configs.BF16_TILE_OVERRIDES)
    # and their oversized blocks shrink to the problem
    # (ops.common.shrink_block); explicit KernelShape objects run as-is.
    from ft_sgemm_tpu.ops.common import shrink_block

    eff = (shrink_block(ft.shape_config, a.shape[0], b.shape[0], k)
           if isinstance(shape, str) else ft.shape_config)
    points = []
    for mag in magnitudes:
        inj = InjectionSpec.reference_like(k, eff.bk, num_faults=num_faults,
                                           magnitude=float(mag))
        per_tile = inj.expected_faults(k, eff.bk)
        grid_m = -(-a.shape[0] // eff.bm)
        grid_n = -(-b.shape[0] // eff.bn)
        expected = per_tile * grid_m * grid_n
        res = ft(a, b, c, inj)
        detected = int(res.num_detected)
        ok, _, _ = verify_matrix(want, np.asarray(res.c), verbose=False)
        points.append(DetectionPoint(
            magnitude=float(mag),
            expected_faults=expected,
            detected=detected,
            detection_rate=detected / expected if expected else 0.0,
            output_correct=bool(ok),
        ))
    return points


__all__ = [
    "DetectionPoint",
    "ThresholdCalibration",
    "adaptive_threshold_estimate",
    "calibrate_threshold",
    "detection_rate_sweep",
    "estimate_noise_floor",
    "measure_noise_floor",
]
