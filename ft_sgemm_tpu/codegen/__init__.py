"""Kernel-variant generator CLI (reference ``code_gen/`` workflow analog)."""
