"""Kernel generator CLI — the TPU analog of the reference's code generator.

The reference metaprograms CUDA source strings: ``code_gen/main.py`` takes
``<shape> <if_abft>`` argv, calls ``ft_sgemm_code_gen`` (``code_gen.py:4``)
and writes ``../include_code_gen/{ft_}sgemm_<shape>.cuh``; ``gen.sh`` loops
the 6 shapes x {0,1} (``gen.sh:1-13``). The emitted source is committed and
compiled later.

On TPU the "generator" is the Pallas kernel factory + XLA: kernels are
instantiated from :class:`KernelShape` configs at trace time, so there is no
source string to write. What IS worth materializing — and what this CLI
emits — is the **lowered artifact** per variant: the jaxpr and the
StableHLO/Mosaic text the factory produces for given shapes, written to
``generated/{ft_}sgemm_<shape>.txt``. Same argv contract, same 12-variant
sweep, same inspect-what-will-run purpose.

Usage (mirrors main.py / gen.sh):
    python -m ft_sgemm_tpu.codegen.gen <shape> <if_abft> [M N K] [--out=DIR]
    python -m ft_sgemm_tpu.codegen.gen all            # the gen.sh loop
    python -m ft_sgemm_tpu.codegen.gen list           # the param table
    python -m ft_sgemm_tpu.codegen.gen tuned          # tuner-cache winners

``--dtype=`` lowers any member of the kernel family's input-dtype axis
(``configs.IN_DTYPES`` + the fp8 aliases) — an axis the CUDA generator
has no analog for. Per-dtype legality routes through
``configs.check_kernel_legality``: the FT variant runs each dtype's
``DEFAULT_STRATEGY`` (int8 -> rowcol), and a (shape, dtype) pair the
family cannot lower is SKIPPED with the named constraint, never a crash.

``tuned`` dumps the lowered artifact for every persisted tuner-cache
winner (``ft_sgemm_tpu.tuner.cache``) — tile AND variant axes
(pipeline depth, grid order, dimension semantics, cadence, fused
epilogue), the way the reference generator emitted its tuned family.
Artifacts land as ``tuned_<bm>x<bn>x<bk>[_<variant tags>].txt``.
"""

from __future__ import annotations

import pathlib
import sys

import jax
import jax.numpy as jnp

from ft_sgemm_tpu.configs import (
    DEFAULT_STRATEGY,
    IN_DTYPES,
    SHAPES,
    SHAPE_ORDER,
    KernelShape,
    KernelVariant,
    canonical_in_dtype,
    canonical_variant,
    check_kernel_legality,
)
from ft_sgemm_tpu.injection import InjectionSpec
from ft_sgemm_tpu.ops.common import dtype_suffix
from ft_sgemm_tpu.ops.ft_sgemm import make_ft_sgemm
from ft_sgemm_tpu.ops.sgemm import make_sgemm

DEFAULT_OUT = pathlib.Path("generated")
DEFAULT_MNK = (1024, 1024, 1024)


def variant_name(shape_name: str, if_abft: bool,
                 in_dtype: str = "float32") -> str:
    return f"{'ft_' if if_abft else ''}sgemm_{shape_name}{dtype_suffix(in_dtype)}"


def strategy_for_dtype(in_dtype: str) -> str:
    """The FT strategy the generator lowers for one dtype — the family's
    own per-dtype default (``configs.DEFAULT_STRATEGY``: weighted for the
    float dtypes, rowcol for int8's exact path)."""
    return DEFAULT_STRATEGY[canonical_in_dtype(in_dtype)]


def lower_variant(shape_name, if_abft: bool, m: int, n: int, k: int,
                  in_dtype: str = "float32",
                  variant: KernelVariant | None = None,
                  strategy: str | None = None,
                  encode: str = "vpu"):
    """Build + lower one kernel variant; returns (jaxpr text, lowered text).

    ``shape_name`` is a named shape or an explicit
    :class:`~ft_sgemm_tpu.configs.KernelShape` (the ``tuned`` path);
    ``variant`` pins the kernel-variant axes (None = defaults);
    ``strategy`` overrides the per-dtype default FT strategy. Legality
    routes through ``configs.check_kernel_legality`` — an illegal
    (strategy, dtype) pair raises the family's own constraint error,
    which ``main`` renders as a NAMED skip.
    """
    in_dtype = canonical_in_dtype(in_dtype)
    var = canonical_variant(variant)
    if if_abft:
        strategy = strategy or strategy_for_dtype(in_dtype)
        check_kernel_legality(strategy=strategy, encode=encode,
                              in_dtype=in_dtype)
        kfn = make_ft_sgemm(shape_name, in_dtype=in_dtype,
                            strategy=strategy, encode=encode,
                            variant=variant, tunable=False)
        if var.epilogue_spec.bias:
            bias = jnp.zeros((n,), jnp.float32)
            fn = lambda a, b, c: kfn(  # noqa: E731
                a, b, c, InjectionSpec.none(), bias=bias).c
        else:
            fn = lambda a, b, c: kfn(a, b, c, InjectionSpec.none()).c  # noqa: E731
    else:
        kfn = make_sgemm(shape_name, in_dtype=in_dtype, variant=variant,
                         tunable=False)
        if var.epilogue_spec.bias:
            bias = jnp.zeros((n,), jnp.float32)
            fn = lambda a, b, c: kfn(a, b, c, bias=bias)  # noqa: E731
        else:
            fn = kfn
    # a/b enter as f32 and are cast inside fn — matches the CLI/user path.
    args = (
        jax.ShapeDtypeStruct((m, k), jnp.float32),
        jax.ShapeDtypeStruct((n, k), jnp.float32),
        jax.ShapeDtypeStruct((m, n), jnp.float32),
    )
    jaxpr = jax.make_jaxpr(fn)(*args)
    lowered = jax.jit(fn).lower(*args)
    return str(jaxpr), lowered.as_text()


def dump_variant(shape_name: str, if_abft: bool, m: int, n: int, k: int,
                 out_dir: pathlib.Path,
                 in_dtype: str = "float32") -> pathlib.Path:
    name = variant_name(shape_name, if_abft, in_dtype)
    jaxpr, lowered = lower_variant(shape_name, if_abft, m, n, k, in_dtype)
    out_dir.mkdir(parents=True, exist_ok=True)
    path = out_dir / f"{name}.txt"
    # Record the tile the variant actually lowers with: bf16 named shapes
    # resolve through configs.BF16_TILE_OVERRIDES, and named shapes
    # auto-shrink oversized blocks to the problem size.
    from ft_sgemm_tpu.configs import shape_for_dtype
    from ft_sgemm_tpu.ops.common import shrink_block

    shape = shrink_block(
        shape_for_dtype(SHAPES[shape_name], if_abft, in_dtype), m, n, k)
    header = (
        f"// {name}: Pallas TPU kernel variant (M,N,K)=({m},{n},{k})\n"
        f"// block tile (bm,bn,bk)={shape.block}"
        f"  reference params {shape.ref_params}\n"
        f"// in_dtype={in_dtype}  backend={jax.default_backend()}\n"
    )
    path.write_text(
        header
        + "\n// ===== jaxpr =====\n" + jaxpr
        + "\n\n// ===== lowered (StableHLO) =====\n" + lowered
    )
    return path


def _variant_tags(var: KernelVariant) -> str:
    """Filename tags for a tuned winner's non-default variant axes, e.g.
    ``_pipe3_nm_cad2_epi_bias_relu`` (empty for the default variant)."""
    tags = []
    if var.pipeline_depth != 2:
        tags.append(f"pipe{var.pipeline_depth}")
    if var.grid_order != "mn":
        tags.append(var.grid_order)
    if var.dim_semantics != "parallel":
        tags.append(var.dim_semantics[:3])
    if var.check_every is not None:
        tags.append(f"cad{var.check_every}")
    if var.epilogue != "none":
        tags.append("epi_" + var.epilogue.replace("+", "_"))
    return ("_" + "_".join(tags)) if tags else ""


def dump_tuned(out_dir: pathlib.Path, cache_path=None, out=None):
    """Dump the lowered artifact for every tuner-cache winner.

    Iterates the persisted schema-4 entries (``tuner.cache``), rebuilds
    each winner as an explicit tile + :class:`KernelVariant`, and lowers
    the FT kernel it would dispatch — the generator's answer to "show me
    the code the TUNED family runs", not just the shipped SHAPES table.
    Entries whose key axes this build cannot lower (foreign device
    kinds are fine — lowering is device-independent — but e.g. a stale
    illegal combo) are skipped with the named reason. Returns the list
    of written paths.
    """
    from ft_sgemm_tpu.tuner import cache as tuner_cache

    out = sys.stdout if out is None else out
    entries = tuner_cache.load_entries(cache_path)
    written = []
    if not entries:
        print("no tuner-cache entries"
              f" ({cache_path or tuner_cache.cache_path()})", file=out)
        return written
    for key, rec in sorted(entries.items()):
        parts = dict(
            p.split("=", 1) for p in key.split("|") if "=" in p)
        fields = key.split("|")
        in_dtype = fields[2] if len(fields) > 2 else "float32"
        strategy = fields[3] if len(fields) > 3 else "weighted"
        bm, bn, bk = rec["block"]
        problem = rec.get("problem") or [bm, bn, bk]
        try:
            var = canonical_variant(rec.get("variant"))
            tile = KernelShape(f"tuned_{bm}x{bn}x{bk}", bm, bn, bk,
                               (0,) * 7)
            if_abft = strategy != "plain"
            jaxpr, lowered = lower_variant(
                tile, if_abft, *problem, in_dtype=in_dtype, variant=var,
                strategy=(None if not if_abft else strategy),
                encode=parts.get("enc", "vpu"))
        except (ValueError, KeyError) as e:
            print(f"skip {key}: {e}", file=out)
            continue
        out_dir.mkdir(parents=True, exist_ok=True)
        name = (f"tuned_{bm}x{bn}x{bk}{_variant_tags(var)}"
                f"{dtype_suffix(in_dtype)}"
                + ("" if strategy == "plain" else f"_{strategy}"))
        path = out_dir / f"{name}.txt"
        header = (
            f"// {name}: TUNED Pallas kernel variant\n"
            f"// cache key: {key}\n"
            f"// problem (M,N,K)={tuple(problem)}"
            f"  block tile (bm,bn,bk)=({bm},{bn},{bk})\n"
            f"// variant: pipe={var.pipeline_depth}"
            f" grid={var.grid_spelling} cad={var.cadence_spelling}"
            f" epi={var.epilogue}"
            f"  (key constraint: pipe={parts.get('pipe', 'auto')}"
            f" grid={parts.get('grid', 'auto')})\n"
            f"// in_dtype={in_dtype}  backend={jax.default_backend()}\n"
        )
        path.write_text(
            header
            + "\n// ===== jaxpr =====\n" + jaxpr
            + "\n\n// ===== lowered (StableHLO) =====\n" + lowered
        )
        written.append(path)
        print(f"wrote {path}", file=out)
    return written


def print_table(out=sys.stdout):
    """The canonical shape table (reference main.py:8-16)."""
    print(f"{'name':8s} {'bm':>5s} {'bn':>5s} {'bk':>5s}   "
          f"{'reference [ms,ns,ks,mw,nw,mr,nr]'}", file=out)
    for name in (*SHAPE_ORDER, "test"):
        s = SHAPES[name]
        print(f"{name:8s} {s.bm:5d} {s.bn:5d} {s.bk:5d}   {list(s.ref_params)}",
              file=out)


class _UsageError(Exception):
    pass


def _parse_mnk(tokens, what):
    """M N K must be given together (all three) or not at all."""
    if not tokens:
        return DEFAULT_MNK
    if len(tokens) != 3:
        raise _UsageError(
            f"{what}: M N K must be given as all three values, got {tokens}")
    try:
        return tuple(map(int, tokens))
    except ValueError:
        raise _UsageError(f"{what}: M N K must be integers, got {tokens}")


def main(argv=None) -> int:
    argv = list(sys.argv if argv is None else argv)
    if any(a in ("-h", "--help") for a in argv[1:]):
        print(__doc__)
        return 0
    args = []
    out_dir = DEFAULT_OUT
    in_dtype = "float32"
    for tok in argv[1:]:
        if tok.startswith("--out="):
            out_dir = pathlib.Path(tok.split("=", 1)[1])
        elif tok.startswith("--dtype="):
            in_dtype = tok.split("=", 1)[1]
            try:
                in_dtype = canonical_in_dtype(in_dtype)
            except ValueError:
                print(f"--dtype must be one of {IN_DTYPES} (or an fp8"
                      f" alias), got {in_dtype!r}", file=sys.stderr)
                return 2
        elif tok.startswith("--"):
            print(f"unknown flag {tok!r} (--out=DIR, --dtype=DTYPE)",
                  file=sys.stderr)
            return 2
        else:
            args.append(tok)
    if not args:
        print(__doc__)
        return 2
    try:
        if args[0] == "list":
            print_table()
            return 0
        if args[0] == "tuned":
            if len(args) > 1:
                print(f"tuned takes no positional arguments, got"
                      f" {args[1:]}", file=sys.stderr)
                return 2
            dump_tuned(out_dir)
            return 0
        if args[0] == "all":
            m, n, k = _parse_mnk(args[1:], "all")
            for if_abft in (False, True):  # gen.sh order: plain 6, then ft 6
                for name in SHAPE_ORDER:
                    try:
                        path = dump_variant(name, if_abft, m, n, k,
                                            out_dir, in_dtype)
                    except ValueError as e:
                        # Named skip, never a crash: the kernel family's
                        # own legality constraint says WHY this (shape,
                        # dtype) row cannot lower (the tuner's
                        # prune-reason discipline).
                        print(f"skip {variant_name(name, if_abft, in_dtype)}:"
                              f" {e}")
                        continue
                    print(f"wrote {path}")
            return 0
        shape_name = args[0]
        if shape_name not in SHAPES:
            print(f"unknown shape {shape_name!r}; known: {sorted(SHAPES)}",
                  file=sys.stderr)
            return 2
        if len(args) > 1:
            try:
                if_abft = bool(int(args[1]))
            except ValueError:
                raise _UsageError(
                    f"if_abft must be 0 or 1, got {args[1]!r}")
        else:
            if_abft = False
        m, n, k = _parse_mnk(args[2:5] if len(args) > 2 else [], shape_name)
        if len(args) > 5:
            print(f"unexpected extra arguments: {args[5:]}", file=sys.stderr)
            return 2
    except _UsageError as e:
        print(str(e), file=sys.stderr)
        return 2
    path = dump_variant(shape_name, if_abft, m, n, k, out_dir, in_dtype)
    print(f"wrote {path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
