"""Kernel generator CLI — the TPU analog of the reference's code generator.

The reference metaprograms CUDA source strings: ``code_gen/main.py`` takes
``<shape> <if_abft>`` argv, calls ``ft_sgemm_code_gen`` (``code_gen.py:4``)
and writes ``../include_code_gen/{ft_}sgemm_<shape>.cuh``; ``gen.sh`` loops
the 6 shapes x {0,1} (``gen.sh:1-13``). The emitted source is committed and
compiled later.

On TPU the "generator" is the Pallas kernel factory + XLA: kernels are
instantiated from :class:`KernelShape` configs at trace time, so there is no
source string to write. What IS worth materializing — and what this CLI
emits — is the **lowered artifact** per variant: the jaxpr and the
StableHLO/Mosaic text the factory produces for given shapes, written to
``generated/{ft_}sgemm_<shape>.txt``. Same argv contract, same 12-variant
sweep, same inspect-what-will-run purpose.

Usage (mirrors main.py / gen.sh):
    python -m ft_sgemm_tpu.codegen.gen <shape> <if_abft> [M N K] [--out=DIR]
    python -m ft_sgemm_tpu.codegen.gen all            # the gen.sh loop
    python -m ft_sgemm_tpu.codegen.gen list           # the param table

``--dtype=bfloat16`` lowers the bf16 input variants (suffix ``_bfloat16``
in the artifact name) — an axis the CUDA generator has no analog for.
"""

from __future__ import annotations

import pathlib
import sys

import jax
import jax.numpy as jnp

from ft_sgemm_tpu.configs import SHAPES, SHAPE_ORDER
from ft_sgemm_tpu.injection import InjectionSpec
from ft_sgemm_tpu.ops.common import dtype_suffix
from ft_sgemm_tpu.ops.ft_sgemm import make_ft_sgemm
from ft_sgemm_tpu.ops.sgemm import make_sgemm

DEFAULT_OUT = pathlib.Path("generated")
DEFAULT_MNK = (1024, 1024, 1024)


def variant_name(shape_name: str, if_abft: bool,
                 in_dtype: str = "float32") -> str:
    return f"{'ft_' if if_abft else ''}sgemm_{shape_name}{dtype_suffix(in_dtype)}"


def lower_variant(shape_name: str, if_abft: bool, m: int, n: int, k: int,
                  in_dtype: str = "float32"):
    """Build + lower one kernel variant; returns (jaxpr text, lowered text)."""
    if if_abft:
        kfn = make_ft_sgemm(shape_name, in_dtype=in_dtype)
        fn = lambda a, b, c: kfn(a, b, c, InjectionSpec.none()).c  # noqa: E731
    else:
        fn = make_sgemm(shape_name, in_dtype=in_dtype)
    # a/b enter as f32 and are cast inside fn — matches the CLI/user path.
    args = (
        jax.ShapeDtypeStruct((m, k), jnp.float32),
        jax.ShapeDtypeStruct((n, k), jnp.float32),
        jax.ShapeDtypeStruct((m, n), jnp.float32),
    )
    jaxpr = jax.make_jaxpr(fn)(*args)
    lowered = jax.jit(fn).lower(*args)
    return str(jaxpr), lowered.as_text()


def dump_variant(shape_name: str, if_abft: bool, m: int, n: int, k: int,
                 out_dir: pathlib.Path,
                 in_dtype: str = "float32") -> pathlib.Path:
    name = variant_name(shape_name, if_abft, in_dtype)
    jaxpr, lowered = lower_variant(shape_name, if_abft, m, n, k, in_dtype)
    out_dir.mkdir(parents=True, exist_ok=True)
    path = out_dir / f"{name}.txt"
    # Record the tile the variant actually lowers with: bf16 named shapes
    # resolve through configs.BF16_TILE_OVERRIDES, and named shapes
    # auto-shrink oversized blocks to the problem size.
    from ft_sgemm_tpu.configs import shape_for_dtype
    from ft_sgemm_tpu.ops.common import shrink_block

    shape = shrink_block(
        shape_for_dtype(SHAPES[shape_name], if_abft, in_dtype), m, n, k)
    header = (
        f"// {name}: Pallas TPU kernel variant (M,N,K)=({m},{n},{k})\n"
        f"// block tile (bm,bn,bk)={shape.block}"
        f"  reference params {shape.ref_params}\n"
        f"// in_dtype={in_dtype}  backend={jax.default_backend()}\n"
    )
    path.write_text(
        header
        + "\n// ===== jaxpr =====\n" + jaxpr
        + "\n\n// ===== lowered (StableHLO) =====\n" + lowered
    )
    return path


def print_table(out=sys.stdout):
    """The canonical shape table (reference main.py:8-16)."""
    print(f"{'name':8s} {'bm':>5s} {'bn':>5s} {'bk':>5s}   "
          f"{'reference [ms,ns,ks,mw,nw,mr,nr]'}", file=out)
    for name in (*SHAPE_ORDER, "test"):
        s = SHAPES[name]
        print(f"{name:8s} {s.bm:5d} {s.bn:5d} {s.bk:5d}   {list(s.ref_params)}",
              file=out)


class _UsageError(Exception):
    pass


def _parse_mnk(tokens, what):
    """M N K must be given together (all three) or not at all."""
    if not tokens:
        return DEFAULT_MNK
    if len(tokens) != 3:
        raise _UsageError(
            f"{what}: M N K must be given as all three values, got {tokens}")
    try:
        return tuple(map(int, tokens))
    except ValueError:
        raise _UsageError(f"{what}: M N K must be integers, got {tokens}")


def main(argv=None) -> int:
    argv = list(sys.argv if argv is None else argv)
    if any(a in ("-h", "--help") for a in argv[1:]):
        print(__doc__)
        return 0
    args = []
    out_dir = DEFAULT_OUT
    in_dtype = "float32"
    for tok in argv[1:]:
        if tok.startswith("--out="):
            out_dir = pathlib.Path(tok.split("=", 1)[1])
        elif tok.startswith("--dtype="):
            in_dtype = tok.split("=", 1)[1]
            if in_dtype not in ("float32", "bfloat16"):
                print(f"--dtype must be float32 or bfloat16, got {in_dtype!r}",
                      file=sys.stderr)
                return 2
        elif tok.startswith("--"):
            print(f"unknown flag {tok!r} (--out=DIR, --dtype=DTYPE)",
                  file=sys.stderr)
            return 2
        else:
            args.append(tok)
    if not args:
        print(__doc__)
        return 2
    try:
        if args[0] == "list":
            print_table()
            return 0
        if args[0] == "all":
            m, n, k = _parse_mnk(args[1:], "all")
            for if_abft in (False, True):  # gen.sh order: plain 6, then ft 6
                for name in SHAPE_ORDER:
                    path = dump_variant(name, if_abft, m, n, k, out_dir,
                                        in_dtype)
                    print(f"wrote {path}")
            return 0
        shape_name = args[0]
        if shape_name not in SHAPES:
            print(f"unknown shape {shape_name!r}; known: {sorted(SHAPES)}",
                  file=sys.stderr)
            return 2
        if len(args) > 1:
            try:
                if_abft = bool(int(args[1]))
            except ValueError:
                raise _UsageError(
                    f"if_abft must be 0 or 1, got {args[1]!r}")
        else:
            if_abft = False
        m, n, k = _parse_mnk(args[2:5] if len(args) > 2 else [], shape_name)
        if len(args) > 5:
            print(f"unexpected extra arguments: {args[5:]}", file=sys.stderr)
            return 2
    except _UsageError as e:
        print(str(e), file=sys.stderr)
        return 2
    path = dump_variant(shape_name, if_abft, m, n, k, out_dir, in_dtype)
    print(f"wrote {path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
