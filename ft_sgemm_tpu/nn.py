"""Training-framework integration: fault-tolerant flax modules.

The reference is a standalone kernel study; a TPU framework's GEMMs live
inside model code. This module packages the differentiable FT matmul
(:mod:`ft_sgemm_tpu.ops.autodiff`) as drop-in `flax.linen`_ layers so a
model gains ABFT protection by swapping ``nn.Dense`` for
:class:`FtDense` — forward and both backward GEMMs run through the
fused-ABFT Pallas kernels, and per-step fault counts are observable
through flax's variable collections.

.. _flax.linen: https://flax.readthedocs.io

Example::

    import flax.linen as nn
    from ft_sgemm_tpu.nn import FtDense

    class Model(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = nn.relu(FtDense(512, threshold="auto")(x))
            return FtDense(10, threshold="auto")(x)

    model = Model()
    vars_ = model.init(key, x)
    out, mutated = model.apply(vars_, x, mutable=["ft_counts"])
    mutated["ft_counts"]  # per-layer detections / uncorrectable

``mutable=["ft_counts"]`` is only needed when you want the counts; a
plain ``model.apply(vars_, x)`` works and simply drops them.

``ft_counts`` is a PER-APPLY output (like flax's ``intermediates``):
read it from the mutated-variables return and act on it; never merge it
back into the variables passed to the next apply — sow reduces onto
carried-in values, so merging would accumulate counts across steps and
permanently latch the ``uncorrectable`` re-run gate. Within one apply,
counts DO sum across invocations of the same module instance (weight
tying, ``nn.scan``), so no invocation's report can be overwritten.
"""

from __future__ import annotations

from typing import Optional, Union

import flax.linen as nn
import jax.numpy as jnp

from ft_sgemm_tpu.configs import KernelShape
from ft_sgemm_tpu.injection import InjectionSpec
from ft_sgemm_tpu.ops.autodiff import make_ft_matmul

# Counts are written to this flax variable collection (pass
# ``mutable=["ft_counts"]`` to ``apply`` to receive them).
COUNTS_COLLECTION = "ft_counts"


class FtDense(nn.Module):
    """``nn.Dense`` with every GEMM ABFT-protected.

    The layer computes ``x @ kernel + bias`` with ``x`` (..., in) flattened
    to (batch, in): the forward product and both gradient products (dX,
    dKernel) run through the fused-ABFT kernels of
    :func:`ft_sgemm_tpu.make_ft_matmul` — SDC in any of them is detected
    and corrected in-kernel before it can reach activations, gradients,
    or optimizer state.

    ``threshold`` defaults to ``"auto"``: each GEMM's detection
    threshold calibrates to its own operands per call, so unit-scale
    activations and cotangent-scale gradients both get correspondingly
    tight thresholds (a fixed reference-style 9500 would be inert at
    training magnitudes — ops/autodiff.py module docstring).

    Detections and the residual-after-correct ``uncorrectable`` count of
    the forward GEMM are stored in the ``ft_counts`` variable collection
    under this module's scope — request them with
    ``apply(..., mutable=["ft_counts"])``; nonzero ``uncorrectable``
    means the step must be re-run (corruption reported, never silent).
    """

    features: int
    use_bias: bool = True
    strategy: str = "weighted"
    # "auto" by default: training-scale activations and (smaller still)
    # cotangents sit far below the reference's fixed 9500 operating
    # point — a fixed default would leave detection inert at exactly the
    # scales this layer exists to protect. Per-call calibration costs no
    # recompiles (runtime SMEM thresholds).
    threshold: Union[float, str] = "auto"
    bwd_threshold: Optional[Union[float, str]] = None
    shape: Union[KernelShape, str] = "huge"
    # "bfloat16" feeds the GEMMs at the MXU's full-rate input format (f32
    # accumulation and checksums); the layer's output then follows the
    # input's dtype so downstream ops keep the model's precision.
    in_dtype: str = "float32"
    inject: Optional[InjectionSpec] = None  # self-test mode
    inject_bwd: Optional[InjectionSpec] = None  # bwd-only self-test mode
    kernel_init: nn.initializers.Initializer = (
        nn.initializers.lecun_normal())
    bias_init: nn.initializers.Initializer = nn.initializers.zeros_init()

    @nn.compact
    def __call__(self, x, bwd_sink=None):
        """Apply the layer; optionally open the backward-counts channel.

        ``bwd_sink`` (any (2,) f32 array, value ignored) opens the
        gradient side-channel of :func:`ft_sgemm_tpu.make_ft_matmul`:
        thread one sink through the model into each FtDense and
        differentiate the loss with respect to it — the sink's "gradient"
        is ``[detections, uncorrectable]`` summed over every backward
        GEMM that consumed it, so a violated correction assumption in
        dX/dKernel is reported to the training loop, never silent
        (``examples/train_ft.py`` shows the step shape).
        """
        in_features = x.shape[-1]
        kernel = self.param("kernel", self.kernel_init,
                            (in_features, self.features), jnp.float32)
        batch_shape = x.shape[:-1]
        x2 = x.reshape(-1, in_features)
        mm = make_ft_matmul(
            self.shape, strategy=self.strategy, threshold=self.threshold,
            bwd_threshold=self.bwd_threshold, inject=self.inject,
            inject_bwd=self.inject_bwd, in_dtype=self.in_dtype,
            with_counts=True, with_bwd_counts=bwd_sink is not None)
        # The FT kernels compute a @ b.T with b stored (out, in): pass the
        # transposed kernel, matching a linear layer's stored weight.
        kt = jnp.swapaxes(kernel, 0, 1)
        res = (mm(x2, kt) if bwd_sink is None
               else mm(x2, kt, bwd_sink))
        out = res.out
        # Counts ride a variable collection via sow: flax's channel for
        # non-differentiable per-call outputs. Integer values take no
        # gradients; when the collection is not mutable (plain apply),
        # sow drops the writes silently. reduce_fn SUMS across calls: a
        # module instance applied more than once per step (weight tying,
        # nn.scan) must not let a later clean call's 0 overwrite an
        # earlier call's nonzero uncorrectable — every invocation's
        # report survives into the step's re-run gate. sow also reduces
        # onto any value already present in the PASSED-IN variables, so:
        # (a) nothing is sown during the init trace (init's returned
        # variables would otherwise pre-load the first real step), and
        # (b) ``ft_counts`` is a per-apply output like flax's
        # ``intermediates`` — read it from ``mutated``, do NOT merge it
        # back into the variables you pass to the next apply (doing so
        # would accumulate counts across steps and latch the re-run gate).
        if not self.is_initializing():
            accumulate = lambda prev, new: prev + new  # noqa: E731
            zero = lambda: jnp.int32(0)  # noqa: E731
            self.sow(COUNTS_COLLECTION, "detections", res.detections,
                     reduce_fn=accumulate, init_fn=zero)
            self.sow(COUNTS_COLLECTION, "uncorrectable", res.uncorrectable,
                     reduce_fn=accumulate, init_fn=zero)
        if self.use_bias:
            bias = self.param("bias", self.bias_init, (self.features,),
                              jnp.float32)
            out = out + bias
        # Drop-in dtype behavior: the FT kernels accumulate and return
        # f32; hand downstream ops the caller's activation dtype.
        return out.astype(x.dtype).reshape(*batch_shape, self.features)


__all__ = ["COUNTS_COLLECTION", "FtDense"]
