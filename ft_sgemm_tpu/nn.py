"""Training-framework integration: fault-tolerant flax modules.

The reference is a standalone kernel study; a TPU framework's GEMMs live
inside model code. This module packages the differentiable FT matmul
(:mod:`ft_sgemm_tpu.ops.autodiff`) as drop-in `flax.linen`_ layers so a
model gains ABFT protection by swapping ``nn.Dense`` for
:class:`FtDense` — forward and both backward GEMMs run through the
fused-ABFT Pallas kernels, and per-step fault counts are observable
through flax's variable collections.

.. _flax.linen: https://flax.readthedocs.io

Example::

    import flax.linen as nn
    from ft_sgemm_tpu.nn import FtDense

    class Model(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = nn.relu(FtDense(512, threshold="auto")(x))
            return FtDense(10, threshold="auto")(x)

    model = Model()
    vars_ = model.init(key, x)
    out, mutated = model.apply(vars_, x, mutable=["ft_counts"])
    mutated["ft_counts"]  # per-layer detections / uncorrectable

``mutable=["ft_counts"]`` is only needed when you want the counts; a
plain ``model.apply(vars_, x)`` works and simply drops them.

``ft_counts`` is a PER-APPLY output (like flax's ``intermediates``):
read it from the mutated-variables return and act on it; never merge it
back into the variables passed to the next apply — sow reduces onto
carried-in values, so merging would accumulate counts across steps and
permanently latch the ``uncorrectable`` re-run gate. Within one apply,
counts DO sum across repeated invocations of the same module instance
(weight tying), so no invocation's report can be overwritten; under
``nn.scan`` with ``variable_axes={"ft_counts": 0}`` (what
:class:`FtTransformer` does) each step instead sows into its own
stacked per-layer slice.
"""

from __future__ import annotations

from typing import Optional, Union

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from ft_sgemm_tpu import telemetry
from ft_sgemm_tpu.configs import KernelShape
from ft_sgemm_tpu.injection import InjectionSpec
from ft_sgemm_tpu.ops.attention import (
    QK_SHAPE,
    PV_SHAPE,
    make_ft_attention_diff,
)
from ft_sgemm_tpu.ops.autodiff import make_ft_matmul

# Counts are written to this flax variable collection (pass
# ``mutable=["ft_counts"]`` to ``apply`` to receive them).
COUNTS_COLLECTION = "ft_counts"


def _sow_counts(module, pairs):
    """Report count leaves into ``ft_counts`` under ``module``'s scope.

    Counts ride a variable collection via sow: flax's channel for
    non-differentiable per-call outputs. Integer values take no
    gradients; when the collection is not mutable (plain apply), sow
    drops the writes silently. reduce_fn SUMS across calls: a module
    instance applied more than once per step (weight tying, nn.scan)
    must not let a later clean call's 0 overwrite an earlier call's
    nonzero uncorrectable — every invocation's report survives into the
    step's re-run gate. sow also reduces onto any value already present
    in the PASSED-IN variables, so: (a) nothing is sown during the init
    trace (init's returned variables would otherwise pre-load the first
    real step), and (b) ``ft_counts`` is a per-apply output like flax's
    ``intermediates`` — read it from ``mutated``, do NOT merge it back
    into the variables you pass to the next apply (doing so would
    accumulate counts across steps and latch the re-run gate).
    """
    if module.is_initializing():
        return
    pairs = list(pairs)
    accumulate = lambda prev, new: prev + new  # noqa: E731
    zero = lambda: jnp.int32(0)  # noqa: E731
    for name, leaf in pairs:
        module.sow(COUNTS_COLLECTION, name, jnp.asarray(leaf),
                   reduce_fn=accumulate, init_fn=zero)
    if telemetry.enabled():
        # Per-layer fault attribution: the telemetry event carries the
        # module's scope path (e.g. "attn/query") alongside the counts.
        # Under a caller's jit the counts are tracers and record_* skips
        # itself; eager applies record one event per layer invocation.
        import types

        d = dict(pairs)
        counts = types.SimpleNamespace(
            detections=d.get("detections"),
            uncorrectable=d.get("uncorrectable"),
            softmax_flags=d.get("softmax_flags"))
        path = getattr(module, "path", None)
        layer = ("/".join(str(p) for p in path) if path
                 else (module.name or type(module).__name__))
        record = (telemetry.record_attention if "softmax_flags" in d
                  else telemetry.record_gemm)
        record(f"nn.{type(module).__name__}", counts, layer=layer)


def _summed_counts(res):
    """(name, scalar) count pairs for an attention result — per-call
    totals of the vmapped per-head counters."""
    return (("detections", jnp.sum(res.detections)),
            ("softmax_flags", jnp.sum(res.softmax_flags)),
            ("uncorrectable", jnp.sum(res.uncorrectable)))


class FtDense(nn.Module):
    """``nn.Dense`` with every GEMM ABFT-protected.

    The layer computes ``x @ kernel + bias`` with ``x`` (..., in) flattened
    to (batch, in): the forward product and both gradient products (dX,
    dKernel) run through the fused-ABFT kernels of
    :func:`ft_sgemm_tpu.make_ft_matmul` — SDC in any of them is detected
    and corrected in-kernel before it can reach activations, gradients,
    or optimizer state.

    ``threshold`` defaults to ``"auto"``: each GEMM's detection
    threshold calibrates to its own operands per call, so unit-scale
    activations and cotangent-scale gradients both get correspondingly
    tight thresholds (a fixed reference-style 9500 would be inert at
    training magnitudes — ops/autodiff.py module docstring).

    Detections and the residual-after-correct ``uncorrectable`` count of
    the forward GEMM are stored in the ``ft_counts`` variable collection
    under this module's scope — request them with
    ``apply(..., mutable=["ft_counts"])``; nonzero ``uncorrectable``
    means the step must be re-run (corruption reported, never silent).
    """

    features: int
    use_bias: bool = True
    strategy: str = "weighted"
    # "auto" by default: training-scale activations and (smaller still)
    # cotangents sit far below the reference's fixed 9500 operating
    # point — a fixed default would leave detection inert at exactly the
    # scales this layer exists to protect. Per-call calibration costs no
    # recompiles (runtime SMEM thresholds).
    threshold: Union[float, str] = "auto"
    bwd_threshold: Optional[Union[float, str]] = None
    shape: Union[KernelShape, str] = "huge"
    # "bfloat16" feeds the GEMMs at the MXU's full-rate input format (f32
    # accumulation and checksums); the layer's output then follows the
    # input's dtype so downstream ops keep the model's precision.
    in_dtype: str = "float32"
    inject: Optional[InjectionSpec] = None  # self-test mode
    inject_bwd: Optional[InjectionSpec] = None  # bwd-only self-test mode
    kernel_init: nn.initializers.Initializer = (
        nn.initializers.lecun_normal())
    bias_init: nn.initializers.Initializer = nn.initializers.zeros_init()

    @nn.compact
    def __call__(self, x, bwd_sink=None):
        """Apply the layer; optionally open the backward-counts channel.

        ``bwd_sink`` (any (2,) f32 array, value ignored) opens the
        gradient side-channel of :func:`ft_sgemm_tpu.make_ft_matmul`:
        thread one sink through the model into each FtDense and
        differentiate the loss with respect to it — the sink's "gradient"
        is ``[detections, uncorrectable]`` summed over every backward
        GEMM that consumed it, so a violated correction assumption in
        dX/dKernel is reported to the training loop, never silent
        (``examples/train_ft.py`` shows the step shape).
        """
        in_features = x.shape[-1]
        kernel = self.param("kernel", self.kernel_init,
                            (in_features, self.features), jnp.float32)
        batch_shape = x.shape[:-1]
        x2 = x.reshape(-1, in_features)
        mm = make_ft_matmul(
            self.shape, strategy=self.strategy, threshold=self.threshold,
            bwd_threshold=self.bwd_threshold, inject=self.inject,
            inject_bwd=self.inject_bwd, in_dtype=self.in_dtype,
            with_counts=True, with_bwd_counts=bwd_sink is not None)
        # The FT kernels compute a @ b.T with b stored (out, in): pass the
        # transposed kernel, matching a linear layer's stored weight.
        kt = jnp.swapaxes(kernel, 0, 1)
        # suppress(): this layer's _sow_counts record (with the module
        # path) is the one event for the call; the inner FT matmul must
        # not also record an anonymous op-level event.
        with telemetry.suppress():
            res = (mm(x2, kt) if bwd_sink is None
                   else mm(x2, kt, bwd_sink))
        out = res.out
        # Counts ride the ft_counts collection (semantics: _sow_counts).
        _sow_counts(self, (("detections", res.detections),
                           ("uncorrectable", res.uncorrectable)))
        if self.use_bias:
            bias = self.param("bias", self.bias_init, (self.features,),
                              jnp.float32)
            out = out + bias
        # Drop-in dtype behavior: the FT kernels accumulate and return
        # f32; hand downstream ops the caller's activation dtype.
        return out.astype(x.dtype).reshape(*batch_shape, self.features)


def _qkv_projections(mod, x, bwd_sink):
    """Shared attention preamble: resolve feature sizes and apply the
    FtDense Q/K/V projections (called from the owning module's compact
    ``__call__``, so the submodules attach to its scope). Self-test
    injection drives EVERY GEMM of the layer — the projections as well
    as the attention core — so a layer-level ``inject``/``inject_bwd``
    exercises the full protection surface. Returns
    ``(q, k, v, qkv, out_features, d_head, dense_kw)``."""
    d_model = x.shape[-1]
    qkv = mod.qkv_features or d_model
    out_feat = mod.out_features or d_model
    if qkv % mod.num_heads:
        raise ValueError(
            f"qkv_features {qkv} not divisible by num_heads "
            f"{mod.num_heads}")
    dense_kw = dict(
        use_bias=mod.use_bias, strategy=mod.strategy,
        threshold=mod.threshold, bwd_threshold=mod.bwd_threshold,
        shape=mod.dense_shape, in_dtype=mod.in_dtype,
        inject=mod.inject, inject_bwd=mod.inject_bwd)
    q = FtDense(qkv, name="query", **dense_kw)(x, bwd_sink)
    k = FtDense(qkv, name="key", **dense_kw)(x, bwd_sink)
    v = FtDense(qkv, name="value", **dense_kw)(x, bwd_sink)
    return q, k, v, qkv, out_feat, qkv // mod.num_heads, dense_kw


class FtSelfAttention(nn.Module):
    """Multi-head self-attention with every GEMM ABFT-protected.

    The model-family layer above :class:`FtDense`: Q/K/V/output
    projections are :class:`FtDense` layers, and each head's attention
    core runs through :func:`ft_sgemm_tpu.make_ft_attention_diff` — all
    six GEMM executions of its forward + backward (QKᵀ, PV, dV, dP, dQ,
    dK) go through the fused-ABFT Pallas kernels, the softmax
    normalization invariant and sampled dual recompute guard the
    elementwise stage, and counts surface per layer through the
    ``ft_counts`` collection (``detections`` / ``uncorrectable`` sum the
    projections and the attention core; ``softmax_flags`` is the
    attention core's softmax check).

    Accepts ``(L, D)`` or ``(batch, L, D)`` inputs. ``causal=True``
    applies the end-aligned decoder mask. ``bwd_sink`` (optional, any
    (2,) f32 array) opens the backward-counts gradient side-channel
    through the projections AND the attention core — differentiate with
    respect to it for ``[detections, uncorrectable]`` over every
    backward GEMM of the layer.
    """

    num_heads: int
    qkv_features: Optional[int] = None  # default: model dim
    out_features: Optional[int] = None  # default: model dim
    causal: bool = False
    use_bias: bool = True
    strategy: str = "weighted"
    threshold: Union[float, str] = "auto"  # see FtDense.threshold
    bwd_threshold: Optional[Union[float, str]] = None
    dense_shape: Union[KernelShape, str] = "huge"
    qk_shape: KernelShape = QK_SHAPE
    pv_shape: KernelShape = PV_SHAPE
    in_dtype: str = "float32"
    inject: Optional[InjectionSpec] = None  # attention-core self-test
    inject_bwd: Optional[InjectionSpec] = None

    @nn.compact
    def __call__(self, x, bwd_sink=None):
        q, k, v, qkv, out_feat, d_head, dense_kw = _qkv_projections(
            self, x, bwd_sink)

        batch_shape = x.shape[:-2]
        length = x.shape[-2]
        split = lambda t: t.reshape(  # noqa: E731 — (B, H, L, d_head)
            -1, length, self.num_heads, d_head).transpose(0, 2, 1, 3)
        q, k, v = split(q), split(k), split(v)

        attn = make_ft_attention_diff(
            causal=self.causal, strategy=self.strategy,
            threshold=self.threshold, bwd_threshold=self.bwd_threshold,
            inject=self.inject, inject_bwd=self.inject_bwd,
            qk_shape=self.qk_shape, pv_shape=self.pv_shape,
            in_dtype=self.in_dtype, with_counts=True,
            with_bwd_counts=bwd_sink is not None)
        args = (q, k, v) + (() if bwd_sink is None else (bwd_sink,))
        axes = (0, 0, 0) + (() if bwd_sink is None else (None,))
        res = jax.vmap(jax.vmap(attn, in_axes=axes), in_axes=axes)(*args)

        _sow_counts(self, _summed_counts(res))

        out = res.out.transpose(0, 2, 1, 3).reshape(
            *batch_shape, length, qkv)
        return FtDense(out_feat, name="out", **dense_kw)(out, bwd_sink)


class FtRingSelfAttention(nn.Module):
    """Long-context self-attention: the attention core runs the DISTRIBUTED
    ring (sequence-parallel) path over a device mesh.

    Same protection surface as :class:`FtSelfAttention`, but each head's
    core is :func:`ft_sgemm_tpu.parallel.make_ring_ft_attention_diff`:
    K/V shards rotate the ICI ring through the online-softmax recurrence,
    every per-hop GEMM of the forward AND the backward ring pass goes
    through the fused-ABFT kernels, detection counts ``psum`` over the
    ring, and dK/dV accumulators rotate home with their blocks. The layer
    is how a transformer trains on sequences no single device can hold —
    with the same never-silent fault contract as the single-device path.

    Input is an unbatched ``(L, D)`` sequence with ``L`` divisible by the
    mesh's ring size (sequence parallelism shards L; batch, if any, is an
    outer ``vmap``/``shard_map`` axis). ``bwd_sink`` opens the gradient
    side-channel through the projections and every ring hop's backward
    GEMMs (psum'd over the ring).
    """

    mesh: Mesh
    num_heads: int
    qkv_features: Optional[int] = None
    out_features: Optional[int] = None
    causal: bool = False
    use_bias: bool = True
    strategy: str = "weighted"
    threshold: Union[float, str] = "auto"
    bwd_threshold: Optional[Union[float, str]] = None
    dense_shape: Union[KernelShape, str] = "huge"
    qk_shape: KernelShape = QK_SHAPE
    pv_shape: KernelShape = PV_SHAPE
    in_dtype: str = "float32"
    inject: Optional[InjectionSpec] = None
    inject_bwd: Optional[InjectionSpec] = None

    @nn.compact
    def __call__(self, x, bwd_sink=None):
        from ft_sgemm_tpu.parallel import make_ring_ft_attention_diff

        if x.ndim != 2:
            raise ValueError(
                f"FtRingSelfAttention takes an unbatched (L, D) sequence, "
                f"got shape {x.shape}; vmap/shard_map an outer batch axis")
        q, k, v, qkv, out_feat, d_head, dense_kw = _qkv_projections(
            self, x, bwd_sink)

        length = x.shape[0]
        heads = lambda t: t.reshape(  # noqa: E731 — (H, L, d_head)
            length, self.num_heads, d_head).transpose(1, 0, 2)
        q, k, v = heads(q), heads(k), heads(v)

        attn = make_ring_ft_attention_diff(
            self.mesh, causal=self.causal, strategy=self.strategy,
            threshold=self.threshold, bwd_threshold=self.bwd_threshold,
            inject=self.inject, inject_bwd=self.inject_bwd,
            qk_shape=self.qk_shape, pv_shape=self.pv_shape,
            in_dtype=self.in_dtype, with_counts=True,
            with_bwd_counts=bwd_sink is not None)
        # vmap over heads COMPOSES with the inner shard_map: every hop
        # ppermutes the head-stacked K/V block once, so ring rounds stay
        # 2·(devices) per step instead of multiplying by num_heads (a
        # per-head Python loop would serialize H full ring passes).
        args = (q, k, v) + (() if bwd_sink is None else (bwd_sink,))
        axes = (0, 0, 0) + (() if bwd_sink is None else (None,))
        res = jax.vmap(attn, in_axes=axes)(*args)

        _sow_counts(self, _summed_counts(res))

        out = jnp.moveaxis(res.out, 0, 1).reshape(length, qkv)
        return FtDense(out_feat, name="out", **dense_kw)(out, bwd_sink)


class FtTransformerBlock(nn.Module):
    """Pre-LN transformer block with ABFT on every GEMM.

    ``x + Attn(LN(x))`` then ``x + MLP(LN(x))`` — the standard block,
    with :class:`FtSelfAttention` as the mixer and an :class:`FtDense`
    pair (``mlp_ratio``× expansion, GELU) as the MLP, so every matrix
    product of the block's forward and backward is ABFT-protected and
    every sub-layer reports into ``ft_counts``. LayerNorm, GELU, and the
    residual adds are elementwise VPU compute outside the checksum
    domain (same honesty boundary as the softmax stage —
    ops/attention.py module docstring).

    A stack of these blocks is a fault-tolerant transformer; thread one
    ``bwd_sink`` through every block to fold all backward-GEMM reports
    into a single step-level ``[detections, uncorrectable]`` gradient.

    ``ring_mesh`` switches the mixer to :class:`FtRingSelfAttention`
    over that mesh — a long-context transformer block is then a config
    flag, not a rewrite (inputs must be unbatched ``(L, D)``, the ring
    module's contract).
    """

    num_heads: int
    mlp_ratio: int = 4
    causal: bool = False
    strategy: str = "weighted"
    threshold: Union[float, str] = "auto"
    bwd_threshold: Optional[Union[float, str]] = None
    dense_shape: Union[KernelShape, str] = "huge"
    qk_shape: KernelShape = QK_SHAPE
    pv_shape: KernelShape = PV_SHAPE
    in_dtype: str = "float32"
    ring_mesh: Optional[Mesh] = None  # sequence-parallel attention core
    inject: Optional[InjectionSpec] = None
    inject_bwd: Optional[InjectionSpec] = None

    @nn.compact
    def __call__(self, x, bwd_sink=None):
        d_model = x.shape[-1]
        kw = dict(strategy=self.strategy, threshold=self.threshold,
                  bwd_threshold=self.bwd_threshold,
                  in_dtype=self.in_dtype)
        attn_kw = dict(
            num_heads=self.num_heads, causal=self.causal,
            dense_shape=self.dense_shape, qk_shape=self.qk_shape,
            pv_shape=self.pv_shape, inject=self.inject,
            inject_bwd=self.inject_bwd, name="attn", **kw)
        h = nn.LayerNorm(name="ln_attn")(x)
        if self.ring_mesh is not None:
            h = FtRingSelfAttention(mesh=self.ring_mesh,
                                    **attn_kw)(h, bwd_sink)
        else:
            h = FtSelfAttention(**attn_kw)(h, bwd_sink)
        x = x + h
        h = nn.LayerNorm(name="ln_mlp")(x)
        mlp_kw = dict(shape=self.dense_shape, inject=self.inject,
                      inject_bwd=self.inject_bwd, **kw)
        h = FtDense(self.mlp_ratio * d_model,
                    name="mlp_in", **mlp_kw)(h, bwd_sink)
        h = nn.gelu(h)
        h = FtDense(d_model, name="mlp_out", **mlp_kw)(h, bwd_sink)
        return x + h


class FtTransformer(nn.Module):
    """A stack of :class:`FtTransformerBlock` layers via ``nn.scan``.

    The model-scale composition: ``num_layers`` blocks share one traced
    body (compile time stays constant in depth — the XLA-friendly way to
    stack), and parameters AND ``ft_counts`` carry a leading layer axis
    (``variable_axes``): each layer sows into its own stacked slice, so
    every layer's fault report is individually visible and no layer can
    overwrite another's. Step-level readers that sum count leaves (the
    re-run gate, the training examples) are unchanged by the extra axis.
    ``bwd_sink`` broadcasts to every layer, so one sink gradient reports
    the whole stack's backward GEMMs.
    """

    num_layers: int
    num_heads: int
    mlp_ratio: int = 4
    causal: bool = False
    strategy: str = "weighted"
    threshold: Union[float, str] = "auto"
    bwd_threshold: Optional[Union[float, str]] = None
    dense_shape: Union[KernelShape, str] = "huge"
    qk_shape: KernelShape = QK_SHAPE
    pv_shape: KernelShape = PV_SHAPE
    in_dtype: str = "float32"
    ring_mesh: Optional[Mesh] = None  # sequence-parallel attention cores
    # Rematerialize each block's forward during backward (jax.checkpoint):
    # activation memory drops from O(layers) block-internals to O(layers)
    # residual-stream tensors — the HBM-for-FLOPs trade long sequences
    # need. The replayed forward GEMMs run through the same FT kernels,
    # so the recompute is protected like the original pass.
    remat: bool = False
    inject: Optional[InjectionSpec] = None
    inject_bwd: Optional[InjectionSpec] = None

    @nn.compact
    def __call__(self, x, bwd_sink=None):
        block_kw = dict(
            num_heads=self.num_heads, mlp_ratio=self.mlp_ratio,
            causal=self.causal, strategy=self.strategy,
            threshold=self.threshold, bwd_threshold=self.bwd_threshold,
            dense_shape=self.dense_shape, qk_shape=self.qk_shape,
            pv_shape=self.pv_shape, in_dtype=self.in_dtype,
            ring_mesh=self.ring_mesh,
            inject=self.inject, inject_bwd=self.inject_bwd)

        class _Step(nn.Module):
            @nn.compact
            def __call__(self, carry, _):
                return (FtTransformerBlock(name="block", **block_kw)(
                    carry, bwd_sink), None)

        # prevent_cse=False: scan already provides the barrier remat's
        # default CSE protection exists for; keeping it would wrap every
        # layer's replay in optimization barriers that inhibit fusion —
        # on exactly the deep-stack path this flag targets.
        step = nn.remat(_Step, prevent_cse=False) if self.remat else _Step
        scan = nn.scan(
            step,
            # ft_counts stacks with a leading layer axis (like flax's
            # "intermediates"): per-layer fault visibility, and readers
            # that sum leaves (the step-level re-run gate) are unchanged.
            variable_axes={"params": 0, COUNTS_COLLECTION: 0},
            split_rngs={"params": True},
            length=self.num_layers)
        y, _ = scan(name="layers")(x, None)
        return y


__all__ = ["COUNTS_COLLECTION", "FtDense", "FtRingSelfAttention",
           "FtSelfAttention", "FtTransformer", "FtTransformerBlock"]
