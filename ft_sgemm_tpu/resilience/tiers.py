"""Hierarchical data-plane checksum tiers over a device mesh.

The in-kernel ABFT check (ops/ft_sgemm.py) verifies what ONE kernel
produced; the staged counter reduction (parallel/reduce.py) made the
mesh's DETECTION traffic hierarchical. What neither covers is the data
plane between kernels: a partial product corrupted after its kernel's
check, a value torn in the reduction's in-flight buffers, a resident
shard flipped while it waited. *Large Scale Distributed Linear Algebra
With TPUs* (PAPERS.md, arXiv 2112.09017) structures its checksums
hierarchically — per-panel sums combined per host, then globally — and
this module applies that panel structure to CHECKSUM ROW VECTORS, one
staged axis at a time (the ``hierarchical_psum`` discipline), instead of
just the int32 counter plane:

- **device tier** — each device compares the observed column sums of its
  local K-partial against the encoded expectation
  (``sum_rows(A_loc) @ B_loc.T``). No collective at all: the cheapest
  check, and the one with the sharpest localization (device + columns).
- **host tier** — the signed residual vectors reduce over the first
  (ICI) staged axis. Corruptions on sibling devices that are each below
  the per-device tolerance ACCUMULATE here; the combined vector crosses
  the (wider) host tolerance while every device tier stayed blind.
- **global tier** — after every axis: one vector for the whole mesh,
  the only stage whose values cross DCN, catching mesh-wide drift no
  narrower tier could resolve.

Detection scans tiers cheapest-communication first and records the FIRST
tier whose residual exceeds that tier's tolerance — the
``tier-of-detection`` telemetry label (``recovery_tier``, mirrored in
``contracts.RECOVERY_TIERS``). Unlike the counter tiers the staged
values are f32, so staged == flat only up to reassociation noise: every
comparison here is tolerance-gated (:func:`checksum_tolerance`, widening
by sqrt(fan-in) per stage) where the counter staging is exact — the
asymmetry DESIGN.md §18 documents.

The mesh-side emission lives in
:func:`ft_sgemm_tpu.parallel.sharded.make_tiered_ft_step`;
:func:`verify_resident` is the host-side twin for output that already
sits in memory (the resident-shard window, and the re-verification the
recompute ladder runs after every rung).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence, Tuple

import numpy as np

# Runtime spelling of contracts.RECOVERY_TIERS (the lint axis-drift pass
# cross-checks the two), ordered cheapest-communication first.
TIERS = ("device", "host", "global")


def checksum_tolerance(m: int, k: int, amax: float, bmax: float,
                       *, margin: float = 64.0) -> float:
    """The f32 noise floor of one device-tier checksum comparison.

    The observed and expected column sums are both f32 reductions over
    ``m * k`` products of magnitude <= ``amax * bmax``; their clean
    difference is rounding noise that grows like ``eps * k * sqrt(m)``
    times the operand scale. ``margin`` is the calibration headroom
    (the ROC machinery's stance: wide enough for zero false positives
    on clean traffic, tight enough that a single flipped mantissa bit of
    consequence lands above it). Higher tiers widen this by
    ``sqrt(fan-in)`` — independent per-device noise adds in quadrature.
    """
    eps = float(np.finfo(np.float32).eps)
    scale = max(float(amax) * float(bmax), 1e-30)
    return margin * eps * scale * max(k, 1) * math.sqrt(max(m, 1))


@dataclasses.dataclass
class TierReport:
    """What one tiered check saw.

    ``tier`` is the tier-of-detection (None when clean): the FIRST tier,
    scanning cheapest-communication first, whose max-abs residual
    exceeded that tier's tolerance. ``residuals`` / ``tolerances`` carry
    every tier's numbers so the caller sees how close the quiet tiers
    ran. ``device_coords`` names the worst device (mesh coordinates)
    when the device tier detected; ``columns`` lists implicated GLOBAL
    output columns at the detecting tier — the localization the
    recompute ladder starts from.
    """

    detected: bool
    tier: Optional[str]
    residuals: dict
    tolerances: dict
    device_coords: Optional[Tuple[int, ...]] = None
    columns: Optional[list] = None

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def staged_reduce_np(grid: np.ndarray,
                     axes: Sequence[int]) -> list:
    """Host-side mirror of the in-mesh staging: reduce a per-device
    vector grid one axis at a time, keeping every stage's partials.
    ``grid`` is ``(d0, d1, ..., n)``; each stage sums one device axis
    (keepdims) so stage ``s`` holds the combined vectors at that tier.
    The staged END VALUE equals the flat sum up to f32 reassociation —
    the tolerance-aware equality ``tests/test_resilience.py`` pins.
    """
    stages = [grid]
    cur = grid
    for ax in axes:
        cur = cur.sum(axis=ax, keepdims=True)
        stages.append(cur)
    return stages


def detect_tiers(r_dev: np.ndarray, tol0: float,
                 *, tier_axes: Sequence[int] = (1, 0),
                 col_offset: int = 0,
                 tier_stages: Optional[Sequence[int]] = None) -> TierReport:
    """Scan the staged residuals cheapest tier first.

    ``r_dev`` is the per-device residual grid ``(X, Y, n)`` (signed f32
    vectors); staging follows ``tier_axes``. Tolerance at stage ``s``
    is ``tol0 * sqrt(fan-in so far)``.

    With more staged axes than tiers (the 3-axis fleet mesh stages
    device -> y -> x -> host, four values for three tier names),
    ``tier_stages`` names which stage each tier reads: the fleet
    mapping is ``(0, 2, 3)`` — "device" the raw grid, "host" after ALL
    intra-process ICI axes, "global" after the DCN ``host`` axis, so a
    global-tier detection means the corruption was seen ONLY across
    DCN. Default: tier ``i`` reads stage ``i`` (the 2-axis meshes).
    """
    grid = np.asarray(r_dev, np.float64)
    stages = staged_reduce_np(grid, tier_axes)
    # Fan-in at stage s = how many devices each stage-s vector already
    # combines; independent per-device noise adds in quadrature, so the
    # tolerance widens by sqrt(fan-in).
    fanins = [1]
    for ax in tier_axes:
        fanins.append(fanins[-1] * grid.shape[ax])
    return detect_tiers_from_stages(stages, tol0, fanins=fanins,
                                    tier_stages=tier_stages,
                                    col_offset=col_offset)


def detect_tiers_from_stages(stages: Sequence, tol0: float,
                             *, fanins: Sequence[int],
                             tier_stages: Optional[Sequence[int]] = None,
                             col_offset: int = 0) -> TierReport:
    """Tier scan over ACTUAL staged residual grids (one per stage).

    :func:`detect_tiers` recomputes the staging host-side from the
    per-device grid — correct for corruption resident in the partials,
    but blind to corruption that struck a staged value IN FLIGHT (the
    DCN hop): that only exists in the stage grids the mesh itself
    emitted (``make_tiered_ft_step``'s ``r_stages``). This variant
    scans those emitted grids directly, so a clean ``r_dev`` with a
    dirty post-DCN stage is detected at — and only at — the global
    tier. ``fanins[s]`` is the device fan-in each stage-``s`` vector
    combines (its tolerance widens by ``sqrt(fanin)``).
    """
    if tier_stages is None:
        tier_stages = range(min(len(TIERS), len(stages)))
    residuals = {}
    tolerances = {}
    detection = None
    for name, si in zip(TIERS, tier_stages):
        stage = np.asarray(stages[si], np.float64)
        fanin = fanins[si]
        tol = tol0 * math.sqrt(fanin)
        resid = float(np.max(np.abs(stage))) if stage.size else 0.0
        residuals[name] = resid
        tolerances[name] = tol
        if detection is None and resid > tol:
            flat = np.abs(stage).max(axis=-1)
            worst = np.unravel_index(int(np.argmax(flat)), flat.shape)
            vec = np.abs(stage[worst])
            cols = [int(j) + col_offset
                    for j in np.nonzero(vec > tol / 2.0)[0]]
            detection = (name, tuple(int(w) for w in worst), cols)
    if detection is None:
        return TierReport(False, None, residuals, tolerances)
    tier, worst, cols = detection
    return TierReport(
        True, tier, residuals, tolerances,
        device_coords=worst if tier == "device" else None,
        columns=cols or None)


def verify_resident(a, b, c, *, alpha: float = 1.0, beta: float = 0.0,
                    c0=None, margin: float = 64.0) -> TierReport:
    """Host-side checksum check of a RESIDENT output block.

    Recomputes the encoded expectation of ``c = alpha * a @ b.T +
    beta * c0`` from the resident operands (column sums AND row sums —
    the row/col locator pair) and compares against the observed sums of
    ``c``. A single-tier (device) report: this is the check a device
    runs over its own shard between kernels, and the re-verification
    every recompute-ladder rung must pass. The residual VECTORS needed
    for localization are attached by :func:`residual_vectors` (the
    ladder's entry point) — this function answers only detected-or-not
    plus magnitude.
    """
    r_col, r_row, tol = residual_vectors(a, b, c, alpha=alpha, beta=beta,
                                         c0=c0, margin=margin)
    resid = float(max(np.max(np.abs(r_col), initial=0.0),
                      np.max(np.abs(r_row), initial=0.0)))
    detected = resid > tol
    cols = [int(j) for j in np.nonzero(np.abs(r_col) > tol)[0]]
    return TierReport(
        detected, "device" if detected else None,
        residuals={"device": resid}, tolerances={"device": tol},
        columns=cols or None)


def residual_vectors(a, b, c, *, alpha: float = 1.0, beta: float = 0.0,
                     c0=None, margin: float = 64.0):
    """The (column, row) signed checksum residual vectors of a resident
    output plus the device-tier tolerance — the localization raw
    material the recompute ladder consumes.

    Column residual ``r_col[j] = sum_i c[i,j] - expected``; a corrupted
    element ``(i, j)`` of delta ``d`` shows up as ``r_col[j] == d`` and
    ``r_row[i] == d`` — the classic ABFT row/col intersection.
    """
    a = np.asarray(a, np.float32)
    b = np.asarray(b, np.float32)
    c = np.asarray(c, np.float32)
    m, k = a.shape
    n = b.shape[0]
    exp_col = alpha * (a.sum(axis=0) @ b.T)
    exp_row = alpha * (a @ b.sum(axis=0))
    if beta != 0.0 and c0 is not None:
        c0 = np.asarray(c0, np.float32)
        exp_col = exp_col + beta * c0.sum(axis=0)
        exp_row = exp_row + beta * c0.sum(axis=1)
    r_col = c.sum(axis=0) - exp_col
    r_row = c.sum(axis=1) - exp_row
    amax = float(np.max(np.abs(a), initial=0.0))
    bmax = float(np.max(np.abs(b), initial=0.0))
    tol = checksum_tolerance(max(m, n), k, amax, bmax, margin=margin)
    return r_col.astype(np.float64), r_row.astype(np.float64), tol


def tiered_ft_sgemm(a, b, c, mesh, shape="huge", *,
                    alpha: float = 1.0, beta: float = -1.5,
                    inject=None, strategy: str = "weighted",
                    threshold=None, in_dtype: str = "float32",
                    interpret: Optional[bool] = None,
                    inject_coords: Optional[Tuple[int, int]] = None,
                    tier_corrupt: Sequence = (),
                    margin: float = 64.0,
                    registry=None):
    """Fused-ABFT mesh GEMM WITH hierarchical data-plane checksum tiers.

    The ``sharded_ft_sgemm`` layout (A ``P("x", "y")``, B
    ``P(None, "y")``, C ``P("x", None)``) with the step swapped for
    :func:`~ft_sgemm_tpu.parallel.sharded.make_tiered_ft_step`: besides
    the usual result the call returns a :class:`TierReport` from the
    staged per-device checksum residual vectors. ``tier_corrupt``
    entries (``((x, y), (i, j), delta)`` — LOCAL indices into that
    device's partial) strike the data plane between the in-kernel check
    and the reduction: the between-kernels corruption self-test.

    On detection the report lands in telemetry (an ``uncorrectable``
    event, op ``data_tiers``, with the tier-of-detection riding
    ``extra["recovery_tier"]``) and the registry
    (``recovery_tier_checks`` / ``recovery_tier_detections``). Returns
    ``(FtSgemmResult, TierReport)``.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ft_sgemm_tpu import telemetry
    from ft_sgemm_tpu.injection import InjectionSpec, REFERENCE_THRESHOLD
    from ft_sgemm_tpu.ops.common import resolve_in_dtype
    from ft_sgemm_tpu.ops.ft_sgemm import FtSgemmResult, make_ft_sgemm
    from ft_sgemm_tpu.parallel.sharded import (
        _check_divisible,
        make_tiered_ft_step,
        shard_map,
    )

    inject = inject or InjectionSpec.none()
    threshold = REFERENCE_THRESHOLD if threshold is None else threshold
    cast_dtype, _ = resolve_in_dtype(in_dtype, "highest")
    a = jnp.asarray(a, cast_dtype)
    b = jnp.asarray(b, cast_dtype)
    c = jnp.asarray(c, jnp.float32)
    (m, k), (n, _) = a.shape, b.shape
    mx, my = mesh.shape["x"], mesh.shape["y"]
    _check_divisible("M", m, mx)
    _check_divisible("K", k, my)

    local_ft = make_ft_sgemm(
        shape, alpha=1.0, beta=0.0, strategy=strategy,
        threshold=threshold, in_dtype=in_dtype, interpret=interpret)
    step = make_tiered_ft_step(
        local_ft, alpha, beta, inject, det_axes=("y", "x"),
        tier_axes=("y", "x"), inject_coords=inject_coords,
        tier_corrupt=tuple(tier_corrupt))

    vec_spec = P("x", "y", None)
    fn = shard_map(
        step, mesh=mesh,
        in_specs=(P("x", "y"), P(None, "y"), P("x", None)),
        out_specs=(P("x", None), P(None, None), P(None, None),
                   P("x", "y"), P("x", "y"),
                   vec_spec, vec_spec, vec_spec))
    with telemetry.trace_span("tiered_ft_sgemm"):
        out, det, unc, dev_det, dev_unc, r_dev, r_host, r_glob = \
            jax.jit(fn)(a, b, c)
    result = FtSgemmResult(out, det, unc)

    amax = float(np.max(np.abs(np.asarray(a, np.float32)), initial=0.0))
    bmax = float(np.max(np.abs(np.asarray(b, np.float32)), initial=0.0))
    # Per-DEVICE problem: each residual vector covers an
    # (m/mx, k/my)-shaped partial.
    tol0 = checksum_tolerance(m // mx, k // my, amax, bmax, margin=margin)
    report = detect_tiers(np.asarray(r_dev), tol0, tier_axes=(1, 0))

    if registry is None:
        registry = telemetry.get_registry()
    registry.counter("recovery_tier_checks").inc()
    if report.detected:
        registry.counter("recovery_tier_detections",
                         recovery_tier=report.tier).inc()
        telemetry.record_step_event(
            "uncorrectable", op="data_tiers",
            extra={"recovery_tier": report.tier,
                   "residual": report.residuals.get(report.tier),
                   "tolerance": report.tolerances.get(report.tier),
                   "device_coords": (list(report.device_coords)
                                     if report.device_coords else None),
                   "columns": report.columns,
                   "mesh": f"mesh{mx}x{my}"})
    return result, report


def fleet_tiered_ft_sgemm(a, b, c, mesh, shape="huge", *,
                          alpha: float = 1.0, beta: float = -1.5,
                          inject=None, strategy: str = "weighted",
                          threshold=None, in_dtype: str = "float32",
                          interpret: Optional[bool] = None,
                          inject_coords: Optional[Tuple[int, int, int]] = None,
                          tier_corrupt: Sequence = (),
                          dcn_corrupt: Sequence = (),
                          margin: float = 64.0,
                          registry=None):
    """:func:`tiered_ft_sgemm` on the 3-axis ("host", "x", "y") fleet
    mesh — the checksum tiers made DCN-honest.

    Staging runs device -> ``y`` -> ``x`` -> ``host``: four staged
    values for three tier names, mapped ``tier_stages=(0, 2, 3)`` so
    "host" reads the post-ICI stage and "global" the post-DCN stage —
    on a real multi-process mesh a global-tier detection now means the
    corruption was SEEN ONLY ACROSS DCN. ``dcn_corrupt`` entries
    (``((h, x, y), col, delta)``) strike the staged residual in flight
    on the DCN hop itself (see
    :func:`~ft_sgemm_tpu.parallel.sharded.make_tiered_ft_step`) — the
    self-test that pins that meaning. Stage grids are emitted fully
    REPLICATED (all-gathered in-step) so every rank — including ones
    that cannot address the faulty device — runs the same host-side
    detection on the complete grid. Returns ``(FtSgemmResult,
    TierReport)``; works identically single-process (tests) and across
    real processes (fleet/worker.py).
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ft_sgemm_tpu import telemetry
    from ft_sgemm_tpu.injection import InjectionSpec, REFERENCE_THRESHOLD
    from ft_sgemm_tpu.ops.common import resolve_in_dtype
    from ft_sgemm_tpu.ops.ft_sgemm import FtSgemmResult, make_ft_sgemm
    from ft_sgemm_tpu.parallel.sharded import (
        _check_divisible,
        make_tiered_ft_step,
        shard_map,
    )

    inject = inject or InjectionSpec.none()
    threshold = REFERENCE_THRESHOLD if threshold is None else threshold
    cast_dtype, _ = resolve_in_dtype(in_dtype, "highest")
    a = jnp.asarray(a, cast_dtype)
    b = jnp.asarray(b, cast_dtype)
    c = jnp.asarray(c, jnp.float32)
    (m, k), (n, _) = a.shape, b.shape
    h, mx, my = mesh.shape["host"], mesh.shape["x"], mesh.shape["y"]
    _check_divisible("M", m, h * mx)
    _check_divisible("K", k, my)

    local_ft = make_ft_sgemm(
        shape, alpha=1.0, beta=0.0, strategy=strategy,
        threshold=threshold, in_dtype=in_dtype, interpret=interpret)
    step = make_tiered_ft_step(
        local_ft, alpha, beta, inject, det_axes=("y", "x", "host"),
        mesh_axes=("host", "x", "y"), tier_axes=("y", "x", "host"),
        inject_coords=inject_coords, tier_corrupt=tuple(tier_corrupt),
        dcn_corrupt=tuple(dcn_corrupt), gather_stages=True)

    grid_spec = P(None, None, None, None)  # replicated (h, x, y, n)
    fn = shard_map(
        step, mesh=mesh,
        in_specs=(P(("host", "x"), "y"), P(None, "y"),
                  P(("host", "x"), None)),
        out_specs=(P(("host", "x"), None), P(None, None), P(None, None),
                   P("host", "x", "y"), P("host", "x", "y"),
                   grid_spec, grid_spec, grid_spec, grid_spec))
    with telemetry.trace_span("fleet_tiered_ft_sgemm"):
        out, det, unc, dev_det, dev_unc, r_dev, r_y, r_ici, r_glob = \
            jax.jit(fn)(a, b, c)
    result = FtSgemmResult(out, det, unc)

    amax = float(np.max(np.abs(np.asarray(a, np.float32)), initial=0.0))
    bmax = float(np.max(np.abs(np.asarray(b, np.float32)), initial=0.0))
    tol0 = checksum_tolerance(m // (h * mx), k // my, amax, bmax,
                              margin=margin)
    # The grids are replicated: every rank materializes all four staged
    # (h, x, y, n) grids locally — no cross-process fetch. Detection
    # scans the ACTUAL emitted stages (not a host-side re-staging of
    # r_dev) so in-flight DCN corruption — present only in the post-DCN
    # stage — is seen, at the global tier alone.
    report = detect_tiers_from_stages(
        [np.asarray(r_dev), np.asarray(r_y), np.asarray(r_ici),
         np.asarray(r_glob)],
        tol0, fanins=[1, my, mx * my, h * mx * my], tier_stages=(0, 2, 3))

    if registry is None:
        registry = telemetry.get_registry()
    registry.counter("recovery_tier_checks").inc()
    if report.detected:
        registry.counter("recovery_tier_detections",
                         recovery_tier=report.tier).inc()
        host = (report.device_coords[0]
                if report.device_coords is not None else None)
        telemetry.record_step_event(
            "uncorrectable", op="data_tiers",
            extra={"recovery_tier": report.tier,
                   "residual": report.residuals.get(report.tier),
                   "tolerance": report.tolerances.get(report.tier),
                   "device_coords": (list(report.device_coords)
                                     if report.device_coords else None),
                   "host": host,
                   "columns": report.columns,
                   "mesh": f"mesh{h}x{mx}x{my}"})
    return result, report


__all__ = [
    "TIERS",
    "TierReport",
    "checksum_tolerance",
    "detect_tiers",
    "detect_tiers_from_stages",
    "fleet_tiered_ft_sgemm",
    "residual_vectors",
    "staged_reduce_np",
    "tiered_ft_sgemm",
    "verify_resident",
]
