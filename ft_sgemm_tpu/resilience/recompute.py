"""Single-panel recompute: the recovery ladder below a full retry.

Before this module, an ``uncorrectable`` report had exactly one answer:
re-run everything (``train.resilient_step`` retries the whole step, the
serve engine re-executes the whole request). But the checksum machinery
LOCALIZES: the row/col residual pair names the element, the column
residuals name the output panel, the tier report names the device. The
ladder spends exactly as many flops as the localization demands —
cheapest rung first, each rung RE-VERIFIED through the resident
checksums (:func:`~ft_sgemm_tpu.resilience.tiers.residual_vectors`)
before the ladder stops, escalating only when the cheaper rung provably
could not or demonstrably did not suffice:

1. **element_correct** — one bad row x one bad column intersect at a
   single element whose delta IS the column residual: subtract it.
   O(m + n) work, the in-kernel correction replayed host-side.
2. **panel_recompute** — bad columns confined to few output panels:
   recompute only those panels from the resident A/B shards
   (``2 * m * k * panel_width`` flops per panel — the arXiv 2112.09017
   panel as the recovery quantum, ~1/num_panels of a full recompute).
3. **shard_restore** — localization too wide (or panel recompute did
   not verify): recompute the device's whole resident output shard.
4. **full_retry** — even the shard recompute failed to verify (the
   resident OPERANDS are suspect): the caller must re-run the whole
   distributed GEMM. The ladder never performs this itself — it
   returns the verdict and the flops the caller would spend.

``recomputed_flops / full_retry_flops`` is the ledger measurement
(``recovery.panel_recompute_flops_ratio``) the acceptance criterion
pins: a panel recompute must cost ~1/num_panels of the full retry it
replaces.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import numpy as np

from ft_sgemm_tpu.resilience.tiers import residual_vectors

# Runtime spelling of contracts.LADDER_RUNGS (lint-cross-checked),
# cheapest-flops first.
LADDER_RUNGS = ("element_correct", "panel_recompute", "shard_restore",
                "full_retry")


@dataclasses.dataclass
class RecoveryOutcome:
    """What one ladder run did.

    ``rung`` is the rung that produced the returned output (the
    terminal ``"full_retry"`` means nothing local sufficed);
    ``attempted`` lists every rung actually RUN, in order — the
    never-skip pin asserts the list is a prefix-consistent walk of
    ``LADDER_RUNGS`` restricted to rungs whose localization
    precondition held. Flops counts are exact multiply-add pairs
    (2*m*k*width per recomputed panel).
    """

    rung: str
    attempted: Tuple[str, ...]
    corrected: bool
    recomputed_flops: int
    full_retry_flops: int
    flops_ratio: float
    panels: Optional[list] = None
    element: Optional[Tuple[int, int]] = None
    residual_before: float = 0.0
    residual_after: float = 0.0

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def panel_bounds(n: int, num_panels: int) -> list:
    """Split ``n`` output columns into ``num_panels`` contiguous panels
    (last panel absorbs the remainder). The panel is the recovery
    quantum: localization only has to name a panel, never an exact
    extent."""
    num_panels = max(1, min(int(num_panels), n))
    width = max(1, n // num_panels)
    bounds = []
    lo = 0
    while lo < n:
        hi = n if len(bounds) == num_panels - 1 else min(n, lo + width)
        bounds.append((lo, hi))
        lo = hi
    return bounds


def _verify(a, b, c, alpha, beta, c0, margin, expected=None):
    """Checksum residuals of ``c``. With ``expected`` (the column/row
    checksum vectors captured at ENCODE time, i.e. computed from the
    operands as they were when the kernel ran) the comparison is
    independent of the resident operands — the only reference that can
    convict a corrupted resident shard of A/B, since recomputing the
    expectation from corrupted operands would self-verify."""
    if expected is None:
        r_col, r_row, tol = residual_vectors(
            a, b, c, alpha=alpha, beta=beta, c0=c0, margin=margin)
    else:
        exp_col, exp_row = expected
        c32 = np.asarray(c, np.float32)
        r_col = c32.sum(axis=0).astype(np.float64) - np.asarray(
            exp_col, np.float64)
        r_row = c32.sum(axis=1).astype(np.float64) - np.asarray(
            exp_row, np.float64)
        _, _, tol = residual_vectors(a, b, c, alpha=alpha, beta=beta,
                                     c0=c0, margin=margin)
    resid = float(max(np.max(np.abs(r_col), initial=0.0),
                      np.max(np.abs(r_row), initial=0.0)))
    return r_col, r_row, tol, resid


def encode_expected(a, b, *, alpha: float = 1.0, beta: float = 0.0,
                    c0=None):
    """The (column, row) checksum expectation vectors of
    ``alpha * a @ b.T + beta * c0`` — what a caller captures at encode
    time and hands to :func:`recover_local` as ``expected`` so later
    recoveries verify against the operands AS THEY WERE, not as they
    are."""
    a = np.asarray(a, np.float32)
    b = np.asarray(b, np.float32)
    exp_col = alpha * (a.sum(axis=0) @ b.T)
    exp_row = alpha * (a @ b.sum(axis=0))
    if beta != 0.0 and c0 is not None:
        c0 = np.asarray(c0, np.float32)
        exp_col = exp_col + beta * c0.sum(axis=0)
        exp_row = exp_row + beta * c0.sum(axis=1)
    return exp_col.astype(np.float64), exp_row.astype(np.float64)


def recover_local(a, b, c_bad, *, alpha: float = 1.0, beta: float = 0.0,
                  c0=None, num_panels: int = 8, margin: float = 64.0,
                  global_flops: Optional[int] = None,
                  max_panels: Optional[int] = None,
                  expected=None):
    """Run the recovery ladder over one device's resident block.

    ``a`` (m, k) and ``b`` (n, k) are the device's RESIDENT operand
    shards, ``c_bad`` its (m, n) output block that failed a checksum
    check (tier report or resident verify). ``global_flops`` is what a
    full distributed retry would cost (defaults to this block's own
    recompute cost — the single-device degenerate case);
    ``max_panels`` bounds how many implicated panels rung 2 will
    recompute before escalating (default: half the panels — past that
    a shard restore is cheaper bookkeeping for the same flops).
    ``expected`` (see :func:`encode_expected`) makes verification
    independent of the resident operands — the configuration that can
    reach the terminal ``full_retry`` rung when A/B themselves are
    corrupted.

    Returns ``(c_fixed, RecoveryOutcome)``. ``c_fixed`` is always the
    best available block; ``outcome.rung == "full_retry"`` tells the
    caller it is still unverified and the whole GEMM must re-run.
    """
    a = np.asarray(a, np.float32)
    b = np.asarray(b, np.float32)
    c = np.array(c_bad, np.float32, copy=True)
    m, k = a.shape
    n = b.shape[0]
    if c0 is None and beta != 0.0:
        raise ValueError("recover_local needs c0 when beta != 0 (the "
                         "epilogue input is part of the expectation)")
    full_local = 2 * m * k * n
    full_flops = int(global_flops) if global_flops else full_local
    bounds = panel_bounds(n, num_panels)
    if max_panels is None:
        max_panels = max(1, len(bounds) // 2)

    def oracle_cols(lo, hi):
        block = alpha * (a @ b[lo:hi].T)
        if beta != 0.0:
            block = block + beta * np.asarray(c0, np.float32)[:, lo:hi]
        return block

    attempted = []
    spent = 0
    r_col, r_row, tol, resid0 = _verify(a, b, c, alpha, beta, c0, margin,
                                       expected=expected)
    resid = resid0
    bad_cols = np.nonzero(np.abs(r_col) > tol)[0]
    bad_rows = np.nonzero(np.abs(r_row) > tol)[0]

    def outcome(rung, corrected, panels=None, element=None):
        return RecoveryOutcome(
            rung=rung, attempted=tuple(attempted), corrected=corrected,
            recomputed_flops=spent, full_retry_flops=full_flops,
            flops_ratio=(spent / full_flops if full_flops else 0.0),
            panels=panels, element=element,
            residual_before=resid0, residual_after=resid)

    if resid0 <= tol:
        # Nothing to recover: the clean fast path (rung vocabulary
        # deliberately not consumed — attempted stays empty).
        return c, outcome(LADDER_RUNGS[0], True)

    # Rung 1: a single located element. Precondition: exactly one bad
    # row AND one bad column (the ABFT intersection); the correction is
    # the residual itself.
    if len(bad_cols) == 1 and len(bad_rows) == 1:
        attempted.append("element_correct")
        i, j = int(bad_rows[0]), int(bad_cols[0])
        c[i, j] -= np.float32(r_col[j])
        spent += m + n  # the two checksum sums' worth of work
        r_col, r_row, tol, resid = _verify(a, b, c, alpha, beta, c0,
                                           margin, expected=expected)
        if resid <= tol:
            return c, outcome("element_correct", True, element=(i, j))
        bad_cols = np.nonzero(np.abs(r_col) > tol)[0]

    # Rung 2: recompute only the implicated panels. Precondition: the
    # bad columns are confined to few enough panels that panel work
    # stays well under a shard restore.
    hit = sorted({pi for pi, (lo, hi) in enumerate(bounds)
                  if np.any((bad_cols >= lo) & (bad_cols < hi))})
    if bad_cols.size and 0 < len(hit) <= max_panels:
        attempted.append("panel_recompute")
        for pi in hit:
            lo, hi = bounds[pi]
            c[:, lo:hi] = oracle_cols(lo, hi)
            spent += 2 * m * k * (hi - lo)
        r_col, r_row, tol, resid = _verify(a, b, c, alpha, beta, c0,
                                           margin, expected=expected)
        if resid <= tol:
            return c, outcome("panel_recompute", True, panels=hit)

    # Rung 3: the whole resident shard.
    attempted.append("shard_restore")
    c = oracle_cols(0, n)
    spent += full_local
    r_col, r_row, tol, resid = _verify(a, b, c, alpha, beta, c0, margin,
                                       expected=expected)
    if resid <= tol:
        return c, outcome("shard_restore", True)

    # Rung 4: nothing local verifies — the resident operands themselves
    # are suspect. The caller owns the distributed re-run; we price it.
    attempted.append("full_retry")
    spent += full_flops
    return c, outcome("full_retry", False)


__all__ = ["LADDER_RUNGS", "RecoveryOutcome", "encode_expected",
           "panel_bounds", "recover_local"]
