"""Live device eviction + reshard: the mesh sheds a sick member.

The PR-14 pool DRAINS a sick device — it stops receiving new batches
while its score is low, but it remains a placement candidate (the
relative floor can re-admit it) and its queued work just waits. Eviction
is the terminal rung one level up: a device whose
:class:`~ft_sgemm_tpu.telemetry.monitor.DeviceHealthTracker` score
crosses the EVICTION floor — or that keeps forcing panel recomputes —
is removed from placement permanently, its queued batches MIGRATE to
the survivors (re-placed through the normal health steer, so the trace
flow shows where every request went), and the serving executables for
the surviving set are (re)confirmed through the prewarm machinery — the
"re-AOT window", the only place a compile span is legitimate after
steady state began.

Pieces:

- :class:`EvictionPolicy` / :class:`ElasticController` — the decision:
  score below ``floor x fleet median`` with enough evidence, or
  ``panel_recompute_limit`` ladder escalations blamed on one device.
  The controller never leaves fewer than ``min_survivors`` devices.
- :func:`surviving_mesh` — the reshard target for MESH-RESIDENT paths
  (training): a fresh 2-D mesh over the largest power-of-two subset of
  the surviving devices, ready for re-AOT through the existing factory
  machinery (``train.resilient_step``'s ``on_persistent_fault`` hook
  returns a step rebuilt on it).
- :func:`run_eviction_drill` — the fire drill ``cli drill`` and the CI
  step run: persistent faults on one device under live load → eviction
  → queued work migrates → goodput recovers on the survivors, with
  MTTR, tier-of-detection counts, and the recompute-ladder flops ratio
  measured and returned for ledger ingestion.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Optional, Tuple

__all__ = ["ElasticController", "EvictionPolicy", "run_eviction_drill",
           "surviving_mesh"]


@dataclasses.dataclass
class EvictionPolicy:
    """When a device stops being worth keeping.

    ``floor`` is the eviction threshold on the health score, RELATIVE to
    the fleet median like the pool's drain floor (uniform degradation
    must never evict the fleet) but strictly below it — a device is
    drained first, evicted only when evidence keeps mounting. ``min_calls``
    is the evidence floor (a single bad request is not a pattern);
    ``panel_recompute_limit`` evicts a device that keeps forcing
    recompute-ladder escalations even if its score survives;
    ``min_survivors`` is the hard floor on fleet size after eviction.
    """

    floor: float = 0.25
    min_calls: int = 8
    panel_recompute_limit: int = 3
    min_survivors: int = 1
    # Host granularity (the fleet plane): repeated device blames landing
    # on ONE process evict the whole host — the failure domain on a real
    # multi-process mesh is the process (its runtime, its NIC, its host
    # memory), not the chip. ``host_blame_limit`` counts blame events
    # (faulty replies, tier detections, ladder escalations attributed to
    # any device of that host); ``min_surviving_hosts`` is the hard
    # floor on fleet width after a host eviction.
    host_blame_limit: int = 3
    min_surviving_hosts: int = 1


class ElasticController:
    """Decides — and remembers — evictions for one pool.

    The engine consults :meth:`should_evict` on every placement (the
    dispatcher thread) and performs the actual eviction through
    ``ServeEngine.evict_device`` (which calls :meth:`record_eviction`
    with the facts). :meth:`note_panel_recompute` is the ladder's blame
    feed. Thread-safe; a decision is handed out at most once per device.
    """

    def __init__(self, policy: Optional[EvictionPolicy] = None, *,
                 registry=None, timeline=None):
        self.policy = policy or EvictionPolicy()
        self.registry = registry
        self.timeline = timeline
        self._lock = threading.Lock()
        self._recomputes: dict = {}
        self._deciding: set = set()
        self.evictions: list = []
        self.fault_marked_at: Optional[float] = None
        self._host_blames: dict = {}   # host -> {device: count}
        self._host_deciding: set = set()
        self.host_evictions: list = []

    # -- evidence feeds ----------------------------------------------------

    def mark_fault(self, ts: Optional[float] = None) -> float:
        """Timestamp the onset of the fault this controller is watching
        (the drill's MTTR zero point)."""
        with self._lock:
            self.fault_marked_at = time.monotonic() if ts is None else ts
            return self.fault_marked_at

    def note_panel_recompute(self, device: str) -> int:
        """One recompute-ladder escalation blamed on ``device``."""
        with self._lock:
            n = self._recomputes.get(str(device), 0) + 1
            self._recomputes[str(device)] = n
            return n

    def recompute_count(self, device: str) -> int:
        with self._lock:
            return self._recomputes.get(str(device), 0)

    def note_device_blame(self, host: int, device: str) -> int:
        """One fault blamed on ``device`` of process ``host`` (a faulty
        serve reply, a tier detection, a ladder escalation) — the
        host-granularity evidence feed. Returns the host's total."""
        with self._lock:
            row = self._host_blames.setdefault(int(host), {})
            row[str(device)] = row.get(str(device), 0) + 1
            return sum(row.values())

    def host_blames(self, host: int) -> dict:
        with self._lock:
            return dict(self._host_blames.get(int(host), {}))

    # -- the host-granularity decision -------------------------------------

    def should_evict_host(self, *, total_hosts: int,
                          evicted_hosts=()) -> Optional[Tuple[int, str]]:
        """``(host, reason)`` when one process has accumulated
        ``host_blame_limit`` device blames, else None. Mirrors
        :meth:`should_evict` one failure-domain up: never proposes a
        host already evicted (or handed out), never shrinks the fleet
        below ``min_surviving_hosts`` processes. The worst-blamed
        eligible host wins a tie-free decision."""
        pol = self.policy
        with self._lock:
            blocked = set(evicted_hosts) | self._host_deciding
            if total_hosts - len(set(evicted_hosts)) - 1 \
                    < pol.min_surviving_hosts:
                return None
            worst = None
            for host, row in self._host_blames.items():
                if host in blocked:
                    continue
                total = sum(row.values())
                if total >= pol.host_blame_limit and (
                        worst is None or total > worst[1]):
                    worst = (host, total)
            if worst is None:
                return None
            self._host_deciding.add(worst[0])
            return (worst[0], "host_blame")

    def record_host_eviction(self, facts: dict) -> None:
        with self._lock:
            self.host_evictions.append(dict(facts))
            self._host_deciding.discard(facts.get("host"))

    # -- the decision ------------------------------------------------------

    def should_evict(self, pool) -> Optional[Tuple[int, str]]:
        """``(device index, reason)`` when one device crosses the policy,
        else None. Never proposes a device already evicted (or already
        handed out), and never shrinks the fleet below
        ``min_survivors``."""
        pol = self.policy
        n = len(pool.devices)
        with self._lock:
            blocked = set(pool.evicted) | self._deciding
            if n - len(set(pool.evicted)) - 1 < pol.min_survivors:
                return None
            candidates = [i for i in range(n) if i not in blocked]
            if not candidates:
                return None
            decision = None
            if pool.health is not None:
                scores = [pool.score(i) for i in range(n)]
                med = sorted(scores)[len(scores) // 2]
                floor = pol.floor * max(med, 1e-9)
                rows = pool.health.rows()
                for i in candidates:
                    calls = rows.get(pool.labels[i], {}).get("calls", 0)
                    if calls >= pol.min_calls and scores[i] < floor:
                        decision = (i, "health_floor")
                        break
            if decision is None:
                for i in candidates:
                    if self._recomputes.get(pool.labels[i], 0) \
                            >= pol.panel_recompute_limit:
                        decision = (i, "panel_recompute")
                        break
            if decision is not None:
                self._deciding.add(decision[0])
            return decision

    def record_eviction(self, facts: dict) -> None:
        with self._lock:
            self.evictions.append(dict(facts))
            self._deciding.discard(facts.get("index"))

    def mttr_seconds(self, recovered_at: float) -> Optional[float]:
        """MTTR from the marked fault onset to ``recovered_at``."""
        with self._lock:
            if self.fault_marked_at is None:
                return None
            return max(0.0, recovered_at - self.fault_marked_at)


def surviving_mesh(exclude=(), devices=None, *, axis_names=("x", "y"),
                   exclude_hosts=()):
    """A fresh 2-D mesh over the survivors — the reshard target.

    ``exclude`` is a device, its label string, its index into
    ``devices``, or an iterable of those. ``exclude_hosts`` drops every
    device of the named process indices FIRST — the host-eviction
    reshard: on a fleet mesh an evicted HOST takes all its devices out
    of placement at once, and the survivor processes rebuild over what
    remains (all of it addressable to them when one process of two
    died, which is exactly what makes the reshard executable without
    the dead rank). The mesh spans the largest POWER-OF-TWO count of
    surviving devices (power-of-two keeps the existing divisibility
    contracts of the sharded entry points intact through a reshard: a
    256-row M that divided 8 devices still divides 4), most-square
    split — the ``make_mesh`` rule. The caller re-AOTs its step over
    the returned mesh through the ordinary factories; that recompile IS
    the re-AOT window.
    """
    import jax
    import numpy as np
    from jax.sharding import Mesh

    devices = list(jax.devices()) if devices is None else list(devices)
    if not isinstance(exclude, (list, tuple, set, frozenset)):
        exclude = (exclude,)
    if not isinstance(exclude_hosts, (list, tuple, set, frozenset)):
        exclude_hosts = (exclude_hosts,)
    dead_hosts = {int(h) for h in exclude_hosts}
    excluded = {i for i, d in enumerate(devices)
                if getattr(d, "process_index", 0) in dead_hosts}
    for e in exclude:
        if isinstance(e, int):
            excluded.add(e)
        else:
            label = str(e)
            excluded.update(i for i, d in enumerate(devices)
                            if str(d) == label)
    survivors = [d for i, d in enumerate(devices) if i not in excluded]
    if not survivors:
        raise ValueError("surviving_mesh: no devices left after"
                         f" excluding {sorted(excluded)}")
    n = 1
    while n * 2 <= len(survivors):
        n *= 2
    x = int(np.floor(np.sqrt(n)))
    while n % x:
        x -= 1
    return Mesh(np.asarray(survivors[:n]).reshape(x, n // x), axis_names)


# ---------------------------------------------------------------------------
# The eviction fire drill
# ---------------------------------------------------------------------------


def _drive_phase(engine, spec, rng, n_requests, *, timeout=300.0,
                 fault_feed=None, after_ts=None):
    """Submit ``n_requests`` generated requests, poll every future to
    completion (recording approximate resolution timestamps — the MTTR
    probe), verify each result against the XLA oracle, and return the
    phase stats. ``fault_feed(i)`` runs after each submission (the
    persistent-fault evidence stream); ``after_ts`` filters the
    first-correct timestamp to completions at or after it."""
    import numpy as np

    from ft_sgemm_tpu.ops.reference import sgemm_reference
    from ft_sgemm_tpu.serve.loadgen import _gen_request
    from ft_sgemm_tpu.utils.matrices import verify_matrix

    t0 = time.monotonic()
    futs = []
    for i in range(n_requests):
        req = _gen_request(rng, spec, engine.buckets)
        futs.append((req, engine.submit(req)))
        if fault_feed is not None:
            fault_feed(i)
    pending = dict(enumerate(futs))
    resolved_at = {}
    deadline = time.monotonic() + timeout
    while pending:
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"drill phase stuck with {len(pending)} futures pending")
        for idx in list(pending):
            if pending[idx][1].done():
                resolved_at[idx] = time.monotonic()
                del pending[idx]
        if pending:
            time.sleep(0.002)
    wall = time.monotonic() - t0
    completed = correct = incorrect = retries = 0
    first_ok_ts = None
    for idx, (req, fut) in enumerate(futs):
        res = fut.result(timeout=1.0)
        completed += 1
        retries += res.retries
        m, n, _ = req.mnk
        want = np.asarray(sgemm_reference(
            req.a, req.b, np.zeros((m, n), np.float32),
            engine.alpha, engine.beta, in_dtype=req.in_dtype))
        ok_v, _, _ = verify_matrix(want, res.c, verbose=False)
        if res.ok and ok_v:
            correct += 1
            ts = resolved_at[idx]
            if (after_ts is None or ts >= after_ts) and \
                    (first_ok_ts is None or ts < first_ok_ts):
                first_ok_ts = ts
        else:
            incorrect += 1
    return {
        "submitted": len(futs), "completed": completed,
        "correct": correct, "incorrect": incorrect, "retries": retries,
        "wall_seconds": round(wall, 3),
        "goodput_rps": round(correct / wall, 3) if wall > 0 else None,
        "first_correct_ts": first_ok_ts,
    }


def _tier_rehearsal(mesh, registry, *, margin=64.0, interpret=None):
    """Exercise every data-plane checksum tier on the live mesh: one
    corruption shaped for each tier (large-local -> device; sibling
    accumulation -> host; mesh-wide drift -> global) plus a clean
    control, all through :func:`~ft_sgemm_tpu.resilience.tiers.
    tiered_ft_sgemm`. Returns the per-tier detection counts the drill
    reports (and the registry carries)."""
    import numpy as np

    from ft_sgemm_tpu.configs import KernelShape
    from ft_sgemm_tpu.resilience.tiers import checksum_tolerance
    from ft_sgemm_tpu.resilience.tiers import tiered_ft_sgemm as tiered
    from ft_sgemm_tpu.utils.matrices import generate_random_matrix

    mx, my = mesh.shape["x"], mesh.shape["y"]
    m, n, k = 128 * mx, 128, 128 * my
    rng = np.random.default_rng(10)
    a = generate_random_matrix(m, k, rng=rng)
    b = generate_random_matrix(n, k, rng=rng)
    c = generate_random_matrix(m, n, rng=rng)
    tile = KernelShape("drill128", 128, 128, 128, (0,) * 7)
    amax = float(np.max(np.abs(a)))
    bmax = float(np.max(np.abs(b)))
    tol0 = checksum_tolerance(m // mx, k // my, amax, bmax, margin=margin)

    cases = {"clean": ()}
    # Device tier: one unmistakably-local corruption.
    cases["device"] = (((0, 0), (1, 3), 50.0 * tol0),)
    if my >= 2:
        # Host tier: every y-sibling of row x=0 carries a sub-device-
        # threshold delta in ONE column; the first staged (ICI) reduce
        # accumulates them past sqrt(Y) x tol0.
        cases["host"] = tuple(
            ((0, y), (1, 3), 0.9 * tol0) for y in range(my))
    # Global tier: mesh-wide drift — every device sub-threshold, every
    # ICI row sub-host-threshold, the full reduction over the top.
    cases["global"] = tuple(
        ((x, y), (1, 3), 0.9 * tol0 / np.sqrt(my))
        for x in range(mx) for y in range(my))

    counts = {"device": 0, "host": 0, "global": 0}
    checks = 0
    for want, corrupt in cases.items():
        _, report = tiered(a, b, c, mesh, tile, alpha=1.0, beta=0.0,
                           tier_corrupt=corrupt, margin=margin,
                           interpret=interpret, registry=registry)
        checks += 1
        if report.detected:
            counts[report.tier] += 1
    return {"checks": checks, "detections": counts}


def _ladder_rehearsal(registry, *, num_panels=8):
    """Exercise the recompute ladder host-side: a located single element
    and a multi-element panel corruption, flops-accounted. Returns rung
    counts + the panel-recompute flops ratio (the pinned ledger
    measurement)."""
    import numpy as np

    from ft_sgemm_tpu import telemetry
    from ft_sgemm_tpu.resilience.recompute import recover_local

    rng = np.random.default_rng(11)
    m, n, k = 64, 256, 64
    a = rng.standard_normal((m, k)).astype(np.float32)
    b = rng.standard_normal((n, k)).astype(np.float32)
    clean = a @ b.T
    rungs: dict = {}
    ratio = None
    scenarios = (
        ("element", [(3, 7, 1000.0)]),
        ("panel", [(3, 7, 1000.0), (9, 9, -750.0)]),
    )
    for name, hits in scenarios:
        bad = np.array(clean, copy=True)
        for i, j, d in hits:
            bad[i, j] += np.float32(d)
        fixed, outcome = recover_local(a, b, bad,
                                       num_panels=num_panels)
        rungs[outcome.rung] = rungs.get(outcome.rung, 0) + 1
        registry.counter("recovery_ladder",
                         ladder_rung=outcome.rung).inc()
        telemetry.record_step_event(
            "corrected" if outcome.corrected else "uncorrectable",
            op="recompute",
            extra={"ladder_rung": outcome.rung,
                   "attempted": list(outcome.attempted),
                   "recomputed_flops": outcome.recomputed_flops,
                   "full_retry_flops": outcome.full_retry_flops,
                   "flops_ratio": outcome.flops_ratio})
        if name == "panel" and outcome.rung == "panel_recompute":
            ratio = outcome.flops_ratio
        assert np.allclose(fixed, clean, atol=1e-3), \
            "ladder rehearsal produced a wrong block"
    return {"rungs": rungs, "panel_recompute_flops_ratio": ratio,
            "num_panels": num_panels}


def run_eviction_drill(*, smoke: bool = False,
                       devices=None,
                       evict_device: int = 1,
                       bucket_sizes=None,
                       in_dtype: str = "float32",
                       requests_per_phase: Optional[int] = None,
                       max_batch: int = 2,
                       drain_below: float = 0.5,
                       policy: Optional[EvictionPolicy] = None,
                       rehearse_tiers: bool = True,
                       timeline=None,
                       progress_out=None,
                       registry=None,
                       seed: int = 10) -> dict:
    """The eviction fire drill (``cli drill`` / CI): prove that losing a
    device is a bounded, measured, local event.

    Four acts, one artifact:

    1. **Baseline** — clean load through a health-steered pool over all
       local devices; pre-fault goodput recorded, the target device
       demonstrably serving.
    2. **Fault + eviction under live traffic** — a persistent fault
       stream on the target device (synthetic uncorrectable evidence
       into the shared health tracker — ``mark_sick``'s knob, repeated)
       while load keeps flowing; the engine's elastic hook evicts it
       mid-load, migrates its queued batches, and re-confirms the
       survivors' executables (the re-AOT window). MTTR runs from fault
       onset to the first correct response after eviction.
    3. **Recovery proof** — a post-eviction clean load; goodput must
       recover to > 0.7x the baseline on the surviving devices, with
       zero incorrect responses anywhere in the drill.
    4. **Recovery-machinery rehearsal** — every checksum tier fires once
       on the live mesh (tier-of-detection counts) and the recompute
       ladder runs its element/panel rungs (flops ratio) — the same
       artifact carries the whole subsystem's health.

    Returns the stats dict ``bench.py --serve --pool --evict-device=N``
    emits; ``stats["recovery"]`` is what the ledger ingests
    (``recovery.mttr_seconds`` / ``recovery.evictions`` /
    ``recovery.panel_recompute_flops_ratio`` ...).
    """
    import dataclasses as _dc

    import jax
    import numpy as np

    from ft_sgemm_tpu.serve.buckets import default_bucket_set
    from ft_sgemm_tpu.serve.engine import ServeEngine
    from ft_sgemm_tpu.serve.loadgen import LoadSpec
    from ft_sgemm_tpu.serve.pool import DevicePool
    from ft_sgemm_tpu.telemetry.monitor import DeviceHealthTracker
    from ft_sgemm_tpu.telemetry.registry import MetricsRegistry

    def progress(p):
        if timeline is not None:
            timeline.point("recovery", "drill", **p)
        if progress_out is not None:
            print(f"drill: {p}", file=progress_out, flush=True)

    reg = registry if registry is not None else MetricsRegistry()
    devices = jax.local_devices() if devices is None else list(devices)
    if len(devices) < 2:
        raise ValueError("the eviction drill needs >= 2 devices"
                         " (an eviction must leave survivors)")
    evict_device = int(evict_device)
    if not 0 <= evict_device < len(devices):
        raise ValueError(f"evict_device={evict_device} outside the"
                         f" {len(devices)}-device pool")
    sizes = tuple(bucket_sizes) if bucket_sizes else (
        (128, 256) if smoke else (256, 512))
    buckets = default_bucket_set(sizes, in_dtype=in_dtype)
    n_phase = (16 if smoke else 32) if requests_per_phase is None \
        else int(requests_per_phase)
    spec = LoadSpec(num_requests=n_phase, in_dtype=in_dtype, seed=seed)
    largest = max(sizes)
    shapes = tuple(s for s in spec.shapes if max(s) <= largest)
    spec = _dc.replace(spec, shapes=shapes or ((largest // 2,) * 3,))
    rng = np.random.default_rng(seed)

    health = DeviceHealthTracker()
    pool = DevicePool(devices, health=health, drain_below=drain_below,
                      max_in_flight=2)
    controller = ElasticController(policy or EvictionPolicy(),
                                   registry=reg, timeline=timeline)
    target = pool.labels[evict_device]

    t0 = time.monotonic()
    stats: dict = {"devices": len(devices), "evict_device": target,
                   "buckets": [b.key for b in buckets],
                   "smoke": bool(smoke)}
    with ServeEngine(buckets, max_batch=max_batch, timeline=timeline,
                     registry=reg, pool=pool,
                     elastic=controller) as engine:
        prewarm = engine.prewarm()
        progress({"prewarmed": prewarm["compiled"]})
        stats["prewarm"] = prewarm

        # Act 1: baseline.
        pre = _drive_phase(engine, spec, rng, n_phase)
        pre_batches = pool.stats()["per_device"][target]["batches"]
        progress({"phase": "baseline", "goodput_rps": pre["goodput_rps"],
                  "target_batches": pre_batches})

        # Act 2: persistent fault under live traffic. Evidence lands in
        # the shared tracker every submission; once the score crosses
        # the eviction floor with enough calls behind it, the NEXT
        # placement evicts.
        t_fault = controller.mark_fault()

        def fault_feed(i):
            health.observe(target, calls=4, detected=4, uncorrectable=4)

        during = _drive_phase(engine, spec, rng, n_phase,
                              fault_feed=fault_feed, after_ts=None)
        evicted = list(controller.evictions)
        if not evicted:
            # The load outran the evidence stream (tiny phases): one
            # more placement pass settles it deterministically.
            during2 = _drive_phase(engine, spec, rng, 4)
            during["completed"] += during2["completed"]
            during["correct"] += during2["correct"]
            during["incorrect"] += during2["incorrect"]
            evicted = list(controller.evictions)
        progress({"phase": "fault", "evictions": len(evicted)})

        # Act 3: recovery proof on the survivors.
        post = _drive_phase(engine, spec, rng, n_phase)
        pool_stats = engine.stats()["pool"]

    first_ok = post.get("first_correct_ts")
    eviction = evicted[0] if evicted else None
    mttr = controller.mttr_seconds(first_ok) if first_ok else None
    post_batches = pool_stats["per_device"].get(target, {}) \
        .get("batches", 0)
    batches_at_eviction = (eviction or {}).get("target_batches",
                                               post_batches)
    ratio = None
    if pre["goodput_rps"] and post["goodput_rps"]:
        ratio = round(post["goodput_rps"] / pre["goodput_rps"], 3)

    recovery = {
        "evictions": len(evicted),
        "evicted_device": (eviction or {}).get("device"),
        "reason": (eviction or {}).get("reason"),
        "migrated_batches": (eviction or {}).get("migrated", 0),
        "reshard_seconds": (eviction or {}).get("reshard_seconds"),
        "mttr_seconds": round(mttr, 3) if mttr is not None else None,
        "goodput_pre_rps": pre["goodput_rps"],
        "goodput_post_rps": post["goodput_rps"],
        "goodput_recovery_ratio": ratio,
        "pre_fault_target_batches": pre_batches,
        "post_eviction_batches_on_evicted": max(
            0, post_batches - batches_at_eviction),
        "incorrect_responses": (pre["incorrect"] + during["incorrect"]
                                + post["incorrect"]),
    }

    # Act 4: rehearse the rest of the recovery machinery on the live
    # mesh so one artifact carries the whole subsystem's health.
    if rehearse_tiers:
        from ft_sgemm_tpu.parallel.sharded import make_mesh

        mesh = make_mesh(len(devices))
        tiers = _tier_rehearsal(mesh, reg)
        recovery["tier_checks"] = tiers["checks"]
        recovery["tier_detections"] = tiers["detections"]
        ladder = _ladder_rehearsal(reg)
        recovery["ladder"] = ladder["rungs"]
        recovery["panel_recompute_flops_ratio"] = \
            ladder["panel_recompute_flops_ratio"]
        progress({"phase": "rehearsal",
                  "tiers": tiers["detections"],
                  "ladder": ladder["rungs"]})

    stats.update({
        "requests_submitted": 3 * n_phase,
        "completed": (pre["completed"] + during["completed"]
                      + post["completed"]),
        "correct": pre["correct"] + during["correct"] + post["correct"],
        "pre": pre, "during_fault": during, "post": post,
        "recovery": recovery,
        "pool": pool_stats,
        "seconds_total": round(time.monotonic() - t0, 3),
        "wall_seconds": post["wall_seconds"],
        "goodput_rps": post["goodput_rps"],
        "throughput_rps": (round(post["completed"]
                                 / post["wall_seconds"], 3)
                           if post["wall_seconds"] else None),
    })
    stats["ok"] = bool(
        recovery["evictions"] >= 1
        and recovery["evicted_device"] == target
        and recovery["incorrect_responses"] == 0
        and recovery["post_eviction_batches_on_evicted"] == 0
        and (ratio is None or ratio > 0.7)
        and (post["goodput_rps"] or 0) > 0)
    progress({"phase": "done", "ok": stats["ok"],
              "mttr_seconds": recovery["mttr_seconds"],
              "goodput_recovery_ratio": ratio})
    return stats
