"""Elastic recovery: make an uncorrectable fault a bounded, local event.

Three coupled pieces (ROADMAP item 2's survival arc, DESIGN.md §18):

- :mod:`.tiers` — hierarchical DATA-PLANE checksums: every tiered
  sharded FT-GEMM carries per-device checksum residual vectors staged
  ICI-first into host and global tiers, so corruption that escapes the
  in-kernel check — or strikes between kernels — is detected at the
  cheapest tier that can see it, with tier-of-detection recorded.
- :mod:`.recompute` — the recovery ladder: element-correct →
  panel-recompute → shard-restore → full-retry, each rung re-verified,
  replacing the historical jump straight to a full retry. Recomputed
  flops vs full-retry flops is a pinned ledger measurement.
- :mod:`.elastic` — live device eviction + reshard: a health score
  crossing the eviction floor (or repeated panel recomputes on one
  device) removes the device from placement under live traffic, its
  queued batches migrate, and the mesh paths rebuild on the survivors.
"""

from ft_sgemm_tpu.resilience.elastic import (
    ElasticController,
    EvictionPolicy,
    run_eviction_drill,
    surviving_mesh,
)
from ft_sgemm_tpu.resilience.recompute import (
    LADDER_RUNGS,
    RecoveryOutcome,
    recover_local,
)
from ft_sgemm_tpu.resilience.tiers import (
    TIERS,
    TierReport,
    checksum_tolerance,
    fleet_tiered_ft_sgemm,
    tiered_ft_sgemm,
    verify_resident,
)

__all__ = [
    "ElasticController",
    "EvictionPolicy",
    "LADDER_RUNGS",
    "RecoveryOutcome",
    "TIERS",
    "TierReport",
    "checksum_tolerance",
    "fleet_tiered_ft_sgemm",
    "recover_local",
    "run_eviction_drill",
    "surviving_mesh",
    "tiered_ft_sgemm",
    "verify_resident",
]
