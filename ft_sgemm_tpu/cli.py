"""``ft_sgemm`` CLI driver — argv-compatible with the reference binary.

Reference contract (``kernel/ft_sgemm/sgemm.cu:12-19``, ``README.md:12-17``):

    ./ft_sgemm START_SIZE END_SIZE GAP_SIZE ST_KERNEL END_KERNEL

Two passes, like ``main()`` there:

  1. **Verification** at END_SIZE: every kernel id in [ST_KERNEL, END_KERNEL]
     is checked against the vendor GEMM (cuBLAS there, XLA dot here) under
     the ``utils.cu:61`` tolerance. FT kernels run with reference-like fault
     injection ON — passing the diff proves detect+correct, exactly the
     reference's implicit self-test (``sgemm.cu:222-227``).
  2. **Performance**: a GFLOPS table over sizes START..END step GAP, one row
     per kernel id in the 14-row table (``sgemm.cu:235-237``), 5 timed reps
     (``num_tests``), alpha=1, beta=-1.5, GFLOPS = 2*reps*M*N*K/t
     (``sgemm.cu:21-24,234,431-434``).

Timing protocol is adapted to the device boundary: the rep loop runs inside
one jitted computation with a dynamic trip count, chained data-dependently
(C feeds back), reps auto-scaled until device time dominates, with the fixed
dispatch overhead measured by a zero-rep run and subtracted (see
``utils.timing.bench_seconds_per_call`` — the reference's cudaEvent bracket
has no tunnel overhead to cancel).

Usage:
    python -m ft_sgemm_tpu.cli 1024 6144 512 0 16 \
        [--mintime=SECONDS] [--no-verify] [--no-perf] [--trace=DIR]
        [--dtype=bfloat16|float8_e4m3|int8]
        [--strategy=weighted|rowcol|global|fused]
        [--encode=vpu|mxu] [--threshold=static|auto|adaptive|FLOAT]
        [--telemetry=LOG.jsonl]
    python -m ft_sgemm_tpu.cli roc [--smoke] [--out=ROC.json] \
        [--margin=8.0]
    python -m ft_sgemm_tpu.cli telemetry LOG.jsonl \
        [--format=text|prom] [--by-device] \
        [--watch] [--watch-seconds=S] [--interval=S]
    python -m ft_sgemm_tpu.cli top URL[:PORT] \
        [--interval=S] [--iterations=N] [--once]
    python -m ft_sgemm_tpu.cli attribute LOG.jsonl [LOG2.jsonl ...]
    python -m ft_sgemm_tpu.cli timeline RUN.timeline.jsonl \
        [--format=text|json] [--phases]
    python -m ft_sgemm_tpu.cli tune [SIZE | M N K] [--strategy=...] \
        [--encode=vpu|mxu] [--dtype=...] [--threshold=static|adaptive] \
        [--pipe=N] [--grid-order=mn|nm] \
        [--dim-semantics=parallel|arbitrary] [--cad=N] \
        [--epilogue=SPEC] [--axis-tile-top=N] \
        [--plain] [--inject] [--budget=N] \
        [--reps=N] [--samples=N] [--method=wall|interpret|compile] \
        [--dry-run] [--prewarm]
    python -m ft_sgemm_tpu.cli tune-ring [SIZE | M N K] \
        [--strategy=...] [--dtype=...] [--plain] \
        [--method=wall|cost] [--dry-run]
    python -m ft_sgemm_tpu.cli tune-show
    python -m ft_sgemm_tpu.cli prewarm [SIZE] [--dry-run] \
        [--timeline=RUN.timeline.jsonl]
    python -m ft_sgemm_tpu.cli report ARTIFACT.json [--format=md|json]
    python -m ft_sgemm_tpu.cli bench-compare BASELINE.json CANDIDATE.json \
        [--tolerance=0.10] [--format=text|json]
    python -m ft_sgemm_tpu.cli serve [--workload=gemm|block] [--pool] \
        [--buckets=256,512] [--dtype=...] [--epilogue=SPEC] \
        [--requests=N] [--inject-rate=R] [--telemetry=LOG.jsonl] \
        [--sick-device=N|none] [--monitor-port=N] [--dry-run]
    python -m ft_sgemm_tpu.cli serve-bench [--smoke] \
        [--workload=gemm|block] [--pool] [--buckets=...] \
        [--epilogue=SPEC] [--requests=N] [--inject-rate=R] [--rate=RPS] \
        [--decode-ratio=R] [--kv-corrupt-rate=R] \
        [--sick-device=N|none] [--monitor-port=N] [--out=ARTIFACT.json]
    python -m ft_sgemm_tpu.cli drill [--smoke] [--evict-device=N] \
        [--requests=N] [--buckets=128,256] [--telemetry=LOG.jsonl] \
        [--out=ARTIFACT.json]
    python -m ft_sgemm_tpu.cli chaos [--smoke] [--models=a,b] \
        [--episodes=N] [--clean-episodes=N] [--seed=N] \
        [--coverage-out=COVERAGE.json] [--out=ARTIFACT.json] \
        [--telemetry=LOG.jsonl] [--timeline=RUN.timeline.jsonl]
    python -m ft_sgemm_tpu.cli coverage COVERAGE.json \
        [--format=text|json]
    python -m ft_sgemm_tpu.cli fleet [--procs=2] [--vdevs=4] \
        [--program=smoke|counters|noop|wedge] [--deadline=SECONDS] \
        [--workdir=DIR]
    python -m ft_sgemm_tpu.cli history [LEDGER.jsonl] \
        [--limit=N] [--format=text|json]
    python -m ft_sgemm_tpu.cli trend [LEDGER.jsonl] [--gate] \
        [--window=N] [--min-runs=N] [--sigma=X] [--floor=F] \
        [--format=text|json]
    python -m ft_sgemm_tpu.cli ingest LEDGER.jsonl ARTIFACT.json... \
        [--run-id=ID]
    python -m ft_sgemm_tpu.cli trace-export RUN.timeline.jsonl \
        [--events=LOG.jsonl] [--out=TRACE.json] [--run-id=ID]
    python -m ft_sgemm_tpu.cli trace-export FLEET_WORKDIR --fleet \
        [--out=TRACE.json] [--run-id=ID]
    python -m ft_sgemm_tpu.cli economics ARTIFACT.json \
        [--format=text|json]
    python -m ft_sgemm_tpu.cli lint [--format=text|json] \
        [--only=CHECK,...] [--allowlist=PATH] [--root=DIR]

``report`` renders the RunReport a bench artifact embeds
(``ft_sgemm_tpu.perf``): the environment manifest (device, jax/jaxlib,
git rev, tuner cache hits, fault counters) and the per-stage roofline
table — seconds, GFLOP/s, arithmetic intensity, %-of-peak compute and
HBM bandwidth, compute/memory-bound verdict, and the ABFT-overhead
fraction of each stage's FLOPs. ``bench-compare`` is the noise-aware A/B
gate over two artifacts: per-stage improvement / within-noise /
regression / incomparable verdicts under a relative tolerance; exit 0
means no regression (incomparable stages are listed, never fatal),
nonzero means a measured regression — what CI runs against the committed
smoke baseline.

``tune`` runs the autotuner (``ft_sgemm_tpu.tuner``): enumerate the legal
tile space for the problem, prune candidates the VMEM footprint model
rejects, measure the survivors (warmup + median-of-k), and persist the
winner in the tile cache (``FT_SGEMM_TUNER_CACHE`` or
``~/.cache/ft_sgemm_tpu/tuner_cache.json``) keyed by device kind, size
bucket, dtype, strategy, and injection. Later dispatches of the same key
pick the cached tile automatically. ``--dry-run`` stops after the static
prune and prints the candidate table (no measurement, no cache write —
runs anywhere, including CPU CI). On a non-TPU backend measurement falls
back to Pallas interpret mode: the machinery is exercised end to end, and
the entries land under the CPU device kind (they never serve a TPU).
``tune-show`` prints the persisted entries (winning variant axes shown
in ``{...}`` when non-default). ``tune`` searches the JOINT kernel-
variant space by default — block tile x pipeline depth x grid traversal
order x dimension semantics x detect/correct cadence — with per-axis
prune reasons for everything not tried; ``--pipe=N`` /
``--grid-order=mn|nm`` / ``--dim-semantics=parallel|arbitrary`` /
``--cad=N`` pin one axis (the cache key then spells the pinned value
instead of ``auto``), ``--epilogue=SPEC`` tunes for a fused epilogue
(``bias``, ``relu``/``gelu``, ``qint8``/``qfp8`` quantize-rescale,
``+``-joined — e.g. ``bias+relu``; the epilogue is workload-owned and
keys the search, it is never enumerated), and ``--axis-tile-top=N``
widens how many leading tiles explore the non-default axes.

``--telemetry=LOG.jsonl`` enables the fault-telemetry subsystem for the
run (``ft_sgemm_tpu.telemetry``): every FT kernel call appends a
structured event — counters, outcome, tile coordinates, and a host-side
residual measurement — to LOG.jsonl. The ``telemetry`` subcommand then
summarizes such a log: per-op/per-layer totals, outcome counts, and the
residual-magnitude histogram that feeds threshold calibration
(``analysis.calibrate_threshold``); ``--by-device`` prints the
per-device SDC localization view instead (host, device, shard coords,
counts — DESIGN.md §8).

``attribute`` merges one or more per-host fault-event shards
(``telemetry.aggregate``) and ranks every implicated device most
suspect first — the fleet-screening "which chip do I pull" view.
``timeline`` renders a bench run's streamed span timeline
(``telemetry.timeline``): per-stage wall time, heartbeat gaps, kill
markers, in-flight work — post hoc on a killed run or live mid-run;
``--phases`` appends the wall-clock phase attribution
(``perf.wallclock``): how much of the run's wall went to import /
backend init / XLA compile / tuning / transfer / execute vs
unattributed ``other``.

``prewarm`` is the warm-start actuator: it AOT-compiles
(``jit.lower().compile()``) the exact bench rep-loop computations at
the target size into the persistent compile cache
(``FT_SGEMM_COMPILE_CACHE``; see ``perf/compile_cache.py``), so a bench
run inside a later tunnel window pays cache retrieval instead of XLA
compile — the attack on the compile-dominated deadline kills of
BENCH_r02-r05. ``tune --prewarm`` chains the same compile pass after a
tuning run, so the winner it just persisted dispatches warm too.

``--dtype=bfloat16`` runs the whole table (vendor row, plain kernels,
two-pass baseline, fused-ABFT kernels) in the bf16 input mode — the MXU's
full-rate path, an axis the CUDA reference has no analog for. Verification
then diffs against the XLA dot over the same bf16-rounded inputs.
``--dtype=float8_e4m3`` (aliases ``fp8``/``fp8_e4m3``) runs the fp8
serving mode (f32 accumulation, f32 checksums over the fp8-rounded
values); ``--dtype=int8`` runs the int32-EXACT mode — inputs are scaled
to the integer lattice ±{0..9}, the FT rows accumulate and checksum in
wrapping int32 (clean residuals are identically zero), and the plain/
baseline rows are skipped (they accumulate f32).

``--threshold`` picks the detection-threshold mode for the FT rows:
``static`` (default — the reference's fixed 9500 operating point, or any
explicit float), ``auto`` (one traced per-call threshold from the full
inputs' moments), or ``adaptive`` (per-tile per-check thresholds derived
INSIDE the kernel from running encode-pass moment statistics — the
V-ABFT capability that keeps false positives at zero when operand
statistics vary; the mode that opens bf16-and-below to production use).
``roc`` runs the proof: clean false-positive rates and injected-fault
detection rates, static vs adaptive, per dtype x strategy x encode
across input scales, with a JSON artifact (``--out=``) and a per-combo
domination verdict; ``--smoke`` is the CPU-runnable CI grid.

``--strategy`` picks the fused-ABFT checksum design for the FT rows:
``weighted`` (default — deferred per-column localization; at its default
single-final-check cadence the expected checksums are precomputed by one
stacked XLA dot, so the hot loop is the plain kernel's MXU dot and the
flagship overhead is the lowest of the family), ``rowcol`` (reference
parity: row+col residual intersection checked every ~K/20 columns, the
reference's shipped design), ``global`` (detect-only; its rows are
excluded from the verification gate since corruption is left in the
output by design), or ``fused`` (checksum moments ride extra A rows
through the same MXU dot — the warp-level design's TPU analog).

``--encode`` picks the checksum-encode mode for the FT rows
(``ops/ft_sgemm.py`` "Encode modes"): ``vpu`` (default — per-K-step VPU
reductions, the original design) or ``mxu`` (expected checksums ride the
systolic array as augmented operand rows: one dot per K step yields the
product AND the expected checksums). Applies to every strategy; the
``tune`` subcommand searches and caches the two modes under separate
keys.

``--trace=DIR`` wraps the perf pass in a ``jax.profiler`` trace (the TPU
analog of nsight/NVTX instrumentation the reference lacks — SURVEY.md §5
"Tracing"); open DIR with TensorBoard or Perfetto.

``serve`` runs the fault-tolerant serving layer (``ft_sgemm_tpu.serve``,
DESIGN.md §11): shape-bucketed continuous batching over an AOT-prewarmed
bucket set, SLO-aware retry (corrected SDCs are free; an uncorrectable
one retries only the affected bucket's batch), per-request fault
attribution. Without ``--dry-run`` it prewarms the bucket set and drives
a short synthetic load, printing the serving stats; ``--dry-run`` prints
the bucket plan, per-bucket injection variants, and the resolved
compile-cache location without touching the backend (the CI smoke).
``--telemetry=LOG.jsonl`` records one ``serve_gemm`` event per request
(request id, bucket, tile blame, latency) — summarize or export with the
``telemetry`` subcommand (``--format=prom`` includes the
``serve_latency_seconds`` histogram rebuilt from the events).
``serve-bench`` runs the load-generator goodput bench and prints the
same JSON artifact line as ``python bench.py --serve``: p50/p99 latency,
throughput, and goodput-under-injection (correct results per second).
``--workload=block`` serves TRANSFORMER BLOCKS instead of bare GEMMs
(``serve/blocks.py``, DESIGN.md §15): ragged prefill/decode attention
requests bucket on padded sequence length, run through the FT attention
executors (faults attributed through QK/softmax/PV per request), and
decode reads every cached K/V page through the ABFT-checked KV cache —
stored-state corruption is detected on read, corrected in place when
localizable, or recovered by the bounded page-scoped restore ladder.
Goodput becomes tokens-correct-per-second; ``--decode-ratio=R`` sets
the prefill/decode mix and ``--kv-corrupt-rate=R`` the stored-page
corruption rate (the block workload's ``--buckets=`` values are padded
SEQUENCE sizes).

``--pool`` (DESIGN.md §17) runs the MULTI-DEVICE pool
stage: the same load drives the single-device engine and then a
health-steered device pool over every local device — per-device AOT
executable replicas, placement by ``DeviceHealthTracker`` score over
queue depth (sick devices drain, not schedule), a bounded async
in-flight window per device worker — reporting goodput scaling vs the
single-device control, per-device placement, and the
``--sick-device=N`` drain self-test outcome (``none`` disables the
marking; GEMM workload only). ``--pool --workload=block`` dispatches
the TRANSFORMER-BLOCK engine through the same pool (per-device block
executor replicas; ring executors are mutually exclusive with pool
replicas and switch off).

``drill`` is the elastic-recovery fire drill (``ft_sgemm_tpu
.resilience``, DESIGN.md §18): baseline load through a health-steered
pool, a persistent fault stream on one device (``--evict-device=N``,
default 1) under live traffic, EVICTION — placement permanently stops
naming the device, its queued batches migrate, survivors' executables
are re-confirmed (the re-AOT window) — then a recovery load plus one
rehearsal of every data-plane checksum tier and recompute-ladder rung.
Prints MTTR, goodput recovery ratio, tier-of-detection counts, and the
panel-recompute flops ratio, and emits the artifact line whose
``recovery.*`` facts the run ledger ingests (``cli trend`` then gates
recovery health longitudinally). Exit 0 iff evicted (not just
drained), zero incorrect/lost responses, nothing placed on the evicted
device afterward, and goodput recovered past 0.7x baseline.

``chaos`` runs the chaos campaign (``ft_sgemm_tpu.chaos``, DESIGN.md
§20): every declared fault model (``contracts.FAULT_MODELS``) compiled
onto the existing actuators and swept across its workloads — GEMM
serve, block serve with the checked KV cache, ``resilient_step``, and
the health-steered pool — measuring per cell the detection rate,
injection-to-event detection latency (the
``fault_detection_latency_seconds`` histogram), tier-of-detection,
correction rate, MTTR, clean-twin false-positive rate, and goodput
retention. Prints the coverage table, emits the ``chaos_coverage``
artifact line (``--out=`` for ledger ingestion; ``cli trend`` then
gates per-model ``chaos.*`` regressions), and writes the full matrix
to ``--coverage-out=``. Exit 0 iff every swept model measured a
detection rate, every CORRECTABLE model detected at rate 1.0, and no
cell produced an incorrect result or a clean-twin false positive.
``coverage`` re-renders a saved COVERAGE.json. The ring collective paths' hop schedule is the related
``ring_overlap`` axis (``--ring-overlap=serial|overlap`` on the ring
entry points; ``tune-ring`` searches it — wall-timed on TPU, priced by
the compute/ICI cost model elsewhere — and banks the winner the
``auto`` dispatch spelling serves).

Live monitoring (``ft_sgemm_tpu.telemetry.monitor``, DESIGN.md §12):
``--monitor-port=N`` on ``serve`` / ``serve-bench`` starts the stdlib
HTTP exporter for the run's duration — ``/metrics`` (Prometheus text:
serve histograms, ``slo_budget_remaining`` / ``slo_burn_rate``,
``device_health{device=...}``), ``/healthz`` (OK / DEGRADED / FAILING
with named reasons), ``/events?since=`` (recent fault events with
request trace IDs). Port 0 binds an ephemeral port (the resolved URL
streams to stderr). ``top URL`` is the live terminal view over those
endpoints: SLO budget, per-bucket latency/goodput, the device-health
column, and the recent-event tail, refreshed until Ctrl-C.
``telemetry LOG --watch`` follows a GROWING shard instead (incremental
tail + re-summarize) when only the JSONL plane is available.

Run history & trends (``ft_sgemm_tpu.perf.ledger`` / ``.trend``,
DESIGN.md §13): ``ingest`` appends artifacts to the append-only run
ledger (null/partial ones land with named degradation reasons, never
errors); ``history`` renders the run table with PARTIAL/kill
annotations — the BENCH_r01–r05 trajectory at a glance; ``trend``
judges the latest run of every (measurement, platform) series against
a rolling-window noise model estimated from the ledger itself —
improvement / flat / regression / insufficient-data — plus fault-rate
and SLO-burn drift. ``--gate`` makes the exit code CI-facing
(``perf/compare.py`` contract: only regression verdicts fail;
insufficient data never does). The ledger path defaults to
``$FT_SGEMM_LEDGER`` or ``LEDGER.jsonl``. ``trace-export`` merges one
run's streamed timeline (+ optional fault-event JSONL via
``--events=``) into a single Chrome-trace-event JSON — stage/attempt/
compile spans on per-kind tracks, faults as instants with tile coords,
serve requests as flows joined by ``trace_id`` across
enqueue→flush→detect→retry — loadable directly in Perfetto or
``chrome://tracing``. ``trace-export --fleet`` takes a fleet WORKDIR
instead and stitches the supervisor timeline plus every rank's
(skew-corrected by the per-host clock offsets the dispatcher measured,
rank-namespaced so identical span names never alias) into ONE
multi-process trace whose flows cross process rows. ``economics``
renders the cost plane a serving artifact embeds (useful-flops
fraction, overhead breakdown by cause, tokens-correct throughput per
device — ``perf/economics.py``).

Static analysis (``ft_sgemm_tpu.lint``, DESIGN.md §14): ``lint`` runs
the repo-native static contract checker — five AST passes verifying the
hand-maintained invariants (stdlib-only/path-loadable modules, kernel-
axis spellings across configs/vmem/tuner/telemetry/serve/CLI, lock-
guarded shared state, the SMEM scalar-slot ABI, the declared telemetry
schema) against the literal declarations in ``contracts.py`` /
``configs.py``. Exit 0 clean, 1 findings (or stale allowlist entries),
2 internal error — the ``bench-compare`` contract; CI runs it blocking.
``--only=`` selects checks; audited-safe findings ride the committed
``lint-allowlist.json`` (one justification per entry). The checker
itself is stdlib-only: ``python ft_sgemm_tpu/lint/core.py`` runs it by
file path with no jax anywhere in the process.
"""

from __future__ import annotations

import functools
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from ft_sgemm_tpu.configs import (
    DEFAULT_STRATEGY,
    DIM_SEMANTICS,
    ENCODE_MODES,
    GRID_ORDERS,
    IN_DTYPES,
    KERNEL_TABLE,
    PERF_ROW_IDS,
    PIPELINE_DEPTHS,
    THRESHOLD_MODES,
    EpilogueSpec,
    canonical_in_dtype,
    kernel_for_id,
)
from ft_sgemm_tpu.injection import InjectionSpec
from ft_sgemm_tpu.ops.abft_baseline import abft_baseline_sgemm
from ft_sgemm_tpu.ops.ft_sgemm import STRATEGIES, make_ft_sgemm
from ft_sgemm_tpu.ops.reference import sgemm_reference
from ft_sgemm_tpu.ops.sgemm import make_sgemm
from ft_sgemm_tpu.utils.matrices import generate_random_matrix, verify_matrix
from ft_sgemm_tpu.utils.timing import bench_seconds_per_call

ALPHA = 1.0   # sgemm.cu:22
BETA = -1.5   # sgemm.cu:24,234


def _build_ft(kernel_id: int, size: int, in_dtype: str, strategy: str,
              encode: str = "vpu", threshold="static"):
    """The fused-ABFT kernel + reference-like injection for one kernel id —
    the ONE place the verification and perf paths get their FT recipe
    (kernel from the shape NAME so per-dtype tile overrides apply;
    injection cadence following the tile the kernel actually runs)."""
    _, shape, _ = kernel_for_id(kernel_id)
    ft = make_ft_sgemm(shape.name, alpha=ALPHA, beta=BETA, in_dtype=in_dtype,
                       strategy=strategy, encode=encode, threshold=threshold)
    inj = InjectionSpec.reference_like(size, ft.shape_config.bk)
    return ft, inj


def _int8_capable(kernel_id: int) -> bool:
    """Whether a kernel id can run the int8 input mode: the XLA oracle
    row and the fused-ABFT rows (whose kernels carry the int32-exact
    accumulation path). The plain Pallas rows and the two-pass baseline
    accumulate in f32 and are skipped under ``--dtype=int8``."""
    _, _, is_abft = kernel_for_id(kernel_id)
    return kernel_id == 0 or (is_abft and kernel_id != 10)


def _build_callable(kernel_id: int, size: int, inject_ft: bool,
                    in_dtype: str = "float32", strategy: str = "weighted",
                    encode: str = "vpu", threshold="static"):
    """Return fn(a, b, c) -> (M, N) array for one kernel id, or None."""
    name, shape, is_abft = kernel_for_id(kernel_id)
    if kernel_id == 0:
        return lambda a, b, c: sgemm_reference(a, b, c, ALPHA, BETA,
                                               in_dtype=in_dtype)
    if kernel_id == 10:
        return lambda a, b, c: abft_baseline_sgemm(a, b, c, ALPHA, BETA,
                                                   in_dtype=in_dtype).c
    # Pass the NAME (not the KernelShape object) so per-dtype tile
    # overrides (configs.BF16_TILE_OVERRIDES) apply.
    if not is_abft:
        return make_sgemm(shape.name, alpha=ALPHA, beta=BETA,
                          in_dtype=in_dtype)
    ft, inj = _build_ft(kernel_id, size, in_dtype, strategy, encode,
                        threshold)
    if not inject_ft:
        inj = InjectionSpec.none()
    return lambda a, b, c: ft(a, b, c, inj).c


def print_device_info(out=None) -> None:
    """Hardware line before any results — the reference's ``getDetails``
    (``utils/utils.cu:8-13``: device name, clock, memory) adapted to the
    JAX device model. ``out`` resolves to stdout at CALL time (a def-time
    default would pin whatever sys.stdout was at first import — stale
    under test capture, same hazard run_telemetry_summary documents)."""
    out = sys.stdout if out is None else out
    try:
        devs = jax.devices()
        kind = getattr(devs[0], "device_kind", devs[0].platform)
        print(f"Device: {jax.default_backend()} | {kind} x{len(devs)}"
              f" | process {jax.process_index() + 1}/{jax.process_count()}"
              f" | jax {jax.__version__}", file=out)
    except RuntimeError as e:  # backend init failure: report, don't die
        print(f"Device: unavailable ({e})", file=out)


def _quantize_for_dtype(x: np.ndarray, in_dtype: str) -> np.ndarray:
    """int8 input mode: scale the quantized ±{0,.1,...,.9} distribution to
    the integer lattice ±{0..9} (the int8 cast truncates fractions — the
    unscaled distribution would collapse to zero). Other dtypes pass
    through; the kernels' own casts do the rounding."""
    if canonical_in_dtype(in_dtype) == "int8":
        return np.round(x * 10.0).astype(np.float32)
    return x


@functools.lru_cache(maxsize=1)
def _host_inputs(size: int, in_dtype: str = "float32"):
    """Host-side A/B/C for one sweep size. The perf sweep iterates
    SIZE-major (all kernel rows per size), so this generates each size's
    ~O(n^2) RNG draws exactly once per sweep — and only the current
    size's set needs to stay resident (maxsize=1: a second 6144^2 set
    would hold ~450 MB of dead host memory at sweep end)."""
    rng = np.random.default_rng(10)
    return (
        _quantize_for_dtype(generate_random_matrix(size, size, rng=rng),
                            in_dtype),
        _quantize_for_dtype(generate_random_matrix(size, size, rng=rng),
                            in_dtype),
        generate_random_matrix(size, size, rng=rng),
    )


def _verify_global_strategy(kernel_id: int, end_size: int, a, b, c, want,
                            in_dtype: str, encode: str = "vpu",
                            threshold="static"):
    """Verification gate for the detect-only ``global`` design: the output
    keeps injected corruption by definition, so the diff gate moves to
    (a) exact fault-event counting with injection ON and (b) a clean-run
    diff against the oracle."""
    from ft_sgemm_tpu.ops.common import shrink_block

    _, shape, _ = kernel_for_id(kernel_id)
    ft = make_ft_sgemm(shape.name, alpha=ALPHA, beta=BETA,
                       in_dtype=in_dtype, strategy="global", encode=encode,
                       threshold=threshold)
    eff = shrink_block(ft.shape_config, end_size, end_size, end_size)
    inj = InjectionSpec.reference_like(end_size, eff.bk)
    res = ft(a, b, c, inj)
    tiles = (-(-end_size // eff.bm)) * (-(-end_size // eff.bn))
    expected = tiles * inj.expected_faults(end_size, eff.bk)
    got_events = int(res.num_detected)
    ok_clean, nbad, first = verify_matrix(want, np.asarray(ft(a, b, c).c),
                                          verbose=False)
    ok = ok_clean and got_events == expected
    if ok:
        return True, f"pass (detected {got_events}/{expected}, clean diff ok)"
    parts = []
    if got_events != expected:
        parts.append(f"detected {got_events}, expected {expected}")
    if not ok_clean:
        parts.append(f"clean run: {nbad} bad, first at {first}")
    return False, "FAIL (" + "; ".join(parts) + ")"


def run_verification(end_size: int, st_kernel: int, end_kernel: int,
                     out=sys.stdout, in_dtype: str = "float32",
                     strategy: str = "weighted",
                     encode: str = "vpu", threshold="static") -> bool:
    """Pass 1: diff every selected kernel against the XLA oracle (for bf16
    mode: the XLA dot over the same bf16-rounded inputs; for int8: the
    exact int32-accumulating dot over the integer-scaled inputs).

    A and B reproduce the reference driver's post-``srand(10)`` buffers
    bit-for-bit when the native toolchain is available
    (``runtime.generate_reference_driver_inputs``, mirroring
    ``sgemm.cu:12,57-60``); C starts zeroed like ``fill_vector(C, 0)``.
    """
    from ft_sgemm_tpu import runtime

    a, b = runtime.generate_reference_driver_inputs(end_size)
    a = _quantize_for_dtype(a, in_dtype)
    b = _quantize_for_dtype(b, in_dtype)
    c = np.zeros((end_size, end_size), np.float32)  # fill_vector(C,0)

    want = np.asarray(sgemm_reference(a, b, c, ALPHA, BETA, in_dtype=in_dtype))
    all_ok = True
    int8_mode = canonical_in_dtype(in_dtype) == "int8"
    for kernel_id in sorted(KERNEL_TABLE):
        if kernel_id < st_kernel or kernel_id > end_kernel:
            continue
        name, _, is_abft = kernel_for_id(kernel_id)
        if int8_mode and not _int8_capable(kernel_id):
            print(f"Verification of kernel {kernel_id:2d} ({name:20s}): "
                  "skipped (int8 runs the FT rows' int32-exact kernels"
                  " only)", file=out)
            continue
        if is_abft and kernel_id != 10 and strategy == "global":
            ok, status = _verify_global_strategy(
                kernel_id, end_size, a, b, c, want, in_dtype, encode,
                threshold)
            all_ok &= ok
        elif is_abft and kernel_id != 10:
            # Correcting FT rows: diff gate PLUS the residual-after-correct
            # re-check — an interval the kernel itself could not verify
            # fails the row even if the diff happens to pass.
            ft, inj = _build_ft(kernel_id, end_size, in_dtype, strategy,
                                encode, threshold)
            res = ft(a, b, c, inj)
            ok, nbad, first = verify_matrix(want, np.asarray(res.c),
                                            verbose=False)
            unc = int(res.num_uncorrectable)
            parts = []
            if not ok:
                parts.append(f"{nbad} bad, first at {first}")
            if unc:
                parts.append(f"{unc} uncorrectable intervals reported")
            ok = ok and unc == 0
            status = "pass" if ok else "FAIL (" + "; ".join(parts) + ")"
            all_ok &= ok
        else:
            fn = _build_callable(kernel_id, end_size, inject_ft=True,
                                 in_dtype=in_dtype, strategy=strategy,
                                 encode=encode, threshold=threshold)
            got = np.asarray(fn(a, b, c))
            ok, nbad, first = verify_matrix(want, got, verbose=False)
            status = "pass" if ok else f"FAIL ({nbad} bad, first at {first})"
            all_ok &= ok
        print(f"Verification of kernel {kernel_id:2d} ({name:20s}): {status}",
              file=out)
    return all_ok


def run_perf_table(start_size: int, end_size: int, gap_size: int,
                   st_kernel: int, end_kernel: int,
                   min_device_time: float = 1.0, out=sys.stdout,
                   in_dtype: str = "float32",
                   strategy: str = "weighted",
                   encode: str = "vpu", threshold="static") -> dict:
    """Pass 2: the GFLOPS table (format parity with sgemm.cu:240-439).

    The sweep runs SIZE-major — all kernel rows measured per size — so
    each size's host inputs are generated and device_put ONCE for the
    whole sweep (the reference regenerates nothing because its buffers
    live on device for the whole run, ``sgemm.cu:69-96``; a row-major
    sweep here would regenerate ~O(n^2) host RNG draws per row). The
    table still prints row-major for output parity; per-size progress
    goes to stderr.
    """
    sizes = list(range(start_size, end_size + 1, gap_size))
    row_ids = [kid for kid in PERF_ROW_IDS if st_kernel <= kid <= end_kernel]
    if canonical_in_dtype(in_dtype) == "int8":
        skipped = [kid for kid in row_ids if not _int8_capable(kid)]
        if skipped:
            print(f"ft_sgemm: int8 mode skips rows {skipped} (plain/"
                  "baseline kernels accumulate f32; the FT rows carry the"
                  " int32-exact path)", file=sys.stderr, flush=True)
        row_ids = [kid for kid in row_ids if _int8_capable(kid)]

    cells = {}
    for size in sizes:
        print(f"ft_sgemm: measuring size {size} "
              f"({len(row_ids)} kernel rows)...", file=sys.stderr, flush=True)
        ah, bh, ch = _host_inputs(size, canonical_in_dtype(in_dtype))
        a, b, c = map(jax.device_put, (ah, bh, ch))
        for kernel_id in row_ids:
            fn = _build_callable(kernel_id, size, inject_ft=True,
                                 in_dtype=in_dtype, strategy=strategy,
                                 encode=encode, threshold=threshold)
            sec_per_rep = bench_seconds_per_call(
                fn, a, b, c, min_device_time=min_device_time)
            gf = 2.0 * size**3 / 1e9 / sec_per_rep
            cells[(kernel_id, size)] = gf
            # Flush every measured cell immediately (stderr keeps stdout's
            # table format intact): a tunnel death mid-sweep must not
            # discard completed measurements — the exact failure mode of
            # the round-1/2 bench artifacts.
            name, _, _ = kernel_for_id(kernel_id)
            print(f"ft_sgemm: {name} @ {size}: {gf:8.0f} GFLOPS",
                  file=sys.stderr, flush=True)

    print("################## Performance (GFLOPS) ########################",
          file=out)
    print("Matrix Size         |" + "".join(f"{s:8d}|" for s in sizes),
          file=out)
    results = {}
    for kernel_id in row_ids:
        name, _, _ = kernel_for_id(kernel_id)
        print(f"{name:<20s}|"
              + "".join(f"{cells[(kernel_id, s)]:8.0f}|" for s in sizes),
              file=out, flush=True)
        results[name] = {s: cells[(kernel_id, s)] for s in sizes}
    return results


def run_telemetry_summary(log_path: str, out=None, fmt: str = "text",
                          by_device: bool = False) -> int:
    """``telemetry`` subcommand: summarize a fault-event JSONL log.

    ``fmt="text"`` prints the human summary (totals, per-op/per-layer
    tables, residual histogram + p50/p95/max percentiles);
    ``fmt="prom"`` rebuilds a metrics registry from the events and
    exports it in the Prometheus text exposition format — pipe it to a
    node-exporter textfile collector or a pushgateway. ``--by-device``
    prints the per-device localization view instead: one row per
    ``(host, device)`` that appeared in the events' attribution entries
    (``telemetry.aggregate`` — shard coords, detected/uncorrectable
    counts, fault rate).
    """
    from ft_sgemm_tpu.telemetry import (
        aggregate, format_summary, read_events, registry_from_events,
        summarize_events, to_prometheus)

    # Resolve stdout at CALL time (a def-time default would pin whatever
    # object sys.stdout was at import — stale under test capture or any
    # caller that swaps streams).
    out = sys.stdout if out is None else out
    try:
        if by_device:
            table = aggregate.device_table(read_events(log_path))
            print(f"per-device fault attribution of {log_path}", file=out)
            print(aggregate.format_device_table(table), file=out)
            return 0
        if fmt == "prom":
            reg = registry_from_events(read_events(log_path))
            out.write(to_prometheus(reg.collect()))
            return 0
        summary = summarize_events(read_events(log_path))
    except OSError as e:
        print(f"ft_sgemm: cannot read telemetry log: {e}", file=sys.stderr)
        return 2
    print(f"telemetry summary of {log_path}", file=out)
    print(format_summary(summary), file=out)
    return 0


def run_attribute(paths, out=None) -> int:
    """``attribute`` subcommand: the fleet-screening view.

    Merges one or more per-host fault-event JSONL shards
    (``telemetry.aggregate.merge_shards`` — each process of a multi-host
    run writes its own shard listing only its devices) and prints every
    implicated device ranked most-suspect first: uncorrectable count,
    then detections, then fault rate. The "which chip do I pull" list.
    """
    from ft_sgemm_tpu.telemetry import aggregate

    out = sys.stdout if out is None else out
    try:
        events = aggregate.merge_shards(paths)
    except OSError as e:
        print(f"ft_sgemm: cannot read telemetry log: {e}", file=sys.stderr)
        return 2
    table = aggregate.device_table(events)
    print(f"fault attribution over {len(paths)} shard(s), "
          f"{len(events)} events", file=out)
    print(aggregate.format_device_table(table, ranked=True), file=out)
    return 0


def run_timeline(path: str, out=None, fmt: str = "text",
                 phases: bool = False) -> int:
    """``timeline`` subcommand: render a streamed run timeline.

    Reads the append-only span JSONL a bench worker streams
    (``telemetry.timeline``) — works post-hoc on a finished/killed run
    or mid-run on a live one (in-flight spans render as such) — and
    prints per-span wall time, heartbeat gaps, and any supervisor kill
    markers. ``--phases`` appends the wall-clock phase attribution
    (``perf.wallclock``): the run's import / backend_init / compile /
    tune / transfer / execute / other seconds and fractions — the view
    that turns "the run died at stage X" into "the run spent N% of its
    wall in XLA compile". ``--format=json`` emits the summary dict
    instead (with a ``wall`` key under ``--phases``). Exit 2 on an
    unreadable file, 1 when the file holds no timeline records.
    """
    import json as _json

    from ft_sgemm_tpu.telemetry import timeline as tl

    out = sys.stdout if out is None else out
    try:
        records = tl.read_timeline(path)
    except OSError as e:
        print(f"ft_sgemm: cannot read timeline: {e}", file=sys.stderr)
        return 2
    if not records:
        print(f"ft_sgemm: {path} holds no timeline records",
              file=sys.stderr)
        return 1
    summary = tl.summarize_timeline(records)
    attribution = None
    if phases:
        from ft_sgemm_tpu.perf import wallclock

        attribution = wallclock.attribute_wall(summary)
    if fmt == "json":
        if attribution is not None:
            summary = dict(summary, wall=attribution)
        print(_json.dumps(summary, indent=1, sort_keys=True), file=out)
    else:
        print(f"timeline of {path}", file=out)
        print(tl.format_timeline(summary), file=out)
        if attribution is not None:
            from ft_sgemm_tpu.perf import wallclock

            print(wallclock.format_wall(attribution), file=out)
    return 0


def run_report(artifact_path: str, out=None, fmt: str = "md") -> int:
    """``report`` subcommand: render a bench artifact's embedded
    RunReport (``ft_sgemm_tpu.perf.report``).

    ``--format=md`` (default) renders markdown; ``--format=json``
    re-emits the report dict pretty-printed. Exit 2 on an unreadable
    artifact, 1 when the artifact carries no RunReport (an old or null
    artifact — CI's report step treats that as a failed observability
    contract), 0 otherwise.
    """
    import json as _json

    from ft_sgemm_tpu.perf import compare as perf_compare
    from ft_sgemm_tpu.perf import from_artifact

    out = sys.stdout if out is None else out
    try:
        artifact = perf_compare.load_artifact(artifact_path)
    except (OSError, ValueError) as e:
        print(f"ft_sgemm: cannot read artifact: {e}", file=sys.stderr)
        return 2
    rr = from_artifact(artifact)
    if rr is None:
        print(f"ft_sgemm: {artifact_path} carries no run_report "
              "(null or pre-perf-subsystem artifact); metric="
              f"{artifact.get('metric')!r} value={artifact.get('value')!r}",
              file=sys.stderr)
        return 1
    if fmt == "json":
        print(_json.dumps(rr.to_dict(), indent=1, sort_keys=True),
              file=out)
    else:
        print(rr.to_markdown(), file=out)
    return 0


def run_bench_compare(baseline_path: str, candidate_path: str, out=None,
                      tolerance: Optional[float] = None,
                      fmt: str = "text") -> int:
    """``bench-compare`` subcommand: the noise-aware A/B perf gate.

    Exit 0 = no regression (within-noise / improved / incomparable-only),
    1 = at least one stage regressed beyond the tolerance, 2 = an
    artifact could not be read. ``--tolerance=0.10`` is the relative
    band; CI uses a loose one on CPU where smoke timings are noisy.
    """
    import json as _json

    from ft_sgemm_tpu.perf import compare as perf_compare

    out = sys.stdout if out is None else out
    try:
        a = perf_compare.load_artifact(baseline_path)
        b = perf_compare.load_artifact(candidate_path)
    except (OSError, ValueError) as e:
        print(f"ft_sgemm: cannot read artifact: {e}", file=sys.stderr)
        return 2
    tol = perf_compare.DEFAULT_TOLERANCE if tolerance is None else tolerance
    result = perf_compare.compare(a, b, tolerance=tol)
    if fmt == "json":
        print(_json.dumps(result, indent=1, sort_keys=True), file=out)
    else:
        print(f"baseline:  {baseline_path}", file=out)
        print(f"candidate: {candidate_path}", file=out)
        print(perf_compare.format_comparison(result), file=out)
    return perf_compare.exit_code(result)


def _default_ledger_path() -> str:
    return os.environ.get("FT_SGEMM_LEDGER") or "LEDGER.jsonl"


def run_history(args, flags, out=None) -> int:
    """``history`` subcommand: the run table over the ledger — one line
    per run with PARTIAL/kill annotations and degradation reasons.
    Exit 2 = ledger unreadable."""
    import json as _json

    from ft_sgemm_tpu.perf import ledger as perf_ledger

    out = sys.stdout if out is None else out
    path = args[0] if args else _default_ledger_path()
    limit = None
    fmt = "text"
    for f in flags:
        if f.startswith("--limit="):
            try:
                limit = int(f.split("=", 1)[1])
            except ValueError:
                print(f"--limit must be an int, got {f!r}", file=sys.stderr)
                return 2
        elif f.startswith("--format="):
            fmt = f.split("=", 1)[1]
            if fmt not in ("text", "json"):
                print(f"--format must be text or json, got {fmt!r}",
                      file=sys.stderr)
                return 2
    try:
        entries = perf_ledger.read_ledger(path)
    except OSError as e:
        print(f"ft_sgemm: cannot read ledger: {e}", file=sys.stderr)
        return 2
    if fmt == "json":
        shown = perf_ledger.dedup_entries(entries)
        if limit:
            shown = shown[-limit:]
        print(_json.dumps(shown, indent=1, sort_keys=True), file=out)
    else:
        print(f"ledger: {path}", file=out)
        print(perf_ledger.format_history(entries, limit=limit), file=out)
    return 0


def run_trend(args, flags, out=None) -> int:
    """``trend`` subcommand: N-run verdicts against the ledger's own
    rolling-window noise model.

    Exit contract (``--gate``): 0 = no regression (flat / improvement /
    insufficient-data all pass), 1 = at least one regression verdict,
    2 = the ledger could not be read. Without ``--gate`` the exit code
    is informational-0 unless the ledger is unreadable."""
    import json as _json

    from ft_sgemm_tpu.perf import ledger as perf_ledger
    from ft_sgemm_tpu.perf import trend as perf_trend

    out = sys.stdout if out is None else out
    path = args[0] if args else _default_ledger_path()
    kw = {}
    fmt = "text"
    bad = None
    for f in flags:
        try:
            if f.startswith("--window="):
                kw["window"] = int(f.split("=", 1)[1])
            elif f.startswith("--min-runs="):
                kw["min_runs"] = int(f.split("=", 1)[1])
            elif f.startswith("--sigma="):
                kw["sigma"] = float(f.split("=", 1)[1])
            elif f.startswith("--floor="):
                kw["rel_floor"] = float(f.split("=", 1)[1])
            elif f.startswith("--format="):
                fmt = f.split("=", 1)[1]
                if fmt not in ("text", "json"):
                    print(f"--format must be text or json, got {fmt!r}",
                          file=sys.stderr)
                    return 2
        except ValueError as e:
            bad = f"{f}: {e}"
    if bad:
        print(f"ft_sgemm: bad trend flag {bad}", file=sys.stderr)
        return 2
    try:
        entries = perf_ledger.dedup_entries(perf_ledger.read_ledger(path))
    except OSError as e:
        print(f"ft_sgemm: cannot read ledger: {e}", file=sys.stderr)
        return 2
    report = perf_trend.trend_report(entries, **kw)
    if fmt == "json":
        print(_json.dumps(report, indent=1, sort_keys=True), file=out)
    else:
        print(f"ledger: {path} ({len(entries)} runs)", file=out)
        print(perf_trend.format_trend(report), file=out)
    return perf_trend.exit_code(report) if "--gate" in flags else 0


def run_ingest(args, flags, out=None) -> int:
    """``ingest`` subcommand: append one or more artifacts to the run
    ledger. Hostile inputs never fail the command — they land as rows
    with named degradation reasons (the r01–r05 diet is the norm)."""
    from ft_sgemm_tpu.perf import ledger as perf_ledger

    out = sys.stdout if out is None else out
    ledger_path, artifacts = args[0], args[1:]
    run_id = None
    for f in flags:
        if f.startswith("--run-id="):
            run_id = f.split("=", 1)[1]
    if run_id is not None and len(artifacts) > 1:
        print("--run-id= only applies to a single artifact",
              file=sys.stderr)
        return 2
    for path in artifacts:
        entry = perf_ledger.ingest_file(path, run_id=run_id)
        perf_ledger.append(ledger_path, entry)
        deg = entry.get("degradations") or []
        print(f"ingested {entry['run_id']} ({entry['kind']}) from"
              f" {os.path.basename(path)}"
              + (f"  [{'; '.join(deg[:2])}]" if deg else ""), file=out)
    return 0


def run_trace_export(args, flags, out=None) -> int:
    """``trace-export`` subcommand: one merged Chrome-trace JSON per
    run, loadable in Perfetto / ``chrome://tracing``. Exit 2 = the
    timeline could not be read; 1 = it held no records (nothing to
    draw is a named outcome, not a silent empty file)."""
    from ft_sgemm_tpu.telemetry import traceview

    out = sys.stdout if out is None else out
    timeline_path = args[0]
    events_path = out_path = run_id = None
    for f in flags:
        if f.startswith("--events="):
            events_path = f.split("=", 1)[1]
        elif f.startswith("--out="):
            out_path = f.split("=", 1)[1]
        elif f.startswith("--run-id="):
            run_id = f.split("=", 1)[1]
    if "--fleet" in flags:
        # args[0] is a fleet WORKDIR: stitch supervisor + every rank's
        # timeline into one skew-corrected multi-process trace.
        try:
            trace, path = traceview.merge_fleet(
                timeline_path, out_path=out_path, run_id=run_id)
        except OSError as e:
            print(f"ft_sgemm: cannot read fleet workdir: {e}",
                  file=sys.stderr)
            return 2
        meta = trace["otherData"]
        skew = meta.get("clock_skew_seconds") or {}
        print(f"fleet trace written to {path}: {meta['spans']} spans,"
              f" {meta['points']} points, {meta['flows']} flows"
              f" ({meta['cross_process_flows']} cross-process),"
              f" ranks {meta.get('ranks')},"
              f" clock skew {skew}", file=out)
        if not (meta["spans"] or meta["points"]):
            print("ft_sgemm: fleet workdir held no records",
                  file=sys.stderr)
            return 1
        return 0
    try:
        trace, path = traceview.export_trace(
            timeline_path, events_path=events_path, out_path=out_path,
            run_id=run_id)
    except OSError as e:
        print(f"ft_sgemm: cannot read timeline: {e}", file=sys.stderr)
        return 2
    meta = trace["otherData"]
    print(f"trace written to {path}: {meta['spans']} spans"
          f" ({meta['in_flight']} in flight), {meta['points']} points,"
          f" {meta['fault_events']} fault events, {meta['flows']} request"
          f" flows ({meta['flow_events']} flow events),"
          f" {meta['dropped']} dropped", file=out)
    if not (meta["spans"] or meta["points"] or meta["fault_events"]):
        print("ft_sgemm: timeline held no records", file=sys.stderr)
        return 1
    return 0


def _find_economics(doc):
    """Locate the economics block in a bench artifact, tolerantly: the
    fleet path (``context.fleet.economics``), the serve paths, or a
    bare CostLedger snapshot handed in directly."""
    ctx = doc.get("context", doc) if isinstance(doc, dict) else {}
    for keys in (("economics",), ("fleet", "economics"),
                 ("serve", "economics"),
                 ("serve", "engine", "economics"),
                 ("slo", "economics")):
        cur = ctx
        for k in keys:
            cur = cur.get(k) if isinstance(cur, dict) else None
        if isinstance(cur, dict):
            return cur
    return None


def run_economics(args, flags, out=None) -> int:
    """``economics`` subcommand: render the cost plane a serving/fleet
    artifact embeds (``perf/economics.py``) — useful-vs-overhead flops
    split, overhead breakdown by cause, tokens-correct throughput per
    device. Exit 2 = unreadable artifact; 1 = no economics block (a run
    without the cost plane is a named outcome, not an empty table)."""
    import json as _json

    out = sys.stdout if out is None else out
    try:
        with open(args[0], "r", encoding="utf-8") as fh:
            doc = _json.load(fh)
    except (OSError, _json.JSONDecodeError) as e:
        print(f"ft_sgemm: cannot read artifact: {e}", file=sys.stderr)
        return 2
    econ = _find_economics(doc)
    if econ is None:
        print("ft_sgemm: artifact holds no economics block",
              file=sys.stderr)
        return 1
    if "--format=json" in flags:
        _json.dump(econ, out, indent=2, sort_keys=True)
        out.write("\n")
        return 0
    print("request cost economics", file=out)
    print(f"  requests             {econ.get('requests', '-')}"
          f"  (ok {econ.get('requests_ok', '-')})", file=out)
    print(f"  useful flops         "
          f"{econ.get('useful_flops_fraction', '-')}"
          f"  of total {econ.get('flops_total', '-')}", file=out)
    fracs = econ.get("overhead_fractions") or {}
    for cause in sorted(fracs):
        if fracs[cause] is not None:
            print(f"    overhead[{cause}] {fracs[cause]}", file=out)
    print(f"  tokens               {econ.get('tokens', '-')}"
          f"  correct {econ.get('tokens_correct', '-')}", file=out)
    tcs = econ.get("tokens_correct_per_second_per_device")
    if tcs is not None:
        print(f"  tokens-correct/s/device {tcs}"
              f"  (devices {econ.get('devices', '-')},"
              f" wall {econ.get('wall_seconds', '-')}s)", file=out)
    per_dev = econ.get("per_device") or {}
    if per_dev:
        print(f"  {'device':<30s} {'reqs':>6s} {'useful':>12s}"
              f" {'overhead':>12s} {'tok-ok':>7s}", file=out)
        for dev in sorted(per_dev):
            row = per_dev[dev] if isinstance(per_dev[dev], dict) else {}
            print(f"  {str(dev):<30s} {row.get('requests', 0):>6}"
                  f" {row.get('flops_productive', 0):>12.4g}"
                  f" {row.get('flops_overhead', 0):>12.4g}"
                  f" {row.get('tokens_correct', 0):>7}", file=out)
    return 0


def run_tune(args, flags, out=None) -> int:
    """``tune`` subcommand: search the tile space, persist the winner."""
    from ft_sgemm_tpu import tuner

    out = sys.stdout if out is None else out
    try:
        sizes = [int(a) for a in args]
    except ValueError:
        print(f"ft_sgemm: tune sizes must be integers, got {args}",
              file=sys.stderr)
        return 2
    if len(sizes) == 0:
        m = n = k = 1024
    elif len(sizes) == 1:
        m = n = k = sizes[0]
    elif len(sizes) == 3:
        m, n, k = sizes
    else:
        print("ft_sgemm: tune takes SIZE or M N K", file=sys.stderr)
        return 2
    strategy = "weighted"
    encode = "vpu"
    in_dtype = "float32"
    threshold_mode = "static"
    budget = 8
    method = None
    reps, samples = 3, 3
    variant_kw = {}
    for f in flags:
        if f.startswith("--pipe="):
            try:
                variant_kw["pipeline_depth"] = int(f.split("=", 1)[1])
            except ValueError:
                print(f"--pipe must be an integer from {PIPELINE_DEPTHS},"
                      f" got {f.split('=', 1)[1]!r}", file=sys.stderr)
                return 2
            if variant_kw["pipeline_depth"] not in PIPELINE_DEPTHS:
                print(f"--pipe must be one of {PIPELINE_DEPTHS}, got"
                      f" {variant_kw['pipeline_depth']}", file=sys.stderr)
                return 2
        elif f.startswith("--grid-order="):
            variant_kw["grid_order"] = f.split("=", 1)[1]
            if variant_kw["grid_order"] not in GRID_ORDERS:
                print(f"--grid-order must be one of {GRID_ORDERS}, got"
                      f" {variant_kw['grid_order']!r}", file=sys.stderr)
                return 2
        elif f.startswith("--dim-semantics="):
            variant_kw["dim_semantics"] = f.split("=", 1)[1]
            if variant_kw["dim_semantics"] not in DIM_SEMANTICS:
                print(f"--dim-semantics must be one of {DIM_SEMANTICS},"
                      f" got {variant_kw['dim_semantics']!r}",
                      file=sys.stderr)
                return 2
        elif f.startswith("--cad="):
            try:
                variant_kw["check_every"] = int(f.split("=", 1)[1])
            except ValueError:
                print(f"--cad must be a positive integer (K-grid steps),"
                      f" got {f.split('=', 1)[1]!r}", file=sys.stderr)
                return 2
        elif f.startswith("--epilogue="):
            try:
                variant_kw["epilogue"] = EpilogueSpec.parse(
                    f.split("=", 1)[1]).spelling
            except ValueError as e:
                print(f"--epilogue: {e}", file=sys.stderr)
                return 2
        elif f.startswith("--axis-tile-top="):
            variant_kw["axis_tile_top"] = int(f.split("=", 1)[1])
        elif f.startswith("--strategy="):
            strategy = f.split("=", 1)[1]
            if strategy not in STRATEGIES:
                print(f"--strategy must be one of {STRATEGIES}, got"
                      f" {strategy!r}", file=sys.stderr)
                return 2
        elif f.startswith("--encode="):
            encode = f.split("=", 1)[1]
            if encode not in ENCODE_MODES:
                print(f"--encode must be one of {ENCODE_MODES}, got"
                      f" {encode!r}", file=sys.stderr)
                return 2
        elif f.startswith("--dtype="):
            in_dtype = f.split("=", 1)[1]
            try:
                in_dtype = canonical_in_dtype(in_dtype)
            except ValueError:
                print(f"--dtype must be one of {IN_DTYPES} (or an fp8"
                      f" alias), got {in_dtype!r}", file=sys.stderr)
                return 2
        elif f.startswith("--threshold="):
            threshold_mode = f.split("=", 1)[1]
            if threshold_mode not in ("static", "adaptive"):
                print("--threshold must be static or adaptive for tune"
                      " (auto shares static's program and key), got"
                      f" {threshold_mode!r}", file=sys.stderr)
                return 2
        elif f.startswith("--budget="):
            budget = int(f.split("=", 1)[1])
        elif f.startswith("--reps="):
            reps = int(f.split("=", 1)[1])
        elif f.startswith("--samples="):
            samples = int(f.split("=", 1)[1])
        elif f.startswith("--method="):
            method = f.split("=", 1)[1]
            if method not in tuner.METHODS:
                print(f"--method must be one of {tuner.METHODS}, got"
                      f" {method!r}", file=sys.stderr)
                return 2
    if "--plain" in flags:
        strategy = None
    dry_run = "--dry-run" in flags

    print_device_info()

    def progress(r):
        v = r.variant
        tags = []
        if v is not None and not v.is_default:
            if v.pipeline_depth != 2:
                tags.append(f"pipe={v.pipeline_depth}")
            if v.grid_order != "mn":
                tags.append(f"grid={v.grid_order}")
            if v.dim_semantics != "parallel":
                tags.append(f"sem={v.dim_semantics}")
            if v.check_every is not None:
                tags.append(f"cad={v.check_every}")
        row = (f"{str(tuple(r.block)):>18s}"
               + (("{" + " ".join(tags) + "}") if tags else ""))
        if r.ok and r.gflops is not None:
            print(f"  {row}  {r.gflops:9.1f} GFLOPS"
                  f"  [{r.method}]", file=out, flush=True)
        elif r.ok:
            print(f"  {row}  compiled ok"
                  f"  (grid-step score {r.score:.0f})", file=out, flush=True)
        else:
            print(f"  {row}  FAILED: {r.error}",
                  file=out, flush=True)

    try:
        report = tuner.tune(
            m, n, k, strategy=strategy, encode=encode, in_dtype=in_dtype,
            threshold_mode=threshold_mode,
            inject="--inject" in flags, method=method, budget=budget,
            reps=reps, samples=samples, dry_run=dry_run, progress=progress,
            **variant_kw)
    except ValueError as e:
        # Illegal (strategy, encode, dtype, threshold) combination: the
        # kernel factory's message says which constraint and why.
        print(f"ft_sgemm: {e}", file=sys.stderr)
        return 2
    strat = report["strategy"]
    print(f"tune {m}x{n}x{k} strategy={strat} encode={report['encode']}"
          f" dtype={in_dtype} thr={report.get('threshold_mode', 'static')}"
          f" epi={report.get('epilogue', 'none')}"
          f" method={report['method']} key={report['key']}", file=out)
    print(f"candidates: {len(report['feasible'])} feasible,"
          f" {len(report['pruned'])} pruned", file=out)
    if dry_run:
        # Per-reason prune census first (the joint space prunes whole
        # axis families — counts read better than 300 rows), then the
        # VMEM-priced rows.
        reasons = {}
        for p in report["pruned"]:
            head = p["reason"].split(" (")[0].split(" >")[0]
            reasons[head] = reasons.get(head, 0) + 1
        for head, count in sorted(reasons.items(),
                                  key=lambda kv: -kv[1]):
            print(f"  pruned x{count}: {head}", file=out)
        shown = 0
        for p in report["pruned"]:
            if "VMEM" in p["reason"]:
                vtag = f" [{p['variant']}]" if p.get("variant") else ""
                print(f"  pruned {str(tuple(p['block'])):>18s}{vtag}:"
                      f" {p['reason']}", file=out)
                shown += 1
                if shown >= 10:
                    break
        print("dry run: nothing measured, nothing written", file=out)
        return 0
    best = report.get("best")
    heur = report.get("heuristic")
    if best is None:
        print("tune: no candidate measured successfully", file=sys.stderr)
        return 1

    def vtag(row):
        v = row.get("variant") or {}
        tags = []
        if v.get("pipeline_depth", 2) != 2:
            tags.append(f"pipe={v['pipeline_depth']}")
        if v.get("grid_order", "mn") != "mn":
            tags.append(f"grid={v['grid_order']}")
        if v.get("dim_semantics", "parallel") != "parallel":
            tags.append(f"sem={v['dim_semantics']}")
        if v.get("check_every") is not None:
            tags.append(f"cad={v['check_every']}")
        if v.get("epilogue", "none") != "none":
            tags.append(f"epi={v['epilogue']}")
        return (" {" + " ".join(tags) + "}") if tags else ""

    print(f"heuristic {tuple(heur['block'])}{vtag(heur)}: "
          + (f"{heur['gflops']:.1f} GFLOPS" if heur and heur.get("gflops")
             else "n/a"), file=out)
    print(f"best      {tuple(best['block'])}{vtag(best)}: "
          + (f"{best['gflops']:.1f} GFLOPS" if best.get("gflops")
             else f"score {best['score']:.0f}"), file=out)
    print(f"cache written: {report.get('cache_path')}", file=out)
    if "--prewarm" in flags:
        # Tune-time warm start: the tuner just spent a window's minutes
        # finding winners — AOT-compile the bench computations at this
        # size NOW so the winner (served through the cache the line
        # above wrote) and every comparison stage hit the persistent
        # compile cache when the bench relaunches.
        if m == n == k:
            tl_path = os.environ.get("FT_SGEMM_BENCH_TIMELINE")
            _prewarm_compile(m, tl_path=tl_path, out=out)
        else:
            print("tune: --prewarm skipped (bench shapes are square;"
                  f" got {m}x{n}x{k})", file=sys.stderr)
    return 0


def run_tune_ring(args, flags, out=None) -> int:
    """``tune-ring`` subcommand: search the ring hop-schedule axis
    (``--ring-overlap=serial|overlap`` is the dispatch pin; this banks
    the searched winner) for one global ring problem and persist it
    under the per-device local-shard key (``tuner.tune_ring``)."""
    out = sys.stdout if out is None else out
    from ft_sgemm_tpu import tuner

    size = 1024
    dims = [int(a) for a in args[:3]] if args else [size]
    m = dims[0]
    n = dims[1] if len(dims) > 1 else None
    k = dims[2] if len(dims) > 2 else None
    strategy = "weighted"
    in_dtype = "float32"
    method = None
    write_cache = "--dry-run" not in flags
    for f in flags:
        if f.startswith("--strategy="):
            strategy = f.split("=", 1)[1]
        elif f.startswith("--dtype="):
            in_dtype = canonical_in_dtype(f.split("=", 1)[1])
        elif f.startswith("--method="):
            method = f.split("=", 1)[1]
    if "--plain" in flags:
        strategy = None
    print_device_info(out=sys.stderr)
    try:
        report = tuner.tune_ring(m, n, k, strategy=strategy,
                                 in_dtype=in_dtype, method=method,
                                 write_cache=write_cache)
    except ValueError as e:
        print(f"ft_sgemm: tune-ring: {e}", file=sys.stderr)
        return 2
    for mode in ("serial", "overlap"):
        row = report[mode]
        extra = (f"  {row['gflops']:.1f} GFLOP/s"
                 if row.get("gflops") else "")
        print(f"  {mode:<8s} score={row['score']:.3e}{extra}", file=out)
    print(f"winner: {report['winner']}  (method={report['method']},"
          f" ring size {report['d']})", file=out)
    if write_cache:
        print(f"cached under {report['key']}", file=out)
    return 0


def run_roc(flags, out=None) -> int:
    """``roc`` subcommand: the static-vs-adaptive threshold ROC sweep.

    Runs ``injection.roc_sweep`` — clean false-positive rates and
    injected-fault detection rates across input scales, per
    (dtype, strategy, encode) combo, static threshold (calibrated at
    scale 1) vs ``threshold="adaptive"`` — and prints the per-combo
    verdict table. ``--smoke`` cuts to the CI-sized grid
    (bf16 + int8, rowcol + global — CPU-runnable in ~1 min);
    ``--out=PATH`` writes the full JSON artifact. Exit 0 iff adaptive
    Pareto-dominates static for every combo AND adaptive produced zero
    clean-run false positives (the acceptance contract CI grep-asserts).
    """
    import json as _json

    from ft_sgemm_tpu.injection import roc_sweep

    out = sys.stdout if out is None else out
    kwargs = {}
    out_path = None
    for f in flags:
        if f.startswith("--out="):
            out_path = f.split("=", 1)[1]
        elif f.startswith("--margin="):
            kwargs["margin"] = float(f.split("=", 1)[1])
    if "--smoke" in flags:
        kwargs.update(dtypes=("bfloat16", "int8"),
                      strategies=("rowcol", "global"))
    print_device_info()

    def progress(p):
        print(f"  {p.dtype:>14s}/{p.strategy}/{p.encode} {p.mode:>8s} "
              f"scale={p.scale:<6g} clean_det={p.clean_detections:<4d} "
              f"det={p.detected}/{p.expected_faults}", file=out, flush=True)

    artifact = roc_sweep(progress=progress, **kwargs)
    s = artifact["summary"]
    print("\nROC summary (aggregate over scales "
          f"{artifact['config']['scales']}):", file=out)
    for key, v in s["combos"].items():
        a, st = v["adaptive"], v["static"]
        verdict = ("STRICT" if v["strict"]
                   else "dominates" if v["dominates"] else "DOMINATED")
        print(f"  {key:<34s} static fp={st['fp_rate']:.3f}"
              f" det={st['detection_rate']:.3f} | adaptive"
              f" fp={a['fp_rate']:.3f} det={a['detection_rate']:.3f}"
              f"  [{verdict}]", file=out)
    print(f"adaptive false positives: {s['adaptive_false_positives']}",
          file=out)
    print(f"all combos dominated by adaptive: {s['all_dominate']}",
          file=out)
    if out_path:
        with open(out_path, "w", encoding="utf-8") as fh:
            _json.dump(artifact, fh, indent=1, sort_keys=True)
            fh.write("\n")
        print(f"roc artifact written to {out_path}", file=out)
    ok = s["all_dominate"] and s["adaptive_false_positives"] == 0
    return 0 if ok else 1


def run_tune_show(out=None) -> int:
    """``tune-show`` subcommand: print the persisted tile-cache entries."""
    from ft_sgemm_tpu import tuner

    out = sys.stdout if out is None else out
    path = tuner.cache_path()
    entries = tuner.cache.load_entries(path)
    print(f"tile cache {path} (schema {tuner.cache.SCHEMA_VERSION}):"
          f" {len(entries)} entries", file=out)
    for key in sorted(entries):
        rec = entries[key]
        gf = rec.get("gflops")
        hgf = rec.get("heuristic_gflops")
        extra = ""
        if gf:
            extra += f"  {gf:9.1f} GFLOPS"
        if gf and hgf:
            extra += f"  (heuristic {hgf:.1f}, x{gf / hgf:.3f})"
        vrec = rec.get("variant")
        vtags = []
        if isinstance(vrec, dict):
            # Non-default winning variant axes, compactly (schema 4).
            if vrec.get("pipeline_depth", 2) != 2:
                vtags.append(f"pipe={vrec['pipeline_depth']}")
            if vrec.get("grid_order", "mn") != "mn":
                vtags.append(f"grid={vrec['grid_order']}")
            if vrec.get("dim_semantics", "parallel") != "parallel":
                vtags.append(f"sem={vrec['dim_semantics']}")
            if vrec.get("check_every") is not None:
                vtags.append(f"cad={vrec['check_every']}")
            if vrec.get("epilogue", "none") != "none":
                vtags.append(f"epi={vrec['epilogue']}")
        vextra = ("  {" + " ".join(vtags) + "}") if vtags else ""
        print(f"  {key}  ->  {tuple(rec['block'])}{vextra}"
              f"  [{rec.get('method', '?')}]{extra}", file=out)
    return 0


def _prewarm_variants(size: int):
    """The bench worker's stage set as ``(name, operand_aval, thunk)``
    triples — thunks so a dry run builds no kernels. Mirrors
    ``scripts/compile_probe.py`` / ``bench.py``'s worker: same factory
    args and injection schedule, so each AOT compile banks the exact
    executable the later timed run will request."""
    from ft_sgemm_tpu.configs import SHAPES

    f32 = jax.ShapeDtypeStruct((size, size), jnp.float32)
    bf16 = jax.ShapeDtypeStruct((size, size), jnp.bfloat16)
    nk = size // SHAPES["huge"].bk

    def ft(**kwargs):
        kern = make_ft_sgemm("huge", alpha=ALPHA, beta=BETA, **kwargs)
        inj = InjectionSpec.reference_like(size, kern.shape_config.bk)
        return lambda a, b, x: kern(a, b, x, inj).c

    variants = [
        ("xla_dot", f32,
         lambda: (lambda a, b, x: sgemm_reference(a, b, x, ALPHA, BETA))),
        ("plain_huge", f32,
         lambda: make_sgemm("huge", alpha=ALPHA, beta=BETA)),
        # The headline ladder, rung by rung, then the comparison stages.
        ("ft_weighted_precomp", f32, lambda: ft(strategy="weighted")),
        ("ft_rowcol", f32, lambda: ft(strategy="rowcol")),
        ("ft_rowcol_mxu", f32,
         lambda: ft(strategy="rowcol", encode="mxu")),
        ("ft_fused", f32, lambda: ft(strategy="fused")),
        ("bf16_xla", bf16,
         lambda: (lambda a, b, x: sgemm_reference(
             a, b, x, ALPHA, BETA, in_dtype="bfloat16"))),
        ("bf16_plain", bf16,
         lambda: make_sgemm("huge", alpha=ALPHA, beta=BETA,
                            in_dtype="bfloat16")),
        ("bf16_abft", bf16,
         lambda: ft(strategy="weighted", in_dtype="bfloat16")),
        ("bf16_fused", bf16,
         lambda: ft(strategy="fused", in_dtype="bfloat16")),
    ]
    if nk >= 2:
        variants.insert(3, ("ft_weighted_inkernel", f32,
                            lambda: ft(strategy="weighted",
                                       check_every=nk // 2)))
    return variants


def _prewarm_compile(size: int, tl_path=None, out=None) -> int:
    """AOT-compile the bench stage set at ``size``, each as a recorded
    compile span, with the persistent compile cache enabled — the shared
    core of ``cli prewarm`` and ``cli tune --prewarm``. Returns the
    number of variants that FAILED to compile."""
    from ft_sgemm_tpu.perf import compile_cache
    from ft_sgemm_tpu.utils.timing import compile_bench_loop

    out = sys.stdout if out is None else out
    status = compile_cache.enable()
    if status["enabled"]:
        print(f"prewarm: compile cache at {status['path']}", file=out)
    else:
        print(f"prewarm: compile cache OFF ({status['reason']}) — "
              "compiles will not persist past this process", file=out)
    recorder = None
    if tl_path:
        from ft_sgemm_tpu.telemetry.timeline import TimelineRecorder

        recorder = TimelineRecorder(tl_path)
    import contextlib

    f32_out = jax.ShapeDtypeStruct((size, size), jnp.float32)
    failures = 0
    for name, ab, thunk in _prewarm_variants(size):
        span = (recorder.span(name, kind="compile")
                if recorder is not None else contextlib.nullcontext({}))
        t0 = time.perf_counter()
        try:
            with span:
                compile_bench_loop(thunk(), ab, ab, f32_out)
            dt = time.perf_counter() - t0
            print(f"prewarm: {name:<22s} OK   {dt:7.1f}s", file=out,
                  flush=True)
        except Exception as e:  # noqa: BLE001 — per-variant report is the job
            failures += 1
            print(f"prewarm: {name:<22s} FAIL {type(e).__name__}: "
                  f"{str(e)[:200]}", file=out, flush=True)
    s = compile_cache.stats()
    print(f"prewarm: cache traffic — hits {s['hits']}, misses"
          f" {s['misses']}, files written {s['files_written']}, bytes"
          f" written {s['bytes_written']}", file=out)
    return failures


def run_prewarm(args, flags, out=None) -> int:
    """``prewarm`` subcommand: the warm-start actuator.

    AOT ``lower().compile()``s the EXACT jitted rep-loop computations
    ``bench.py`` will time at the target size (default 4096) — operands
    are ``ShapeDtypeStruct``s, so no data touches the device and on the
    axon tunnel only the compile service is needed — with the persistent
    compile cache (``FT_SGEMM_COMPILE_CACHE``) enabled, so a bench
    relaunch inside a later tunnel window resumes warm: its compile
    phase collapses to cache retrieval and the window's minutes go to
    measurement. Each compile is recorded as a ``compile`` span when
    ``--timeline=PATH`` (or ``FT_SGEMM_BENCH_TIMELINE``) names a stream.

    ``--dry-run`` prints the variant plan and the resolved cache
    location without compiling anything (CPU/CI-safe: compiling 4096
    interpret-mode kernels on CPU is not). Exit 0 iff every variant
    compiled (or dry run).
    """
    out = sys.stdout if out is None else out
    size = 4096
    if args:
        try:
            size = int(args[0])
        except ValueError:
            print(f"ft_sgemm: prewarm SIZE must be an integer, got"
                  f" {args[0]!r}", file=sys.stderr)
            return 2
    tl_path = None
    for f in flags:
        if f.startswith("--timeline="):
            tl_path = f.split("=", 1)[1]
    tl_path = tl_path or os.environ.get("FT_SGEMM_BENCH_TIMELINE")
    if "--dry-run" in flags:
        from ft_sgemm_tpu.perf import compile_cache

        path, reason = compile_cache.resolve_dir()
        print(f"prewarm (dry run): size {size}, compile cache "
              + (f"at {path}" if path else f"OFF ({reason})"), file=out)
        for name, ab, _ in _prewarm_variants(size):
            print(f"  would compile {name:<22s} operands"
                  f" {tuple(ab.shape)} {ab.dtype}", file=out)
        print("dry run: nothing compiled, nothing written", file=out)
        return 0
    print_device_info()
    failures = _prewarm_compile(size, tl_path=tl_path, out=out)
    return 0 if failures == 0 else 1


def _parse_serve_flags(flags):
    """Shared ``serve`` / ``serve-bench`` flag parsing. Returns
    ``(workload, kwargs)`` or an error string. ``--buckets=`` values are
    padded (M, N, K) sizes for the gemm workload and padded SEQUENCE
    sizes for the block workload — the kwarg is renamed accordingly."""
    kw = {}
    workload = "gemm"
    sizes = None
    pool = "--pool" in flags
    for f in flags:
        try:
            if f.startswith("--sick-device="):
                val = f.split("=", 1)[1]
                kw["sick_device"] = None if val == "none" else int(val)
            elif f.startswith("--workload="):
                workload = f.split("=", 1)[1]
                if workload not in ("gemm", "block"):
                    raise ValueError(
                        f"unknown workload {workload!r} (gemm|block)")
            elif f.startswith("--buckets="):
                sizes = tuple(
                    int(v) for v in f.split("=", 1)[1].split(",") if v)
            elif f.startswith("--requests="):
                kw["num_requests"] = int(f.split("=", 1)[1])
            elif f.startswith("--inject-rate="):
                kw["inject_rate"] = float(f.split("=", 1)[1])
            elif f.startswith("--adversarial-rate="):
                kw["adversarial_rate"] = float(f.split("=", 1)[1])
            elif f.startswith("--rate="):
                kw["rate"] = float(f.split("=", 1)[1])
            elif f.startswith("--decode-ratio="):
                kw["decode_ratio"] = float(f.split("=", 1)[1])
            elif f.startswith("--kv-corrupt-rate="):
                kw["kv_corrupt_rate"] = float(f.split("=", 1)[1])
            elif f.startswith("--dtype="):
                kw["in_dtype"] = canonical_in_dtype(f.split("=", 1)[1])
            elif f.startswith("--monitor-port="):
                kw["monitor_port"] = int(f.split("=", 1)[1])
            elif f.startswith("--epilogue="):
                kw["epilogue"] = EpilogueSpec.parse(
                    f.split("=", 1)[1]).spelling
        except ValueError as e:
            return None, None, f"{f}: {e}"
    if workload != "block":
        for flag in ("decode_ratio", "kv_corrupt_rate"):
            if flag in kw:
                return None, None, (f"--{flag.replace('_', '-')}= needs"
                                    " --workload=block")
    elif "epilogue" in kw:
        return None, None, "--epilogue= needs --workload=gemm"
    if "sick_device" in kw and (not pool or workload == "block"):
        return None, None, ("--sick-device= needs --pool with the gemm"
                            " workload (the drain A/B control)")
    if sizes is not None:
        kw["seq_sizes" if workload == "block" else "bucket_sizes"] = sizes
    if pool:
        workload = "block_pool" if workload == "block" else "pool"
    return workload, kw, None


def run_serve(flags, out=None) -> int:
    """``serve`` subcommand: the serving layer, driven locally.

    ``--dry-run`` prints the serving PLAN — bucket set (dims, dtype,
    strategy, tuner-cache key each bucket dispatches under), the
    injection variants that would be prewarmed, and the resolved
    compile-cache location — without initializing a backend or compiling
    anything (CPU/CI-safe). Without it, the engine prewarms the bucket
    set (AOT compile, persisted when ``FT_SGEMM_COMPILE_CACHE`` is live)
    and serves a short synthetic load, printing the stats table. Exit 0
    iff every completed request resolved correct.
    """
    from ft_sgemm_tpu.serve import (
        default_block_bucket_set, default_bucket_set)
    from ft_sgemm_tpu.serve.engine import VARIANTS

    out = sys.stdout if out is None else out
    workload, kw, err = _parse_serve_flags(flags)
    if err:
        print(f"ft_sgemm: serve: {err}", file=sys.stderr)
        return 2
    in_dtype = kw.pop("in_dtype", "float32")
    block = workload in ("block", "block_pool")
    pool = workload in ("pool", "block_pool")
    try:
        if block:
            sizes = kw.pop("seq_sizes", None) or (128, 256)
            buckets = default_block_bucket_set(sizes, in_dtype=in_dtype)
        else:
            sizes = kw.pop("bucket_sizes", None) or (256, 512)
            buckets = default_bucket_set(
                sizes, in_dtype=in_dtype,
                epilogue=kw.get("epilogue", "none"))
    except ValueError as e:
        print(f"ft_sgemm: serve: {e}", file=sys.stderr)
        return 2
    if "--dry-run" in flags:
        from ft_sgemm_tpu import tuner
        from ft_sgemm_tpu.perf import compile_cache

        path, reason = compile_cache.resolve_dir()
        print(f"serve (dry run): {len(buckets)} {workload} buckets, "
              "compile cache "
              + (f"at {path}" if path else f"OFF ({reason})"), file=out)
        if pool:
            print("  pool: per-device AOT replicas over every local"
                  " device, health-steered placement"
                  f" (sick-device self-test: {kw.get('sick_device', 1)})",
                  file=out)
        for b in buckets:
            if block:
                # Block buckets dispatch explicit per-bucket tiles (the
                # tuner is off for them); the plan shows the padded
                # geometry and the prewarmed variants.
                print(f"  bucket {b.key:<40s}"
                      f" variants={','.join(VARIANTS)}"
                      f"  prefill={b.lq == b.lk}", file=out)
                continue
            # device placeholder: the dry run must never pay (or hang
            # on) backend init just to render the plan.
            key = tuner.make_key(b.m, b.n, b.k, strategy=b.strategy,
                                 in_dtype=b.in_dtype,
                                 injection_enabled=False,
                                 epi=b.epilogue,
                                 device="<device>")
            print(f"  bucket {b.key:<36s} variants={','.join(VARIANTS)}"
                  f"  tuner-key {key}", file=out)
        print("dry run: nothing compiled, nothing served", file=out)
        return 0

    telemetry_log = None
    for f in flags:
        if f.startswith("--telemetry="):
            telemetry_log = f.split("=", 1)[1]
    if telemetry_log:
        from ft_sgemm_tpu import telemetry

        telemetry.configure(telemetry_log, log_clean=True)
    print_device_info()
    from ft_sgemm_tpu.serve import (
        run_block_serve_bench, run_pool_serve_bench, run_serve_bench)

    try:
        if block:
            stats = run_block_serve_bench(smoke=True, in_dtype=in_dtype,
                                          seq_sizes=sizes, verify=True,
                                          pool=pool,
                                          progress_out=sys.stderr, **kw)
        elif pool:
            stats = run_pool_serve_bench(smoke=True, in_dtype=in_dtype,
                                         bucket_sizes=sizes, verify=True,
                                         progress_out=sys.stderr, **kw)
        else:
            stats = run_serve_bench(smoke=True, in_dtype=in_dtype,
                                    bucket_sizes=sizes, verify=True,
                                    progress_out=sys.stderr, **kw)
    finally:
        if telemetry_log:
            from ft_sgemm_tpu import telemetry

            telemetry.disable()
            print(f"serve events written to {telemetry_log}",
                  file=sys.stderr)
    print(f"served {stats['completed']}/{stats['requests_submitted']} "
          f"requests over {stats['wall_seconds']}s "
          f"({stats['requests_rejected']} rejected)", file=out)
    if block:
        print(f"  goodput {stats['goodput_tps']} correct tokens/s  "
              f"(throughput {stats['throughput_tps']} tokens/s; "
              f"{stats['phases']['prefill']} prefill / "
              f"{stats['phases']['decode']} decode)", file=out)
        kv = stats["kv"]
        print(f"  kv cache: {kv['pages_verified']} page verifications  "
              f"faults {stats['kv_faults']}  corrected in place "
              f"{stats['kv_corrected_in_place']}  page restores "
              f"{stats['kv_page_restores']}  verify hit rate "
              f"{kv['verify_hit_rate']}", file=out)
    else:
        print(f"  goodput {stats['goodput_rps']} correct req/s  "
              f"(throughput {stats['throughput_rps']} req/s)", file=out)
    if pool:
        scaling = stats.get("scaling") or {}
        ps = stats.get("pool") or {}
        print(f"  pool: {ps.get('devices_used')}/{ps.get('devices')} "
              f"devices used  scaling x{scaling.get('throughput_ratio')}"
              f"  sick {stats.get('sick_device')} drained="
              f"{stats.get('sick_device_drained')}", file=out)
        for label, row in sorted((ps.get("per_device") or {}).items()):
            print(f"    {label:<16s} batches={row['batches']:<3d} "
                  f"requests={row['requests']:<4d} "
                  f"health={row['health']}", file=out)
    print(f"  latency p50<={stats['p50_latency_seconds']}s "
          f"p99<={stats['p99_latency_seconds']}s", file=out)
    print(f"  corrected free: {stats['corrected_free']}   bucket retries: "
          f"{stats['bucket_retries']}   whole-queue retries: "
          f"{stats['whole_queue_retries']}   uncorrectable after retries: "
          f"{stats['uncorrectable_final']}", file=out)
    slo = stats.get("slo")
    if slo:
        print(f"  slo: {slo['status']}  budget remaining "
              f"{slo['budget_remaining']}  burn {slo['burn_rate']}x  "
              f"device health min {slo['device_health_min']}", file=out)
    for key, row in sorted(stats["per_bucket"].items()):
        print(f"    {key:<36s} requests={row['requests']:<4d} "
              f"batches={row['batches']:<3d} retries={row['retries']}",
              file=out)
    ok = (stats["completed"] > 0
          and stats["correct"] == stats["completed"])
    return 0 if ok else 1


def run_serve_bench_cmd(flags, out=None) -> int:
    """``serve-bench`` subcommand: the goodput bench as a JSON artifact
    line (the same assembly ``python bench.py --serve`` emits — this is
    the in-package spelling for hosts where the bench driver isn't
    checked out). Exit 0 iff goodput > 0 and every completed request
    resolved correct."""
    import json as _json

    out = sys.stdout if out is None else out
    workload, kw, err = _parse_serve_flags(flags)
    if err:
        print(f"ft_sgemm: serve-bench: {err}", file=sys.stderr)
        return 2
    out_path = None
    for f in flags:
        if f.startswith("--out="):
            out_path = f.split("=", 1)[1]
    print_device_info(out=sys.stderr)
    from ft_sgemm_tpu.serve import run_block_serve_bench, run_serve_bench

    if workload in ("block", "block_pool"):
        stats = run_block_serve_bench(smoke="--smoke" in flags,
                                      pool=workload == "block_pool",
                                      progress_out=sys.stderr, **kw)
        artifact = {
            "metric": "serve_block_goodput_tps",
            "value": stats.get("goodput_tps"),
            "unit": "tokens/s",
            "vs_baseline": None,
            "context": stats,
        }
    elif workload == "pool":
        from ft_sgemm_tpu.serve import run_pool_serve_bench

        stats = run_pool_serve_bench(smoke="--smoke" in flags,
                                     progress_out=sys.stderr, **kw)
        artifact = {
            "metric": "serve_goodput_rps",
            "value": stats.get("goodput_rps"),
            "unit": "requests/s",
            "vs_baseline": None,
            "context": stats,
        }
    else:
        stats = run_serve_bench(smoke="--smoke" in flags,
                                progress_out=sys.stderr, **kw)
        artifact = {
            "metric": "serve_goodput_rps",
            "value": stats.get("goodput_rps"),
            "unit": "requests/s",
            "vs_baseline": None,
            "context": stats,
        }
    line = _json.dumps(artifact)
    print(line, file=out, flush=True)
    if out_path:
        with open(out_path, "w", encoding="utf-8") as fh:
            fh.write(line + "\n")
    ok = (stats.get("completed", 0) > 0
          and stats.get("correct") == stats.get("completed")
          and (artifact["value"] or 0) > 0)
    return 0 if ok else 1


def run_drill(flags, out=None) -> int:
    """``drill`` subcommand: the eviction fire drill (DESIGN.md §18).

    Runs :func:`ft_sgemm_tpu.resilience.run_eviction_drill` — baseline
    load through a health-steered pool over every local device, a
    persistent fault stream on one device under live traffic, eviction
    + queued-batch migration + re-AOT, a post-eviction recovery load,
    and one rehearsal of every checksum tier and recompute-ladder rung
    — then prints the recovery facts and emits the artifact line
    (``--out=`` writes it to a file for ledger ingestion). Exit 0 iff
    the device was EVICTED (not just drained), zero responses were lost
    or incorrect, the evicted device received nothing after eviction,
    and goodput recovered past 0.7x the pre-fault baseline.
    """
    import json as _json

    out = sys.stdout if out is None else out
    kw = {}
    out_path = None
    telemetry_log = None
    try:
        for f in flags:
            if f.startswith("--evict-device="):
                kw["evict_device"] = int(f.split("=", 1)[1])
            elif f.startswith("--requests="):
                kw["requests_per_phase"] = int(f.split("=", 1)[1])
            elif f.startswith("--buckets="):
                kw["bucket_sizes"] = tuple(
                    int(v) for v in f.split("=", 1)[1].split(",") if v)
            elif f.startswith("--out="):
                out_path = f.split("=", 1)[1]
            elif f.startswith("--telemetry="):
                telemetry_log = f.split("=", 1)[1]
    except ValueError as e:
        print(f"ft_sgemm: drill: {e}", file=sys.stderr)
        return 2
    if telemetry_log:
        from ft_sgemm_tpu import telemetry

        telemetry.configure(telemetry_log, log_clean=True)
    print_device_info(out=sys.stderr)
    from ft_sgemm_tpu.resilience import run_eviction_drill

    try:
        stats = run_eviction_drill(smoke="--smoke" in flags,
                                   progress_out=sys.stderr, **kw)
    finally:
        if telemetry_log:
            from ft_sgemm_tpu import telemetry

            telemetry.disable()
    rec = stats["recovery"]
    print(f"drill: evicted {rec['evicted_device']} "
          f"(reason={rec['reason']})  migrated "
          f"{rec['migrated_batches']} queued requests  mttr "
          f"{rec['mttr_seconds']}s", file=out)
    print(f"  goodput {rec['goodput_pre_rps']} -> "
          f"{rec['goodput_post_rps']} req/s "
          f"(recovery x{rec['goodput_recovery_ratio']})  incorrect "
          f"responses {rec['incorrect_responses']}  batches on evicted "
          f"after eviction {rec['post_eviction_batches_on_evicted']}",
          file=out)
    if rec.get("tier_detections") is not None:
        tiers = "  ".join(f"{t}={n}"
                          for t, n in rec["tier_detections"].items())
        print(f"  checksum tiers: {tiers}  (checks "
              f"{rec['tier_checks']})", file=out)
    if rec.get("ladder") is not None:
        rungs = "  ".join(f"{r}={n}" for r, n in rec["ladder"].items())
        print(f"  recompute ladder: {rungs}  panel flops ratio "
              f"{rec['panel_recompute_flops_ratio']}", file=out)
    artifact = {
        "metric": "serve_goodput_rps",
        "value": stats.get("goodput_rps"),
        "unit": "requests/s",
        "vs_baseline": None,
        "context": dict(stats, serve=True, drill=True),
    }
    line = _json.dumps(artifact)
    print(line, file=out, flush=True)
    if out_path:
        with open(out_path, "w", encoding="utf-8") as fh:
            fh.write(line + "\n")
    return 0 if stats.get("ok") else 1


def chaos_verdict(doc) -> bool:
    """The campaign's pass predicate (shared by ``cli chaos`` and
    ``bench.py --chaos``): every swept model measured a detection rate,
    every CORRECTABLE model detected at 1.0, and no cell produced an
    incorrect result or a clean-twin false positive.
    """
    models = ((doc.get("context") or {}).get("chaos") or {}).get(
        "models") or {}
    if not models:
        return False
    for entry in models.values():
        rollup = entry.get("rollup") or {}
        det = rollup.get("detection_rate")
        if det is None:
            return False
        if (entry.get("spec") or {}).get("correctable") and det < 1.0:
            return False
        if rollup.get("incorrect_results"):
            return False
        if rollup.get("false_positive_rate"):
            return False
    return True


def run_chaos(flags, out=None) -> int:
    """``chaos`` subcommand: the fault-model coverage campaign
    (DESIGN.md §20).

    Runs :class:`ft_sgemm_tpu.chaos.ChaosCampaign` over the selected
    fault models (default: all of ``contracts.FAULT_MODELS``) and
    prints the coverage table plus the ``chaos_coverage`` artifact line
    (``--out=`` writes it for ledger ingestion; ``--coverage-out=``
    writes the full COVERAGE.json matrix). ``--smoke`` shrinks to 2
    faulted + 1 clean episodes per cell. Exit per
    :func:`chaos_verdict`.
    """
    import json as _json

    out = sys.stdout if out is None else out
    kw = {}
    out_path = None
    coverage_path = None
    telemetry_log = None
    tl_path = None
    try:
        for f in flags:
            if f.startswith("--models="):
                kw["models"] = tuple(
                    v for v in f.split("=", 1)[1].split(",") if v)
            elif f.startswith("--episodes="):
                kw["episodes"] = int(f.split("=", 1)[1])
            elif f.startswith("--clean-episodes="):
                kw["clean_episodes"] = int(f.split("=", 1)[1])
            elif f.startswith("--seed="):
                kw["seed"] = int(f.split("=", 1)[1])
            elif f.startswith("--out="):
                out_path = f.split("=", 1)[1]
            elif f.startswith("--coverage-out="):
                coverage_path = f.split("=", 1)[1]
            elif f.startswith("--telemetry="):
                telemetry_log = f.split("=", 1)[1]
            elif f.startswith("--timeline="):
                tl_path = f.split("=", 1)[1]
    except ValueError as e:
        print(f"ft_sgemm: chaos: {e}", file=sys.stderr)
        return 2
    if "--smoke" in flags:
        kw.setdefault("episodes", 2)
        kw.setdefault("clean_episodes", 1)
    if telemetry_log:
        from ft_sgemm_tpu import telemetry

        telemetry.configure(telemetry_log, log_clean=True)
        kw["registry"] = telemetry.get_registry()
    recorder = None
    if tl_path:
        from ft_sgemm_tpu.telemetry.timeline import TimelineRecorder

        recorder = TimelineRecorder(tl_path)
        kw["timeline"] = recorder
    print_device_info(out=sys.stderr)
    from ft_sgemm_tpu.chaos.campaign import (
        ChaosCampaign,
        render_coverage,
    )

    try:
        doc = ChaosCampaign(**kw).run()
    except ValueError as e:
        print(f"ft_sgemm: chaos: {e}", file=sys.stderr)
        return 2
    finally:
        if recorder is not None:
            recorder.close()
        if telemetry_log:
            from ft_sgemm_tpu import telemetry

            telemetry.disable()
    print(render_coverage(doc), file=out)
    line = _json.dumps(doc)
    print(line, file=out, flush=True)
    if out_path:
        with open(out_path, "w", encoding="utf-8") as fh:
            fh.write(line + "\n")
    if coverage_path:
        with open(coverage_path, "w", encoding="utf-8") as fh:
            _json.dump(doc, fh, indent=1)
            fh.write("\n")
    return 0 if chaos_verdict(doc) else 1


def run_coverage(path, fmt="text", out=None) -> int:
    """``coverage`` subcommand: re-render a saved COVERAGE.json."""
    import json as _json

    out = sys.stdout if out is None else out
    try:
        with open(path, encoding="utf-8") as fh:
            doc = _json.load(fh)
    except (OSError, ValueError) as e:
        print(f"ft_sgemm: coverage: {path}: {e}", file=sys.stderr)
        return 2
    if fmt == "json":
        print(_json.dumps(doc, indent=1), file=out)
        return 0
    from ft_sgemm_tpu.chaos.campaign import render_coverage

    print(render_coverage(doc), file=out)
    return 0


def run_fleet(flags, out=None) -> int:
    """``fleet`` subcommand: launch a real multi-process fleet.

    Spawns ``--procs`` local CPU processes (each its own jax.distributed
    rank with ``--vdevs`` virtual devices) via the kill-safe launcher
    (``ft_sgemm_tpu/fleet/launch.py``) and runs ``--program`` in every
    rank — default ``smoke``: the DCN-honesty phases (staged-vs-flat
    counters across the real process boundary, cross-process fault
    localization, global-tier detection of in-flight DCN corruption)
    plus the cross-host serve acts (per-process pools, host-granularity
    blame, whole-host eviction under load, reshard onto the survivors).
    Prints the merged fleet view and the per-rank statuses; exit 0 iff
    every rank reported ok. The supervisor side never imports jax — the
    ranks own the runtime.
    """
    import json as _json

    out = sys.stdout if out is None else out
    procs, vdevs = 2, 4
    program = "smoke"
    deadline = 540.0
    workdir = None
    try:
        for f in flags:
            if f.startswith("--procs="):
                procs = int(f.split("=", 1)[1])
            elif f.startswith("--vdevs="):
                vdevs = int(f.split("=", 1)[1])
            elif f.startswith("--program="):
                program = f.split("=", 1)[1]
            elif f.startswith("--deadline="):
                deadline = float(f.split("=", 1)[1])
            elif f.startswith("--workdir="):
                workdir = f.split("=", 1)[1]
    except ValueError as e:
        print(f"ft_sgemm: fleet: {e}", file=sys.stderr)
        return 2
    if workdir is None:
        import tempfile

        workdir = tempfile.mkdtemp(prefix="ft_sgemm_fleet_")
    from ft_sgemm_tpu.fleet.launch import FleetSpec, launch_fleet

    print(f"fleet: launching {procs} procs x {vdevs} vdevs "
          f"(program={program}, workdir={workdir})", file=sys.stderr)
    report = launch_fleet(FleetSpec(
        procs=procs, vdevs=vdevs, program=program, workdir=workdir,
        deadline_seconds=deadline, wedge_after=max(120.0, deadline / 3)))
    for rank in sorted(report["ranks"]):
        info = report["ranks"][rank]
        line = (f"  rank{rank}: {info['status']}  rc={info['rc']}  "
                f"heartbeats={info['heartbeats']}")
        if info.get("salvage"):
            line += f"  salvaged_at={info['salvage'].get('killed_at_stage')}"
        print(line, file=out)
    result = report.get("result") or {}
    fleet = result.get("fleet") or {}
    if not fleet and result.get("dcn_tier"):
        # counters program: the DCN-honesty facts live at the result's
        # top level (no serve tier ran, so no fleet block).
        loc = result.get("localized") or {}
        print(f"fleet: dcn_tier={result['dcn_tier']}  "
              f"localized=host{loc.get('host')}:{loc.get('device')} "
              f"coords={loc.get('coords')}  "
              f"merged_hosts={result.get('merged_hosts')}  "
              f"staged_equals_flat={result.get('staged_equals_flat')}",
              file=out)
    if fleet:
        loc = fleet.get("localized") or {}
        print(f"fleet: global tier={fleet.get('global_tier')}  "
              f"localized host{loc.get('host')}:{loc.get('device')} "
              f"coords={loc.get('coords')}", file=out)
        print(f"  evicted host{fleet.get('evicted_host')} "
              f"({fleet.get('eviction_action')})  goodput "
              f"{fleet.get('goodput_pre_rps')} -> "
              f"{fleet.get('goodput_post_rps')} req/s  mttr "
              f"{fleet.get('mttr_seconds')}s  incorrect "
              f"{fleet.get('incorrect_responses')}", file=out)
    print(_json.dumps({"ok": report["ok"], "procs": procs,
                       "vdevs": vdevs, "program": program,
                       "wall_seconds": report["wall_seconds"],
                       "fleet": fleet or None}), file=out, flush=True)
    return 0 if report["ok"] else 1


def run_telemetry_watch(log_path: str, out=None, interval: float = 0.5,
                        max_seconds=None) -> int:
    """``telemetry --watch``: follow a GROWING fault-event shard.

    Tails the JSONL file byte-incrementally (only appended bytes are
    read and parsed — the shard may grow without bound), re-summarizes
    on every batch of new events, and reprints the summary, so an
    in-flight run is inspectable without the HTTP monitoring plane.
    Torn tails are left unconsumed until the writer completes the line
    (the JsonlSink flushes per event, so a torn line is always the one
    in flight). The file not existing yet is fine — the watch waits for
    it. Stdlib-only by the timeline discipline: following a log must
    never need a backend. Stops on Ctrl-C (exit 0) or after
    ``max_seconds`` (the bounded form tests and scripts use)."""
    from ft_sgemm_tpu.telemetry import format_summary, summarize_events
    from ft_sgemm_tpu.telemetry.events import parse_event_line

    out = sys.stdout if out is None else out
    events = []
    offset = 0
    rendered_count = -1
    t0 = time.monotonic()
    try:
        while True:
            if os.path.exists(log_path):
                try:
                    with open(log_path, "rb") as fh:
                        fh.seek(offset)
                        chunk = fh.read()
                except OSError as e:
                    print(f"ft_sgemm: cannot read telemetry log: {e}",
                          file=sys.stderr)
                    return 2
                # Only consume through the last complete line; a torn
                # tail stays unread until its newline lands.
                end = chunk.rfind(b"\n")
                if end >= 0:
                    for raw in chunk[:end + 1].splitlines():
                        ev = parse_event_line(
                            raw.decode("utf-8", errors="replace"))
                        if ev is not None:
                            events.append(ev)
                    offset += end + 1
            if len(events) != rendered_count:
                rendered_count = len(events)
                print(f"--- telemetry watch of {log_path} "
                      f"({rendered_count} events) ---", file=out)
                print(format_summary(summarize_events(events)), file=out,
                      flush=True)
            if max_seconds is not None and \
                    time.monotonic() - t0 >= max_seconds:
                return 0
            time.sleep(interval)
    except KeyboardInterrupt:
        print(f"watch stopped ({len(events)} events seen)", file=out)
        return 0


def _http_get(url: str, timeout: float = 5.0) -> str:
    import urllib.request

    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read().decode("utf-8", errors="replace")


def _render_top(url: str, out, since: int, poll: int) -> int:
    """One ``cli top`` frame: scrape /healthz + /metrics + /events and
    render the live serving view. Returns the advanced event cursor."""
    import json as _json

    from ft_sgemm_tpu.telemetry.registry import (
        histogram_percentiles, parse_prometheus)

    health = _json.loads(_http_get(url + "/healthz"))
    series = parse_prometheus(_http_get(url + "/metrics"))
    ev = _json.loads(_http_get(f"{url}/events?since={since}&limit=8"))

    def find(name, **labels):
        for s in series:
            if s["name"] == name and all(
                    s["labels"].get(k) == v for k, v in labels.items()):
                yield s

    def value(name, default=None, **labels):
        for s in find(name, **labels):
            return s["value"]
        return default

    print(f"ft-sgemm top — {url}  (poll #{poll}, Ctrl-C to stop)",
          file=out)
    print(f"health: {health['status']}"
          + ("  [" + "; ".join(health["reasons"]) + "]"
             if health.get("reasons") else ""), file=out)
    print(f"slo: budget remaining {value('slo_budget_remaining', '-')}"
          f"  burn {value('slo_burn_rate', '-')}x"
          f"  window requests {value('slo_window_requests', '-')}"
          f"  goodput {value('slo_goodput_ratio', '-')}", file=out)
    # Block-serving gauges (PR 12) — rendered only when the process
    # serves the block workload; older exporters (and ledger-replayed
    # registries) simply lack the series and the line is skipped.
    tps = value("serve_block_tokens_per_second")
    kv_hit = value("kv_verify_hit_rate")
    if tps is not None or kv_hit is not None:
        print("block: "
              + (f"tokens-correct/s {tps}" if tps is not None else "")
              + ("  " if tps is not None and kv_hit is not None else "")
              + (f"kv verify hit rate {kv_hit}"
                 if kv_hit is not None else ""), file=out)
    buckets = sorted({s["labels"]["bucket"]
                      for s in find("serve_requests")
                      if "bucket" in s["labels"]})
    if buckets:
        print(f"  {'bucket':<36s} {'reqs':>6s} {'retries':>7s} "
              f"{'p50':>10s} {'p99':>10s}", file=out)
        for b in buckets:
            hist = value("serve_latency_seconds", bucket=b)
            pct = (histogram_percentiles(hist, quantiles=(0.5, 0.99))
                   if isinstance(hist, dict) else {})

            def fmt(v):
                return f"{v:.4g}s" if isinstance(v, (int, float)) else "-"

            print(f"  {b:<36s} {value('serve_requests', 0, bucket=b):>6} "
                  f"{value('serve_retries', 0, bucket=b):>7} "
                  f"{fmt(pct.get('p50')):>10s} {fmt(pct.get('p99')):>10s}",
                  file=out)
    blk_buckets = sorted({s["labels"]["bucket"]
                          for s in find("serve_block_requests")
                          if "bucket" in s["labels"]})
    if blk_buckets:
        print(f"  {'block bucket':<40s} {'reqs':>6s} {'retries':>7s} "
              f"{'p50':>10s} {'p99':>10s}", file=out)
        for b in blk_buckets:
            hist = value("serve_block_latency_seconds", bucket=b)
            pct = (histogram_percentiles(hist, quantiles=(0.5, 0.99))
                   if isinstance(hist, dict) else {})

            def fmt(v):
                return f"{v:.4g}s" if isinstance(v, (int, float)) else "-"

            reqs = sum(s["value"]
                       for s in find("serve_block_requests", bucket=b))
            print(f"  {b:<40s} {reqs:>6.0f} "
                  f"{value('serve_block_retries', 0, bucket=b):>7} "
                  f"{fmt(pct.get('p50')):>10s} {fmt(pct.get('p99')):>10s}",
                  file=out)
    # Cost plane (PR 20) — economics_* gauges the engines publish per
    # request; absent on processes without the cost plane, line skipped.
    uff = value("economics_useful_flops_fraction")
    if uff is not None:
        tcs = value("economics_tokens_correct_per_second_per_device")
        print(f"economics: useful flops {uff}"
              f"  requests {value('economics_requests', '-')}"
              f"  tokens-correct {value('economics_tokens_correct', '-')}"
              + (f"  tok-correct/s/dev {tcs}" if tcs is not None else ""),
              file=out)
        causes = sorted(
            find("economics_overhead_flops_fraction"),
            key=lambda s: -s["value"])
        if causes:
            print("  overhead: " + "  ".join(
                f"{s['labels'].get('overhead_cause', '?')}={s['value']}"
                for s in causes), file=out)
    # Fleet rows (PR 20) — per-host clock skew + hop latency, present
    # only when the process runs the fleet dispatcher.
    skews = sorted(find("fleet_clock_skew_seconds"),
                   key=lambda s: s["labels"].get("host", ""))
    if skews:
        print("fleet: clock skew " + "  ".join(
            f"host{s['labels'].get('host', '?')}={s['value']:+.4f}s"
            for s in skews), file=out)
        from ft_sgemm_tpu.contracts import FLEET_HOPS
        for hop in FLEET_HOPS:
            rows = list(find(f"fleet_hop_{hop}_seconds"))
            vals = [s["value"] for s in rows if isinstance(s["value"], dict)]
            if not vals:
                continue
            merged = {"buckets": vals[0]["buckets"],
                      "counts": [sum(v["counts"][i] for v in vals)
                                 for i in range(len(vals[0]["counts"]))],
                      "sum": sum(v["sum"] for v in vals),
                      "count": sum(v["count"] for v in vals)}
            if not merged["count"]:
                continue
            pct = histogram_percentiles(merged, quantiles=(0.5, 0.95))
            print(f"  hop {hop:<16s} p50 {pct.get('p50', 0):.4g}s"
                  f"  p95 {pct.get('p95', 0):.4g}s"
                  f"  n {merged['count']:.0f}", file=out)
    dh = sorted(find("device_health"),
                key=lambda s: s["value"])
    if dh:
        print("device health:", file=out)
        for s in dh:
            drift = value("device_health_drift", 0.0,
                          **{k: v for k, v in s["labels"].items()})
            flag = ("  !!" if s["value"] < 0.9 else "")
            print(f"  {s['labels'].get('device', '?'):<28s} "
                  f"{s['value']:.3f}"
                  + (f"  drift z={drift:.1f}" if drift else "") + flag,
                  file=out)
    if ev.get("events"):
        print("recent events:", file=out)
        for e in ev["events"]:
            extra = e.get("extra") or {}
            bits = [e.get("outcome", "?"), e.get("op", "?")]
            if extra.get("trace_id"):
                bits.append(f"trace={extra['trace_id']}")
            if extra.get("bucket"):
                bits.append(f"bucket={extra['bucket']}")
            if e.get("tiles"):
                bits.append(f"tiles={e['tiles']}")
            if extra.get("kind"):
                bits.append(f"kind={extra['kind']}")
            print("  " + "  ".join(str(b) for b in bits), file=out)
    return ev.get("next", since)


def run_top(url: str, out=None, interval: float = 2.0,
            iterations=None) -> int:
    """``top`` subcommand: the live terminal view of a serving process.

    Polls a monitor exporter's ``/metrics`` + ``/healthz`` + ``/events``
    (started with ``serve --monitor-port=N`` / ``bench.py --serve
    --monitor-port=N``) and renders per-bucket request/latency rows, the
    SLO budget, the device-health column, and the recent-event tail.
    ``--once`` (or ``--iterations=N``) bounds the loop for scripts/CI;
    unbounded mode refreshes every ``--interval`` seconds until Ctrl-C
    (rendered as a clean kill point, exit 0). Exit 2 when the exporter
    is unreachable."""
    out = sys.stdout if out is None else out
    url = url.rstrip("/")
    if "://" not in url:
        url = "http://" + url
    since = 0
    poll = 0
    try:
        while True:
            poll += 1
            try:
                since = _render_top(url, out, since, poll)
            except (OSError, ValueError) as e:
                print(f"ft_sgemm: top: cannot scrape {url}: {e}",
                      file=sys.stderr)
                return 2
            if iterations is not None and poll >= iterations:
                return 0
            print("", file=out, flush=True)
            time.sleep(interval)
    except KeyboardInterrupt:
        print("top: stopped", file=out)
        return 0


def main(argv=None) -> int:
    argv = list(sys.argv if argv is None else argv)
    args = [a for a in argv[1:] if not a.startswith("--")]
    flags = {a for a in argv[1:] if a.startswith("--")}
    if args and args[0] == "lint":
        # The linter is stdlib-only and reads declarations via ast; its
        # own main() parses the flag set (order-independent).
        from ft_sgemm_tpu.lint.core import main as lint_main

        return lint_main(sorted(flags))
    if args and args[0] == "tune":
        return run_tune(args[1:], flags)
    if args and args[0] == "tune-ring":
        return run_tune_ring(args[1:], flags)
    if args and args[0] == "tune-show":
        return run_tune_show()
    if args and args[0] == "roc":
        return run_roc(flags)
    if args and args[0] == "prewarm":
        return run_prewarm(args[1:], flags)
    if args and args[0] == "serve":
        return run_serve(flags)
    if args and args[0] == "serve-bench":
        return run_serve_bench_cmd(flags)
    if args and args[0] == "drill":
        return run_drill(flags)
    if args and args[0] == "chaos":
        return run_chaos(flags)
    if args and args[0] == "coverage":
        if len(args) < 2:
            print(__doc__)
            return 2
        fmt = "text"
        for f in flags:
            if f.startswith("--format="):
                fmt = f.split("=", 1)[1]
                if fmt not in ("text", "json"):
                    print(f"--format must be text or json, got {fmt!r}",
                          file=sys.stderr)
                    return 2
        return run_coverage(args[1], fmt=fmt)
    if args and args[0] == "fleet":
        return run_fleet(flags)
    if args and args[0] == "history":
        return run_history(args[1:], flags)
    if args and args[0] == "trend":
        return run_trend(args[1:], flags)
    if args and args[0] == "ingest":
        if len(args) < 3:
            print(__doc__)
            return 2
        return run_ingest(args[1:], flags)
    if args and args[0] == "trace-export":
        if len(args) < 2:
            print(__doc__)
            return 2
        return run_trace_export(args[1:], flags)
    if args and args[0] == "economics":
        if len(args) < 2:
            print(__doc__)
            return 2
        return run_economics(args[1:], flags)
    if args and args[0] == "top":
        if len(args) < 2:
            print(__doc__)
            return 2
        interval = 2.0
        iterations = None
        for f in flags:
            if f.startswith("--interval="):
                try:
                    interval = float(f.split("=", 1)[1])
                except ValueError:
                    print(f"--interval must be a float, got {f!r}",
                          file=sys.stderr)
                    return 2
            elif f.startswith("--iterations="):
                try:
                    iterations = int(f.split("=", 1)[1])
                except ValueError:
                    print(f"--iterations must be an int, got {f!r}",
                          file=sys.stderr)
                    return 2
        if "--once" in flags:
            iterations = 1
        return run_top(args[1], interval=interval, iterations=iterations)
    if args and args[0] == "telemetry":
        if len(args) < 2:
            print(__doc__)
            return 2
        fmt = "text"
        watch_seconds = None
        interval = 0.5
        for f in flags:
            if f.startswith("--format="):
                fmt = f.split("=", 1)[1]
                if fmt not in ("text", "prom"):
                    print(f"--format must be text or prom, got {fmt!r}",
                          file=sys.stderr)
                    return 2
            elif f.startswith("--watch-seconds="):
                try:
                    watch_seconds = float(f.split("=", 1)[1])
                except ValueError:
                    print(f"--watch-seconds must be a float, got {f!r}",
                          file=sys.stderr)
                    return 2
            elif f.startswith("--interval="):
                try:
                    interval = float(f.split("=", 1)[1])
                except ValueError:
                    print(f"--interval must be a float, got {f!r}",
                          file=sys.stderr)
                    return 2
        if "--watch" in flags or watch_seconds is not None:
            return run_telemetry_watch(args[1], interval=interval,
                                       max_seconds=watch_seconds)
        return run_telemetry_summary(args[1], fmt=fmt,
                                     by_device="--by-device" in flags)
    if args and args[0] == "attribute":
        if len(args) < 2:
            print(__doc__)
            return 2
        return run_attribute(args[1:])
    if args and args[0] == "timeline":
        if len(args) < 2:
            print(__doc__)
            return 2
        fmt = "text"
        for f in flags:
            if f.startswith("--format="):
                fmt = f.split("=", 1)[1]
                if fmt not in ("text", "json"):
                    print(f"--format must be text or json, got {fmt!r}",
                          file=sys.stderr)
                    return 2
        return run_timeline(args[1], fmt=fmt, phases="--phases" in flags)
    if args and args[0] == "report":
        if len(args) < 2:
            print(__doc__)
            return 2
        fmt = "md"
        for f in flags:
            if f.startswith("--format="):
                fmt = f.split("=", 1)[1]
                if fmt not in ("md", "json"):
                    print(f"--format must be md or json, got {fmt!r}",
                          file=sys.stderr)
                    return 2
        return run_report(args[1], fmt=fmt)
    if args and args[0] == "bench-compare":
        if len(args) < 3:
            print(__doc__)
            return 2
        tolerance = None
        fmt = "text"
        for f in flags:
            if f.startswith("--tolerance="):
                try:
                    tolerance = float(f.split("=", 1)[1])
                except ValueError:
                    print(f"--tolerance must be a float, got {f!r}",
                          file=sys.stderr)
                    return 2
                if tolerance < 0:
                    print("--tolerance must be >= 0", file=sys.stderr)
                    return 2
            elif f.startswith("--format="):
                fmt = f.split("=", 1)[1]
                if fmt not in ("text", "json"):
                    print(f"--format must be text or json, got {fmt!r}",
                          file=sys.stderr)
                    return 2
        return run_bench_compare(args[1], args[2], tolerance=tolerance,
                                 fmt=fmt)
    if len(args) < 5:
        print(__doc__)
        return 2
    try:
        start_size, end_size, gap_size, st_kernel, end_kernel = map(int, args[:5])
    except ValueError:
        print(f"ft_sgemm: arguments must be integers, got {args[:5]}",
              file=sys.stderr)
        print(__doc__)
        return 2
    min_device_time = 1.0
    trace_dir = None
    in_dtype = "float32"
    strategy = None  # resolved per-dtype after flag parsing
    encode = "vpu"
    threshold = "static"
    telemetry_log = None
    for f in flags:
        if f.startswith("--mintime="):
            min_device_time = float(f.split("=", 1)[1])
        elif f.startswith("--trace="):
            trace_dir = f.split("=", 1)[1]
        elif f.startswith("--telemetry="):
            telemetry_log = f.split("=", 1)[1]
        elif f.startswith("--dtype="):
            in_dtype = f.split("=", 1)[1]
            try:
                in_dtype = canonical_in_dtype(in_dtype)
            except ValueError:
                print(f"--dtype must be one of {IN_DTYPES} (or an fp8"
                      f" alias), got {in_dtype!r}", file=sys.stderr)
                return 2
        elif f.startswith("--threshold="):
            threshold = f.split("=", 1)[1]
            if threshold not in THRESHOLD_MODES:
                try:
                    threshold = float(threshold)
                except ValueError:
                    print(f"--threshold must be one of {THRESHOLD_MODES} or"
                          f" a float, got {threshold!r}", file=sys.stderr)
                    return 2
        elif f.startswith("--strategy="):
            strategy = f.split("=", 1)[1]
            if strategy not in STRATEGIES:
                print(f"--strategy must be one of {STRATEGIES}, got"
                      f" {strategy!r}", file=sys.stderr)
                return 2
        elif f.startswith("--encode="):
            encode = f.split("=", 1)[1]
            if encode not in ENCODE_MODES:
                print(f"--encode must be one of {ENCODE_MODES}, got"
                      f" {encode!r}", file=sys.stderr)
                return 2
    if strategy is None:
        # weighted is the reference default, but int8 only ships the
        # exact strategies (configs.check_kernel_legality); an explicit
        # illegal --strategy= still errors with the constraint.
        strategy = DEFAULT_STRATEGY[in_dtype]
        if in_dtype == "int8":
            print(f"--dtype=int8: defaulting --strategy={strategy}"
                  " (weighted-ratio localization is illegal for int8)",
                  file=sys.stderr)

    if telemetry_log is not None:
        # Observability mode: events + host-side residual measurements
        # for every FT call of the run (clean calls included — their
        # residuals are the noise-floor half of the calibration input).
        from ft_sgemm_tpu import telemetry

        telemetry.configure(telemetry_log, measure_residual=True,
                            log_clean=True)
    print_device_info()
    ok = True
    try:
        if "--no-verify" not in flags:
            ok = run_verification(end_size, st_kernel, end_kernel,
                                  in_dtype=in_dtype, strategy=strategy,
                                  encode=encode, threshold=threshold)
        if "--no-perf" not in flags:
            import contextlib

            ctx = (jax.profiler.trace(trace_dir) if trace_dir
                   else contextlib.nullcontext())
            with ctx:
                run_perf_table(start_size, end_size, gap_size, st_kernel,
                               end_kernel, min_device_time=min_device_time,
                               in_dtype=in_dtype, strategy=strategy,
                               encode=encode, threshold=threshold)
    finally:
        if telemetry_log is not None:
            from ft_sgemm_tpu import telemetry

            telemetry.disable()
            print(f"telemetry events written to {telemetry_log}",
                  file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
