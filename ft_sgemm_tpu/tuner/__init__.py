"""Autotuner subsystem: searched + persisted tile configs, cache-backed dispatch.

The kernel family (``configs.SHAPES`` + the ``KernelShape`` parameterization)
is exactly the reference's generated-kernel family, and like the reference
the shipped tile choices come from hand-run sweeps at a few sizes. This
subsystem closes the loop: it searches the family per
``(device_kind, M/N/K bucket, dtype, strategy, injection)`` and serves the
winner from a persistent cache on every later dispatch.

Pipeline (:func:`tune`):

1. :mod:`.space` enumerates the legal tile space and prunes infeasible
   candidates with the calibrated ``ops/vmem`` footprint model — nothing
   over the Mosaic scoped-VMEM budget is ever compiled.
2. :mod:`.measure` times the survivors (warmup + median-of-k via
   ``utils/timing``), clean or injected, recording through the telemetry
   registry. On CPU it falls back to interpret/compile-only measurement so
   the whole subsystem runs under ``JAX_PLATFORMS=cpu``.
3. :mod:`.cache` persists the winner in a versioned, schema-checked JSON
   document (``FT_SGEMM_TUNER_CACHE`` overrides the path).
4. Dispatch (:func:`lookup_tile`, called by ``make_sgemm`` /
   ``make_ft_sgemm`` / the attention factories) overrides the heuristic
   block choice with a cached winner.

**Zero-regression guarantee.** The lookup is pure host-side Python at
trace time: with no cache entry (or tuning disabled via
``FT_SGEMM_TUNING=0`` or :func:`override_disabled`), dispatch returns to
the heuristic path before touching anything traced, so the emitted HLO is
byte-identical to the untuned build (pinned in ``tests/test_tuner.py``,
the ``tests/test_telemetry.py`` technique). Explicit ``KernelShape``
dispatches are never overridden — a tile sweep measures the tile its row
label claims, and the tuner's own measurements can never recurse into the
cache they are filling.

CLI: ``python -m ft_sgemm_tpu.cli tune`` / ``tune-show``;
``python bench.py --tuned`` reports heuristic-vs-tuned side by side.
"""

from __future__ import annotations

import contextlib
import os
import threading
from typing import Optional

from ft_sgemm_tpu.configs import (
    EpilogueSpec,
    KernelShape,
    KernelVariant,
    canonical_variant,
)
from ft_sgemm_tpu.tuner import cache, measure, space
from ft_sgemm_tpu.tuner.cache import (
    ENV_CACHE_PATH,
    cache_path,
    device_kind,
    make_key,
    mnk_bucket,
)
from ft_sgemm_tpu.tuner.measure import (
    METHODS,
    MeasureResult,
    best_result,
    default_method,
    measure_space,
)
from ft_sgemm_tpu.tuner.space import (
    enumerate_joint_space,
    enumerate_space,
    heuristic_shape,
)

ENV_TUNING = "FT_SGEMM_TUNING"
_OFF_VALUES = ("0", "off", "false", "no")

_LOCAL = threading.local()

# Dispatch-lookup outcome counts (process-wide, plain ints under a lock):
# the perf subsystem's RunReport embeds them so an artifact says whether
# its kernels ran tuned tiles or heuristics. Kept independent of the
# telemetry on/off switch — a counter bump is ~free and the manifest
# wants the answer even on un-instrumented runs; the telemetry registry
# is additionally mirrored when enabled (the subsystem convention).
_STATS_LOCK = threading.Lock()
_STATS = {"hits": 0, "misses": 0}


def lookup_stats() -> dict:
    """Snapshot of dispatch cache-lookup outcomes: ``{"hits", "misses"}``
    since process start (lookups while tuning is disabled don't count —
    nothing was asked of the cache)."""
    with _STATS_LOCK:
        return dict(_STATS)


def reset_lookup_stats() -> None:
    """Zero the lookup counters (tests; between independent runs)."""
    with _STATS_LOCK:
        _STATS["hits"] = 0
        _STATS["misses"] = 0


def _count_lookup(hit: bool) -> None:
    with _STATS_LOCK:
        _STATS["hits" if hit else "misses"] += 1
    from ft_sgemm_tpu import telemetry

    if telemetry.enabled():
        telemetry.get_registry().counter(
            "tuner.cache_lookups",
            result="hit" if hit else "miss").inc()


def enabled() -> bool:
    """Whether dispatch consults the tile cache.

    On by default (an empty cache is a no-op by construction); ``FT_SGEMM_
    TUNING=0`` turns lookup off process-wide, :func:`override_disabled`
    scopes it off for a block (the measurement path uses this so a search
    can never serve itself stale winners).
    """
    if getattr(_LOCAL, "off_depth", 0) > 0:
        return False
    return os.environ.get(ENV_TUNING, "").lower() not in _OFF_VALUES


@contextlib.contextmanager
def override_disabled():
    """Scope with tuner dispatch off in this thread (measurement, sweeps,
    HLO-pinning tests)."""
    _LOCAL.off_depth = getattr(_LOCAL, "off_depth", 0) + 1
    try:
        yield
    finally:
        _LOCAL.off_depth -= 1


def variant_key_components(variant: Optional[KernelVariant],
                           cadence: Optional[int],
                           epilogue: str = "none") -> dict:
    """The schema-5 ``pipe=``/``grid=``/``cad=``/``epi=``/``ring=`` key
    components for one dispatch constraint: ``"auto"`` for every axis
    the caller left to the search, the explicit spelling for pinned
    axes. ONE resolver shared by dispatch lookup and the search's store
    so the two sides can never key differently. The single-device
    kernel family has no ring, so its constraint spells
    ``ring="serial"`` (the :class:`KernelVariant` default) — the ring
    wrappers key their own lookups ``ring="auto"`` through
    :func:`lookup_ring_overlap`."""
    if variant is not None:
        pipe = str(variant.pipeline_depth)
        grid = variant.grid_spelling
        ring = variant.ring_overlap
    else:
        pipe = grid = "auto"
        ring = "serial"
    return {
        "pipe": pipe,
        "grid": grid,
        "cad": "auto" if cadence is None else str(cadence),
        "epi": EpilogueSpec.parse(epilogue).spelling,
        "ring": ring,
    }


def lookup_winner(
    m: int, n: int, k: int, *, strategy: Optional[str],
    in_dtype, injection_enabled: bool,
    encode: str = "vpu",
    threshold_mode: str = "static",
    variant: Optional[KernelVariant] = None,
    cadence: Optional[int] = None,
    epilogue: str = "none",
) -> tuple:
    """The cached winner for one dispatch site:
    ``(tile or None, winning KernelVariant or None)``.

    Pure host-side and cheap (one ``os.stat`` + dict probe in the steady
    state); returns ``(None, None)`` without touching anything when
    tuning is off, so the no-entry/disabled dispatch path is bit-for-bit
    the heuristic one. ``variant``/``cadence``/``epilogue`` are the
    caller's CONSTRAINTS (:func:`variant_key_components`): a pinned axis
    keys with its explicit spelling and the returned variant echoes the
    record's — the caller decides which unpinned axes to adopt. A record
    without a valid ``variant`` field yields ``(tile, None)``.
    """
    if not enabled():
        return None, None
    comp = variant_key_components(variant, cadence, epilogue)
    rec = cache.lookup(make_key(m, n, k, strategy=strategy,
                                in_dtype=in_dtype, encode=encode,
                                threshold_mode=threshold_mode,
                                injection_enabled=injection_enabled,
                                **comp))
    _count_lookup(rec is not None)
    if rec is None:
        return None, None
    bm, bn, bk = rec["block"]
    tile = KernelShape(space.candidate_name(bm, bn, bk), bm, bn, bk,
                       (0,) * 7)
    win_var = None
    vrec = rec.get("variant")
    if isinstance(vrec, dict):
        try:
            win_var = canonical_variant(vrec)
        except ValueError:
            win_var = None  # stale/foreign record: tile still serves
    return tile, win_var


def lookup_tile(m: int, n: int, k: int, *, strategy: Optional[str],
                in_dtype, injection_enabled: bool,
                encode: str = "vpu",
                threshold_mode: str = "static") -> Optional[KernelShape]:
    """The cached winning tile for one dispatch site, or None (heuristics).

    The tile-only view of :func:`lookup_winner` (default-variant
    constraint), kept for callers with no variant axis of their own —
    the attention factories' QK/PV tile dispatch.
    """
    tile, _ = lookup_winner(
        m, n, k, strategy=strategy, in_dtype=in_dtype,
        injection_enabled=injection_enabled, encode=encode,
        threshold_mode=threshold_mode)
    return tile


def lookup_ring_overlap(m_loc: int, n_loc: int, k: int, *,
                        strategy: Optional[str], in_dtype,
                        injection_enabled: bool = False) -> Optional[str]:
    """The cached winning ring hop schedule for one PER-DEVICE local
    shard problem, or None (dispatch then runs the serial default).

    The ring wrappers key on the local shard dims — ``(m/d, n/d, k)``
    for the GEMM ring, the per-hop QK problem for ring attention — so
    the ring size rides the key through the bucketed dims, and the
    constraint spells ``ring="auto"`` (the record's ``variant`` carries
    the searched winner, :func:`tune_ring` banks it). Pure host-side
    and subject to the same enabled()/disabled discipline as every
    other lookup.
    """
    from ft_sgemm_tpu.configs import RING_OVERLAP_MODES

    if not enabled():
        return None
    rec = cache.lookup(make_key(
        m_loc, n_loc, k, strategy=strategy, in_dtype=in_dtype,
        injection_enabled=injection_enabled, ring="auto"))
    _count_lookup(rec is not None)
    if rec is None:
        return None
    vrec = rec.get("variant")
    mode = vrec.get("ring_overlap") if isinstance(vrec, dict) else None
    return mode if mode in RING_OVERLAP_MODES else None


def tune_ring(
    m: int, n: Optional[int] = None, k: Optional[int] = None, *,
    mesh=None,
    strategy: Optional[str] = "weighted",
    in_dtype: str = "float32",
    method: Optional[str] = None,
    alpha: float = 1.0, beta: float = -1.5,
    reps: int = 2, samples: int = 2,
    write_cache: bool = True,
) -> dict:
    """Search the ``ring_overlap`` axis for one GLOBAL ring problem and
    persist the winner under the per-device local-shard key.

    ``method`` is ``"wall"`` (time both schedules through jit-once ring
    executors — the TPU default) or ``"cost"`` (the
    :func:`measure.ring_schedule_cost` model — the CPU default, where
    virtual devices have no ICI to time). The stored record's
    ``variant.ring_overlap`` is what :func:`lookup_ring_overlap` serves
    to ``ring_ft_sgemm``/ring attention dispatch with
    ``ring_overlap=None``/"auto".
    """
    n = m if n is None else n
    k = m if k is None else k
    if mesh is None:
        from ft_sgemm_tpu.parallel.ring import make_ring_mesh

        mesh = make_ring_mesh()
    d = mesh.shape["x"]
    with override_disabled():
        report = measure.measure_ring_schedules(
            m, n, k, mesh, strategy=strategy, in_dtype=in_dtype,
            method=method, alpha=alpha, beta=beta, reps=reps,
            samples=samples)
    win = report["winner"]
    key = make_key(m // d, n // d, k, strategy=strategy,
                   in_dtype=in_dtype, injection_enabled=False,
                   ring="auto")
    report["key"] = key
    if write_cache:
        tile = heuristic_shape(m // d, n // d, k, strategy=strategy,
                               in_dtype=in_dtype)
        record = {
            "block": list(tile.block),
            "variant": variant_asdict(KernelVariant(ring_overlap=win)),
            "ring": {mode: report[mode] for mode in ("serial", "overlap")},
            "method": report["method"],
            "problem": [m, n, k],
            "ring_size": d,
        }
        report["cache_path"] = cache.store(key, record)
    return report


def tune(
    m: int, n: Optional[int] = None, k: Optional[int] = None, *,
    strategy: Optional[str] = "weighted",
    encode: str = "vpu",
    in_dtype: str = "float32",
    threshold_mode: str = "static",
    inject=False,
    method: Optional[str] = None,
    budget: Optional[int] = 8,
    alpha: float = 1.0, beta: float = -1.5,
    reps: int = 3, samples: int = 3,
    dry_run: bool = False,
    write_cache: bool = True,
    progress=None,
    epilogue: str = "none",
    pipeline_depth: Optional[int] = None,
    grid_order: Optional[str] = None,
    dim_semantics: Optional[str] = None,
    check_every: Optional[int] = None,
    axis_tile_top: int = 2,
) -> dict:
    """Search the JOINT (tile x variant) space for one problem and
    persist the winner.

    Returns a report dict: the candidate space (feasible + pruned with
    reasons, per tile AND per variant axis), per-candidate measurements,
    the heuristic baseline row, the winner, and the cache key/path
    written. ``dry_run`` stops after the static prune (nothing measured,
    nothing written). ``inject`` is False, True (a reference-like
    schedule), or an explicit ``InjectionSpec``. ``budget`` caps how
    many candidates are timed (best-guess-first order); None times them
    all. ``encode`` is a searched dimension since schema 2, the
    threshold mode and low-precision dtypes since schema 3, and the
    pipeline/grid/cadence variant axes since schema 4 — searched by
    default (``enumerate_joint_space``'s per-axis pruning names
    everything not tried), or pinned via ``pipeline_depth`` /
    ``grid_order`` / ``dim_semantics`` / ``check_every``. ``epilogue``
    is the workload-owned fused-epilogue spelling: it keys the search
    (``epi=``) and rides every measured candidate, but is never
    enumerated against other epilogues. Illegal (strategy, encode,
    dtype) combinations (e.g. int8 x mxu) are rejected up front with the
    kernel factory's error.
    """
    from ft_sgemm_tpu.configs import check_kernel_legality
    from ft_sgemm_tpu.injection import InjectionSpec

    n = m if n is None else n
    k = m if k is None else k
    if strategy is not None:
        in_dtype = check_kernel_legality(
            strategy=strategy, encode=encode, in_dtype=in_dtype,
            threshold_mode=threshold_mode)
    method = default_method() if method is None else method
    epi = EpilogueSpec.parse(epilogue).spelling
    pinned_axes = (pipeline_depth is not None or grid_order is not None
                   or dim_semantics is not None)
    pin_variant = KernelVariant(
        pipeline_depth=pipeline_depth if pipeline_depth is not None else 2,
        grid_order=grid_order if grid_order is not None else "mn",
        dim_semantics=(dim_semantics if dim_semantics is not None
                       else "parallel"),
        epilogue=epi) if pinned_axes else None
    candidates, pruned = enumerate_joint_space(
        m, n, k, strategy=strategy, encode=encode, in_dtype=in_dtype,
        threshold_mode=threshold_mode, epilogue=epi,
        axis_tile_top=axis_tile_top,
        pin_pipeline=pipeline_depth, pin_grid_order=grid_order,
        pin_dim_semantics=dim_semantics, pin_check_every=check_every)
    key = make_key(m, n, k, strategy=strategy, in_dtype=in_dtype,
                   encode=encode, threshold_mode=threshold_mode,
                   injection_enabled=bool(
                       inject.enabled if isinstance(inject, InjectionSpec)
                       else inject),
                   **variant_key_components(pin_variant, check_every, epi))
    report = {
        "problem": [m, n, k],
        "strategy": "plain" if strategy is None else strategy,
        "encode": "vpu" if strategy is None else encode,
        "in_dtype": str(in_dtype),
        "threshold_mode": "static" if strategy is None else threshold_mode,
        "epilogue": epi,
        "method": method,
        "key": key,
        "feasible": [{"block": list(c.shape.block),
                      "variant": variant_asdict(c.variant)}
                     for c in candidates],
        "pruned": [{"block": list(p.shape.block), "reason": p.reason,
                    **({"variant": p.variant} if p.variant else {})}
                   for p in pruned],
    }
    if dry_run:
        return report

    # The heuristic baseline is measured FIRST (and exempt from the
    # budget): every persisted winner is a measured comparison against
    # what dispatch would have done, and the report carries both numbers.
    heuristic = heuristic_shape(m, n, k, strategy=strategy,
                                in_dtype=in_dtype)
    heur_variant = (pin_variant if pin_variant is not None
                    else KernelVariant(epilogue=epi))
    if check_every is not None:
        import dataclasses as _dc

        heur_variant = _dc.replace(heur_variant, check_every=check_every)
    heur_cand = space.JointCandidate(heuristic, heur_variant)
    candidates = [heur_cand] + [
        c for c in candidates
        if not (c.shape.block == heuristic.block
                and c.variant == heur_variant)]
    budget_n = None if budget is None else budget + 1
    if isinstance(inject, InjectionSpec):
        spec = inject
    elif inject:
        # One representative reference-like schedule for the whole search
        # (per-candidate bk-matched schedules would change the injected
        # fault COUNT between rows and make times incomparable).
        spec = InjectionSpec.reference_like(k, 512)
    else:
        spec = InjectionSpec.none()

    with override_disabled():
        results = measure_space(
            candidates, m, n, k, strategy=strategy, encode=encode,
            in_dtype=in_dtype, threshold_mode=threshold_mode,
            inject=spec, method=method, budget=budget_n,
            alpha=alpha, beta=beta, reps=reps, samples=samples,
            progress=progress)
    best = best_result(results)
    report["results"] = [dataclasses_asdict(r) for r in results]
    report["heuristic"] = dataclasses_asdict(results[0]) if results else None
    report["best"] = dataclasses_asdict(best) if best else None
    if best is not None and write_cache:
        record = {
            "block": best.block,
            "gflops": best.gflops,
            "seconds_per_call": best.seconds,
            "method": best.method,
            "variant": variant_asdict(best.variant),
            "heuristic_block": list(heuristic.block),
            "heuristic_gflops": (results[0].gflops
                                 if results and results[0].ok else None),
            "problem": [m, n, k],
        }
        report["cache_path"] = cache.store(key, record)
    return report


def variant_asdict(v: Optional[KernelVariant]) -> Optional[dict]:
    """A JSON-friendly view of one kernel variant (None passes through)."""
    if v is None:
        return None
    import dataclasses as _dc

    return _dc.asdict(v)


def dataclasses_asdict(r: MeasureResult) -> dict:
    """A JSON-friendly view of one measurement (KernelShape flattened to
    its block)."""
    return {
        "block": r.block, "method": r.method, "ok": r.ok,
        "seconds_per_call": r.seconds, "gflops": r.gflops,
        "score": r.score, "error": r.error,
        "variant": variant_asdict(r.variant),
    }


__all__ = [
    "ENV_CACHE_PATH",
    "ENV_TUNING",
    "METHODS",
    "MeasureResult",
    "best_result",
    "cache",
    "cache_path",
    "default_method",
    "device_kind",
    "enabled",
    "enumerate_joint_space",
    "enumerate_space",
    "heuristic_shape",
    "lookup_ring_overlap",
    "lookup_stats",
    "lookup_tile",
    "lookup_winner",
    "make_key",
    "tune_ring",
    "reset_lookup_stats",
    "measure",
    "measure_space",
    "mnk_bucket",
    "override_disabled",
    "space",
    "tune",
    "variant_asdict",
    "variant_key_components",
]
