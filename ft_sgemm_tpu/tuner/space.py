"""Tile-config search-space enumeration and static pruning.

The kernel family is parameterized by one block tile ``(bm, bn, bk)``
(``configs.KernelShape``); the reference picks its per-size winners by a
hand-run sweep of the generated family (``code_gen/main.py``,
``scripts/tune_tiles.py`` here). This module makes that space a first-class
object the autotuner can search: it enumerates every legal MXU tile within
a curated dimension menu, drops tiles that are strictly wasteful for the
problem (a block dim larger than the 128-padded problem dim only buys
padding FLOPs), and rejects candidates the :mod:`ft_sgemm_tpu.ops.vmem`
footprint model predicts over the Mosaic scoped-VMEM budget — BEFORE
anything is compiled or timed, so a search never burns measurement budget
(or a scarce TPU tunnel window) dying inside the compiler.

Candidates are returned best-guess-first: descending block FLOPs-per-byte
(larger output tiles amortize the FT checksum VPU work — encode cost per
FLOP ~ 1/bm + 1/bn — and deeper K means fewer detect/correct epilogues), so
a budget-capped measurement pass spends its calls on the likely winners.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

from ft_sgemm_tpu.configs import (
    SHAPES,
    EpilogueSpec,
    KernelShape,
    KernelVariant,
    shape_for_dtype,
)
from ft_sgemm_tpu.ops.vmem import MIB, estimate_vmem_bytes

# Dimension menus: multiples of 128 spanning the shipped family and the
# live-sweep candidates of scripts/tune_tiles.py. Curated, not exhaustive —
# the sub-128 and non-multiple tiles are illegal on the MXU, and dims past
# 2048 exceed the 64 MiB budget for every variant at f32.
BM_MENU = (128, 256, 384, 512, 768, 1024, 1536, 2048)
BN_MENU = (128, 256, 384, 512, 768, 1024, 1536, 2048)
BK_MENU = (128, 256, 512, 1024, 2048)


def variant_for(strategy: Optional[str], *, single_check: bool = True,
                encode: str = "vpu",
                threshold_mode: str = "static") -> str:
    """The :data:`~ft_sgemm_tpu.ops.vmem.TEMP_TILE_FACTORS` key a strategy's
    dispatch will actually run at the tuner's measurement settings.

    Mirrors ``make_ft_sgemm``'s resolution: ``encode`` maps through
    ``resolve_kernel_strategy`` (the MXU-encode bodies have their own
    footprints — augmented tiles cost VMEM), and the weighted strategy at
    its default single-final-check VPU cadence runs the lighter
    precomputed-expectations body — EXCEPT under ``threshold_mode=
    "adaptive"``, whose moment statistics need the in-kernel encode.
    ``None`` is the plain (non-FT) kernel. This is also how the CADENCE
    axis is priced (ops/vmem docstring): an intermediate cadence on the
    weighted strategy is ``single_check=False`` — the running-partial-sum
    body, two VMEM units heavier.
    """
    from ft_sgemm_tpu.ops.ft_sgemm import resolve_kernel_strategy

    if strategy is None:
        return "plain"
    kernel_strategy = resolve_kernel_strategy(strategy, encode)
    if (kernel_strategy == "weighted" and single_check
            and threshold_mode != "adaptive"):
        return "weighted_precomp"
    return kernel_strategy


def _round_up(v: int, mult: int) -> int:
    return -(-v // mult) * mult


def candidate_name(bm: int, bn: int, bk: int) -> str:
    return f"tuned_{bm}x{bn}x{bk}"


@dataclasses.dataclass(frozen=True)
class PrunedCandidate:
    """A candidate rejected before measurement, with the reason.

    ``variant`` names the variant-axis spelling the prune applies to
    (None = the tile itself was pruned, every variant with it)."""

    shape: KernelShape
    reason: str
    est_bytes: Optional[int] = None
    variant: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class JointCandidate:
    """One point of the joint (tile x variant) search space."""

    shape: KernelShape
    variant: KernelVariant


def heuristic_shape(m: int, n: int, k: int, *, strategy: Optional[str],
                    in_dtype: str = "float32",
                    name: str = "huge") -> KernelShape:
    """The tile today's static dispatch would run for this problem — the
    baseline every search measures first, so a tuned winner is always a
    measured improvement over (or tie with) the shipped heuristic."""
    from ft_sgemm_tpu.ops.common import shrink_block

    shape = shape_for_dtype(SHAPES[name], strategy is not None, in_dtype)
    return shrink_block(shape, m, n, k)


def enumerate_space(
    m: int, n: int, k: int, *,
    strategy: Optional[str] = "weighted",
    encode: str = "vpu",
    in_dtype: str = "float32",
    threshold_mode: str = "static",
    limit: Optional[int] = None,
    bm_menu: Sequence[int] = BM_MENU,
    bn_menu: Sequence[int] = BN_MENU,
    bk_menu: Sequence[int] = BK_MENU,
) -> Tuple[list, list]:
    """Enumerate and statically prune the tile space for one problem.

    Returns ``(feasible, pruned)``: ``feasible`` is a best-guess-first list
    of :class:`~ft_sgemm_tpu.configs.KernelShape`; ``pruned`` a list of
    :class:`PrunedCandidate` explaining every rejection (a search report
    must say what it did NOT try — silent truncation reads as coverage).

    Pruning, in order:
      1. **Problem fit** — a block dim beyond the 128-padded problem dim
         pads pure waste (padded FLOPs are real FLOPs; ``shrink_block``
         exists to undo exactly this for the shipped tiles).
      2. **VMEM footprint** — the calibrated ``ops/vmem`` model at the
         variant the dispatch would run; over-``limit`` candidates are a
         compile-time Mosaic OOM on hardware and must never reach
         measurement.
    """
    from ft_sgemm_tpu.configs import canonical_in_dtype, vmem_limit_bytes

    if limit is None:
        limit = vmem_limit_bytes()
    import jax.numpy as jnp

    itemsize = jnp.dtype(canonical_in_dtype(in_dtype)).itemsize
    adaptive = threshold_mode == "adaptive"
    exact = canonical_in_dtype(in_dtype) == "int8" and strategy is not None
    variant = variant_for(strategy, encode=encode,
                          threshold_mode=threshold_mode)
    max_bm = _round_up(m, 128)
    max_bn = _round_up(n, 128)
    max_bk = _round_up(k, 128)

    feasible, pruned = [], []
    for bm in bm_menu:
        for bn in bn_menu:
            for bk in bk_menu:
                shape = KernelShape(candidate_name(bm, bn, bk),
                                    bm, bn, bk, (0,) * 7)
                if bm > max_bm or bn > max_bn or bk > max_bk:
                    pruned.append(PrunedCandidate(
                        shape, "exceeds 128-padded problem"
                        f" ({max_bm}x{max_bn}x{max_bk})"))
                    continue
                est = estimate_vmem_bytes(shape, variant,
                                          in_itemsize=itemsize,
                                          adaptive=adaptive, exact=exact)
                if est > limit:
                    pruned.append(PrunedCandidate(
                        shape,
                        f"predicted ~{est / MIB:.1f} MiB scoped VMEM >"
                        f" {limit / MIB:.0f} MiB limit ({variant})",
                        est_bytes=est))
                    continue
                feasible.append(shape)

    # Best-guess-first: big output tiles and deep K amortize per-check and
    # per-grid-step overheads; among equals prefer squarer aspect (the
    # sweep-measured winners are square-ish at every size, configs.SHAPES).
    def score(s: KernelShape):
        aspect = max(s.bm, s.bn) / min(s.bm, s.bn)
        return (-(s.bm * s.bn * min(s.bk, max_bk)), aspect)

    feasible.sort(key=score)
    return feasible, pruned


def default_cadence_menu(strategy: Optional[str]) -> Tuple[int, ...]:
    """The detect/correct cadences the joint search explores beyond the
    strategy's auto default (the reference's ~K/20 rule for rowcol/
    global, the single deferred final check for weighted/fused). Small
    explicit cadences are where the MTBF-vs-overhead tradeoff actually
    lives (arXiv 2305.01024 / 2305.02444): every-step and every-other-
    step checking bound the per-fault exposure window at measured cost.
    The plain kernel has no checks, hence no cadence axis."""
    return () if strategy is None else (1, 2)


def enumerate_joint_space(
    m: int, n: int, k: int, *,
    strategy: Optional[str] = "weighted",
    encode: str = "vpu",
    in_dtype: str = "float32",
    threshold_mode: str = "static",
    epilogue: str = "none",
    limit: Optional[int] = None,
    axis_tile_top: int = 2,
    pin_pipeline: Optional[int] = None,
    pin_grid_order: Optional[str] = None,
    pin_dim_semantics: Optional[str] = None,
    pin_check_every: Optional[int] = None,
    bm_menu: Sequence[int] = BM_MENU,
    bn_menu: Sequence[int] = BN_MENU,
    bk_menu: Sequence[int] = BK_MENU,
) -> Tuple[list, list]:
    """Enumerate and prune the JOINT (tile x variant) space.

    Returns ``(candidates, pruned)``: ``candidates`` a best-guess-first
    list of :class:`JointCandidate`; ``pruned`` the
    :class:`PrunedCandidate` list naming every rejection — tiles dropped
    by the base enumeration (problem fit / VMEM) and variant axes
    dropped per tile, each with its reason (a search report must say
    what it did NOT try; acceptance criterion of ISSUE 13).

    ``epilogue`` is the workload-owned epilogue spelling: it rides every
    candidate (and the cache key) but is never enumerated — a fused-
    epilogue deployment tunes for its own epilogue, not against others.
    ``pin_*`` arguments pin one axis to an explicit value (the
    corresponding key component then spells that value; the search
    explores only it). Per-axis pruning, in order:

      1. every axis value that is structurally degenerate for the
         problem (pipeline depth 3 on a single-panel K; grid order on a
         single-output-tile grid; cadences at or past the K-grid depth);
      2. VMEM: depth-3 windows and intermediate-cadence running-sum
         bodies re-priced through ``ops/vmem`` (the cadence pricing —
         weighted's in-kernel encode body — is ``variant_for``'s
         ``single_check=False`` resolution);
      3. search budget: non-default axis values are explored on the top
         ``axis_tile_top`` tiles only, one axis at a time (the named
         ``joint-axis exploration capped`` reason) — the axes are
         near-separable from the tile choice, and a full cross product
         would burn the measurement budget the tiles need.
    """
    from ft_sgemm_tpu.configs import (
        DIM_SEMANTICS,
        GRID_ORDERS,
        PIPELINE_DEPTHS,
        canonical_in_dtype,
        vmem_limit_bytes,
    )

    if limit is None:
        limit = vmem_limit_bytes()
    import jax.numpy as jnp

    epi = EpilogueSpec.parse(epilogue).spelling
    itemsize = jnp.dtype(canonical_in_dtype(in_dtype)).itemsize
    adaptive = threshold_mode == "adaptive"
    exact = canonical_in_dtype(in_dtype) == "int8" and strategy is not None
    base_variant = variant_for(strategy, encode=encode,
                               threshold_mode=threshold_mode)
    cadence_body = variant_for(strategy, single_check=False, encode=encode,
                               threshold_mode=threshold_mode)
    tiles, pruned = enumerate_space(
        m, n, k, strategy=strategy, encode=encode, in_dtype=in_dtype,
        threshold_mode=threshold_mode, limit=limit,
        bm_menu=bm_menu, bn_menu=bn_menu, bk_menu=bk_menu)
    kpad = _round_up(k, 128)
    mpad = _round_up(m, 128)
    npad = _round_up(n, 128)

    depth_menu = (PIPELINE_DEPTHS if pin_pipeline is None
                  else (pin_pipeline,))
    order_menu = (GRID_ORDERS if pin_grid_order is None
                  else (pin_grid_order,))
    sem_menu = (DIM_SEMANTICS if pin_dim_semantics is None
                else (pin_dim_semantics,))
    cad_menu = (default_cadence_menu(strategy) if pin_check_every is None
                else (pin_check_every,))

    def est(shape, body, depth):
        return estimate_vmem_bytes(shape, body, in_itemsize=itemsize,
                                   adaptive=adaptive, exact=exact,
                                   pipeline_depth=depth)

    candidates = []
    for t_idx, s in enumerate(tiles):
        default = KernelVariant(
            pipeline_depth=(pin_pipeline or 2),
            grid_order=(pin_grid_order or "mn"),
            dim_semantics=(pin_dim_semantics or "parallel"),
            check_every=pin_check_every, epilogue=epi)
        candidates.append(JointCandidate(s, default))
        axis_variants = []
        for depth in depth_menu:
            if depth == default.pipeline_depth:
                continue
            if kpad < (depth - 1) * s.bk:
                pruned.append(PrunedCandidate(
                    s, f"pipeline depth {depth} needs {depth - 1} K"
                    f" panels of bk={s.bk}; 128-padded K is {kpad}",
                    variant=f"pipe={depth}"))
                continue
            e = est(s, base_variant, depth)
            if e > limit:
                pruned.append(PrunedCandidate(
                    s, f"pipeline depth {depth} predicted"
                    f" ~{e / MIB:.1f} MiB scoped VMEM >"
                    f" {limit / MIB:.0f} MiB limit ({base_variant})",
                    est_bytes=e, variant=f"pipe={depth}"))
                continue
            axis_variants.append(dataclasses.replace(
                default, pipeline_depth=depth))
        gm_t = -(-mpad // s.bm)
        gn_t = -(-npad // s.bn)
        for order in order_menu:
            if order == default.grid_order:
                continue
            if gm_t == 1 or gn_t == 1:
                pruned.append(PrunedCandidate(
                    s, "grid traversal order is degenerate: one of the"
                    " output-tile dims has a single 128-granule tile",
                    variant=f"grid={order}"))
                continue
            axis_variants.append(dataclasses.replace(
                default, grid_order=order))
        for sem in sem_menu:
            if sem == default.dim_semantics:
                continue
            axis_variants.append(dataclasses.replace(
                default, dim_semantics=sem))
        nk_tile = -(-kpad // s.bk)
        for cad in cad_menu:
            if cad is None or cad == default.check_every:
                continue
            if cad >= nk_tile:
                pruned.append(PrunedCandidate(
                    s, f"cadence {cad} >= K-grid depth {nk_tile}:"
                    " identical to the auto final check",
                    variant=f"cad={cad}"))
                continue
            if cadence_body != base_variant:
                e = est(s, cadence_body, default.pipeline_depth)
                if e > limit:
                    pruned.append(PrunedCandidate(
                        s, f"cadence {cad} needs the running-partial-sum"
                        f" body ({cadence_body}): predicted"
                        f" ~{e / MIB:.1f} MiB scoped VMEM >"
                        f" {limit / MIB:.0f} MiB limit",
                        est_bytes=e, variant=f"cad={cad}"))
                    continue
            axis_variants.append(dataclasses.replace(
                default, check_every=cad))
        if t_idx < axis_tile_top:
            candidates.extend(JointCandidate(s, v) for v in axis_variants)
        else:
            for v in axis_variants:
                delta = [p for p in
                         (f"pipe={v.pipeline_depth}"
                          if v.pipeline_depth != default.pipeline_depth
                          else None,
                          f"grid={v.grid_order}"
                          if v.grid_order != default.grid_order else None,
                          f"sem={v.dim_semantics}"
                          if v.dim_semantics != default.dim_semantics
                          else None,
                          f"cad={v.check_every}"
                          if v.check_every != default.check_every
                          else None) if p]
                pruned.append(PrunedCandidate(
                    s, f"joint-axis exploration capped to top"
                    f" {axis_tile_top} tiles (search budget)",
                    variant="+".join(delta) or "variant"))
    return candidates, pruned
