"""Tile-config search-space enumeration and static pruning.

The kernel family is parameterized by one block tile ``(bm, bn, bk)``
(``configs.KernelShape``); the reference picks its per-size winners by a
hand-run sweep of the generated family (``code_gen/main.py``,
``scripts/tune_tiles.py`` here). This module makes that space a first-class
object the autotuner can search: it enumerates every legal MXU tile within
a curated dimension menu, drops tiles that are strictly wasteful for the
problem (a block dim larger than the 128-padded problem dim only buys
padding FLOPs), and rejects candidates the :mod:`ft_sgemm_tpu.ops.vmem`
footprint model predicts over the Mosaic scoped-VMEM budget — BEFORE
anything is compiled or timed, so a search never burns measurement budget
(or a scarce TPU tunnel window) dying inside the compiler.

Candidates are returned best-guess-first: descending block FLOPs-per-byte
(larger output tiles amortize the FT checksum VPU work — encode cost per
FLOP ~ 1/bm + 1/bn — and deeper K means fewer detect/correct epilogues), so
a budget-capped measurement pass spends its calls on the likely winners.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

from ft_sgemm_tpu.configs import SHAPES, KernelShape, shape_for_dtype
from ft_sgemm_tpu.ops.vmem import MIB, estimate_vmem_bytes

# Dimension menus: multiples of 128 spanning the shipped family and the
# live-sweep candidates of scripts/tune_tiles.py. Curated, not exhaustive —
# the sub-128 and non-multiple tiles are illegal on the MXU, and dims past
# 2048 exceed the 64 MiB budget for every variant at f32.
BM_MENU = (128, 256, 384, 512, 768, 1024, 1536, 2048)
BN_MENU = (128, 256, 384, 512, 768, 1024, 1536, 2048)
BK_MENU = (128, 256, 512, 1024, 2048)


def variant_for(strategy: Optional[str], *, single_check: bool = True,
                encode: str = "vpu", threshold_mode: str = "static") -> str:
    """The :data:`~ft_sgemm_tpu.ops.vmem.TEMP_TILE_FACTORS` key a strategy's
    dispatch will actually run at the tuner's measurement settings.

    Mirrors ``make_ft_sgemm``'s resolution: ``encode`` maps through
    ``resolve_kernel_strategy`` (the MXU-encode bodies have their own
    footprints — augmented tiles cost VMEM), and the weighted strategy at
    its default single-final-check VPU cadence runs the lighter
    precomputed-expectations body — EXCEPT under ``threshold_mode=
    "adaptive"``, whose moment statistics need the in-kernel encode.
    ``None`` is the plain (non-FT) kernel.
    """
    from ft_sgemm_tpu.ops.ft_sgemm import resolve_kernel_strategy

    if strategy is None:
        return "plain"
    kernel_strategy = resolve_kernel_strategy(strategy, encode)
    if (kernel_strategy == "weighted" and single_check
            and threshold_mode != "adaptive"):
        return "weighted_precomp"
    return kernel_strategy


def _round_up(v: int, mult: int) -> int:
    return -(-v // mult) * mult


def candidate_name(bm: int, bn: int, bk: int) -> str:
    return f"tuned_{bm}x{bn}x{bk}"


@dataclasses.dataclass(frozen=True)
class PrunedCandidate:
    """A candidate rejected before measurement, with the reason."""

    shape: KernelShape
    reason: str
    est_bytes: Optional[int] = None


def heuristic_shape(m: int, n: int, k: int, *, strategy: Optional[str],
                    in_dtype: str = "float32",
                    name: str = "huge") -> KernelShape:
    """The tile today's static dispatch would run for this problem — the
    baseline every search measures first, so a tuned winner is always a
    measured improvement over (or tie with) the shipped heuristic."""
    from ft_sgemm_tpu.ops.common import shrink_block

    shape = shape_for_dtype(SHAPES[name], strategy is not None, in_dtype)
    return shrink_block(shape, m, n, k)


def enumerate_space(
    m: int, n: int, k: int, *,
    strategy: Optional[str] = "weighted",
    encode: str = "vpu",
    in_dtype: str = "float32",
    threshold_mode: str = "static",
    limit: Optional[int] = None,
    bm_menu: Sequence[int] = BM_MENU,
    bn_menu: Sequence[int] = BN_MENU,
    bk_menu: Sequence[int] = BK_MENU,
) -> Tuple[list, list]:
    """Enumerate and statically prune the tile space for one problem.

    Returns ``(feasible, pruned)``: ``feasible`` is a best-guess-first list
    of :class:`~ft_sgemm_tpu.configs.KernelShape`; ``pruned`` a list of
    :class:`PrunedCandidate` explaining every rejection (a search report
    must say what it did NOT try — silent truncation reads as coverage).

    Pruning, in order:
      1. **Problem fit** — a block dim beyond the 128-padded problem dim
         pads pure waste (padded FLOPs are real FLOPs; ``shrink_block``
         exists to undo exactly this for the shipped tiles).
      2. **VMEM footprint** — the calibrated ``ops/vmem`` model at the
         variant the dispatch would run; over-``limit`` candidates are a
         compile-time Mosaic OOM on hardware and must never reach
         measurement.
    """
    from ft_sgemm_tpu.configs import canonical_in_dtype, vmem_limit_bytes

    if limit is None:
        limit = vmem_limit_bytes()
    import jax.numpy as jnp

    itemsize = jnp.dtype(canonical_in_dtype(in_dtype)).itemsize
    adaptive = threshold_mode == "adaptive"
    exact = canonical_in_dtype(in_dtype) == "int8" and strategy is not None
    variant = variant_for(strategy, encode=encode,
                          threshold_mode=threshold_mode)
    max_bm = _round_up(m, 128)
    max_bn = _round_up(n, 128)
    max_bk = _round_up(k, 128)

    feasible, pruned = [], []
    for bm in bm_menu:
        for bn in bn_menu:
            for bk in bk_menu:
                shape = KernelShape(candidate_name(bm, bn, bk),
                                    bm, bn, bk, (0,) * 7)
                if bm > max_bm or bn > max_bn or bk > max_bk:
                    pruned.append(PrunedCandidate(
                        shape, "exceeds 128-padded problem"
                        f" ({max_bm}x{max_bn}x{max_bk})"))
                    continue
                est = estimate_vmem_bytes(shape, variant,
                                          in_itemsize=itemsize,
                                          adaptive=adaptive, exact=exact)
                if est > limit:
                    pruned.append(PrunedCandidate(
                        shape,
                        f"predicted ~{est / MIB:.1f} MiB scoped VMEM >"
                        f" {limit / MIB:.0f} MiB limit ({variant})",
                        est_bytes=est))
                    continue
                feasible.append(shape)

    # Best-guess-first: big output tiles and deep K amortize per-check and
    # per-grid-step overheads; among equals prefer squarer aspect (the
    # sweep-measured winners are square-ish at every size, configs.SHAPES).
    def score(s: KernelShape):
        aspect = max(s.bm, s.bn) / min(s.bm, s.bn)
        return (-(s.bm * s.bn * min(s.bk, max_bk)), aspect)

    feasible.sort(key=score)
    return feasible, pruned
