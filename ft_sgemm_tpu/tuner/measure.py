"""Candidate measurement: time the survivors of the static prune.

Each candidate is built as an EXPLICIT :class:`~ft_sgemm_tpu.configs
.KernelShape` (explicit shapes bypass both the named-shape auto-shrink and
the tuner's own cache lookup, so a measurement can never recurse into the
cache it is trying to fill, and the row measured is exactly the tile its
label claims — the ``scripts/tune_tiles.py`` invariant) and timed with the
warmup/median-of-k discipline of
:func:`ft_sgemm_tpu.utils.timing.median_seconds_per_call`.

Three measurement methods, because the search must run everywhere:

- ``"wall"`` — real device timing (the TPU path; also honest on any
  backend that executes compiled kernels).
- ``"interpret"`` — forces Pallas interpret mode: the CPU fallback that
  exercises the identical dispatch/measure/persist machinery without a
  TPU. Interpret wall time is an emulation-cost ranking, not hardware
  truth — entries it produces are keyed under the CPU ``device_kind`` and
  can never serve a TPU dispatch.
- ``"compile"`` — AOT lower+compile only (no execution): proves each
  candidate clears Mosaic (the scoped-VMEM gate the static model can only
  predict) and ranks by a grid-step proxy. For chipless compile-service
  windows (``scripts/hw_watch.sh``'s probe stage).

Results are recorded through the PR-1 telemetry registry (when telemetry
is enabled): per-candidate ``tuner_candidate_gflops`` gauges plus
``tuner_measurements``/``tuner_failures`` counters, under a
``tuner_measure`` profiler span — a tuning run shows up in traces and
scrapes like any other fault-tolerance work.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

from ft_sgemm_tpu.configs import DEFAULT_VARIANT, KernelShape, KernelVariant

METHODS = ("wall", "interpret", "compile")


@dataclasses.dataclass
class MeasureResult:
    """One measured candidate (a tile, at one kernel variant)."""

    shape: KernelShape
    method: str
    ok: bool
    seconds: Optional[float] = None   # per call; None for compile-only
    gflops: Optional[float] = None
    score: float = float("inf")       # lower is better, any method
    error: Optional[str] = None
    variant: KernelVariant = DEFAULT_VARIANT

    @property
    def block(self):
        return list(self.shape.block)


def default_method() -> str:
    """``wall`` on a real TPU backend, ``interpret`` everywhere else."""
    import jax

    return "wall" if jax.default_backend() == "tpu" else "interpret"


def _build_fn(shape: KernelShape, *, strategy: Optional[str], in_dtype: str,
              inject, alpha: float, beta: float, interpret: Optional[bool],
              encode: str = "vpu", threshold_mode: str = "static",
              variant: Optional[KernelVariant] = None):
    """fn(a, b, c) -> array for one candidate, clean or injected.

    ``variant`` pins the full kernel-variant descriptor on the factory
    (explicit variants bypass winner application, exactly as explicit
    shapes bypass the tile cache — a measurement must run the variant
    its row label claims). A bias-fusing epilogue gets a deterministic
    all-ones bias so the measured program is the program dispatch will
    run."""
    import numpy as np

    from ft_sgemm_tpu.ops.ft_sgemm import make_ft_sgemm
    from ft_sgemm_tpu.ops.sgemm import make_sgemm

    variant = DEFAULT_VARIANT if variant is None else variant
    if strategy is None:
        fn = make_sgemm(shape, alpha=alpha, beta=beta, in_dtype=in_dtype,
                        interpret=interpret, variant=variant)
        if variant.epilogue_spec.bias:
            return lambda a, b, c: fn(
                a, b, c, bias=np.ones((c.shape[1],), np.float32))
        return fn
    threshold = ("adaptive" if threshold_mode == "adaptive"
                 else "auto" if threshold_mode == "auto" else "static")
    ft = make_ft_sgemm(shape, alpha=alpha, beta=beta, strategy=strategy,
                       encode=encode, threshold=threshold,
                       in_dtype=in_dtype, interpret=interpret,
                       variant=variant)
    if variant.epilogue_spec.bias:
        return lambda a, b, c: ft(
            a, b, c, inject, bias=np.ones((c.shape[1],), np.float32)).c
    return lambda a, b, c: ft(a, b, c, inject).c


def make_inputs(m: int, n: int, k: int, in_dtype: str = "float32"):
    """Device-resident (a, b, c) operands for measurement (one set for the
    whole search; the reference driver's quantized distribution — scaled
    to integer values for int8, whose cast truncates fractions)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ft_sgemm_tpu.configs import canonical_in_dtype
    from ft_sgemm_tpu.utils.matrices import generate_random_matrix

    in_dtype = canonical_in_dtype(in_dtype)
    rng = np.random.default_rng(10)
    a = generate_random_matrix(m, k, rng=rng)
    b = generate_random_matrix(n, k, rng=rng)
    c = generate_random_matrix(m, n, rng=rng)
    if in_dtype == "int8":
        # The quantized ±{0,.1,...,.9} distribution at integer scale:
        # ±{0..9} — the int8 kernels' natural operand class.
        a = np.round(a * 10.0).astype(np.float32)
        b = np.round(b * 10.0).astype(np.float32)
    if jnp.dtype(in_dtype) != jnp.float32:
        # Pre-cast so the wrappers' casts trace to no-ops (timing.py).
        a = jnp.asarray(a, in_dtype)
        b = jnp.asarray(b, in_dtype)
    return tuple(map(jax.device_put, (a, b, c)))


def measure_candidate(
    shape: KernelShape, a, b, c, *,
    strategy: Optional[str] = "weighted",
    encode: str = "vpu",
    in_dtype: str = "float32",
    threshold_mode: str = "static",
    inject=None,
    method: Optional[str] = None,
    alpha: float = 1.0, beta: float = -1.5,
    reps: int = 3, samples: int = 3,
    variant: Optional[KernelVariant] = None,
) -> MeasureResult:
    """Measure ONE candidate (tile x variant); failures are recorded,
    never raised (a search must survive a candidate the static model
    wrongly admitted).
    """
    import jax
    import jax.numpy as jnp

    from ft_sgemm_tpu.injection import InjectionSpec
    from ft_sgemm_tpu.utils.timing import median_seconds_per_call

    method = default_method() if method is None else method
    if method not in METHODS:
        raise ValueError(f"unknown method {method!r}; pick from {METHODS}")
    inject = inject or InjectionSpec.none()
    variant = DEFAULT_VARIANT if variant is None else variant
    m, n = c.shape
    k = a.shape[1]
    interpret = True if method == "interpret" else None
    try:
        fn = _build_fn(shape, strategy=strategy, encode=encode,
                       threshold_mode=threshold_mode,
                       in_dtype=in_dtype, inject=inject, alpha=alpha,
                       beta=beta, interpret=interpret, variant=variant)
        if method == "compile":
            args = (jax.ShapeDtypeStruct(a.shape, jnp.dtype(in_dtype)),
                    jax.ShapeDtypeStruct(b.shape, jnp.dtype(in_dtype)),
                    jax.ShapeDtypeStruct(c.shape, jnp.float32))
            jax.jit(fn).lower(*args).compile()
            # Rank compiled-only candidates by grid-step count: fewer,
            # bigger steps is the measured direction at every swept size
            # (configs.SHAPES provenance) — and a deep pipeline's wider
            # K window means fewer steps, mirroring its intent. A proxy,
            # not a measurement — the record says so via
            # method="compile".
            kwin = shape.bk * (variant.pipeline_depth - 1)
            steps = (-(-m // shape.bm)) * (-(-n // shape.bn)) * (
                -(-k // kwin))
            return MeasureResult(shape, method, ok=True,
                                 score=float(steps), variant=variant)
        sec = median_seconds_per_call(fn, a, b, c, reps=reps,
                                      samples=samples)
        gf = 2.0 * m * n * k / 1e9 / sec
        return MeasureResult(shape, method, ok=True, seconds=sec,
                             gflops=gf, score=sec, variant=variant)
    except Exception as e:  # noqa: BLE001 — sweep must survive bad tiles
        return MeasureResult(shape, method, ok=False, variant=variant,
                             error=f"{type(e).__name__}: {str(e)[:200]}")


def measure_space(
    candidates: Sequence[KernelShape], m: int, n: int, k: int, *,
    strategy: Optional[str] = "weighted",
    encode: str = "vpu",
    in_dtype: str = "float32",
    threshold_mode: str = "static",
    inject=None,
    method: Optional[str] = None,
    budget: Optional[int] = None,
    alpha: float = 1.0, beta: float = -1.5,
    reps: int = 3, samples: int = 3,
    progress=None,
) -> list:
    """Measure up to ``budget`` candidates (order preserved — callers pass
    the best-guess-first list from :func:`..space.enumerate_space` or
    the joint :func:`..space.enumerate_joint_space`; bare
    ``KernelShape`` entries measure at the default variant).
    Returns the list of :class:`MeasureResult`. ``progress`` is an optional
    ``fn(result)`` callback (the CLI streams rows as they land, so a
    killed search still printed everything it measured).
    """
    from ft_sgemm_tpu import telemetry

    method = default_method() if method is None else method
    picked = list(candidates if budget is None else candidates[:budget])
    results = []
    strat_label = "plain" if strategy is None else strategy
    with telemetry.trace_span("tuner_measure"):
        for cand in picked:
            shape = getattr(cand, "shape", cand)
            cand_variant = getattr(cand, "variant", None)
            a, b, c = _inputs_memo(m, n, k, in_dtype)
            res = measure_candidate(
                shape, a, b, c, strategy=strategy, encode=encode,
                threshold_mode=threshold_mode,
                in_dtype=in_dtype, inject=inject, method=method,
                alpha=alpha, beta=beta, reps=reps, samples=samples,
                variant=cand_variant)
            results.append(res)
            if telemetry.enabled():
                reg = telemetry.get_registry()
                labels = dict(op="tuner", strategy=strat_label,
                              encode=encode, method=method)
                reg.counter("tuner_measurements", **labels).inc()
                if not res.ok:
                    reg.counter("tuner_failures", **labels).inc()
                elif res.gflops is not None:
                    reg.gauge("tuner_candidate_gflops",
                              tile=shape.name, **labels).set(res.gflops)
            if progress is not None:
                progress(res)
    return results


# One operand set per (problem, dtype) per process: measurement loops call
# measure_space repeatedly from the CLI and tests.
_INPUT_MEMO: dict = {}


def _inputs_memo(m, n, k, in_dtype):
    key = (m, n, k, str(in_dtype))
    if key not in _INPUT_MEMO:
        _INPUT_MEMO.clear()  # hold at most one problem's operands resident
        _INPUT_MEMO[key] = make_inputs(m, n, k, in_dtype)
    return _INPUT_MEMO[key]


def best_result(results: Sequence[MeasureResult]) -> Optional[MeasureResult]:
    """The winning measurement (lowest score among ok results), or None."""
    ok = [r for r in results if r.ok]
    return min(ok, key=lambda r: r.score) if ok else None


# ---------------------------------------------------------------------------
# Ring hop-schedule axis (configs.RING_OVERLAP_MODES)
# ---------------------------------------------------------------------------

# One ICI link direction's sustained bandwidth, bytes/second. Provenance:
# ~100 GB/s per link per direction on v4/v5p (Google's published 4800
# Gbps aggregate over 6 links, two directions), derated ~10% for
# protocol/framing — the same spirit as the roofline table's documented
# estimates (perf/roofline.py). The COST METHOD only ranks the two hop
# schedules; absolute accuracy matters far less than the compute/ICI
# ratio's sign, and the wall method exists for hardware truth.
ICI_BYTES_PER_SECOND = 9.0e10

RING_METHODS = ("wall", "cost")


def default_ring_method() -> str:
    """``wall`` on a real TPU (ICI is real there); ``cost`` everywhere
    else — CPU virtual devices have no interconnect, so wall-timing the
    two schedules there measures host-threading noise, not the ring."""
    import jax

    return "wall" if jax.default_backend() == "tpu" else "cost"


def ring_schedule_cost(m: int, n: int, k: int, d: int, *, overlap: bool,
                       peak_flops: Optional[float] = None,
                       itemsize: int = 4,
                       ici_bytes_per_second: float = ICI_BYTES_PER_SECOND,
                       device_kind: Optional[str] = None,
                       in_dtype: str = "float32") -> float:
    """Modeled seconds for one full ring sweep under one hop schedule —
    the ``ring_overlap`` axis priced in the cost model.

    Per hop a device computes a 2*(m/d)*(n/d)*k-flop local FT-GEMM and
    moves one (n/d, k) B shard over ICI. The serial schedule pays the
    two in sequence every hop; rotate-ahead pays the slower of the two
    (plus one exposed transfer and compute at the pipeline's ends,
    and the prologue's extra rotation documented in
    ``parallel/ring.py``). ``peak_flops`` defaults to the roofline
    table's dtype-correct peak for ``device_kind`` (the live device when
    None), falling back to 1 TFLOP/s when no spec is known — rankings,
    not absolute truth.
    """
    if peak_flops is None:
        peak_flops = _peak_flops_for(device_kind, in_dtype)
    t_c = 2.0 * (m / d) * (n / d) * k / peak_flops
    t_i = (n / d) * k * itemsize / ici_bytes_per_second
    if overlap:
        return t_c + t_i + (d - 1) * max(t_c, t_i) + t_i
    return d * (t_c + t_i)


def _peak_flops_for(device_kind: Optional[str], in_dtype: str) -> float:
    try:
        from ft_sgemm_tpu.perf.roofline import find_spec

        if device_kind is None:
            import jax

            device_kind = str(jax.local_devices()[0].device_kind)
        spec = find_spec(device_kind)
        peak = spec.peak_for(in_dtype) if spec is not None else None
        if peak:
            return float(peak)
    except Exception:  # noqa: BLE001 — ranking fallback, never a gate
        pass
    return 1.0e12


def measure_ring_schedules(
    m: int, n: int, k: int, mesh=None, *,
    strategy: Optional[str] = "weighted",
    in_dtype: str = "float32",
    method: Optional[str] = None,
    alpha: float = 1.0, beta: float = -1.5,
    reps: int = 2, samples: int = 2,
) -> dict:
    """Measure (or cost-model) BOTH ring hop schedules for one problem.

    Returns ``{"serial": {...}, "overlap": {...}, "winner": mode,
    "method": method, "d": ring_size}`` where each mode row carries
    ``score`` (lower is better: wall seconds or modeled seconds) and,
    for the wall method, ``seconds``/``gflops``. The wall method builds
    each schedule's executor ONCE (``parallel.ring.make_ring_ft_sgemm_fn``)
    and times it with the usual warmup/median discipline; the cost
    method never touches a device.
    """
    method = default_ring_method() if method is None else method
    if method not in RING_METHODS:
        raise ValueError(
            f"unknown ring method {method!r}; pick from {RING_METHODS}")
    import jax

    if mesh is None:
        from ft_sgemm_tpu.parallel.ring import make_ring_mesh

        mesh = make_ring_mesh()
    d = mesh.shape["x"]
    out = {"method": method, "d": d, "problem": [m, n, k]}
    if method == "cost":
        kind = str(jax.local_devices()[0].device_kind)
        for mode in ("serial", "overlap"):
            out[mode] = {"score": ring_schedule_cost(
                m, n, k, d, overlap=mode == "overlap", device_kind=kind,
                in_dtype=in_dtype)}
    else:
        import jax.numpy as jnp

        from ft_sgemm_tpu.injection import InjectionSpec
        from ft_sgemm_tpu.parallel.ring import make_ring_ft_sgemm_fn
        from ft_sgemm_tpu.tuner.space import heuristic_shape
        from ft_sgemm_tpu.utils.timing import median_seconds_per_call

        a, b, c = _inputs_memo(m, n, k, in_dtype)
        shape = heuristic_shape(m // d, n // d, k, strategy=strategy,
                                in_dtype=in_dtype)
        for mode in ("serial", "overlap"):
            fn = make_ring_ft_sgemm_fn(
                mesh, d, n // d, n, shape, alpha=alpha, beta=beta,
                inject=InjectionSpec.none(),
                strategy=strategy or "weighted", threshold="static",
                precision="highest", in_dtype=in_dtype, interpret=None,
                inject_coords=None, overlap=mode == "overlap")
            jfn = jax.jit(lambda x, y, z, _f=fn: _f(x, y, z)[0])
            a32 = jnp.asarray(a, jnp.float32)
            b32 = jnp.asarray(b, jnp.float32)
            sec = median_seconds_per_call(jfn, a32, b32, c, reps=reps,
                                          samples=samples)
            out[mode] = {"score": sec, "seconds": sec,
                         "gflops": 2.0 * m * n * k / 1e9 / sec}
    out["winner"] = min(("serial", "overlap"),
                        key=lambda mode: out[mode]["score"])
    return out
