"""Persistent, versioned tile-config cache.

Winners found by the search (:mod:`ft_sgemm_tpu.tuner.measure`) are
persisted as one JSON document keyed by
``(device_kind, M/N/K bucket, dtype, strategy, injection-enabled)`` so a
tuning run's result survives the process and serves every later dispatch
on the same device class. Design points:

- **Bucketed problem sizes.** Exact (M, N, K) keys would make every new
  shape a cache miss; each dim is bucketed to the next power of two
  (floored at the 128 MXU granule), which is also how tile efficiency
  actually generalizes — a 4096-tuned tile serves 3500 well, a 256-tuned
  one does not.
- **Versioned, schema-checked load.** The file carries a schema version;
  a corrupt file, a foreign JSON document, or an entry whose block fails
  the MXU legality rules is ignored WITH A WARNING and treated as a miss
  — a bad cache must never take down (or silently mis-tile) dispatch.
- **Env-overridable path.** ``FT_SGEMM_TUNER_CACHE`` points dispatch and
  the CLI at a specific cache file; the default lives under
  ``~/.cache/ft_sgemm_tpu/``.
- **Cheap hot-path reads.** Dispatch consults the cache on every call; the
  parsed document is memoized per ``(mtime, size)`` stat signature, so the
  steady-state cost is one ``os.stat``.
- **Atomic writes.** Store is read-merge-replace via a temp file +
  ``os.replace`` so a crashed writer can never leave a torn document.
"""

from __future__ import annotations

import functools
import json
import os
import tempfile
import threading
import warnings
from typing import Optional

# Schema 2: the checksum-encode mode joined the cache key (``enc=vpu`` /
# ``enc=mxu``) when ``encode`` became a searched dimension — schema-1
# files carry keys that would silently collide the two encode modes'
# winners, so they are ignored (with the standard warning) rather than
# migrated.
# Schema 3: the threshold mode joined the key (``thr=static`` /
# ``thr=adaptive``) when ``threshold="adaptive"`` became a searched
# dimension — adaptive kernels carry the in-kernel moment/derivation work,
# so their winning tiles genuinely differ; a schema-2 file would collide
# the two modes' winners under one key. Like the 1->2 bump, old files are
# ignored-with-warning (a clean MISS -> re-tune), never migrated and never
# an exception: the dtype axis widened at the same time (int8 / fp8 keys)
# and stale entries must not mis-serve the new spellings.
# Schema 4: the kernel VARIANT axes joined the key when the tuner took
# over the whole kernel (ROADMAP item 4): ``pipe=`` (pipeline depth
# constraint), ``grid=`` (traversal order + dimension semantics),
# ``cad=`` (detect/correct cadence), ``epi=`` (fused-epilogue spelling).
# Unconstrained dispatch keys as ``pipe=auto|grid=auto|cad=auto`` and the
# RECORD's ``variant`` field carries the winning searched values; a
# pinned axis keys with its explicit spelling. Epilogues are always
# concrete (``epi=none`` by default) — an epilogue-fused call must never
# be served a tile tuned for the bare kernel's register/VPU mix. Like
# every prior bump, schema-3 files are ignored-with-warning (a clean
# MISS -> re-tune, pinned in tests/test_variants.py), never migrated:
# their keys would silently collide every variant's winner onto one
# entry.
# Schema 5: the ring hop schedule joined the key (``ring=serial`` /
# ``ring=overlap`` / ``ring=auto``) when the ring collective paths'
# rotate-ahead pipeline became a searched axis (``tuner.tune_ring``
# banks winners keyed on the PER-DEVICE local shard problem). The
# single-device key family carries ``ring=serial`` — there is no ring —
# so schema-4 files would not collide, but every key string changed
# shape; the standard ignored-with-warning miss (pinned in
# tests/test_overlap_pool.py) keeps the contract uniform.
SCHEMA_VERSION = 5
ENV_CACHE_PATH = "FT_SGEMM_TUNER_CACHE"
_DEFAULT_PATH = os.path.join(
    os.path.expanduser("~"), ".cache", "ft_sgemm_tpu", "tuner_cache.json")

_LOCK = threading.Lock()
# path -> ((mtime_ns, size), entries dict). Entries are the validated
# key -> record mapping; an unreadable/invalid file memoizes as {} so the
# load warning fires once per file state, not once per dispatch.
_MEMO: dict = {}
# path -> Lock serializing parse/store IO per cache file (single-flight:
# under the serving layer's concurrent dispatch, N threads missing the
# memo at once must produce ONE parse and ONE warning, not N — and a
# store's read-merge-replace must never interleave with a concurrent
# parse of the half-written state). _LOCK guards only the dicts, so a
# slow parse on one path never blocks lookups on another.
_PATH_LOCKS: dict = {}


def _path_lock(path: str) -> threading.Lock:
    with _LOCK:
        lk = _PATH_LOCKS.get(path)
        if lk is None:
            lk = _PATH_LOCKS[path] = threading.Lock()
        return lk


def cache_path() -> str:
    """The active cache file path (``FT_SGEMM_TUNER_CACHE`` or default)."""
    return os.environ.get(ENV_CACHE_PATH) or _DEFAULT_PATH


@functools.lru_cache(maxsize=1)
def device_kind() -> str:
    """The local accelerator's device kind (cache-key component).

    ``cpu`` on the CPU backend — CPU-tuned entries are real entries (the
    interpret-mode fallback measures something), they just never collide
    with any TPU generation's key.
    """
    try:
        import jax

        return str(jax.local_devices()[0].device_kind)
    except Exception:  # noqa: BLE001 — no backend yet: still a valid key
        return "unknown"


def mnk_bucket(m: int, n: int, k: int) -> tuple:
    """Bucket each problem dim to the next power of two, floored at 128."""

    def bucket(v: int) -> int:
        b = 128
        while b < v:
            b *= 2
        return b

    return (bucket(max(1, m)), bucket(max(1, n)), bucket(max(1, k)))


def make_key(m: int, n: int, k: int, *, strategy: Optional[str],
             in_dtype, injection_enabled: bool, encode: str = "vpu",
             threshold_mode: str = "static",
             pipe: str = "auto", grid: str = "auto", cad: str = "auto",
             epi: str = "none", ring: str = "serial",
             device: Optional[str] = None) -> str:
    """The canonical cache key for one dispatch site.

    ``encode`` is the checksum-encode mode (``configs.ENCODE_MODES``) —
    a first-class key component since the winning tile genuinely differs
    between encodes (MXU encode trades VPU reductions for augmented tile
    rows, shifting the VMEM/efficiency balance). The plain (non-FT)
    kernel has no encode axis and always keys as ``vpu``.

    ``threshold_mode`` keys the detection-threshold axis (schema 3):
    ``adaptive`` kernels run the in-kernel moment accumulation +
    per-check derivation (and, for weighted, the in-kernel encode body
    instead of the lighter precomp one), so their winners differ;
    ``auto`` shares the ``static`` key — same program, the threshold is
    a runtime scalar. The dtype axis needs no spelling change here:
    ``jnp.dtype(...).name`` already keys int8 / float8_e4m3fn distinctly
    (``configs.canonical_in_dtype`` normalizes aliases upstream).

    The variant axes (schema 4) key the dispatch CONSTRAINT, not the
    winner: ``pipe``/``grid``/``cad`` are ``"auto"`` when the caller
    left the axis to the search (the record's ``variant`` field then
    carries the winning value) and the explicit spelling
    (``pipe="3"``, ``grid="nm.arbitrary"``, ``cad="8"``) when the
    caller pinned it — a pinned call's tile is tuned for exactly that
    variant. ``epi`` is the fused-epilogue SPELLING
    (``configs.EpilogueSpec``, default ``"none"``): always concrete,
    since the epilogue is workload-owned and changes the winning tile's
    register/VPU balance. ``ring`` is the ring hop schedule axis
    (schema 5, ``configs.RING_OVERLAP_MODES``): the single-device key
    family spells it ``serial`` (there is no ring), ring dispatch keys
    ``auto`` with the winning mode in the record's ``variant``, and the
    problem dims of a ring key are the PER-DEVICE local shard — the
    ring size therefore rides the key through the bucketed shard dims.
    """
    from ft_sgemm_tpu.configs import canonical_in_dtype

    bm, bn, bk = mnk_bucket(m, n, k)
    dev = device_kind() if device is None else device
    strat = "plain" if strategy is None else strategy
    enc = "vpu" if strategy is None else encode
    thr = "static" if strategy is None or threshold_mode != "adaptive" \
        else "adaptive"
    return (f"{dev}|{bm}x{bn}x{bk}|{canonical_in_dtype(in_dtype)}"
            f"|{strat}|enc={enc}|thr={thr}"
            f"|inj={int(bool(injection_enabled))}"
            f"|pipe={pipe}|grid={grid}|cad={cad}|epi={epi}"
            f"|ring={ring}")


def _valid_block(block) -> bool:
    return (isinstance(block, (list, tuple)) and len(block) == 3
            and all(isinstance(v, int) and v > 0 and v % 128 == 0
                    for v in block))


def _load_validated(path: str) -> dict:
    """Parse + schema-check one cache file; {} (with a warning) on any
    structural problem. Per-entry validation: a bad entry is dropped, the
    good ones survive."""
    try:
        with open(path) as fh:
            doc = json.load(fh)
    except OSError:
        return {}  # absent file: the ordinary empty-cache case, no warning
    except ValueError as e:
        warnings.warn(
            f"ft_sgemm_tpu tuner: ignoring corrupt tile cache {path!r}"
            f" ({e}); dispatch falls back to heuristics", stacklevel=3)
        return {}
    if not isinstance(doc, dict) or doc.get("schema") != SCHEMA_VERSION:
        warnings.warn(
            f"ft_sgemm_tpu tuner: ignoring tile cache {path!r} with"
            f" schema {doc.get('schema') if isinstance(doc, dict) else '?'}"
            f" (this build reads schema {SCHEMA_VERSION}); dispatch falls"
            " back to heuristics", stacklevel=3)
        return {}
    entries = doc.get("entries")
    if not isinstance(entries, dict):
        warnings.warn(
            f"ft_sgemm_tpu tuner: tile cache {path!r} has no 'entries'"
            " mapping; ignoring it", stacklevel=3)
        return {}
    valid = {}
    for key, rec in entries.items():
        if isinstance(rec, dict) and _valid_block(rec.get("block")):
            valid[key] = rec
        else:
            warnings.warn(
                f"ft_sgemm_tpu tuner: dropping invalid cache entry"
                f" {key!r} in {path!r} (block must be three positive"
                " multiples of 128)", stacklevel=3)
    return valid


def load_entries(path: Optional[str] = None) -> dict:
    """The validated entries of the cache file, memoized by stat signature.

    Thread-safe AND single-flight per path: the steady-state hit path is
    one ``os.stat`` plus a memo probe under the cheap dict lock (no file
    lock — serving-layer dispatch threads must never convoy on it), while
    memo MISSES serialize on the per-path lock so N threads arriving at a
    changed file produce ONE parse and ONE warning, not N racing parses.
    """
    path = cache_path() if path is None else path

    def _sig():
        try:
            st = os.stat(path)
            return (st.st_mtime_ns, st.st_size)
        except OSError:
            return None  # absent: memoize the miss (stat already said so)

    sig = _sig()
    with _LOCK:
        hit = _MEMO.get(path)
        if hit is not None and hit[0] == sig:
            return hit[1]
    with _path_lock(path):
        # Re-stat and re-probe under the parse lock: the thread that won
        # the race already memoized the state this thread was about to
        # parse.
        sig = _sig()
        with _LOCK:
            hit = _MEMO.get(path)
            if hit is not None and hit[0] == sig:
                return hit[1]
        entries = _load_validated(path) if sig is not None else {}
        with _LOCK:
            _MEMO[path] = (sig, entries)
        return entries


def lookup(key: str, path: Optional[str] = None) -> Optional[dict]:
    """The cache record for ``key``, or None (a miss)."""
    return load_entries(path).get(key)


def store(key: str, record: dict, path: Optional[str] = None) -> str:
    """Insert/overwrite one entry (read-merge-atomic-replace). Returns the
    path written."""
    if not _valid_block(record.get("block")):
        raise ValueError(
            f"tuner cache record needs a legal 'block' (three positive"
            f" multiples of 128), got {record.get('block')!r}")
    path = cache_path() if path is None else path
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    # The per-path lock (shared with load_entries) makes read-merge-
    # replace atomic against concurrent lookups AND concurrent stores;
    # the global _LOCK only ever guards the memo dict now, so one path's
    # file IO cannot stall every other path's dispatch lookups.
    with _path_lock(path):
        entries = dict(_load_validated(path))
        entries[key] = record
        doc = {"schema": SCHEMA_VERSION, "entries": entries}
        fd, tmp = tempfile.mkstemp(
            dir=os.path.dirname(os.path.abspath(path)), suffix=".tmp")
        try:
            with os.fdopen(fd, "w") as fh:
                json.dump(doc, fh, indent=1, sort_keys=True)
                fh.write("\n")
            os.replace(tmp, path)
        finally:
            try:
                os.unlink(tmp)
            except OSError:
                pass
        with _LOCK:
            _MEMO.pop(path, None)
    return path


def clear_memo() -> None:
    """Drop the in-process memo (tests; after external cache edits the
    stat signature normally handles invalidation by itself)."""
    with _LOCK:
        _MEMO.clear()
