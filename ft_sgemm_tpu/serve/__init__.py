"""Fault-tolerant serving layer: shape-bucketed continuous batching with
SLO-aware ABFT retry (ROADMAP item 3).

Everything below this package turns ONE GEMM call fault-tolerant; this
package turns a STREAM of ragged requests into sustained, high-goodput
traffic that exploits the online-ABFT economics (arXiv 2305.01024 — the
overhead is low enough to leave on in production, which only pays off if
a detected-and-corrected SDC costs the serving path nothing):

- :mod:`.buckets` — shape bucketing: ragged (M, N, K, dtype) requests
  fold onto a small padded bucket set aligned with the autotuner's cache
  buckets, so every bucket hits a tuner-cached tile and one prewarmed
  executable. Oversized requests get the named
  :class:`~ft_sgemm_tpu.serve.buckets.BucketOverflowError`. Ragged
  SEQUENCES bucket the same way (:class:`~ft_sgemm_tpu.serve.buckets.
  BlockBucket` — padded (L_q, L_k) under the identical power-of-two
  rule).
- :mod:`.engine` — the async continuous-batching dispatch queue: per-
  bucket accumulation, flush on batch-full or max-wait, AOT-prewarmed
  executables (zero compile spans in steady state — timeline-pinned),
  per-request fault attribution from each request's own counter grids,
  and the SLO-aware retry ladder: corrected SDCs are FREE, an
  uncorrectable one retries only the affected bucket's batch — never the
  whole queue — bounded, backed off, and recorded as telemetry ladder
  events.
- :mod:`.blocks` — transformer-block serving (the paper's real
  customer): ragged prefill/decode attention requests through the FT
  attention executors, per-request fault attribution through
  QK/softmax/PV, and the decode path's ABFT-checked paged KV cache.
- :mod:`.kv_cache` — the checked store itself: every page carries two
  appended checksum rows (plain + weighted column sums), verified on
  read, single-element corruption corrected IN PLACE, wider corruption
  recovered by the engine's bounded page-scoped restore ladder.
- :mod:`.pool` — the multi-device dispatcher: each bucket's executable
  replicated (AOT) across mesh devices, placement steered by
  ``DeviceHealthTracker`` scores (sick devices drain, not schedule),
  and a bounded async in-flight window per device worker — the mesh,
  not one chip, is the unit of serving throughput
  (``bench.py --serve --pool`` reports goodput scaling vs the
  single-device engine).
- :mod:`.loadgen` — the load-generator bench (``bench.py --serve``,
  ``cli serve-bench``): configurable arrival process with SDC injection,
  reporting p50/p99 latency (from the telemetry histogram machinery),
  throughput, and goodput-under-injection — requests-correct/sec for
  the GEMM workload, tokens-correct/sec for the block workload
  (``--workload=gemm|block``).
- :mod:`.tracing` — request-scoped trace IDs, minted per request and
  propagated through enqueue -> flush -> execute -> detection (in
  flight AND stored-state ``kv_page`` findings) -> retry, so one grep
  joins a user request to the tile/device/page that corrupted it. The
  live plane (``--monitor-port=``, ``cli top``) is
  :mod:`ft_sgemm_tpu.telemetry.monitor`.

CLI: ``python -m ft_sgemm_tpu.cli serve [--dry-run] [--monitor-port=N]``
and ``python -m ft_sgemm_tpu.cli serve-bench [--smoke]
[--workload=gemm|block]``.
"""

from __future__ import annotations

from ft_sgemm_tpu.serve.blocks import (
    BlockEngine,
    BlockRequest,
    BlockResult,
)
from ft_sgemm_tpu.serve.buckets import (
    BlockBucket,
    Bucket,
    BucketOverflowError,
    default_block_bucket_set,
    default_bucket_set,
    select_block_bucket,
    select_bucket,
)
from ft_sgemm_tpu.serve.engine import (
    VARIANTS,
    ServeEngine,
    ServeRequest,
    ServeResult,
)
from ft_sgemm_tpu.serve.kv_cache import KVPageFault, PagedKVCache
from ft_sgemm_tpu.serve.loadgen import (
    BlockLoadSpec,
    LoadSpec,
    block_smoke_spec,
    pool_smoke_spec,
    run_block_load,
    run_block_serve_bench,
    run_load,
    run_pool_serve_bench,
    run_serve_bench,
    smoke_spec,
)
from ft_sgemm_tpu.serve.pool import PLACEMENTS, DevicePool
from ft_sgemm_tpu.serve.tracing import (
    current_trace_id,
    new_trace_id,
    trace_scope,
)

__all__ = [
    "BlockBucket",
    "BlockEngine",
    "BlockLoadSpec",
    "BlockRequest",
    "BlockResult",
    "Bucket",
    "BucketOverflowError",
    "DevicePool",
    "KVPageFault",
    "LoadSpec",
    "PLACEMENTS",
    "PagedKVCache",
    "ServeEngine",
    "ServeRequest",
    "ServeResult",
    "VARIANTS",
    "block_smoke_spec",
    "current_trace_id",
    "default_block_bucket_set",
    "default_bucket_set",
    "new_trace_id",
    "pool_smoke_spec",
    "run_block_load",
    "run_block_serve_bench",
    "run_load",
    "run_pool_serve_bench",
    "run_serve_bench",
    "select_block_bucket",
    "select_bucket",
    "smoke_spec",
    "trace_scope",
]
