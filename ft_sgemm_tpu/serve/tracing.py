"""Request-scoped trace IDs for the serving plane.

A serving fault is only diagnosable if one identifier survives the whole
request lifecycle: enqueue -> bucket flush -> AOT executable call ->
detection/correction -> retry ladder -> response. This module is that
identifier — a short random hex token minted per
:class:`~ft_sgemm_tpu.serve.engine.ServeRequest` and stamped into every
artifact the request touches:

- the ``serve_gemm`` fault event (``extra["trace_id"]`` — alongside the
  per-request tile blame, so the trace joins a USER REQUEST to the exact
  tile/device that corrupted it),
- every ``retry`` / ``exhausted`` ladder event the request's
  uncorrectable path emits,
- the serve batch's timeline span (``trace_ids`` — which requests were
  in flight when a span was killed),
- the live monitor's event ring (``/events`` — the endpoint the
  ISSUE-9 trace-join acceptance asserts against).

One ``grep TRACE_ID`` over any of those streams reconstructs the
request's story; ``cli top`` renders the same join live.

Propagation rules (DESIGN.md §12):

1. The ID is minted at REQUEST CONSTRUCTION (not at execution), so a
   request that waits in the queue, overflows, or is rejected still has
   an identity.
2. The engine enters :func:`trace_scope` for the request's execution
   window; anything recorded inside (including nested telemetry
   recorders that know nothing about serving) can pick the ID up via
   :func:`current_trace_id` / :func:`stamp`.
3. Events always carry the ID in ``extra["trace_id"]`` — never as a new
   top-level field, so the JSONL schema and every existing reader stay
   untouched.

HARD CONSTRAINT — stdlib only, no package imports: like
``telemetry/timeline.py`` this module must be loadable by file path in a
jax-free process (the monitor's HTTP plane and the CLI's follow mode
both run without a backend).
"""

from __future__ import annotations

import contextlib
import contextvars
import uuid
from typing import Optional

# contextvars (not threading.local): the dispatch thread executes many
# requests and a future async engine would interleave them — context
# variables scope correctly under both.
_CURRENT: contextvars.ContextVar[Optional[str]] = contextvars.ContextVar(
    "ft_sgemm_trace_id", default=None)


def new_trace_id() -> str:
    """A fresh 16-hex-char request trace ID (64 random bits — collision
    probability is negligible at any realistic request volume, and the
    short form stays grep- and column-friendly in JSONL/terminal views)."""
    return uuid.uuid4().hex[:16]


def current_trace_id() -> Optional[str]:
    """The trace ID of the enclosing :func:`trace_scope`, or None."""
    return _CURRENT.get()


@contextlib.contextmanager
def trace_scope(trace_id: Optional[str]):
    """Make ``trace_id`` the ambient trace for the block (restored on
    exit, nesting-safe). ``None`` scopes are allowed and simply clear
    the ambient ID for the block."""
    token = _CURRENT.set(trace_id)
    try:
        yield trace_id
    finally:
        _CURRENT.reset(token)


def stamp(extra: Optional[dict] = None,
          trace_id: Optional[str] = None) -> Optional[dict]:
    """Return ``extra`` with ``trace_id`` merged in (explicit argument
    first, else the ambient scope's). Never overwrites an existing
    ``trace_id`` key and returns the input unchanged (possibly None)
    when there is no ID to stamp — so stamping is safe to apply
    unconditionally on every event-emission path."""
    tid = trace_id if trace_id is not None else _CURRENT.get()
    if tid is None:
        return extra
    if extra is not None and extra.get("trace_id") is not None:
        return extra
    merged = dict(extra or {})
    merged["trace_id"] = tid
    return merged


__all__ = ["current_trace_id", "new_trace_id", "stamp", "trace_scope"]
