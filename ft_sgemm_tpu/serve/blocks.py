"""Transformer-block serving: ragged prefill/decode attention requests.

The serving plane (PRs 8–10) batches bare ``(M, N, K)`` GEMMs; the
workload that actually reaches users is attention — ROADMAP item 3's
"the paper's real customer". This module extends the engine contract to
TRANSFORMER-BLOCK requests while keeping every serving discipline the
GEMM plane established:

- **Bucketing.** Ragged sequences fold onto a small padded
  :class:`~ft_sgemm_tpu.serve.buckets.BlockBucket` set under the same
  tuner-aligned power-of-two rule GEMM shapes use, so each block bucket
  dispatches exactly ONE AOT-compiled executable per injection variant
  and steady-state serving records ZERO compile spans (the PR-8
  warm-path pin, same timeline accounting).
- **Executors.** The compiled executors are the existing FT attention
  factories — :func:`ft_sgemm_tpu.ops.attention.make_ft_attention`
  single-device, :func:`ft_sgemm_tpu.parallel.ring_attention.
  make_ring_ft_attention` when a ring mesh is live — so both GEMMs of
  every request run through the fused-ABFT kernels and the softmax
  stage keeps its decomposed invariant + dual-recompute checks. Fault
  attribution flows through QK/softmax/PV into ONE ``serve_block``
  event per request carrying the request's ``trace_id`` (the PR-9
  ``serve_gemm``-style join); ring-path events additionally carry
  per-ring-position device blame (``record_mesh_attention``,
  ``inject_coords`` localizes the self-test fault to one device).
- **Causal padding is exact.** Everything runs ``causal=True`` with
  END-anchored positions: prefill pads queries and keys together
  (``lq == lk``), so real query row ``i`` attends exactly keys
  ``0..i`` and padded keys are masked by construction; decode places
  its single real query at row ``len - 1 - (lk - lq)``, which the
  decode buckets' ``lq = lk/2`` rule keeps in range — zero-padding
  never leaks probability mass (the GEMM plane's "padding is exact"
  property, recovered for softmax by geometry instead of masks).
- **Stored state is checked.** The decode path reads every cached
  K/V page through the ABFT-checked
  :class:`~ft_sgemm_tpu.serve.kv_cache.PagedKVCache`: corruption in
  *state* — not just in flight — is detected on read, attributed to
  ``(seq, layer, head, page)`` in a ``kv_page`` fault event joined to
  the request's trace, corrected in place when localizable, and
  otherwise recovered by a bounded page-scoped restore/re-verify
  ladder that mirrors the PR-8 bucket-scoped retry ladder (never the
  whole queue).

Goodput for this workload is **tokens-correct-per-second**: a prefill
contributes its sequence length, a decode one token, and only verified-
or-clean results count (``serve/loadgen.py::run_block_load``).
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
import time
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from ft_sgemm_tpu.serve.buckets import BlockBucket, select_block_bucket
from ft_sgemm_tpu.serve.engine import (
    VARIANTS,
    _Future,
    _as_recorder,
    _device_label,
)
from ft_sgemm_tpu.perf.economics import (
    CostLedger,
    attention_cost,
    gemm_request_cost,
    kv_reverify_flops,
)
from ft_sgemm_tpu.serve.kv_cache import PagedKVCache
from ft_sgemm_tpu.serve.tracing import new_trace_id, trace_scope
from ft_sgemm_tpu.telemetry.registry import (
    LATENCY_BUCKETS,
    histogram_percentiles,
)

# The two block-request phases — mirrored as literals in
# contracts.BLOCK_PHASES and telemetry's AXIS_LABELS["block_phase"]
# (the lint axis-drift pass cross-checks the spellings).
PHASES = ("prefill", "decode")

_REQ_IDS = itertools.count(1)
_SEQ_IDS = itertools.count(1)


def new_sequence_id() -> int:
    """Mint a fresh serving-sequence identity (one conversation)."""
    return next(_SEQ_IDS)


@dataclasses.dataclass
class BlockRequest:
    """One transformer-block request.

    ``phase="prefill"``: ``q``/``k``/``v`` are the full ragged sequence
    (``(L, d)``, ``(L, d)``, ``(L, dv)``); the engine runs causal
    attention over it AND writes K/V into the checked KV cache under
    ``(seq_id, layer, head)``. ``phase="decode"``: single new-token rows
    (``(1, d)`` / ``(1, dv)``); the engine appends them, reads the whole
    cached prefix back THROUGH the page checksums, and attends the new
    query over it. ``variant`` selects the prewarmed in-flight injection
    variant (same vocabulary as the GEMM engine); stored-state faults
    are injected separately via :meth:`BlockEngine.corrupt_kv`.

    Decodes for one sequence must be submitted sequentially (wait for
    the previous decode's future): the cache length at submit time picks
    the bucket.
    """

    phase: str
    q: np.ndarray
    k: np.ndarray
    v: np.ndarray
    seq_id: int = dataclasses.field(default_factory=new_sequence_id)
    layer: int = 0
    head: int = 0
    in_dtype: str = "float32"
    variant: str = "clean"
    request_id: int = dataclasses.field(
        default_factory=lambda: next(_REQ_IDS))
    trace_id: str = dataclasses.field(default_factory=new_trace_id)

    def __post_init__(self):
        if self.phase not in PHASES:
            raise ValueError(
                f"BlockRequest.phase={self.phase!r} must be one of"
                f" {PHASES}")
        if self.variant not in VARIANTS:
            raise ValueError(
                f"BlockRequest.variant={self.variant!r} must be one of"
                f" {VARIANTS} (one executable per (bucket, variant))")
        self.q = np.asarray(self.q, np.float32)
        self.k = np.asarray(self.k, np.float32)
        self.v = np.asarray(self.v, np.float32)
        if self.q.ndim != 2 or self.k.ndim != 2 or self.v.ndim != 2:
            raise ValueError("BlockRequest q/k/v must be 2-D")
        if self.q.shape[1] != self.k.shape[1]:
            raise ValueError(
                f"BlockRequest head-dim mismatch: q {self.q.shape} vs"
                f" k {self.k.shape}")
        if self.k.shape[0] != self.v.shape[0]:
            raise ValueError(
                f"BlockRequest k/v row mismatch: {self.k.shape[0]} !="
                f" {self.v.shape[0]}")
        if self.phase == "prefill":
            if self.q.shape[0] != self.k.shape[0]:
                raise ValueError(
                    "prefill needs q and k/v over the SAME sequence"
                    f" ({self.q.shape[0]} != {self.k.shape[0]})")
        elif self.q.shape[0] != 1 or self.k.shape[0] != 1:
            raise ValueError("decode carries exactly ONE new token row")

    @property
    def tokens(self) -> int:
        """Output tokens this request produces (prefill: L, decode: 1)."""
        return self.q.shape[0]


@dataclasses.dataclass
class BlockResult:
    """What a block request's future resolves to."""

    request_id: int
    bucket_key: str
    phase: str
    seq_id: int
    out: np.ndarray               # (tokens, dv), sliced to true rows
    detections: int               # corrected in-flight GEMM faults
    softmax_flags: int            # final softmax-stage flags (0 when ok)
    uncorrectable: int            # final in-flight uncorrectable count
    retries: int                  # in-flight bucket-scoped retries
    kv_faults: int                # stored-state faults detected on read
    kv_corrected: int             # ... corrected in place (free)
    kv_restores: int              # ... recovered by page restore
    kv_ok: bool                   # stored state verified (or no reads)
    tokens: int
    ok: bool                      # verified-or-corrected end to end
    corrected: bool               # ok with any fault corrected en route
    latency_seconds: float
    trace_id: Optional[str] = None
    devices: Optional[list] = None  # ring-path per-device blame entries


@dataclasses.dataclass
class _Entry:
    request: BlockRequest
    bucket: BlockBucket
    future: _Future
    t_enqueue: float


class BlockEngine:
    """Shape-bucketed continuous-batching transformer-block server.

    Lifecycle mirrors :class:`~ft_sgemm_tpu.serve.engine.ServeEngine`::

        engine = BlockEngine(default_block_bucket_set((128, 256)))
        engine.start(); engine.prewarm()
        fut = engine.submit(BlockRequest("prefill", q, k, v))
        res = fut.result(timeout=300)      # BlockResult
        engine.drain(); engine.close()

    ``ring=True`` builds the ``inject`` variant's PREFILL executors
    through :func:`~ft_sgemm_tpu.parallel.ring_attention.
    make_ring_ft_attention` over all local devices, with
    ``inject_coords`` pinning the self-test fault to one ring position —
    injected in-flight faults then carry per-device blame entries in
    their ``serve_block`` events. Decode (single new query) and the
    clean/adversarial variants stay single-device.

    ``kv_checksums=False`` disables the stored-state checksums; the
    compiled executors are byte-identical either way (the cache is
    host-side numpy — pinned in ``tests/test_serve_blocks.py``).

    ``pool=DevicePool(...)`` (serve/pool.py) gives block serving the
    GEMM engine's multi-device dispatch: the dispatcher PLACES ready
    batches on health-steered per-device workers, each device runs its
    own AOT replica of every (bucket, variant) executor, and
    ``elastic=ElasticController(...)`` adds the PR-15 eviction path —
    a device crossing the eviction floor is removed from placement with
    its queued block batches migrated. Pool mode forces ``ring=False``
    (replicas are single-device by construction) and serializes the
    host-side KV cache behind one lock.
    """

    def __init__(self, buckets: Sequence[BlockBucket], *,
                 threshold="static",
                 max_batch: int = 4, max_wait: float = 0.05,
                 max_retries: int = 2, retry_backoff: float = 0.01,
                 kv_page_size: int = 32, kv_checksums: bool = True,
                 kv_threshold: Optional[float] = None,
                 ring: bool = False,
                 inject_coords: Optional[tuple] = (1,),
                 timeline=None, registry=None, monitor=None,
                 pool=None, elastic=None):
        if not buckets:
            raise ValueError("BlockEngine needs at least one bucket")
        if pool is not None and ring:
            # A pool dispatches per-device SINGLE-DEVICE replicas; the
            # ring executor spans the whole mesh — the two placement
            # models are mutually exclusive by construction.
            raise ValueError("BlockEngine(pool=) needs ring=False (ring"
                             " executors span the mesh; pool replicas"
                             " are single-device)")
        dims = {(b.d, b.dv, b.in_dtype) for b in buckets}
        if len(dims) != 1:
            raise ValueError(
                "BlockEngine buckets must share (d, dv, in_dtype): one"
                f" engine serves one model geometry, got {sorted(dims)}")
        self.buckets: Tuple[BlockBucket, ...] = tuple(buckets)
        self.d, self.dv, self.in_dtype = next(iter(dims))
        self.threshold = threshold
        self.max_batch = int(max_batch)
        self.max_wait = float(max_wait)
        self.max_retries = int(max_retries)
        self.retry_backoff = float(retry_backoff)
        self.ring = bool(ring)
        self.inject_coords = tuple(inject_coords) if inject_coords else None
        self._mesh = None
        self._tl = _as_recorder(timeline)
        self.monitor = monitor
        # Multi-device dispatch + elastic recovery (serve/pool.py,
        # resilience/elastic.py): the GEMM engine's placement/drain/
        # eviction discipline, block-typed. pool=None keeps the
        # historical single-dispatcher engine exactly.
        self.pool = pool
        self.elastic = elastic
        self._pool_threads: list = []
        # The KV cache and per-stream source rows are host-side state
        # shared by every pool worker; one lock serializes stored-state
        # access (single-dispatcher mode pays an uncontended acquire).
        self._kv_lock = threading.RLock()
        kv_kw = {} if kv_threshold is None else {"threshold": kv_threshold}
        self.kv = PagedKVCache(self.d, self.dv, page_size=kv_page_size,
                               checksums=kv_checksums, **kv_kw)
        # Authoritative per-stream source rows — the stand-in for
        # upstream re-materialization (re-running prefill from the
        # prompt) that the page-restore ladder draws on. Dispatcher-
        # thread-only after submit.
        self._source: Dict[tuple, dict] = {}
        from ft_sgemm_tpu import telemetry

        self.registry = registry if registry is not None \
            else telemetry.get_registry()

        self._cond = threading.Condition()
        self._pending: Dict[str, list] = {b.key: [] for b in self.buckets}
        self._by_key = {b.key: b for b in self.buckets}
        self._outstanding = 0
        self._draining = False
        self._stop = False
        self._thread: Optional[threading.Thread] = None

        self._compile_lock = threading.Lock()
        self._compiled: Dict[Tuple[str, str], object] = {}
        self._prewarmed = False
        self._t_first: Optional[float] = None

        self._stats_lock = threading.Lock()
        self._counts = {
            "requests": 0, "completed": 0, "batches": 0,
            "corrected_free": 0, "retries": 0, "whole_queue_retries": 0,
            "uncorrectable_exhausted": 0, "rejected": 0,
            "tokens_ok": 0, "tokens_total": 0,
            "prefill": 0, "decode": 0,
        }
        self._per_bucket: Dict[str, dict] = {
            b.key: {"requests": 0, "batches": 0, "retries": 0}
            for b in self.buckets}
        # Cost plane: every request is priced with the same component
        # cost model the checker uses (attention_cost), so the useful /
        # overhead split is exact by construction, not sampled.
        self.economics = CostLedger()

    # -- executors: one AOT executable per (bucket, variant) ----------------

    def _attn_shapes(self, bucket: BlockBucket):
        """Explicit kernel tiles per bucket (no auto-shrink, tuner off —
        the bucket IS the shape contract). QK contracts over the head
        dim (one 128-granule); PV contracts over L_k and keeps ``bk``
        at one granule for lk <= 512 so its K grid is >= 2 steps on the
        256+ buckets — the depth the adversarial same-column schedule
        needs to produce a genuine uncorrectable interval (the GEMM
        engine's ``_bucket_tile`` rule, applied to the PV product)."""
        from ft_sgemm_tpu.configs import KernelShape

        bm = min(bucket.lq, 512)
        bn = min(bucket.lk, 512)
        qk = KernelShape(f"blkqk{bm}x{bn}", bm, bn, 128, (0,) * 7)
        pvk = 128 if bucket.lk <= 512 else 512
        pv = KernelShape(f"blkpv{bm}x{pvk}", bm, 128, pvk, (0,) * 7)
        return qk, pv

    def _variant_spec(self, variant: str):
        from ft_sgemm_tpu.injection import InjectionSpec

        if variant == "clean":
            return InjectionSpec.none()
        if variant == "inject":
            # Reference-like correctable SDCs: rotating columns, every
            # K step, corrected in-kernel by both attention GEMMs.
            return InjectionSpec(enabled=True, every=1, magnitude=10000.0)
        # Adversarial: same-column faults — uncorrectable through the
        # PV product's >= 2-step K grid on lk >= 256 buckets (the QK
        # product's single head-dim step degenerates to a corrected
        # single fault, which is fine: one uncorrectable source drives
        # the retry ladder).
        return InjectionSpec(enabled=True, every=1, magnitude=10000.0,
                             col_stride=0)

    def _use_ring(self, bucket: BlockBucket, variant: str) -> bool:
        """Ring executors serve the INJECT variant's prefill buckets
        (lq == lk, dims divide the ring) when ring mode is on — the
        configuration that buys per-device fault attribution."""
        if not self.ring or variant != "inject":
            return False
        if bucket.lq != bucket.lk:
            return False
        mesh = self._ring_mesh()
        if mesh is None:
            return False
        dnum = mesh.shape["x"]
        return bucket.lq % dnum == 0 and bucket.lk % dnum == 0

    def _ring_mesh(self):
        if self._mesh is None and self.ring:
            from ft_sgemm_tpu.parallel.ring import make_ring_mesh

            try:
                self._mesh = make_ring_mesh()
            except Exception:  # noqa: BLE001 — <2 devices: stay local
                self._mesh = None
                self.ring = False
        return self._mesh

    def _executor_fn(self, bucket: BlockBucket, variant: str):
        """The python callable ``fn(q, k, v)`` the AOT executable is
        compiled from. Returns raw ``(out, det, flags, unc)`` (+ ring
        device counters when sharded) so the compiled signature is a
        plain array tuple."""
        qk_shape, pv_shape = self._attn_shapes(bucket)
        spec = self._variant_spec(variant)
        if self._use_ring(bucket, variant):
            from ft_sgemm_tpu.configs import KernelShape
            from ft_sgemm_tpu.parallel.ring_attention import (
                make_ring_ft_attention)

            # Shard-local tiles: each hop's GEMMs see (lq/D, lk/D)
            # blocks — one 128-granule tile bounds the padding (and the
            # interpret-mode cost of the CPU smoke).
            tile = KernelShape("blkring", 128, 128, 128, (0,) * 7)
            return make_ring_ft_attention(
                self._ring_mesh(), causal=True, inject=spec,
                strategy=bucket.strategy, threshold=self.threshold,
                qk_shape=tile, pv_shape=tile, in_dtype=bucket.in_dtype,
                inject_coords=self.inject_coords)
        from ft_sgemm_tpu.ops.attention import make_ft_attention

        attn = make_ft_attention(
            causal=True, strategy=bucket.strategy,
            threshold=self.threshold, qk_shape=qk_shape,
            pv_shape=pv_shape, in_dtype=bucket.in_dtype)

        def fn(q, k, v):
            res = attn(q, k, v, spec)
            return (res.out, res.detections, res.softmax_flags,
                    res.uncorrectable)

        return fn

    def lowered_executor_text(self, bucket: BlockBucket,
                              variant: str = "clean") -> str:
        """The executor's lowered HLO as text — the surface
        ``tests/test_serve_blocks.py`` pins byte-identical across
        ``kv_checksums`` on/off (stored-state checking must never touch
        the compiled computation)."""
        import jax

        fn, avals = self._jit_fn(bucket, variant)
        return jax.jit(fn).lower(*avals).as_text()

    def _jit_fn(self, bucket: BlockBucket, variant: str, device=None):
        import jax
        import jax.numpy as jnp

        fn = self._executor_fn(bucket, variant)
        if device is None:
            def av(shape):
                return jax.ShapeDtypeStruct(shape, jnp.float32)
        else:
            from jax.sharding import SingleDeviceSharding

            sh = SingleDeviceSharding(device)

            def av(shape):
                return jax.ShapeDtypeStruct(shape, jnp.float32,
                                            sharding=sh)
        avals = (av((bucket.lq, self.d)),
                 av((bucket.lk, self.d)),
                 av((bucket.lk, self.dv)))
        return fn, avals

    def _get_compiled(self, bucket: BlockBucket, variant: str,
                      device=None):
        label = None if device is None else str(device)
        key = (bucket.key, variant, label)
        compiled = self._compiled.get(key)
        if compiled is not None:
            return compiled
        with self._compile_lock:
            compiled = self._compiled.get(key)
            if compiled is not None:
                return compiled
            import jax

            fn, avals = self._jit_fn(bucket, variant, device=device)
            span = f"compile[{bucket.key}:{variant}]" if label is None \
                else f"compile[{bucket.key}:{variant}@{label}]"
            with self._tl.span(span, kind="compile"):
                compiled = jax.jit(fn).lower(*avals).compile()
            self._compiled[key] = compiled
            return compiled

    def prewarm(self, variants=VARIANTS) -> dict:
        """AOT-compile every (bucket, variant[, pool device]) executor;
        everything after the ``prewarm_done`` point is the steady state
        the zero-compile-span pin measures (same contract as the GEMM
        engine's prewarm)."""
        t0 = time.monotonic()
        compiled = 0
        devices = (None,) if self.pool is None else self.pool.devices
        for bucket in self.buckets:
            for variant in variants:
                for device in devices:
                    self._get_compiled(bucket, variant, device=device)
                    compiled += 1
        self._prewarmed = True
        seconds = round(time.monotonic() - t0, 3)
        self._tl.point("serve_block", "prewarm_done", compiled=compiled,
                       seconds=seconds)
        return {"compiled": compiled, "buckets": len(self.buckets),
                "seconds": seconds}

    # -- queue (the GEMM engine's discipline, block-typed) ------------------

    def start(self) -> "BlockEngine":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._dispatch_loop, daemon=True,
                name="serve-block-dispatch")
            self._thread.start()
        if self.pool is not None and not self._pool_threads:
            for i in range(len(self.pool.devices)):
                t = threading.Thread(target=self._pool_worker, args=(i,),
                                     daemon=True,
                                     name=f"serve-block-pool-{i}")
                t.start()
                self._pool_threads.append(t)
        return self

    def __enter__(self) -> "BlockEngine":
        return self.start()

    def __exit__(self, *exc):
        if not any(exc):
            self.drain()
        self.close()
        return False

    def request_length(self, request: BlockRequest) -> int:
        """The token count the request's bucket is selected on: prefill
        length, or cached-prefix length + the new token for decode."""
        if request.phase == "prefill":
            return request.q.shape[0]
        with self._kv_lock:
            return self.kv.length(request.seq_id, request.layer,
                                  request.head) + 1

    def submit(self, request: BlockRequest) -> _Future:
        length = self.request_length(request)
        try:
            bucket = select_block_bucket(self.buckets, length,
                                         request.phase,
                                         in_dtype=request.in_dtype)
        except Exception:
            with self._stats_lock:
                self._counts["rejected"] += 1
            self.registry.counter("serve_block_rejected").inc()
            raise
        fut = _Future()
        entry = _Entry(request, bucket, fut, time.monotonic())
        with self._cond:
            if self._stop:
                raise RuntimeError("BlockEngine is closed")
            self._pending[bucket.key].append(entry)
            self._outstanding += 1
            if self._t_first is None:
                self._t_first = time.monotonic()
            self._cond.notify_all()
        with self._stats_lock:
            self._counts["requests"] += 1
            self._counts[request.phase] += 1
            self._per_bucket[bucket.key]["requests"] += 1
        self.registry.counter("serve_block_requests", bucket=bucket.key,
                              block_phase=request.phase).inc()
        self._tl.point("serve_block", "enqueue",
                       trace_id=request.trace_id,
                       request_id=request.request_id,
                       bucket=bucket.key, block_phase=request.phase)
        return fut

    def _ready_keys(self, now: float) -> list:
        out = []
        for key, q in self._pending.items():
            if not q:
                continue
            if (len(q) >= self.max_batch or self._draining or self._stop
                    or now - q[0].t_enqueue >= self.max_wait):
                out.append(key)
        return out

    def _next_deadline(self, now: float) -> Optional[float]:
        waits = [self.max_wait - (now - q[0].t_enqueue)
                 for q in self._pending.values() if q]
        return max(0.0, min(waits)) if waits else None

    def _dispatch_loop(self):
        while True:
            batches = []
            with self._cond:
                while True:
                    now = time.monotonic()
                    ready = self._ready_keys(now)
                    if ready:
                        break
                    if self._stop:
                        return
                    timeout = self._next_deadline(now)
                    self._cond.wait(0.1 if timeout is None else timeout)
                for key in ready:
                    q = self._pending[key]
                    take = q[:self.max_batch]
                    del q[:len(take)]
                    batches.append((self._by_key[key], take))
            for bucket, entries in batches:
                if self.pool is not None:
                    self._place_batch(bucket, entries)
                else:
                    self._execute_batch(bucket, entries)

    def _check_elastic(self) -> None:
        if self.elastic is None or self.pool is None:
            return
        decision = self.elastic.should_evict(self.pool)
        if decision is not None:
            self.evict_device(decision[0], reason=decision[1])

    def evict_device(self, index: int, reason: str = "manual") -> dict:
        """The GEMM engine's eviction contract, block-typed: placement
        stops naming the device, queued block batches migrate through
        the placer, survivors' executors are confirmed (the re-AOT
        window — a pure cache walk when prewarmed)."""
        from ft_sgemm_tpu import telemetry

        label = self.pool.labels[index]
        t0 = time.monotonic()
        leftovers = self.pool.evict(index)
        survivors = [d for i, d in enumerate(self.pool.devices)
                     if i not in self.pool.evicted]
        with self._tl.span(f"reshard[{label}]", kind="stage") as info:
            for bucket in self.buckets:
                for variant in VARIANTS:
                    for device in survivors:
                        self._get_compiled(bucket, variant, device=device)
            migrated = 0
            for bucket, entries in leftovers:
                self._place_batch(bucket, entries)
                migrated += len(entries)
            info["value"] = {"device": label, "reason": reason,
                             "migrated_requests": migrated}
        seconds = round(time.monotonic() - t0, 6)
        facts = {"index": index, "device": label, "reason": reason,
                 "migrated": migrated, "reshard_seconds": seconds,
                 "survivors": len(survivors), "ts": time.monotonic()}
        self.registry.counter("recovery_evictions", device=label).inc()
        telemetry.record_step_event(
            "evicted", op="serve_pool",
            extra={"device": label, "reason": reason,
                   "migrated": migrated, "workload": "block",
                   "reshard_seconds": seconds})
        self._tl.point("recovery", "evicted", device=label,
                       reason=reason, migrated=migrated)
        if self.elastic is not None:
            self.elastic.record_eviction(facts)
        return facts

    def _place_batch(self, bucket: BlockBucket, entries) -> None:
        self._check_elastic()
        index = self.pool.choose()
        label = self.pool.labels[index]
        depth = self.pool.put(index, (bucket, entries))
        self.registry.gauge("serve_pool_queue_depth",
                            device=label).set(depth)
        self.registry.counter("serve_pool_placements", device=label).inc()
        self._tl.point("serve_block", "placement", device=label,
                       pool_placement=self.pool.placement,
                       bucket=bucket.key,
                       trace_ids=[e.request.trace_id for e in entries])

    def _pool_worker(self, index: int) -> None:
        label = self.pool.labels[index]
        while True:
            item = self.pool.get(index)
            if item is None:
                if self.pool.stopped:
                    return
                continue
            self.registry.gauge("serve_pool_queue_depth", device=label) \
                .set(self.pool.queue_depth(index))
            bucket, entries = item
            self.pool.note_batch(index, len(entries))
            self.registry.counter("serve_pool_batches", device=label).inc()
            self._execute_batch(bucket, entries, device_index=index)

    def drain(self, timeout: Optional[float] = None) -> None:
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            self._draining = True
            self._cond.notify_all()
            try:
                while self._outstanding > 0:
                    if deadline is not None and time.monotonic() > deadline:
                        raise TimeoutError(
                            f"drain timed out with {self._outstanding}"
                            " block requests outstanding")
                    self._cond.wait(0.05)
            finally:
                self._draining = False

    def close(self) -> None:
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        leftovers = []
        if self.pool is not None:
            for _bucket, entries in self.pool.stop():
                leftovers.extend(entries)
            for t in self._pool_threads:
                t.join(timeout=10.0)
            self._pool_threads = []
        with self._cond:
            for q in self._pending.values():
                leftovers.extend(q)
                q.clear()
            self._outstanding -= len(leftovers)
        for entry in leftovers:
            entry.future._reject(RuntimeError(
                "BlockEngine closed with request still queued"))

    # -- stored-state fault injection (the loadgen/test hook) ---------------

    def corrupt_kv(self, seq_id: int, layer: int = 0, head: int = 0, *,
                   page: Optional[int] = None, row: int = 0, cols=(0,),
                   magnitude: float = 1000.0, which: str = "k",
                   target: str = "data") -> int:
        """Corrupt one stored page between decode steps (delegates to
        :meth:`PagedKVCache.corrupt`; ``page=None`` targets the last
        written page). Returns the corrupted page index."""
        with self._kv_lock:
            if page is None:
                length = self.kv.length(seq_id, layer, head)
                if length == 0:
                    raise ValueError(
                        f"sequence {seq_id} has no cached state")
                page = (length - 1) // self.kv.page_size
            self.kv.corrupt(seq_id, layer, head, page, row=row, cols=cols,
                            magnitude=magnitude, which=which,
                            target=target)
        return page

    # -- execution ----------------------------------------------------------

    def _execute_batch(self, bucket: BlockBucket, entries,
                       device_index: Optional[int] = None):
        with self._stats_lock:
            self._counts["batches"] += 1
            self._per_bucket[bucket.key]["batches"] += 1
        self.registry.counter("serve_block_batches",
                              bucket=bucket.key).inc()
        trace_ids = [e.request.trace_id for e in entries]
        with self._tl.span(f"serve_block[{bucket.key}]", kind="stage",
                           trace_ids=trace_ids) as info:
            det_total = unc_total = 0
            for entry in entries:
                det, unc = self._execute_one(bucket, entry,
                                             device_index=device_index)
                det_total += det
                unc_total += unc
            info["value"] = {"batch": len(entries),
                             "detections": det_total,
                             "uncorrectable_final": unc_total,
                             "trace_ids": trace_ids}
            if device_index is not None:
                info["value"]["device"] = self.pool.labels[device_index]

    def _append_source(self, key: tuple, k_rows, v_rows) -> None:
        src = self._source.setdefault(
            key, {"k": np.zeros((0, self.d), np.float32),
                  "v": np.zeros((0, self.dv), np.float32)})
        src["k"] = np.concatenate([src["k"], np.asarray(k_rows,
                                                        np.float32)])
        src["v"] = np.concatenate([src["v"], np.asarray(v_rows,
                                                        np.float32)])

    def _emit_kv_fault(self, fault, request, bucket) -> None:
        """One stored-state finding -> kv_page event + timeline point +
        monitor ring, all joined by the request's trace_id."""
        from ft_sgemm_tpu import telemetry

        outcome = "corrected" if fault.corrected else "uncorrectable"
        coords = fault.coords()
        extra = dict(coords)
        extra.update(trace_id=request.trace_id,
                     request_id=request.request_id, bucket=bucket.key,
                     block_phase=request.phase)
        self.registry.counter("kv_page_faults").inc()
        if fault.corrected:
            self.registry.counter("kv_page_corrected").inc()
        telemetry.record_kv_page(
            outcome, layer=f"L{fault.layer}H{fault.head}",
            detected=1, corrected=1 if fault.corrected else 0,
            uncorrectable=0 if fault.corrected else 1,
            tiles=[[fault.page,
                    fault.row if fault.row is not None else -1]],
            extra=extra)
        self._tl.point("kv_page", outcome, trace_id=request.trace_id,
                       **coords)
        if self.monitor is not None:
            self.monitor.observe_retry(
                {"outcome": outcome, "op": "kv_page",
                 "detected": 1,
                 "uncorrectable": 0 if fault.corrected else 1,
                 "ts": time.time(), "extra": extra})

    def _read_kv_verified(self, request: BlockRequest,
                          bucket: BlockBucket):
        """Read the request's cached stream through the page checksums,
        with the bounded page-scoped restore/re-verify ladder. Returns
        ``(K, V, info)``; ``info["ok"]`` False means a page stayed
        unverified after the ladder was exhausted."""
        from ft_sgemm_tpu import telemetry

        key = (request.seq_id, request.layer, request.head)
        info = {"faults": 0, "corrected": 0, "restores": 0,
                "attempts": 0, "ok": True}
        attempts = 0
        while True:
            self.registry.counter("kv_page_reads").inc()
            K, V, faults = self.kv.read(*key)
            for fault in faults:
                info["faults"] += 1
                if fault.corrected:
                    info["corrected"] += 1
                self._emit_kv_fault(fault, request, bucket)
            bad = [f for f in faults if not f.corrected]
            self._set_kv_gauge()
            if not bad:
                return K, V, info
            if attempts >= self.max_retries:
                info["ok"] = False
                return K, V, info
            attempts += 1
            info["attempts"] = attempts
            src = self._source.get(key)
            for fault in bad:
                if src is None:
                    info["ok"] = False
                    return K, V, info
                sl = self.kv.page_slice(fault.page)
                self.kv.restore(request.seq_id, request.layer,
                                request.head, fault.page,
                                src["k"][sl], src["v"][sl])
                info["restores"] += 1
                self.registry.counter("kv_page_restores").inc()
                # The ladder event: page-scoped, bounded, joined to the
                # request — the stored-state mirror of the bucket-scoped
                # GEMM retry.
                telemetry.record_step_event(
                    "retry", op="kv_page", uncorrectable=1,
                    extra={"trace_id": request.trace_id,
                           "request_id": request.request_id,
                           "bucket": bucket.key, "page": fault.page,
                           "seq_id": fault.seq_id, "layer": fault.layer,
                           "head": fault.head, "attempt": attempts})
                self._tl.point("kv_page", "restore",
                               trace_id=request.trace_id,
                               seq_id=fault.seq_id, page=fault.page,
                               layer=fault.layer, head=fault.head,
                               attempt=attempts)

    def _set_kv_gauge(self) -> None:
        rate = self.kv.stats().get("verify_hit_rate")
        if rate is not None:
            self.registry.gauge("kv_verify_hit_rate").set(rate)

    def _pad_operands(self, bucket: BlockBucket, request: BlockRequest,
                      K: Optional[np.ndarray], V: Optional[np.ndarray]):
        """Zero-pad to the bucket's executor shape. Prefill packs the
        sequence at the TOP (rows 0..L-1; causal lq == lk masks padded
        keys for every real query). Decode places the single real query
        at row ``len - 1 - (lk - lq)`` so its end-anchored causal
        position equals the last key — it attends exactly the ``len``
        real keys and none of the padding."""
        qp = np.zeros((bucket.lq, self.d), np.float32)
        kp = np.zeros((bucket.lk, self.d), np.float32)
        vp = np.zeros((bucket.lk, self.dv), np.float32)
        if request.phase == "prefill":
            length = request.q.shape[0]
            qp[:length] = request.q
            kp[:length] = request.k
            vp[:length] = request.v
            return qp, kp, vp, slice(0, length)
        length = K.shape[0]
        row = length - 1 - (bucket.lk - bucket.lq)
        qp[row] = request.q[0]
        kp[:length] = K
        vp[:length] = V
        return qp, kp, vp, slice(row, row + 1)

    def _run_executor(self, bucket, variant, qp, kp, vp, device=None):
        """One executor call, normalized to ``(out, det, flags, unc,
        dev_entries)`` with host ints."""
        compiled = self._get_compiled(bucket, variant, device=device)
        res = compiled(qp, kp, vp)
        dev_det = dev_unc = None
        if len(res) == 6:  # ring executor: trailing per-device counters
            out, det, flags, unc, dev_det, dev_unc = res
        else:
            out, det, flags, unc = res
        return (out, int(np.asarray(det)), int(np.asarray(flags)),
                int(np.asarray(unc)), dev_det, dev_unc)

    def _execute_one(self, bucket: BlockBucket, entry: _Entry,
                     device_index: Optional[int] = None) -> Tuple[int, int]:
        from ft_sgemm_tpu import telemetry

        request = entry.request
        with trace_scope(request.trace_id):
            return self._execute_one_traced(bucket, entry, telemetry,
                                            device_index=device_index)

    def _execute_one_traced(self, bucket: BlockBucket, entry: _Entry,
                            telemetry,
                            device_index: Optional[int] = None
                            ) -> Tuple[int, int]:
        request = entry.request
        trace_id = request.trace_id
        key = (request.seq_id, request.layer, request.head)
        device = (None if device_index is None
                  else self.pool.devices[device_index])
        K = V = None
        kv_info = {"faults": 0, "corrected": 0, "restores": 0, "ok": True}
        if request.phase == "decode":
            # New token enters the checked store FIRST (its page is
            # resealed on write), then the whole prefix reads back
            # through the checksums. The kv lock serializes stored-state
            # access across pool workers.
            with self._kv_lock:
                self.kv.append(*key, request.k, request.v)
                self.registry.counter("kv_page_writes").inc()
                self._append_source(key, request.k, request.v)
                K, V, kv_info = self._read_kv_verified(request, bucket)
            length = K.shape[0]
            if not (bucket.fits_decode(length)):
                # The submit-time length raced a concurrent decode of
                # the same sequence (callers should sequence them);
                # re-route honestly — a compile here is RECORDED.
                bucket = select_block_bucket(self.buckets, length,
                                             "decode",
                                             in_dtype=request.in_dtype)
        qp, kp, vp, out_slice = self._pad_operands(bucket, request, K, V)
        variant = request.variant
        retries = 0
        out = det = flags = unc = None
        dev_det = dev_unc = None
        while True:
            out, det, flags, unc, dev_det, dev_unc = self._run_executor(
                bucket, variant, qp, kp, vp, device=device)
            # Softmax flags are detect-only (no redundancy to correct
            # from): a flagged step re-runs, exactly like an
            # uncorrectable GEMM interval.
            if (unc == 0 and flags == 0) or retries >= self.max_retries:
                break
            retries += 1
            backoff = self.retry_backoff * (2 ** (retries - 1))
            with self._stats_lock:
                self._counts["retries"] += 1
                self._per_bucket[bucket.key]["retries"] += 1
            self.registry.counter("serve_block_retries",
                                  bucket=bucket.key).inc()
            retry_extra = {"trace_id": trace_id, "bucket": bucket.key,
                           "request_id": request.request_id,
                           "block_phase": request.phase,
                           "attempt": retries,
                           "softmax_flags": flags,
                           "backoff_seconds": round(backoff, 6)}
            telemetry.record_step_event(
                "retry", op="serve_block", uncorrectable=unc,
                extra=retry_extra)
            self._tl.point("serve_block", "retry", trace_id=trace_id,
                           bucket=bucket.key, attempt=retries,
                           uncorrectable=unc, softmax_flags=flags)
            if self.monitor is not None:
                self.monitor.observe_retry(
                    {"outcome": "retry", "op": "serve_block",
                     "uncorrectable": unc, "ts": time.time(),
                     "extra": retry_extra})
            if backoff > 0:
                time.sleep(backoff)
            # Transient-SDC model: the retry re-executes clean.
            variant = "clean"
        kv_ok = bool(kv_info["ok"])
        ok = unc == 0 and flags == 0 and kv_ok
        corrected = ok and (det > 0 or kv_info["corrected"] > 0
                            or kv_info["restores"] > 0)
        if corrected:
            with self._stats_lock:
                self._counts["corrected_free"] += 1
            self.registry.counter("serve_block_corrected_free",
                                  bucket=bucket.key).inc()
        if not ok:
            with self._stats_lock:
                self._counts["uncorrectable_exhausted"] += 1
            self.registry.counter("serve_block_uncorrectable_exhausted",
                                  bucket=bucket.key).inc()
            exhausted_extra = {"trace_id": trace_id, "bucket": bucket.key,
                               "request_id": request.request_id,
                               "block_phase": request.phase,
                               "attempts": retries,
                               "kv_ok": kv_ok}
            telemetry.record_step_event(
                "exhausted", op="serve_block", uncorrectable=unc,
                extra=exhausted_extra)
            self._tl.point("serve_block", "exhausted", trace_id=trace_id,
                           bucket=bucket.key, attempts=retries,
                           uncorrectable=unc)
            if self.monitor is not None:
                self.monitor.observe_retry(
                    {"outcome": "exhausted", "op": "serve_block",
                     "uncorrectable": unc, "ts": time.time(),
                     "extra": exhausted_extra})
        if request.phase == "prefill" and ok:
            # Verified prefill state enters the checked store: every
            # page seals its checksum rows as it is written.
            with self._kv_lock:
                self.kv.append(*key, request.k, request.v)
                self.registry.counter("kv_page_writes").inc()
                self._append_source(key, request.k, request.v)
        latency = time.monotonic() - entry.t_enqueue
        tokens = request.tokens
        with self._stats_lock:
            self._counts["tokens_total"] += tokens
            if ok:
                self._counts["tokens_ok"] += tokens
            tokens_ok = self._counts["tokens_ok"]
        if ok:
            self.registry.counter("serve_block_tokens").inc(tokens)
        if self._t_first is not None:
            elapsed = max(time.monotonic() - self._t_first, 1e-9)
            self.registry.gauge("serve_block_tokens_per_second").set(
                round(tokens_ok / elapsed, 3))
        for labels in ({}, {"bucket": bucket.key}):
            self.registry.histogram("serve_block_latency_seconds",
                                    buckets=LATENCY_BUCKETS,
                                    **labels).observe(latency)
        try:
            # Cost plane: the bucket shape is what actually executed
            # (padding flops are real work), retries re-execute the
            # full checked kernel, and the kv ladder's restores +
            # re-reads are priced as "kv_reverify" overhead.
            parts = attention_cost(bucket.lq, bucket.lk, self.d, self.dv)
            productive, overhead = gemm_request_cost(parts,
                                                     retries=retries)
            overhead["kv_reverify"] = kv_reverify_flops(
                restores=kv_info["restores"],
                reread_rows=kv_info.get("attempts", 0) * bucket.lk,
                page_size=self.kv.page_size, d=self.d, dv=self.dv)
            self.economics.add(
                flops_productive=productive, overhead=overhead,
                tokens=tokens, tokens_correct=tokens if ok else 0,
                seconds=latency, device=_device_label(out),
                bucket=bucket.key, trace_id=trace_id,
                request_id=request.request_id, ok=ok)
            self.economics.publish(self.registry)
            if self.monitor is not None:
                self.monitor.observe_economics(self.economics.snapshot())
        except Exception:  # noqa: BLE001 — accounting never fails serving
            pass
        request_extra = {
            "trace_id": trace_id,
            "request_id": request.request_id,
            "bucket": bucket.key,
            "block_phase": request.phase,
            "seq_id": request.seq_id,
            "layer": request.layer,
            "head": request.head,
            "variant": request.variant,
            "retries": retries,
            "tokens": tokens,
            "kv_faults": kv_info["faults"],
            "kv_corrected": kv_info["corrected"],
            "kv_restores": kv_info["restores"],
            "latency_seconds": round(latency, 6)}
        devices = None
        if telemetry.enabled():
            from ft_sgemm_tpu.ops.attention import FtAttentionResult

            res_like = FtAttentionResult(out, np.int32(det),
                                         np.int32(flags), np.int32(unc))
            if dev_det is not None:
                ev = telemetry.record_mesh_attention(
                    "serve_block", res_like, strategy=bucket.strategy,
                    dev_detections=dev_det, dev_uncorrectable=dev_unc,
                    axes=("x",), extra=dict(request_extra))
                devices = ev.devices if ev is not None else None
            else:
                telemetry.record_attention(
                    "serve_block", res_like, strategy=bucket.strategy,
                    layer=bucket.key, extra=dict(request_extra))
        if self.monitor is not None:
            self.monitor.observe_request({
                "outcome": ("uncorrectable" if not ok else
                            "corrected" if corrected else "clean"),
                "op": "serve_block", "detected": det,
                "corrected": det if corrected else 0,
                "uncorrectable": unc, "strategy": bucket.strategy,
                "layer": bucket.key, "tiles": None,
                "device": _device_label(out), "ts": time.time(),
                "extra": dict(request_extra, ok=ok)})
        out_rows = np.asarray(out)[out_slice, :self.dv]
        result = BlockResult(
            request_id=request.request_id, bucket_key=bucket.key,
            phase=request.phase, seq_id=request.seq_id, out=out_rows,
            detections=det, softmax_flags=flags, uncorrectable=unc,
            retries=retries, kv_faults=kv_info["faults"],
            kv_corrected=kv_info["corrected"],
            kv_restores=kv_info["restores"], kv_ok=kv_ok,
            tokens=tokens, ok=ok, corrected=corrected,
            latency_seconds=latency, trace_id=trace_id, devices=devices)
        with self._stats_lock:
            self._counts["completed"] += 1
        entry.future._resolve(result)
        with self._cond:
            self._outstanding -= 1
            self._cond.notify_all()
        return det, unc

    # -- stats --------------------------------------------------------------

    def latency_percentiles(self, quantiles=(0.5, 0.99)) -> dict:
        hist = self.registry.histogram("serve_block_latency_seconds",
                                       buckets=LATENCY_BUCKETS)
        return histogram_percentiles(hist.value, quantiles=quantiles)

    def stats(self) -> dict:
        with self._stats_lock:
            counts = dict(self._counts)
            per_bucket = {k: dict(v) for k, v in self._per_bucket.items()}
        out = dict(counts)
        out["per_bucket"] = per_bucket
        out["prewarmed"] = self._prewarmed
        out["latency"] = self.latency_percentiles()
        with self._kv_lock:
            out["kv"] = self.kv.stats()
        out["ring"] = self.ring
        out["economics"] = self.economics.snapshot(
            devices=self.pool.active_devices()
            if self.pool is not None else None)
        if self.pool is not None:
            out["pool"] = self.pool.stats()
        return out


__all__ = ["BlockEngine", "BlockRequest", "BlockResult", "PHASES",
           "new_sequence_id"]
