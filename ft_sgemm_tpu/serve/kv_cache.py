"""ABFT-checked paged KV cache: checksum rows carried by STORED state.

The serving plane's fault story so far covers work *in flight*: every
GEMM/attention accumulator is checksummed inside its kernel, so an SDC
that strikes during a call is detected (and usually corrected) before
the result leaves the op. Decode traffic adds a second exposure window
the kernels cannot see: the KV cache. A key/value row written during
prefill may sit in memory for thousands of decode steps before it is
read again, and a bit flip in that *stored* state poisons every
subsequent token silently — no kernel checksum ever observes it.

This module extends the paper's core economics (arXiv 2305.01024:
detect-and-correct in the same pass, so a corrected SDC is free) from
products to state, the way the attention-ABFT literature prescribes for
transformer stacks (arXiv 2507.16676 carries checksums through
QK/softmax/PV; the cache is the stage between the two):

- **Pages.** Each ``(sequence, layer, head)`` stream is stored as fixed
  ``page_size``-row pages, K and V separately. Page granularity bounds
  both the verify cost per read and the blast radius of a restore.
- **Checksum rows appended on write.** Every page tensor carries TWO
  extra rows (``contracts.KV_PAGE_CHECKSUM_ROWS``) derived whenever the
  page's data changes: row ``p`` is the plain column sum ``1ᵀP`` and row
  ``p+1`` the weighted column sum ``wᵀP`` with ``w_i = i + 1`` — the
  classic ABFT row-locator pair, the same plain/weighted trick the
  ``weighted`` kernel strategy uses for in-flight products.
- **Verify on read.** A read recomputes both sums and compares against
  the stored rows. A clean page costs two vector reductions. A single
  corrupted element is *located* (column from the plain residual, row
  from the weighted/plain ratio) and corrected IN PLACE — a stored-state
  SDC repaired for free, no upstream recompute. A corrupted checksum row
  itself (data intact) is rebuilt in place. Anything wider — multiple
  columns, a non-integral row locator — is reported ``uncorrectable``
  with full ``(layer, head, page)`` blame coordinates, and the caller
  (the block engine's bounded page-scoped retry ladder) restores the
  page from its authoritative source and re-verifies.
- **Clean path untouched.** ``checksums=False`` stores bare pages and
  skips verification entirely. Checksumming is HOST-side numpy over the
  cache's own arrays: it never enters a traced computation, so the
  compiled attention executors are byte-identical with checksums on or
  off (pinned in ``tests/test_serve_blocks.py``).

``corrupt()`` is the stored-state analog of the kernels'
``InjectionSpec`` — the self-test hook load generators and tests use to
flip elements of a page *between* decode steps, modeling the SDC that
strikes memory rather than a MAC array.

Thread-safety: one lock guards all page state (reads verify-and-repair,
so even reads mutate). The block engine calls from its dispatcher
thread while load generators inject corruption from producer threads.

Stdlib + numpy only — no jax import, ever: cache state and its
verification live on host by construction.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

# Rows appended to every page tensor: [plain colsum, weighted colsum].
# Mirrored as a literal in contracts.KV_PAGE_CHECKSUM_ROWS (the
# lint-checked declaration); keep the two in sync.
CHECKSUM_ROWS = 2

# Clean-path recompute noise for f32 sums over <= page_size unit-scale
# rows is ulp-scale (< 1e-5 observed at page_size 64); 1e-3 sits orders
# above it and far below any fault that could skew attention output.
DEFAULT_THRESHOLD = 1e-3


@dataclasses.dataclass(frozen=True)
class KVPageFault:
    """One page-verification finding: the blame coordinates a fault
    event carries (``seq_id``/``layer``/``head``/``page`` name the page;
    ``which`` says K or V; ``row``/``col`` localize a corrected single
    element, None when localization failed)."""

    seq_id: int
    layer: int
    head: int
    page: int
    which: str                  # "k" | "v"
    corrected: bool
    residual: float
    row: Optional[int] = None
    col: Optional[int] = None

    def coords(self) -> dict:
        """The event/extra payload spelling of the blame coordinates."""
        return {"seq_id": self.seq_id, "layer": self.layer,
                "head": self.head, "page": self.page, "which": self.which,
                "row": self.row, "col": self.col,
                "residual": self.residual}


@dataclasses.dataclass
class _PageStream:
    """All pages of one (seq, layer, head) stream for one of K/V."""

    width: int
    pages: List[np.ndarray] = dataclasses.field(default_factory=list)
    rows: int = 0  # total valid rows across pages


class PagedKVCache:
    """Paged KV store whose pages carry their own checksum rows.

    ``head_dim`` is K's row width, ``value_dim`` V's (defaults to
    ``head_dim``). Pages hold ``page_size`` rows; with checksums on,
    each page tensor is ``(page_size + CHECKSUM_ROWS, width)`` and the
    trailing rows hold the plain/weighted column sums of the data rows
    (zero padding rows contribute nothing, so partial pages verify
    exactly like full ones).
    """

    def __init__(self, head_dim: int, value_dim: Optional[int] = None, *,
                 page_size: int = 32, checksums: bool = True,
                 threshold: float = DEFAULT_THRESHOLD):
        if page_size < 1:
            raise ValueError(f"page_size={page_size} must be >= 1")
        self.head_dim = int(head_dim)
        self.value_dim = int(value_dim if value_dim is not None
                             else head_dim)
        self.page_size = int(page_size)
        self.checksums = bool(checksums)
        self.threshold = float(threshold)
        # Row-locator weights, fixed per cache (i + 1 so row 0 is
        # distinguishable from "no corruption").
        self._w = (np.arange(1, self.page_size + 1, dtype=np.float32)
                   [:, None])
        self._lock = threading.Lock()
        self._streams: Dict[tuple, Dict[str, _PageStream]] = {}
        self._counts = {
            "writes": 0, "reads": 0, "pages_verified": 0,
            "faults_detected": 0, "corrected_in_place": 0,
            "checksum_rows_rebuilt": 0, "uncorrectable": 0,
            "restores": 0,
        }

    # -- layout helpers -----------------------------------------------------

    def _page_rows(self) -> int:
        return self.page_size + (CHECKSUM_ROWS if self.checksums else 0)

    def _new_page(self, width: int) -> np.ndarray:
        return np.zeros((self._page_rows(), width), np.float32)

    def _reseal(self, page: np.ndarray) -> None:
        """Recompute and store the page's checksum rows from its data."""
        if not self.checksums:
            return
        data = page[:self.page_size]
        page[self.page_size] = data.sum(axis=0, dtype=np.float32)
        page[self.page_size + 1] = (self._w * data).sum(
            axis=0, dtype=np.float32)

    def _stream(self, seq_id: int, layer: int, head: int,
                which: str) -> _PageStream:
        key = (int(seq_id), int(layer), int(head))
        entry = self._streams.setdefault(key, {
            "k": _PageStream(self.head_dim),
            "v": _PageStream(self.value_dim)})
        return entry[which]

    # -- write path ---------------------------------------------------------

    def append(self, seq_id: int, layer: int, head: int,
               k_rows, v_rows) -> int:
        """Append K/V rows (shape ``(n, head_dim)`` / ``(n, value_dim)``)
        to the stream, page-packing and resealing every touched page's
        checksum rows. Returns the stream's new total row count."""
        k_rows = np.asarray(k_rows, np.float32)
        v_rows = np.asarray(v_rows, np.float32)
        if k_rows.ndim != 2 or k_rows.shape[1] != self.head_dim:
            raise ValueError(
                f"k_rows shape {k_rows.shape} != (n, {self.head_dim})")
        if v_rows.ndim != 2 or v_rows.shape[1] != self.value_dim:
            raise ValueError(
                f"v_rows shape {v_rows.shape} != (n, {self.value_dim})")
        if k_rows.shape[0] != v_rows.shape[0]:
            raise ValueError("k_rows and v_rows must append together "
                             f"({k_rows.shape[0]} != {v_rows.shape[0]})")
        with self._lock:
            for which, rows in (("k", k_rows), ("v", v_rows)):
                stream = self._stream(seq_id, layer, head, which)
                cursor = 0
                while cursor < rows.shape[0]:
                    slot = stream.rows % self.page_size
                    if slot == 0 and stream.rows == len(
                            stream.pages) * self.page_size:
                        stream.pages.append(self._new_page(stream.width))
                    page = stream.pages[-1]
                    take = min(self.page_size - slot,
                               rows.shape[0] - cursor)
                    fresh = rows[cursor:cursor + take]
                    page[slot:slot + take] = fresh
                    if self.checksums:
                        # Checksums update INCREMENTALLY from the rows
                        # being written — never re-derived from stored
                        # data, which would silently launder corruption
                        # already sitting in the page (the write path
                        # must preserve, not erase, the evidence a later
                        # read needs).
                        page[self.page_size] += fresh.sum(
                            axis=0, dtype=np.float32)
                        page[self.page_size + 1] += (
                            self._w[slot:slot + take] * fresh).sum(
                                axis=0, dtype=np.float32)
                    stream.rows += take
                    cursor += take
            self._counts["writes"] += 1
            return self._stream(seq_id, layer, head, "k").rows

    def length(self, seq_id: int, layer: int, head: int) -> int:
        with self._lock:
            key = (int(seq_id), int(layer), int(head))
            entry = self._streams.get(key)
            return entry["k"].rows if entry else 0

    def drop(self, seq_id: int) -> None:
        """Free every stream of one sequence (end-of-conversation)."""
        with self._lock:
            for key in [k for k in self._streams if k[0] == int(seq_id)]:
                del self._streams[key]

    # -- verify / read path -------------------------------------------------

    def _verify_page(self, page: np.ndarray, rows_valid: int,
                     seq_id, layer, head, idx, which
                     ) -> Optional[KVPageFault]:
        """Verify one page; correct a localizable single-element fault or
        a corrupted checksum row in place. Returns the fault record (or
        None for a clean page)."""
        data = page[:self.page_size]
        c0 = data.sum(axis=0, dtype=np.float32)
        c1 = (self._w * data).sum(axis=0, dtype=np.float32)
        r0 = page[self.page_size] - c0
        r1 = page[self.page_size + 1] - c1
        tol = self.threshold
        bad0 = np.abs(r0) > tol
        bad1 = np.abs(r1) > tol
        self._counts["pages_verified"] += 1
        if not bad0.any() and not bad1.any():
            return None
        self._counts["faults_detected"] += 1
        residual = float(max(np.abs(r0).max(), np.abs(r1).max()))
        fault = dict(seq_id=int(seq_id), layer=int(layer), head=int(head),
                     page=int(idx), which=which, residual=residual)
        cols0 = np.flatnonzero(bad0)
        if cols0.size == 0 or (bad1 & ~bad0).any():
            # Plain row consistent but weighted row flags (or vice-versa
            # mixed): the CHECKSUM rows themselves took the hit — the
            # data still matches at least one independent sum, so the
            # cheap repair is to reseal from data.
            if cols0.size == 0:
                self._reseal(page)
                self._counts["checksum_rows_rebuilt"] += 1
                return KVPageFault(corrected=True, **fault)
        if cols0.size == 1 and not (bad1 & ~bad0).any():
            c = int(cols0[0])
            if abs(r0[c]) > 0 and bad1[c]:
                ratio = float(r1[c]) / float(r0[c])
                r = int(round(ratio)) - 1
                if (abs(ratio - round(ratio)) < 0.05
                        and 0 <= r < rows_valid):
                    # Single element located: subtract the delta the
                    # residual measures (stored - recomputed = -delta).
                    data[r, c] += r0[c]
                    self._reseal(page)
                    self._counts["corrected_in_place"] += 1
                    return KVPageFault(corrected=True, row=r, col=c,
                                       **fault)
            elif not bad1[c]:
                # Plain checksum row corrupted at one column, weighted
                # row agrees with data: rebuild the checksum rows.
                self._reseal(page)
                self._counts["checksum_rows_rebuilt"] += 1
                return KVPageFault(corrected=True, col=c, **fault)
        self._counts["uncorrectable"] += 1
        return KVPageFault(corrected=False, **fault)

    def read(self, seq_id: int, layer: int, head: int
             ) -> Tuple[np.ndarray, np.ndarray, List[KVPageFault]]:
        """Assemble the stream's full ``(K, V)`` matrices, verifying (and
        where possible repairing) every page on the way. Returns
        ``(K (n, head_dim), V (n, value_dim), faults)`` — ``faults``
        lists every page whose checksums flagged, corrected or not; a
        fault with ``corrected=False`` means the returned rows of that
        page are UNVERIFIED and the caller must restore + re-read."""
        with self._lock:
            key = (int(seq_id), int(layer), int(head))
            entry = self._streams.get(key)
            self._counts["reads"] += 1
            if entry is None:
                return (np.zeros((0, self.head_dim), np.float32),
                        np.zeros((0, self.value_dim), np.float32), [])
            faults: List[KVPageFault] = []
            outs = {}
            for which in ("k", "v"):
                stream = entry[which]
                parts = []
                for idx, page in enumerate(stream.pages):
                    valid = min(self.page_size,
                                stream.rows - idx * self.page_size)
                    if self.checksums:
                        f = self._verify_page(page, valid, seq_id, layer,
                                              head, idx, which)
                        if f is not None:
                            faults.append(f)
                    parts.append(page[:valid])
                outs[which] = (np.concatenate(parts, axis=0) if parts
                               else np.zeros((0, stream.width),
                                             np.float32))
            return outs["k"], outs["v"], faults

    # -- fault injection + recovery ------------------------------------------

    def corrupt(self, seq_id: int, layer: int, head: int, page: int, *,
                row: int = 0, cols=(0,), magnitude: float = 1000.0,
                which: str = "k", target: str = "data") -> None:
        """Self-test hook (the stored-state ``InjectionSpec``): add
        ``magnitude`` to the page's element(s) at ``(row, col)`` for each
        col in ``cols`` WITHOUT resealing — modeling an SDC that strikes
        memory after the write. ``target="checksum"`` corrupts the plain
        checksum row instead of data. One col = the correctable single-
        element case; several = the uncorrectable multi-column case."""
        with self._lock:
            stream = self._stream(seq_id, layer, head, which)
            if not 0 <= page < len(stream.pages):
                raise IndexError(
                    f"page {page} out of range ({len(stream.pages)} pages)")
            base = self.page_size if target == "checksum" else int(row)
            for col in cols:
                stream.pages[page][base, int(col)] += magnitude

    def restore(self, seq_id: int, layer: int, head: int, page: int,
                k_rows, v_rows) -> None:
        """Rewrite ONE page from authoritative source rows (the page's
        slice of the upstream K/V — re-materialized by the caller) and
        reseal its checksums: the recovery arm of the block engine's
        bounded page-scoped retry ladder."""
        k_rows = np.asarray(k_rows, np.float32)
        v_rows = np.asarray(v_rows, np.float32)
        with self._lock:
            for which, rows in (("k", k_rows), ("v", v_rows)):
                stream = self._stream(seq_id, layer, head, which)
                if not 0 <= page < len(stream.pages):
                    raise IndexError(
                        f"page {page} out of range "
                        f"({len(stream.pages)} pages)")
                fresh = self._new_page(stream.width)
                fresh[:rows.shape[0]] = rows
                self._reseal(fresh)
                stream.pages[page] = fresh
            self._counts["restores"] += 1

    def page_slice(self, page: int) -> slice:
        """The row range of ``page`` in the assembled stream — what the
        caller slices out of its authoritative copy to feed
        :meth:`restore`."""
        return slice(page * self.page_size, (page + 1) * self.page_size)

    # -- stats ----------------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            out = dict(self._counts)
            out["checksums"] = self.checksums
            out["page_size"] = self.page_size
            out["streams"] = len(self._streams)
            out["pages"] = sum(len(e[w].pages)
                               for e in self._streams.values()
                               for w in ("k", "v"))
            verified = out["pages_verified"]
            out["verify_hit_rate"] = (
                round(1.0 - out["faults_detected"] / verified, 6)
                if verified else None)
            return out


__all__ = ["CHECKSUM_ROWS", "DEFAULT_THRESHOLD", "KVPageFault",
           "PagedKVCache"]
