"""Multi-device serve dispatch: a health-steered device pool.

The single-device :class:`~ft_sgemm_tpu.serve.engine.ServeEngine` runs
every batch on the default device from one dispatcher thread, blocking
per request — the mesh sits idle while one chip works. This module makes
the MESH the unit of serving throughput:

- **Replicated executables.** Each (bucket, variant) executable is
  AOT-compiled once PER POOL DEVICE (``jax.ShapeDtypeStruct`` avals
  carrying a ``SingleDeviceSharding`` — the engine's
  ``_get_compiled(..., device=)`` does the compiling), so steady-state
  dispatch on any device never re-enters tracing and the
  zero-compile-span warm-path contract holds pool-wide.
- **Health-steered placement.** Each ready batch is placed on the
  healthiest least-loaded device: eligibility is
  ``DeviceHealthTracker.score >= drain_below x the fleet MEDIAN score``
  (relative on purpose — see :meth:`DevicePool._drain_floor`; sick
  devices are DRAINED — they finish what they hold but receive no new
  batches — unless every device falls through the floor, when refusing
  service would be worse than degraded service), and among eligible
  devices the one with the fewest queued+in-flight batches per unit of
  health wins. The tracker
  is normally the live monitor's (``Monitor.health`` — the same scores
  ``/healthz`` reports), so a device whose detection counters or
  residual drift degrade MID-RUN stops receiving traffic without any
  operator action; :meth:`DevicePool.mark_sick` injects synthetic
  uncorrectable counts for one device — the drain self-test knob, the
  serving analog of ``inject_coords``.
- **Bounded async in-flight.** Workers launch up to ``max_in_flight``
  requests' executables before materializing the first result, riding
  JAX's async dispatch instead of a synchronous per-request wait — on a
  real mesh the next request's host-side work (padding, bookkeeping)
  and the previous one's device compute overlap, and a retrying request
  (backoff sleep) never head-of-line-blocks the other devices' queues.

Observability: per-device ``serve_pool_queue_depth`` / ``serve_pool_in_
flight`` gauges and ``serve_pool_batches`` counters in the registry, and
a ``placement`` timeline point per batch carrying the batch's trace_ids,
the chosen device, and the policy — so the trace flow shows WHERE each
request ran, joined to the tile-level blame the engine already emits.

``PLACEMENTS`` is the runtime spelling of ``contracts.POOL_PLACEMENTS``
(the lint axis-drift pass cross-checks the two): ``"health"`` as above,
``"round_robin"`` ignores health (the A/B control).
"""

from __future__ import annotations

import collections
import itertools
import threading
from typing import Dict, List, Optional, Sequence

PLACEMENTS = ("health", "round_robin")


class DevicePool:
    """Placement + queueing state for multi-device serve dispatch.

    The pool owns WHERE work runs (device choice, per-device queues,
    health eligibility); the engine owns WHAT runs (executables, the
    retry ladder, futures). ``devices`` defaults to every local device;
    ``health`` is a :class:`~ft_sgemm_tpu.telemetry.monitor
    .DeviceHealthTracker` (the engine wires the monitor's in when one
    exists; a private tracker otherwise). ``drain_below`` is the
    eligibility threshold on the tracker's score; ``max_in_flight``
    bounds each worker's async launch window.
    """

    def __init__(self, devices: Optional[Sequence] = None, *,
                 placement: str = "health",
                 health=None,
                 drain_below: float = 0.5,
                 max_in_flight: int = 2):
        if placement not in PLACEMENTS:
            raise ValueError(
                f"DevicePool.placement={placement!r} must be one of"
                f" {PLACEMENTS}")
        if devices is None:
            import jax

            devices = jax.local_devices()
        if not devices:
            raise ValueError("DevicePool needs at least one device")
        if max_in_flight < 1:
            raise ValueError(
                f"max_in_flight={max_in_flight} must be >= 1")
        self.devices = tuple(devices)
        self.labels = tuple(str(d) for d in self.devices)
        self.placement = placement
        self.drain_below = float(drain_below)
        self.max_in_flight = int(max_in_flight)
        if health is None and placement == "health":
            from ft_sgemm_tpu.telemetry.monitor import DeviceHealthTracker

            health = DeviceHealthTracker()
        self.health = health

        self._lock = threading.Lock()
        self._queues: Dict[int, collections.deque] = {
            i: collections.deque() for i in range(len(self.devices))}
        self._in_flight = {i: 0 for i in range(len(self.devices))}
        self._batches = {i: 0 for i in range(len(self.devices))}
        self._requests = {i: 0 for i in range(len(self.devices))}
        self._rr = itertools.cycle(range(len(self.devices)))
        self._work = threading.Condition(self._lock)
        self._stop = False
        self._evicted: set = set()

    # -- health ------------------------------------------------------------

    def score(self, index: int) -> float:
        if self.health is None:
            return 1.0
        return float(self.health.score(self.labels[index]))

    def mark_sick(self, index: int, *, calls: int = 100,
                  uncorrectable: Optional[int] = None) -> str:
        """Feed synthetic uncorrectable counts for one device into the
        health tracker — the drain SELF-TEST knob (the serving analog of
        ``inject_coords``): the marked device's score collapses below
        any sane ``drain_below`` and placement must route around it.
        Returns the device label marked."""
        if self.health is None:
            raise ValueError("mark_sick needs a health tracker"
                             " (placement='health')")
        unc = calls if uncorrectable is None else uncorrectable
        self.health.observe(self.labels[index], calls=calls,
                            detected=unc, uncorrectable=unc)
        return self.labels[index]

    # -- eviction (resilience/elastic.py) ----------------------------------

    @property
    def evicted(self) -> frozenset:
        """Indices of evicted devices (never placed on again)."""
        with self._lock:
            return frozenset(self._evicted)

    def evict(self, index: int) -> list:
        """Remove one device from placement PERMANENTLY and hand its
        queued, unexecuted batches back for migration.

        Eviction is strictly stronger than the drain floor: a drained
        device can be re-admitted when the fleet median moves (and still
        serves as the degraded-service fallback when every device is
        below the floor); an evicted device is never a candidate again
        and its queue is emptied NOW — the caller (``ServeEngine
        .evict_device``) re-places the returned items on the survivors.
        Refuses to evict the last live device. Idempotent: a second
        eviction of the same index returns [].
        """
        with self._lock:
            if index in self._evicted:
                return []
            survivors = [i for i in range(len(self.devices))
                         if i != index and i not in self._evicted]
            if not survivors:
                raise RuntimeError(
                    "DevicePool.evict would remove the last live device"
                    f" ({self.labels[index]}) — refusing")
            self._evicted.add(index)
            leftovers = list(self._queues[index])
            self._queues[index].clear()
            self._work.notify_all()
            return leftovers

    def _drain_floor(self, scores: List[float]) -> float:
        """The eligibility floor for one score snapshot:
        ``drain_below`` x the fleet MEDIAN. Relative, not absolute, on
        purpose: the tracker's score compounds detection rates, so a
        uniformly-injected load (every device correcting SDCs at the
        same rate) depresses every score together — an absolute floor
        would then drain the whole fleet, and refusing all service over
        corrected (i.e. FREE) faults is exactly the economics the paper
        rejects. A device an order of magnitude sicker than its peers —
        uncorrectables, drift — falls through the relative floor no
        matter where the fleet baseline sits."""
        med = sorted(scores)[len(scores) // 2]
        return self.drain_below * max(med, 1e-9)

    def eligible(self) -> List[int]:
        """Devices placement may use: non-evicted ones at or above the
        relative drain floor; every non-evicted device when none clears
        it (degraded service beats refused service — but an EVICTED
        device is out even then)."""
        with self._lock:
            idx = [i for i in range(len(self.devices))
                   if i not in self._evicted]
        if self.placement != "health" or self.health is None:
            return idx
        scores = [self.score(i) for i in idx]
        floor = self._drain_floor(scores)
        ok = [i for i, s in zip(idx, scores) if s >= floor]
        return ok or idx

    # -- placement + queues ------------------------------------------------

    def choose(self) -> int:
        """Pick the device for one ready batch (called under no lock;
        takes the pool lock briefly). Health policy: among eligible
        devices, least (queued + in-flight) per unit of score."""
        if self.placement == "round_robin":
            with self._lock:
                for _ in range(len(self.devices)):
                    i = next(self._rr)
                    if i not in self._evicted:
                        return i
                raise RuntimeError("every pool device is evicted")
        cand = self.eligible()
        if not cand:
            raise RuntimeError("every pool device is evicted")
        with self._lock:
            return min(cand, key=lambda i: (
                (len(self._queues[i]) + self._in_flight[i] + 1)
                / max(self.score(i), 1e-6), i))

    def put(self, index: int, item) -> int:
        """Enqueue one placed batch for ``index``'s worker; returns the
        device's new queue depth."""
        with self._lock:
            self._queues[index].append(item)
            depth = len(self._queues[index])
            self._work.notify_all()
        return depth

    def get(self, index: int, timeout: float = 0.1):
        """Worker side: pop the next batch for device ``index`` (None on
        timeout/stop)."""
        with self._lock:
            if not self._queues[index] and not self._stop:
                self._work.wait(timeout)
            if self._queues[index]:
                return self._queues[index].popleft()
            return None

    def stop(self) -> list:
        """Flag workers to exit and return every unexecuted queued item
        (the engine rejects their futures — a closed pool must not
        strand waiters)."""
        leftovers = []
        with self._lock:
            self._stop = True
            for q in self._queues.values():
                leftovers.extend(q)
                q.clear()
            self._work.notify_all()
        return leftovers

    @property
    def stopped(self) -> bool:
        return self._stop

    # -- accounting --------------------------------------------------------

    def note_batch(self, index: int, n_requests: int) -> None:
        with self._lock:
            self._batches[index] += 1
            self._requests[index] += n_requests

    def adjust_in_flight(self, index: int, delta: int) -> int:
        with self._lock:
            self._in_flight[index] += delta
            return self._in_flight[index]

    def queue_depth(self, index: int) -> int:
        with self._lock:
            return len(self._queues[index])

    def active_devices(self) -> int:
        """Devices still eligible for placement (total minus evicted) —
        the denominator the cost plane uses so per-device throughput
        reflects the fleet that is actually serving."""
        with self._lock:
            return max(len(self.devices) - len(self._evicted), 1)

    def stats(self) -> dict:
        """Per-device placement snapshot + the drain picture."""
        with self._lock:
            rows = {
                self.labels[i]: {
                    "batches": self._batches[i],
                    "requests": self._requests[i],
                    "queued": len(self._queues[i]),
                    "in_flight": self._in_flight[i],
                }
                for i in range(len(self.devices))
            }
        scores = [self.score(i) for i in range(len(self.devices))]
        for i, label in enumerate(self.labels):
            rows[label]["health"] = round(scores[i], 6)
        used = sum(1 for r in rows.values() if r["batches"] > 0)
        drained = []
        if self.placement == "health" and self.health is not None:
            floor = self._drain_floor(scores)
            drained = [label for i, label in enumerate(self.labels)
                       if scores[i] < floor]
        with self._lock:
            evicted = [self.labels[i] for i in sorted(self._evicted)]
        return {"devices": len(self.devices), "devices_used": used,
                "placement": self.placement,
                "drain_below": self.drain_below,
                "max_in_flight": self.max_in_flight,
                "drained": drained, "evicted": evicted,
                "per_device": rows}


__all__ = ["DevicePool", "PLACEMENTS"]
