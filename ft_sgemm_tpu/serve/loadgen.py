"""Load generator + goodput bench for the serving layer.

Drives a :class:`~ft_sgemm_tpu.serve.engine.ServeEngine` with a
configurable arrival process — ragged shapes, a request rate (Poisson
inter-arrivals; 0 = open loop), and per-request SDC injection at a
configurable rate — and reports the serving numbers that matter:

- **p50 / p99 latency** — straight from the engine's
  ``serve_latency_seconds`` registry histogram
  (``telemetry.registry.histogram_percentiles``), no second stats path.
- **throughput** — completed requests per second of drive wall.
- **goodput-under-injection** — CORRECT results per second: the paper's
  claim made measurable. A detected-and-corrected SDC costs zero retries,
  so goodput under a nonzero injection rate should track clean throughput;
  every uncorrectable costs exactly one bucket-scoped retry.

``verify=True`` checks every result against the XLA oracle
(``sgemm_reference`` at the request's true shape), so "correct" means
numerically verified, not merely "no fault reported".

The bench core (:func:`run_serve_bench`) is shared by ``bench.py
--serve`` and ``cli serve-bench``; progress streams as timeline points
(``serve_progress``) so a deadline-killed run leaves partial stats on
disk for the supervisor/reader — the PR-5 kill-safety discipline.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Optional, Sequence, Tuple

import numpy as np

from ft_sgemm_tpu.serve.buckets import (
    BucketOverflowError,
    default_bucket_set,
    select_bucket,
)
from ft_sgemm_tpu.serve.engine import ServeEngine, ServeRequest


@dataclasses.dataclass(frozen=True)
class LoadSpec:
    """One load-generation scenario.

    ``shapes`` is the ragged (m, n, k) menu requests sample from —
    deliberately NOT bucket-aligned, so padding is exercised.
    ``inject_rate`` / ``adversarial_rate`` are per-request probabilities
    of the correctable / uncorrectable injection variants (adversarial
    requests are routed to buckets deep enough to express the failure —
    see the engine's variant notes — and downgrade to "inject"
    otherwise). ``rate`` is mean request arrivals per second (Poisson);
    0 submits as fast as the queue accepts.
    """

    num_requests: int = 64
    rate: float = 0.0
    shapes: Tuple[Tuple[int, int, int], ...] = (
        (96, 120, 100), (128, 128, 128), (200, 180, 160),
        (250, 140, 250), (256, 256, 256))
    in_dtype: str = "float32"
    inject_rate: float = 0.0
    adversarial_rate: float = 0.0
    seed: int = 10
    verify: bool = False
    result_timeout: float = 300.0


def smoke_spec() -> LoadSpec:
    """The CPU-runnable CI scenario: a couple dozen ragged requests, a
    quarter of them carrying correctable SDCs, a handful adversarial —
    enough traffic to pin goodput > 0, zero whole-queue retries, and a
    populated latency histogram in about a minute of interpret mode."""
    return LoadSpec(num_requests=18, inject_rate=0.25,
                    adversarial_rate=0.12, verify=True)


def _gen_request(rng, spec: LoadSpec, buckets) -> ServeRequest:
    m, n, k = spec.shapes[int(rng.integers(len(spec.shapes)))]
    a = rng.standard_normal((m, k)).astype(np.float32)
    b = rng.standard_normal((n, k)).astype(np.float32)
    if spec.in_dtype == "int8":
        # The integer lattice the exact path expects (the CLI's
        # quantization convention).
        a = np.round(a * 3.0)
        b = np.round(b * 3.0)
    u = float(rng.random())
    variant = "clean"
    if u < spec.adversarial_rate:
        variant = "adversarial"
        try:
            bucket = select_bucket(buckets, m, n, k, in_dtype=spec.in_dtype)
            if bucket.k < 256:
                # Too shallow for a same-column multi-fault interval:
                # the schedule would be corrected, not uncorrectable.
                variant = "inject"
        except BucketOverflowError:
            pass  # submit() will reject it either way
    elif u < spec.adversarial_rate + spec.inject_rate:
        variant = "inject"
    return ServeRequest(a=a, b=b, in_dtype=spec.in_dtype, variant=variant)


def run_load(engine: ServeEngine, spec: LoadSpec, *,
             should_stop: Optional[Callable[[], bool]] = None,
             progress: Optional[Callable[[dict], None]] = None) -> dict:
    """Drive one load scenario to completion (or early stop) and return
    the serving stats dict.

    ``should_stop`` (checked between arrivals) ends submission early —
    already-submitted requests still drain and the stats are marked
    ``partial`` — the hook ``bench.py --serve`` wires to SIGTERM so a
    deadline-killed run emits what it measured instead of nothing.
    """
    rng = np.random.default_rng(spec.seed)
    t0 = time.monotonic()
    submitted = []
    rejected = 0
    partial = False
    for i in range(spec.num_requests):
        if should_stop is not None and should_stop():
            partial = True
            break
        req = _gen_request(rng, spec, engine.buckets)
        try:
            fut = engine.submit(req)
        except BucketOverflowError:
            rejected += 1
            continue
        submitted.append((req, fut))
        if progress is not None and (i + 1) % 8 == 0:
            progress({"submitted": i + 1})
        if spec.rate > 0:
            time.sleep(float(rng.exponential(1.0 / spec.rate)))
    engine.drain(timeout=spec.result_timeout)
    wall = time.monotonic() - t0

    completed = correct = corrected = uncorrectable_final = 0
    retries = 0
    verify_failures = 0
    variant_counts: dict = {}
    for req, fut in submitted:
        res = fut.result(timeout=spec.result_timeout)
        completed += 1
        retries += res.retries
        variant_counts[req.variant] = variant_counts.get(req.variant, 0) + 1
        if res.corrected:
            corrected += 1
        if not res.ok:
            uncorrectable_final += 1
            continue
        if spec.verify:
            from ft_sgemm_tpu.ops.reference import sgemm_reference
            from ft_sgemm_tpu.utils.matrices import verify_matrix

            m, n, _ = req.mnk
            want = np.asarray(sgemm_reference(
                req.a, req.b, np.zeros((m, n), np.float32),
                engine.alpha, engine.beta, in_dtype=req.in_dtype))
            ok, _, _ = verify_matrix(want, res.c, verbose=False)
            if not ok:
                verify_failures += 1
                continue
        correct += 1

    eng = engine.stats()
    lat = eng["latency"]
    stats = {
        "requests_submitted": len(submitted),
        "requests_rejected": rejected,
        "completed": completed,
        "correct": correct,
        "corrected_free": corrected,
        "uncorrectable_final": uncorrectable_final,
        "verify_failures": verify_failures,
        "verified": bool(spec.verify),
        "retries": retries,
        "bucket_retries": eng["retries"],
        "whole_queue_retries": eng["whole_queue_retries"],
        "batches": eng["batches"],
        "variants": variant_counts,
        "inject_rate": spec.inject_rate,
        "adversarial_rate": spec.adversarial_rate,
        "wall_seconds": round(wall, 3),
        "throughput_rps": round(completed / wall, 3) if wall > 0 else None,
        "goodput_rps": round(correct / wall, 3) if wall > 0 else None,
        "p50_latency_seconds": lat.get("p50"),
        "p99_latency_seconds": lat.get("p99"),
        "max_latency_seconds": lat.get("max"),
        "per_bucket": eng["per_bucket"],
    }
    if partial:
        stats["partial"] = True
    return stats


def run_serve_bench(*, smoke: bool = False,
                    bucket_sizes: Optional[Sequence[int]] = None,
                    in_dtype: str = "float32",
                    num_requests: Optional[int] = None,
                    inject_rate: Optional[float] = None,
                    adversarial_rate: Optional[float] = None,
                    rate: Optional[float] = None,
                    max_batch: int = 4, max_wait: float = 0.05,
                    verify: Optional[bool] = None,
                    timeline=None,
                    should_stop: Optional[Callable[[], bool]] = None,
                    progress_out=None,
                    monitor="auto", monitor_port: Optional[int] = None,
                    slo=None) -> dict:
    """The serve-bench core shared by ``bench.py --serve`` and
    ``cli serve-bench``: build the bucket set, prewarm it (AOT compile,
    recorded as compile spans), drive the load, and return the artifact
    context dict — p50/p99 latency, throughput, goodput-under-injection,
    retry/fault counters, bucket set, prewarm cost, and the final
    SLO/health snapshot (``slo`` / ``device_health`` keys).

    ``smoke`` selects the CI scenario (tiny buckets + :func:`smoke_spec`,
    verification on). Explicit keyword args override either profile's
    defaults.

    Monitoring: ``monitor="auto"`` (default) builds a live
    :class:`~ft_sgemm_tpu.telemetry.monitor.Monitor` (SLO error budget +
    device-health scoring; pass ``slo=SloConfig(...)`` to tighten the
    objectives) so every run's artifact carries the SLO section; pass an
    existing Monitor to share one, or ``monitor=None`` to run bare.
    ``monitor_port`` additionally starts the HTTP exporter
    (``/metrics`` / ``/healthz`` / ``/events``; 0 = ephemeral — the
    resolved URL streams as a ``serve_progress`` point and lands in the
    stats as ``monitor_url``) for the duration of the bench.
    """
    sizes = tuple(bucket_sizes) if bucket_sizes else (
        (128, 256) if smoke else (256, 512, 1024))
    buckets = default_bucket_set(sizes, in_dtype=in_dtype)
    base = smoke_spec() if smoke else LoadSpec(
        inject_rate=0.2, adversarial_rate=0.05, verify=False)
    spec = dataclasses.replace(
        base,
        in_dtype=in_dtype,
        num_requests=base.num_requests if num_requests is None
        else int(num_requests),
        inject_rate=base.inject_rate if inject_rate is None
        else float(inject_rate),
        adversarial_rate=base.adversarial_rate if adversarial_rate is None
        else float(adversarial_rate),
        rate=base.rate if rate is None else float(rate),
        verify=base.verify if verify is None else bool(verify),
    )
    # Keep every shape routable inside the configured set.
    largest = max(s for s in sizes)
    shapes = tuple(s for s in spec.shapes if max(s) <= largest)
    spec = dataclasses.replace(spec, shapes=shapes or ((largest // 2,) * 3,))

    def progress(p):
        if timeline is not None:
            timeline.point("serve_progress", "load", **p)
        if progress_out is not None:
            print(f"serve-bench: {p}", file=progress_out, flush=True)

    mon = None
    mon_server = None
    if monitor == "auto":
        from ft_sgemm_tpu.telemetry.monitor import Monitor

        mon = Monitor(slo=slo)
    elif monitor is not None:
        mon = monitor
    if mon is not None:
        mon.attach()
        if monitor_port is not None:
            from ft_sgemm_tpu.telemetry.monitor import MonitorServer

            mon_server = MonitorServer(mon, port=monitor_port).start()
            progress({"monitor_url": mon_server.url})
    try:
        with ServeEngine(buckets, max_batch=max_batch, max_wait=max_wait,
                         timeline=timeline, monitor=mon) as engine:
            t0 = time.monotonic()
            prewarm = engine.prewarm()
            progress({"prewarmed": prewarm["compiled"],
                      "seconds": prewarm["seconds"]})
            stats = run_load(engine, spec, should_stop=should_stop,
                             progress=progress)
            stats["prewarm"] = prewarm
            stats["buckets"] = [b.key for b in buckets]
            stats["smoke"] = bool(smoke)
            stats["seconds_total"] = round(time.monotonic() - t0, 3)
        if mon is not None:
            # The final SLO/budget + health snapshot the artifact embeds
            # (and RunReport's "SLO" section renders).
            stats["slo"] = mon.snapshot()
            stats["device_health"] = stats["slo"]["device_health"]
            if mon_server is not None:
                stats["monitor_url"] = mon_server.url
    finally:
        if mon_server is not None:
            mon_server.close()
        if mon is not None:
            mon.detach()
    return stats


__all__ = ["LoadSpec", "run_load", "run_serve_bench", "smoke_spec"]
