"""Load generator + goodput bench for the serving layer.

Drives a :class:`~ft_sgemm_tpu.serve.engine.ServeEngine` with a
configurable arrival process — ragged shapes, a request rate (Poisson
inter-arrivals; 0 = open loop), and per-request SDC injection at a
configurable rate — and reports the serving numbers that matter:

- **p50 / p99 latency** — straight from the engine's
  ``serve_latency_seconds`` registry histogram
  (``telemetry.registry.histogram_percentiles``), no second stats path.
- **throughput** — completed requests per second of drive wall.
- **goodput-under-injection** — CORRECT results per second: the paper's
  claim made measurable. A detected-and-corrected SDC costs zero retries,
  so goodput under a nonzero injection rate should track clean throughput;
  every uncorrectable costs exactly one bucket-scoped retry.

``verify=True`` checks every result against the XLA oracle
(``sgemm_reference`` at the request's true shape), so "correct" means
numerically verified, not merely "no fault reported".

The bench core (:func:`run_serve_bench`) is shared by ``bench.py
--serve`` and ``cli serve-bench``; progress streams as timeline points
(``serve_progress``) so a deadline-killed run leaves partial stats on
disk for the supervisor/reader — the PR-5 kill-safety discipline.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Optional, Sequence, Tuple

import numpy as np

from ft_sgemm_tpu.serve.buckets import (
    BucketOverflowError,
    default_bucket_set,
    select_bucket,
)
from ft_sgemm_tpu.serve.engine import ServeEngine, ServeRequest


@dataclasses.dataclass(frozen=True)
class LoadSpec:
    """One load-generation scenario.

    ``shapes`` is the ragged (m, n, k) menu requests sample from —
    deliberately NOT bucket-aligned, so padding is exercised.
    ``inject_rate`` / ``adversarial_rate`` are per-request probabilities
    of the correctable / uncorrectable injection variants (adversarial
    requests are routed to buckets deep enough to express the failure —
    see the engine's variant notes — and downgrade to "inject"
    otherwise). ``rate`` is mean request arrivals per second (Poisson);
    0 submits as fast as the queue accepts.
    """

    num_requests: int = 64
    rate: float = 0.0
    shapes: Tuple[Tuple[int, int, int], ...] = (
        (96, 120, 100), (128, 128, 128), (200, 180, 160),
        (250, 140, 250), (256, 256, 256))
    in_dtype: str = "float32"
    inject_rate: float = 0.0
    adversarial_rate: float = 0.0
    seed: int = 10
    verify: bool = False
    result_timeout: float = 300.0
    # Fused-epilogue spelling the bucket set serves (Bucket.epilogue);
    # a bias-fusing epilogue makes every generated request carry its own
    # bias vector, and verification composes the epilogue oracle
    # (ops.reference.epilogue_reference) over the GEMM oracle.
    epilogue: str = "none"


def smoke_spec() -> LoadSpec:
    """The CPU-runnable CI scenario: a couple dozen ragged requests, a
    quarter of them carrying correctable SDCs, a handful adversarial —
    enough traffic to pin goodput > 0, zero whole-queue retries, and a
    populated latency histogram in about a minute of interpret mode."""
    return LoadSpec(num_requests=18, inject_rate=0.25,
                    adversarial_rate=0.12, verify=True)


def _gen_request(rng, spec: LoadSpec, buckets) -> ServeRequest:
    m, n, k = spec.shapes[int(rng.integers(len(spec.shapes)))]
    a = rng.standard_normal((m, k)).astype(np.float32)
    b = rng.standard_normal((n, k)).astype(np.float32)
    if spec.in_dtype == "int8":
        # The integer lattice the exact path expects (the CLI's
        # quantization convention).
        a = np.round(a * 3.0)
        b = np.round(b * 3.0)
    u = float(rng.random())
    variant = "clean"
    if u < spec.adversarial_rate:
        variant = "adversarial"
        try:
            bucket = select_bucket(buckets, m, n, k, in_dtype=spec.in_dtype)
            if bucket.k < 256:
                # Too shallow for a same-column multi-fault interval:
                # the schedule would be corrected, not uncorrectable.
                variant = "inject"
        except BucketOverflowError:
            pass  # submit() will reject it either way
    elif u < spec.adversarial_rate + spec.inject_rate:
        variant = "inject"
    bias = None
    from ft_sgemm_tpu.configs import EpilogueSpec

    if EpilogueSpec.parse(spec.epilogue).bias:
        bias = rng.standard_normal((n,)).astype(np.float32)
    return ServeRequest(a=a, b=b, in_dtype=spec.in_dtype, variant=variant,
                        bias=bias)


def run_load(engine: ServeEngine, spec: LoadSpec, *,
             should_stop: Optional[Callable[[], bool]] = None,
             progress: Optional[Callable[[dict], None]] = None) -> dict:
    """Drive one load scenario to completion (or early stop) and return
    the serving stats dict.

    ``should_stop`` (checked between arrivals) ends submission early —
    already-submitted requests still drain and the stats are marked
    ``partial`` — the hook ``bench.py --serve`` wires to SIGTERM so a
    deadline-killed run emits what it measured instead of nothing.
    """
    rng = np.random.default_rng(spec.seed)
    t0 = time.monotonic()
    submitted = []
    rejected = 0
    partial = False
    for i in range(spec.num_requests):
        if should_stop is not None and should_stop():
            partial = True
            break
        req = _gen_request(rng, spec, engine.buckets)
        try:
            fut = engine.submit(req)
        except BucketOverflowError:
            rejected += 1
            continue
        submitted.append((req, fut))
        if progress is not None and (i + 1) % 8 == 0:
            progress({"submitted": i + 1})
        if spec.rate > 0:
            time.sleep(float(rng.exponential(1.0 / spec.rate)))
    engine.drain(timeout=spec.result_timeout)
    wall = time.monotonic() - t0

    completed = correct = corrected = uncorrectable_final = 0
    retries = 0
    verify_failures = 0
    variant_counts: dict = {}
    for req, fut in submitted:
        res = fut.result(timeout=spec.result_timeout)
        completed += 1
        retries += res.retries
        variant_counts[req.variant] = variant_counts.get(req.variant, 0) + 1
        if res.corrected:
            corrected += 1
        if not res.ok:
            uncorrectable_final += 1
            continue
        if spec.verify:
            from ft_sgemm_tpu.ops.reference import (
                epilogue_reference,
                sgemm_reference,
            )
            from ft_sgemm_tpu.utils.matrices import verify_matrix

            m, n, _ = req.mnk
            want = np.asarray(sgemm_reference(
                req.a, req.b, np.zeros((m, n), np.float32),
                engine.alpha, engine.beta, in_dtype=req.in_dtype))
            if spec.epilogue != "none":
                # The oracle composes the SAME epilogue the bucket
                # fuses: goodput counts results correct THROUGH the
                # fused bias/activation/quantize, not just the GEMM.
                want = epilogue_reference(want, spec.epilogue, req.bias)
            ok, _, _ = verify_matrix(want, res.c, verbose=False)
            if not ok:
                verify_failures += 1
                continue
        correct += 1

    eng = engine.stats()
    lat = eng["latency"]
    stats = {
        "requests_submitted": len(submitted),
        "requests_rejected": rejected,
        "completed": completed,
        "correct": correct,
        "corrected_free": corrected,
        "uncorrectable_final": uncorrectable_final,
        "verify_failures": verify_failures,
        "verified": bool(spec.verify),
        "retries": retries,
        "bucket_retries": eng["retries"],
        "whole_queue_retries": eng["whole_queue_retries"],
        "batches": eng["batches"],
        "variants": variant_counts,
        "inject_rate": spec.inject_rate,
        "adversarial_rate": spec.adversarial_rate,
        "wall_seconds": round(wall, 3),
        "throughput_rps": round(completed / wall, 3) if wall > 0 else None,
        "goodput_rps": round(correct / wall, 3) if wall > 0 else None,
        "p50_latency_seconds": lat.get("p50"),
        "p99_latency_seconds": lat.get("p99"),
        "max_latency_seconds": lat.get("max"),
        "per_bucket": eng["per_bucket"],
    }
    if partial:
        stats["partial"] = True
    return stats


def run_serve_bench(*, smoke: bool = False,
                    bucket_sizes: Optional[Sequence[int]] = None,
                    in_dtype: str = "float32",
                    num_requests: Optional[int] = None,
                    inject_rate: Optional[float] = None,
                    adversarial_rate: Optional[float] = None,
                    rate: Optional[float] = None,
                    max_batch: int = 4, max_wait: float = 0.05,
                    verify: Optional[bool] = None,
                    timeline=None,
                    should_stop: Optional[Callable[[], bool]] = None,
                    progress_out=None,
                    monitor="auto", monitor_port: Optional[int] = None,
                    slo=None,
                    epilogue: str = "none") -> dict:
    """The serve-bench core shared by ``bench.py --serve`` and
    ``cli serve-bench``: build the bucket set, prewarm it (AOT compile,
    recorded as compile spans), drive the load, and return the artifact
    context dict — p50/p99 latency, throughput, goodput-under-injection,
    retry/fault counters, bucket set, prewarm cost, and the final
    SLO/health snapshot (``slo`` / ``device_health`` keys).

    ``smoke`` selects the CI scenario (tiny buckets + :func:`smoke_spec`,
    verification on). Explicit keyword args override either profile's
    defaults.

    Monitoring: ``monitor="auto"`` (default) builds a live
    :class:`~ft_sgemm_tpu.telemetry.monitor.Monitor` (SLO error budget +
    device-health scoring; pass ``slo=SloConfig(...)`` to tighten the
    objectives) so every run's artifact carries the SLO section; pass an
    existing Monitor to share one, or ``monitor=None`` to run bare.
    ``monitor_port`` additionally starts the HTTP exporter
    (``/metrics`` / ``/healthz`` / ``/events``; 0 = ephemeral — the
    resolved URL streams as a ``serve_progress`` point and lands in the
    stats as ``monitor_url``) for the duration of the bench.
    """
    sizes = tuple(bucket_sizes) if bucket_sizes else (
        (128, 256) if smoke else (256, 512, 1024))
    buckets = default_bucket_set(sizes, in_dtype=in_dtype,
                                 epilogue=epilogue)
    base = smoke_spec() if smoke else LoadSpec(
        inject_rate=0.2, adversarial_rate=0.05, verify=False)
    spec = dataclasses.replace(
        base,
        in_dtype=in_dtype,
        epilogue=buckets[0].epilogue,
        num_requests=base.num_requests if num_requests is None
        else int(num_requests),
        inject_rate=base.inject_rate if inject_rate is None
        else float(inject_rate),
        adversarial_rate=base.adversarial_rate if adversarial_rate is None
        else float(adversarial_rate),
        rate=base.rate if rate is None else float(rate),
        verify=base.verify if verify is None else bool(verify),
    )
    # Keep every shape routable inside the configured set.
    largest = max(s for s in sizes)
    shapes = tuple(s for s in spec.shapes if max(s) <= largest)
    spec = dataclasses.replace(spec, shapes=shapes or ((largest // 2,) * 3,))

    def progress(p):
        if timeline is not None:
            timeline.point("serve_progress", "load", **p)
        if progress_out is not None:
            print(f"serve-bench: {p}", file=progress_out, flush=True)

    mon = None
    mon_server = None
    if monitor == "auto":
        from ft_sgemm_tpu.telemetry.monitor import Monitor

        mon = Monitor(slo=slo)
    elif monitor is not None:
        mon = monitor
    if mon is not None:
        mon.attach()
        if monitor_port is not None:
            from ft_sgemm_tpu.telemetry.monitor import MonitorServer

            mon_server = MonitorServer(mon, port=monitor_port).start()
            progress({"monitor_url": mon_server.url})
    try:
        with ServeEngine(buckets, max_batch=max_batch, max_wait=max_wait,
                         timeline=timeline, monitor=mon) as engine:
            t0 = time.monotonic()
            prewarm = engine.prewarm()
            progress({"prewarmed": prewarm["compiled"],
                      "seconds": prewarm["seconds"]})
            stats = run_load(engine, spec, should_stop=should_stop,
                             progress=progress)
            stats["prewarm"] = prewarm
            stats["buckets"] = [b.key for b in buckets]
            stats["smoke"] = bool(smoke)
            stats["epilogue"] = buckets[0].epilogue
            stats["seconds_total"] = round(time.monotonic() - t0, 3)
        if mon is not None:
            # The final SLO/budget + health snapshot the artifact embeds
            # (and RunReport's "SLO" section renders).
            stats["slo"] = mon.snapshot()
            stats["device_health"] = stats["slo"]["device_health"]
            if mon_server is not None:
                stats["monitor_url"] = mon_server.url
    finally:
        if mon_server is not None:
            mon_server.close()
        if mon is not None:
            mon.detach()
    return stats


# ---------------------------------------------------------------------------
# Multi-device pool workload: goodput scaling + health-steered draining
# ---------------------------------------------------------------------------


def pool_smoke_spec() -> LoadSpec:
    """The 8-vdev pool CI scenario: a few dozen ragged requests with
    enough adversarial (uncorrectable -> retry-ladder) traffic that the
    retry backoff stalls are a real fraction of the wall — the
    head-of-line blocking the pool's per-device workers remove — plus
    correctable SDCs and full verification."""
    return LoadSpec(num_requests=28, inject_rate=0.2,
                    adversarial_rate=0.25, verify=True)


def run_pool_serve_bench(*, smoke: bool = False,
                         bucket_sizes: Optional[Sequence[int]] = None,
                         in_dtype: str = "float32",
                         num_requests: Optional[int] = None,
                         inject_rate: Optional[float] = None,
                         adversarial_rate: Optional[float] = None,
                         rate: Optional[float] = None,
                         max_batch: int = 2, max_wait: float = 0.05,
                         verify: Optional[bool] = None,
                         devices=None,
                         placement: str = "health",
                         sick_device: Optional[int] = 1,
                         drain_below: float = 0.5,
                         max_in_flight: int = 2,
                         retry_backoff: float = 0.2,
                         timeline=None,
                         should_stop: Optional[Callable[[], bool]] = None,
                         progress_out=None,
                         monitor="auto", monitor_port: Optional[int] = None,
                         slo=None,
                         epilogue: str = "none") -> dict:
    """``bench.py --serve --pool``: the SAME load through the
    single-device engine and the device-pool engine, reporting goodput
    scaling.

    Two stages, identical :class:`LoadSpec` (same seed — identical
    request streams) and identical retry config:

    1. **single** — the historical one-device engine (the control).
    2. **pool** — a :class:`~ft_sgemm_tpu.serve.pool.DevicePool` over
       ``devices`` (default: every local device), health-steered
       placement sharing the live monitor's tracker, bounded async
       in-flight per device worker.

    ``sick_device`` (default 1; ``None`` disables) marks that pool
    device sick BEFORE the load (``DevicePool.mark_sick`` — synthetic
    uncorrectable counts, the drain self-test the same way
    ``inject_coords`` is the attribution self-test): the acceptance
    facts are placement spread over >1 device, ZERO batches on the
    marked device, and goodput intact without it.

    ``retry_backoff`` (applied to BOTH engines — the comparison stays
    apples-to-apples) models the transient-SDC cool-down before an
    uncorrectable request's clean re-run. On the single-device engine
    every backoff stalls the one dispatch thread — head-of-line
    blocking for every bucket; the pool overlaps the stalls across
    device workers (and, on multi-core/TPU hosts, overlaps the compute
    itself), which is where the throughput scaling comes from.

    Per-engine stats are isolated in private registries so the two
    stages' latency histograms never mix. Returns the pool stats dict
    with ``single`` (the control's numbers), ``scaling``
    (pool/single throughput + goodput ratios), and ``pool`` (per-device
    placement, drained list) sections.
    """
    from ft_sgemm_tpu.serve.pool import DevicePool
    from ft_sgemm_tpu.telemetry.registry import MetricsRegistry

    sizes = tuple(bucket_sizes) if bucket_sizes else (
        (128, 256) if smoke else (256, 512, 1024))
    buckets = default_bucket_set(sizes, in_dtype=in_dtype,
                                 epilogue=epilogue)
    base = pool_smoke_spec() if smoke else LoadSpec(
        num_requests=64, inject_rate=0.2, adversarial_rate=0.1,
        verify=False)
    spec = dataclasses.replace(
        base,
        in_dtype=in_dtype,
        epilogue=buckets[0].epilogue,
        num_requests=base.num_requests if num_requests is None
        else int(num_requests),
        inject_rate=base.inject_rate if inject_rate is None
        else float(inject_rate),
        adversarial_rate=base.adversarial_rate if adversarial_rate is None
        else float(adversarial_rate),
        rate=base.rate if rate is None else float(rate),
        verify=base.verify if verify is None else bool(verify),
    )
    largest = max(s for s in sizes)
    shapes = tuple(s for s in spec.shapes if max(s) <= largest)
    spec = dataclasses.replace(spec, shapes=shapes or ((largest // 2,) * 3,))

    def progress(p):
        if timeline is not None:
            timeline.point("serve_progress", "load", **p)
        if progress_out is not None:
            print(f"serve-pool-bench: {p}", file=progress_out, flush=True)

    if devices is None:
        import jax

        devices = jax.local_devices()
    mon = None
    mon_server = None
    if monitor == "auto":
        from ft_sgemm_tpu.telemetry.monitor import Monitor

        mon = Monitor(slo=slo)
    elif monitor is not None:
        mon = monitor
    if mon is not None:
        mon.attach()
        if monitor_port is not None:
            from ft_sgemm_tpu.telemetry.monitor import MonitorServer

            mon_server = MonitorServer(mon, port=monitor_port).start()
            progress({"monitor_url": mon_server.url})
    try:
        t0 = time.monotonic()
        # Stage 1: the single-device control. Private registry so its
        # latency histogram never bleeds into the pool stage's.
        with ServeEngine(buckets, max_batch=max_batch, max_wait=max_wait,
                         retry_backoff=retry_backoff,
                         timeline=timeline,
                         registry=MetricsRegistry()) as engine:
            single_prewarm = engine.prewarm()
            progress({"stage": "single",
                      "prewarmed": single_prewarm["compiled"]})
            single = run_load(engine, spec, should_stop=should_stop,
                              progress=progress)

        # Stage 2: the pool. Health steering shares the live monitor's
        # tracker when one exists, so mid-run degradation drains too.
        pool = DevicePool(devices, placement=placement,
                          health=mon.health if mon is not None else None,
                          drain_below=drain_below,
                          max_in_flight=max_in_flight)
        sick_label = None
        if sick_device is not None and len(pool.devices) > 1 \
                and 0 <= sick_device < len(pool.devices):
            sick_label = pool.mark_sick(sick_device)
            progress({"stage": "pool", "sick_device": sick_label})
        with ServeEngine(buckets, max_batch=max_batch, max_wait=max_wait,
                         retry_backoff=retry_backoff,
                         timeline=timeline, monitor=mon,
                         registry=MetricsRegistry(),
                         pool=pool) as engine:
            pool_prewarm = engine.prewarm()
            progress({"stage": "pool",
                      "prewarmed": pool_prewarm["compiled"]})
            stats = run_load(engine, spec, should_stop=should_stop,
                             progress=progress)
            stats["pool"] = engine.stats()["pool"]
        stats["prewarm"] = pool_prewarm
        stats["single_prewarm"] = single_prewarm
        stats["buckets"] = [b.key for b in buckets]
        stats["smoke"] = bool(smoke)
        stats["epilogue"] = buckets[0].epilogue
        stats["retry_backoff"] = retry_backoff
        stats["sick_device"] = sick_label
        if sick_label is not None:
            row = stats["pool"]["per_device"].get(sick_label, {})
            stats["sick_device_batches"] = row.get("batches")
            stats["sick_device_drained"] = (
                sick_label in stats["pool"]["drained"]
                and row.get("batches", 0) == 0)
        stats["single"] = {
            k: single.get(k)
            for k in ("completed", "correct", "throughput_rps",
                      "goodput_rps", "p50_latency_seconds",
                      "p99_latency_seconds", "wall_seconds", "retries",
                      "uncorrectable_final")}
        scaling = {}
        for key in ("throughput_rps", "goodput_rps"):
            s, p = single.get(key), stats.get(key)
            if s and p:
                scaling[key.replace("_rps", "_ratio")] = round(p / s, 3)
        stats["scaling"] = scaling
        stats["seconds_total"] = round(time.monotonic() - t0, 3)
        if mon is not None:
            stats["slo"] = mon.snapshot()
            stats["device_health"] = stats["slo"]["device_health"]
            if mon_server is not None:
                stats["monitor_url"] = mon_server.url
    finally:
        if mon_server is not None:
            mon_server.close()
        if mon is not None:
            mon.detach()
    return stats


# ---------------------------------------------------------------------------
# Transformer-block workload: ragged prefill/decode, tokens-correct/sec
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class BlockLoadSpec:
    """One transformer-block load scenario.

    ``seq_lengths`` (+ optional ``seq_length_weights``) is the ragged
    PREFILL length distribution — deliberately not bucket-aligned, so
    padding and the causal-placement geometry are exercised.
    ``decode_ratio`` is the prefill/decode mix knob: the target fraction
    of requests that are decode steps (a decode only fires when some
    sequence's previous request has resolved — decodes are sequential
    per sequence — so the realized mix tracks the knob without blocking
    the arrival loop). ``inject_rate`` / ``adversarial_rate`` drive the
    IN-FLIGHT attention variants exactly like the GEMM spec;
    ``kv_corrupt_rate`` is the per-decode probability that a STORED page
    of the sequence is corrupted first (``kv_corrupt_elements=1`` is the
    in-place-correctable single element, ``>1`` the multi-column
    corruption only the page-restore ladder recovers).
    """

    num_requests: int = 24
    decode_ratio: float = 0.6
    seq_lengths: Tuple[int, ...] = (24, 48, 100, 180, 250)
    seq_length_weights: Optional[Tuple[float, ...]] = None
    d: int = 64
    dv: int = 64
    rate: float = 0.0
    in_dtype: str = "float32"
    inject_rate: float = 0.0
    adversarial_rate: float = 0.0
    kv_corrupt_rate: float = 0.0
    kv_corrupt_elements: int = 1
    # Alternate single-element and 3-element corruption across
    # injections, so one run exercises BOTH recovery arms: in-place
    # correction (free) and the page-restore ladder.
    kv_corrupt_alternate: bool = False
    kv_corrupt_magnitude: float = 1000.0
    seed: int = 10
    verify: bool = False
    result_timeout: float = 600.0


def block_smoke_spec() -> BlockLoadSpec:
    """The CPU-runnable CI block scenario: a handful of ragged
    sequences, in-flight SDCs on a quarter of requests, stored-page
    corruption on half the decodes (mixing the correctable single
    element with the restore-ladder multi-column case), everything
    verified — enough traffic to pin tokens-correct goodput > 0 and
    both fault planes detected in about a minute of interpret mode."""
    return BlockLoadSpec(num_requests=14, decode_ratio=0.6,
                         seq_lengths=(24, 60, 100, 150),
                         inject_rate=0.25, adversarial_rate=0.1,
                         kv_corrupt_rate=0.5,
                         kv_corrupt_alternate=True, verify=True)


def _block_variant(rng, spec, engine, length, phase) -> str:
    from ft_sgemm_tpu.serve.buckets import select_block_bucket

    u = float(rng.random())
    if u < spec.adversarial_rate:
        try:
            bucket = select_block_bucket(engine.buckets, length, phase,
                                         in_dtype=spec.in_dtype)
            # The adversarial same-column schedule needs the PV
            # product's K grid >= 2 steps (lk >= 256 at the serve
            # tile); shallower buckets correct it — downgrade honestly.
            if bucket.lk >= 256:
                return "adversarial"
        except BucketOverflowError:
            pass
        return "inject"
    if u < spec.adversarial_rate + spec.inject_rate:
        return "inject"
    return "clean"


def run_block_load(engine, spec: BlockLoadSpec, *,
                   should_stop: Optional[Callable[[], bool]] = None,
                   progress: Optional[Callable[[dict], None]] = None
                   ) -> dict:
    """Drive one transformer-block scenario and return the serving
    stats dict — the block analog of :func:`run_load`, with goodput
    measured in tokens-correct-per-second.

    The generator keeps an authoritative host copy of every sequence's
    K/V rows, so ``verify=True`` checks each result against the plain
    XLA causal-attention oracle at the TRUE ragged shape — including
    decodes whose stored pages were corrupted and recovered ("correct"
    means numerically verified, not "no fault reported")."""
    from ft_sgemm_tpu.ops.attention import attention_reference
    from ft_sgemm_tpu.serve.blocks import BlockRequest

    rng = np.random.default_rng(spec.seed)
    t0 = time.monotonic()
    sequences = []   # dicts: seq_id, k/v/q history, last future
    submitted = []   # (request, future, seq record)
    rejected = 0
    corruptions = {"injected": 0, "elements": 0}
    partial = False
    for i in range(spec.num_requests):
        if should_stop is not None and should_stop():
            partial = True
            break
        def decodable_seqs(block: bool) -> list:
            # Decodes are response-driven AND sequential per sequence: a
            # sequence is decodable once its previous request resolved
            # ok. ``block=True`` waits for the oldest in-flight one (a
            # decode arrival cannot exist before its predecessor's
            # response), keeping the realized mix near the knob even in
            # the open-loop (rate=0) drive.
            out = []
            for s in sequences:
                f = s["fut"]
                if f is None:
                    continue
                if not f.done():
                    if not block:
                        continue
                    try:
                        f.result(timeout=spec.result_timeout)
                    except TimeoutError:
                        continue
                    block = False  # one wait per arrival is plenty
                if s["ok_so_far"] and not f.result(0).ok:
                    s["ok_so_far"] = False  # dead: stop extending
                if s["ok_so_far"]:
                    out.append(s)
            return out

        decodable = []
        if sequences and float(rng.random()) < spec.decode_ratio:
            decodable = decodable_seqs(block=False) \
                or decodable_seqs(block=True)
        if decodable:
            s = decodable[int(rng.integers(len(decodable)))]
            if spec.kv_corrupt_rate > 0 and engine.kv.checksums \
                    and float(rng.random()) < spec.kv_corrupt_rate:
                length = engine.kv.length(s["seq_id"], 0, 0)
                page = int(rng.integers(
                    (length - 1) // engine.kv.page_size + 1))
                valid = min(engine.kv.page_size,
                            length - page * engine.kv.page_size)
                n_cols = max(1, int(spec.kv_corrupt_elements))
                if spec.kv_corrupt_alternate \
                        and corruptions["injected"] % 2 == 1:
                    n_cols = 3
                cols = rng.choice(spec.d, size=min(n_cols, spec.d),
                                  replace=False)
                engine.corrupt_kv(
                    s["seq_id"], page=page,
                    row=int(rng.integers(valid)),
                    cols=[int(c) for c in cols],
                    magnitude=spec.kv_corrupt_magnitude)
                corruptions["injected"] += 1
                corruptions["elements"] += len(cols)
            q = rng.standard_normal((1, spec.d)).astype(np.float32)
            k = rng.standard_normal((1, spec.d)).astype(np.float32)
            v = rng.standard_normal((1, spec.dv)).astype(np.float32)
            length = s["k"].shape[0] + 1
            req = BlockRequest("decode", q, k, v, seq_id=s["seq_id"],
                               in_dtype=spec.in_dtype,
                               variant=_block_variant(
                                   rng, spec, engine, length, "decode"))
        else:
            lengths = np.asarray(spec.seq_lengths)
            weights = spec.seq_length_weights
            if weights is not None:
                w = np.asarray(weights, np.float64)
                length = int(rng.choice(lengths, p=w / w.sum()))
            else:
                length = int(lengths[int(rng.integers(len(lengths)))])
            q = rng.standard_normal((length, spec.d)).astype(np.float32)
            k = rng.standard_normal((length, spec.d)).astype(np.float32)
            v = rng.standard_normal((length, spec.dv)).astype(np.float32)
            s = {"seq_id": None, "k": np.zeros((0, spec.d), np.float32),
                 "v": np.zeros((0, spec.dv), np.float32),
                 "fut": None, "ok_so_far": True}
            req = BlockRequest("prefill", q, k, v,
                               in_dtype=spec.in_dtype,
                               variant=_block_variant(
                                   rng, spec, engine, length, "prefill"))
            s["seq_id"] = req.seq_id
            sequences.append(s)
        try:
            fut = engine.submit(req)
        except BucketOverflowError:
            rejected += 1
            if req.phase == "prefill":
                sequences.remove(s)
            continue
        s["fut"] = fut
        s["k"] = np.concatenate([s["k"], req.k])
        s["v"] = np.concatenate([s["v"], req.v])
        # The key count AS OF this request: later decodes extend the
        # history, and this request's oracle must not see their keys.
        submitted.append((req, fut, s, s["k"].shape[0]))
        if progress is not None and (i + 1) % 8 == 0:
            progress({"submitted": i + 1})
        if spec.rate > 0:
            time.sleep(float(rng.exponential(1.0 / spec.rate)))
    engine.drain(timeout=spec.result_timeout)
    wall = time.monotonic() - t0

    completed = correct = corrected = uncorrectable_final = 0
    tokens_total = tokens_correct = 0
    kv_faults = kv_corrected = kv_restores = 0
    retries = 0
    verify_failures = 0
    variant_counts: dict = {}
    phase_counts = {"prefill": 0, "decode": 0}
    for req, fut, s, n_keys in submitted:
        res = fut.result(timeout=spec.result_timeout)
        completed += 1
        retries += res.retries
        tokens_total += res.tokens
        kv_faults += res.kv_faults
        kv_corrected += res.kv_corrected
        kv_restores += res.kv_restores
        variant_counts[req.variant] = variant_counts.get(req.variant,
                                                         0) + 1
        phase_counts[req.phase] += 1
        if res.corrected:
            corrected += 1
        if not res.ok:
            s["ok_so_far"] = False
            uncorrectable_final += 1
            continue
        if spec.verify:
            if req.phase == "prefill":
                want = np.asarray(attention_reference(
                    req.q, req.k, req.v, causal=True))
            else:
                want = np.asarray(attention_reference(
                    req.q, s["k"][:n_keys], s["v"][:n_keys],
                    causal=True))
            if not np.allclose(res.out, want, rtol=1e-3, atol=1e-3):
                verify_failures += 1
                s["ok_so_far"] = False
                continue
        correct += 1
        tokens_correct += res.tokens

    eng = engine.stats()
    lat = eng["latency"]
    stats = {
        "workload": "block",
        "requests_submitted": len(submitted),
        "requests_rejected": rejected,
        "completed": completed,
        "correct": correct,
        "corrected_free": corrected,
        "uncorrectable_final": uncorrectable_final,
        "verify_failures": verify_failures,
        "verified": bool(spec.verify),
        "retries": retries,
        "bucket_retries": eng["retries"],
        "whole_queue_retries": eng["whole_queue_retries"],
        "batches": eng["batches"],
        "variants": variant_counts,
        "phases": phase_counts,
        "sequences": len(sequences),
        "inject_rate": spec.inject_rate,
        "adversarial_rate": spec.adversarial_rate,
        "kv_corrupt_rate": spec.kv_corrupt_rate,
        "kv_corruptions_injected": corruptions["injected"],
        "kv_faults": kv_faults,
        "kv_corrected_in_place": kv_corrected,
        "kv_page_restores": kv_restores,
        "kv": eng["kv"],
        "tokens_total": tokens_total,
        "tokens_correct": tokens_correct,
        "wall_seconds": round(wall, 3),
        "throughput_tps": (round(tokens_total / wall, 3)
                           if wall > 0 else None),
        "goodput_tps": (round(tokens_correct / wall, 3)
                        if wall > 0 else None),
        "throughput_rps": round(completed / wall, 3) if wall > 0 else None,
        "p50_latency_seconds": lat.get("p50"),
        "p99_latency_seconds": lat.get("p99"),
        "max_latency_seconds": lat.get("max"),
        "per_bucket": eng["per_bucket"],
        "ring": eng["ring"],
    }
    if partial:
        stats["partial"] = True
    return stats


def run_block_serve_bench(*, smoke: bool = False,
                          seq_sizes: Optional[Sequence[int]] = None,
                          d: int = 64, dv: Optional[int] = None,
                          in_dtype: str = "float32",
                          num_requests: Optional[int] = None,
                          decode_ratio: Optional[float] = None,
                          inject_rate: Optional[float] = None,
                          adversarial_rate: Optional[float] = None,
                          kv_corrupt_rate: Optional[float] = None,
                          rate: Optional[float] = None,
                          max_batch: int = 4, max_wait: float = 0.05,
                          verify: Optional[bool] = None,
                          kv_checksums: bool = True,
                          kv_page_size: int = 32,
                          ring="auto",
                          inject_coords: Optional[tuple] = (1,),
                          pool: bool = False,
                          timeline=None,
                          should_stop: Optional[Callable[[], bool]] = None,
                          progress_out=None,
                          monitor="auto",
                          monitor_port: Optional[int] = None,
                          slo=None) -> dict:
    """The transformer-block serve-bench core shared by ``bench.py
    --serve --workload=block`` and ``cli serve-bench --workload=block``:
    build the block-bucket set, prewarm it, drive the ragged
    prefill/decode load (in-flight injection AND stored-page
    corruption), and return the artifact context dict — goodput in
    tokens-correct-per-second, KV verify/fault/restore counters, p50/p99
    latency, and the SLO/health snapshot.

    ``ring="auto"`` (default) routes the inject variant's prefill
    executors through ring attention with ``inject_coords`` when two or
    more local devices exist — injected in-flight faults then carry
    per-ring-position device blame; pass ``ring=False`` to pin
    single-device.

    ``pool=True`` dispatches through a
    :class:`~ft_sgemm_tpu.serve.pool.DevicePool` over every local
    device (per-device AOT replicas, health-steered placement sharing
    the live monitor's tracker) — the GEMM plane's multi-device +
    eviction path, block-typed. Mutually exclusive with ring executors
    (the pool wins under ``ring="auto"``).
    """
    from ft_sgemm_tpu.serve.blocks import BlockEngine
    from ft_sgemm_tpu.serve.buckets import default_block_bucket_set

    sizes = tuple(seq_sizes) if seq_sizes else (
        (128, 256) if smoke else (128, 256, 512))
    buckets = default_block_bucket_set(sizes, d=d, dv=dv,
                                       in_dtype=in_dtype)
    base = block_smoke_spec() if smoke else BlockLoadSpec(
        inject_rate=0.2, adversarial_rate=0.05, kv_corrupt_rate=0.3,
        verify=False)
    spec = dataclasses.replace(
        base,
        d=d, dv=d if dv is None else int(dv),
        in_dtype=in_dtype,
        num_requests=base.num_requests if num_requests is None
        else int(num_requests),
        decode_ratio=base.decode_ratio if decode_ratio is None
        else float(decode_ratio),
        inject_rate=base.inject_rate if inject_rate is None
        else float(inject_rate),
        adversarial_rate=base.adversarial_rate if adversarial_rate is None
        else float(adversarial_rate),
        kv_corrupt_rate=base.kv_corrupt_rate if kv_corrupt_rate is None
        else float(kv_corrupt_rate),
        rate=base.rate if rate is None else float(rate),
        verify=base.verify if verify is None else bool(verify),
    )
    largest = max(sizes)
    lengths = tuple(v for v in spec.seq_lengths if v <= largest)
    spec = dataclasses.replace(spec,
                               seq_lengths=lengths or (largest // 2,))

    if pool and ring is True:
        raise ValueError("--pool block serving uses per-device replicas;"
                         " ring executors span the mesh (pass"
                         " ring=False)")
    if ring == "auto":
        import jax

        # Pool dispatch and ring executors are mutually exclusive by
        # construction (BlockEngine refuses the combination): the pool
        # wins when both would apply.
        ring = (not pool) and jax.device_count() >= 2

    def progress(p):
        if timeline is not None:
            timeline.point("serve_progress", "load", **p)
        if progress_out is not None:
            print(f"serve-block-bench: {p}", file=progress_out,
                  flush=True)

    mon = None
    mon_server = None
    if monitor == "auto":
        from ft_sgemm_tpu.telemetry.monitor import Monitor

        mon = Monitor(slo=slo)
    elif monitor is not None:
        mon = monitor
    if mon is not None:
        mon.attach()
        if monitor_port is not None:
            from ft_sgemm_tpu.telemetry.monitor import MonitorServer

            mon_server = MonitorServer(mon, port=monitor_port).start()
            progress({"monitor_url": mon_server.url})
    try:
        dev_pool = None
        if pool:
            from ft_sgemm_tpu.serve.pool import DevicePool

            dev_pool = DevicePool(
                health=mon.health if mon is not None else None)
            progress({"pool_devices": len(dev_pool.devices)})
        with BlockEngine(buckets, max_batch=max_batch, max_wait=max_wait,
                         kv_checksums=kv_checksums,
                         kv_page_size=kv_page_size, ring=bool(ring),
                         inject_coords=inject_coords,
                         timeline=timeline, monitor=mon,
                         pool=dev_pool) as engine:
            t0 = time.monotonic()
            prewarm = engine.prewarm()
            progress({"prewarmed": prewarm["compiled"],
                      "seconds": prewarm["seconds"]})
            stats = run_block_load(engine, spec, should_stop=should_stop,
                                   progress=progress)
            if dev_pool is not None:
                stats["pool"] = engine.stats()["pool"]
            stats["prewarm"] = prewarm
            stats["buckets"] = [b.key for b in buckets]
            stats["smoke"] = bool(smoke)
            stats["kv_checksums"] = bool(kv_checksums)
            stats["seconds_total"] = round(time.monotonic() - t0, 3)
        if mon is not None:
            stats["slo"] = mon.snapshot()
            stats["device_health"] = stats["slo"]["device_health"]
            if mon_server is not None:
                stats["monitor_url"] = mon_server.url
    finally:
        if mon_server is not None:
            mon_server.close()
        if mon is not None:
            mon.detach()
    return stats


__all__ = ["BlockLoadSpec", "LoadSpec", "block_smoke_spec",
           "pool_smoke_spec", "run_block_load", "run_block_serve_bench",
           "run_load", "run_pool_serve_bench", "run_serve_bench",
           "smoke_spec"]
