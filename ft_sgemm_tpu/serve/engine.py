"""Async continuous-batching dispatch with SLO-aware ABFT retry.

The serving core (ROADMAP item 3): requests accumulate per shape bucket
(:mod:`.buckets`), a background dispatcher flushes a bucket when it is
batch-full or its oldest request has waited ``max_wait``, and every
request runs one of a small set of AOT-compiled executables — compiled
once (at :meth:`ServeEngine.prewarm` or, lazily, as a RECORDED compile
span on first use), then reused for every request the bucket ever serves.
Steady-state dispatch on a prewarmed bucket set therefore records ZERO
compile spans in the run timeline — the warm-path contract
``perf/wallclock.py`` phase attribution pins in ``tests/test_serve.py``.

The retry policy is where the paper's economics land (arXiv 2305.01024:
online ABFT is cheap enough to leave on — IF the serving path exploits
it):

- **Corrected SDC = free.** A result with ``detections > 0`` and
  ``uncorrectable == 0`` was repaired in-kernel; the request completes
  with ZERO retries (``serve_corrected_free`` counts them — the goodput
  the fused kernel buys).
- **Uncorrectable = bucket-scoped retry.** Only the affected requests of
  the affected bucket's batch re-execute — never the whole queue
  (``serve_whole_queue_retries`` exists solely to be pinned at zero).
  Retries are bounded (``max_retries``) with exponential backoff, and
  every transition lands as a telemetry ladder event
  (``retry`` / ``exhausted``, the ``train.resilient_step`` vocabulary).
  Retries re-execute without injection: the injected fault models a
  TRANSIENT hardware SDC, which does not replay on the same data.

Per-request fault attribution: each request's own ``FtSgemmResult``
counter grids (the PR-5 per-device/per-tile attribution machinery) are
materialized per request, so a fault is blamed on a REQUEST — tile
coordinates, bucket, request id — not just on a call. When telemetry is
enabled each request emits one ``serve_gemm`` event carrying
``request_id`` / ``bucket`` / ``variant`` / ``latency_seconds`` /
``retries`` in ``extra``; latencies additionally feed the registry's
``serve_latency_seconds`` histogram (``registry.LATENCY_BUCKETS``), whose
:func:`~ft_sgemm_tpu.telemetry.registry.histogram_percentiles` estimates
are the ONLY p50/p99 implementation the serving layer has.

:mod:`ft_sgemm_tpu.serve.blocks` extends this engine contract from
(M, N, K) GEMM requests to transformer-block requests (ragged
prefill/decode attention over an ABFT-checked KV cache), reusing the
queue/future/timeline machinery here — the ``_Future`` /
``_NullRecorder`` / ``_as_recorder`` / ``_device_label`` helpers are
shared plumbing, not engine-private.
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import itertools
import threading
import time
from typing import Dict, Iterable, Optional, Sequence, Tuple

import numpy as np

from ft_sgemm_tpu.perf.economics import CostLedger, gemm_request_cost
from ft_sgemm_tpu.serve.buckets import Bucket, select_bucket
from ft_sgemm_tpu.serve.tracing import new_trace_id, trace_scope
from ft_sgemm_tpu.telemetry.registry import (
    LATENCY_BUCKETS,
    histogram_percentiles,
)

# Injection variants the engine prewarms per bucket. A request names a
# VARIANT, not an arbitrary InjectionSpec: one executable per
# (bucket, variant) is the whole point of bucketing, and a free-form
# per-request schedule would force a fresh trace+compile onto the hot
# path. "clean" runs no injection; "inject" is the reference-like
# correctable schedule (rotating columns — every fault corrected
# in-kernel); "adversarial" pins every fault to ONE column under a
# single final check, the schedule known to defeat column-localized
# correction (the uncorrectable-SDC simulator driving the retry path).
VARIANTS = ("clean", "inject", "adversarial")

_REQ_IDS = itertools.count(1)


@dataclasses.dataclass
class ServeRequest:
    """One GEMM request: ``alpha * a @ b.T + beta * c`` at the request's
    own ragged shape — ``a`` is (m, k), ``b`` is (n, k) (the family's
    operand convention), ``c`` (m, n) or None for zeros. ``variant``
    selects one of the engine's prewarmed injection variants
    (:data:`VARIANTS`) — load generators use it to model SDC arrival."""

    a: np.ndarray
    b: np.ndarray
    c: Optional[np.ndarray] = None
    in_dtype: str = "float32"
    variant: str = "clean"
    # Per-request fused-epilogue bias (length n), consumed only when the
    # serving bucket's epilogue fuses one (Bucket.epilogue "bias+...");
    # None there means a zero bias. Zero-padded to the bucket width like
    # every other operand.
    bias: Optional[np.ndarray] = None
    request_id: int = dataclasses.field(
        default_factory=lambda: next(_REQ_IDS))
    # Minted at construction (DESIGN.md §12 rule 1): a request that only
    # ever waits, overflows, or is rejected still has a joinable identity.
    trace_id: str = dataclasses.field(default_factory=new_trace_id)

    def __post_init__(self):
        if self.variant not in VARIANTS:
            raise ValueError(
                f"ServeRequest.variant={self.variant!r} must be one of"
                f" {VARIANTS} (per-request free-form injection would"
                " defeat the one-executable-per-bucket contract)")
        self.a = np.asarray(self.a)
        self.b = np.asarray(self.b)
        if self.a.ndim != 2 or self.b.ndim != 2:
            raise ValueError("ServeRequest operands must be 2-D: a is"
                             " (m, k), b is (n, k)")
        if self.a.shape[1] != self.b.shape[1]:
            raise ValueError(
                f"ServeRequest contraction mismatch: a is {self.a.shape}"
                f" (m, k), b is {self.b.shape} (n, k)")
        if self.bias is not None:
            self.bias = np.asarray(self.bias).reshape(-1)
            if self.bias.shape[0] != self.b.shape[0]:
                raise ValueError(
                    f"ServeRequest.bias must have length n="
                    f"{self.b.shape[0]}, got {self.bias.shape[0]}")

    @property
    def mnk(self) -> Tuple[int, int, int]:
        return (self.a.shape[0], self.b.shape[0], self.a.shape[1])


@dataclasses.dataclass
class ServeResult:
    """What a request's future resolves to."""

    request_id: int
    bucket_key: str
    c: np.ndarray
    detections: int
    uncorrectable: int
    retries: int
    ok: bool                      # verified-or-corrected; False = exhausted
    corrected: bool               # detections > 0 and repaired in-kernel
    latency_seconds: float
    blame_tiles: Optional[list]   # nonzero per-tile coords, request-scoped
    trace_id: Optional[str] = None


class _Future:
    """Minimal thread-safe future (stdlib concurrent.futures would work
    too; this keeps the wait/notify under the engine's own discipline)."""

    def __init__(self):
        self._ev = threading.Event()
        self._result: Optional[ServeResult] = None
        self._error: Optional[BaseException] = None

    def _resolve(self, result: ServeResult) -> None:
        self._result = result
        self._ev.set()

    def _reject(self, error: BaseException) -> None:
        self._error = error
        self._ev.set()

    def done(self) -> bool:
        return self._ev.is_set()

    def result(self, timeout: Optional[float] = None) -> ServeResult:
        if not self._ev.wait(timeout):
            raise TimeoutError("serve request still pending")
        if self._error is not None:
            raise self._error
        return self._result


@dataclasses.dataclass
class _Entry:
    request: ServeRequest
    future: _Future
    t_enqueue: float


class _NullRecorder:
    """Timeline stand-in when the engine runs without one."""

    path = None

    def point(self, *a, **k):
        pass

    @contextlib.contextmanager
    def span(self, *a, **k):
        yield {}


def _device_label(x) -> str:
    """The device a materialized result lives on, as a stable string —
    version-defensive across jax's Array.device / .devices() spellings,
    and degrading to "host" rather than raising (a monitor label is
    never worth failing a request over)."""
    try:
        devs = getattr(x, "devices", None)
        if callable(devs):
            ds = list(devs())
            if ds:
                return str(ds[0])
    except Exception:  # noqa: BLE001
        pass
    try:
        d = getattr(x, "device", None)
        if d is not None:
            return str(d() if callable(d) else d)
    except Exception:  # noqa: BLE001
        pass
    return "host"


def _as_recorder(timeline):
    if timeline is None:
        return _NullRecorder()
    if isinstance(timeline, str):
        from ft_sgemm_tpu.telemetry.timeline import TimelineRecorder

        return TimelineRecorder(timeline)
    return timeline


class ServeEngine:
    """Shape-bucketed continuous-batching GEMM server.

    Lifecycle::

        engine = ServeEngine(default_bucket_set((256, 512)))
        engine.start()
        engine.prewarm()              # AOT-compile every (bucket, variant)
        fut = engine.submit(ServeRequest(a, b))
        res = fut.result(timeout=30)  # ServeResult
        engine.drain(); engine.close()

    or ``with ServeEngine(...) as engine: ...`` (start on enter,
    drain+close on exit). Thread-safe: ``submit`` may be called from any
    number of producer threads; execution runs on the engine's single
    dispatcher thread (one device, one dispatch stream — batching, not
    device contention, is the concurrency model).

    ``pool=DevicePool(...)`` (serve/pool.py) turns the dispatcher into
    a PLACER: ready batches are steered to per-device worker threads by
    health score and load, each device runs its own AOT-compiled
    replica of every (bucket, variant) executable, and workers keep a
    bounded async in-flight window instead of a synchronous per-request
    wait — the mesh, not one chip, becomes the unit of throughput.
    Share the live monitor's tracker (``DevicePool(health=mon.health)``)
    so mid-run health degradation drains a device without operator
    action.
    """

    def __init__(self, buckets: Sequence[Bucket], *,
                 alpha: float = 1.0, beta: float = 0.0,
                 threshold="static",
                 max_batch: int = 4, max_wait: float = 0.05,
                 max_retries: int = 2, retry_backoff: float = 0.01,
                 timeline=None, registry=None, monitor=None, pool=None,
                 elastic=None):
        if not buckets:
            raise ValueError("ServeEngine needs at least one bucket")
        if max_batch < 1:
            raise ValueError(f"max_batch={max_batch} must be >= 1")
        self.buckets: Tuple[Bucket, ...] = tuple(buckets)
        self.alpha = float(alpha)
        self.beta = float(beta)
        self.threshold = threshold
        self.max_batch = int(max_batch)
        self.max_wait = float(max_wait)
        self.max_retries = int(max_retries)
        self.retry_backoff = float(retry_backoff)
        self._tl = _as_recorder(timeline)
        # Live observability plane (telemetry/monitor.py): a direct
        # per-request feed — SLO accounting, device-health scoring, and
        # the /events ring. STRICTLY host-side, consulted only after a
        # request's result is already materialized: monitor=None leaves
        # the compiled executables and the steady-state hot path
        # byte-identical (pinned in tests/test_monitor.py, the same
        # discipline as --telemetry in PR 1).
        self.monitor = monitor
        # Multi-device dispatch (serve/pool.py): with a DevicePool the
        # dispatcher thread only PLACES ready batches (health-steered);
        # per-device worker threads execute them against per-device AOT
        # executables with a bounded async in-flight window. pool=None
        # keeps the historical single-device engine byte-for-byte.
        self.pool = pool
        # Elastic recovery (resilience/elastic.py): with an
        # ElasticController the placer consults the eviction policy on
        # every batch — a device whose health evidence crosses the
        # eviction floor (or that keeps forcing panel recomputes) is
        # removed from placement mid-run via evict_device(), its queued
        # batches migrating to the survivors. elastic=None (or
        # pool=None) keeps the historical behavior exactly.
        self.elastic = elastic
        from ft_sgemm_tpu import telemetry

        self.registry = registry if registry is not None \
            else telemetry.get_registry()

        self._cond = threading.Condition()
        self._pending: Dict[str, collections.deque] = {
            b.key: collections.deque() for b in self.buckets}
        self._by_key = {b.key: b for b in self.buckets}
        self._outstanding = 0
        self._draining = False
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        self._pool_threads: list = []

        self._compile_lock = threading.Lock()
        # (bucket key, variant, device label or None) -> executable.
        self._compiled: Dict[Tuple[str, str, Optional[str]], object] = {}
        self._kernels: Dict[Tuple[str, str], object] = {}
        self._prewarmed = False

        self._stats_lock = threading.Lock()
        self._counts = {
            "requests": 0, "completed": 0, "batches": 0,
            "corrected_free": 0, "retries": 0, "whole_queue_retries": 0,
            "uncorrectable_exhausted": 0, "rejected": 0,
        }
        self._per_bucket: Dict[str, dict] = {
            b.key: {"requests": 0, "batches": 0, "retries": 0}
            for b in self.buckets}
        # The request cost plane (perf/economics.py): every completed
        # request rolls its productive + overhead flops in; stats() and
        # the live economics_* gauges read the same ledger.
        self.economics = CostLedger()

    # -- kernel family per (bucket, variant) --------------------------------

    def _bucket_tile(self, bucket: Bucket):
        """The bucket's explicit base tile (the tuner cache, consulted at
        trace time via ``tunable=True``, overrides it with a measured
        winner when one exists). ``bk`` stays at one 128-granule for
        k <= 512 so the K grid is >= 2 steps on the 256+ buckets — the
        depth the adversarial variant's same-column schedule needs to
        produce a genuine uncorrectable interval."""
        from ft_sgemm_tpu.configs import KernelShape

        bm = min(bucket.m, 512)
        bn = min(bucket.n, 512)
        bk = 128 if bucket.k <= 512 else 512
        return KernelShape(f"serve{bm}x{bn}x{bk}", bm, bn, bk, (0,) * 7)

    def _variant_spec(self, bucket: Bucket, variant: str):
        from ft_sgemm_tpu.injection import InjectionSpec

        if variant == "clean":
            return InjectionSpec.none()
        if variant == "inject":
            # Reference-like correctable SDCs: rotating columns (the
            # coprime stride), one fault per K step — every one is
            # detected and corrected in-kernel.
            return InjectionSpec(enabled=True, every=1, magnitude=10000.0)
        # Adversarial: every fault in ONE column — under weighted's
        # deferred single final check, two-plus same-column faults in the
        # interval defeat per-column localization and report
        # uncorrectable: the transient-SDC failure the retry ladder
        # exists for. (Needs a bucket with nk >= 2, i.e. k >= 256 at the
        # serve tile; int8/rowcol buckets correct even this schedule —
        # their intersection disambiguates by row.)
        return InjectionSpec(enabled=True, every=1, magnitude=10000.0,
                             col_stride=0)

    def _kernel(self, bucket: Bucket, variant: str):
        key = (bucket.key, variant)
        kern = self._kernels.get(key)
        if kern is not None:
            return kern
        from ft_sgemm_tpu.ops.ft_sgemm import make_ft_sgemm

        tile = self._bucket_tile(bucket)
        # The adversarial variant runs with the tuner OFF: a tuned tile
        # deepening bk would collapse the K grid to one step, and the
        # same-column schedule needs >= 2 faults in one check interval
        # to actually defeat weighted localization (nk = 1 degenerates
        # to a corrected single fault). Clean/inject dispatch stays
        # tuner-backed — the serving hot path is the one the cache is
        # for.
        kern = make_ft_sgemm(
            tile, alpha=self.alpha, beta=self.beta,
            strategy=bucket.strategy, in_dtype=bucket.in_dtype,
            threshold=self.threshold,
            epilogue=bucket.epilogue,
            tunable=variant != "adversarial")
        self._kernels[key] = kern
        return kern

    def _get_compiled(self, bucket: Bucket, variant: str, device=None):
        """The AOT-compiled executable for one (bucket, variant[,
        device]) — the object steady-state dispatch calls directly, so
        serving never re-enters jit tracing. With ``device`` the avals
        carry its ``SingleDeviceSharding``, so the executable runs (and
        its results live) on exactly that pool device. A compile that
        happens here (i.e. the bucket was NOT prewarmed) is recorded as
        a ``compile`` span: the timeline never lies about warm-path
        purity."""
        label = None if device is None else str(device)
        key = (bucket.key, variant, label)
        compiled = self._compiled.get(key)
        if compiled is not None:
            return compiled
        with self._compile_lock:
            compiled = self._compiled.get(key)
            if compiled is not None:
                return compiled
            import jax
            import jax.numpy as jnp

            if device is None:
                def av(shape):
                    return jax.ShapeDtypeStruct(shape, jnp.float32)
            else:
                from jax.sharding import SingleDeviceSharding

                sh = SingleDeviceSharding(device)

                def av(shape):
                    return jax.ShapeDtypeStruct(shape, jnp.float32,
                                                sharding=sh)

            kern = self._kernel(bucket, variant)
            spec = self._variant_spec(bucket, variant)
            avals = (av((bucket.m, bucket.k)), av((bucket.n, bucket.k)),
                     av((bucket.m, bucket.n)))
            if bucket.epilogue_spec.bias:
                # The fused bias is a fourth positional operand of the
                # bucket's ONE executable — per-request bias values,
                # zero steady-state recompiles.
                fn = jax.jit(
                    lambda a, b, c, bias: kern(a, b, c, spec, bias=bias))
                avals = avals + (av((bucket.n,)),)
            else:
                fn = jax.jit(lambda a, b, c: kern(a, b, c, spec))
            span = f"compile[{bucket.key}:{variant}]" if label is None \
                else f"compile[{bucket.key}:{variant}@{label}]"
            with self._tl.span(span, kind="compile"):
                compiled = fn.lower(*avals).compile()
            self._compiled[key] = compiled
            return compiled

    def prewarm(self, variants: Iterable[str] = VARIANTS) -> dict:
        """AOT-compile every (bucket, variant) executable up front —
        ``cli prewarm``'s machinery applied to the bucket set, with the
        persistent compile cache (``FT_SGEMM_COMPILE_CACHE``) banking
        each one when enabled, so even a server RESTART resumes warm.
        With a device pool the set is (bucket, variant, DEVICE) — every
        pool device gets its own replica, so placement never compiles on
        the hot path. Emits a ``prewarm_done`` timeline point:
        everything after it is the steady state the zero-compile-span
        pin measures."""
        t0 = time.monotonic()
        compiled = 0
        devices = (None,) if self.pool is None else self.pool.devices
        for bucket in self.buckets:
            for variant in variants:
                for device in devices:
                    self._get_compiled(bucket, variant, device=device)
                    compiled += 1
        self._prewarmed = True
        seconds = round(time.monotonic() - t0, 3)
        self._tl.point("serve", "prewarm_done", compiled=compiled,
                       seconds=seconds)
        return {"compiled": compiled, "buckets": len(self.buckets),
                "seconds": seconds}

    # -- queue --------------------------------------------------------------

    def start(self) -> "ServeEngine":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._dispatch_loop, daemon=True,
                name="serve-dispatch")
            self._thread.start()
        if self.pool is not None and not self._pool_threads:
            for i in range(len(self.pool.devices)):
                t = threading.Thread(target=self._pool_worker, args=(i,),
                                     daemon=True, name=f"serve-pool-{i}")
                t.start()
                self._pool_threads.append(t)
        return self

    def __enter__(self) -> "ServeEngine":
        return self.start()

    def __exit__(self, *exc):
        if not any(exc):
            self.drain()
        self.close()
        return False

    def submit(self, request: ServeRequest) -> _Future:
        """Route one request to its bucket and enqueue it. Raises
        :class:`~ft_sgemm_tpu.serve.buckets.BucketOverflowError`
        synchronously for shapes nothing fits (counted as rejected)."""
        m, n, k = request.mnk
        try:
            bucket = select_bucket(self.buckets, m, n, k,
                                   in_dtype=request.in_dtype)
        except Exception:
            with self._stats_lock:
                self._counts["rejected"] += 1
            self.registry.counter("serve_rejected").inc()
            raise
        fut = _Future()
        entry = _Entry(request, fut, time.monotonic())
        with self._cond:
            if self._stop:
                raise RuntimeError("ServeEngine is closed")
            self._pending[bucket.key].append(entry)
            self._outstanding += 1
            self._cond.notify_all()
        with self._stats_lock:
            self._counts["requests"] += 1
            self._per_bucket[bucket.key]["requests"] += 1
        self.registry.counter("serve_requests", bucket=bucket.key).inc()
        # First hop of the trace: the enqueue point names the trace the
        # moment the queue owns it (DESIGN.md §12 — enqueue -> flush ->
        # execute -> detect -> retry all carry the same ID).
        self._tl.point("serve", "enqueue", trace_id=request.trace_id,
                       request_id=request.request_id, bucket=bucket.key)
        return fut

    def _ready_keys(self, now: float) -> list:
        out = []
        for key, q in self._pending.items():
            if not q:
                continue
            if (len(q) >= self.max_batch or self._draining or self._stop
                    or now - q[0].t_enqueue >= self.max_wait):
                out.append(key)
        return out

    def _next_deadline(self, now: float) -> Optional[float]:
        waits = [self.max_wait - (now - q[0].t_enqueue)
                 for q in self._pending.values() if q]
        return max(0.0, min(waits)) if waits else None

    def _dispatch_loop(self):
        while True:
            batches = []
            with self._cond:
                while True:
                    now = time.monotonic()
                    ready = self._ready_keys(now)
                    if ready:
                        break
                    if self._stop:
                        return
                    timeout = self._next_deadline(now)
                    self._cond.wait(0.1 if timeout is None else timeout)
                for key in ready:
                    q = self._pending[key]
                    take = [q.popleft()
                            for _ in range(min(len(q), self.max_batch))]
                    batches.append((self._by_key[key], take))
            for bucket, entries in batches:
                if self.pool is not None:
                    self._place_batch(bucket, entries)
                else:
                    self._execute_batch(bucket, entries)

    def _check_elastic(self) -> None:
        """Consult the eviction policy before placing (pool mode with an
        ElasticController only). Re-entrant-safe: a device being evicted
        is never proposed twice, and the migration re-placement below
        lands here again harmlessly."""
        if self.elastic is None or self.pool is None:
            return
        decision = self.elastic.should_evict(self.pool)
        if decision is not None:
            self.evict_device(decision[0], reason=decision[1])

    def evict_device(self, index: int, reason: str = "manual") -> dict:
        """Evict one pool device under live traffic: placement stops
        naming it, its queued batches MIGRATE to the survivors through
        the ordinary placer (so the trace flow shows where each request
        went), and the survivors' executables are confirmed through the
        prewarm machinery — the re-AOT window, the only place a compile
        span is legitimate after steady state began (with a prewarmed
        set it is a pure cache walk: zero compile spans). Returns the
        eviction facts (also recorded on the controller when one is
        attached)."""
        label = self.pool.labels[index]
        batches_before = self.pool.stats()["per_device"][label]["batches"]
        t0 = time.monotonic()
        leftovers = self.pool.evict(index)
        survivors = [d for i, d in enumerate(self.pool.devices)
                     if i not in self.pool.evicted]
        compiled = 0
        with self._tl.span(f"reshard[{label}]", kind="stage") as info:
            for bucket in self.buckets:
                for variant in VARIANTS:
                    for device in survivors:
                        self._get_compiled(bucket, variant, device=device)
                        compiled += 1
            migrated = 0
            for bucket, entries in leftovers:
                self._place_batch(bucket, entries)
                migrated += len(entries)
            info["value"] = {"device": label, "reason": reason,
                             "confirmed_executables": compiled,
                             "migrated_requests": migrated}
        seconds = round(time.monotonic() - t0, 6)
        facts = {"index": index, "device": label, "reason": reason,
                 "migrated": migrated, "migrated_batches": len(leftovers),
                 "reshard_seconds": seconds,
                 "target_batches": batches_before,
                 "survivors": len(survivors), "ts": time.monotonic()}
        self.registry.counter("recovery_evictions", device=label).inc()
        self.registry.gauge("recovery_pool_survivors").set(len(survivors))
        from ft_sgemm_tpu import telemetry

        telemetry.record_step_event(
            "evicted", op="serve_pool",
            extra={"device": label, "reason": reason,
                   "migrated": migrated,
                   "reshard_seconds": seconds})
        self._tl.point("recovery", "evicted", device=label, reason=reason,
                       migrated=migrated, reshard_seconds=seconds)
        if self.monitor is not None:
            self.monitor.observe_retry(
                {"outcome": "evicted", "op": "serve_pool",
                 "ts": time.time(),
                 "extra": {"device": label, "reason": reason,
                           "migrated": migrated}})
        if self.elastic is not None:
            self.elastic.record_eviction(facts)
        return facts

    def _place_batch(self, bucket: Bucket, entries: Sequence[_Entry]):
        """Pool mode: the dispatcher only PLACES — the chosen device's
        worker executes. The placement decision lands in the timeline
        (trace flow: WHERE each request ran) and the per-device gauges,
        and the choice itself is the health steer: a drained device's
        queue receives nothing new."""
        self._check_elastic()
        index = self.pool.choose()
        label = self.pool.labels[index]
        depth = self.pool.put(index, (bucket, entries))
        self.registry.gauge("serve_pool_queue_depth",
                            device=label).set(depth)
        self.registry.counter("serve_pool_placements", device=label).inc()
        self._tl.point("serve", "placement", device=label,
                       pool_placement=self.pool.placement,
                       bucket=bucket.key,
                       trace_ids=[e.request.trace_id for e in entries])

    def _pool_worker(self, index: int):
        label = self.pool.labels[index]
        while True:
            item = self.pool.get(index)
            if item is None:
                if self.pool.stopped:
                    return
                continue
            self.registry.gauge("serve_pool_queue_depth", device=label) \
                .set(self.pool.queue_depth(index))
            bucket, entries = item
            self.pool.note_batch(index, len(entries))
            self.registry.counter("serve_pool_batches", device=label).inc()
            self._execute_batch(bucket, entries, device_index=index)

    def drain(self, timeout: Optional[float] = None) -> None:
        """Block until every submitted request has resolved. Flushes
        partial batches immediately (max_wait is waived while draining).
        A drain of an empty queue returns at once."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            self._draining = True
            self._cond.notify_all()
            try:
                while self._outstanding > 0:
                    if deadline is not None and time.monotonic() > deadline:
                        raise TimeoutError(
                            f"drain timed out with {self._outstanding}"
                            " requests outstanding")
                    self._cond.wait(0.05)
            finally:
                self._draining = False

    def close(self) -> None:
        """Stop the dispatcher (and any pool workers). Unresolved
        futures are rejected (a closed engine must never strand a
        waiter)."""
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        leftovers = []
        if self.pool is not None:
            for bucket, entries in self.pool.stop():
                leftovers.extend(entries)
            for t in self._pool_threads:
                t.join(timeout=10.0)
            self._pool_threads = []
        with self._cond:
            for q in self._pending.values():
                leftovers.extend(q)
                q.clear()
            self._outstanding -= len(leftovers)
        for entry in leftovers:
            entry.future._reject(RuntimeError("ServeEngine closed with"
                                              " request still queued"))

    # -- execution ----------------------------------------------------------

    def _pad_operands(self, bucket: Bucket, request: ServeRequest):
        m, n, k = request.mnk
        a = np.zeros((bucket.m, bucket.k), np.float32)
        b = np.zeros((bucket.n, bucket.k), np.float32)
        c = np.zeros((bucket.m, bucket.n), np.float32)
        a[:m, :k] = request.a
        b[:n, :k] = request.b
        if request.c is not None:
            c[:m, :n] = request.c
        if not bucket.epilogue_spec.bias:
            return a, b, c
        bias = np.zeros((bucket.n,), np.float32)
        if request.bias is not None:
            bias[:n] = request.bias
        return a, b, c, bias

    def _execute_batch(self, bucket: Bucket, entries: Sequence[_Entry],
                       device_index: Optional[int] = None):
        with self._stats_lock:
            self._counts["batches"] += 1
            self._per_bucket[bucket.key]["batches"] += 1
        self.registry.counter("serve_batches", bucket=bucket.key).inc()
        # The batch span names every in-flight trace: a kill mid-flush
        # still says WHICH requests were riding the batch.
        trace_ids = [e.request.trace_id for e in entries]
        with self._tl.span(f"serve[{bucket.key}]", kind="stage",
                           trace_ids=trace_ids) as info:
            det_total = unc_total = 0
            if device_index is None:
                for entry in entries:
                    det, unc = self._execute_one(bucket, entry)
                    det_total += det
                    unc_total += unc
            else:
                det_total, unc_total = self._execute_batch_pooled(
                    bucket, entries, device_index)
            info["value"] = {"batch": len(entries),
                             "detections": det_total,
                             "uncorrectable_final": unc_total,
                             "trace_ids": trace_ids}
            if device_index is not None:
                info["value"]["device"] = self.pool.labels[device_index]

    def _execute_batch_pooled(self, bucket: Bucket,
                              entries: Sequence[_Entry],
                              device_index: int) -> Tuple[int, int]:
        """One batch on one pool device, with a bounded ASYNC in-flight
        window: up to ``pool.max_in_flight`` requests' executables are
        launched (JAX async dispatch — the call returns before the
        device finishes) before the oldest result is materialized and
        its retry ladder/future run. The next request's host-side
        padding and bookkeeping ride under the previous one's device
        compute instead of behind a synchronous per-request wait."""
        device = self.pool.devices[device_index]
        label = self.pool.labels[device_index]
        det_total = unc_total = 0
        window = []

        def complete(item):
            nonlocal det_total, unc_total
            entry, operands, res = item
            det, unc = self._execute_one(
                bucket, entry, device_index=device_index,
                prelaunched=(operands, res))
            n_inf = self.pool.adjust_in_flight(device_index, -1)
            self.registry.gauge("serve_pool_in_flight",
                                device=label).set(n_inf)
            det_total += det
            unc_total += unc

        for entry in entries:
            operands = self._pad_operands(bucket, entry.request)
            compiled = self._get_compiled(bucket, entry.request.variant,
                                          device=device)
            res = compiled(*operands)  # async: materialized at complete()
            n_inf = self.pool.adjust_in_flight(device_index, +1)
            self.registry.gauge("serve_pool_in_flight",
                                device=label).set(n_inf)
            window.append((entry, operands, res))
            if len(window) >= self.pool.max_in_flight:
                complete(window.pop(0))
        while window:
            complete(window.pop(0))
        return det_total, unc_total

    def _execute_one(self, bucket: Bucket, entry: _Entry,
                     device_index: Optional[int] = None,
                     prelaunched=None) -> Tuple[int, int]:
        """Run one request (with the bucket-scoped retry ladder); resolve
        its future. Returns the final (detections, uncorrectable).

        The whole execution window runs inside the request's
        :func:`~ft_sgemm_tpu.serve.tracing.trace_scope`, and every event
        it emits — the ``serve_gemm`` record, each ``retry``, a terminal
        ``exhausted`` — carries ``extra["trace_id"]``, so one grep joins
        the user request to the tile/device that corrupted it and to the
        retry that saved (or failed) it."""
        from ft_sgemm_tpu import telemetry

        request = entry.request
        with trace_scope(request.trace_id):
            return self._execute_one_traced(
                bucket, entry, telemetry, device_index=device_index,
                prelaunched=prelaunched)

    def _execute_one_traced(self, bucket: Bucket, entry: _Entry,
                            telemetry, device_index: Optional[int] = None,
                            prelaunched=None) -> Tuple[int, int]:
        request = entry.request
        trace_id = request.trace_id
        m, n, _ = request.mnk
        device = (None if device_index is None
                  else self.pool.devices[device_index])
        if prelaunched is not None:
            # Pool path: attempt 0 was already launched asynchronously
            # by the batch's in-flight window; materializing it here is
            # the bounded wait.
            operands, first_res = prelaunched
        else:
            operands = self._pad_operands(bucket, request)
            first_res = None
        variant = request.variant
        retries = 0
        res = det = unc = None
        while True:
            if first_res is not None:
                res, first_res = first_res, None
            else:
                compiled = self._get_compiled(bucket, variant,
                                              device=device)
                res = compiled(*operands)
            det = int(np.sum(np.asarray(res.detections)))
            unc = int(np.sum(np.asarray(res.uncorrectable)))
            if unc == 0 or retries >= self.max_retries:
                break
            # Bucket-scoped retry: ONLY this bucket's affected request
            # re-executes; every other bucket's queue — and even this
            # bucket's clean batchmates — are untouched. Bounded, backed
            # off, and recorded as a ladder event. The retry runs the
            # clean variant: the injected fault models a transient SDC,
            # which does not replay on identical data.
            retries += 1
            backoff = self.retry_backoff * (2 ** (retries - 1))
            with self._stats_lock:
                self._counts["retries"] += 1
                self._per_bucket[bucket.key]["retries"] += 1
            self.registry.counter("serve_retries",
                                  bucket=bucket.key).inc()
            retry_extra = {"trace_id": trace_id,
                           "bucket": bucket.key,
                           "request_id": request.request_id,
                           "attempt": retries,
                           "backoff_seconds": round(backoff, 6)}
            telemetry.record_step_event(
                "retry", op="serve", uncorrectable=unc, extra=retry_extra)
            # The retry hop also lands in the run timeline (not just the
            # telemetry stream): the streamed file must carry the whole
            # enqueue -> flush -> retry trace join on its own, so a
            # trace-export of a killed run — or one with telemetry off —
            # still draws the flow (DESIGN.md §13).
            self._tl.point("serve", "retry", trace_id=trace_id,
                           bucket=bucket.key, attempt=retries,
                           uncorrectable=unc)
            if self.monitor is not None:
                self.monitor.observe_retry(
                    {"outcome": "retry", "op": "serve",
                     "uncorrectable": unc, "ts": time.time(),
                     "extra": retry_extra})
            if backoff > 0:
                time.sleep(backoff)
            variant = "clean"
        ok = unc == 0
        corrected = ok and det > 0
        if corrected:
            with self._stats_lock:
                self._counts["corrected_free"] += 1
            self.registry.counter("serve_corrected_free",
                                  bucket=bucket.key).inc()
        if not ok:
            with self._stats_lock:
                self._counts["uncorrectable_exhausted"] += 1
            self.registry.counter("serve_uncorrectable_exhausted",
                                  bucket=bucket.key).inc()
            exhausted_extra = {"trace_id": trace_id,
                               "bucket": bucket.key,
                               "request_id": request.request_id,
                               "attempts": retries}
            telemetry.record_step_event(
                "exhausted", op="serve", uncorrectable=unc,
                extra=exhausted_extra)
            self._tl.point("serve", "exhausted", trace_id=trace_id,
                           bucket=bucket.key, attempts=retries,
                           uncorrectable=unc)
            if self.monitor is not None:
                self.monitor.observe_retry(
                    {"outcome": "exhausted", "op": "serve",
                     "uncorrectable": unc, "ts": time.time(),
                     "extra": exhausted_extra})
        latency = time.monotonic() - entry.t_enqueue
        det_grid = np.asarray(res.detections)
        blame = np.argwhere(det_grid != 0)
        blame_tiles = ([[int(i), int(j)] for i, j in blame]
                       if blame.size else None)
        for labels in ({}, {"bucket": bucket.key}):
            self.registry.histogram("serve_latency_seconds",
                                    buckets=LATENCY_BUCKETS,
                                    **labels).observe(latency)
        request_extra = {
            "trace_id": trace_id,
            "request_id": request.request_id,
            "bucket": bucket.key,
            "variant": request.variant,
            "retries": retries,
            "latency_seconds": round(latency, 6)}
        if bucket.epilogue != "none":
            # Epilogue-fused buckets label their events with the fused
            # spelling; epilogue-free buckets' events stay byte-identical
            # to the pre-epilogue build.
            request_extra["epilogue"] = bucket.epilogue
        if telemetry.enabled():
            # Per-request fault attribution: the request's OWN counter
            # grids (not the batch's, not the process's) feed the event,
            # so `cli telemetry` blames faults on requests.
            telemetry.record_gemm(
                "serve_gemm", res, strategy=bucket.strategy,
                layer=bucket.key, extra=dict(request_extra))
        if self.monitor is not None:
            # The monitor's direct feed: the same event shape the JSONL
            # stream carries, plus the executed device — so the health
            # scorer attributes serve traffic without a mesh.
            self.monitor.observe_request({
                "outcome": ("uncorrectable" if not ok else
                            "corrected" if corrected else "clean"),
                "op": "serve_gemm", "detected": det,
                "corrected": det if corrected else 0,
                "uncorrectable": unc, "strategy": bucket.strategy,
                "layer": bucket.key, "tiles": blame_tiles,
                "device": _device_label(res.c), "ts": time.time(),
                "extra": dict(request_extra, ok=ok)})
        try:
            # Cost plane: price the request with the SAME component
            # cost model the roofline uses. The bucket shape (not the
            # ragged request shape) is what actually executed — padding
            # flops are spent for real, so they are what gets split
            # into productive vs overhead. Tokens = the request's own
            # output rows (the ragged m), correct only when the final
            # result verified.
            from ft_sgemm_tpu.ops.common import gemm_cost_breakdown

            itemsize = {"bfloat16": 2, "int8": 1,
                        "float8_e4m3fn": 1}.get(bucket.in_dtype, 4)
            tile = self._bucket_tile(bucket)
            parts = gemm_cost_breakdown(
                bucket.m, bucket.n, bucket.k, itemsize,
                block=(tile.bm, tile.bn, tile.bk),
                strategy=bucket.strategy)
            productive, overhead = gemm_request_cost(parts,
                                                     retries=retries)
            self.economics.add(
                flops_productive=productive, overhead=overhead,
                tokens=m, tokens_correct=m if ok else 0,
                seconds=latency, device=_device_label(res.c),
                bucket=bucket.key, trace_id=trace_id,
                request_id=request.request_id, ok=ok)
            self.economics.publish(self.registry)
            if self.monitor is not None:
                self.monitor.observe_economics(self.economics.snapshot())
        except Exception:  # noqa: BLE001 — accounting never fails serving
            pass
        out = np.asarray(res.c)[:m, :n]
        result = ServeResult(
            request_id=request.request_id, bucket_key=bucket.key,
            c=out, detections=det, uncorrectable=unc, retries=retries,
            ok=ok, corrected=corrected, latency_seconds=latency,
            blame_tiles=blame_tiles, trace_id=trace_id)
        with self._stats_lock:
            self._counts["completed"] += 1
        entry.future._resolve(result)
        with self._cond:
            self._outstanding -= 1
            self._cond.notify_all()
        return det, unc

    # -- stats --------------------------------------------------------------

    def latency_percentiles(self, quantiles=(0.5, 0.99)) -> dict:
        """p50/p99/max latency estimates straight from the registry's
        ``serve_latency_seconds`` histogram — the telemetry machinery IS
        the stats implementation (there is deliberately no second one)."""
        hist = self.registry.histogram("serve_latency_seconds",
                                       buckets=LATENCY_BUCKETS)
        return histogram_percentiles(hist.value, quantiles=quantiles)

    def stats(self) -> dict:
        """Snapshot: engine counters, per-bucket rows, latency
        percentiles."""
        with self._stats_lock:
            counts = dict(self._counts)
            per_bucket = {k: dict(v) for k, v in self._per_bucket.items()}
        out = dict(counts)
        out["per_bucket"] = per_bucket
        out["prewarmed"] = self._prewarmed
        out["latency"] = self.latency_percentiles()
        out["economics"] = self.economics.snapshot()
        if self.pool is not None:
            out["pool"] = self.pool.stats()
        return out


__all__ = ["ServeEngine", "ServeRequest", "ServeResult", "VARIANTS"]
