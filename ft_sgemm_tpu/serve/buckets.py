"""Shape bucketing: map ragged request streams onto a small padded set.

A serving stream is ragged — every request brings its own (M, N, K) — but
one compiled executable serves exactly one operand shape. Recompiling per
request would put XLA compile on the hot path (the exact wall sink the
PR-6 phase attribution measured dominating the bench rounds), so the
serving layer folds the stream onto a SMALL, FIXED set of padded buckets:

- Each :class:`Bucket` is a padded ``(M, N, K, dtype, strategy)`` target.
  A request is routed to the smallest bucket that fits (exact-boundary
  shapes route to their own bucket — no unnecessary padding step), its
  operands are zero-padded to the bucket dims, and the result is sliced
  back to the request's true shape. Zero padding is exact for GEMM: the
  padded rows/columns contribute nothing.
- Bucket dims are powers of two floored at the 128 MXU granule — the SAME
  bucketing the autotuner cache keys on (``tuner.mnk_bucket``), so every
  bucket's dispatch hits at most ONE tuner-cache entry, and prewarming the
  bucket set AOT-compiles exactly the executables steady-state requests
  will run.
- A request larger than the largest bucket is REJECTED with the named
  :class:`BucketOverflowError` (silent unbounded padding or per-request
  recompiles are both worse than a clear refusal the caller can route to
  a bigger deployment).

Per-dtype strategy legality is enforced at bucket construction through
``configs.check_kernel_legality`` — an int8 bucket can only carry the
exact strategies (``rowcol``/``global``), so int8 requests are routed to
``rowcol`` kernels by construction (the PR-7 constraint).
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, Optional, Sequence, Tuple

from ft_sgemm_tpu.configs import (
    DEFAULT_STRATEGY,
    canonical_in_dtype,
    check_kernel_legality,
)


class BucketOverflowError(ValueError):
    """A request exceeds every configured bucket — named so servers can
    map it to a clean client-facing rejection instead of a 500."""


def _pow2_dim(v: int) -> int:
    """Next power of two >= v, floored at 128 (tuner.mnk_bucket's rule)."""
    b = 128
    while b < v:
        b *= 2
    return b


@dataclasses.dataclass(frozen=True)
class Bucket:
    """One padded serving target: requests routed here run one compiled
    kernel family at exactly ``(m, n, k)`` in ``in_dtype`` under
    ``strategy``.

    Dims must be positive multiples of 128 (the MXU granule every
    ``KernelShape`` is built from); the (strategy, dtype) pair must pass
    the kernel family's legality gate — constructing an int8 bucket with
    a ratio-localizing strategy raises the factory's own error.
    """

    m: int
    n: int
    k: int
    in_dtype: str = "float32"
    strategy: str = "weighted"

    def __post_init__(self):
        for field in ("m", "n", "k"):
            v = getattr(self, field)
            if not isinstance(v, int) or v <= 0 or v % 128 != 0:
                raise ValueError(
                    f"Bucket.{field}={v!r} must be a positive multiple of"
                    " 128 (MXU granule; tuner-cache bucket alignment)")
        # Canonicalize the dtype AND validate the (strategy, dtype) pair
        # with the kernel factory's single legality source — the int8 ->
        # rowcol/global routing constraint lives there, not here.
        canon = check_kernel_legality(
            strategy=self.strategy, encode="vpu", in_dtype=self.in_dtype)
        object.__setattr__(self, "in_dtype", canon)

    @property
    def key(self) -> str:
        """Stable bucket identity: dims, dtype, strategy."""
        return f"{self.m}x{self.n}x{self.k}|{self.in_dtype}|{self.strategy}"

    @property
    def volume(self) -> int:
        return self.m * self.n * self.k

    def fits(self, m: int, n: int, k: int) -> bool:
        return m <= self.m and n <= self.n and k <= self.k


def default_bucket_set(sizes: Sequence[int] = (256, 512, 1024),
                       in_dtype: str = "float32",
                       strategy: Optional[str] = None) -> Tuple[Bucket, ...]:
    """A ladder of square buckets — the deliberately SMALL default set.

    Square powers of two keep the set prewarmable in seconds and make
    every bucket's dims equal its own tuner-cache bucket
    (``mnk_bucket(m, n, k) == (m, n, k)`` for power-of-two dims), so one
    ``cli tune SIZE`` per rung covers the whole serving path. ``strategy``
    defaults per dtype: ``weighted`` (the family flagship — deferred
    localization, lowest overhead) for the float dtypes, ``rowcol`` for
    int8, whose exact path ships only the non-ratio-localizing
    strategies (``configs.check_kernel_legality``, the PR-7 routing
    constraint).
    """
    dtype = canonical_in_dtype(in_dtype)
    if strategy is None:
        # One declaration for per-dtype routing (configs.DEFAULT_STRATEGY,
        # machine-checked against the legality tables) instead of a local
        # int8-vs-rest spelling that could drift from the kernel family.
        strategy = DEFAULT_STRATEGY[dtype]
    out = []
    for s in sorted(set(int(v) for v in sizes)):
        if s != _pow2_dim(s):
            raise ValueError(
                f"default_bucket_set sizes must be powers of two >= 128"
                f" (tuner-cache bucket alignment), got {s}")
        out.append(Bucket(s, s, s, in_dtype=dtype, strategy=strategy))
    if not out:
        raise ValueError("default_bucket_set needs at least one size")
    return tuple(out)


def select_bucket(buckets: Iterable[Bucket], m: int, n: int, k: int,
                  in_dtype: str = "float32") -> Bucket:
    """The smallest configured bucket that fits an ``(m, n, k, dtype)``
    request — smallest by padded volume, so boundary-exact shapes pay
    zero padding and ragged ones pay the least available.

    Raises :class:`BucketOverflowError` (with the request shape and the
    largest available bucket named) when nothing fits — the caller's cue
    to reject the request, never to silently compile a fresh shape.
    """
    dtype = canonical_in_dtype(in_dtype)
    fitting = [b for b in buckets
               if b.in_dtype == dtype and b.fits(m, n, k)]
    if not fitting:
        same_dtype = [b for b in buckets if b.in_dtype == dtype]
        largest = (max(same_dtype, key=lambda b: b.volume).key
                   if same_dtype else "none configured for this dtype")
        raise BucketOverflowError(
            f"request {m}x{n}x{k} ({dtype}) exceeds every configured"
            f" bucket (largest: {largest}); reject or deploy a larger"
            " bucket set")
    return min(fitting, key=lambda b: (b.volume, b.key))


__all__ = ["Bucket", "BucketOverflowError", "default_bucket_set",
           "select_bucket"]
