"""Shape bucketing: map ragged request streams onto a small padded set.

A serving stream is ragged — every request brings its own (M, N, K) — but
one compiled executable serves exactly one operand shape. Recompiling per
request would put XLA compile on the hot path (the exact wall sink the
PR-6 phase attribution measured dominating the bench rounds), so the
serving layer folds the stream onto a SMALL, FIXED set of padded buckets:

- Each :class:`Bucket` is a padded ``(M, N, K, dtype, strategy)`` target.
  A request is routed to the smallest bucket that fits (exact-boundary
  shapes route to their own bucket — no unnecessary padding step), its
  operands are zero-padded to the bucket dims, and the result is sliced
  back to the request's true shape. Zero padding is exact for GEMM: the
  padded rows/columns contribute nothing.
- Bucket dims are powers of two floored at the 128 MXU granule — the SAME
  bucketing the autotuner cache keys on (``tuner.mnk_bucket``), so every
  bucket's dispatch hits at most ONE tuner-cache entry, and prewarming the
  bucket set AOT-compiles exactly the executables steady-state requests
  will run.
- A request larger than the largest bucket is REJECTED with the named
  :class:`BucketOverflowError` (silent unbounded padding or per-request
  recompiles are both worse than a clear refusal the caller can route to
  a bigger deployment).

Per-dtype strategy legality is enforced at bucket construction through
``configs.check_kernel_legality`` — an int8 bucket can only carry the
exact strategies (``rowcol``/``global``), so int8 requests are routed to
``rowcol`` kernels by construction (the PR-7 constraint).
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, Optional, Sequence, Tuple

from ft_sgemm_tpu.configs import (
    DEFAULT_STRATEGY,
    EpilogueSpec,
    canonical_in_dtype,
    check_kernel_legality,
)


class BucketOverflowError(ValueError):
    """A request exceeds every configured bucket — named so servers can
    map it to a clean client-facing rejection instead of a 500."""


def pow2_dim(v: int) -> int:
    """Next power of two >= v, floored at 128 (tuner.mnk_bucket's rule —
    the ONE padding rule every serving bucket family shares, GEMM mnk
    and transformer-block sequence dims alike)."""
    b = 128
    while b < v:
        b *= 2
    return b


_pow2_dim = pow2_dim  # original (pre-block) spelling, kept for callers


@dataclasses.dataclass(frozen=True)
class Bucket:
    """One padded serving target: requests routed here run one compiled
    kernel family at exactly ``(m, n, k)`` in ``in_dtype`` under
    ``strategy``.

    Dims must be positive multiples of 128 (the MXU granule every
    ``KernelShape`` is built from); the (strategy, dtype) pair must pass
    the kernel family's legality gate — constructing an int8 bucket with
    a ratio-localizing strategy raises the factory's own error.
    """

    m: int
    n: int
    k: int
    in_dtype: str = "float32"
    strategy: str = "weighted"
    # Fused-epilogue spelling (configs.EpilogueSpec) every request this
    # bucket serves runs: bias/activation/quantize fused into the FT
    # kernel's detect-correct epilogue — what int8/fp8 serving actually
    # wants from a GEMM endpoint. "none" (the default) keeps the bucket's
    # executables byte-identical to the pre-epilogue build.
    epilogue: str = "none"

    def __post_init__(self):
        for field in ("m", "n", "k"):
            v = getattr(self, field)
            if not isinstance(v, int) or v <= 0 or v % 128 != 0:
                raise ValueError(
                    f"Bucket.{field}={v!r} must be a positive multiple of"
                    " 128 (MXU granule; tuner-cache bucket alignment)")
        # Canonicalize the dtype AND validate the (strategy, dtype) pair
        # with the kernel factory's single legality source — the int8 ->
        # rowcol/global routing constraint lives there, not here.
        canon = check_kernel_legality(
            strategy=self.strategy, encode="vpu", in_dtype=self.in_dtype)
        object.__setattr__(self, "in_dtype", canon)
        # One parser for the epilogue spelling (CLI / tuner key / bucket
        # field all agree), canonicalized so spellings key stably.
        object.__setattr__(
            self, "epilogue", EpilogueSpec.parse(self.epilogue).spelling)

    @property
    def epilogue_spec(self) -> EpilogueSpec:
        return EpilogueSpec.parse(self.epilogue)

    @property
    def key(self) -> str:
        """Stable bucket identity: dims, dtype, strategy — and the fused
        epilogue when one is configured (historical keys unchanged for
        epilogue-free buckets)."""
        base = f"{self.m}x{self.n}x{self.k}|{self.in_dtype}|{self.strategy}"
        if self.epilogue != "none":
            base += f"|epi={self.epilogue}"
        return base

    @property
    def volume(self) -> int:
        return self.m * self.n * self.k

    def fits(self, m: int, n: int, k: int) -> bool:
        return m <= self.m and n <= self.n and k <= self.k


def default_bucket_set(sizes: Sequence[int] = (256, 512, 1024),
                       in_dtype: str = "float32",
                       strategy: Optional[str] = None,
                       epilogue: str = "none") -> Tuple[Bucket, ...]:
    """A ladder of square buckets — the deliberately SMALL default set.

    Square powers of two keep the set prewarmable in seconds and make
    every bucket's dims equal its own tuner-cache bucket
    (``mnk_bucket(m, n, k) == (m, n, k)`` for power-of-two dims), so one
    ``cli tune SIZE`` per rung covers the whole serving path. ``strategy``
    defaults per dtype: ``weighted`` (the family flagship — deferred
    localization, lowest overhead) for the float dtypes, ``rowcol`` for
    int8, whose exact path ships only the non-ratio-localizing
    strategies (``configs.check_kernel_legality``, the PR-7 routing
    constraint).
    """
    dtype = canonical_in_dtype(in_dtype)
    if strategy is None:
        # One declaration for per-dtype routing (configs.DEFAULT_STRATEGY,
        # machine-checked against the legality tables) instead of a local
        # int8-vs-rest spelling that could drift from the kernel family.
        strategy = DEFAULT_STRATEGY[dtype]
    out = []
    for s in sorted(set(int(v) for v in sizes)):
        if s != _pow2_dim(s):
            raise ValueError(
                f"default_bucket_set sizes must be powers of two >= 128"
                f" (tuner-cache bucket alignment), got {s}")
        out.append(Bucket(s, s, s, in_dtype=dtype, strategy=strategy,
                          epilogue=epilogue))
    if not out:
        raise ValueError("default_bucket_set needs at least one size")
    return tuple(out)


def select_bucket(buckets: Iterable[Bucket], m: int, n: int, k: int,
                  in_dtype: str = "float32") -> Bucket:
    """The smallest configured bucket that fits an ``(m, n, k, dtype)``
    request — smallest by padded volume, so boundary-exact shapes pay
    zero padding and ragged ones pay the least available.

    Raises :class:`BucketOverflowError` (with the request shape and the
    largest available bucket named) when nothing fits — the caller's cue
    to reject the request, never to silently compile a fresh shape.
    """
    dtype = canonical_in_dtype(in_dtype)
    fitting = [b for b in buckets
               if b.in_dtype == dtype and b.fits(m, n, k)]
    if not fitting:
        same_dtype = [b for b in buckets if b.in_dtype == dtype]
        largest = (max(same_dtype, key=lambda b: b.volume).key
                   if same_dtype else "none configured for this dtype")
        raise BucketOverflowError(
            f"request {m}x{n}x{k} ({dtype}) exceeds every configured"
            f" bucket (largest: {largest}); reject or deploy a larger"
            " bucket set")
    return min(fitting, key=lambda b: (b.volume, b.key))


# ---------------------------------------------------------------------------
# Transformer-block buckets: ragged sequences onto padded (L_q, L_k)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class BlockBucket:
    """One padded transformer-block serving target: attention requests
    routed here run ONE compiled executor at exactly ``(lq, d) x (lk, d)
    x (lk, dv)`` under ``strategy``. Sequence dims follow the SAME
    tuner-aligned power-of-two-at-128 rule GEMM buckets use
    (:func:`pow2_dim`); head dims ``d``/``dv`` are fixed per bucket set
    (the model's geometry, not a ragged axis).

    Decode buckets keep ``lq < lk`` (a single new query over a long
    cached prefix). Causal masking is end-anchored by placing the real
    query row at ``lq - 1 - (lk - len)``, which requires
    ``len > lk - lq`` — :meth:`fits_decode` enforces it, and
    :func:`default_block_bucket_set` builds decode rungs with
    ``lq = lk / 2`` (floored at 128) so the smallest fitting rung always
    satisfies it.
    """

    lq: int
    lk: int
    d: int
    dv: int
    in_dtype: str = "float32"
    strategy: str = "weighted"

    def __post_init__(self):
        for field in ("lq", "lk"):
            v = getattr(self, field)
            if not isinstance(v, int) or v <= 0 or v != pow2_dim(v):
                raise ValueError(
                    f"BlockBucket.{field}={v!r} must be a power of two"
                    " >= 128 (tuner-cache bucket alignment)")
        for field in ("d", "dv"):
            v = getattr(self, field)
            if not isinstance(v, int) or v <= 0:
                raise ValueError(
                    f"BlockBucket.{field}={v!r} must be a positive int")
        if self.lq > self.lk:
            raise ValueError(
                f"BlockBucket lq={self.lq} > lk={self.lk}: causal serving"
                " never has more queries than keys")
        canon = check_kernel_legality(
            strategy=self.strategy, encode="vpu", in_dtype=self.in_dtype)
        object.__setattr__(self, "in_dtype", canon)

    @property
    def key(self) -> str:
        """Stable bucket identity: padded seq dims, head dims, dtype,
        strategy."""
        return (f"L{self.lq}xK{self.lk}xD{self.d}v{self.dv}"
                f"|{self.in_dtype}|{self.strategy}")

    @property
    def volume(self) -> int:
        # Padded attention work ~ lq*lk*(d + dv): both GEMMs' FLOP scale.
        return self.lq * self.lk * (self.d + self.dv)

    def fits_prefill(self, length: int) -> bool:
        return length <= self.lq and length <= self.lk

    def fits_decode(self, length: int) -> bool:
        """One query over ``length`` cached keys: needs the keys to fit
        AND the end-anchored causal placement to exist (see class
        docstring)."""
        return length <= self.lk and length > self.lk - self.lq


def default_block_bucket_set(seq_sizes: Sequence[int] = (128, 256, 512),
                             d: int = 64, dv: Optional[int] = None,
                             in_dtype: str = "float32",
                             strategy: Optional[str] = None
                             ) -> Tuple[BlockBucket, ...]:
    """The block-bucket ladder: per padded sequence rung ``s``, one
    PREFILL bucket ``(s, s)`` and one DECODE bucket ``(max(128, s/2),
    s)`` (deduped where they coincide). The half-lq decode rule makes
    the smallest fitting rung always satisfy the end-anchored causal
    placement (``len > lk - lq`` holds whenever ``len > lk/2``, which
    the power-of-two ladder guarantees for the smallest ``lk >= len``).
    """
    dtype = canonical_in_dtype(in_dtype)
    if strategy is None:
        strategy = DEFAULT_STRATEGY[dtype]
    dv = d if dv is None else dv
    out = []
    for s in sorted(set(int(v) for v in seq_sizes)):
        if s != pow2_dim(s):
            raise ValueError(
                f"default_block_bucket_set sizes must be powers of two"
                f" >= 128 (tuner-cache bucket alignment), got {s}")
        for lq in (s, max(128, s // 2)):
            b = BlockBucket(lq, s, d, dv, in_dtype=dtype,
                            strategy=strategy)
            if b not in out:
                out.append(b)
    if not out:
        raise ValueError("default_block_bucket_set needs at least one"
                         " size")
    return tuple(out)


def select_block_bucket(buckets: Iterable[BlockBucket], length: int,
                        phase: str, in_dtype: str = "float32"
                        ) -> BlockBucket:
    """The smallest configured block bucket that fits a ``length``-token
    request of the given phase (``"prefill"`` routes on
    :meth:`BlockBucket.fits_prefill`, ``"decode"`` on
    :meth:`~BlockBucket.fits_decode`). Raises
    :class:`BucketOverflowError` when nothing fits — same refusal
    contract as :func:`select_bucket`."""
    dtype = canonical_in_dtype(in_dtype)
    fits = (BlockBucket.fits_prefill if phase == "prefill"
            else BlockBucket.fits_decode)
    fitting = [b for b in buckets
               if b.in_dtype == dtype and fits(b, length)]
    if not fitting:
        same = [b for b in buckets if b.in_dtype == dtype]
        largest = (max(same, key=lambda b: b.volume).key
                   if same else "none configured for this dtype")
        raise BucketOverflowError(
            f"{phase} request of {length} tokens ({dtype}) exceeds every"
            f" configured block bucket (largest: {largest}); reject or"
            " deploy a larger bucket set")
    return min(fitting, key=lambda b: (b.volume, b.key))


__all__ = ["BlockBucket", "Bucket", "BucketOverflowError",
           "default_block_bucket_set", "default_bucket_set", "pow2_dim",
           "select_block_bucket", "select_bucket"]
