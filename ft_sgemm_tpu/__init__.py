"""ft_sgemm_tpu — TPU-native fault-tolerant SGEMM framework.

A from-scratch JAX/XLA/Pallas re-design of the capabilities of
shixun404/Fault-Tolerant-SGEMM-on-NVIDIA-GPUs (arXiv:2305.01024):

- a parameterized Pallas MXU kernel family (6 named shapes) computing
  ``C = alpha * A @ B.T + beta * C`` (reference: generated CUDA kernels in
  ``kernel/ft_sgemm/include_code_gen/``),
- a fused online-ABFT variant that encodes row/column checksums inside the
  matmul pipeline, detects silent data corruption against a threshold, and
  corrects the corrupted accumulator entries in the same kernel
  (reference: ``include_code_gen/ft_sgemm_*.cuh``),
- a two-pass (non-fused) ABFT baseline built from plain XLA ops
  (reference: ``kernel/ft_sgemm/include/baseline_ft_sgemm.cuh``),
- first-class, parameterized fault injection (the reference hardcodes
  injection constants into the generated kernels, ``code_gen.py:333-337``),
- an argv-compatible CLI driver + GFLOPS bench harness
  (reference: ``kernel/ft_sgemm/sgemm.cu``; see ``ft_sgemm_tpu.cli``).

Nothing here is a translation of the CUDA sources: block/warp/thread tiling
becomes Pallas grid/BlockSpec tiling onto the 128x128 MXU, warp shuffles
become tile-axis reductions, shared-memory double buffering becomes Mosaic's
automatically pipelined VMEM blocks.
"""

from ft_sgemm_tpu import perf, serve, telemetry, tuner, utils
from ft_sgemm_tpu.configs import (
    ENCODE_MODES,
    KERNEL_TABLE,
    SHAPES,
    KernelShape,
    kernel_for_id,
)
from ft_sgemm_tpu.injection import InjectionSpec
from ft_sgemm_tpu.ops.abft_baseline import abft_baseline_sgemm
from ft_sgemm_tpu.ops.attention import (
    FtAttentionResult,
    attention_reference,
    ft_attention,
    make_ft_attention,
    make_ft_attention_diff,
)
from ft_sgemm_tpu.ops.autodiff import (
    FtMatmulResult,
    ft_matmul,
    make_ft_matmul,
)
from ft_sgemm_tpu.ops.ft_sgemm import (
    STRATEGIES,
    FtSgemmResult,
    ft_sgemm,
    make_ft_sgemm,
)
from ft_sgemm_tpu.ops.reference import sgemm_reference
from ft_sgemm_tpu.ops.sgemm import make_sgemm, sgemm

__version__ = "0.1.0"

__all__ = [
    "KernelShape",
    "SHAPES",
    "KERNEL_TABLE",
    "kernel_for_id",
    "InjectionSpec",
    "sgemm_reference",
    "make_sgemm",
    "sgemm",
    "make_ft_sgemm",
    "ft_sgemm",
    "FtMatmulResult",
    "FtSgemmResult",
    "ENCODE_MODES",
    "STRATEGIES",
    "abft_baseline_sgemm",
    "FtAttentionResult",
    "attention_reference",
    "ft_attention",
    "make_ft_attention",
    "make_ft_attention_diff",
    "ft_matmul",
    "make_ft_matmul",
    "perf",
    "serve",
    "telemetry",
    "tuner",
]
