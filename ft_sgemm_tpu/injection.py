"""First-class fault-injection specification.

The reference bakes injection into the generated kernels as compile-time
constants: every ``K/20`` outer iterations, one rotating thread adds
``error_inject = 10000.0`` to its first accumulator element, with detection
threshold ``err_bound1 = 9500.0`` (``include_code_gen/ft_sgemm_huge.cuh:49-51,
324-327``; template ``code_gen.py:333-337``). Injection cannot be turned off
without regenerating and recompiling.

Here injection is a runtime parameter: an :class:`InjectionSpec` is lowered
into the Pallas kernel through scalar operands (SMEM), so the same compiled
kernel can run clean, or inject any count/magnitude/placement of faults. The
default spec reproduces the reference's schedule: ~20 faults per run, spread
across K, magnitude 1e4, rotating target element.
"""

from __future__ import annotations

import dataclasses

import numpy as np

# Reference constants (include_code_gen/ft_sgemm_huge.cuh:49-51).
REFERENCE_MAGNITUDE = 10000.0
REFERENCE_THRESHOLD = 9500.0
REFERENCE_NUM_FAULTS = 20


@dataclasses.dataclass(frozen=True)
class InjectionSpec:
    """Runtime description of accumulator-fault injection.

    Faults model silent data corruption in the f32 accumulator: at K-step
    ``k`` (a Pallas grid step along the contraction axis), if
    ``enabled and k % every == 0``, ``magnitude`` is added to one element of
    the accumulator tile. The element rotates with ``k // every`` (and with
    the output-tile coordinates) so successive faults land on different
    rows/columns, mirroring the reference's rotating ``tx`` target
    (``include_code_gen/ft_sgemm_huge.cuh:324-327``).

    ``enabled=False`` compiles to a no-op branch — the clean path the
    reference lacks.

    ``col_stride`` sets how far the target COLUMN advances per scheduled
    fault. The default 61 is coprime to every legal tile width, so
    consecutive faults land in distinct columns (the property the
    column-localized correcting strategies rely on). ``col_stride=0`` pins
    every fault to one column — the adversarial schedule that defeats
    per-column localization and exercises the kernels'
    residual-after-correct re-check (``FtSgemmResult.uncorrectable``).
    """

    enabled: bool = False
    every: int = 1  # inject at every k-step where k % every == 0
    magnitude: float = REFERENCE_MAGNITUDE
    col_stride: int = 61  # column advance per fault; 0 = same column always

    def __post_init__(self):
        if self.every < 1:
            raise ValueError(f"InjectionSpec.every={self.every} must be >= 1")
        if not np.isfinite(np.float32(self.magnitude)):
            raise ValueError(
                f"InjectionSpec.magnitude={self.magnitude} not finite in f32"
            )
        if self.col_stride < 0:
            raise ValueError(
                f"InjectionSpec.col_stride={self.col_stride} must be >= 0")

    @staticmethod
    def none() -> "InjectionSpec":
        return InjectionSpec(enabled=False)

    @staticmethod
    def reference_like(K: int, bk: int, num_faults: int = REFERENCE_NUM_FAULTS,
                       magnitude: float = REFERENCE_MAGNITUDE) -> "InjectionSpec":
        """Schedule ~num_faults faults across the K-grid of a (K, bk) run,
        like the reference's ``(k % (K/20)) == 0`` cadence
        (``code_gen.py:333``). The period rounds to nearest so the realized
        count lands as close to ``num_faults`` as the grid allows (floor
        would nearly double it when nk/num_faults is just above 1, e.g.
        nk=32 -> 32 faults instead of ~20 with the reference's ~16)."""
        num_k_steps = _num_k_steps(K, bk)
        every = max(1, round(num_k_steps / num_faults))
        return InjectionSpec(enabled=True, every=every, magnitude=magnitude)

    def as_operand(self) -> np.ndarray:
        """Pack into the (4,) f32 scalar operand consumed by the kernels:
        [enabled, every, magnitude, col_stride]."""
        return np.asarray(
            [1.0 if self.enabled else 0.0, float(self.every),
             float(self.magnitude), float(self.col_stride)],
            dtype=np.float32,
        )

    def expected_faults(self, K: int, bk: int) -> int:
        """Number of faults this spec injects over a full K sweep.

        Counts over the zero-padded K grid the kernels actually run
        (K rounded up to a multiple of bk)."""
        if not self.enabled:
            return 0
        num_k_steps = _num_k_steps(K, bk)
        return len([k for k in range(num_k_steps) if k % self.every == 0])


def _num_k_steps(K: int, bk: int) -> int:
    """K-grid length after the kernels' zero padding: ceil(K / bk)."""
    return max(1, -(-K // bk))


# Threshold note: REFERENCE_THRESHOLD (9500) pairs with the reference's
# 10000-magnitude faults (``ft_sgemm_huge.cuh:50``); inputs quantized to
# ±{0,.1,...,.9} (``utils.cu:23-31``) keep f32 checksum noise orders of
# magnitude below it even at K=6144.


# ---------------------------------------------------------------------------
# ROC sweep: static vs adaptive thresholds, per dtype x strategy x encode
# ---------------------------------------------------------------------------
#
# The artifact that closes the low-precision loop (ISSUE 7 / ROADMAP item
# 2): a STATIC detection threshold is one number for every run, but clean
# checksum-residual noise scales with the operands' variance (~scale^2 when
# both operands scale) — so a static threshold calibrated on one operating
# point false-positives when the data runs hotter and silently misses
# faults when it runs colder. The sweep makes that concrete: the same
# kernel family runs at several input scales, clean and fault-injected,
# under (a) the static threshold a careful engineer would ship (margin x
# the calibrated noise bound AT THE CALIBRATION SCALE) and (b)
# ``threshold="adaptive"`` (per-tile in-kernel variance bounds). Per
# (dtype, strategy, encode) the summary reports aggregate false-positive
# and detection rates for both modes and whether adaptive dominates
# (fp <= static AND detection >= static; ``strict`` when at least one is
# a strict improvement — everywhere noise exists, i.e. every float dtype;
# int8's exact integer arithmetic makes both modes perfect, an honest
# tie).

# Fault magnitude per run: FAULT_FACTOR x the run's noise bound — 8x the
# adaptive threshold (margin 8), so adaptive detection has the same
# headroom at every scale; the static threshold (calibrated at scale 1)
# overshoots it at CAL_SCALE/sqrt-ish colder scales and drowns under the
# clean noise at hotter ones.
ROC_FAULT_FACTOR = 64.0
ROC_CAL_SCALE = 1.0


@dataclasses.dataclass(frozen=True)
class RocPoint:
    """One (combo, mode, scale) cell of the ROC sweep."""

    dtype: str
    strategy: str
    encode: str
    mode: str                 # "static" | "adaptive"
    scale: float
    threshold: float | None   # the static threshold (None for adaptive)
    magnitude: float          # injected |fault|
    clean_detections: int     # detections on the CLEAN run (false positives)
    checks: int               # detection opportunities (tiles x checks)
    expected_faults: int      # faults injected over the run
    detected: int             # detections on the injected run

    @property
    def fp_rate(self) -> float:
        return self.clean_detections / self.checks if self.checks else 0.0

    @property
    def detection_rate(self) -> float:
        if not self.expected_faults:
            return 0.0
        return min(1.0, self.detected / self.expected_faults)

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["fp_rate"] = self.fp_rate
        d["detection_rate"] = self.detection_rate
        return d


def _roc_combos(dtypes, strategies, encodes):
    """The legal (dtype, strategy, encode) grid, canonical spellings only
    (``weighted``+mxu IS ``fused``: enumerate each program once)."""
    from ft_sgemm_tpu.configs import canonical_in_dtype, check_kernel_legality

    combos = []
    for dtype in dtypes:
        name = canonical_in_dtype(dtype)
        for strategy in strategies:
            for encode in encodes:
                if strategy == "fused" and encode != "mxu":
                    continue
                if strategy == "weighted" and encode == "mxu":
                    continue  # the fused spelling of the same program
                try:
                    check_kernel_legality(strategy=strategy, encode=encode,
                                          in_dtype=name,
                                          threshold_mode="adaptive")
                except ValueError:
                    continue
                combos.append((name, strategy, encode))
    return combos


def _roc_inputs(m, n, k, scale, dtype_name, seed):
    """Operands at one input scale.

    Float dtypes draw CONTINUOUS standard-normal data scaled by
    ``scale`` — the production distribution whose products genuinely
    round (the reference's quantized ±{0,.1,...,.9} lattice turns into
    exact small integers at 10x scale, where f32 accumulation is EXACT
    and no threshold can false-positive — a degenerate sweep). int8
    draws integer values of magnitude ~9 * scale (floored at ±1 so the
    fault domain never vanishes)."""
    rng = np.random.default_rng(seed)
    a = rng.standard_normal((m, k)).astype(np.float32)
    b = rng.standard_normal((n, k)).astype(np.float32)
    if dtype_name == "int8":
        scale_i = max(1.0, round(9.0 * scale))
        a = np.clip(np.round(a * scale_i / 2.0), -127, 127).astype(
            np.float32)
        b = np.clip(np.round(b * scale_i / 2.0), -127, 127).astype(
            np.float32)
    else:
        a = a * np.float32(scale)
        b = b * np.float32(scale)
    return a, b


def roc_sweep(
    *,
    m: int = 128,
    n: int = 128,
    k: int = 256,
    dtypes=("float32", "bfloat16", "float8_e4m3fn", "int8"),
    strategies=("rowcol", "global", "weighted", "fused"),
    encodes=("vpu", "mxu"),
    scales=(0.1, 1.0, 16.0),
    margin: float | None = None,
    seed: int = 10,
    interpret=None,
    progress=None,
) -> dict:
    """Run the static-vs-adaptive ROC sweep; returns the artifact dict.

    Per legal (dtype, strategy, encode) combo and per input ``scale``:
    one CLEAN run (detections are false positives) and one
    fault-injected run (``every=1``, magnitude ``ROC_FAULT_FACTOR`` x
    that scale's noise bound), under the statically calibrated threshold
    and under ``threshold="adaptive"``. ``progress`` is an optional
    ``fn(point)`` streaming callback. The summary's per-combo verdict is
    the acceptance contract: ``dominates`` = adaptive's aggregate
    (fp_rate, detection_rate) Pareto-dominates static's.
    """
    from ft_sgemm_tpu.analysis import estimate_noise_floor
    from ft_sgemm_tpu.configs import KernelShape
    from ft_sgemm_tpu.ops.common import DEFAULT_THRESHOLD_MARGIN
    from ft_sgemm_tpu.ops.ft_sgemm import make_ft_sgemm

    margin = DEFAULT_THRESHOLD_MARGIN if margin is None else margin
    tile = KernelShape("roc", 128, 128, 128, (0,) * 7)
    bm, bn, bk = tile.block
    tiles = (-(-m // bm)) * (-(-n // bn))
    nk = _num_k_steps(k, bk)
    points: list[RocPoint] = []

    def noise_bound(dtype_name, scale):
        if dtype_name == "int8":
            return 0.0  # exact int32 accumulation: clean residuals are 0
        a, b = _roc_inputs(m, n, k, scale, dtype_name, seed)
        # beta=0 below: the sweep isolates the product-term noise.
        return estimate_noise_floor(a, b, None, alpha=1.0, beta=0.0)

    for dtype_name, strategy, encode in _roc_combos(dtypes, strategies,
                                                    encodes):
        # The static operating point a careful engineer ships: margin x
        # the calibrated bound at the calibration scale (the auto-mode
        # formula, including its global-strategy sqrt(bn) scaling). For
        # int8 the bound is 0: the sane static threshold is the half-ulp.
        cal = noise_bound(dtype_name, ROC_CAL_SCALE)
        static_thr = margin * cal if cal > 0 else 0.5
        if strategy == "global" and cal > 0:
            # The whole-tile residual aggregates ~bn column residuals
            # (the auto-mode sqrt(bn) scaling); meaningless for int8's
            # exact arithmetic, where the half-ulp is the whole story.
            static_thr *= float(np.sqrt(bn))
        for mode in ("static", "adaptive"):
            ft = make_ft_sgemm(
                tile, alpha=1.0, beta=0.0, strategy=strategy,
                encode=encode, in_dtype=dtype_name,
                threshold=("adaptive" if mode == "adaptive"
                           else float(static_thr)),
                threshold_margin=margin, interpret=interpret)
            for scale in scales:
                a, b = _roc_inputs(m, n, k, scale, dtype_name, seed)
                c = np.zeros((m, n), np.float32)
                bound = noise_bound(dtype_name, scale)
                if dtype_name == "int8":
                    mag = max(1.0, round(3.0 * scale))
                else:
                    mag = ROC_FAULT_FACTOR * bound
                    if strategy == "global":
                        # The whole-tile residual's noise (and both
                        # modes' thresholds) carry the sqrt(bn)
                        # aggregation factor: faults worth detecting
                        # there are correspondingly larger.
                        mag *= float(np.sqrt(bn))
                clean = ft(a, b, c)
                inj = InjectionSpec(enabled=True, every=1,
                                    magnitude=float(mag))
                faulty = ft(a, b, c, inj)
                expected = tiles * inj.expected_faults(k, bk)
                point = RocPoint(
                    dtype=dtype_name, strategy=strategy, encode=encode,
                    mode=mode, scale=float(scale),
                    threshold=(None if mode == "adaptive"
                               else float(static_thr)),
                    magnitude=float(mag),
                    clean_detections=int(clean.num_detected),
                    checks=tiles * nk,
                    expected_faults=expected,
                    detected=int(faulty.num_detected))
                points.append(point)
                if progress is not None:
                    progress(point)

    return {
        "config": {"m": m, "n": n, "k": k, "tile": list(tile.block),
                   "scales": list(map(float, scales)),
                   "margin": float(margin), "seed": seed,
                   "fault_factor": ROC_FAULT_FACTOR,
                   "cal_scale": ROC_CAL_SCALE},
        "points": [p.to_dict() for p in points],
        "summary": summarize_roc(points),
    }


def summarize_roc(points) -> dict:
    """Aggregate ROC points into per-combo verdicts + the headline.

    Per (dtype, strategy, encode): each mode's aggregate false-positive
    rate (summed clean detections / summed check opportunities) and
    detection rate (summed detected, capped per scale / summed expected).
    ``dominates`` = adaptive fp <= static fp AND adaptive detection >=
    static detection; ``strict`` additionally requires one strict
    inequality. ``adaptive_false_positives`` totals adaptive clean
    detections across the WHOLE sweep — the number CI grep-asserts is 0.
    """
    combos: dict = {}
    for p in points:
        key = f"{p.dtype}|{p.strategy}|{p.encode}"
        combos.setdefault(key, {"static": [], "adaptive": []})[
            p.mode].append(p)

    def agg(ps):
        checks = sum(p.checks for p in ps)
        expected = sum(p.expected_faults for p in ps)
        detected = sum(min(p.detected, p.expected_faults) for p in ps)
        fps = sum(p.clean_detections for p in ps)
        return {"false_positives": fps,
                "fp_rate": fps / checks if checks else 0.0,
                "detection_rate": detected / expected if expected else 0.0}

    summary: dict = {"combos": {}}
    adaptive_fps = 0
    all_dominate = True
    for key, modes in sorted(combos.items()):
        s = agg(modes["static"])
        a = agg(modes["adaptive"])
        adaptive_fps += a["false_positives"]
        dominates = (a["fp_rate"] <= s["fp_rate"]
                     and a["detection_rate"] >= s["detection_rate"])
        strict = dominates and (a["fp_rate"] < s["fp_rate"]
                                or a["detection_rate"]
                                > s["detection_rate"])
        all_dominate &= dominates
        summary["combos"][key] = {"static": s, "adaptive": a,
                                  "dominates": dominates, "strict": strict}
    summary["all_dominate"] = all_dominate
    summary["adaptive_false_positives"] = adaptive_fps
    return summary


__all__ = [
    "InjectionSpec",
    "REFERENCE_MAGNITUDE",
    "REFERENCE_THRESHOLD",
    "REFERENCE_NUM_FAULTS",
    "RocPoint",
    "roc_sweep",
    "summarize_roc",
]
