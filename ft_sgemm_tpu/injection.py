"""First-class fault-injection specification.

The reference bakes injection into the generated kernels as compile-time
constants: every ``K/20`` outer iterations, one rotating thread adds
``error_inject = 10000.0`` to its first accumulator element, with detection
threshold ``err_bound1 = 9500.0`` (``include_code_gen/ft_sgemm_huge.cuh:49-51,
324-327``; template ``code_gen.py:333-337``). Injection cannot be turned off
without regenerating and recompiling.

Here injection is a runtime parameter: an :class:`InjectionSpec` is lowered
into the Pallas kernel through scalar operands (SMEM), so the same compiled
kernel can run clean, or inject any count/magnitude/placement of faults. The
default spec reproduces the reference's schedule: ~20 faults per run, spread
across K, magnitude 1e4, rotating target element.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np

# Reference constants (include_code_gen/ft_sgemm_huge.cuh:49-51).
REFERENCE_MAGNITUDE = 10000.0
REFERENCE_THRESHOLD = 9500.0
REFERENCE_NUM_FAULTS = 20


@dataclasses.dataclass(frozen=True)
class InjectionSpec:
    """Runtime description of accumulator-fault injection.

    Faults model silent data corruption in the f32 accumulator: at K-step
    ``k`` (a Pallas grid step along the contraction axis), if
    ``enabled and k % every == 0``, ``magnitude`` is added to one element of
    the accumulator tile. The element rotates with ``k // every`` (and with
    the output-tile coordinates) so successive faults land on different
    rows/columns, mirroring the reference's rotating ``tx`` target
    (``include_code_gen/ft_sgemm_huge.cuh:324-327``).

    ``enabled=False`` compiles to a no-op branch — the clean path the
    reference lacks.

    ``col_stride`` sets how far the target COLUMN advances per scheduled
    fault. The default 61 is coprime to every legal tile width, so
    consecutive faults land in distinct columns (the property the
    column-localized correcting strategies rely on). ``col_stride=0`` pins
    every fault to one column — the adversarial schedule that defeats
    per-column localization and exercises the kernels'
    residual-after-correct re-check (``FtSgemmResult.uncorrectable``).
    """

    enabled: bool = False
    every: int = 1  # inject at every k-step where k % every == 0
    magnitude: float = REFERENCE_MAGNITUDE
    col_stride: int = 61  # column advance per fault; 0 = same column always

    def __post_init__(self):
        if self.every < 1:
            raise ValueError(f"InjectionSpec.every={self.every} must be >= 1")
        if not np.isfinite(np.float32(self.magnitude)):
            raise ValueError(
                f"InjectionSpec.magnitude={self.magnitude} not finite in f32"
            )
        if self.col_stride < 0:
            raise ValueError(
                f"InjectionSpec.col_stride={self.col_stride} must be >= 0")

    @staticmethod
    def none() -> "InjectionSpec":
        return InjectionSpec(enabled=False)

    @staticmethod
    def reference_like(K: int, bk: int, num_faults: int = REFERENCE_NUM_FAULTS,
                       magnitude: float = REFERENCE_MAGNITUDE) -> "InjectionSpec":
        """Schedule ~num_faults faults across the K-grid of a (K, bk) run,
        like the reference's ``(k % (K/20)) == 0`` cadence
        (``code_gen.py:333``). The period rounds to nearest so the realized
        count lands as close to ``num_faults`` as the grid allows (floor
        would nearly double it when nk/num_faults is just above 1, e.g.
        nk=32 -> 32 faults instead of ~20 with the reference's ~16)."""
        num_k_steps = _num_k_steps(K, bk)
        every = max(1, round(num_k_steps / num_faults))
        return InjectionSpec(enabled=True, every=every, magnitude=magnitude)

    def as_operand(self) -> np.ndarray:
        """Pack into the (4,) f32 scalar operand consumed by the kernels:
        [enabled, every, magnitude, col_stride]."""
        return np.asarray(
            [1.0 if self.enabled else 0.0, float(self.every),
             float(self.magnitude), float(self.col_stride)],
            dtype=np.float32,
        )

    def expected_faults(self, K: int, bk: int) -> int:
        """Number of faults this spec injects over a full K sweep.

        Counts over the zero-padded K grid the kernels actually run
        (K rounded up to a multiple of bk)."""
        if not self.enabled:
            return 0
        num_k_steps = _num_k_steps(K, bk)
        return len([k for k in range(num_k_steps) if k % self.every == 0])


def _num_k_steps(K: int, bk: int) -> int:
    """K-grid length after the kernels' zero padding: ceil(K / bk)."""
    return max(1, -(-K // bk))


# Threshold note: REFERENCE_THRESHOLD (9500) pairs with the reference's
# 10000-magnitude faults (``ft_sgemm_huge.cuh:50``); inputs quantized to
# ±{0,.1,...,.9} (``utils.cu:23-31``) keep f32 checksum noise orders of
# magnitude below it even at K=6144.

__all__ = [
    "InjectionSpec",
    "REFERENCE_MAGNITUDE",
    "REFERENCE_THRESHOLD",
    "REFERENCE_NUM_FAULTS",
]
