"""Compute ops: reference oracle, Pallas SGEMM family, fused ABFT, two-pass baseline."""
