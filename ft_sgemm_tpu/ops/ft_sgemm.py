"""Fused online-ABFT SGEMM Pallas kernels — the framework's core capability.

TPU-native re-design of the reference's generated FT kernels
(``include_code_gen/ft_sgemm_{small..huge}.cuh``; template
``code_gen/code_gen.py:198-553``; algorithm described in SURVEY.md §2.3).
Everything happens inside one kernel — encode, accumulate, inject, detect,
correct — no second pass over C:

  1. **Input checksum encode, every K panel.** The reference sums each
     thread's loaded A/B elements and completes the sums with
     ``__shfl_xor_sync`` butterflies (``code_gen.py:207-226``). Here the
     panel checksums are whole-tile VPU reductions: ``s_a = sum_m A_blk``,
     ``s_b = sum_n B_blk`` over the (bm, bk)/(bn, bk) VMEM blocks.
  2. **Expected-checksum accumulation.** The reference forms a running
     per-thread expected checksum via a saxpy outer product through shared
     memory (``code_gen.py:231-280``). Here the expected row/column sums of
     the accumulated product are carried in VMEM scratch vectors:
     ``r_exp += (A_blk * s_b).sum(k)`` and ``c_exp += (B_blk * s_a).sum(k)``
     — elementwise VPU work that overlaps the MXU matmul, touching nothing
     in the accumulator (so accumulator faults stay detectable).
  3. **Periodic detect + correct.** The reference checks every ``K/20``
     columns (``code_gen.py:333``): reduce the accumulator to row/col sums,
     subtract from the expected sums, and add the row residual at
     row-AND-column threshold intersections (``code_gen.py:372-424``). Here
     the same residual-intersection correction is two VPU reductions of the
     VMEM accumulator plus one masked broadcast add.
  4. **Fault injection** is a runtime :class:`InjectionSpec` lowered through
     SMEM scalars (the reference hardcodes it, ``ft_sgemm_huge.cuh:49-51``).

Four checksum strategies mirror the reference's preserved designs.
``"weighted"`` is the default: at its default single-final-check cadence
its expected checksums are closed-form and precomputed by one stacked XLA
dot (``_ft_kernel_weighted_precomp``), so the hot loop is exactly the
plain kernel's MXU dot — the measured overhead class the reference's
fused flagship competes in (16.4 %, BASELINE.md) at ~4-6 % — while its
per-column localization corrects ANY number of accumulated faults (one
per corrupted column) in one check. ``"rowcol"`` is the reference-parity
strategy (the reference's generated kernels check row+col intersections
every ~K/20 columns) behind ``strategy="rowcol"``; its per-check
accumulator reductions cost ~19 % at the 4096 flagship point
(``.bench/records_b855854_4096.jsonl``), which is why it is no longer
the default.

  - ``"rowcol"`` (reference parity): row+column checksums, residual-intersection
    correction — the shipped generated kernels
    (``include_code_gen/ft_sgemm_*.cuh``) and the warp-level design
    (``include/ft_sgemm_huge_warp.cuh``). Unlike the reference (which can
    only correct ONE fault per check interval and guarantees that by
    checking exactly where it injects, ``code_gen.py:333-337``), this
    kernel also carries a row-index-weighted column checksum in its
    multi-fault mode: when more than one row AND more than one column flag
    — the case where bare row/col residual intersection is provably
    ambiguous (equal-magnitude faults at (r1,c1),(r2,c2) admit the wrong
    pairing (r1,c2),(r2,c1) with identical row/col sums) — each flagged
    column's fault row is localized by the weighted-residual ratio and
    corrected independently. Any number of faults per interval is
    corrected as long as each corrupted *column* holds at most one fault.
  - ``"global"``: one scalar checksum per output tile, detect-only — the
    thread-local design (``include/ft_sgemm_huge_thread.cuh:106-177``).
  - ``"weighted"``: column checksums plus index-weighted column checksums;
    the weighted residual ratio *localizes* the faulty row for single-fault
    correction — the weighted design (``include/ft_sgemm_huge.cuh:59,
    280-296``, ``correct_t`` macro :13-17). Because localization works per
    column, ONE deferred check corrects any number of accumulated faults as
    long as each corrupted column holds a single fault — so its default
    cadence is a single final check, making per-step overhead ~encode-only
    (~3-4% at 4096 vs the reference flagship's 16.4%, BASELINE.md).
  - ``"fused"``: the warp-level design's TPU analog
    (``include/ft_sgemm_huge_warp.cuh:139-207``). The reference fuses its
    checksum dot-products INTO the kk-loop using per-warp smem-cached
    input checksums; here the same fusion is **operand augmentation** —
    each A row-tile carries its three checksum-moment rows (``1^T A_i``,
    ``w^T A_i``, ``(w^2)^T A_i``), so the SAME MXU dot that accumulates
    the C tile accumulates the expected column moments as extra output
    rows. Zero per-panel VPU encode work INSIDE the kernel; the costs are
    8/bm extra MXU rows (~1.6% FLOPs at bm=512) for f32 or 16/bm (~3.1%)
    for bf16 (moment rows ride as hi/lo/lo2 triples, ``_tile_moments``),
    plus a per-call wrapper prep: ``_augment_tiles`` reduces A's moments
    (O(M*K) VPU) and materializes the augmented A copy in HBM (~one extra
    read+write of A) — cheap next to the GEMM at large K but, unlike the
    in-kernel encode strategies, not free; bench rows time it. Correction
    semantics match ``weighted`` (per-column localization + three-moment
    re-check) at ANY cadence — intermediate checks cost no extra encode,
    unlike weighted's running-sum variant.

**Encode modes.** The operand-augmentation trick generalizes beyond the
fused strategy: ``make_ft_sgemm(..., encode="mxu")`` computes EVERY
strategy's expected checksums via augmented MXU operands instead of
per-K-step VPU reductions, so one ``dot_general`` per K step yields both
the partial product and the expected-checksum accumulators — the encode
rides the systolic array nearly free while detection/correction stay
unchanged at the ``check_every`` cadence:

  - ``weighted`` + ``"mxu"`` runs the fused kernel (augmented A rows) at
    any cadence — ``strategy="fused"`` is exactly this combination.
  - ``rowcol`` + ``"mxu"`` augments BOTH operands
    (:func:`_ft_kernel_rowcol_mxu`): A's tail rows carry its plain and
    row-index-weighted checksum rows, B's tail rows its plain checksum
    rows, and the one augmented dot's extra output rows/columns are the
    expected column/row sums the VPU encode used to build elementwise.
  - ``global`` + ``"mxu"`` augments both with plain checksum rows and
    reads the expected whole-tile sum off the dot's corner block
    (:func:`_ft_kernel_global_mxu`).

``encode="vpu"`` (the default) is the original per-step VPU encode,
bit-for-bit: the encode axis changes nothing unless selected (HLO pinned
in ``tests/test_encode_mxu.py``).

**Threshold modes and the low-precision dtypes.** Detection thresholds
come in three modes (``configs.THRESHOLD_MODES``): a fixed float /
``"static"`` (the reference's operating point — the default, HLO pinned
in ``tests/test_low_precision.py``), ``"auto"`` (one traced per-call
bound from the full inputs' moments), and ``"adaptive"`` (per-tile
per-check variance bounds derived INSIDE the kernel from running
encode-pass moment statistics — V-ABFT, DESIGN.md §10). Adaptive mode is
what opens the low-precision input dtypes: ``in_dtype="float8_e4m3fn"``
runs fp8 operands over the f32-accumulating float kernels, and
``in_dtype="int8"`` runs an int32-EXACT variant of the rowcol/global
kernels (separate int32 accumulator block, wrapping int32 checksum
streams — clean residuals identically zero, exact correction).
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ft_sgemm_tpu import telemetry
from ft_sgemm_tpu.configs import (
    DEFAULT_VARIANT,
    ENCODE_MODES,
    SHAPES,
    STRATEGIES,
    THRESHOLD_MODES,
    EpilogueSpec,
    KernelShape,
    KernelVariant,
    aug_rows as _aug_rows,
    canonical_variant,
    check_kernel_legality as _check_kernel_legality,
    shape_for_dtype,
    vmem_limit_bytes,
)
from ft_sgemm_tpu.injection import InjectionSpec, REFERENCE_THRESHOLD
from ft_sgemm_tpu.ops.common import (
    CompilerParams as _CompilerParams,
    DEFAULT_THRESHOLD_MARGIN,
    apply_epilogue as _apply_epilogue,
    attach_bias as _attach_bias,
    dtype_suffix as _dtype_suffix,
    epilogue_bias_row as _epilogue_bias_row,
    estimate_noise_floor_jnp as _estimate_noise_floor_jnp,
    gemm_cost_estimate as _gemm_cost_estimate,
    grid_and_maps as _grid_and_maps,
    grid_ij as _grid_ij,
    pad_bias as _pad_bias,
    pad_to as _pad_to,
    resolve_in_dtype as _resolve_in_dtype,
    should_interpret as _should_interpret,
    shrink_block as _shrink_block,
    sub_panels as _sub_panels,
    variance_bound_threshold as _variance_bound_threshold,
)
from ft_sgemm_tpu.ops.vmem import fit_block_to_vmem as _fit_block_to_vmem

# STRATEGIES is declared in configs (the kernel-axis single source the
# static contract checker reads) and re-exported here unchanged — every
# historical importer spells it ``ops.ft_sgemm.STRATEGIES``.


class FtSgemmResult(NamedTuple):
    """Output of a fused-ABFT GEMM.

    ``detections`` counts distinct fault events per C tile, uniformly
    across strategies:
      - ``rowcol``/``weighted``/``fused``: number of corrected accumulator
        elements — one per injected fault whenever each corrupted column
        holds at most one fault per check interval (guaranteed for the
        rotating injector). ``fused`` shares ``weighted``'s correction and
        three-moment re-check exactly (both call ``_moment_detect_correct``);
        only the encode path differs.
      - ``global``: number of check intervals in which NEW corruption
        appeared (the residual moved by more than the threshold since the
        previous check). The strategy never corrects, so this equals the
        injected fault count when at most one fault lands per interval;
        multiple same-interval faults collapse into one event.

    ``uncorrectable`` is the residual-after-correct re-check: the
    correcting strategies recompute their checksum residuals AFTER
    applying corrections and count residuals still above threshold — the
    case where correction assumptions were violated (e.g. multiple faults
    in one column of one check interval defeat per-column localization:
    the column's total deficit lands on one rounded row). ``weighted``
    re-checks three column moments (plain, w, w^2): a single point-mass
    correction can match the first two moments of a multi-fault column
    (equal faults at rows in arithmetic progression do), but never all
    three when the faults share a sign; sign-mixed fault sets that match
    all three moments exactly remain theoretically silent (measure-zero
    for real SDC). ``rowcol`` additionally re-checks per-row residuals,
    which flag any same-column multi-fault miscorrection directly.
    The value is the post-FINAL-check state — the number of checksum
    residuals still above threshold after every correction ran (residuals
    are cumulative over K, so a broken interval anywhere in the run stays
    visible at the last check; a per-check accumulation would re-count it
    once per check and scale with cadence instead of damage). Nonzero
    means the output may still be corrupted and the caller must re-run —
    corruption is REPORTED, not silent. For the detect-only ``global``
    strategy every detection is uncorrected, so it equals ``detections``.

    Under ``threshold="auto"`` the w/w^2 re-check moments use noise-scaled
    thresholds (their floors are ~bm and ~bm^2 times the plain one), so
    the report certifies miscorrections whose moment signature exceeds
    those scaled floors — an information limit, not a tunable: a
    multi-fault column whose faults sit near the auto detection threshold
    itself leaves a second-moment signature underneath second-moment
    noise. At the reference's static 9500 operating point all moments
    share the one threshold and the adversarial-schedule reports are
    maximally sensitive.
    """

    c: jax.Array           # (M, N) corrected output
    detections: jax.Array  # (grid_m, grid_n) int32 — see class docstring
    uncorrectable: jax.Array  # (grid_m, grid_n) int32 — see class docstring

    @property
    def num_detected(self):
        return jnp.sum(self.detections)

    @property
    def num_uncorrectable(self):
        return jnp.sum(self.uncorrectable)


def _inject(out_ref, inj_ref, k, i, j, bm, bn, exact=False):
    """Add inj.magnitude to one rotating accumulator element when scheduled.

    Models SDC in the f32 accumulator (reference rotates the target thread:
    ``if(tx == (k+8)/(K/20)) res[0] += error_inject``,
    ``include_code_gen/ft_sgemm_huge.cuh:324-327``). The target rotates with
    the injection ordinal and the output-tile coordinates; the default
    column stride (61) is coprime to every legal bn, so consecutive faults
    land in distinct columns for up to bn injections — the property
    multi-fault correction relies on (see make_ft_sgemm). A runtime
    ``col_stride`` of 0 pins the column: the adversarial schedule for the
    uncorrectable-interval re-check.
    """
    enabled = inj_ref[0] > 0.0
    every = jnp.maximum(inj_ref[1].astype(jnp.int32), 1)
    magnitude = inj_ref[2]
    col_stride = inj_ref[3].astype(jnp.int32)
    do = enabled & (k % every == 0)

    @pl.when(do)
    def _():
        ordinal = k // every + 3 * i + 5 * j
        m0 = (ordinal * 131 + 7) % bm
        n0 = (ordinal * col_stride + 3) % bn
        # Read-modify-write one aligned (8, 128) subtile instead of masking
        # the whole (bm, bn) accumulator: a full-tile iota mask costs ~14%
        # of the kernel at bm=bn=512; this costs <1%. (Mosaic cannot load a
        # 1x1 VMEM vector at an arbitrary dynamic offset, hence the aligned
        # subtile + local mask.)
        m0a = pl.multiple_of((m0 // 8) * 8, 8)
        n0a = pl.multiple_of((n0 // 128) * 128, 128)
        sub = out_ref[pl.ds(m0a, 8), pl.ds(n0a, 128)]
        rows = jax.lax.broadcasted_iota(jnp.int32, (8, 128), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (8, 128), 1)
        hit = (rows == m0 - m0a) & (cols == n0 - n0a)
        if exact:
            # int32-exact accumulator (int8 inputs): the injected value is
            # the rounded magnitude — SDC in the integer domain.
            out_ref[pl.ds(m0a, 8), pl.ds(n0a, 128)] = sub + jnp.where(
                hit, jnp.round(magnitude).astype(jnp.int32), 0)
        else:
            out_ref[pl.ds(m0a, 8), pl.ds(n0a, 128)] = sub + jnp.where(
                hit, magnitude, 0.0)


def _moment_detect_correct(acc, exp_c, exp_cw, exp_cw2, thresholds,
                           bm, bn):
    """Shared three-moment detect / localize / correct / re-check.

    The weighted, weighted-precomp, and fused kernels differ ONLY in where
    their expected column moments come from (running VMEM accumulation, a
    precomputed XLA dot, or augmented MXU output rows); everything from
    residual formation through the residual-after-correct re-check is this
    one function, so their correction and reporting behavior stays in
    lockstep (LEVEL semantics for the uncorrectable count — see
    FtSgemmResult). ``thresholds`` is the per-moment triple
    ``(thr, thr_m1, thr_m2)``: detection and the plain re-check use
    ``thr``; the weighted (w) and second-moment (w^2) re-checks use their
    own thresholds because their noise floors are ~bm and ~bm^2 larger
    (identical to ``thr`` at the reference's static operating point;
    noise-scaled under ``threshold="auto"``). Returns
    ``(corrected_acc, n_hit, n_unc)``.
    """
    threshold, thr_m1, thr_m2 = thresholds
    w_col = jax.lax.broadcasted_iota(
        jnp.int32, (bm, 1), 0).astype(jnp.float32) + 1.0
    w2 = w_col * w_col
    cs = jnp.sum(acc, axis=0, keepdims=True)             # (1, bn)
    csw = jnp.sum(acc * w_col, axis=0, keepdims=True)    # (1, bn)
    csw2 = jnp.sum(acc * w2, axis=0, keepdims=True)      # (1, bn)
    res_c = exp_c - cs
    res_cw = exp_cw - csw
    det_c = jnp.abs(res_c) > threshold
    hit = _weighted_localize(res_c, res_cw, det_c, bm, bn)
    delta = jnp.where(hit, res_c, 0.0)
    # Residual-after-correct re-check: residuals are linear in the
    # accumulator, so post-correction residuals are the pre-correction
    # ones minus delta's moment sums. A point-mass correction can match
    # the first two moments of a multi-fault column (equal faults at rows
    # in arithmetic progression do) but never all three for same-sign
    # faults — anything still above threshold is REPORTED, not silent.
    res_c2 = res_c - jnp.sum(delta, axis=0, keepdims=True)
    res_cw2 = res_cw - jnp.sum(delta * w_col, axis=0, keepdims=True)
    res_cm2 = exp_cw2 - csw2 - jnp.sum(delta * w2, axis=0, keepdims=True)
    # A correction of magnitude |delta| cannot verify tighter than its own
    # f32 rounding (~eps * |delta| deposited into the corrected element):
    # widen each column's re-check threshold by that floor, amplified by
    # the corrected rows' ACTUAL moment weights (worst-case bm/bm^2 would
    # over-widen by up to (bm/w[loc])^2 and mask reportable
    # miscorrections). Tiny auto thresholds would otherwise false-flag
    # every large corrected fault; negligible at the static 9500 point.
    pad, pad_w, pad_w2 = _correction_pads(delta, 0, w_col, w2)
    n_unc = jnp.sum(
        ((jnp.abs(res_c2) > threshold + pad)
         | (jnp.abs(res_cw2) > thr_m1 + pad_w)
         | (jnp.abs(res_cm2) > thr_m2 + pad_w2))
        .astype(jnp.int32))
    return acc + delta, jnp.sum(hit.astype(jnp.int32)), n_unc


def _correction_pads(delta, axis, *weights):
    """Correction-rounding floors for the residual-after-correct re-check.

    A correction of magnitude |delta| leaves ~eps * |delta| of f32 remnant
    in the corrected element, so a re-check along ``axis`` cannot verify
    tighter than ``8 * eps * sum(|delta| [* weight])``. Returns one pad
    per requested weighting (the plain pad first, then one per weight) —
    the ONE implementation shared by every correcting kernel so the floor
    model can never drift between them.
    """
    eps8 = 8.0 * float(np.finfo(np.float32).eps)
    ad = jnp.abs(delta)
    pads = [eps8 * jnp.sum(ad, axis=axis, keepdims=True)]
    for w in weights:
        pads.append(eps8 * jnp.sum(ad * w, axis=axis, keepdims=True))
    return pads


def _adaptive_threshold(mom_ref, k, *, bk, bm, bn, nk, margin,
                        global_tile=False):
    """Per-tile detection threshold from the running moment scratch
    (``threshold="adaptive"`` — the V-ABFT capability).

    ``mom_ref`` is the SMEM ``(4,)`` f32 scratch ``[sum_a, sumsq_a,
    sum_b, sumsq_b]`` the encode pass accumulates over every A/B element
    this tile has consumed through K step ``k``. The bound is the
    calibrated noise model (``ops.common.variance_bound_threshold``, one
    implementation shared with the host twin) evaluated on THIS tile's
    statistics at THIS check's accumulation depth — a threshold that
    tracks per-tile operand variance instead of assuming one global
    operating point, which is what keeps false positives at zero when
    tile statistics are heterogeneous or drift run-to-run (the static-
    threshold failure mode at bf16 and below; DESIGN.md §10). The bias
    term's log factor uses the STATIC full-run ``log2`` (monotone in t:
    early checks get a slightly conservative bias bound and the kernel
    traces no transcendental). The detect-only ``global`` strategy's
    whole-tile residual aggregates ~bn column residuals, hence its
    ``sqrt(bn)`` scale — mirroring the wrapper's ``threshold="auto"``
    scalings exactly.
    """
    tk = ((k + 1) * bk).astype(jnp.float32)
    tmax = float(max(bm, bn))
    t_full = float(nk * bk) * tmax
    thr = _variance_bound_threshold(
        mom_ref[0], mom_ref[1], mom_ref[2], mom_ref[3],
        n_a=tk * float(bm), n_b=tk * float(bn), t_ab=tk * tmax,
        log2_t=float(np.log2(max(t_full, 2.0))), margin=margin, xp=jnp)
    if global_tile:
        thr = thr * float(np.sqrt(bn))
    return thr


def _accumulate_moments(mom_ref, af, bf):
    """Running per-tile moment statistics of the encode pass: sum and
    sum-of-squares per operand (``_adaptive_threshold``'s input). Four
    whole-block VPU reductions of values already resident in VMEM —
    overlapping the MXU dot, the "nearly free" half of the V-ABFT
    design."""
    mom_ref[0] += jnp.sum(af)
    mom_ref[1] += jnp.sum(af * af)
    mom_ref[2] += jnp.sum(bf)
    mom_ref[3] += jnp.sum(bf * bf)


def _rowcol_detect_correct(out_ref, count_ref, unc_count_ref, res_r, res_c,
                           thresholds, bm, bn, multifault, moments_fn,
                           exact=False):
    """Shared rowcol detect / correct / re-check, from residuals to stores.

    The VPU-encode and MXU-encode rowcol kernels differ ONLY in where
    their expected row/column sums come from (running elementwise VPU
    accumulation vs augmented-dot output rows); everything from detection
    through the residual-after-correct re-check is this one function so
    the two encodes' correction and reporting behavior stays in lockstep.
    ``thresholds`` is ``(thr, thr_m1)``; ``moments_fn()`` returns
    ``(w_col, res_cw)`` — the weighted-residual pieces, evaluated only in
    multifault mode so the plain kernel traces no weighted-moment ops.
    ``exact`` marks the int32 accumulation path (int8 inputs): residuals
    are exact integers compared against the f32 threshold scalar, the
    correction is exact integer addition, and the re-check needs no
    rounding-floor pads.
    """
    threshold, thr_m1 = thresholds

    def mag(x):
        # |residual| in the threshold's f32 domain — a no-op cast for the
        # float kernels (same-dtype convert is elided), the int32->f32
        # compare domain for the exact ones.
        return jnp.abs(x).astype(jnp.float32)

    det_r = mag(res_r) > threshold
    det_c = mag(res_c) > threshold
    hit = jnp.logical_and(det_r, det_c)                 # (bm, bn)
    # Residual source: with exactly one flagged row and several flagged
    # columns, the faults all sit in that row and the *column* residuals
    # carry the per-fault values (and vice versa). The reference always
    # uses the row residual (col for the wide shape, code_gen.py:417-424)
    # and miscorrects that case; disambiguating costs two scalar counts.
    n_rows_flagged = jnp.sum(det_r.astype(jnp.int32))
    n_cols_flagged = jnp.sum(det_c.astype(jnp.int32))
    use_col = (n_rows_flagged == 1) & (n_cols_flagged > 1)
    corr = jnp.where(use_col, jnp.broadcast_to(res_c, hit.shape),
                     jnp.broadcast_to(res_r, hit.shape))
    if multifault:
        # >1 row AND >1 col flagged: intersection is ambiguous (the
        # wrong fault pairing has identical row/col sums). Localize
        # each flagged column's fault row by the weighted-residual
        # ratio instead — exact while each corrupted column holds at
        # most one fault (the rotating injector guarantees distinct
        # columns for up to bn faults per interval).
        w_col, res_cw = moments_fn()
        hit_w = _weighted_localize(res_c, res_cw, det_c, bm, bn)
        ambiguous = (n_rows_flagged > 1) & (n_cols_flagged > 1)
        hit = jnp.where(ambiguous, hit_w, hit)
        corr = jnp.where(ambiguous, jnp.broadcast_to(res_c, hit.shape),
                         corr)
    delta = jnp.where(hit, corr, 0 if exact else 0.0)
    out_ref[:] += delta
    count_ref[0] += jnp.sum(hit.astype(jnp.int32))
    # Residual-after-correct re-check: residuals are linear in the
    # accumulator, so the post-correction residuals are the pre-
    # correction ones minus delta's row/col sums — no accumulator
    # re-read. Anything still above threshold means a correction
    # assumption broke (e.g. two same-column faults in the ambiguous
    # >1-row/>1-col case): REPORT instead of staying silent.
    res_r2 = res_r - jnp.sum(delta, axis=1, keepdims=True)
    res_c2 = res_c - jnp.sum(delta, axis=0, keepdims=True)
    if exact:
        # Integer correction leaves no rounding remnant: the re-check
        # compares the exact post-correction residuals unpadded.
        pad_r = pad_c = 0.0
    else:
        # Correction-rounding floors shared with the moment kernels
        # (_correction_pads): remnants of large corrected faults must not
        # false-flag tiny auto thresholds.
        (pad_r,) = _correction_pads(delta, 1)
        (pad_c,) = _correction_pads(delta, 0)
    bad_c = mag(res_c2) > threshold + pad_c
    bad = (jnp.sum((mag(res_r2) > threshold + pad_r)
                   .astype(jnp.int32))
           + jnp.sum(bad_c.astype(jnp.int32)))
    if multifault:
        # The weighted residual exposes corrections that balanced the
        # plain column sum on the WRONG row (its own noise-scaled
        # threshold: see _moment_detect_correct).
        res_cw2 = res_cw - jnp.sum(delta * w_col, axis=0, keepdims=True)
        _, pad_w = _correction_pads(delta, 0, w_col)
        bad += jnp.sum(((jnp.abs(res_cw2) > thr_m1 + pad_w)
                        & ~bad_c).astype(jnp.int32))
    # LEVEL, not accumulation: residuals are cumulative over K, so a
    # stale broken interval stays visible at every later check —
    # accumulating would re-count it once per check and inflate with
    # cadence. The value reported is the state after the FINAL check.
    unc_count_ref[0] = bad


def _weighted_localize(res_c, res_cw, det_c, bm, bn):
    """Per-column fault-row localization by the weighted-residual ratio.

    For each flagged column (``det_c``), the fault row is
    ``round(res_cw / res_c) - 1`` — the TPU analog of the reference's
    ``correct_t`` macro (``include/ft_sgemm_huge.cuh:13-17``) with weight
    base {1..8} generalized to {1..bm}. Returns the (bm, bn) boolean mask
    of elements to correct; exact while each flagged column holds at most
    one fault. Shared by the weighted, weighted-precomp, and
    rowcol-multifault kernels so their correction behavior stays in
    lockstep.
    """
    safe = jnp.where(det_c, res_c, 1.0)
    loc = jnp.round(res_cw / safe).astype(jnp.int32) - 1     # (1, bn)
    rows = jax.lax.broadcasted_iota(jnp.int32, (bm, bn), 0)
    return det_c & (rows == loc)


def _ft_kernel_rowcol(
    inj_ref, a_ref, b_ref, c_ref, out_ref, det_ref, unc_ref,
    r_exp_ref, c_exp_ref, *rest,
    alpha, beta, nk, prec, check_every, bm, bn, multifault,
    exact=False, adaptive=False, bk=None,
    unroll=1, swap_ij=False, epi=None, bias_ref=None,
):
    # Optional scratch tail, in declaration order (_scratch_for): the
    # multifault weighted stream, the int32-exact accumulator (int8
    # inputs accumulate apart from the f32 output block), the adaptive
    # moment scalars, then the counters.
    idx = 0
    if multifault:
        cw_exp_ref = rest[idx]
        idx += 1
    acc_ref = out_ref
    if exact:
        acc_ref = rest[idx]
        idx += 1
    if adaptive and not exact:
        mom_ref = rest[idx]
        idx += 1
    count_ref, unc_count_ref = rest[idx], rest[idx + 1]
    k = pl.program_id(2)
    i, j = _grid_ij(swap_ij)
    threshold = inj_ref[4]  # runtime scalars: per-call thresholds
    thr_m1 = inj_ref[5]     # weighted-moment re-check (multifault mode)

    @pl.when(k == 0)
    def _zero():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        r_exp_ref[:] = jnp.zeros_like(r_exp_ref)
        c_exp_ref[:] = jnp.zeros_like(c_exp_ref)
        if multifault:
            cw_exp_ref[:] = jnp.zeros_like(cw_exp_ref)
        if adaptive and not exact:
            mom_ref[:] = jnp.zeros_like(mom_ref)
        count_ref[0] = 0
        unc_count_ref[0] = 0

    _inject(acc_ref, inj_ref, k, i, j, bm, bn, exact=exact)

    a_blk = a_ref[:]
    b_blk = b_ref[:]

    # MXU: main partial product. f32 accumulation for the float dtypes;
    # int8 inputs accumulate EXACTLY in int32 (preferred_element_type) —
    # clean checksum residuals are then identically zero mod 2^32.
    # unroll > 1 (deep pipeline): one dot per K sub-panel of the window.
    for a_sub, b_sub in _sub_panels(a_blk, b_blk, unroll):
        acc_ref[:] += jax.lax.dot_general(
            a_sub, b_sub,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.int32 if exact else jnp.float32,
            precision=prec,
        )

    # VPU: panel input checksums (replaces __shfl_xor butterflies) and
    # expected row/col sums of the accumulated product. Always the
    # accumulation dtype: for bf16/fp8 inputs the checksums are computed in
    # f32 on the same rounded values the MXU consumes, so input rounding
    # cancels out of the residual and only f32 accumulation-order noise
    # remains; for int8 the int32 checksum arithmetic wraps consistently
    # with the accumulator (mod 2^32), keeping clean residuals exactly 0.
    af = a_blk.astype(jnp.int32 if exact else jnp.float32)
    bf = b_blk.astype(jnp.int32 if exact else jnp.float32)
    s_b = jnp.sum(bf, axis=0, keepdims=True)               # (1, bk)
    s_a = jnp.sum(af, axis=0, keepdims=True)               # (1, bk)
    r_exp_ref[:] += jnp.sum(af * s_b, axis=1, keepdims=True)     # (bm, 1)
    c_exp_ref[:] += jnp.sum(bf * s_a, axis=1, keepdims=True)     # (bn, 1)
    if multifault:
        # Row-index-weighted A column sums -> weighted expected column
        # checksum (the weighted design's localization vector,
        # include/ft_sgemm_huge.cuh:59, folded into rowcol so coarse check
        # cadences stay safe under multiple faults per interval).
        w_col = jax.lax.broadcasted_iota(
            jnp.int32, (bm, 1), 0).astype(jnp.float32) + 1.0
        s_aw = jnp.sum(af * w_col, axis=0, keepdims=True)  # (1, bk)
        cw_exp_ref[:] += jnp.sum(bf * s_aw, axis=1, keepdims=True)  # (bn, 1)
    if adaptive and not exact:
        _accumulate_moments(mom_ref, af, bf)

    do_check = ((k + 1) % check_every == 0) | (k == nk - 1)

    @pl.when(do_check)
    def _detect_correct():
        acc = acc_ref[:]
        rs = jnp.sum(acc, axis=1, keepdims=True)            # (bm, 1)
        cs = jnp.sum(acc, axis=0, keepdims=True)            # (1, bn)
        res_r = r_exp_ref[:] - rs                           # (bm, 1)
        res_c = jnp.swapaxes(c_exp_ref[:], 0, 1) - cs       # (1, bn)
        if adaptive:
            if exact:
                # Exact integer arithmetic: any nonzero residual is a
                # fault — the adaptive "variance bound" is the half-ulp.
                thr = thr_w = jnp.float32(0.5)
            else:
                thr = _adaptive_threshold(mom_ref, k, bk=bk, bm=bm, bn=bn,
                                          nk=nk, margin=inj_ref[7])
                thr_w = thr * float(bm / np.sqrt(3.0))
            thrs = (thr, thr_w)
        else:
            thrs = (threshold, thr_m1)

        def moments():
            w_col = jax.lax.broadcasted_iota(
                jnp.int32, (bm, 1), 0).astype(jnp.float32) + 1.0
            csw = jnp.sum(acc * w_col, axis=0, keepdims=True)    # (1, bn)
            res_cw = jnp.swapaxes(cw_exp_ref[:], 0, 1) - csw     # (1, bn)
            return w_col, res_cw

        _rowcol_detect_correct(acc_ref, count_ref, unc_count_ref,
                               res_r, res_c, thrs, bm, bn,
                               multifault, moments, exact=exact)

    @pl.when(k == nk - 1)
    def _epilogue():
        # Fused epilogue strictly AFTER the detect/correct pass above
        # (same-step pl.when blocks run in definition order): checksums
        # verify the pre-epilogue accumulator.
        if exact:
            out_ref[:] = _apply_epilogue(
                alpha * acc_ref[:].astype(jnp.float32) + beta * c_ref[:],
                epi, _epilogue_bias_row(bias_ref))
        else:
            out_ref[:] = _apply_epilogue(
                alpha * out_ref[:] + beta * c_ref[:],
                epi, _epilogue_bias_row(bias_ref))
        det_ref[i, j] = count_ref[0]
        unc_ref[i, j] = unc_count_ref[0]


def _ft_kernel_rowcol_mxu(
    inj_ref, a_ref, b_ref, c_ref, out_ref, det_ref, unc_ref,
    r_exp_ref, c_exp_ref, *rest,
    alpha, beta, nk, prec, check_every, bm, bn, multifault, n_terms,
    adaptive=False, bk=None,
    unroll=1, swap_ij=False, epi=None, bias_ref=None,
):
    """Rowcol with MXU-fused encode (``encode="mxu"`` — module docstring).

    ``a_ref`` blocks are (bm + aug_a, bk): the tail rows hold A's plain
    and row-index-weighted checksum rows (``_augment_tiles`` with 2
    moments — row ``2*t + mi`` for term t, moment mi). ``b_ref`` blocks
    are (bn + aug_b, bk): tail rows hold B's plain checksum rows (1
    moment, row = term index). The ONE augmented dot therefore yields,
    beyond the (bm, bn) partial product: the expected column-sum /
    weighted-column-sum rows (``prod[bm:, :bn]``, accumulated in
    ``c_exp_ref``) and the expected row-sum columns (``prod[:bm, bn:]``,
    accumulated in ``r_exp_ref``); the (aug_a, aug_b) corner is unused.
    Zero per-K-step VPU encode work; detection/correction/reporting is
    byte-for-byte the rowcol kernel's (:func:`_rowcol_detect_correct`)
    at the same cadence. SDC landing in a checksum row/column itself
    surfaces as a residual with no consistent intersection: the re-check
    flags the interval as uncorrectable (those rows never touch C).

    ``adaptive`` appends the moment scratch and accumulates the per-tile
    operand statistics on the VPU from the UN-augmented block slices
    (the checksum tail rows are derived data, not operand samples) while
    the MXU runs the augmented dot — the two-unit overlap the V-ABFT
    design counts on.
    """
    if adaptive:
        (mom_ref, count_ref, unc_count_ref) = rest
    else:
        count_ref, unc_count_ref = rest
    k = pl.program_id(2)
    i, j = _grid_ij(swap_ij)
    threshold = inj_ref[4]  # runtime scalars: per-call thresholds
    thr_m1 = inj_ref[5]     # weighted-moment re-check (multifault mode)

    @pl.when(k == 0)
    def _zero():
        out_ref[:] = jnp.zeros_like(out_ref)
        r_exp_ref[:] = jnp.zeros_like(r_exp_ref)
        c_exp_ref[:] = jnp.zeros_like(c_exp_ref)
        if adaptive:
            mom_ref[:] = jnp.zeros_like(mom_ref)
        count_ref[0] = 0
        unc_count_ref[0] = 0

    _inject(out_ref, inj_ref, k, i, j, bm, bn)

    a_blk = a_ref[:]
    b_blk = b_ref[:]
    for a_sub, b_sub in _sub_panels(a_blk, b_blk, unroll):
        prod = jax.lax.dot_general(
            a_sub, b_sub,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=prec,
        )                             # (bm + aug_a, bn + aug_b)
        out_ref[:] += prod[:bm, :bn]
        c_exp_ref[:] += prod[bm:, :bn]
        r_exp_ref[:] += prod[:bm, bn:]
    if adaptive:
        _accumulate_moments(mom_ref, a_blk[:bm].astype(jnp.float32),
                            b_blk[:bn].astype(jnp.float32))

    do_check = ((k + 1) % check_every == 0) | (k == nk - 1)

    @pl.when(do_check)
    def _detect_correct():
        acc = out_ref[:]
        rs = jnp.sum(acc, axis=1, keepdims=True)            # (bm, 1)
        cs = jnp.sum(acc, axis=0, keepdims=True)            # (1, bn)
        # Term-summed expected moments: r_exp's columns are B's plain-sum
        # terms (hi/lo/lo2 for bf16), c_exp's rows interleave A's (plain,
        # weighted) moments at row 2*t + mi; zero pad rows add nothing.
        res_r = jnp.sum(r_exp_ref[:], axis=1, keepdims=True) - rs
        c_exp = c_exp_ref[0:1, :]
        cw_exp = c_exp_ref[1:2, :]
        for t in range(1, n_terms):
            c_exp = c_exp + c_exp_ref[2 * t:2 * t + 1, :]
            cw_exp = cw_exp + c_exp_ref[2 * t + 1:2 * t + 2, :]
        res_c = c_exp - cs
        if adaptive:
            thr = _adaptive_threshold(mom_ref, k, bk=bk, bm=bm, bn=bn,
                                      nk=nk, margin=inj_ref[7])
            thrs = (thr, thr * float(bm / np.sqrt(3.0)))
        else:
            thrs = (threshold, thr_m1)

        def moments():
            w_col = jax.lax.broadcasted_iota(
                jnp.int32, (bm, 1), 0).astype(jnp.float32) + 1.0
            csw = jnp.sum(acc * w_col, axis=0, keepdims=True)   # (1, bn)
            return w_col, cw_exp - csw

        _rowcol_detect_correct(out_ref, count_ref, unc_count_ref,
                               res_r, res_c, thrs, bm, bn,
                               multifault, moments)

    @pl.when(k == nk - 1)
    def _epilogue():
        out_ref[:] = _apply_epilogue(
            alpha * out_ref[:] + beta * c_ref[:], epi,
            _epilogue_bias_row(bias_ref))
        det_ref[i, j] = count_ref[0]
        unc_ref[i, j] = unc_count_ref[0]


def _ft_kernel_global_mxu(
    inj_ref, a_ref, b_ref, c_ref, out_ref, det_ref, unc_ref,
    t_exp_ref, prev_ref, count_ref, *rest,
    alpha, beta, nk, prec, check_every, bm, bn,
    adaptive=False, bk=None,
    unroll=1, swap_ij=False, epi=None, bias_ref=None,
):
    """Global (scalar-checksum, detect-only) with MXU-fused encode.

    Both operands carry their plain checksum rows (``_augment_tiles``
    with 1 moment), so the augmented dot's (aug_a, aug_b) corner holds
    every (A-sum term) x (B-sum term) product — its total IS the panel
    product's expected sum (zero pad rows/columns contribute nothing).
    Detection is byte-for-byte :func:`_ft_kernel_global`'s.
    """
    if adaptive:
        (mom_ref,) = rest
    k = pl.program_id(2)
    i, j = _grid_ij(swap_ij)
    threshold = inj_ref[4]  # runtime scalar (no moment re-checks here)

    @pl.when(k == 0)
    def _zero():
        out_ref[:] = jnp.zeros_like(out_ref)
        t_exp_ref[0] = 0.0
        prev_ref[0] = 0.0
        if adaptive:
            mom_ref[:] = jnp.zeros_like(mom_ref)
        count_ref[0] = 0

    _inject(out_ref, inj_ref, k, i, j, bm, bn)

    a_blk = a_ref[:]
    b_blk = b_ref[:]
    for a_sub, b_sub in _sub_panels(a_blk, b_blk, unroll):
        prod = jax.lax.dot_general(
            a_sub, b_sub,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=prec,
        )                             # (bm + aug, bn + aug)
        out_ref[:] += prod[:bm, :bn]
        t_exp_ref[0] += jnp.sum(prod[bm:, bn:])
    if adaptive:
        _accumulate_moments(mom_ref, a_blk[:bm].astype(jnp.float32),
                            b_blk[:bn].astype(jnp.float32))

    do_check = ((k + 1) % check_every == 0) | (k == nk - 1)

    @pl.when(do_check)
    def _detect():
        # Fault EVENTS, not failed checks — see _ft_kernel_global.
        res = t_exp_ref[0] - jnp.sum(out_ref[:])
        if adaptive:
            thr = _adaptive_threshold(mom_ref, k, bk=bk, bm=bm, bn=bn,
                                      nk=nk, margin=inj_ref[7],
                                      global_tile=True)
        else:
            thr = threshold
        count_ref[0] += (jnp.abs(res - prev_ref[0]) > thr).astype(
            jnp.int32)
        prev_ref[0] = res

    @pl.when(k == nk - 1)
    def _epilogue():
        out_ref[:] = _apply_epilogue(
            alpha * out_ref[:] + beta * c_ref[:], epi,
            _epilogue_bias_row(bias_ref))
        det_ref[i, j] = count_ref[0]
        # Detect-only strategy: every detection is by definition
        # uncorrected (FtSgemmResult docstring).
        unc_ref[i, j] = count_ref[0]


def _ft_kernel_global(
    inj_ref, a_ref, b_ref, c_ref, out_ref, det_ref, unc_ref,
    t_exp_ref, prev_ref, count_ref, *rest,
    alpha, beta, nk, prec, check_every, bm, bn,
    exact=False, adaptive=False, bk=None,
    unroll=1, swap_ij=False, epi=None, bias_ref=None,
):
    """Scalar-checksum, detect-only variant (``ft_sgemm_huge_thread.cuh``)."""
    idx = 0
    acc_ref = out_ref
    if exact:
        acc_ref = rest[idx]
        idx += 1
    if adaptive and not exact:
        mom_ref = rest[idx]
        idx += 1
    k = pl.program_id(2)
    i, j = _grid_ij(swap_ij)
    threshold = inj_ref[4]  # runtime scalar (no moment re-checks here)

    @pl.when(k == 0)
    def _zero():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        t_exp_ref[0] = 0 if exact else 0.0
        prev_ref[0] = 0 if exact else 0.0
        if adaptive and not exact:
            mom_ref[:] = jnp.zeros_like(mom_ref)
        count_ref[0] = 0

    _inject(acc_ref, inj_ref, k, i, j, bm, bn, exact=exact)

    a_blk = a_ref[:]
    b_blk = b_ref[:]
    for a_sub, b_sub in _sub_panels(a_blk, b_blk, unroll):
        acc_ref[:] += jax.lax.dot_general(
            a_sub, b_sub,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.int32 if exact else jnp.float32,
            precision=prec,
        )
    enc_t = jnp.int32 if exact else jnp.float32
    s_b = jnp.sum(b_blk.astype(enc_t), axis=0, keepdims=True)  # (1, bk)
    # Total expected sum of this panel's product: sum_k s_a[k] * s_b[k].
    t_exp_ref[0] += jnp.sum(
        jnp.sum(a_blk.astype(enc_t), axis=0, keepdims=True) * s_b)
    if adaptive and not exact:
        _accumulate_moments(mom_ref, a_blk.astype(jnp.float32),
                            b_blk.astype(jnp.float32))

    do_check = ((k + 1) % check_every == 0) | (k == nk - 1)

    @pl.when(do_check)
    def _detect():
        # Count fault EVENTS, not failed checks: an uncorrected fault keeps
        # the residual high forever, so compare against the previous check's
        # residual — only NEW corruption (residual moved by > threshold)
        # increments the count. Makes num_detected comparable across
        # strategies (FtSgemmResult docstring).
        res = t_exp_ref[0] - jnp.sum(acc_ref[:])
        if adaptive:
            thr = (jnp.float32(0.5) if exact else _adaptive_threshold(
                mom_ref, k, bk=bk, bm=bm, bn=bn, nk=nk, margin=inj_ref[7],
                global_tile=True))
        else:
            thr = threshold
        count_ref[0] += (jnp.abs(res - prev_ref[0]).astype(jnp.float32)
                         > thr).astype(jnp.int32)
        prev_ref[0] = res

    @pl.when(k == nk - 1)
    def _epilogue():
        if exact:
            out_ref[:] = _apply_epilogue(
                alpha * acc_ref[:].astype(jnp.float32) + beta * c_ref[:],
                epi, _epilogue_bias_row(bias_ref))
        else:
            out_ref[:] = _apply_epilogue(
                alpha * out_ref[:] + beta * c_ref[:],
                epi, _epilogue_bias_row(bias_ref))
        det_ref[i, j] = count_ref[0]
        # Detect-only strategy: every detection is by definition
        # uncorrected (FtSgemmResult docstring).
        unc_ref[i, j] = count_ref[0]


def _ft_kernel_weighted(
    inj_ref, a_ref, b_ref, c_ref, out_ref, det_ref, unc_ref,
    c_exp_ref, cw_exp_ref, cw2_exp_ref, *rest,
    alpha, beta, nk, prec, check_every, bm, bn,
    adaptive=False, bk=None,
    unroll=1, swap_ij=False, epi=None, bias_ref=None,
):
    """Weighted-checksum variant with fault *localization*.

    Two column checksums — plain and row-index-weighted — let the kernel
    compute WHICH row of a corrupted column holds the fault:
    ``row = round(res_weighted / res) - 1`` (the TPU analog of the
    reference's ``correct_t`` macro, ``include/ft_sgemm_huge.cuh:13-17``,
    with weight base {1..8} generalized to {1..bm}).
    """
    if adaptive:
        (mom_ref, count_ref, unc_count_ref) = rest
    else:
        count_ref, unc_count_ref = rest
    k = pl.program_id(2)
    i, j = _grid_ij(swap_ij)
    threshold = inj_ref[4]  # runtime scalars: per-call thresholds
    thr_m1 = inj_ref[5]     # weighted-moment re-check threshold
    thr_m2 = inj_ref[6]     # second-moment re-check threshold

    # tpu.iota is integer-only; cast to f32 for the weights {1..bm}.
    w_col = jax.lax.broadcasted_iota(jnp.int32, (bm, 1), 0).astype(jnp.float32) + 1.0

    @pl.when(k == 0)
    def _zero():
        out_ref[:] = jnp.zeros_like(out_ref)
        c_exp_ref[:] = jnp.zeros_like(c_exp_ref)
        cw_exp_ref[:] = jnp.zeros_like(cw_exp_ref)
        cw2_exp_ref[:] = jnp.zeros_like(cw2_exp_ref)
        if adaptive:
            mom_ref[:] = jnp.zeros_like(mom_ref)
        count_ref[0] = 0
        unc_count_ref[0] = 0

    _inject(out_ref, inj_ref, k, i, j, bm, bn)

    a_blk = a_ref[:]
    b_blk = b_ref[:]
    for a_sub, b_sub in _sub_panels(a_blk, b_blk, unroll):
        out_ref[:] += jax.lax.dot_general(
            a_sub, b_sub,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=prec,
        )
    af = a_blk.astype(jnp.float32)
    bf = b_blk.astype(jnp.float32)
    s_a = jnp.sum(af, axis=0, keepdims=True)                 # (1, bk)
    s_aw = jnp.sum(af * w_col, axis=0, keepdims=True)        # (1, bk)
    # Second-moment (w^2) stream: consumed only by the after-correct
    # re-check — a point-mass correction can match the 0th and 1st column
    # moments of a multi-fault column (equal faults at rows in arithmetic
    # progression do exactly that) but never all three for same-sign
    # faults (strict convexity of w^2).
    s_aw2 = jnp.sum(af * (w_col * w_col), axis=0, keepdims=True)  # (1, bk)
    c_exp_ref[:] += jnp.sum(bf * s_a, axis=1, keepdims=True)       # (bn, 1)
    cw_exp_ref[:] += jnp.sum(bf * s_aw, axis=1, keepdims=True)     # (bn, 1)
    cw2_exp_ref[:] += jnp.sum(bf * s_aw2, axis=1, keepdims=True)   # (bn, 1)
    if adaptive:
        _accumulate_moments(mom_ref, af, bf)

    do_check = ((k + 1) % check_every == 0) | (k == nk - 1)

    @pl.when(do_check)
    def _detect_correct():
        if adaptive:
            thr = _adaptive_threshold(mom_ref, k, bk=bk, bm=bm, bn=bn,
                                      nk=nk, margin=inj_ref[7])
            thrs = (thr, thr * float(bm / np.sqrt(3.0)),
                    thr * float(bm ** 2 / np.sqrt(5.0)))
        else:
            thrs = (threshold, thr_m1, thr_m2)
        corrected, n_hit, n_unc = _moment_detect_correct(
            out_ref[:], jnp.swapaxes(c_exp_ref[:], 0, 1),
            jnp.swapaxes(cw_exp_ref[:], 0, 1),
            jnp.swapaxes(cw2_exp_ref[:], 0, 1),
            thrs, bm, bn)
        out_ref[:] = corrected
        count_ref[0] += n_hit
        unc_count_ref[0] = n_unc  # LEVEL semantics (helper docstring)

    @pl.when(k == nk - 1)
    def _epilogue():
        out_ref[:] = _apply_epilogue(
            alpha * out_ref[:] + beta * c_ref[:], epi,
            _epilogue_bias_row(bias_ref))
        det_ref[i, j] = count_ref[0]
        unc_ref[i, j] = unc_count_ref[0]


def _ft_kernel_weighted_precomp(
    inj_ref, a_ref, b_ref, c_ref, exp_ref, out_ref, det_ref, unc_ref,
    count_ref,
    *, alpha, beta, nk, prec, bm, bn,
    unroll=1, swap_ij=False, epi=None, bias_ref=None,
):
    """Weighted variant with PRECOMPUTED expected checksums (deferred check).

    The weighted strategy's default cadence is a single final check (its
    per-column localization corrects the whole fault backlog at once), so
    the running ``c_exp``/``cw_exp`` accumulation never serves an
    intermediate check — the totals are all that is consumed. Those totals
    are a closed form over the inputs: for output tile (i, j),

        c_exp  = (1^T A_i) B_j^T      cw_exp = (w^T A_i) B_j^T

    which the wrapper computes for ALL tiles with one stacked XLA dot over
    A (FLOP cost 2 * 2 * (M/bm) * N * K — ~0.2 % of the GEMM at bm=512,
    full MXU rate). That strips every per-panel VPU/encode instruction out
    of the kernel body: the hot loop is exactly the plain kernel's MXU dot,
    and ABFT work happens once, at ``k == nk - 1``. The in-kernel encode
    variant (:func:`_ft_kernel_weighted`) remains for user-set intermediate
    cadences (``check_every < nk``), which need running partial sums.

    Fault-coverage semantics are unchanged: expectations come from a
    separate accumulation path over the same rounded inputs, so any
    accumulator corruption (injected or real SDC) still surfaces as a
    column residual at the final check, localized by the weighted ratio.
    """
    k = pl.program_id(2)
    i, j = _grid_ij(swap_ij)
    threshold = inj_ref[4]  # runtime scalars: per-call thresholds
    thr_m1 = inj_ref[5]     # weighted-moment re-check threshold
    thr_m2 = inj_ref[6]     # second-moment re-check threshold

    @pl.when(k == 0)
    def _zero():
        out_ref[:] = jnp.zeros_like(out_ref)
        count_ref[0] = 0

    _inject(out_ref, inj_ref, k, i, j, bm, bn)

    for a_sub, b_sub in _sub_panels(a_ref[:], b_ref[:], unroll):
        out_ref[:] += jax.lax.dot_general(
            a_sub, b_sub,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=prec,
        )

    @pl.when(k == nk - 1)
    def _detect_correct_epilogue():
        corrected, n_hit, n_unc = _moment_detect_correct(
            out_ref[:], exp_ref[0:1, :], exp_ref[1:2, :], exp_ref[2:3, :],
            (threshold, thr_m1, thr_m2), bm, bn)
        count_ref[0] += n_hit
        unc_ref[i, j] = n_unc
        # Correction precedes the alpha/beta epilogue AND the fused
        # epilogue: checksums verify the pre-epilogue accumulator.
        out_ref[:] = _apply_epilogue(
            alpha * corrected + beta * c_ref[:], epi,
            _epilogue_bias_row(bias_ref))
        det_ref[i, j] = count_ref[0]


def _ft_kernel_fused(
    inj_ref, a_ref, b_ref, c_ref, out_ref, det_ref, unc_ref,
    exp_ref, *rest,
    alpha, beta, nk, prec, check_every, bm, bn, n_terms,
    adaptive=False, bk=None,
    unroll=1, swap_ij=False, epi=None, bias_ref=None,
):
    """MXU-fused checksum variant (warp-level analog — module docstring).

    ``a_ref`` blocks are (bm + aug, bk): the augmented tail rows hold the
    input checksum moments (``_augment_tiles`` layout: for term t and moment
    mi, tail row ``3*t + mi``), so the very same MXU dot that accumulates
    the C tile produces the EXPECTED column-moment rows — there is no
    separate encode path to corrupt independently. The moment rows
    accumulate in the ``exp_ref`` VMEM scratch while the C rows accumulate
    in the resident output block, keeping the output array (M, N) with no
    de-augmentation pass over HBM. SDC landing in a checksum row itself
    shows up as a residual with no localizable source row: the correction
    misses, the re-check flags, and the interval is reported uncorrectable
    (never applied to C, which those rows never touch).
    """
    if adaptive:
        (mom_ref, count_ref, unc_count_ref) = rest
    else:
        count_ref, unc_count_ref = rest
    k = pl.program_id(2)
    i, j = _grid_ij(swap_ij)
    threshold = inj_ref[4]  # runtime scalars: per-call thresholds
    thr_m1 = inj_ref[5]     # weighted-moment re-check threshold
    thr_m2 = inj_ref[6]     # second-moment re-check threshold

    @pl.when(k == 0)
    def _zero():
        out_ref[:] = jnp.zeros_like(out_ref)
        exp_ref[:] = jnp.zeros_like(exp_ref)
        if adaptive:
            mom_ref[:] = jnp.zeros_like(mom_ref)
        count_ref[0] = 0
        unc_count_ref[0] = 0

    _inject(out_ref, inj_ref, k, i, j, bm, bn)

    a_blk = a_ref[:]
    b_blk = b_ref[:]
    for a_sub, b_sub in _sub_panels(a_blk, b_blk, unroll):
        prod = jax.lax.dot_general(
            a_sub, b_sub,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=prec,
        )                                   # (bm + aug, bn): C + moments
        out_ref[:] += prod[:bm, :]
        exp_ref[:] += prod[bm:, :]
    if adaptive:
        _accumulate_moments(mom_ref, a_blk[:bm].astype(jnp.float32),
                            b_blk.astype(jnp.float32))

    do_check = ((k + 1) % check_every == 0) | (k == nk - 1)

    @pl.when(do_check)
    def _detect_correct():
        # Expected moments: sum the per-term scratch rows (1 term f32, 3
        # for bf16 hi/lo/lo2 — _augment_tiles).
        exp = [exp_ref[mi:mi + 1, :] for mi in range(3)]
        for t in range(1, n_terms):
            exp = [e + exp_ref[3 * t + mi:3 * t + mi + 1, :]
                   for mi, e in enumerate(exp)]
        if adaptive:
            thr = _adaptive_threshold(mom_ref, k, bk=bk, bm=bm, bn=bn,
                                      nk=nk, margin=inj_ref[7])
            thrs = (thr, thr * float(bm / np.sqrt(3.0)),
                    thr * float(bm ** 2 / np.sqrt(5.0)))
        else:
            thrs = (threshold, thr_m1, thr_m2)
        corrected, n_hit, n_unc = _moment_detect_correct(
            out_ref[:], exp[0], exp[1], exp[2],
            thrs, bm, bn)
        out_ref[:] = corrected
        count_ref[0] += n_hit
        unc_count_ref[0] = n_unc  # LEVEL semantics (helper docstring)

    @pl.when(k == nk - 1)
    def _epilogue():
        out_ref[:] = _apply_epilogue(
            alpha * out_ref[:] + beta * c_ref[:], epi,
            _epilogue_bias_row(bias_ref))
        det_ref[i, j] = count_ref[0]
        unc_ref[i, j] = unc_count_ref[0]


def _tile_moments(ap, bm, n_moments=3):
    """Per-row-tile checksum-moment rows of an operand, in ``ap``'s dtype.

    Returns (gm, R, K): for f32 inputs R=``n_moments`` rows — the first
    ``n_moments`` of the plain / w / w^2 column moments (weights
    {1..bm}) of each (bm, K) row tile; for bf16 R=``3*n_moments`` — each
    moment expanded to bf16 hi+lo+lo2 terms at row ``n_moments*t + mi``
    (term t, moment mi). The 3-term split matters because a single bf16
    cast of ``w^T A_i`` (magnitudes ~1e4) leaves ~0.3-1.4 of expectation
    noise — deposited INTO corrected elements, failing the 0.01/0.01
    verify tolerance — and the w^2 row reaches ~bm^2-scale magnitudes
    where even a 2-term split's noise could graze the 9500 detection
    threshold at K=6144; three terms put every row's error in the f32
    accumulation-noise class. Shared by ``_augment_tiles`` (every MXU
    encode) and ``_expected_col_checksums`` (weighted precomp) so the
    encode numerics of all MXU-side checksum paths stay in lockstep.
    """
    m, kdim = ap.shape
    gm = m // bm
    af = ap.reshape(gm, bm, kdim).astype(jnp.float32)
    w = (jnp.arange(bm, dtype=jnp.float32) + 1.0)[None, :, None]
    cols = [jnp.sum(af, axis=1)]
    if n_moments >= 2:
        cols.append(jnp.sum(af * w, axis=1))
    if n_moments >= 3:
        cols.append(jnp.sum(af * (w * w), axis=1))
    moments = jnp.stack(cols, axis=1)            # (gm, n_moments, K)
    if ap.dtype == jnp.bfloat16:
        hi = moments.astype(jnp.bfloat16)
        rem = moments - hi.astype(jnp.float32)
        lo = rem.astype(jnp.bfloat16)
        lo2 = (rem - lo.astype(jnp.float32)).astype(jnp.bfloat16)
        return jnp.concatenate([hi, lo, lo2], axis=1)  # (gm, 3R, K) bf16
    return moments                               # (gm, n_moments, K) f32


def _augment_tiles(ap, bm, aug, n_moments=3):
    """Append per-row-tile checksum-moment rows to one operand.

    Returns (gm * (bm + aug), K) in ``ap``'s dtype: each tile's tail
    ``aug`` rows hold the ``_tile_moments`` rows (``n_moments`` for f32,
    ``3*n_moments`` hi/lo/lo2 terms for bf16), zero-padded to the
    sublane-aligned ``aug`` (``configs.aug_rows``). Used on A by the
    fused/weighted-mxu (3 moments) and rowcol-mxu (2) paths, and on B by
    the rowcol-mxu and global-mxu paths (1 — B only ever contributes its
    plain sums).
    """
    m, kdim = ap.shape
    gm = m // bm
    rows = _tile_moments(ap, bm, n_moments)
    tail = jnp.zeros((gm, aug, kdim), ap.dtype)
    tail = tail.at[:, :rows.shape[1], :].set(rows.astype(ap.dtype))
    return jnp.concatenate(
        [ap.reshape(gm, bm, kdim), tail], axis=1).reshape(
            gm * (bm + aug), kdim)


def _expected_col_checksums(ap, bp, bm, prec):
    """Per-tile expected (plain, weighted, w^2) column checksums, via XLA.

    ``ap`` is the padded (M, K) input in the kernel's consumption dtype
    (checksums must see the same rounded values the MXU consumes — moment
    rows and bf16 term-splitting come from ``_tile_moments``). Returns
    one (8 * M/bm, N) f32 array: within each 8-row group i, rows 0-2 hold
    ``1^T A_i @ B^T``, ``w^T A_i @ B^T``, ``(w^2)^T A_i @ B^T``; rows 3-7
    are zero — an (8, bn)-blockable layout (Mosaic requires sublane dims
    divisible by 8).
    """
    rows = _tile_moments(ap, bm)                     # (gm, R, K)
    gm, r, kdim = rows.shape
    if bp.dtype.itemsize == 1:
        # fp8 operands: the moment rows are f32 (magnitudes ~bm * max|x|
        # are unrepresentable in e4m3 — the same reason encode="mxu" is
        # illegal for 1-byte dtypes), so the precompute dot upcasts B and
        # runs at full f32 precision; expectations then carry only f32
        # accumulation noise over the SAME fp8-rounded values the kernel
        # consumes, exactly like the in-kernel VPU encode.
        bp = bp.astype(jnp.float32)
        prec = jax.lax.Precision("highest")
    exp = jax.lax.dot_general(
        rows.reshape(gm * r, kdim), bp,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=prec,
    ).reshape(gm, r, -1)                             # (gm, R, N) f32
    if r == 9:  # bf16: sum the hi/lo/lo2 term rows per moment
        exp = exp[:, 0:3] + exp[:, 3:6] + exp[:, 6:9]
    grouped = jnp.zeros((gm, 8, exp.shape[2]), jnp.float32)
    grouped = grouped.at[:, :3, :].set(exp)
    return grouped.reshape(8 * gm, exp.shape[2])


def _scratch_for(strategy, bm, bn, multifault, exact=False, adaptive=False):
    # No accumulator scratch on the float paths: the kernels accumulate in
    # the resident f32 output block (see _matmul_kernel in ops/sgemm.py for
    # the rationale). The int8-exact path (``exact``) accumulates apart in
    # an int32 VMEM block (the f32 output cannot hold wrapping int32
    # partials) with int32 checksum streams; adaptive mode appends the
    # (4,) SMEM moment scalars the in-kernel threshold derivation reads
    # (skipped for exact — its threshold is the constant half-ulp).
    count = pltpu.SMEM((1,), jnp.int32)
    unc = pltpu.SMEM((1,), jnp.int32)
    acc_t = jnp.int32 if exact else jnp.float32
    extra = []
    if exact:
        extra.append(pltpu.VMEM((bm, bn), jnp.int32))      # acc
    if adaptive and not exact:
        extra.append(pltpu.SMEM((4,), jnp.float32))        # moments
    if strategy == "rowcol":
        vecs = [pltpu.VMEM((bm, 1), acc_t),
                pltpu.VMEM((bn, 1), acc_t)]
        if multifault:
            vecs.append(pltpu.VMEM((bn, 1), jnp.float32))  # cw_exp
        return [*vecs, *extra, count, unc]
    if strategy == "global":
        return [pltpu.SMEM((1,), acc_t),
                pltpu.SMEM((1,), acc_t), count, *extra]
    if strategy == "weighted":
        return [pltpu.VMEM((bn, 1), jnp.float32),
                pltpu.VMEM((bn, 1), jnp.float32),
                pltpu.VMEM((bn, 1), jnp.float32), *extra, count, unc]
    raise ValueError(f"unknown strategy {strategy!r}; pick from {STRATEGIES}")


_KERNELS = {
    "rowcol": _ft_kernel_rowcol,
    "global": _ft_kernel_global,
    "weighted": _ft_kernel_weighted,
}

# User-facing (strategy, encode) -> the kernel-level strategy value
# _ft_sgemm_padded dispatches on. The fused strategy IS the weighted
# design's MXU encode, so the two spellings share one kernel body.
_MXU_KERNEL_STRATEGY = {
    "weighted": "fused",
    "fused": "fused",
    "rowcol": "rowcol_mxu",
    "global": "global_mxu",
}


def resolve_kernel_strategy(strategy: str, encode: str) -> str:
    """The kernel/variant name a (strategy, encode) pair runs — shared
    with the VMEM footprint model and the tuner's variant mapping (the
    fitting variant must be the body that runs)."""
    if encode == "mxu" or strategy == "fused":
        return _MXU_KERNEL_STRATEGY[strategy]
    return strategy


@functools.partial(
    jax.jit,
    static_argnames=(
        "shape", "alpha", "beta", "precision", "check_every",
        "strategy", "interpret", "multifault", "adaptive", "variant",
    ),
)
def _ft_sgemm_padded(
    a, b, c, inj,
    *, shape: KernelShape, alpha, beta, precision, threshold, check_every,
    strategy, interpret, multifault=False, adaptive=False, margin=None,
    variant: KernelVariant = DEFAULT_VARIANT, bias=None,
):
    m, k = a.shape
    n, _ = b.shape
    bm, bn, bk = shape.block
    unroll = variant.pipeline_depth - 1
    kw = bk * unroll           # buffered K window (unroll panels/step)
    nk = k // kw
    gm, gn = m // bm, n // bn
    prec = jax.lax.Precision(precision)
    check_every = max(1, check_every)
    swap_ij = variant.grid_order == "nm"
    epi = variant.epilogue_spec
    epi = None if epi.is_identity else epi
    grid, a_map, b_map, c_map, row_map = _grid_and_maps(
        variant.grid_order, gm, gn, nk)
    # int8 inputs run the int32-exact accumulation bodies (rowcol/global
    # only — configs.check_kernel_legality gates the rest).
    exact = a.dtype == jnp.int8
    # Runtime thresholds ride the scalar operand (slots 4-6: detection,
    # weighted-moment re-check, second-moment re-check): per-call —
    # including traced, data-dependent "auto" — thresholds at zero
    # recompile cost.
    # Each threshold saturates at a finite huge value: downstream moment
    # scalings (bm, bm^2) could re-overflow an already-saturated bound to
    # inf, which would silently disable the very check it parameterizes.
    cap = jnp.float32(np.finfo(np.float32).max / 16.0)
    parts = [
        jnp.asarray(inj, jnp.float32),
        jnp.stack([jnp.minimum(jnp.asarray(t, jnp.float32), cap)
                   for t in threshold])]
    if adaptive:
        # Slot 7: the threshold margin the in-kernel variance-bound
        # derivation multiplies (slots 4-6 are unread in adaptive mode).
        parts.append(jnp.asarray(margin, jnp.float32)[None])
    inj = jnp.concatenate(parts)

    # Weighted strategy at its default single-final-check cadence: expected
    # checksums are closed-form totals, precomputed by XLA outside the
    # kernel (see _ft_kernel_weighted_precomp). Intermediate cadences need
    # the running in-kernel encode — as does adaptive mode, whose moment
    # statistics ride the encode pass.
    precomp = (strategy == "weighted" and check_every >= nk
               and not adaptive)

    a_rows = bm  # A block / output block row count (augmented for MXU encode)
    b_rows = bn  # B block row count (augmented when B carries checksum rows)
    n_terms = 3 if a.dtype == jnp.bfloat16 else 1
    # Variant axes every kernel body understands: the deep-pipeline
    # sub-panel unroll, the grid-order program-id swap, and the fused
    # epilogue (the bias operand, when fused, rides LAST so positional
    # signatures stay stable — _attach_bias re-routes it).
    vkw = dict(unroll=unroll, swap_ij=swap_ij, epi=epi)
    in_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),  # inj spec + thresholds (7,)
        None,  # A spec placed below once a_rows is final
        None,  # B spec placed below once b_rows is final
        pl.BlockSpec((bm, bn), c_map),
    ]
    operands = [inj, a, b, c]
    if precomp:
        kernel = functools.partial(
            _ft_kernel_weighted_precomp,
            alpha=alpha, beta=beta, nk=nk, prec=prec, bm=bm, bn=bn,
            **vkw,
        )
        exp = _expected_col_checksums(a, b, bm, prec)
        in_specs += [pl.BlockSpec((8, bn), c_map)]
        operands += [exp]
        scratch = [pltpu.SMEM((1,), jnp.int32)]
    elif strategy == "fused":
        aug = _aug_rows(a.dtype.itemsize)
        a_rows = bm + aug
        operands[1] = _augment_tiles(a, bm, aug)
        kernel = functools.partial(
            _ft_kernel_fused,
            alpha=alpha, beta=beta, nk=nk, prec=prec,
            check_every=check_every, bm=bm, bn=bn, n_terms=n_terms,
            adaptive=adaptive, bk=kw,
            **vkw,
        )
        scratch = [pltpu.VMEM((aug, bn), jnp.float32)]
        if adaptive:
            scratch.append(pltpu.SMEM((4,), jnp.float32))
        scratch += [pltpu.SMEM((1,), jnp.int32), pltpu.SMEM((1,), jnp.int32)]
    elif strategy == "rowcol_mxu":
        aug = _aug_rows(a.dtype.itemsize)
        a_rows, b_rows, _ = shape.aug_block(aug, aug)
        operands[1] = _augment_tiles(a, bm, aug, n_moments=2)
        operands[2] = _augment_tiles(b, bn, aug, n_moments=1)
        kernel = functools.partial(
            _ft_kernel_rowcol_mxu,
            alpha=alpha, beta=beta, nk=nk, prec=prec,
            check_every=check_every, bm=bm, bn=bn,
            multifault=multifault, n_terms=n_terms,
            adaptive=adaptive, bk=kw,
            **vkw,
        )
        scratch = [pltpu.VMEM((bm, aug), jnp.float32),   # r_exp term cols
                   pltpu.VMEM((aug, bn), jnp.float32)]   # c_exp moment rows
        if adaptive:
            scratch.append(pltpu.SMEM((4,), jnp.float32))
        scratch += [pltpu.SMEM((1,), jnp.int32), pltpu.SMEM((1,), jnp.int32)]
    elif strategy == "global_mxu":
        aug = _aug_rows(a.dtype.itemsize)
        a_rows, b_rows, _ = shape.aug_block(aug, aug)
        operands[1] = _augment_tiles(a, bm, aug, n_moments=1)
        operands[2] = _augment_tiles(b, bn, aug, n_moments=1)
        kernel = functools.partial(
            _ft_kernel_global_mxu,
            alpha=alpha, beta=beta, nk=nk, prec=prec,
            check_every=check_every, bm=bm, bn=bn,
            adaptive=adaptive, bk=kw,
            **vkw,
        )
        scratch = [pltpu.SMEM((1,), jnp.float32),
                   pltpu.SMEM((1,), jnp.float32), pltpu.SMEM((1,), jnp.int32)]
        if adaptive:
            scratch.append(pltpu.SMEM((4,), jnp.float32))
    else:
        extra = {"multifault": multifault} if strategy == "rowcol" else {}
        if strategy in ("rowcol", "global"):
            extra["exact"] = exact
        kernel = functools.partial(
            _KERNELS[strategy],
            alpha=alpha, beta=beta, nk=nk, prec=prec,
            check_every=check_every, bm=bm, bn=bn,
            adaptive=adaptive, bk=kw,
            **extra,
            **vkw,
        )
        scratch = _scratch_for(strategy, bm, bn, multifault,
                               exact=exact, adaptive=adaptive)
    in_specs[1] = pl.BlockSpec((a_rows, kw), a_map)
    in_specs[2] = pl.BlockSpec((b_rows, kw), b_map)
    if epi is not None and epi.bias:
        in_specs.append(pl.BlockSpec((8, bn), row_map))
        operands.append(bias)
        kernel = _attach_bias(kernel, n_in=len(operands))

    out, det, unc = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((bm, bn), c_map),
            # Full-array SMEM blocks: each (i, j) program writes its own cell
            # (grid-blocked SMEM outputs must match the array shape).
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((m, n), jnp.float32),
            jax.ShapeDtypeStruct((gm, gn), jnp.int32),
            jax.ShapeDtypeStruct((gm, gn), jnp.int32),
        ],
        scratch_shapes=scratch,
        # The C operand aliases the f32 output: the beta*C epilogue reads
        # each C tile in the same grid step that retires its output tile,
        # so under jit XLA reuses the buffer instead of allocating and
        # copying a second (M, N) HBM array (pinned in tests).
        input_output_aliases={3: 0},
        compiler_params=_CompilerParams(
            dimension_semantics=(variant.dim_semantics,
                                 variant.dim_semantics, "arbitrary"),
            vmem_limit_bytes=vmem_limit_bytes(),
        ),
        cost_estimate=_gemm_cost_estimate(
            m, n, k, a.dtype.itemsize, block=shape.block, strategy=strategy,
            multifault=multifault, check_every=check_every),
        interpret=interpret,
    )(*operands)
    return out, det, unc


def make_ft_sgemm(
    shape: KernelShape | str,
    *,
    alpha: float = 1.0,
    beta: float = -1.5,
    strategy: str = "weighted",
    encode: str = "vpu",
    threshold: float | str = REFERENCE_THRESHOLD,
    threshold_margin: float = DEFAULT_THRESHOLD_MARGIN,
    check_every: Optional[int] = None,
    precision: str = "highest",
    in_dtype: str = "float32",
    multifault: Optional[bool] = None,
    interpret: Optional[bool] = None,
    tunable: Optional[bool] = None,
    variant: Optional[KernelVariant] = None,
    epilogue=None,
):
    """Build the fused-ABFT SGEMM for one named shape.

    Returns ``fn(a, b, c, inject=None, bias=None) -> FtSgemmResult``.
    ``inject`` is an
    :class:`InjectionSpec` (default: no injection — the clean path the
    reference lacks). ``check_every`` is the detect/correct cadence in
    K-grid steps; default scales to ~20 checks per run like the reference's
    ``K/20``-column cadence (``code_gen.py:333``), clamped to every step for
    short K.

    ``multifault`` (``rowcol`` only) selects the multi-fault-safe variant
    that carries an extra weighted column checksum so ANY check cadence
    corrects any number of per-interval faults (one per corrupted column).
    Default ``None`` auto-selects: skipped only when the injection spec
    itself proves at most one fault lands per check interval (cadence <=
    injection period), where the plain intersection is already exact —
    matching the reference's by-construction guarantee
    (``code_gen.py:333-337``) at zero extra encode cost; enabled otherwise
    (including clean runs, where real SDC counts are unknown). For the
    column-localized correcting strategies (``rowcol``/``weighted``/
    ``fused``), the cadence is clamped to ``bn * inject.every`` (when the
    injector's column stride is coprime to bn) so the rotating injector
    cannot wrap two faults into the same column of one interval.

    ``in_dtype="bfloat16"`` feeds A/B to the MXU at its full-rate bf16 input
    format; the accumulator, checksums, and detect/correct math all stay
    f32. Checksums are computed on the bf16-rounded values the MXU actually
    consumes, so the residual noise floor is unchanged from the f32 path and
    the same thresholds apply. ``in_dtype="float8_e4m3fn"`` (aliases
    ``fp8``/``fp8_e4m3``) works the same way — fp8 operands, f32
    accumulation, f32 checksums over the rounded values.
    ``in_dtype="int8"`` runs the int32-EXACT path: the dot accumulates in
    int32 (a separate VMEM accumulator block), the checksum streams are
    int32, and wrapping arithmetic keeps residuals exact mod 2^32 — clean
    residuals are identically zero and corrections are exact. Pass
    integer-valued data (the cast truncates fractions). Per-dtype
    legality (``configs.check_kernel_legality``): the 1-byte dtypes
    cannot carry MXU checksum rows (``encode="vpu"`` only, no ``fused``),
    and int8 ships the non-ratio-localizing strategies
    (``rowcol``/``global``, no ``multifault``) — see DESIGN.md §10.

    ``strategy="fused"`` runs the MXU-augmented variant (module docstring):
    checksum moments ride extra A rows through the same dot — weighted-
    class correction at any cadence with zero per-panel encode work.

    ``encode`` selects how expected checksums are produced for the WHOLE
    strategy family (module docstring "Encode modes"): ``"vpu"`` (default)
    keeps the original per-K-step VPU reductions — the emitted HLO is
    byte-identical to not passing ``encode`` at all; ``"mxu"`` appends the
    panel checksum rows to the A (and, for rowcol/global, B) tiles so ONE
    ``dot_general`` per K step yields the partial product and the
    expected-checksum accumulators. ``strategy="fused"`` is the
    ``("weighted", "mxu")`` combination under its historical name and
    always encodes on the MXU. Detection, correction, cadence, threshold,
    and reporting semantics are identical across encodes.

    ``threshold`` is a float (one fixed detection threshold — the
    reference's operating point; the literal ``"static"`` names this
    default and lowers to byte-identical HLO) or a mode string:

    - ``"auto"`` computes the threshold PER CALL from the full inputs'
      moments: ``threshold_margin`` x the calibrated closed-form
      noise-floor bound (``analysis.estimate_noise_floor``). Same kernel
      program as static — thresholds are runtime scalars riding the SMEM
      operand, so the mode costs zero recompiles and composes under
      ``jit``. With the reference's quantized inputs at 4096 this lands
      near 0.02 instead of 9500: faults five orders of magnitude smaller
      become reliably detectable, at an unchanged false-positive margin.
    - ``"adaptive"`` derives the threshold PER TILE PER CHECK inside the
      kernel (the V-ABFT capability, DESIGN.md §10): the encode pass
      accumulates each tile's running sum and sum-of-squares (four VPU
      reductions overlapping the MXU dot, both encodes), and every check
      evaluates ``threshold_margin`` x the variance bound at that tile's
      statistics and accumulation depth. The mode that holds zero false
      positives under heterogeneous or drifting operand statistics —
      what makes detection calibrated at bf16 and below (``cli roc``
      produces the static-vs-adaptive domination artifact). Correction
      semantics are unchanged; the weighted strategy runs its in-kernel
      encode body (the precomp body has no encode pass to ride).

    ``variant`` pins the full kernel-variant descriptor
    (:class:`~ft_sgemm_tpu.configs.KernelVariant`): pipeline depth (the
    deep-pipeline K-window unroll), grid traversal order, Mosaic
    dimension semantics, detect/correct cadence, and the fused epilogue.
    ``None`` (the default) dispatches the historical behavior —
    byte-identical HLO — and lets a tuned winner's variant axes apply;
    an explicit variant is respected verbatim (the tuner may still
    serve a tile for that exact variant key). ``check_every`` and
    ``variant.check_every`` name the same axis; the explicit
    ``check_every`` argument wins when both are given. With a deep
    pipeline the cadence (and the injection schedule) counts GRID steps,
    each of which now consumes ``(pipeline_depth - 1)`` K panels.

    ``epilogue`` (an :class:`~ft_sgemm_tpu.configs.EpilogueSpec` or a
    spelling like ``"bias+relu"`` / ``"bias+gelu+qint8x0.5"``) fuses a
    bias add, activation, and int8/fp8 quantize-rescale into the
    detect-correct epilogue — applied strictly AFTER correction, so the
    ABFT checksums verify the pre-epilogue accumulator and
    detection/correction semantics are untouched (oracle-pinned under
    injection in tests/test_variants.py). A fused bias is passed per
    call: ``fn(a, b, c, inject, bias=v)`` with ``v`` of length N.

    ``tunable`` controls whether dispatch consults the autotuner's tile
    cache (``ft_sgemm_tpu.tuner``). Default ``None`` resolves to "named
    shapes only": a persisted winner for this call's
    ``(device, M/N/K bucket, dtype, strategy, injection, variant)`` key
    then overrides the heuristic block choice (and, for un-pinned
    callers, the variant axes); with no cache entry (or tuning
    disabled) the dispatch path — and the emitted HLO — is untouched.
    Explicit ``KernelShape`` objects stay un-tuned by default (a tile
    sweep measures the tile its row label claims); the attention
    factories opt their default tiles in with ``tunable=True``.
    """
    if strategy not in STRATEGIES:
        raise ValueError(f"unknown strategy {strategy!r}; pick from {STRATEGIES}")
    if encode not in ENCODE_MODES:
        raise ValueError(
            f"unknown encode mode {encode!r}; pick from {ENCODE_MODES}")
    if isinstance(threshold, str):
        if threshold not in THRESHOLD_MODES:
            raise ValueError(
                f"threshold must be a float or one of {THRESHOLD_MODES},"
                f" got {threshold!r}")
        threshold_mode = threshold
        if threshold == "static":
            threshold = REFERENCE_THRESHOLD  # the named default spelling
    else:
        threshold_mode = "static"
    adaptive = threshold_mode == "adaptive"
    # Low-precision / threshold-mode legality (per-dtype constraints:
    # 1-byte dtypes cannot carry MXU checksum rows; int8 runs the exact
    # non-localizing strategies) — one gate shared with the CLI and tuner.
    in_dtype = _check_kernel_legality(
        strategy=strategy, encode=encode, in_dtype=in_dtype,
        threshold_mode=threshold_mode, multifault=multifault)
    if strategy == "fused":
        encode = "mxu"  # the fused strategy IS the weighted MXU encode
    kernel_strategy = resolve_kernel_strategy(strategy, encode)
    in_dtype, precision = _resolve_in_dtype(in_dtype, precision,
                                            allow_low_precision=True)
    exact = in_dtype == jnp.int8
    # Variant resolution: an explicit variant (or explicit check_every)
    # pins those axes; everything left unpinned may be overridden by a
    # tuned winner at dispatch. The epilogue is workload-owned: it is
    # always concrete (default "none"), never searched per call.
    pinned_variant = variant is not None
    var = canonical_variant(variant)
    if epilogue is not None:
        var = dataclasses.replace(
            var, epilogue=EpilogueSpec.parse(epilogue).spelling)
    if check_every is None:
        check_every = var.check_every
    named = isinstance(shape, str)
    tunable = named if tunable is None else bool(tunable)
    if named:
        # Named shapes pick up the dtype-tuned tile; explicit KernelShape
        # objects are always respected as-is — including no auto-shrinking,
        # so a tile sweep (scripts/tune_tiles.py) measures exactly the tile
        # its row label claims.
        shape = shape_for_dtype(SHAPES[shape], True, in_dtype)

    def fn(a, b, c, inject: Optional[InjectionSpec] = None,
           bias=None) -> FtSgemmResult:
        inject = inject or InjectionSpec.none()
        a = jnp.asarray(a, in_dtype)
        b = jnp.asarray(b, in_dtype)
        c = jnp.asarray(c, jnp.float32)
        m, n = c.shape
        # (placeholder; thresholds are computed after the tile resolves,
        # since the re-check scales depend on bm — see below)
        eff = _shrink_block(shape, m, n, a.shape[1]) if named else shape
        eff_var = var
        ce_req = check_every   # cadence constraint (None = strategy auto)
        if tunable:
            # Cache-backed dispatch: a persisted tuned winner for this
            # exact (device, size bucket, dtype, strategy, injection,
            # variant) key overrides the heuristic tile — and, where the
            # caller pinned nothing, the variant axes. Pure host-side
            # lookup — a miss (or tuning disabled) leaves eff/eff_var,
            # and therefore the traced computation, bit-for-bit
            # unchanged.
            from ft_sgemm_tpu import tuner as _tuner

            tuned, tuned_var = _tuner.lookup_winner(
                m, n, a.shape[1],
                strategy=("weighted" if strategy == "fused" else strategy),
                encode=encode, in_dtype=in_dtype,
                injection_enabled=inject.enabled,
                threshold_mode=("adaptive" if adaptive else "static"),
                variant=var if pinned_variant else None,
                cadence=check_every, epilogue=var.epilogue)
            if tuned is not None:
                eff = tuned
            if tuned_var is not None and not pinned_variant:
                # The winner's searched pipeline/grid/cadence apply; the
                # epilogue stays the caller's (it is part of the key, so
                # the spellings already agree), and an explicit
                # check_every argument keeps priority over the winner's
                # cadence.
                eff_var = dataclasses.replace(
                    tuned_var, epilogue=var.epilogue)
                if check_every is None:
                    ce_req = tuned_var.check_every

        unroll = eff_var.pipeline_depth - 1

        def resolve_cadence(e):
            """nk and the effective check cadence at tile ``e``.

            One resolver for the VMEM-fit variant choice AND the final
            kernel parameters, so the fitted body is the body that runs.
            ``nk`` counts GRID steps: with a deep pipeline each step
            consumes ``unroll`` K panels of ``e.bk``.
            """
            nk_ = -(-a.shape[1] // (e.bk * unroll))
            if ce_req is not None:
                ce_ = ce_req
            elif strategy in ("weighted", "fused"):
                ce_ = nk_  # single final check: localization absorbs
                # the whole fault backlog
            else:
                # ~20 checks per run like the reference's K/20-column
                # cadence (code_gen.py:333), rounded to nearest so
                # shallow-K-grid runs don't overshoot (nk=32: every-other-
                # step = 16 checks, vs 32 checks with floor — the
                # reference does 20 regardless).
                ce_ = max(1, round(nk_ / 20))
            if (inject.enabled
                    and strategy in ("rowcol", "weighted", "fused")
                    and math.gcd(inject.col_stride, e.bn) == 1):
                # Column-localized correction needs the interval's faults
                # in DISTINCT columns. A column stride coprime to bn
                # advances the column by a full cycle only after bn
                # injections, so up to bn faults per interval stay
                # distinct; only clamp for K deep enough to wrap the
                # cycle. Non-coprime strides (e.g. the adversarial
                # col_stride=0) can collide regardless of cadence — no
                # clamp helps; the in-kernel residual-after-correct
                # re-check reports those intervals via
                # FtSgemmResult.uncorrectable.
                ce_ = min(ce_, e.bn * max(1, inject.every))
            return nk_, ce_

        # Trace-time scoped-VMEM guard: a tile over the Mosaic budget is
        # auto-shrunk (named shapes) or loudly warned about (explicit
        # shapes) instead of dying inside the compiler — the failure mode
        # that cost round 4 its hardware window (ops/vmem.py). The fit
        # targets the body that will actually run: weighted at a single-
        # final-check cadence runs the lighter precomp body (estimating
        # the in-kernel encode body instead would warn/shrink for tiles
        # the real kernel fits — the tuner's pre-filter makes the same
        # call, scripts/tune_tiles.py).
        nk0, ce0 = resolve_cadence(eff)
        fit_variant = kernel_strategy
        if kernel_strategy == "weighted" and ce0 >= nk0 and not adaptive:
            # Adaptive mode always runs the in-kernel encode body: its
            # moment statistics ride the encode pass (_ft_sgemm_padded).
            fit_variant = "weighted_precomp"
        limit = vmem_limit_bytes()
        itemsize = jnp.dtype(in_dtype).itemsize
        depth = eff_var.pipeline_depth
        eff = _fit_block_to_vmem(
            eff, fit_variant, limit=limit, in_itemsize=itemsize,
            allow_shrink=named, adaptive=adaptive, exact=exact,
            pipeline_depth=depth)
        if fit_variant == "weighted_precomp":
            nk1, ce1 = resolve_cadence(eff)
            if ce1 < nk1:
                # A bk shrink deepened the K grid past an explicit
                # check_every (or the injection clamp): the in-kernel
                # encode body will run after all — re-fit against it.
                eff = _fit_block_to_vmem(
                    eff, "weighted", limit=limit, in_itemsize=itemsize,
                    allow_shrink=named, adaptive=adaptive, exact=exact,
                    pipeline_depth=depth)
        bm, bn, bk = eff.block
        kwin = bk * unroll      # K consumed per grid step
        ap = _pad_to(a, bm, kwin)
        bp = _pad_to(b, bn, kwin)
        cp = _pad_to(c, bm, bn)
        _, ce = resolve_cadence(eff)
        if strategy != "rowcol" or exact:
            # Only rowcol reads the flag (keep jit keys stable); the
            # int8-exact path never localizes by weighted ratio
            # (configs.check_kernel_legality rejects an explicit True).
            mf = False
        elif multifault is None:
            # Auto: the weighted checksum is dead weight iff the injection
            # schedule guarantees <= 1 fault per check interval.
            mf = not (inject.enabled and ce <= max(1, inject.every))
        else:
            mf = multifault
        margin = None
        if adaptive:
            # Per-tile thresholds are derived INSIDE the kernel from the
            # encode pass's running moments; only the margin crosses the
            # host boundary (slots 4-6 ride along zeroed and unread).
            thr = thr_m1 = thr_m2 = jnp.float32(0.0)
            margin = jnp.float32(threshold_margin)
        elif threshold == "auto":
            # Data-dependent thresholds from the PRE-pad inputs (padding
            # zeros would dilute the moments); traced, so they follow the
            # actual call-time data even under jit. The weighted (w) and
            # second-moment (w^2) re-check floors are ~rms(w) = bm/sqrt(3)
            # and ~rms(w^2) = bm^2/sqrt(5) times the plain one; the
            # detect-only global strategy's single whole-tile residual
            # aggregates ~bn column residuals (~sqrt(bn) noise).
            floor = _estimate_noise_floor_jnp(
                a, b, c if beta != 0.0 else None, alpha, beta)
            thr = threshold_margin * floor
            if strategy == "global":
                thr = thr * float(np.sqrt(eff.bn))
            thr_m1 = thr * float(eff.bm / np.sqrt(3.0))
            thr_m2 = thr * float(eff.bm ** 2 / np.sqrt(5.0))
        else:
            # Static operating point (reference parity): one threshold for
            # detection and every re-check moment — at 9500-scale the
            # higher moments' noise is negligible and a single scale keeps
            # the adversarial-schedule reports maximally sensitive.
            thr = thr_m1 = thr_m2 = jnp.float32(threshold)
        bias_op = None
        if eff_var.epilogue_spec.bias:
            if bias is None:
                raise ValueError(
                    f"{op_name}: epilogue {eff_var.epilogue!r} fuses a"
                    f" bias — pass fn(a, b, c, inject, bias=v) with v of"
                    f" length N={n}")
            bias_op = _pad_bias(bias, n, bn)
        elif bias is not None:
            raise ValueError(
                f"{op_name}: bias given but epilogue"
                f" {eff_var.epilogue!r} does not fuse one")
        # The padded wrapper reads the variant's lowering axes only
        # (pipe/grid/semantics/epilogue); the cadence already resolved
        # into check_every — normalize it out of the jit key.
        padded_var = dataclasses.replace(eff_var, check_every=None)
        with telemetry.trace_span(op_name):
            out, det, unc = _ft_sgemm_padded(
                ap, bp, cp, jnp.asarray(inject.as_operand()),
                shape=eff, alpha=alpha, beta=beta, precision=precision,
                threshold=(thr, thr_m1, thr_m2), check_every=ce,
                strategy=kernel_strategy, multifault=mf,
                adaptive=adaptive, margin=margin,
                interpret=_should_interpret(interpret),
                variant=padded_var, bias=bias_op,
            )
        result = FtSgemmResult(out[:m, :n], det, unc)
        if telemetry.enabled():
            # Host-side observation of the already-materialized counters
            # (skipped automatically when they are tracers — a caller's
            # jit); the jitted computation above is untouched either way.
            # Adaptive mode records the host-recomputed full-run threshold
            # estimate and the variance statistic it derives from (the
            # in-kernel per-tile values never materialize on host).
            variance = thr_rec = None
            if adaptive:
                try:
                    from ft_sgemm_tpu.analysis import (
                        adaptive_threshold_estimate)

                    thr_rec, variance = adaptive_threshold_estimate(
                        np.asarray(a, np.float32), np.asarray(b, np.float32),
                        bm=eff.bm, bn=eff.bn, margin=threshold_margin)
                except Exception:  # noqa: BLE001 — telemetry is best-effort
                    pass
            else:
                thr_rec = thr
            # A non-identity epilogue transforms the output away from
            # alpha*A@B.T + beta*C, so the host residual measurement
            # would be meaningless — drop the operands there.
            telemetry.record_gemm(
                op_name, result, strategy=strategy, encode=encode,
                threshold=thr_rec, threshold_mode=threshold_mode,
                variance=variance,
                operands=((a, b, c) if eff_var.epilogue_spec.is_identity
                          else None),
                alpha=alpha, beta=beta,
                epilogue=(eff_var.epilogue
                          if eff_var.epilogue != "none" else None))
        return result

    op_name = (f"ft_sgemm_{shape.name}_{strategy}"
               + ("_mxu" if encode == "mxu" and strategy != "fused" else "")
               + ("_adaptive" if adaptive else "")
               + _dtype_suffix(in_dtype)
               + (("_epi_" + var.epilogue.replace("+", "_"))
                  if var.epilogue != "none" else ""))
    fn.__name__ = op_name
    fn.shape_config = shape
    fn.strategy = strategy
    fn.encode = encode
    fn.in_dtype = in_dtype
    fn.threshold_mode = threshold_mode
    fn.variant = var
    fn.epilogue = var.epilogue
    return fn


def ft_sgemm(a, b, c, shape: KernelShape | str = "huge", *, alpha=1.0,
             beta=-1.5, inject: Optional[InjectionSpec] = None,
             strategy: str = "weighted", encode: str = "vpu",
             threshold: float | str = REFERENCE_THRESHOLD,
             threshold_margin: float = DEFAULT_THRESHOLD_MARGIN,
             check_every: Optional[int] = None, precision: str = "highest",
             in_dtype: str = "float32", multifault: Optional[bool] = None,
             interpret: Optional[bool] = None,
             variant: Optional[KernelVariant] = None,
             epilogue=None, bias=None) -> FtSgemmResult:
    """One-shot fused-ABFT SGEMM (see :func:`make_ft_sgemm`)."""
    return make_ft_sgemm(
        shape, alpha=alpha, beta=beta, strategy=strategy, encode=encode,
        threshold=threshold,
        threshold_margin=threshold_margin, check_every=check_every,
        precision=precision, in_dtype=in_dtype,
        multifault=multifault, interpret=interpret,
        variant=variant, epilogue=epilogue,
    )(a, b, c, inject, bias=bias)
