"""Static per-kernel VMEM-footprint estimation and trace-time tile fitting.

Mosaic compiles each Pallas kernel against a scoped-VMEM budget
(``vmem_limit_bytes``); exceeding it is a COMPILE-time error that CPU
interpret mode can never see. Round 4 lost its one hardware window to
exactly that: every FT strategy except ``rowcol`` died 0.3-2 MiB past the
16 MiB default at the tuned 4096 tiles
(``.bench/records_b855854_4096.jsonl``: weighted-precomp 16.27 MiB,
weighted in-kernel 17.93 MiB, fused 16.38 MiB, bf16 weighted-precomp
17.75 MiB). This module makes that failure class impossible to hit blind:
every kernel wrapper estimates its footprint BEFORE ``pallas_call`` and
either auto-shrinks the tile (named shapes) or warns loudly (explicit
shapes, e.g. tuner candidates — a sweep must measure the tile its row
label claims, so it gets the prediction but keeps the tile).

The model is ``pipeline buffers + scratch + temporaries``:

  - **Pipeline buffers**: each grid-blocked operand/output window is
    multi-buffered by Mosaic; 2x its block bytes.
  - **Scratch**: the wrapper's declared VMEM scratch shapes, exact.
  - **Temporaries**: the kernel body's live vector values (dot results,
    accumulator copies, residual/mask tiles). Not statically derivable
    from Python, so modeled as ``factor x (a_rows * bn * 4)`` — one
    accumulator-tile unit — with per-variant factors CALIBRATED against
    the recorded Mosaic numbers above plus the configs that are known to
    have compiled at 16 MiB (plain f32/bf16, rowcol f32). Factors sit a
    safety margin above the observed temp footprint, so estimates are
    conservative: a predicted fit may still (rarely) OOM for an exotic
    tile, but every recorded real OOM is predicted.

Calibration table (observed total - modeled buffers = observed temps, in
accumulator-tile units of ``bm*bn*4``):

  variant            observed temps   factor used
  weighted-precomp   8.2 (f32) / 4.1 (bf16)   9
  weighted           9.9                     11
  fused              8.2                      9
  rowcol             < 7.9 (compiled @16 MiB) 7
  plain              < 3.9 (bf16 deep-K @16)  3
  global             (no observation)         6
"""

from __future__ import annotations

import dataclasses
import warnings

from ft_sgemm_tpu.configs import KernelShape, aug_rows

MIB = 1024 * 1024

# Per-variant temporary footprint, in accumulator-tile units (see module
# docstring for the calibration provenance). "weighted" is the in-kernel
# encode body; "weighted_precomp" the deferred-check body with the
# precomputed expectations operand; "rowcol_mxu"/"global_mxu" the
# augmented-operand MXU-encode bodies (ops/ft_sgemm "Encode modes").
#
# "global" is UNCALIBRATED — no global-strategy compile has landed in a
# hardware window's records yet, so 6.0 is an interpolation with the
# usual safety margin, MEASURED-BOUNDED on both sides by the same
# window's records: its body is strictly lighter than weighted's
# (observed 9.9 — one scalar residual vs three (bn,) moment streams) and
# strictly heavier than plain's (observed < 3.9 — it adds the panel-sum
# reduction and the residual compare), so the true factor lies in
# (3.9, 9.9) and 6.0 sits mid-interval; its declared scratch really is
# ~0 VMEM bytes (two SMEM scalars + a counter, modeled below as SMEM).
# Recalibrate against Mosaic's own number when a global compile lands in
# a window. The MXU-encode variants are likewise uncalibrated:
# "rowcol_mxu" takes rowcol's 7.0 + 1 for the augmented dot result slices
# (temps already scale with a_rows * b_rows below); "global_mxu" global's
# 6.0 + 1 for the corner-block slice.
TEMP_TILE_FACTORS = {
    "plain": 3.0,
    "global": 6.0,   # uncalibrated: bounded (3.9, 9.9) by the round-4
                     # window's plain/weighted observations (above)
    "global_mxu": 7.0,   # uncalibrated: global + augmented-dot slicing
    "rowcol": 7.0,
    "rowcol_mxu": 8.0,   # uncalibrated: rowcol + augmented-dot slicing
    "fused": 9.0,
    "weighted_precomp": 9.0,
    "weighted": 11.0,
}

# SMEM scalar scratch per variant (bytes): counters and scalar residual
# state. A different memory class than scoped VMEM, but Mosaic accounts
# them against the kernel too — modeled so the "every declared scratch is
# counted" claim holds for the scalar-only global variants as well
# (ADVICE.md round 5).
_SMEM_SCRATCH_BYTES = {
    "plain": 0,
    "global": 12,       # t_exp + prev (f32) + count (i32)
    "global_mxu": 12,
    "rowcol": 8,        # count + unc (i32)
    "rowcol_mxu": 8,
    "fused": 8,
    "weighted_precomp": 4,
    "weighted": 8,
}


def fused_aug_rows(in_itemsize: int) -> int:
    """Sublane-aligned augmented-row count for one operand's checksum rows
    (kept as an alias of :func:`ft_sgemm_tpu.configs.aug_rows`, the
    canonical home since the encode-mode axis made it family-wide)."""
    return aug_rows(in_itemsize)


def estimate_vmem_bytes(shape: KernelShape, variant: str, *,
                        in_itemsize: int = 4, multifault: bool = True,
                        adaptive: bool = False, exact: bool = False,
                        pipeline_depth: int = 2) -> int:
    """Predicted scoped-VMEM bytes for one kernel variant at ``shape``.

    ``variant`` is a :data:`TEMP_TILE_FACTORS` key. ``in_itemsize`` is the
    A/B input width (4 f32, 2 bf16, 1 int8/fp8); the accumulator/output is
    f32 except on the int8-exact path. ``adaptive`` adds the
    ``threshold="adaptive"`` moment scratch (one (4,) f32 SMEM vector —
    16 bytes, modeled so the "every declared scratch is counted" claim
    holds); ``exact`` adds the int8 path's separate (bm, bn) int32
    accumulator block — the one low-precision term that actually moves
    the estimate.

    ``pipeline_depth`` (``configs.PIPELINE_DEPTHS``) prices the searched
    pipeline axis: depth 2 is Mosaic's automatic double buffer — two
    (rows, bk) panels resident per input stream, the historical "2x
    block bytes" assumption. Depth d > 2 widens each buffered window to
    ``d - 1`` K panels (the realization ops/ft_sgemm unrolls in-body),
    and Mosaic still double-buffers the wider window, so ``2 * (d - 1)``
    panels are resident per stream — the model prices exactly that real
    footprint, not the nominal depth. Output/C windows are K-invariant
    and unaffected.

    The detect/correct CADENCE axis is priced through ``variant``, not a
    parameter here: an intermediate cadence on the weighted strategy
    needs the running in-kernel partial-sum encode body (``"weighted"``,
    factor 11) where the deferred single final check runs the lighter
    precomputed-expectations body (``"weighted_precomp"``, factor 9) —
    ``tuner.space.variant_for(check_every=...)`` resolves a cadence to
    the body that will actually run, exactly as ``make_ft_sgemm`` does.
    """
    if variant not in TEMP_TILE_FACTORS:
        raise ValueError(
            f"unknown kernel variant {variant!r}; pick from"
            f" {tuple(TEMP_TILE_FACTORS)}")
    from ft_sgemm_tpu.configs import PIPELINE_DEPTHS

    if pipeline_depth not in PIPELINE_DEPTHS:
        raise ValueError(
            f"unknown pipeline_depth {pipeline_depth!r}; pick from"
            f" {PIPELINE_DEPTHS}")
    bm, bn, bk = shape.block
    aug = aug_rows(in_itemsize)
    aug_a = aug if variant in ("fused", "rowcol_mxu", "global_mxu") else 0
    aug_b = aug if variant in ("rowcol_mxu", "global_mxu") else 0
    a_rows, b_rows, _ = shape.aug_block(aug_a, aug_b)

    panels = 2 * (pipeline_depth - 1)           # resident K panels/stream
    buffers = panels * a_rows * bk * in_itemsize     # A window
    buffers += panels * b_rows * bk * in_itemsize    # B window
    buffers += 2 * bm * bn * 4                  # C operand window
    buffers += 2 * bm * bn * 4                  # output window
    if variant == "weighted_precomp":
        buffers += 2 * 8 * bn * 4               # expected-checksum window

    scratch = _SMEM_SCRATCH_BYTES[variant]
    if adaptive and not exact:
        scratch += 16                           # (4,) f32 moment scalars
    if exact:
        scratch += bm * bn * 4                  # int32 accumulator block
    if variant == "rowcol":
        scratch += (bm + (2 if multifault else 1) * bn) * 4
    elif variant == "rowcol_mxu":
        scratch += (bm * aug_b + aug_a * bn) * 4   # r_exp + c_exp
    elif variant == "weighted":
        scratch += 3 * bn * 4
    elif variant == "fused":
        scratch += aug_a * bn * 4

    temps = int(TEMP_TILE_FACTORS[variant] * a_rows * b_rows * 4)
    return buffers + scratch + temps


def _variant_for(strategy: str | None) -> str:
    """Fitting variant for a wrapper-level strategy.

    Callers that know which body will run pass the exact variant
    (``make_ft_sgemm`` resolves ``weighted`` vs ``weighted_precomp`` from
    the effective cadence; the tuner does the same). ``rowcol`` is fitted
    with ``multifault=True`` scratch — a superset covering both modes.
    ``None`` is the plain (non-FT) kernel.
    """
    return strategy if strategy is not None else "plain"


def fit_block_to_vmem(shape: KernelShape, strategy: str | None, *,
                      limit: int, in_itemsize: int = 4,
                      allow_shrink: bool, adaptive: bool = False,
                      exact: bool = False,
                      pipeline_depth: int = 2) -> KernelShape:
    """Guard one kernel launch against a Mosaic scoped-VMEM OOM.

    Estimates the footprint at ``shape``; if it exceeds ``limit`` either
    shrinks the tile until it fits (``allow_shrink=True`` — named shapes)
    or warns and returns the tile unchanged (explicit shapes: tile sweeps
    must measure what their row label claims; the warning tells the
    operator the compile will likely fail). Shrink order: halve ``bk``
    while ``bk`` alone can absorb the overage (cheapest — K-depth only
    changes pipeline efficiency); when it cannot (the temps term
    ``factor * a_rows * bn * 4`` is bk-independent and dominates for the
    heavy variants — draining bk to 128 would cost all K-depth while
    barely moving the estimate), halve whichever of ``bn``/``bm``/``bk``
    yields the largest predicted reduction per step, all floored at 128.
    Every shrink is announced with one loud warning; an unfittable tile
    (over budget at 128^3) raises instead of dying inside Mosaic.
    """
    variant = _variant_for(strategy)

    def est_for(s):
        return estimate_vmem_bytes(s, variant, in_itemsize=in_itemsize,
                                   adaptive=adaptive, exact=exact,
                                   pipeline_depth=pipeline_depth)

    est = est_for(shape)
    if est <= limit:
        return shape
    if not allow_shrink:
        warnings.warn(
            f"ft_sgemm_tpu: kernel {variant!r} at tile {shape.block} is"
            f" predicted to need ~{est / MIB:.1f} MiB of scoped VMEM,"
            f" over the {limit / MIB:.0f} MiB limit — Mosaic compilation"
            f" will likely fail. (Explicit KernelShape: not auto-shrunk;"
            f" use a named shape for auto-fit, or raise"
            f" FT_SGEMM_VMEM_LIMIT_BYTES if the device allows.)",
            stacklevel=3)
        return shape
    def halve(v):
        # Largest multiple of 128 at or below v/2 (384 -> 128, not the
        # illegal 192), floored at the minimum legal tile dim.
        return max(128, (v // 2) // 128 * 128)

    bm, bn, bk = shape.block

    def est_at(bm_, bn_, bk_):
        return est_for(dataclasses.replace(shape, bm=bm_, bn=bn_, bk=bk_))

    while True:
        est = est_at(bm, bn, bk)
        if est <= limit:
            break
        steps = {}  # dim -> estimate after halving it once
        if bk > 128:
            steps["bk"] = est_at(bm, bn, halve(bk))
        if bn > 128:
            steps["bn"] = est_at(bm, halve(bn), bk)
        if bm > 128:
            steps["bm"] = est_at(halve(bm), bn, bk)
        if not steps:
            raise ValueError(
                f"ft_sgemm_tpu: kernel {variant!r} cannot fit the"
                f" {limit / MIB:.0f} MiB scoped-VMEM limit even at the"
                f" minimum 128x128x128 tile (predicted"
                f" ~{est / MIB:.1f} MiB); raise FT_SGEMM_VMEM_LIMIT_BYTES"
                f" or use a device with more VMEM")
        if "bk" in steps and est_at(bm, bn, 128) <= limit:
            # Draining bk alone can absorb the whole overage: keep the
            # cheap dimension first (K-depth only costs pipeline
            # efficiency; bn/bm halving also halves MXU-tile amortization).
            dim = "bk"
        else:
            # The bk-independent temps term dominates: take the dimension
            # with the largest predicted reduction per step (ties break
            # bk > bn > bm via insertion order — cheapest first).
            dim = min(steps, key=steps.get)
        if dim == "bk":
            bk = halve(bk)
        elif dim == "bn":
            bn = halve(bn)
        else:
            bm = halve(bm)
    fitted = dataclasses.replace(shape, bm=bm, bn=bn, bk=bk)
    warnings.warn(
        f"ft_sgemm_tpu: tile {shape.block} for kernel {variant!r} predicted"
        f" at ~{est_for(shape) / MIB:.1f}"
        f" MiB of scoped VMEM, over the {limit / MIB:.0f} MiB limit —"
        f" auto-shrunk to {fitted.block} (~{est / MIB:.1f} MiB) instead of"
        f" failing Mosaic compilation. Perf characteristics change; tune"
        f" tiles for this device or raise FT_SGEMM_VMEM_LIMIT_BYTES.",
        stacklevel=3)
    return fitted
