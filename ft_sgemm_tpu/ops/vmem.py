"""Static per-kernel VMEM-footprint estimation and trace-time tile fitting.

Mosaic compiles each Pallas kernel against a scoped-VMEM budget
(``vmem_limit_bytes``); exceeding it is a COMPILE-time error that CPU
interpret mode can never see. Round 4 lost its one hardware window to
exactly that: every FT strategy except ``rowcol`` died 0.3-2 MiB past the
16 MiB default at the tuned 4096 tiles
(``.bench/records_b855854_4096.jsonl``: weighted-precomp 16.27 MiB,
weighted in-kernel 17.93 MiB, fused 16.38 MiB, bf16 weighted-precomp
17.75 MiB). This module makes that failure class impossible to hit blind:
every kernel wrapper estimates its footprint BEFORE ``pallas_call`` and
either auto-shrinks the tile (named shapes) or warns loudly (explicit
shapes, e.g. tuner candidates — a sweep must measure the tile its row
label claims, so it gets the prediction but keeps the tile).

The model is ``pipeline buffers + scratch + temporaries``:

  - **Pipeline buffers**: each grid-blocked operand/output window is
    multi-buffered by Mosaic; 2x its block bytes.
  - **Scratch**: the wrapper's declared VMEM scratch shapes, exact.
  - **Temporaries**: the kernel body's live vector values (dot results,
    accumulator copies, residual/mask tiles). Not statically derivable
    from Python, so modeled as ``factor x (a_rows * bn * 4)`` — one
    accumulator-tile unit — with per-variant factors CALIBRATED against
    the recorded Mosaic numbers above plus the configs that are known to
    have compiled at 16 MiB (plain f32/bf16, rowcol f32). Factors sit a
    safety margin above the observed temp footprint, so estimates are
    conservative: a predicted fit may still (rarely) OOM for an exotic
    tile, but every recorded real OOM is predicted.

Calibration table (observed total - modeled buffers = observed temps, in
accumulator-tile units of ``bm*bn*4``):

  variant            observed temps   factor used
  weighted-precomp   8.2 (f32) / 4.1 (bf16)   9
  weighted           9.9                     11
  fused              8.2                      9
  rowcol             < 7.9 (compiled @16 MiB) 7
  plain              < 3.9 (bf16 deep-K @16)  3
  global             (no observation)         6
"""

from __future__ import annotations

import dataclasses
import warnings

from ft_sgemm_tpu.configs import KernelShape

MIB = 1024 * 1024

# Per-variant temporary footprint, in accumulator-tile units (see module
# docstring for the calibration provenance). "weighted" is the in-kernel
# encode body; "weighted_precomp" the deferred-check body with the
# precomputed expectations operand. "global" is UNCALIBRATED — no
# global-strategy compile has landed in a hardware window's records yet,
# so 6.0 is an interpolation (between plain and rowcol, matching its body
# weight) with the usual safety margin, and its declared scratch really is
# ~0 bytes (two SMEM scalars + a counter — no VMEM vectors). Recalibrate
# against Mosaic's own number when a global compile lands in a window.
TEMP_TILE_FACTORS = {
    "plain": 3.0,
    "global": 6.0,  # uncalibrated: no recorded Mosaic observation (above)
    "rowcol": 7.0,
    "fused": 9.0,
    "weighted_precomp": 9.0,
    "weighted": 11.0,
}


def fused_aug_rows(in_itemsize: int) -> int:
    """Sublane-aligned augmented-row count of the fused strategy (3 moment
    rows for f32; 9 hi/lo/lo2 term rows for bf16 — ``_augment_a``)."""
    return 8 if in_itemsize == 4 else 16


def estimate_vmem_bytes(shape: KernelShape, variant: str, *,
                        in_itemsize: int = 4, multifault: bool = True) -> int:
    """Predicted scoped-VMEM bytes for one kernel variant at ``shape``.

    ``variant`` is a :data:`TEMP_TILE_FACTORS` key. ``in_itemsize`` is the
    A/B input width (4 f32, 2 bf16); the accumulator/output is always f32.
    """
    if variant not in TEMP_TILE_FACTORS:
        raise ValueError(
            f"unknown kernel variant {variant!r}; pick from"
            f" {tuple(TEMP_TILE_FACTORS)}")
    bm, bn, bk = shape.block
    aug = fused_aug_rows(in_itemsize) if variant == "fused" else 0
    a_rows = bm + aug

    buffers = 2 * a_rows * bk * in_itemsize     # A window
    buffers += 2 * bn * bk * in_itemsize        # B window
    buffers += 2 * bm * bn * 4                  # C operand window
    buffers += 2 * bm * bn * 4                  # output window
    if variant == "weighted_precomp":
        buffers += 2 * 8 * bn * 4               # expected-checksum window

    scratch = 0
    if variant == "rowcol":
        scratch = (bm + (2 if multifault else 1) * bn) * 4
    elif variant == "weighted":
        scratch = 3 * bn * 4
    elif variant == "fused":
        scratch = aug * bn * 4

    temps = int(TEMP_TILE_FACTORS[variant] * a_rows * bn * 4)
    return buffers + scratch + temps


def _variant_for(strategy: str | None) -> str:
    """Fitting variant for a wrapper-level strategy.

    Callers that know which body will run pass the exact variant
    (``make_ft_sgemm`` resolves ``weighted`` vs ``weighted_precomp`` from
    the effective cadence; the tuner does the same). ``rowcol`` is fitted
    with ``multifault=True`` scratch — a superset covering both modes.
    ``None`` is the plain (non-FT) kernel.
    """
    return strategy if strategy is not None else "plain"


def fit_block_to_vmem(shape: KernelShape, strategy: str | None, *,
                      limit: int, in_itemsize: int = 4,
                      allow_shrink: bool) -> KernelShape:
    """Guard one kernel launch against a Mosaic scoped-VMEM OOM.

    Estimates the footprint at ``shape``; if it exceeds ``limit`` either
    shrinks the tile until it fits (``allow_shrink=True`` — named shapes)
    or warns and returns the tile unchanged (explicit shapes: tile sweeps
    must measure what their row label claims; the warning tells the
    operator the compile will likely fail). Shrink order: halve ``bk``
    while ``bk`` alone can absorb the overage (cheapest — K-depth only
    changes pipeline efficiency); when it cannot (the temps term
    ``factor * a_rows * bn * 4`` is bk-independent and dominates for the
    heavy variants — draining bk to 128 would cost all K-depth while
    barely moving the estimate), halve whichever of ``bn``/``bm``/``bk``
    yields the largest predicted reduction per step, all floored at 128.
    Every shrink is announced with one loud warning; an unfittable tile
    (over budget at 128^3) raises instead of dying inside Mosaic.
    """
    variant = _variant_for(strategy)
    est = estimate_vmem_bytes(shape, variant, in_itemsize=in_itemsize)
    if est <= limit:
        return shape
    if not allow_shrink:
        warnings.warn(
            f"ft_sgemm_tpu: kernel {variant!r} at tile {shape.block} is"
            f" predicted to need ~{est / MIB:.1f} MiB of scoped VMEM,"
            f" over the {limit / MIB:.0f} MiB limit — Mosaic compilation"
            f" will likely fail. (Explicit KernelShape: not auto-shrunk;"
            f" use a named shape for auto-fit, or raise"
            f" FT_SGEMM_VMEM_LIMIT_BYTES if the device allows.)",
            stacklevel=3)
        return shape
    def halve(v):
        # Largest multiple of 128 at or below v/2 (384 -> 128, not the
        # illegal 192), floored at the minimum legal tile dim.
        return max(128, (v // 2) // 128 * 128)

    bm, bn, bk = shape.block

    def est_at(bm_, bn_, bk_):
        return estimate_vmem_bytes(
            dataclasses.replace(shape, bm=bm_, bn=bn_, bk=bk_), variant,
            in_itemsize=in_itemsize)

    while True:
        est = est_at(bm, bn, bk)
        if est <= limit:
            break
        steps = {}  # dim -> estimate after halving it once
        if bk > 128:
            steps["bk"] = est_at(bm, bn, halve(bk))
        if bn > 128:
            steps["bn"] = est_at(bm, halve(bn), bk)
        if bm > 128:
            steps["bm"] = est_at(halve(bm), bn, bk)
        if not steps:
            raise ValueError(
                f"ft_sgemm_tpu: kernel {variant!r} cannot fit the"
                f" {limit / MIB:.0f} MiB scoped-VMEM limit even at the"
                f" minimum 128x128x128 tile (predicted"
                f" ~{est / MIB:.1f} MiB); raise FT_SGEMM_VMEM_LIMIT_BYTES"
                f" or use a device with more VMEM")
        if "bk" in steps and est_at(bm, bn, 128) <= limit:
            # Draining bk alone can absorb the whole overage: keep the
            # cheap dimension first (K-depth only costs pipeline
            # efficiency; bn/bm halving also halves MXU-tile amortization).
            dim = "bk"
        else:
            # The bk-independent temps term dominates: take the dimension
            # with the largest predicted reduction per step (ties break
            # bk > bn > bm via insertion order — cheapest first).
            dim = min(steps, key=steps.get)
        if dim == "bk":
            bk = halve(bk)
        elif dim == "bn":
            bn = halve(bn)
        else:
            bm = halve(bm)
    fitted = dataclasses.replace(shape, bm=bm, bn=bn, bk=bk)
    warnings.warn(
        f"ft_sgemm_tpu: tile {shape.block} for kernel {variant!r} predicted"
        f" at ~{estimate_vmem_bytes(shape, variant, in_itemsize=in_itemsize) / MIB:.1f}"
        f" MiB of scoped VMEM, over the {limit / MIB:.0f} MiB limit —"
        f" auto-shrunk to {fitted.block} (~{est / MIB:.1f} MiB) instead of"
        f" failing Mosaic compilation. Perf characteristics change; tune"
        f" tiles for this device or raise FT_SGEMM_VMEM_LIMIT_BYTES.",
        stacklevel=3)
    return fitted
