"""Plain (non-FT) Pallas SGEMM kernel family.

TPU-native re-design of the reference's 6 generated CUDA kernels
(``kernel/ft_sgemm/include_code_gen/sgemm_{small..huge}.cuh``). The
reference's machinery — 2-level block/warp/thread tiling, float4 global
loads, double-buffered shared memory, an unrolled per-thread ``mr x nr``
outer product (SURVEY.md §2.2) — is all hand-built CUDA pipelining. On TPU
every piece of it maps onto existing hardware/compiler structure:

  block tile          -> Pallas grid step + BlockSpec (bm, bn, bk)
  smem double buffer  -> Mosaic's automatic multi-buffered VMEM pipelining
  warp/thread tiling  -> the 128x128 MXU systolic array
  float4 vector loads -> VMEM lane layout (8x128 f32 tiles)

so the kernel body is just: accumulate ``A_blk @ B_blk.T`` into a VMEM f32
scratch across the K grid dimension, and apply the alpha/beta epilogue on
the last K step. Semantics match the reference's verification target:
``C = alpha * A @ B.T + beta * C`` with A (M, K), B (N, K)
(``sgemm.cu:108``: ``cublasSgemm(OP_N, OP_T)``).

Beyond reference parity, the family carries an ``in_dtype`` axis the CUDA
reference has no analog for: with ``in_dtype="bfloat16"`` the A/B tiles are
fed to the MXU in its native bf16 input format (accumulation stays f32) —
the systolic array's full-rate path. A bf16 x bf16 product is exact in f32
(8-bit mantissas => 16-bit product), so the only accuracy loss vs SGEMM is
the one-time input rounding; accumulation error is identical to the f32
path.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ft_sgemm_tpu.configs import (
    SHAPES,
    KernelShape,
    shape_for_dtype,
    vmem_limit_bytes,
)
from ft_sgemm_tpu.ops.common import (
    CompilerParams as _CompilerParams,
    dtype_suffix as _dtype_suffix,
    gemm_cost_estimate as _gemm_cost_estimate,
    pad_to as _pad_to,
    resolve_in_dtype as _resolve_in_dtype,
    should_interpret as _should_interpret,
    shrink_block as _shrink_block,
)
from ft_sgemm_tpu.ops.vmem import fit_block_to_vmem as _fit_block_to_vmem


def _matmul_kernel(a_ref, b_ref, c_ref, out_ref, *, alpha, beta, nk, prec):
    """One (i, j, k) grid step: acc += A_blk @ B_blk.T; epilogue at k==nk-1.

    The accumulator IS the f32 output block: Mosaic keeps the (i, j) output
    window resident in VMEM across the whole K sweep (the block index does
    not depend on k) and writes it back to HBM once, so accumulating in
    place is free — and saves a bm*bn*4-byte scratch buffer, VMEM that
    instead buys larger tiles (the bf16 flagship's limiting resource).
    """
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _zero():
        out_ref[:] = jnp.zeros_like(out_ref)

    out_ref[:] += jax.lax.dot_general(
        a_ref[:],
        b_ref[:],
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=prec,
    )

    @pl.when(k == nk - 1)
    def _epilogue():
        out_ref[:] = alpha * out_ref[:] + beta * c_ref[:]


@functools.partial(
    jax.jit,
    static_argnames=("shape", "alpha", "beta", "precision", "interpret"),
)
def _sgemm_padded(a, b, c, *, shape: KernelShape, alpha, beta, precision, interpret):
    m, k = a.shape
    n, _ = b.shape
    bm, bn, bk = shape.block
    nk = k // bk
    grid = (m // bm, n // bn, nk)
    prec = jax.lax.Precision(precision)

    return pl.pallas_call(
        functools.partial(
            _matmul_kernel, alpha=alpha, beta=beta, nk=nk, prec=prec
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((bn, bk), lambda i, j, kk: (j, kk)),
            pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), jnp.float32),
        # The C operand aliases the output: the beta*C epilogue reads each
        # C tile in the same grid step that retires its output tile, so
        # under jit XLA reuses the buffer instead of allocating and
        # copying a second (M, N) HBM array (pinned in tests).
        input_output_aliases={2: 0},
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
            vmem_limit_bytes=vmem_limit_bytes(),
        ),
        cost_estimate=_gemm_cost_estimate(m, n, k, a.dtype.itemsize),
        interpret=interpret,
    )(a, b, c)


def make_sgemm(
    shape: KernelShape | str,
    *,
    alpha: float = 1.0,
    beta: float = -1.5,
    precision: str = "highest",
    in_dtype: str = "float32",
    interpret: Optional[bool] = None,
    tunable: Optional[bool] = None,
):
    """Build the plain SGEMM for one named shape.

    Returns ``fn(a, b, c) -> C`` with ``C = alpha*A@B.T + beta*C``; inputs of
    any (M, K)/(N, K)/(M, N) shapes — zero-padded up to the block tile, which
    leaves results exact (padded rows/cols are sliced off).

    ``in_dtype="bfloat16"`` feeds A/B to the MXU in bf16 (full-rate path);
    C and the accumulator stay f32. ``precision`` only applies to f32 inputs
    (XLA splits f32 operands into bf16 passes per the precision level; bf16
    operands are already single-pass).

    ``tunable`` (default: named shapes only) lets a persisted autotuner
    winner (``ft_sgemm_tpu.tuner``) override the heuristic tile; a cache
    miss or disabled tuning leaves dispatch — and the emitted HLO —
    untouched (same contract as :func:`make_ft_sgemm`).
    """
    in_dtype, precision = _resolve_in_dtype(in_dtype, precision)
    named = isinstance(shape, str)
    tunable = named if tunable is None else bool(tunable)
    if named:
        # Named shapes pick up the dtype-tuned tile; explicit KernelShape
        # objects are always respected as-is — including no auto-shrinking,
        # so a tile sweep (scripts/tune_tiles.py) measures exactly the tile
        # its row label claims.
        shape = shape_for_dtype(SHAPES[shape], False, in_dtype)

    def fn(a, b, c):
        a = jnp.asarray(a, in_dtype)
        b = jnp.asarray(b, in_dtype)
        c = jnp.asarray(c, jnp.float32)
        m, n = c.shape
        eff = _shrink_block(shape, m, n, a.shape[1]) if named else shape
        if tunable:
            # Cache-backed dispatch (see make_ft_sgemm): a persisted tuned
            # winner overrides the heuristic tile; a miss changes nothing.
            from ft_sgemm_tpu import tuner as _tuner

            tuned = _tuner.lookup_tile(
                m, n, a.shape[1], strategy=None, in_dtype=in_dtype,
                injection_enabled=False)
            if tuned is not None:
                eff = tuned
        # Trace-time scoped-VMEM guard (ops/vmem.py): auto-shrink named
        # shapes over the Mosaic budget; warn for explicit ones.
        eff = _fit_block_to_vmem(
            eff, None, limit=vmem_limit_bytes(),
            in_itemsize=jnp.dtype(in_dtype).itemsize, allow_shrink=named)
        ap = _pad_to(a, eff.bm, eff.bk)
        bp = _pad_to(b, eff.bn, eff.bk)
        cp = _pad_to(c, eff.bm, eff.bn)
        out = _sgemm_padded(
            ap, bp, cp,
            shape=eff, alpha=alpha, beta=beta,
            precision=precision, interpret=_should_interpret(interpret),
        )
        return out[:m, :n]

    fn.__name__ = f"sgemm_{shape.name}" + _dtype_suffix(in_dtype)
    fn.shape_config = shape
    fn.in_dtype = in_dtype
    return fn


def sgemm(a, b, c, shape: KernelShape | str = "huge", *, alpha=1.0, beta=-1.5,
          precision="highest", in_dtype="float32", interpret=None):
    """One-shot plain SGEMM (see :func:`make_sgemm`)."""
    return make_sgemm(
        shape, alpha=alpha, beta=beta, precision=precision, in_dtype=in_dtype,
        interpret=interpret
    )(a, b, c)
