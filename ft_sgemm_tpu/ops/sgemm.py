"""Plain (non-FT) Pallas SGEMM kernel family.

TPU-native re-design of the reference's 6 generated CUDA kernels
(``kernel/ft_sgemm/include_code_gen/sgemm_{small..huge}.cuh``). The
reference's machinery — 2-level block/warp/thread tiling, float4 global
loads, double-buffered shared memory, an unrolled per-thread ``mr x nr``
outer product (SURVEY.md §2.2) — is all hand-built CUDA pipelining. On TPU
every piece of it maps onto existing hardware/compiler structure:

  block tile          -> Pallas grid step + BlockSpec (bm, bn, bk)
  smem double buffer  -> Mosaic's automatic multi-buffered VMEM pipelining
  warp/thread tiling  -> the 128x128 MXU systolic array
  float4 vector loads -> VMEM lane layout (8x128 f32 tiles)

so the kernel body is just: accumulate ``A_blk @ B_blk.T`` into a VMEM f32
scratch across the K grid dimension, and apply the alpha/beta epilogue on
the last K step. Semantics match the reference's verification target:
``C = alpha * A @ B.T + beta * C`` with A (M, K), B (N, K)
(``sgemm.cu:108``: ``cublasSgemm(OP_N, OP_T)``).

Beyond reference parity, the family carries an ``in_dtype`` axis the CUDA
reference has no analog for: with ``in_dtype="bfloat16"`` the A/B tiles are
fed to the MXU in its native bf16 input format (accumulation stays f32) —
the systolic array's full-rate path. A bf16 x bf16 product is exact in f32
(8-bit mantissas => 16-bit product), so the only accuracy loss vs SGEMM is
the one-time input rounding; accumulation error is identical to the f32
path.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ft_sgemm_tpu.configs import (
    DEFAULT_VARIANT,
    SHAPES,
    EpilogueSpec,
    KernelShape,
    KernelVariant,
    canonical_variant,
    shape_for_dtype,
    vmem_limit_bytes,
)
from ft_sgemm_tpu.ops.common import (
    CompilerParams as _CompilerParams,
    apply_epilogue as _apply_epilogue,
    attach_bias as _attach_bias,
    dtype_suffix as _dtype_suffix,
    epilogue_bias_row as _epilogue_bias_row,
    gemm_cost_estimate as _gemm_cost_estimate,
    grid_and_maps as _grid_and_maps,
    pad_bias as _pad_bias,
    pad_to as _pad_to,
    resolve_in_dtype as _resolve_in_dtype,
    should_interpret as _should_interpret,
    shrink_block as _shrink_block,
    sub_panels as _sub_panels,
)
from ft_sgemm_tpu.ops.vmem import fit_block_to_vmem as _fit_block_to_vmem


def _matmul_kernel(a_ref, b_ref, c_ref, out_ref, *, alpha, beta, nk, prec,
                   unroll=1, epi=None, bias_ref=None):
    """One (i, j, k) grid step: acc += A_blk @ B_blk.T; epilogue at k==nk-1.

    The accumulator IS the f32 output block: Mosaic keeps the (i, j) output
    window resident in VMEM across the whole K sweep (the block index does
    not depend on k) and writes it back to HBM once, so accumulating in
    place is free — and saves a bm*bn*4-byte scratch buffer, VMEM that
    instead buys larger tiles (the bf16 flagship's limiting resource).

    ``unroll`` > 1 is the deep-pipeline realization (``configs.
    PIPELINE_DEPTHS``): the K window holds ``unroll`` panels and the body
    runs one dot per sub-panel. ``epi``/``bias_ref`` fuse the optional
    bias/activation/quantize epilogue into the final write-back.
    """
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _zero():
        out_ref[:] = jnp.zeros_like(out_ref)

    for a_sub, b_sub in _sub_panels(a_ref[:], b_ref[:], unroll):
        out_ref[:] += jax.lax.dot_general(
            a_sub,
            b_sub,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=prec,
        )

    @pl.when(k == nk - 1)
    def _epilogue():
        out_ref[:] = _apply_epilogue(
            alpha * out_ref[:] + beta * c_ref[:], epi,
            _epilogue_bias_row(bias_ref))


@functools.partial(
    jax.jit,
    static_argnames=("shape", "alpha", "beta", "precision", "interpret",
                     "variant"),
)
def _sgemm_padded(a, b, c, *, shape: KernelShape, alpha, beta, precision,
                  interpret, variant: KernelVariant = DEFAULT_VARIANT,
                  bias=None):
    m, k = a.shape
    n, _ = b.shape
    bm, bn, bk = shape.block
    unroll = variant.pipeline_depth - 1
    kw = bk * unroll            # the buffered K window (unroll panels)
    nk = k // kw
    prec = jax.lax.Precision(precision)
    epi = variant.epilogue_spec
    epi = None if epi.is_identity else epi
    grid, a_map, b_map, c_map, row_map = _grid_and_maps(
        variant.grid_order, m // bm, n // bn, nk)

    kernel = functools.partial(
        _matmul_kernel, alpha=alpha, beta=beta, nk=nk, prec=prec,
        unroll=unroll, epi=epi,
    )
    in_specs = [
        pl.BlockSpec((bm, kw), a_map),
        pl.BlockSpec((bn, kw), b_map),
        pl.BlockSpec((bm, bn), c_map),
    ]
    operands = [a, b, c]
    if epi is not None and epi.bias:
        in_specs.append(pl.BlockSpec((8, bn), row_map))
        operands.append(bias)
        kernel = _attach_bias(kernel, n_in=4)

    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((bm, bn), c_map),
        out_shape=jax.ShapeDtypeStruct((m, n), jnp.float32),
        # The C operand aliases the output: the beta*C epilogue reads each
        # C tile in the same grid step that retires its output tile, so
        # under jit XLA reuses the buffer instead of allocating and
        # copying a second (M, N) HBM array (pinned in tests).
        input_output_aliases={2: 0},
        compiler_params=_CompilerParams(
            dimension_semantics=(variant.dim_semantics,
                                 variant.dim_semantics, "arbitrary"),
            vmem_limit_bytes=vmem_limit_bytes(),
        ),
        cost_estimate=_gemm_cost_estimate(m, n, k, a.dtype.itemsize),
        interpret=interpret,
    )(*operands)


def make_sgemm(
    shape: KernelShape | str,
    *,
    alpha: float = 1.0,
    beta: float = -1.5,
    precision: str = "highest",
    in_dtype: str = "float32",
    interpret: Optional[bool] = None,
    tunable: Optional[bool] = None,
    variant: Optional[KernelVariant] = None,
    epilogue=None,
):
    """Build the plain SGEMM for one named shape.

    Returns ``fn(a, b, c, bias=None) -> C`` with
    ``C = epilogue(alpha*A@B.T + beta*C)``; inputs of any
    (M, K)/(N, K)/(M, N) shapes — zero-padded up to the block tile, which
    leaves results exact (padded rows/cols are sliced off).

    ``in_dtype="bfloat16"`` feeds A/B to the MXU in bf16 (full-rate path);
    C and the accumulator stay f32. ``precision`` only applies to f32 inputs
    (XLA splits f32 operands into bf16 passes per the precision level; bf16
    operands are already single-pass).

    ``variant`` pins the kernel-variant axes (:class:`~ft_sgemm_tpu
    .configs.KernelVariant`: pipeline depth, grid traversal order,
    dimension semantics, fused epilogue — the cadence axis is FT-only);
    ``None`` (the default) dispatches the historical behavior,
    byte-identical HLO, and lets a tuned winner's variant apply.
    ``epilogue`` (an :class:`~ft_sgemm_tpu.configs.EpilogueSpec` or a
    spelling like ``"bias+relu"``) fuses bias/activation/quantize into the
    final write-back; a fused bias is passed per call
    (``fn(a, b, c, bias=v)``, v of length N).

    ``tunable`` (default: named shapes only) lets a persisted autotuner
    winner (``ft_sgemm_tpu.tuner``) override the heuristic tile AND (when
    the caller left ``variant=None``) the variant axes; a cache miss or
    disabled tuning leaves dispatch — and the emitted HLO — untouched
    (same contract as :func:`make_ft_sgemm`).
    """
    in_dtype, precision = _resolve_in_dtype(in_dtype, precision)
    pinned = variant is not None
    var = canonical_variant(variant)
    if epilogue is not None:
        import dataclasses as _dc

        var = _dc.replace(var,
                          epilogue=EpilogueSpec.parse(epilogue).spelling)
    named = isinstance(shape, str)
    tunable = named if tunable is None else bool(tunable)
    if named:
        # Named shapes pick up the dtype-tuned tile; explicit KernelShape
        # objects are always respected as-is — including no auto-shrinking,
        # so a tile sweep (scripts/tune_tiles.py) measures exactly the tile
        # its row label claims.
        shape = shape_for_dtype(SHAPES[shape], False, in_dtype)

    def fn(a, b, c, bias=None):
        a = jnp.asarray(a, in_dtype)
        b = jnp.asarray(b, in_dtype)
        c = jnp.asarray(c, jnp.float32)
        m, n = c.shape
        eff = _shrink_block(shape, m, n, a.shape[1]) if named else shape
        eff_var = var
        if tunable:
            # Cache-backed dispatch (see make_ft_sgemm): a persisted tuned
            # winner overrides the heuristic tile (and, for un-pinned
            # callers, the variant axes); a miss changes nothing.
            from ft_sgemm_tpu import tuner as _tuner

            tuned, tuned_var = _tuner.lookup_winner(
                m, n, a.shape[1], strategy=None, in_dtype=in_dtype,
                injection_enabled=False,
                variant=var if pinned else None,
                epilogue=var.epilogue)
            if tuned is not None:
                eff = tuned
            if tuned_var is not None and not pinned:
                eff_var = tuned_var
        # Trace-time scoped-VMEM guard (ops/vmem.py): auto-shrink named
        # shapes over the Mosaic budget; warn for explicit ones.
        eff = _fit_block_to_vmem(
            eff, None, limit=vmem_limit_bytes(),
            in_itemsize=jnp.dtype(in_dtype).itemsize, allow_shrink=named,
            pipeline_depth=eff_var.pipeline_depth)
        kw = eff.bk * (eff_var.pipeline_depth - 1)
        ap = _pad_to(a, eff.bm, kw)
        bp = _pad_to(b, eff.bn, kw)
        cp = _pad_to(c, eff.bm, eff.bn)
        bias_op = None
        if eff_var.epilogue_spec.bias:
            if bias is None:
                raise ValueError(
                    f"{fn.__name__}: epilogue {eff_var.epilogue!r} fuses"
                    " a bias — pass fn(a, b, c, bias=v) with v of"
                    f" length N={n}")
            bias_op = _pad_bias(bias, n, eff.bn)
        elif bias is not None:
            raise ValueError(
                f"{fn.__name__}: bias given but epilogue"
                f" {eff_var.epilogue!r} does not fuse one")
        out = _sgemm_padded(
            ap, bp, cp,
            shape=eff, alpha=alpha, beta=beta,
            precision=precision, interpret=_should_interpret(interpret),
            variant=eff_var, bias=bias_op,
        )
        return out[:m, :n]

    fn.__name__ = f"sgemm_{shape.name}" + _dtype_suffix(in_dtype)
    fn.shape_config = shape
    fn.in_dtype = in_dtype
    fn.variant = var
    return fn


def sgemm(a, b, c, shape: KernelShape | str = "huge", *, alpha=1.0, beta=-1.5,
          precision="highest", in_dtype="float32", interpret=None):
    """One-shot plain SGEMM (see :func:`make_sgemm`)."""
    return make_sgemm(
        shape, alpha=alpha, beta=beta, precision=precision, in_dtype=in_dtype,
        interpret=interpret
    )(a, b, c)
