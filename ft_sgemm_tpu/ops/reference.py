"""Pure-XLA reference GEMM (the "vendor library" oracle).

The reference verifies every kernel against ``cublasSgemm(OP_N, OP_T)``
(``sgemm.cu:108,222``), i.e. ``C = alpha * A @ B.T + beta * C`` with A of
shape (M, K) and B of shape (N, K). Here the oracle is XLA's native dot —
the correctness reference for every Pallas kernel and the perf target for
the bench (kernel id 0, perf-table row "xla_dot"; reference row "cublas",
``sgemm.cu:235-237``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ft_sgemm_tpu.ops.common import resolve_in_dtype


@functools.partial(jax.jit, static_argnames=("precision", "in_dtype"))
def _sgemm_reference_jit(a, b, c, alpha, beta, *, precision, in_dtype):
    dt = jnp.dtype(in_dtype)
    if dt == jnp.int8:
        # int8 oracle: exact int32 accumulation (what the FT kernels'
        # exact path computes), widened to f32 only for the epilogue.
        out = jnp.dot(
            a.astype(dt), b.astype(dt).T,
            preferred_element_type=jnp.int32,
        ).astype(jnp.float32)
    else:
        out = jnp.dot(
            a.astype(dt),
            b.astype(dt).T,
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision(precision),
        )
    return alpha * out + beta * c.astype(jnp.float32)


def sgemm_reference(a, b, c, alpha=1.0, beta=-1.5, *, precision="highest",
                    in_dtype="float32"):
    """``C = alpha * A @ B.T + beta * C`` via XLA's native dot.

    Args:
      a: (M, K) f32. b: (N, K) f32 — B is stored row-per-output-column,
        matching the reference's OP_T operand layout. c: (M, N) f32.
      precision: lax matmul precision; "highest" keeps true-f32 MXU passes
        so the oracle matches f32 CUDA semantics.
      in_dtype: "bfloat16" rounds A/B to bf16 before the dot (accumulation
        stays f32) — the oracle for the kernels' bf16 input mode;
        "float8_e4m3fn" likewise rounds to fp8 with f32 accumulation;
        "int8" truncates to int8 (pass integer-valued data) and
        accumulates exactly in int32 — the oracle for the FT kernels'
        low-precision variants.
    """
    dt, precision = resolve_in_dtype(in_dtype, precision,
                                     allow_low_precision=True)
    return _sgemm_reference_jit(a, b, c, alpha, beta, precision=precision,
                                in_dtype=dt.name)


def epilogue_reference(x, epilogue, bias=None):
    """Host-numpy twin of the in-kernel fused epilogue
    (:func:`ft_sgemm_tpu.ops.common.apply_epilogue`): bias ->
    activation -> quantize on an already-computed f32 output.

    ``epilogue`` is an :class:`~ft_sgemm_tpu.configs.EpilogueSpec` or a
    spelling string; ``bias`` a length-N (or (1, N)) vector when the spec
    fuses one. The serving verifier and the oracle tests compose this
    with :func:`sgemm_reference` / :func:`cpu_gemm` to check
    epilogue-fused kernels end to end.
    """
    import numpy as np

    from ft_sgemm_tpu.configs import EpilogueSpec

    epi = EpilogueSpec.parse(epilogue)
    x = np.asarray(x, np.float32)
    if epi.is_identity:
        return x
    if epi.bias:
        if bias is None:
            raise ValueError(
                "epilogue_reference: spec fuses a bias but none given")
        x = x + np.asarray(bias, np.float32).reshape(1, -1)
    if epi.activation == "relu":
        x = np.maximum(x, 0.0)
    elif epi.activation == "gelu":
        x = 0.5 * x * (1.0 + np.tanh(
            0.7978845608028654 * (x + 0.044715 * x * x * x)))
    if epi.quantize == "int8":
        # np.round rounds half-to-even, matching jnp.round in-kernel.
        x = np.clip(np.round(x * epi.scale), -128.0, 127.0)
    elif epi.quantize == "float8_e4m3fn":
        import ml_dtypes

        x = (x * epi.scale).astype(ml_dtypes.float8_e4m3fn)
        x = x.astype(np.float32)
    return x.astype(np.float32)


def cpu_gemm(alpha, beta, a, b, c):
    """Naive O(n^3)-semantics reference on host numpy (reference
    ``utils.cu:79-89``, row-major ``C = alpha*A@B + beta*C``). Kept as the
    second, XLA-independent oracle for checksum-math tests."""
    import numpy as np

    a = np.asarray(a, dtype=np.float64)
    b = np.asarray(b, dtype=np.float64)
    c = np.asarray(c, dtype=np.float64)
    return (alpha * (a @ b) + beta * c).astype(np.float32)
