"""Shared helpers for the Pallas kernel wrappers."""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def should_interpret(interpret: Optional[bool]) -> bool:
    """Pallas interpret mode: explicit wins; otherwise interpret unless a
    real TPU backend is active (tests/CI run on CPU, SURVEY.md §4)."""
    if interpret is not None:
        return interpret
    return jax.default_backend() != "tpu"


def pad_to(x: jax.Array, row_mult: int, col_mult: int) -> jax.Array:
    """Zero-pad a 2-D array up to multiples of (row_mult, col_mult).

    Zero padding is exact for GEMM and for checksum math: padded rows/cols
    contribute nothing to products or sums and are sliced off by callers.
    """
    r, c = x.shape
    pr = (-r) % row_mult
    pc = (-c) % col_mult
    if pr or pc:
        x = jnp.pad(x, ((0, pr), (0, pc)))
    return x
