"""Shared helpers for the Pallas kernel wrappers."""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.pallas import tpu as pltpu

# jax renamed ``pltpu.TPUCompilerParams`` to ``pltpu.CompilerParams``;
# resolve whichever this jax ships so the kernels build on both sides of
# the rename (single source for every pallas_call in the package).
CompilerParams = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams")


def _register_barrier_batching() -> None:
    """Fill in the ``optimization_barrier`` vmap rule older jax lacks.

    The softmax dual-recompute check (ops/attention.py) barriers its
    duplicate reduction chain; newer jax ships the (trivial — the barrier
    is operand-wise identity, so batch dims pass straight through)
    batching rule, older jax raises NotImplementedError under vmap.
    Registering only when absent means current jax is untouched.
    """
    try:
        from jax._src.lax import lax as _lax_src
        from jax.interpreters import batching

        prim = getattr(_lax_src, "optimization_barrier_p", None)
        if prim is None or prim in batching.primitive_batchers:
            return

        def _rule(args, dims, **params):
            outs = prim.bind(*args, **params)
            if not isinstance(outs, (list, tuple)):
                outs = [outs]
            return outs, list(dims)

        batching.primitive_batchers[prim] = _rule
    except Exception:  # noqa: BLE001 — unpatchable jax: vmap raises as before
        pass


_register_barrier_batching()


def _register_float0_reduce_jvp() -> None:
    """Make ``reduce_sum``'s JVP tolerate instantiated float0 tangents.

    The FT results carry integer fault counters; under ``jax.grad``
    their tracers hold float0 ("void") tangents, which this jax's
    custom_vjp machinery INSTANTIATES as real arrays when the call sits
    inside a ``lax.scan`` body (flax ``nn.scan`` stacks — the
    ``FtTransformer`` composition). ``jnp.sum`` over such a counter then
    binds ``reduce_sum`` on the float0 tangent and raises "does not
    accept dtype void". The wrapper answers a float0 tangent with a
    symbolic Zero (the mathematically correct tangent of an integer
    reduction) and defers every other case to the original rule, so
    current-jax behavior is untouched.
    """
    try:
        from jax._src import ad_util, core, dtypes
        from jax._src.lax import lax as _lax_src
        from jax.interpreters import ad

        prim = getattr(_lax_src, "reduce_sum_p", None)
        orig = ad.primitive_jvps.get(prim)
        if prim is None or orig is None:
            return

        def rule(primals, tangents, **params):
            t = tangents[0]
            if getattr(core.get_aval(t), "dtype", None) == dtypes.float0:
                out = prim.bind(primals[0], **params)
                return out, ad_util.Zero(
                    core.get_aval(out).at_least_vspace())
            return orig(primals, tangents, **params)

        ad.primitive_jvps[prim] = rule
    except Exception:  # noqa: BLE001 — unpatchable jax: grads raise as before
        pass


_register_float0_reduce_jvp()

# Calibrated constants of the clean-residual noise model — single source
# for the numpy estimator (analysis.estimate_noise_floor, where the
# calibration story is documented) and the traced one below.
NOISE_C_RAND = 32.0
NOISE_C_BIAS = 4.0
# Default safety margin between the noise-floor bound and an adaptive
# detection threshold (threshold="auto"); single source for the factory
# default and the detection study's sweep filter.
DEFAULT_THRESHOLD_MARGIN = 8.0


def estimate_noise_floor_jnp(a, b, c, alpha: float, beta: float):
    """Traced clean checksum-residual bound (see
    ``analysis.estimate_noise_floor`` for the model and calibration).

    jnp throughout, so it composes under ``jit`` — this is what
    ``make_ft_sgemm(threshold="auto")`` evaluates per call (input moments
    are O(n^2) reductions, fused by XLA, negligible next to the GEMM).
    Shapes/log/sqrt factors are static; only the moments are traced.
    """
    (m, k), n = a.shape, b.shape[0]
    tmax = float(max(m, n))
    eps = float(np.finfo(np.float32).eps)

    def rms(x):
        # Scale-invariant: normalize by max|x| before squaring so inputs
        # near f32's range cannot overflow the moment to inf (an inf
        # bound would silently disable auto-threshold detection).
        xf = x.astype(jnp.float32)
        scale = jnp.maximum(jnp.max(jnp.abs(xf)), 1e-30)
        return scale * jnp.sqrt(jnp.mean(jnp.square(xf / scale)))

    def term(t, sigma, mu):
        return eps * (NOISE_C_RAND * float(np.sqrt(t)) * sigma
                      + NOISE_C_BIAS * float(np.log2(max(t, 2.0))) * t
                      * jnp.abs(mu))

    t_ab = float(k) * tmax
    noise = abs(alpha) * term(
        t_ab, rms(a) * rms(b),
        jnp.mean(a.astype(jnp.float32)) * jnp.mean(b.astype(jnp.float32)))
    if c is not None and beta != 0.0:
        cf = c.astype(jnp.float32)
        noise += abs(beta) * term(tmax, rms(cf), jnp.mean(cf))
    elif beta != 0.0:
        # Mirror the numpy twin's contract exactly (see
        # analysis.estimate_noise_floor): a silent undershoot here would
        # put auto thresholds below the real floor when |C| dominates.
        raise ValueError(
            "estimate_noise_floor_jnp: pass c (or beta=0) — the beta*C"
            " term contributes residual noise the bound must include")
    # Never return inf: an inf bound would make an auto threshold that
    # silently disables detection. rms() is scale-safe, but the PRODUCT of
    # two near-f32-max rms values can still overflow; such inputs overflow
    # the GEMM itself, so a saturated (finite, enormous) bound is the
    # honest answer.
    return jnp.minimum(noise, jnp.float32(np.finfo(np.float32).max) / 16.0)


def variance_bound_threshold(s_a1, s_a2, s_b1, s_b2, *, n_a, n_b, t_ab,
                             log2_t, margin, c_rand=NOISE_C_RAND,
                             c_bias=NOISE_C_BIAS, eps=None, xp=np):
    """Per-tile variance-bound detection threshold from running moments
    (the V-ABFT capability, arXiv 2602.08043; ``threshold="adaptive"``).

    ``s_a1``/``s_a2`` are the running sum and sum-of-squares of every A
    element this tile's checksum-encode pass has consumed so far (``n_a``
    elements), ``s_b1``/``s_b2``/``n_b`` the B-side twins; all four are
    nearly free VPU reductions of blocks already resident in VMEM. The
    bound is the calibrated clean-residual noise model of
    ``analysis.estimate_noise_floor`` evaluated on THIS tile's moments:

        sigma = rms(a) * rms(b)        (sqrt of the mean-square product)
        mu    = mean(a) * mean(b)
        noise = eps * (c_rand * sqrt(t_ab) * sigma
                       + c_bias * log2_t * t_ab * |mu|)

    with ``t_ab`` the residual's accumulation length (``K_so_far *
    max(bm, bn)``) and ``log2_t`` its log factor — callers pass the
    STATIC full-run ``log2`` (monotone in t, so early checks get a
    slightly conservative bias term and no in-kernel transcendental).
    Returns ``margin * noise`` saturated far below f32 max (downstream
    re-check moments scale it by up to ``bm^2``; an inf threshold would
    silently disable the very check it parameterizes).

    ``xp`` picks the array module: jnp inside the kernels (traced SMEM
    scalars), np for the host twin (``analysis`` must stay jax-free —
    the bench-supervisor constraint), so the two evaluations share one
    formula and can never drift.
    """
    eps = float(np.finfo(np.float32).eps) if eps is None else eps
    mu_ab = (s_a1 / n_a) * (s_b1 / n_b)
    sigma = xp.sqrt((s_a2 / n_a) * (s_b2 / n_b))
    noise = eps * (c_rand * xp.sqrt(t_ab) * sigma
                   + c_bias * log2_t * t_ab * xp.abs(mu_ab))
    cap = float(np.finfo(np.float32).max) / 16.0
    return xp.minimum(margin * noise, cap)


def should_interpret(interpret: Optional[bool]) -> bool:
    """Pallas interpret mode: explicit wins; otherwise interpret unless a
    real TPU backend is active (tests/CI run on CPU, SURVEY.md §4)."""
    if interpret is not None:
        return interpret
    return jax.default_backend() != "tpu"


def resolve_in_dtype(in_dtype, precision: str, *, allow_low_precision=False):
    """Validate an ``in_dtype`` and resolve the dot precision to use with it.

    Returns ``(dtype, precision)``. bf16 operands force ``"default"``
    precision: Mosaic rejects fp32 contract precision on bf16 vectors ("Bad
    lhs type"), and bf16 inputs are single-pass on the MXU anyway; the
    1-byte dtypes are likewise single-pass and take ``"default"``.

    ``allow_low_precision`` opens the fp8_e4m3 / int8 serving dtypes —
    passed by the FT factories, whose kernels carry the dtype-legal
    widened accumulation (f32 / int32) those inputs need. The plain
    kernels accept fp8 (the f32-accumulating dot consumes it directly)
    but not int8.
    """
    from ft_sgemm_tpu.configs import canonical_in_dtype

    dt = jnp.dtype(canonical_in_dtype(in_dtype))
    low = dt not in (jnp.float32, jnp.bfloat16)
    if low and not allow_low_precision and dt == jnp.int8:
        raise ValueError(
            f"in_dtype {dt.name!r} needs the FT kernels' int32-exact"
            " accumulation path (make_ft_sgemm); the plain kernels take"
            " float32/bfloat16/float8_e4m3fn")
    return dt, (precision if dt == jnp.float32 else "default")


def dtype_suffix(in_dtype) -> str:
    """Kernel-name suffix for a non-default input dtype ('' for f32)."""
    dt = jnp.dtype(in_dtype)
    return "" if dt == jnp.float32 else f"_{dt.name}"


def gemm_cost_breakdown(m: int, n: int, k: int, in_itemsize: int, *,
                        block=None, strategy=None, multifault: bool = False,
                        check_every=None) -> dict:
    """Component-wise FLOPs / bytes of one ``C = alpha*A@B.T + beta*C``
    pass: the plain GEMM (``base``) plus, for FT kernels, the
    checksum-``encode`` work and the detect/correct ``check`` epilogue.

    Returns ``{"flops_base", "flops_encode", "flops_check", "bytes_base",
    "bytes_encode", "bytes_check"}`` — the decomposition the perf
    subsystem's roofline rows report as the ABFT-overhead fraction
    (:mod:`ft_sgemm_tpu.perf.roofline`); :func:`gemm_cost_estimate` sums
    it into the ``pl.CostEstimate`` Mosaic's scheduler sees, so the two
    views can never drift apart.

    - **Checksum-encode flops.** VPU encode (``rowcol``/``global``/
      ``weighted``) re-reduces each operand block once per grid step, so
      its cost scales as ``m*n*k*(c_a/bn + c_b/bm)`` with per-strategy
      stream counts; MXU encode (``fused``/``*_mxu``) instead widens the
      dot by the sublane-aligned augmented rows (``configs.aug_rows``):
      ``2*k*(aug_a*n + aug_b*m)`` extra MXU flops plus the one-time
      wrapper reduction over the augmented operand(s).
    - **Detect/correct epilogue.** Each check reduces the (bm, bn)
      accumulator per residual stream and applies the masked correction:
      ``streams * m * n`` flops per check, ``ceil(nk/check_every)``
      checks.
    - **Epilogue bytes.** The augmented operand copies are real HBM
      traffic (``aug * k`` rows per tile row/column), as are the
      per-tile detection/uncorrectable counter outputs and the precomp
      path's expected-checksum operand.

    ``strategy`` takes the KERNEL-level value (``resolve_kernel_strategy``
    — ``weighted`` with ``check_every >= nk`` is costed as the precomp
    body). Plain callers (``strategy=None``) get zero encode/check terms.
    """
    flops_base = 2 * m * n * k
    bytes_base = in_itemsize * (m * k + n * k) + 4 * 2 * m * n
    flops_encode = flops_check = bytes_encode = bytes_check = 0
    if strategy is not None:
        from ft_sgemm_tpu.configs import aug_rows

        bm, bn, bk = block
        nk = max(1, -(-k // bk))
        ce = nk if check_every is None else max(1, min(check_every, nk))
        n_checks = -(-nk // ce)
        precomp = strategy == "weighted" and ce >= nk
        aug = aug_rows(in_itemsize)
        # Encode flops + augmented-operand bytes per encode style.
        if strategy in ("fused", "rowcol_mxu", "global_mxu"):
            aug_a = aug
            aug_b = aug if strategy in ("rowcol_mxu", "global_mxu") else 0
            # Widened dot rows ride the MXU; the wrapper's one-time moment
            # reduction costs ~2 flops per operand element per moment row.
            flops_encode += 2 * k * (aug_a * n + aug_b * m)
            flops_encode += 2 * (aug_a * m * k // max(bm, 1)
                                 + aug_b * n * k // max(bn, 1))
            bytes_encode += in_itemsize * k * (
                aug_a * (m // bm) + aug_b * (n // bn))
        elif precomp:
            # Expected checksums via one stacked XLA dot OUTSIDE the
            # kernel; in-kernel extra cost is only the (8, bn) expected-
            # checksum operand window per tile.
            bytes_encode += 4 * 8 * (m // bm) * n
        else:
            # VPU encode streams per grid step: s_a/s_b reductions plus
            # one elementwise multiply-reduce per expected-checksum
            # stream ("weighted" carries 3 column streams, multifault
            # rowcol 2 + 1 row stream, plain rowcol 1 + 1, global 1 + 1).
            streams_a = {"rowcol": 2 if multifault else 1,
                         "global": 1, "weighted": 3}[strategy]
            streams_b = 1
            flops_encode += 3 * k * (streams_a * n + streams_b * m)
        # Detect/correct epilogue: per check, ~2 flops per accumulator
        # element per residual stream (reduce + masked correct/re-check).
        streams = {"rowcol": 3 if multifault else 2, "rowcol_mxu": 3,
                   "global": 1, "global_mxu": 1,
                   "weighted": 3, "fused": 3}.get(strategy, 2)
        flops_check += 2 * streams * m * n * n_checks
        # det/unc counter outputs.
        bytes_check += 2 * 4 * (m // bm) * (n // bn)
    return {"flops_base": int(flops_base),
            "flops_encode": int(flops_encode),
            "flops_check": int(flops_check),
            "bytes_base": int(bytes_base),
            "bytes_encode": int(bytes_encode),
            "bytes_check": int(bytes_check)}


def gemm_cost_estimate(m: int, n: int, k: int, in_itemsize: int, *,
                       block=None, strategy=None, multifault: bool = False,
                       check_every=None):
    """FLOPs / bytes for one ``C = alpha*A@B.T + beta*C`` pass: A and B at
    their input width, C read+written in f32 — the summed view of
    :func:`gemm_cost_breakdown` as the ``pl.CostEstimate`` every
    ``pallas_call`` in the package hands Mosaic's scheduler. Plain
    callers keep the original 4-argument form and the original numbers.
    """
    import jax.experimental.pallas as pl

    parts = gemm_cost_breakdown(
        m, n, k, in_itemsize, block=block, strategy=strategy,
        multifault=multifault, check_every=check_every)
    return pl.CostEstimate(
        flops=(parts["flops_base"] + parts["flops_encode"]
               + parts["flops_check"]),
        bytes_accessed=(parts["bytes_base"] + parts["bytes_encode"]
                        + parts["bytes_check"]),
        transcendentals=0,
    )


def shrink_block(shape, m: int, n: int, k: int):
    """Halve oversized block dims for small problems.

    Big tuned tiles (e.g. the bf16 flagship's bk=2048) would force heavy
    zero-padding on smaller inputs — padded FLOPs are real FLOPs. Halve each
    block dim while (a) the padding waste on its axis is at least one tile
    granule (128 rows/cols, 256 K-depth) and (b) the halved value stays a
    legal multiple of 128. Leaves well-fitting shapes untouched, so tuned
    behavior at the target sizes is unchanged.
    """
    import dataclasses

    bm, bn, bk = shape.bm, shape.bn, shape.bk
    while bm > 128 and (-m) % bm >= 128 and (bm // 2) % 128 == 0:
        bm //= 2
    while bn > 128 and (-n) % bn >= 128 and (bn // 2) % 128 == 0:
        bn //= 2
    while bk > 256 and (-k) % bk >= 256 and (bk // 2) % 128 == 0:
        bk //= 2
    if (bm, bn, bk) == shape.block:
        return shape
    return dataclasses.replace(shape, bm=bm, bn=bn, bk=bk)


def pad_to(x: jax.Array, row_mult: int, col_mult: int) -> jax.Array:
    """Zero-pad a 2-D array up to multiples of (row_mult, col_mult).

    Zero padding is exact for GEMM and for checksum math: padded rows/cols
    contribute nothing to products or sums and are sliced off by callers.
    """
    r, c = x.shape
    pr = (-r) % row_mult
    pc = (-c) % col_mult
    if pr or pc:
        x = jnp.pad(x, ((0, pr), (0, pc)))
    return x


def grid_and_maps(grid_order: str, gm: int, gn: int, nk: int):
    """The Pallas grid tuple + BlockSpec index maps for one traversal
    order (``configs.GRID_ORDERS``).

    Returns ``(grid, a_map, b_map, c_map, row_map)`` where ``c_map`` also
    serves the output/expected-checksum windows and ``row_map`` the
    ``(8, bn)`` row operands (fused bias, precomputed expectations' pad
    rows use ``c_map``). ``"mn"`` is the historical M-major walk —
    byte-identical lowering; ``"nm"`` permutes the two PARALLEL dims
    only (K stays innermost: the whole family accumulates in the
    resident output block, so K-major traversal is illegal by design —
    the "where legal" clause of the grid-order axis).
    """
    if grid_order == "nm":
        return ((gn, gm, nk),
                lambda j, i, kk: (i, kk),
                lambda j, i, kk: (j, kk),
                lambda j, i, kk: (i, j),
                lambda j, i, kk: (0, j))
    return ((gm, gn, nk),
            lambda i, j, kk: (i, kk),
            lambda i, j, kk: (j, kk),
            lambda i, j, kk: (i, j),
            lambda i, j, kk: (0, j))


def grid_ij(swap_ij: bool):
    """The (output-row-tile, output-col-tile) program ids under one grid
    order — kernel bodies index their SMEM counter cells and the inject
    ordinal with these, so the traversal permutation never changes WHERE
    a tile's counters land."""
    from jax.experimental import pallas as pl

    if swap_ij:
        return pl.program_id(1), pl.program_id(0)
    return pl.program_id(0), pl.program_id(1)


def sub_panels(a_blk, b_blk, unroll: int):
    """Split one K window into ``unroll`` sub-panel operand pairs.

    ``pipeline_depth`` d > 2 widens each buffered window to ``d - 1`` K
    panels (configs.PIPELINE_DEPTHS); the kernel body then runs one MXU
    dot per sub-panel so the dot granularity — and the compute the
    pipeline can overlap against the wider prefetch — matches the
    declared panel size. ``unroll == 1`` returns the window untouched
    (the byte-identical default path)."""
    if unroll <= 1:
        return [(a_blk, b_blk)]
    sub = a_blk.shape[1] // unroll
    return [(a_blk[:, s * sub:(s + 1) * sub],
             b_blk[:, s * sub:(s + 1) * sub]) for s in range(unroll)]


def attach_bias(kernel, n_in: int):
    """Adapter routing the fused-bias operand to a keyword.

    Pallas passes refs positionally (inputs, outputs, scratch); the bias
    rides as the LAST input operand so the kernel bodies' positional
    signatures stay stable across epilogue configurations — this
    re-routes input ref ``n_in - 1`` to the ``bias_ref`` keyword every
    body accepts."""
    def wrapped(*refs):
        return kernel(*refs[:n_in - 1], *refs[n_in:],
                      bias_ref=refs[n_in - 1])
    return wrapped


def pad_bias(bias, n: int, bn: int):
    """The (8, N-padded) f32 fused-bias operand: row 0 carries the bias
    (rows 1-7 are sublane padding so the window blocks legally at
    (8, bn)); validated against the TRUE output width before padding."""
    b = jnp.asarray(bias, jnp.float32).reshape(-1)
    if b.shape[0] != n:
        raise ValueError(
            f"fused bias must have length N={n}, got {b.shape[0]}")
    return pad_to(b[None, :], 8, bn)


def epilogue_bias_row(bias_ref):
    """The (1, bn) bias slice of the padded (8, bn) bias window (row 0
    carries the bias; rows 1-7 are sublane padding), or None."""
    return None if bias_ref is None else bias_ref[0:1, :]


def apply_epilogue(x, epi, bias_row=None):
    """The fused epilogue, applied to one corrected output tile in-kernel.

    ``x`` is the post-detect/correct, post-``alpha/beta`` f32 tile;
    ``epi`` an :class:`~ft_sgemm_tpu.configs.EpilogueSpec` (or None);
    ``bias_row`` a ``(1, bn)``-broadcastable f32 bias slice (required
    when ``epi.bias``). ONE implementation for every kernel body — and,
    via the jnp/np module symmetry of its ops, for the host oracle twin
    (:func:`ft_sgemm_tpu.ops.reference.epilogue_reference`) — so the
    fused and reference epilogue numerics can never drift.

    Identity specs return ``x`` unchanged (the same traced value: default
    dispatch stays byte-identical HLO). Application order is
    bias -> activation -> quantize; quantized values stay in f32 storage
    on the exact target grid (round+clamp for int8, an fp8_e4m3 cast
    round-trip for fp8), so the caller's egress cast is value-exact.

    ABFT ordering contract (DESIGN.md §16): this runs strictly AFTER the
    detect/correct pass of the same grid step — checksums verify the
    pre-epilogue accumulator, and a nonlinear epilogue never launders a
    miscorrection past the residual re-check.
    """
    if epi is None or epi.is_identity:
        return x
    if epi.bias:
        if bias_row is None:
            raise ValueError(
                "apply_epilogue: epi.bias set but no bias_row operand")
        x = x + bias_row
    if epi.activation == "relu":
        x = jnp.maximum(x, 0.0)
    elif epi.activation == "gelu":
        # tanh-approximated GELU (the serving standard): VPU-friendly —
        # one transcendental per element, no erf lowering required.
        x = 0.5 * x * (1.0 + jnp.tanh(
            0.7978845608028654 * (x + 0.044715 * x * x * x)))
    if epi.quantize == "int8":
        x = jnp.clip(jnp.round(x * epi.scale), -128.0, 127.0)
    elif epi.quantize == "float8_e4m3fn":
        x = (x * epi.scale).astype(jnp.float8_e4m3fn).astype(jnp.float32)
    return x
