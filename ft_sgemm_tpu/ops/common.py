"""Shared helpers for the Pallas kernel wrappers."""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def should_interpret(interpret: Optional[bool]) -> bool:
    """Pallas interpret mode: explicit wins; otherwise interpret unless a
    real TPU backend is active (tests/CI run on CPU, SURVEY.md §4)."""
    if interpret is not None:
        return interpret
    return jax.default_backend() != "tpu"


def resolve_in_dtype(in_dtype, precision: str):
    """Validate an ``in_dtype`` and resolve the dot precision to use with it.

    Returns ``(dtype, precision)``. bf16 operands force ``"default"``
    precision: Mosaic rejects fp32 contract precision on bf16 vectors ("Bad
    lhs type"), and bf16 inputs are single-pass on the MXU anyway.
    """
    dt = jnp.dtype(in_dtype)
    if dt not in (jnp.float32, jnp.bfloat16):
        raise ValueError(f"in_dtype must be float32 or bfloat16, got {dt}")
    return dt, ("default" if dt == jnp.bfloat16 else precision)


def dtype_suffix(in_dtype) -> str:
    """Kernel-name suffix for a non-default input dtype ('' for f32)."""
    dt = jnp.dtype(in_dtype)
    return "" if dt == jnp.float32 else f"_{dt.name}"


def gemm_cost_estimate(m: int, n: int, k: int, in_itemsize: int):
    """FLOPs / bytes for one ``C = alpha*A@B.T + beta*C`` pass: A and B at
    their input width, C read+written in f32."""
    import jax.experimental.pallas as pl

    return pl.CostEstimate(
        flops=2 * m * n * k,
        bytes_accessed=in_itemsize * (m * k + n * k) + 4 * 2 * m * n,
        transcendentals=0,
    )


def shrink_block(shape, m: int, n: int, k: int):
    """Halve oversized block dims for small problems.

    Big tuned tiles (e.g. the bf16 flagship's bk=2048) would force heavy
    zero-padding on smaller inputs — padded FLOPs are real FLOPs. Halve each
    block dim while (a) the padding waste on its axis is at least one tile
    granule (128 rows/cols, 256 K-depth) and (b) the halved value stays a
    legal multiple of 128. Leaves well-fitting shapes untouched, so tuned
    behavior at the target sizes is unchanged.
    """
    import dataclasses

    bm, bn, bk = shape.bm, shape.bn, shape.bk
    while bm > 128 and (-m) % bm >= 128 and (bm // 2) % 128 == 0:
        bm //= 2
    while bn > 128 and (-n) % bn >= 128 and (bn // 2) % 128 == 0:
        bn //= 2
    while bk > 256 and (-k) % bk >= 256 and (bk // 2) % 128 == 0:
        bk //= 2
    if (bm, bn, bk) == shape.block:
        return shape
    return dataclasses.replace(shape, bm=bm, bn=bn, bk=bk)


def pad_to(x: jax.Array, row_mult: int, col_mult: int) -> jax.Array:
    """Zero-pad a 2-D array up to multiples of (row_mult, col_mult).

    Zero padding is exact for GEMM and for checksum math: padded rows/cols
    contribute nothing to products or sums and are sliced off by callers.
    """
    r, c = x.shape
    pr = (-r) % row_mult
    pc = (-c) % col_mult
    if pr or pc:
        x = jnp.pad(x, ((0, pr), (0, pc)))
    return x
