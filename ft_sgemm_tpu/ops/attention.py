"""Fault-tolerant attention: ABFT-protected ``softmax(Q K^T / sqrt(d)) V``.

A capability extension beyond the reference (which is a pure GEMM study —
SURVEY.md §5 notes it has no attention or sequence dimension), built the way
the retrieved ABFT-for-attention literature prescribes (PAPERS.md: "Custom
Algorithm-based Fault Tolerance for Attention Layers in Transformers"): the
two GEMMs inside attention are where the FLOPs and the silent-data-corruption
exposure are, and each is protected by the framework's fused-ABFT kernels —
faults in either accumulator are detected and corrected in-kernel, so they
never reach the softmax or the output.

The softmax stage itself is elementwise VPU work that linear checksums cannot
cover. Two detect-only checks guard it (a flagged row has no redundancy to
reconstruct from; re-run the step):

1. **Normalization invariant** — softmax is computed HERE in its decomposed
   form (``m = rowmax(S)``, ``e = exp(S - m)``, ``l = rowsum(e)``,
   ``P = e / l``), so every row of ``P`` sums to 1 only if the divide saw
   the same ``e`` and ``l`` the reductions produced:
   ``max_i |1 - sum_j P[i, j]|`` flags faults striking ``e`` after the
   denominator, the denominator itself, or ``P`` post-normalization. (A
   library ``jax.nn.softmax`` over corrupted logits would renormalize
   consistently and hide exactly these — the round-3 review's point.)
2. **Sampled dual recompute** — on a static row sample, ``rowsum(exp(s-m))``
   is recomputed from the logits behind ``lax.optimization_barrier`` (the
   barrier stops XLA from CSE-ing the duplicate into the primary chain —
   without it the "recompute" would be the same registers and the check
   vacuous) and compared to the saved denominator: flags exp-/max-/sum-stage
   faults that renormalization would launder, at sampled-row coverage
   (``softmax_recheck_rows``, default 16 rows; the GEMM checksums remain
   the deterministic full-coverage layer — this stage's redundancy is
   necessarily duplication, so coverage is bought row-by-row).

GEMM shape mapping (the framework's kernels compute ``A @ B^T``):

  S = Q K^T            ->  ft_sgemm(a=Q (L, d),  b=K (Lk, d))
  O = P V              ->  ft_sgemm(a=P (L, Lk), b=V^T (dv, Lk))

``scale`` is applied OUTSIDE the first kernel (not as its alpha): the ABFT
residual check then sees the unscaled ``Q K^T`` accumulator, so fault
magnitudes compare against the detection threshold undamped — a 1e4 fault
stays 1e4 at the check, rather than 1e4/sqrt(d).

Multi-head / batched use: ``jax.vmap`` over the leading axis.
"""

from __future__ import annotations

import math
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from ft_sgemm_tpu import telemetry
from ft_sgemm_tpu.configs import KernelShape
from ft_sgemm_tpu.injection import InjectionSpec, REFERENCE_THRESHOLD
from ft_sgemm_tpu.ops.ft_sgemm import make_ft_sgemm

# Attention-tuned tiles. QK^T contracts over the head dim (64-256): a
# shallow-K tile avoids padding the contraction several-fold. P@V contracts
# over the (long) key sequence with a narrow output (dv columns): K-deep,
# bn-minimal. Explicit KernelShape objects are used as-is (no auto-shrink);
# small problems pad up to these tiles — pass smaller shapes to tune. When
# these DEFAULTS are in play, the factories opt the inner GEMMs into the
# autotuner's cache-backed dispatch (ft_sgemm_tpu.tuner): a persisted
# winner for the QK/PV problem key overrides them, a cache miss changes
# nothing. A caller-supplied shape is always respected as-is.
QK_SHAPE = KernelShape("attn_qk", 256, 256, 128, (0,) * 7)
PV_SHAPE = KernelShape("attn_pv", 256, 128, 512, (0,) * 7)

# Clean-run |1 - rowsum(softmax)| is a few f32 ulps (observed < 1e-6 at
# Lk = 4096); 1e-3 sits ~3 orders above the noise floor and far below any
# fault that could meaningfully skew a probability row. The same relative
# tolerance guards the sampled denominator recompute (reduction-order
# noise there is also ulp-scale).
SOFTMAX_RESIDUAL_THRESHOLD = 1e-3
# Rows per call re-verified by the dual softmax recompute (static stride
# sample). 0 disables the recompute, leaving only the invariant check.
SOFTMAX_RECHECK_ROWS = 16


class FtAttentionResult(NamedTuple):
    """Output of a fault-tolerant attention call.

    ``detections`` counts corrected accumulator faults across both GEMMs;
    ``softmax_flags`` counts rows whose softmax normalization invariant
    (rowsum == 1) broke — detect-only, 0 on clean runs.
    ``uncorrectable`` aggregates the GEMMs' residual-after-correct
    re-checks (``FtSgemmResult.uncorrectable``): nonzero means a
    correction assumption broke inside a protected GEMM and the output may
    still carry the fault — reported, never silent.
    """

    out: jax.Array            # (L, dv)
    detections: jax.Array     # scalar int32 — corrected GEMM faults
    softmax_flags: jax.Array  # scalar int32 — flagged softmax rows
    uncorrectable: jax.Array  # scalar int32 — unverified GEMM intervals

    @property
    def num_detected(self):
        return self.detections


def softmax_rowsum_residual(p) -> jax.Array:
    """Max |1 - rowsum(p)|: the softmax normalization invariant residual."""
    return jnp.max(jnp.abs(1.0 - jnp.sum(p, axis=-1)))


def _check_causal_lengths(lq: int, lk: int) -> None:
    """Causal masking needs ``lq <= lk`` (end-aligned positions): leading
    query rows would otherwise attend to zero keys and their softmax is
    undefined. Shared by the single-device and ring paths."""
    if lq > lk:
        raise ValueError(
            f"causal attention needs L_q ({lq}) <= L_k ({lk}): leading"
            " queries would attend to zero keys")


def causal_mask_bias(lq: int, lk: int) -> jax.Array:
    """(lq, lk) additive bias: 0 where query may attend, -inf above the
    causal diagonal. Positions align at the sequence END (the decoding
    convention): query row i sits at key position ``i + (lk - lq)``."""
    _check_causal_lengths(lq, lk)
    qpos = jnp.arange(lq)[:, None] + (lk - lq)
    kpos = jnp.arange(lk)[None, :]
    return jnp.where(kpos <= qpos, 0.0, -jnp.inf).astype(jnp.float32)


def _checked_softmax(logits, softmax_threshold, recheck_rows,
                     softmax_fault=None):
    """Decomposed softmax with its two detect-only checks (module
    docstring). Returns ``(p, flags)``.

    ``softmax_fault`` is the stage's self-test hook (the analog of the
    GEMMs' ``InjectionSpec``): ``(stage, row, col, magnitude)`` adds
    ``magnitude`` at one point of the stage — ``"exp"`` corrupts ``e``
    BEFORE the denominator (renormalization launders it; only the dual
    recompute can see it), ``"denom"`` corrupts ``l``, ``"post"``
    corrupts ``P`` after normalization (both break the invariant)."""
    m = jnp.max(logits, axis=-1, keepdims=True)
    e = jnp.exp(logits - m)
    if softmax_fault is not None and softmax_fault[0] == "exp":
        _, r, c, mag = softmax_fault
        e = e.at[r, c].add(mag)
    el = jnp.sum(e, axis=-1, keepdims=True)
    if softmax_fault is not None and softmax_fault[0] == "denom":
        _, r, _, mag = softmax_fault
        el = el.at[r, 0].add(mag)
    p = e / el
    if softmax_fault is not None and softmax_fault[0] == "post":
        _, r, c, mag = softmax_fault
        p = p.at[r, c].add(mag)
    flags = jnp.sum(
        (jnp.abs(1.0 - jnp.sum(p, axis=-1)) > softmax_threshold)
        .astype(jnp.int32))
    if recheck_rows > 0:
        lq = logits.shape[0]
        stride = max(1, lq // min(recheck_rows, lq))
        # The barrier makes the duplicate chain formally distinct inputs:
        # XLA cannot CSE it into the primary max/exp/sum nodes, so this
        # is a genuine second computation of the sampled denominators.
        sl = jax.lax.optimization_barrier(logits[::stride])
        m2 = jnp.max(sl, axis=-1, keepdims=True)
        l2 = jnp.sum(jnp.exp(sl - m2), axis=-1, keepdims=True)
        rel = jnp.abs(el[::stride] - l2) / jnp.maximum(l2, 1e-30)
        flags = flags + jnp.sum((rel > softmax_threshold).astype(jnp.int32))
    return p, flags


def _ft_attention_forward(qk, pv, q, k, v, inject, scale, causal,
                          softmax_threshold,
                          recheck_rows=SOFTMAX_RECHECK_ROWS,
                          softmax_fault=None):
    """The ONE protected-attention forward, shared by the plain and
    differentiable factories: QK kernel -> scale -> (causal mask) ->
    checked softmax (decomposed; invariant + sampled dual recompute) ->
    PV kernel. Returns ``(FtAttentionResult, p, sc)`` — callers that
    don't need the counts or the probabilities just drop them (XLA prunes
    unused outputs)."""
    if causal:
        # Validate BEFORE launching any kernel work.
        _check_causal_lengths(q.shape[0], k.shape[0])
    sc = (1.0 / math.sqrt(q.shape[-1])) if scale is None else scale
    zs = jnp.zeros((q.shape[0], k.shape[0]), jnp.float32)
    s = qk(q, k, zs, inject)
    logits = sc * s.c
    if causal:
        logits = logits + causal_mask_bias(q.shape[0], k.shape[0])
    p, flags = _checked_softmax(logits, softmax_threshold, recheck_rows,
                                softmax_fault)
    zo = jnp.zeros((q.shape[0], v.shape[1]), jnp.float32)
    o = pv(p, jnp.swapaxes(v, 0, 1), zo, inject)
    det = (jnp.sum(s.detections) + jnp.sum(o.detections)).astype(jnp.int32)
    unc = jnp.sum(s.uncorrectable) + jnp.sum(o.uncorrectable)
    return FtAttentionResult(o.c, det, flags, unc), p, sc


def make_ft_attention(
    *,
    scale: Optional[float] = None,
    causal: bool = False,
    strategy: str = "weighted",
    encode: str = "vpu",
    threshold: float | str = REFERENCE_THRESHOLD,
    softmax_threshold: float = SOFTMAX_RESIDUAL_THRESHOLD,
    softmax_recheck_rows: int = SOFTMAX_RECHECK_ROWS,
    softmax_fault=None,
    qk_shape: KernelShape = QK_SHAPE,
    pv_shape: KernelShape = PV_SHAPE,
    in_dtype: str = "float32",
    interpret: Optional[bool] = None,
    layer: Optional[str] = None,
):
    """Build ``fn(q, k, v, inject=None) -> FtAttentionResult``.

    ``q`` (L, d), ``k`` (Lk, d), ``v`` (Lk, dv); any sizes (kernels pad).
    ``scale`` defaults to 1/sqrt(d). ``causal=True`` applies the decoder
    mask (end-aligned positions) AFTER the QK kernel's detect/correct, so
    faults landing at masked positions are still corrected in-kernel before
    the mask zeroes their influence. ``inject`` drives BOTH protected GEMMs
    (fault counts add). ``threshold="auto"`` calibrates each GEMM to its
    own operands per call (P's probability-scale entries get their own
    floor, far below Q/K's). Default strategy is ``weighted``: at its deferred
    single-check cadence the FT GEMM hot loop is identical to the plain
    kernel's (see ops/ft_sgemm.py), so protected attention costs ~one extra
    detect/correct pass per GEMM.

    ``softmax_recheck_rows`` sizes the softmax stage's sampled dual
    recompute (0 disables, leaving only the rowsum invariant);
    ``softmax_fault`` is that stage's self-test hook — see
    :func:`_checked_softmax`.

    ``encode`` selects the protected GEMMs' checksum-encode mode
    (``make_ft_sgemm``): ``"mxu"`` rides the expected checksums through
    the QK/PV dots as augmented operand rows instead of per-K-step VPU
    reductions; the default ``"vpu"`` leaves both kernels bit-for-bit
    unchanged.

    ``layer`` labels the recorded telemetry event (and its registry
    series) so stacked/composite callers — an nn block, a serving bucket
    — attribute faults to THEIR unit, the per-layer attribution the
    attention-ABFT literature (arXiv 2507.16676) calls for in
    transformer stacks.
    """
    qk = make_ft_sgemm(qk_shape, alpha=1.0, beta=0.0, strategy=strategy,
                       encode=encode, threshold=threshold,
                       in_dtype=in_dtype,
                       interpret=interpret, tunable=qk_shape is QK_SHAPE)
    pv = make_ft_sgemm(pv_shape, alpha=1.0, beta=0.0, strategy=strategy,
                       encode=encode, threshold=threshold,
                       in_dtype=in_dtype,
                       interpret=interpret, tunable=pv_shape is PV_SHAPE)

    def fn(q, k, v, inject: Optional[InjectionSpec] = None) -> FtAttentionResult:
        # suppress(): the inner QK/PV GEMMs must not record their own
        # events — this call is ONE logical op and records once.
        with telemetry.trace_span("ft_attention"), telemetry.suppress():
            res, _, _ = _ft_attention_forward(
                qk, pv, q, k, v, inject, scale, causal, softmax_threshold,
                softmax_recheck_rows, softmax_fault)
        if telemetry.enabled():
            telemetry.record_attention("ft_attention", res,
                                       strategy=strategy, encode=encode,
                                       layer=layer)
        return res

    fn.strategy = strategy
    fn.encode = encode
    fn.in_dtype = in_dtype
    fn.causal = causal
    return fn


def ft_attention(q, k, v, *, inject: Optional[InjectionSpec] = None,
                 **kwargs) -> FtAttentionResult:
    """One-shot fault-tolerant attention (see :func:`make_ft_attention`)."""
    return make_ft_attention(**kwargs)(q, k, v, inject)


def make_ft_attention_diff(
    *,
    scale: Optional[float] = None,
    causal: bool = False,
    strategy: str = "weighted",
    encode: str = "vpu",
    threshold: float | str = REFERENCE_THRESHOLD,
    bwd_threshold: Optional[float | str] = None,
    inject: Optional[InjectionSpec] = None,
    inject_bwd: Optional[InjectionSpec] = None,
    qk_shape: KernelShape = QK_SHAPE,
    pv_shape: KernelShape = PV_SHAPE,
    in_dtype: str = "float32",
    interpret: Optional[bool] = None,
    with_counts: bool = False,
    with_bwd_counts: bool = False,
    softmax_threshold: float = SOFTMAX_RESIDUAL_THRESHOLD,
    softmax_recheck_rows: int = SOFTMAX_RECHECK_ROWS,
    softmax_fault=None,
):
    """Differentiable FT attention: ABFT on all six GEMMs of fwd + bwd.

    Returns ``fn(q, k, v) -> (L, dv)`` as a ``jax.custom_vjp``. Forward
    runs the two protected GEMMs of :func:`make_ft_attention`; backward
    runs the four attention-gradient GEMMs through FT kernels too:

        dV = Pᵀ g      dP = g Vᵀ
        dS = P ⊙ (dP − rowsum(dP ⊙ P)) · scale     (softmax bwd, VPU)
        dQ = dS K      dK = dSᵀ Q

    ``with_counts=True`` makes the function return the full
    :class:`FtAttentionResult` pytree instead of the bare output array:
    gradients flow through ``.out`` while the int32 ``detections`` (both
    forward GEMMs) and ``softmax_flags`` (normalization-stage rowsum
    invariant, same as :func:`make_ft_attention`) leaves take zero
    cotangents — so a training loop can log fault activity every step.

    ``with_bwd_counts=True`` adds a trailing ``bwd_sink`` argument —
    ``fn(q, k, v, bwd_sink)``, any (2,) f32 array — whose GRADIENT is
    ``[detections, uncorrectable]`` summed over the four backward GEMMs:
    the gradient side-channel of ``ops.autodiff`` (its module docstring
    has the mechanism), surfacing the backward pass's fault report to
    the caller of ``jax.grad``. The four backward GEMMs are
    ABFT-corrected in-kernel either way (this factory requires a
    correcting strategy); the elementwise softmax forward/backward
    stages remain the only unprotected compute.

    ``bwd_threshold`` tightens the gradient GEMMs' detection threshold —
    cotangents usually live far below activation scale (see
    ops/autodiff.py). ``inject`` is static at build time and drives all
    six GEMMs; ``inject_bwd`` overrides the schedule for the four
    backward GEMMs alone (tests can corrupt exactly the backward pass).
    """
    if strategy == "global":
        raise ValueError(
            "make_ft_attention_diff requires a CORRECTING strategy: "
            "'global' only detects — a detect-only backward fault would "
            "be shipped into gradients/optimizer state (with_bwd_counts "
            "can report it but nothing corrects it). Pick 'rowcol' or "
            "'weighted', or use make_ft_attention for detect-only runs.")
    inj = inject or InjectionSpec.none()
    inj_b = inj if inject_bwd is None else inject_bwd
    bthr = threshold if bwd_threshold is None else bwd_threshold
    mk = lambda shp, thr: make_ft_sgemm(  # noqa: E731
        shp, alpha=1.0, beta=0.0, strategy=strategy, encode=encode,
        threshold=thr, in_dtype=in_dtype, interpret=interpret,
        tunable=shp is QK_SHAPE or shp is PV_SHAPE)
    qk = mk(qk_shape, threshold)
    pv = mk(pv_shape, threshold)
    # Long-contraction grads (dV, dQ, dK) share pv's profile; the
    # short-contraction dP shares qk's. Reuse the forward kernels when the
    # backward threshold is unchanged.
    b_long = pv if bthr == threshold else mk(pv_shape, bthr)
    b_short = qk if bthr == threshold else mk(qk_shape, bthr)

    def _fwd_parts(q, k, v):
        with telemetry.trace_span("ft_attention_diff"), telemetry.suppress():
            res, p, sc = _ft_attention_forward(
                qk, pv, q, k, v, inj, scale, causal, softmax_threshold,
                softmax_recheck_rows, softmax_fault)
        if telemetry.enabled():
            # Skips itself under a caller's jit/grad trace (tracers);
            # eager calls record the forward pass's materialized report.
            telemetry.record_attention("ft_attention_diff", res,
                                       strategy=strategy, encode=encode)
        return (res if with_counts else res.out), p, sc

    def _bwd_products(res, g):
        q, k, v, p, sc = res
        if with_counts:
            # Cotangent mirrors the FtAttentionResult pytree; the integer
            # counts leaves carry zero (float0) cotangents. Index
            # positionally: the container may arrive as a plain tuple.
            g = g[0]
        lq, lk = p.shape
        dv_z = jnp.zeros((lk, v.shape[1]), jnp.float32)
        dp_z = jnp.zeros((lq, lk), jnp.float32)
        dq_z = jnp.zeros((lq, q.shape[1]), jnp.float32)
        dk_z = jnp.zeros((lk, k.shape[1]), jnp.float32)
        pt = jnp.swapaxes(p, 0, 1)
        # dV = P^T g: contract over L_q -> kernel(a=P^T (Lk, L), b=g^T).
        rv = b_long(pt, jnp.swapaxes(g, 0, 1), dv_z, inj_b)
        # dP = g V^T: contract over dv -> kernel(a=g, b=V (Lk, dv)).
        rp = b_short(g, v, dp_z, inj_b)
        # Softmax backward (elementwise; masked entries have p == 0).
        ds = p * (rp.c - jnp.sum(rp.c * p, axis=-1, keepdims=True)) * sc
        # dQ = dS K: contract over L_k -> kernel(a=dS, b=K^T (d, Lk)).
        rq = b_long(ds, jnp.swapaxes(k, 0, 1), dq_z, inj_b)
        # dK = dS^T Q: contract over L_q.
        rk = b_long(jnp.swapaxes(ds, 0, 1), jnp.swapaxes(q, 0, 1),
                    dk_z, inj_b)
        grads = (rq.c.astype(q.dtype), rk.c.astype(k.dtype),
                 rv.c.astype(v.dtype))
        return grads, (rv, rp, rq, rk)

    from ft_sgemm_tpu.ops.autodiff import sink_vjp

    def primal(q, k, v):
        return _fwd_parts(q, k, v)[0]

    def fwd_fn(q, k, v):
        o, p, sc = _fwd_parts(q, k, v)
        return o, (q, k, v, p, sc)

    def bwd_core(res, g):
        grads, rs = _bwd_products(res, g)
        det = sum(jnp.sum(r.detections) for r in rs)
        unc = sum(jnp.sum(r.uncorrectable) for r in rs)
        return grads, det, unc

    return sink_vjp(primal, fwd_fn, bwd_core, with_bwd_counts)


def attention_reference(q, k, v, *, scale: Optional[float] = None,
                        causal: bool = False,
                        in_dtype: str = "float32") -> jax.Array:
    """Plain XLA attention oracle for differential tests.

    Inputs are rounded to ``in_dtype`` like the kernel path, but the
    intermediate ``P = softmax(S)`` stays f32 here while the bf16 kernel
    path rounds P once more feeding the PV GEMM — so bf16 comparisons
    carry ~1e-2 relative P-rounding noise on top of input rounding (tests
    use a correspondingly looser tolerance).
    """
    dt = jnp.dtype(in_dtype)
    q = jnp.asarray(q, dt).astype(jnp.float32)
    k = jnp.asarray(k, dt).astype(jnp.float32)
    v = jnp.asarray(v, dt).astype(jnp.float32)
    sc = (1.0 / math.sqrt(q.shape[-1])) if scale is None else scale
    logits = sc * (q @ k.T)
    if causal:
        logits = logits + causal_mask_bias(q.shape[0], k.shape[0])
    p = jax.nn.softmax(logits, axis=-1)
    return p @ v


__all__ = [
    "FtAttentionResult",
    "PV_SHAPE",
    "QK_SHAPE",
    "SOFTMAX_RECHECK_ROWS",
    "SOFTMAX_RESIDUAL_THRESHOLD",
    "attention_reference",
    "causal_mask_bias",
    "ft_attention",
    "make_ft_attention",
    "make_ft_attention_diff",
    "softmax_rowsum_residual",
]
