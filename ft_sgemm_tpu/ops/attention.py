"""Fault-tolerant attention: ABFT-protected ``softmax(Q K^T / sqrt(d)) V``.

A capability extension beyond the reference (which is a pure GEMM study —
SURVEY.md §5 notes it has no attention or sequence dimension), built the way
the retrieved ABFT-for-attention literature prescribes (PAPERS.md: "Custom
Algorithm-based Fault Tolerance for Attention Layers in Transformers"): the
two GEMMs inside attention are where the FLOPs and the silent-data-corruption
exposure are, and each is protected by the framework's fused-ABFT kernels —
faults in either accumulator are detected and corrected in-kernel, so they
never reach the softmax or the output.

The softmax stage itself is elementwise VPU work that linear checksums cannot
cover. It carries its own *algebraic invariant* instead: every row of
``P = softmax(S)`` sums to exactly 1, so ``max_i |1 - sum_j P[i, j]|`` is a
zero-FLOP detection residual for the normalization stage (detect-only — a
flagged row has no redundancy to reconstruct from; re-run the row). This is
the attention analog of the reference's checksum residual test.

GEMM shape mapping (the framework's kernels compute ``A @ B^T``):

  S = Q K^T            ->  ft_sgemm(a=Q (L, d),  b=K (Lk, d))
  O = P V              ->  ft_sgemm(a=P (L, Lk), b=V^T (dv, Lk))

``scale`` is applied OUTSIDE the first kernel (not as its alpha): the ABFT
residual check then sees the unscaled ``Q K^T`` accumulator, so fault
magnitudes compare against the detection threshold undamped — a 1e4 fault
stays 1e4 at the check, rather than 1e4/sqrt(d).

Multi-head / batched use: ``jax.vmap`` over the leading axis.
"""

from __future__ import annotations

import math
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from ft_sgemm_tpu.configs import KernelShape
from ft_sgemm_tpu.injection import InjectionSpec, REFERENCE_THRESHOLD
from ft_sgemm_tpu.ops.ft_sgemm import make_ft_sgemm

# Attention-tuned tiles. QK^T contracts over the head dim (64-256): a
# shallow-K tile avoids padding the contraction several-fold. P@V contracts
# over the (long) key sequence with a narrow output (dv columns): K-deep,
# bn-minimal. Explicit KernelShape objects are used as-is (no auto-shrink);
# small problems pad up to these tiles — pass smaller shapes to tune.
QK_SHAPE = KernelShape("attn_qk", 256, 256, 128, (0,) * 7)
PV_SHAPE = KernelShape("attn_pv", 256, 128, 512, (0,) * 7)

# Clean-run |1 - rowsum(softmax)| is a few f32 ulps (observed < 1e-6 at
# Lk = 4096); 1e-3 sits ~3 orders above the noise floor and far below any
# fault that could meaningfully skew a probability row.
SOFTMAX_RESIDUAL_THRESHOLD = 1e-3


class FtAttentionResult(NamedTuple):
    """Output of a fault-tolerant attention call.

    ``detections`` counts corrected accumulator faults across both GEMMs;
    ``softmax_flags`` counts rows whose softmax normalization invariant
    (rowsum == 1) broke — detect-only, 0 on clean runs.
    ``uncorrectable`` aggregates the GEMMs' residual-after-correct
    re-checks (``FtSgemmResult.uncorrectable``): nonzero means a
    correction assumption broke inside a protected GEMM and the output may
    still carry the fault — reported, never silent.
    """

    out: jax.Array            # (L, dv)
    detections: jax.Array     # scalar int32 — corrected GEMM faults
    softmax_flags: jax.Array  # scalar int32 — flagged softmax rows
    uncorrectable: jax.Array  # scalar int32 — unverified GEMM intervals

    @property
    def num_detected(self):
        return self.detections


def softmax_rowsum_residual(p) -> jax.Array:
    """Max |1 - rowsum(p)|: the softmax normalization invariant residual."""
    return jnp.max(jnp.abs(1.0 - jnp.sum(p, axis=-1)))


def _check_causal_lengths(lq: int, lk: int) -> None:
    """Causal masking needs ``lq <= lk`` (end-aligned positions): leading
    query rows would otherwise attend to zero keys and their softmax is
    undefined. Shared by the single-device and ring paths."""
    if lq > lk:
        raise ValueError(
            f"causal attention needs L_q ({lq}) <= L_k ({lk}): leading"
            " queries would attend to zero keys")


def causal_mask_bias(lq: int, lk: int) -> jax.Array:
    """(lq, lk) additive bias: 0 where query may attend, -inf above the
    causal diagonal. Positions align at the sequence END (the decoding
    convention): query row i sits at key position ``i + (lk - lq)``."""
    _check_causal_lengths(lq, lk)
    qpos = jnp.arange(lq)[:, None] + (lk - lq)
    kpos = jnp.arange(lk)[None, :]
    return jnp.where(kpos <= qpos, 0.0, -jnp.inf).astype(jnp.float32)


def _ft_attention_forward(qk, pv, q, k, v, inject, scale, causal,
                          softmax_threshold):
    """The ONE protected-attention forward, shared by the plain and
    differentiable factories: QK kernel -> scale -> (causal mask) ->
    softmax + rowsum invariant -> PV kernel. Returns
    ``(FtAttentionResult, p, sc)`` — callers that don't need the counts or
    the probabilities just drop them (XLA prunes unused outputs)."""
    if causal:
        # Validate BEFORE launching any kernel work.
        _check_causal_lengths(q.shape[0], k.shape[0])
    sc = (1.0 / math.sqrt(q.shape[-1])) if scale is None else scale
    zs = jnp.zeros((q.shape[0], k.shape[0]), jnp.float32)
    s = qk(q, k, zs, inject)
    logits = sc * s.c
    if causal:
        logits = logits + causal_mask_bias(q.shape[0], k.shape[0])
    p = jax.nn.softmax(logits, axis=-1)
    flags = jnp.sum(
        (jnp.abs(1.0 - jnp.sum(p, axis=-1)) > softmax_threshold)
        .astype(jnp.int32))
    zo = jnp.zeros((q.shape[0], v.shape[1]), jnp.float32)
    o = pv(p, jnp.swapaxes(v, 0, 1), zo, inject)
    det = (jnp.sum(s.detections) + jnp.sum(o.detections)).astype(jnp.int32)
    unc = jnp.sum(s.uncorrectable) + jnp.sum(o.uncorrectable)
    return FtAttentionResult(o.c, det, flags, unc), p, sc


def make_ft_attention(
    *,
    scale: Optional[float] = None,
    causal: bool = False,
    strategy: str = "weighted",
    threshold: float | str = REFERENCE_THRESHOLD,
    softmax_threshold: float = SOFTMAX_RESIDUAL_THRESHOLD,
    qk_shape: KernelShape = QK_SHAPE,
    pv_shape: KernelShape = PV_SHAPE,
    in_dtype: str = "float32",
    interpret: Optional[bool] = None,
):
    """Build ``fn(q, k, v, inject=None) -> FtAttentionResult``.

    ``q`` (L, d), ``k`` (Lk, d), ``v`` (Lk, dv); any sizes (kernels pad).
    ``scale`` defaults to 1/sqrt(d). ``causal=True`` applies the decoder
    mask (end-aligned positions) AFTER the QK kernel's detect/correct, so
    faults landing at masked positions are still corrected in-kernel before
    the mask zeroes their influence. ``inject`` drives BOTH protected GEMMs
    (fault counts add). ``threshold="auto"`` calibrates each GEMM to its
    own operands per call (P's probability-scale entries get their own
    floor, far below Q/K's). Default strategy is ``weighted``: at its deferred
    single-check cadence the FT GEMM hot loop is identical to the plain
    kernel's (see ops/ft_sgemm.py), so protected attention costs ~one extra
    detect/correct pass per GEMM.
    """
    qk = make_ft_sgemm(qk_shape, alpha=1.0, beta=0.0, strategy=strategy,
                       threshold=threshold, in_dtype=in_dtype,
                       interpret=interpret)
    pv = make_ft_sgemm(pv_shape, alpha=1.0, beta=0.0, strategy=strategy,
                       threshold=threshold, in_dtype=in_dtype,
                       interpret=interpret)

    def fn(q, k, v, inject: Optional[InjectionSpec] = None) -> FtAttentionResult:
        res, _, _ = _ft_attention_forward(
            qk, pv, q, k, v, inject, scale, causal, softmax_threshold)
        return res

    fn.strategy = strategy
    fn.in_dtype = in_dtype
    fn.causal = causal
    return fn


def ft_attention(q, k, v, *, inject: Optional[InjectionSpec] = None,
                 **kwargs) -> FtAttentionResult:
    """One-shot fault-tolerant attention (see :func:`make_ft_attention`)."""
    return make_ft_attention(**kwargs)(q, k, v, inject)


def make_ft_attention_diff(
    *,
    scale: Optional[float] = None,
    causal: bool = False,
    strategy: str = "weighted",
    threshold: float | str = REFERENCE_THRESHOLD,
    bwd_threshold: Optional[float | str] = None,
    inject: Optional[InjectionSpec] = None,
    inject_bwd: Optional[InjectionSpec] = None,
    qk_shape: KernelShape = QK_SHAPE,
    pv_shape: KernelShape = PV_SHAPE,
    in_dtype: str = "float32",
    interpret: Optional[bool] = None,
    with_counts: bool = False,
    with_bwd_counts: bool = False,
    softmax_threshold: float = SOFTMAX_RESIDUAL_THRESHOLD,
):
    """Differentiable FT attention: ABFT on all six GEMMs of fwd + bwd.

    Returns ``fn(q, k, v) -> (L, dv)`` as a ``jax.custom_vjp``. Forward
    runs the two protected GEMMs of :func:`make_ft_attention`; backward
    runs the four attention-gradient GEMMs through FT kernels too:

        dV = Pᵀ g      dP = g Vᵀ
        dS = P ⊙ (dP − rowsum(dP ⊙ P)) · scale     (softmax bwd, VPU)
        dQ = dS K      dK = dSᵀ Q

    ``with_counts=True`` makes the function return the full
    :class:`FtAttentionResult` pytree instead of the bare output array:
    gradients flow through ``.out`` while the int32 ``detections`` (both
    forward GEMMs) and ``softmax_flags`` (normalization-stage rowsum
    invariant, same as :func:`make_ft_attention`) leaves take zero
    cotangents — so a training loop can log fault activity every step.

    ``with_bwd_counts=True`` adds a trailing ``bwd_sink`` argument —
    ``fn(q, k, v, bwd_sink)``, any (2,) f32 array — whose GRADIENT is
    ``[detections, uncorrectable]`` summed over the four backward GEMMs:
    the gradient side-channel of ``ops.autodiff`` (its module docstring
    has the mechanism), surfacing the backward pass's fault report to
    the caller of ``jax.grad``. The four backward GEMMs are
    ABFT-corrected in-kernel either way (this factory requires a
    correcting strategy); the elementwise softmax forward/backward
    stages remain the only unprotected compute.

    ``bwd_threshold`` tightens the gradient GEMMs' detection threshold —
    cotangents usually live far below activation scale (see
    ops/autodiff.py). ``inject`` is static at build time and drives all
    six GEMMs; ``inject_bwd`` overrides the schedule for the four
    backward GEMMs alone (tests can corrupt exactly the backward pass).
    """
    if strategy == "global":
        raise ValueError(
            "make_ft_attention_diff requires a CORRECTING strategy: "
            "'global' only detects — a detect-only backward fault would "
            "be shipped into gradients/optimizer state (with_bwd_counts "
            "can report it but nothing corrects it). Pick 'rowcol' or "
            "'weighted', or use make_ft_attention for detect-only runs.")
    inj = inject or InjectionSpec.none()
    inj_b = inj if inject_bwd is None else inject_bwd
    bthr = threshold if bwd_threshold is None else bwd_threshold
    mk = lambda shp, thr: make_ft_sgemm(  # noqa: E731
        shp, alpha=1.0, beta=0.0, strategy=strategy, threshold=thr,
        in_dtype=in_dtype, interpret=interpret)
    qk = mk(qk_shape, threshold)
    pv = mk(pv_shape, threshold)
    # Long-contraction grads (dV, dQ, dK) share pv's profile; the
    # short-contraction dP shares qk's. Reuse the forward kernels when the
    # backward threshold is unchanged.
    b_long = pv if bthr == threshold else mk(pv_shape, bthr)
    b_short = qk if bthr == threshold else mk(qk_shape, bthr)

    def _fwd_parts(q, k, v):
        res, p, sc = _ft_attention_forward(
            qk, pv, q, k, v, inj, scale, causal, softmax_threshold)
        return (res if with_counts else res.out), p, sc

    def _bwd_products(res, g):
        q, k, v, p, sc = res
        if with_counts:
            # Cotangent mirrors the FtAttentionResult pytree; the integer
            # counts leaves carry zero (float0) cotangents. Index
            # positionally: the container may arrive as a plain tuple.
            g = g[0]
        lq, lk = p.shape
        dv_z = jnp.zeros((lk, v.shape[1]), jnp.float32)
        dp_z = jnp.zeros((lq, lk), jnp.float32)
        dq_z = jnp.zeros((lq, q.shape[1]), jnp.float32)
        dk_z = jnp.zeros((lk, k.shape[1]), jnp.float32)
        pt = jnp.swapaxes(p, 0, 1)
        # dV = P^T g: contract over L_q -> kernel(a=P^T (Lk, L), b=g^T).
        rv = b_long(pt, jnp.swapaxes(g, 0, 1), dv_z, inj_b)
        # dP = g V^T: contract over dv -> kernel(a=g, b=V (Lk, dv)).
        rp = b_short(g, v, dp_z, inj_b)
        # Softmax backward (elementwise; masked entries have p == 0).
        ds = p * (rp.c - jnp.sum(rp.c * p, axis=-1, keepdims=True)) * sc
        # dQ = dS K: contract over L_k -> kernel(a=dS, b=K^T (d, Lk)).
        rq = b_long(ds, jnp.swapaxes(k, 0, 1), dq_z, inj_b)
        # dK = dS^T Q: contract over L_q.
        rk = b_long(jnp.swapaxes(ds, 0, 1), jnp.swapaxes(q, 0, 1),
                    dk_z, inj_b)
        grads = (rq.c.astype(q.dtype), rk.c.astype(k.dtype),
                 rv.c.astype(v.dtype))
        return grads, (rv, rp, rq, rk)

    if not with_bwd_counts:
        @jax.custom_vjp
        def att(q, k, v):
            return _fwd_parts(q, k, v)[0]

        def fwd_fn(q, k, v):
            o, p, sc = _fwd_parts(q, k, v)
            return o, (q, k, v, p, sc)

        def bwd_fn(res, g):
            return _bwd_products(res, g)[0]

        att.defvjp(fwd_fn, bwd_fn)
        return att

    @jax.custom_vjp
    def att_sink(q, k, v, bwd_sink):
        # Sink VALUE unused; only its custom gradient carries information.
        return _fwd_parts(q, k, v)[0]

    def fwd_s(q, k, v, bwd_sink):
        o, p, sc = _fwd_parts(q, k, v)
        return o, (q, k, v, p, sc)

    def bwd_s(res, g):
        grads, (rv, rp, rq, rk) = _bwd_products(res, g)
        dsink = jnp.stack([
            sum(jnp.sum(r.detections) for r in (rv, rp, rq, rk))
            .astype(jnp.float32),
            sum(jnp.sum(r.uncorrectable) for r in (rv, rp, rq, rk))
            .astype(jnp.float32)])
        return grads + (dsink,)

    att_sink.defvjp(fwd_s, bwd_s)
    return att_sink


def attention_reference(q, k, v, *, scale: Optional[float] = None,
                        causal: bool = False,
                        in_dtype: str = "float32") -> jax.Array:
    """Plain XLA attention oracle for differential tests.

    Inputs are rounded to ``in_dtype`` like the kernel path, but the
    intermediate ``P = softmax(S)`` stays f32 here while the bf16 kernel
    path rounds P once more feeding the PV GEMM — so bf16 comparisons
    carry ~1e-2 relative P-rounding noise on top of input rounding (tests
    use a correspondingly looser tolerance).
    """
    dt = jnp.dtype(in_dtype)
    q = jnp.asarray(q, dt).astype(jnp.float32)
    k = jnp.asarray(k, dt).astype(jnp.float32)
    v = jnp.asarray(v, dt).astype(jnp.float32)
    sc = (1.0 / math.sqrt(q.shape[-1])) if scale is None else scale
    logits = sc * (q @ k.T)
    if causal:
        logits = logits + causal_mask_bias(q.shape[0], k.shape[0])
    p = jax.nn.softmax(logits, axis=-1)
    return p @ v


__all__ = [
    "FtAttentionResult",
    "PV_SHAPE",
    "QK_SHAPE",
    "SOFTMAX_RESIDUAL_THRESHOLD",
    "attention_reference",
    "causal_mask_bias",
    "ft_attention",
    "make_ft_attention",
    "make_ft_attention_diff",
    "softmax_rowsum_residual",
]
