"""Non-fused (two-pass) ABFT baseline, built from plain XLA ops.

Re-design of the reference's cuBLAS-composed baseline
(``kernel/ft_sgemm/include/baseline_ft_sgemm.cuh:1-33``): per 256-wide
K-panel it (1) applies the panel's partial product to C, then (2) makes a
*second pass* over C to recompute its row/column sums and compares them with
checksums derived from the panel inputs. The second pass over the full C is
exactly why this loses to the fused kernels — each panel re-reads the M x N
output from HBM (reference: 6 ``cublasSgemv`` + ``cublasSaxpy``/``Sdot``
calls with device syncs between them, ``baseline_ft_sgemm.cuh:7-31``).

Detection-only, like the reference baseline: it reports residuals and a
detected flag, it does not correct.

Fault injection is supported as a first-class parameter (the fused kernels
and this baseline share the same :class:`InjectionSpec` surface): a fault of
``magnitude`` is added to one rotating element of C after the panel update
and before the checksum re-read — the silent-data-corruption window this
scheme is built to catch.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from ft_sgemm_tpu.injection import InjectionSpec, REFERENCE_THRESHOLD
from ft_sgemm_tpu.ops.common import resolve_in_dtype

PANEL_K = 256  # reference K-panel width, baseline_ft_sgemm.cuh:4


class AbftBaselineResult(NamedTuple):
    c: jax.Array            # (M, N) output, alpha*A@B.T + beta*C
    max_row_residual: jax.Array  # scalar f32: max |expected-computed| row sum
    max_col_residual: jax.Array  # scalar f32
    detected: jax.Array     # bool: any residual above threshold


def abft_baseline_sgemm(
    a,
    b,
    c,
    alpha: float = 1.0,
    beta: float = -1.5,
    *,
    inject: InjectionSpec | None = None,
    panel_k: int = PANEL_K,
    threshold: float = REFERENCE_THRESHOLD,
    precision: str = "highest",
    in_dtype: str = "float32",
) -> AbftBaselineResult:
    """Two-pass checksum-verified ``C = alpha*A@B.T + beta*C``.

    Args:
      a: (M, K) f32. b: (N, K) f32. c: (M, N) f32.
      inject: optional fault injection between pass 1 and pass 2 of each
        scheduled panel (``panel % every == 0``).
      panel_k: K-panel width (reference: 256). K is padded up to a multiple.
      in_dtype: "bfloat16" runs the panel dots on bf16-rounded A/B (f32
        accumulation); checksums are computed in f32 on the rounded values,
        so the residual noise class is unchanged — same as the fused family.
    """
    inject = inject or InjectionSpec.none()
    dt, precision = resolve_in_dtype(in_dtype, precision)
    return _abft_baseline_jit(
        a, b, c, alpha=alpha, beta=beta, panel_k=panel_k, threshold=threshold,
        precision=precision, in_dtype=dt.name, inj_enabled=inject.enabled,
        inj_every=inject.every, inj_magnitude=inject.magnitude,
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "alpha", "beta", "panel_k", "threshold", "precision", "in_dtype",
        "inj_enabled", "inj_every", "inj_magnitude",
    ),
)
def _abft_baseline_jit(
    a, b, c, *, alpha, beta, panel_k, threshold, precision, in_dtype,
    inj_enabled, inj_every, inj_magnitude,
) -> AbftBaselineResult:
    a = a.astype(jnp.dtype(in_dtype))
    b = b.astype(jnp.dtype(in_dtype))
    c = c.astype(jnp.float32)
    m, k = a.shape
    n, kb = b.shape
    assert k == kb, (a.shape, b.shape)
    prec = jax.lax.Precision(precision)

    pad = (-k) % panel_k
    if pad:
        a = jnp.pad(a, ((0, 0), (0, pad)))
        b = jnp.pad(b, ((0, 0), (0, pad)))
    num_panels = (k + pad) // panel_k

    # (P, M, panel) / (P, N, panel) panel stacks for scan.
    a_p = a.reshape(m, num_panels, panel_k).transpose(1, 0, 2)
    b_p = b.reshape(n, num_panels, panel_k).transpose(1, 0, 2)

    c0 = beta * c
    # Expected running sums start at the sums of beta*C (the baseline checks
    # full-C checksums after every panel update).
    r_exp0 = jnp.sum(c0, axis=1)  # (M,)
    c_exp0 = jnp.sum(c0, axis=0)  # (N,)

    def body(carry, ab):
        c_acc, r_exp, c_exp, max_r, max_c = carry
        p, ap, bp = ab
        # Pass 1: panel partial product applied to C.
        c_acc = c_acc + alpha * jnp.dot(
            ap, bp.T, preferred_element_type=jnp.float32, precision=prec
        )
        if inj_enabled:
            # SDC between the GEMM pass and the checksum pass: one rotating
            # element of C is corrupted before pass 2 re-reads it.
            do = (p % max(1, inj_every)) == 0
            i0 = (p * 131 + 7) % m
            j0 = (p * 61 + 3) % n
            rows = jax.lax.broadcasted_iota(jnp.int32, (m, n), 0)
            cols = jax.lax.broadcasted_iota(jnp.int32, (m, n), 1)
            hit = (rows == i0) & (cols == j0) & do
            c_acc = c_acc + jnp.where(hit, jnp.float32(inj_magnitude), 0.0)
        # Input-side checksum update (cheap matvecs; reference's
        # cublasSgemv over colsum(A_panel)/rowsum(B_panel)). f32 over the
        # (possibly bf16-rounded) panel values so residual noise stays in
        # the f32 accumulation class.
        apf = ap.astype(jnp.float32)
        bpf = bp.astype(jnp.float32)
        # HIGHEST regardless of the panel-dot precision: these operands are
        # f32 sums (not bf16-exact); DEFAULT would truncate them to bf16 on
        # TPU and inflate the residual noise floor out of the f32 class.
        hi = jax.lax.Precision("highest")
        r_exp = r_exp + alpha * jnp.dot(apf, jnp.sum(bpf, axis=0), precision=hi)
        c_exp = c_exp + alpha * jnp.dot(bpf, jnp.sum(apf, axis=0), precision=hi)
        # Pass 2: full re-read of C to recompute its checksums (this is the
        # non-fused cost the fused kernels eliminate).
        res_r = r_exp - jnp.sum(c_acc, axis=1)
        res_c = c_exp - jnp.sum(c_acc, axis=0)
        max_r = jnp.maximum(max_r, jnp.max(jnp.abs(res_r)))
        max_c = jnp.maximum(max_c, jnp.max(jnp.abs(res_c)))
        return (c_acc, r_exp, c_exp, max_r, max_c), None

    (c_out, _, _, max_r, max_c), _ = jax.lax.scan(
        body,
        (c0, r_exp0, c_exp0, jnp.float32(0), jnp.float32(0)),
        (jnp.arange(num_panels, dtype=jnp.int32), a_p, b_p),
    )
    detected = (max_r > threshold) | (max_c > threshold)
    return AbftBaselineResult(c_out, max_r, max_c, detected)
