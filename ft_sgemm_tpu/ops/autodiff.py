"""Differentiable fault-tolerant matmul: ABFT on the backward pass too.

The reference is an inference-style kernel study — nothing differentiates.
A TPU framework is expected to sit inside ``jax.grad``/``jax.jit`` training
steps, so this module provides ``ft_matmul``: a ``jax.custom_vjp`` matmul
whose forward AND backward products all run through the fused-ABFT kernels.
SDC striking any of the three GEMMs of a linear layer's step (forward
``A Bᵀ``, gradient ``g B`` and ``gᵀ A``) is detected and corrected
in-kernel before it can poison activations, gradients, or optimizer state.

Semantics: ``ft_matmul(a, b) = a @ b.T`` with ``a`` (M, K), ``b`` (N, K) —
the framework's native GEMM orientation (a linear layer with stored weight
``W`` (N, K) applied to activations ``x`` (M, K)).

  dA = g @ B      -> kernel(a=g (M, N), b=Bᵀ (K, N))
  dB = gᵀ @ A     -> kernel(a=gᵀ (N, M), b=Aᵀ (K, M))

Detection counts ARE observable in training loops: build with
``with_counts=True`` and the function returns the
:class:`FtMatmulResult` pytree ``(out, detections, uncorrectable)`` —
``jax.custom_vjp`` supports pytree primals, and the int32 counting leaves
take zero (float0) cotangents, so ``jax.grad(..., has_aux=True)`` style
losses can log corrected-fault counts (and the residual-after-correct
re-check's uncorrectable-interval count) every step while gradients flow
through ``out`` untouched. *Knowing* SDC happened is half the value of
ABFT in a training run. The counts cover the forward GEMM; the two
backward GEMMs are still ABFT-corrected in-kernel (the factories require
a correcting strategy for exactly this reason) but a custom_vjp backward
has no primal output to carry their counts through.

**Threshold scale caveat.** ABFT detection compares checksum residuals
against an ABSOLUTE threshold. Gradients are usually orders of magnitude
smaller than forward activations (mean-reduced losses scale cotangents by
1/batch), so an SDC large relative to gradient scale can still sit below
the forward-calibrated threshold and pass undetected. Two remedies:
``bwd_threshold`` sets the gradient GEMMs' threshold by hand (near the
backward pass's own noise floor), or — simpler — ``threshold="auto"``,
under which EVERY GEMM calibrates to its own operands' moments at trace
time: the backward kernels see cotangent-scale inputs and tighten
automatically, no hand-tuning (tested in
``test_auto_threshold_closes_gradient_scale_blind_spot``).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from ft_sgemm_tpu.injection import InjectionSpec, REFERENCE_THRESHOLD
from ft_sgemm_tpu.ops.ft_sgemm import make_ft_sgemm


class FtMatmulResult(NamedTuple):
    """``with_counts=True`` output of the differentiable FT matmul.

    A ``jax.custom_vjp`` primal pytree: gradients flow through ``out``;
    the int32 leaves take zero cotangents. ``uncorrectable`` is the
    forward GEMM's residual-after-correct re-check
    (``FtSgemmResult.uncorrectable``) — nonzero means REPORTED possible
    corruption, never silent.
    """

    out: jax.Array            # (M, N)
    detections: jax.Array     # scalar int32 — corrected fwd-GEMM faults
    uncorrectable: jax.Array  # scalar int32 — unverified fwd intervals


@functools.lru_cache(maxsize=64)
def _kernels(shape, strategy, threshold, in_dtype, interpret):
    fn = make_ft_sgemm(shape, alpha=1.0, beta=0.0, strategy=strategy,
                       threshold=threshold, in_dtype=in_dtype,
                       interpret=interpret)
    return fn


def make_ft_matmul(
    shape="huge",
    *,
    strategy: str = "weighted",
    threshold: float | str = REFERENCE_THRESHOLD,
    bwd_threshold: Optional[float | str] = None,
    inject: Optional[InjectionSpec] = None,
    in_dtype: str = "float32",
    interpret: Optional[bool] = None,
    with_counts: bool = False,
):
    """Build a differentiable ``fn(a, b) = a @ b.T`` with FT fwd + bwd.

    ``inject`` (static at build time) drives all three protected GEMMs —
    the self-test mode; default None runs clean. ``bwd_threshold``
    (default: ``threshold``) sets the gradient GEMMs' detection threshold
    separately — gradients live at a much smaller scale than activations,
    so a tighter backward threshold catches SDC the forward-calibrated one
    would miss (module docstring). ``threshold="auto"`` removes the
    hand-tuning entirely: every GEMM (forward and backward) calibrates to
    its own operands' moments per call. The returned function is a
    ``jax.custom_vjp``: compose freely with ``jit``/``grad``/``vmap``.

    ``with_counts=True`` changes the return value to the
    :class:`FtMatmulResult` pytree (zero cotangents on the counting
    leaves; see module docstring). The detect-only ``'global'`` strategy
    stays rejected even then: the BACKWARD GEMMs' counts have no primal
    channel, so a detect-only backward fault would be neither corrected
    nor observable — the silent configuration this guard exists to
    prevent.
    """
    if strategy == "global":
        raise ValueError(
            "make_ft_matmul requires a CORRECTING strategy: 'global' only "
            "detects, and the backward GEMMs' detection counts have no "
            "output channel under custom_vjp (with_counts covers the "
            "forward GEMM only) — backward faults would pass silently. "
            "Pick 'rowcol' or 'weighted', or use ft_sgemm directly for "
            "detect-only runs.")
    inj = inject or InjectionSpec.none()
    kern = _kernels(shape, strategy, threshold, in_dtype, interpret)
    bwd_kern = _kernels(
        shape, strategy,
        threshold if bwd_threshold is None else bwd_threshold,
        in_dtype, interpret)

    @jax.custom_vjp
    def ft_mm(a, b):
        z = jnp.zeros((a.shape[0], b.shape[0]), jnp.float32)
        r = kern(a, b, z, inj)
        if with_counts:
            return FtMatmulResult(
                r.c, jnp.sum(r.detections).astype(jnp.int32),
                jnp.sum(r.uncorrectable).astype(jnp.int32))
        return r.c

    def fwd(a, b):
        return ft_mm(a, b), (a, b)

    def bwd(res, g):
        a, b = res
        # Under with_counts the cotangent mirrors the (out, counts) pytree;
        # the int32 counts leaf carries a zero (float0) cotangent.
        gc = g[0] if with_counts else g
        zk_a = jnp.zeros((gc.shape[0], a.shape[1]), jnp.float32)
        zk_b = jnp.zeros((gc.shape[1], a.shape[1]), jnp.float32)
        # dA = g @ B: kernel contracts over the second axis of both args.
        da = bwd_kern(gc, jnp.swapaxes(b, 0, 1), zk_a, inj).c
        # dB = g^T @ A.
        db = bwd_kern(jnp.swapaxes(gc, 0, 1), jnp.swapaxes(a, 0, 1),
                      zk_b, inj).c
        return da.astype(a.dtype), db.astype(b.dtype)

    ft_mm.defvjp(fwd, bwd)
    return ft_mm


def ft_matmul(a, b, **kwargs):
    """One-shot differentiable FT matmul (see :func:`make_ft_matmul`)."""
    return make_ft_matmul(**kwargs)(a, b)


__all__ = ["FtMatmulResult", "ft_matmul", "make_ft_matmul"]
