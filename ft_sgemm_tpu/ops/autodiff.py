"""Differentiable fault-tolerant matmul: ABFT on the backward pass too.

The reference is an inference-style kernel study — nothing differentiates.
A TPU framework is expected to sit inside ``jax.grad``/``jax.jit`` training
steps, so this module provides ``ft_matmul``: a ``jax.custom_vjp`` matmul
whose forward AND backward products all run through the fused-ABFT kernels.
SDC striking any of the three GEMMs of a linear layer's step (forward
``A Bᵀ``, gradient ``g B`` and ``gᵀ A``) is detected and corrected
in-kernel before it can poison activations, gradients, or optimizer state.

Semantics: ``ft_matmul(a, b) = a @ b.T`` with ``a`` (M, K), ``b`` (N, K) —
the framework's native GEMM orientation (a linear layer with stored weight
``W`` (N, K) applied to activations ``x`` (M, K)).

  dA = g @ B      -> kernel(a=g (M, N), b=Bᵀ (K, N))
  dB = gᵀ @ A     -> kernel(a=gᵀ (N, M), b=Aᵀ (K, M))

Detection counts are not part of the differentiable value (a custom_vjp
primal must be the array the cotangent flows against); use
:func:`ft_sgemm_tpu.ft_sgemm` directly where counts must be observable.

**Threshold scale caveat.** ABFT detection compares checksum residuals
against an ABSOLUTE threshold. Gradients are usually orders of magnitude
smaller than forward activations (mean-reduced losses scale cotangents by
1/batch), so an SDC large relative to gradient scale can still sit below
the forward-calibrated threshold and pass undetected. ``bwd_threshold``
exists for exactly this: set it near the backward pass's own noise floor
(``analysis.estimate_noise_floor`` on (g, b) / (g, a) scales) to keep the
gradient GEMMs' detection as tight as the forward one's.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from ft_sgemm_tpu.injection import InjectionSpec, REFERENCE_THRESHOLD
from ft_sgemm_tpu.ops.ft_sgemm import make_ft_sgemm


@functools.lru_cache(maxsize=64)
def _kernels(shape, strategy, threshold, in_dtype, interpret):
    fn = make_ft_sgemm(shape, alpha=1.0, beta=0.0, strategy=strategy,
                       threshold=threshold, in_dtype=in_dtype,
                       interpret=interpret)
    return fn


def make_ft_matmul(
    shape="huge",
    *,
    strategy: str = "weighted",
    threshold: float = REFERENCE_THRESHOLD,
    bwd_threshold: Optional[float] = None,
    inject: Optional[InjectionSpec] = None,
    in_dtype: str = "float32",
    interpret: Optional[bool] = None,
):
    """Build a differentiable ``fn(a, b) = a @ b.T`` with FT fwd + bwd.

    ``inject`` (static at build time) drives all three protected GEMMs —
    the self-test mode; default None runs clean. ``bwd_threshold``
    (default: ``threshold``) sets the gradient GEMMs' detection threshold
    separately — gradients live at a much smaller scale than activations,
    so a tighter backward threshold catches SDC the forward-calibrated one
    would miss (module docstring). The returned function is a
    ``jax.custom_vjp``: compose freely with ``jit``/``grad``/``vmap``.
    """
    if strategy == "global":
        raise ValueError(
            "make_ft_matmul requires a CORRECTING strategy: 'global' only "
            "detects, and the differentiable API discards detection counts "
            "— faults would pass silently. Pick 'rowcol' or 'weighted', or "
            "use ft_sgemm directly for detect-only runs.")
    inj = inject or InjectionSpec.none()
    kern = _kernels(shape, strategy, threshold, in_dtype, interpret)
    bwd_kern = _kernels(
        shape, strategy,
        threshold if bwd_threshold is None else bwd_threshold,
        in_dtype, interpret)

    @jax.custom_vjp
    def ft_mm(a, b):
        z = jnp.zeros((a.shape[0], b.shape[0]), jnp.float32)
        return kern(a, b, z, inj).c

    def fwd(a, b):
        return ft_mm(a, b), (a, b)

    def bwd(res, g):
        a, b = res
        zk_a = jnp.zeros((g.shape[0], a.shape[1]), jnp.float32)
        zk_b = jnp.zeros((g.shape[1], a.shape[1]), jnp.float32)
        # dA = g @ B: kernel contracts over the second axis of both args.
        da = bwd_kern(g, jnp.swapaxes(b, 0, 1), zk_a, inj).c
        # dB = g^T @ A.
        db = bwd_kern(jnp.swapaxes(g, 0, 1), jnp.swapaxes(a, 0, 1),
                      zk_b, inj).c
        return da.astype(a.dtype), db.astype(b.dtype)

    ft_mm.defvjp(fwd, bwd)
    return ft_mm


def ft_matmul(a, b, **kwargs):
    """One-shot differentiable FT matmul (see :func:`make_ft_matmul`)."""
    return make_ft_matmul(**kwargs)(a, b)


__all__ = ["ft_matmul", "make_ft_matmul"]
