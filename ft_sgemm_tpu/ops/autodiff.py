"""Differentiable fault-tolerant matmul: ABFT on the backward pass too.

The reference is an inference-style kernel study — nothing differentiates.
A TPU framework is expected to sit inside ``jax.grad``/``jax.jit`` training
steps, so this module provides ``ft_matmul``: a ``jax.custom_vjp`` matmul
whose forward AND backward products all run through the fused-ABFT kernels.
SDC striking any of the three GEMMs of a linear layer's step (forward
``A Bᵀ``, gradient ``g B`` and ``gᵀ A``) is detected and corrected
in-kernel before it can poison activations, gradients, or optimizer state.

Semantics: ``ft_matmul(a, b) = a @ b.T`` with ``a`` (M, K), ``b`` (N, K) —
the framework's native GEMM orientation (a linear layer with stored weight
``W`` (N, K) applied to activations ``x`` (M, K)).

  dA = g @ B      -> kernel(a=g (M, N), b=Bᵀ (K, N))
  dB = gᵀ @ A     -> kernel(a=gᵀ (N, M), b=Aᵀ (K, M))

Detection counts ARE observable in training loops: build with
``with_counts=True`` and the function returns the
:class:`FtMatmulResult` pytree ``(out, detections, uncorrectable)`` —
``jax.custom_vjp`` supports pytree primals, and the int32 counting leaves
take zero (float0) cotangents, so ``jax.grad(..., has_aux=True)`` style
losses can log corrected-fault counts (and the residual-after-correct
re-check's uncorrectable-interval count) every step while gradients flow
through ``out`` untouched. *Knowing* SDC happened is half the value of
ABFT in a training run. The counts cover the forward GEMM; the two
backward GEMMs are ABFT-corrected in-kernel (the factories require a
correcting strategy for exactly this reason).

**Backward counts are observable too** (``with_bwd_counts=True``): a
custom_vjp backward has no primal output, so the backward GEMMs' counts
ride the one output channel a backward pass does have — a gradient. The
function gains a trailing ``bwd_sink`` argument (any (2,) f32 array; its
value is ignored) whose "gradient" is defined as
``[bwd_detections, bwd_uncorrectable]`` summed over both gradient GEMMs.
``jax.grad(loss, argnums=...)`` over the sink therefore surfaces the
backward pass's fault report to the caller inside a fully jitted step —
pure dataflow, no host callback, composes with jit/vmap/shard_map, and
when one sink array is threaded through several layers JAX's gradient
summation turns it into a step-level accumulator. A violated correction
assumption in dA/dB is then REPORTED, never silent, closing the training
path's last observability gap (VERDICT r3 item 4).

**Threshold scale caveat.** ABFT detection compares checksum residuals
against an ABSOLUTE threshold. Gradients are usually orders of magnitude
smaller than forward activations (mean-reduced losses scale cotangents by
1/batch), so an SDC large relative to gradient scale can still sit below
the forward-calibrated threshold and pass undetected. Two remedies:
``bwd_threshold`` sets the gradient GEMMs' threshold by hand (near the
backward pass's own noise floor), or — simpler — ``threshold="auto"``,
under which EVERY GEMM calibrates to its own operands' moments at trace
time: the backward kernels see cotangent-scale inputs and tighten
automatically, no hand-tuning (tested in
``test_auto_threshold_closes_gradient_scale_blind_spot``).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from ft_sgemm_tpu.injection import InjectionSpec, REFERENCE_THRESHOLD
from ft_sgemm_tpu.ops.ft_sgemm import make_ft_sgemm


class FtMatmulResult(NamedTuple):
    """``with_counts=True`` output of the differentiable FT matmul.

    A ``jax.custom_vjp`` primal pytree: gradients flow through ``out``;
    the int32 leaves take zero cotangents. ``uncorrectable`` is the
    forward GEMM's residual-after-correct re-check
    (``FtSgemmResult.uncorrectable``) — nonzero means REPORTED possible
    corruption, never silent.
    """

    out: jax.Array            # (M, N)
    detections: jax.Array     # scalar int32 — corrected fwd-GEMM faults
    uncorrectable: jax.Array  # scalar int32 — unverified fwd intervals


def sink_vjp(primal, fwd, bwd_core, with_bwd_counts):
    """Wrap a differentiable FT op into a ``jax.custom_vjp``, optionally
    adding the gradient side-channel's trailing ``bwd_sink`` argument —
    the ONE implementation of the channel, shared by the matmul,
    attention, and ring-attention factories (module docstring has the
    mechanism).

    ``primal(*args) -> out``; ``fwd(*args) -> (out, saved)``;
    ``bwd_core(saved, g) -> (grads_tuple, detections, uncorrectable)``
    with one grad per primal arg. Without the sink the counts are
    discarded (XLA prunes the unused reductions); with it they become the
    sink's (2,) f32 "gradient" ``[detections, uncorrectable]``.
    """
    if not with_bwd_counts:
        @jax.custom_vjp
        def fn(*args):
            return primal(*args)

        def fwd_fn(*args):
            return fwd(*args)

        def bwd_fn(saved, g):
            return bwd_core(saved, g)[0]

        fn.defvjp(fwd_fn, bwd_fn)
        return fn

    @jax.custom_vjp
    def fn_sink(*args):
        # Trailing arg is the sink; its VALUE never enters the
        # computation — only its custom gradient carries information.
        return primal(*args[:-1])

    def fwd_s(*args):
        return fwd(*args[:-1])

    def bwd_s(saved, g):
        grads, det, unc = bwd_core(saved, g)
        dsink = jnp.stack([jnp.asarray(det).astype(jnp.float32),
                           jnp.asarray(unc).astype(jnp.float32)])
        return tuple(grads) + (dsink,)

    fn_sink.defvjp(fwd_s, bwd_s)
    return fn_sink


@functools.lru_cache(maxsize=64)
def _kernels(shape, strategy, threshold, in_dtype, interpret):
    fn = make_ft_sgemm(shape, alpha=1.0, beta=0.0, strategy=strategy,
                       threshold=threshold, in_dtype=in_dtype,
                       interpret=interpret)
    return fn


def make_ft_matmul(
    shape="huge",
    *,
    strategy: str = "weighted",
    threshold: float | str = REFERENCE_THRESHOLD,
    bwd_threshold: Optional[float | str] = None,
    inject: Optional[InjectionSpec] = None,
    inject_bwd: Optional[InjectionSpec] = None,
    in_dtype: str = "float32",
    interpret: Optional[bool] = None,
    with_counts: bool = False,
    with_bwd_counts: bool = False,
):
    """Build a differentiable ``fn(a, b) = a @ b.T`` with FT fwd + bwd.

    ``inject`` (static at build time) drives all three protected GEMMs —
    the self-test mode; default None runs clean. ``inject_bwd`` overrides
    the schedule for the two GRADIENT GEMMs alone (default: same as
    ``inject``), so tests can corrupt exactly the backward pass.
    ``bwd_threshold`` (default: ``threshold``) sets the gradient GEMMs'
    detection threshold separately — gradients live at a much smaller
    scale than activations, so a tighter backward threshold catches SDC
    the forward-calibrated one would miss (module docstring).
    ``threshold="auto"`` removes the hand-tuning entirely: every GEMM
    (forward and backward) calibrates to its own operands' moments per
    call. The returned function is a ``jax.custom_vjp``: compose freely
    with ``jit``/``grad``/``vmap``.

    ``with_counts=True`` changes the return value to the
    :class:`FtMatmulResult` pytree (zero cotangents on the counting
    leaves; see module docstring).

    ``with_bwd_counts=True`` adds a trailing ``bwd_sink`` argument —
    ``fn(a, b, bwd_sink)`` with any (2,) f32 array — whose GRADIENT is
    ``[detections, uncorrectable]`` summed over the two backward GEMMs
    (the gradient side-channel; module docstring). Differentiate with
    respect to the sink to read the backward pass's fault report.

    The detect-only ``'global'`` strategy stays rejected in all modes:
    even with the sink channel reporting, a detect-only backward fault
    would be knowingly shipped into optimizer state — the correcting
    strategies fix it in-kernel instead.
    """
    if strategy == "global":
        raise ValueError(
            "make_ft_matmul requires a CORRECTING strategy: 'global' only "
            "detects — a detect-only backward fault would be shipped into "
            "gradients/optimizer state (with_bwd_counts can report it but "
            "nothing corrects it). Pick 'rowcol' or 'weighted', or use "
            "ft_sgemm directly for detect-only runs.")
    inj = inject or InjectionSpec.none()
    inj_b = inj if inject_bwd is None else inject_bwd
    kern = _kernels(shape, strategy, threshold, in_dtype, interpret)
    bwd_kern = _kernels(
        shape, strategy,
        threshold if bwd_threshold is None else bwd_threshold,
        in_dtype, interpret)

    def _fwd_out(a, b):
        z = jnp.zeros((a.shape[0], b.shape[0]), jnp.float32)
        r = kern(a, b, z, inj)
        if with_counts:
            return FtMatmulResult(
                r.c, jnp.sum(r.detections).astype(jnp.int32),
                jnp.sum(r.uncorrectable).astype(jnp.int32))
        return r.c

    def _bwd_products(a, b, g):
        # Under with_counts the cotangent mirrors the (out, counts) pytree;
        # the int32 counts leaf carries a zero (float0) cotangent.
        gc = g[0] if with_counts else g
        zk_a = jnp.zeros((gc.shape[0], a.shape[1]), jnp.float32)
        zk_b = jnp.zeros((gc.shape[1], a.shape[1]), jnp.float32)
        # dA = g @ B: kernel contracts over the second axis of both args.
        ra = bwd_kern(gc, jnp.swapaxes(b, 0, 1), zk_a, inj_b)
        # dB = g^T @ A.
        rb = bwd_kern(jnp.swapaxes(gc, 0, 1), jnp.swapaxes(a, 0, 1),
                      zk_b, inj_b)
        return ra, rb

    def bwd_core(res, g):
        a, b = res
        ra, rb = _bwd_products(a, b, g)
        det = jnp.sum(ra.detections) + jnp.sum(rb.detections)
        unc = jnp.sum(ra.uncorrectable) + jnp.sum(rb.uncorrectable)
        return (ra.c.astype(a.dtype), rb.c.astype(b.dtype)), det, unc

    return sink_vjp(_fwd_out, lambda a, b: (_fwd_out(a, b), (a, b)),
                    bwd_core, with_bwd_counts)


def ft_matmul(a, b, *args, **kwargs):
    """One-shot differentiable FT matmul (see :func:`make_ft_matmul`).

    Extra positional args pass through to the built function — with
    ``with_bwd_counts=True`` that is the ``bwd_sink`` array:
    ``ft_matmul(a, b, sink, with_bwd_counts=True)``.
    """
    return make_ft_matmul(**kwargs)(a, b, *args)


__all__ = ["FtMatmulResult", "ft_matmul", "make_ft_matmul", "sink_vjp"]
