"""N-run trend verdicts over the run ledger — the history-aware CI gate.

``perf/compare.py`` answers "is candidate B meaningfully slower than
baseline A?" for exactly two artifacts under a FIXED relative tolerance.
That tolerance is a guess; the ledger knows better. Given the run
history (``perf/ledger.py``), this module estimates a rolling-window
noise model per (measurement, platform) series — streaming
``(n, Σx, Σx²)`` moments, the PR-7 adaptive-threshold layout reused
host-side — and judges the LATEST run against its own history's noise,
the same rolling-window discipline V-ABFT (arXiv 2602.08043) applies to
detection thresholds: a threshold derived from observed variance beats
any static constant, for perf regressions exactly as for SDCs.

Verdicts extend compare.py's pairwise set to N runs:

- ``improvement`` / ``regression`` — the latest value deviates from the
  window mean beyond ``max(rel_floor, sigma·std/|mean|)`` in the
  series' goodness direction;
- ``flat`` — inside the noise band (compare.py's ``within_noise``);
- ``insufficient_data`` — fewer than ``min_runs`` non-null historical
  values (single-run windows, fresh platforms, the null r01–r05 diet).
  NEVER a failure: a thin history is a setup fact, not a regression —
  the same stance compare.py takes on ``incomparable``.

Exit-code contract (:func:`exit_code`, same as compare.py): 0 = no
regression (flat, improved, or merely insufficient data), 1 = at least
one regression verdict, 2 = the ledger could not be read at all (the
CLI maps that).

Beyond throughput/seconds series, two drift detectors run over the same
window machinery: fault-rate drift (uncorrectable-per-call creeping up
across runs — a chip or threshold going bad *between* runs, invisible
to any single run's counters) and SLO burn-rate drift from the serve
artifacts' embedded snapshots. Both flag on a z-score against the
rolling window, higher-is-worse.

Pure stdlib, no jax — CI and the bench supervisor's tooling can run it
from any process.
"""

from __future__ import annotations

import math
from typing import List, Optional

DEFAULT_WINDOW = 8
DEFAULT_MIN_RUNS = 3
DEFAULT_SIGMA = 3.0
DEFAULT_REL_FLOOR = 0.05

VERDICT_IMPROVEMENT = "improvement"
VERDICT_FLAT = "flat"
VERDICT_REGRESSION = "regression"
VERDICT_INSUFFICIENT = "insufficient_data"
VERDICTS = (VERDICT_IMPROVEMENT, VERDICT_FLAT, VERDICT_REGRESSION,
            VERDICT_INSUFFICIENT)


class Moments:
    """Streaming ``(n, sum, sumsq)`` — the PR-7 moment-accumulator
    layout (``ops/common.variance_bound_threshold`` consumes these same
    three numbers in-kernel; ``telemetry/monitor.py`` keeps them per
    device) applied to per-series run history."""

    __slots__ = ("n", "sum", "sumsq")

    def __init__(self, values=()):
        self.n = 0
        self.sum = 0.0
        self.sumsq = 0.0
        for v in values:
            self.observe(v)

    def observe(self, v: float) -> None:
        self.n += 1
        self.sum += v
        self.sumsq += v * v

    @property
    def mean(self) -> float:
        return self.sum / self.n if self.n else 0.0

    @property
    def variance(self) -> float:
        if self.n < 2:
            return 0.0
        return max(0.0, self.sumsq / self.n - self.mean ** 2)

    @property
    def std(self) -> float:
        return math.sqrt(self.variance)


def _series_key(name: str, entry: dict, ledger_mod=None) -> str:
    p = entry.get("platform") or {}
    plat = p.get("device_kind") or p.get("used") or "?"
    return f"{name}@{plat}"


def collect_series(entries) -> dict:
    """Ledger entries (append order) -> ``{series_key: {"name", "platform",
    "higher_is_better", "points": [{"run_id", "value"}]}}`` for every
    measurement, plus the ``fault_rate`` / ``slo_burn`` drift series.
    Null values stay in the points list (they are history too — a run
    that measured nothing) but never feed the noise model."""
    series: dict = {}

    def _add(name, entry, value, higher_is_better, family="measurement"):
        key = _series_key(name, entry)
        s = series.setdefault(key, {
            "name": name,
            "platform": key.split("@", 1)[1],
            "higher_is_better": higher_is_better,
            "family": family,
            "points": []})
        s["points"].append({"run_id": entry.get("run_id"),
                            "value": value})

    for e in entries:
        # A run whose headline metric exists but measured null (the
        # r02–r05 class) is a NULL POINT in that series: it keeps the
        # run count honest and makes the latest-run verdict
        # ``insufficient_data (latest_null)`` instead of silently
        # judging the previous run as if it were current.
        metric = e.get("metric")
        if (isinstance(metric, str) and e.get("value") is None
                and e.get("kind") in ("bench", "serve")
                and metric not in (e.get("measurements") or {})):
            _add(metric, e, None, higher_is_better=True)
        for name, m in sorted((e.get("measurements") or {}).items()):
            if not isinstance(m, dict):
                continue
            v = m.get("value")
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                v = None
            _add(name, e, v, bool(m.get("higher_is_better", True)))
        fc = e.get("fault_counters")
        if isinstance(fc, dict):
            calls = fc.get("calls")
            unc = fc.get("uncorrectable")
            if isinstance(calls, (int, float)) and calls > 0 \
                    and isinstance(unc, (int, float)):
                _add("fault_rate", e, float(unc) / float(calls),
                     higher_is_better=False, family="drift")
        slo = e.get("slo")
        if isinstance(slo, dict):
            burn = slo.get("burn_rate")
            if isinstance(burn, (int, float)) and not isinstance(burn, bool):
                _add("slo_burn", e, float(burn),
                     higher_is_better=False, family="drift")
    return series


def stage_seconds_history(entries, stage: str,
                          platform: str) -> List[float]:
    """Non-null wall-seconds history of ONE bench stage on ONE platform,
    in append order — the ``stage[<name>].seconds`` measurement series
    the ledger banks from every RunReport's per-stage rows.

    This is the bench supervisor's rung-budgeting input (ISSUE 13 /
    ROADMAP item 1): instead of guarding each headline rung with a flat
    deadline margin, the worker asks how long THIS stage has actually
    taken on THIS platform across the run history.
    """
    series = collect_series(entries)
    s = series.get(f"stage[{stage}].seconds@{platform}")
    if not s:
        return []
    return [p["value"] for p in s["points"]
            if isinstance(p["value"], (int, float))
            and not isinstance(p["value"], bool)]


def stage_wall_budget(entries, stage: str, platform: str, *,
                      default: Optional[float] = None,
                      sigma: float = 2.0,
                      window: int = DEFAULT_WINDOW) -> Optional[float]:
    """A wall budget for one stage: ``mean + sigma*std`` of its recent
    per-stage history (:func:`stage_seconds_history`), or ``default``
    when the series is empty.

    The budget answers "how long should I EXPECT this rung to take if I
    start it now" — the bench worker compares it against its remaining
    deadline and skips rungs that cannot finish, falling through to a
    cheaper rung instead of dying mid-measurement with nothing banked
    (the r02–r05 failure class). Conservative by construction: the
    noise term uses the same moments machinery as the verdicts, and
    callers typically floor the result at their old flat margin.
    """
    hist = stage_seconds_history(entries, stage, platform)[-window:]
    if not hist:
        return default
    mom = Moments(hist)
    return mom.mean + sigma * mom.std


def judge_series(values: List[Optional[float]], *,
                 higher_is_better: bool,
                 window: int = DEFAULT_WINDOW,
                 min_runs: int = DEFAULT_MIN_RUNS,
                 sigma: float = DEFAULT_SIGMA,
                 rel_floor: float = DEFAULT_REL_FLOOR) -> dict:
    """Judge the LAST value of a series against the rolling window of
    non-null values before it.

    Returns ``{"verdict", "latest", "window_n", "mean", "std",
    "tolerance", "delta", "reason"}`` where ``delta`` is the relative
    deviation in the GOODNESS direction (positive = better) and
    ``tolerance`` the noise band actually applied
    (``max(rel_floor, sigma·std/|mean|)``)."""
    out = {"verdict": VERDICT_INSUFFICIENT, "latest": None,
           "window_n": 0, "mean": None, "std": None,
           "tolerance": None, "delta": None, "reason": None}
    if not values:
        out["reason"] = "empty_series"
        return out
    latest = values[-1]
    out["latest"] = latest
    history = [v for v in values[:-1] if isinstance(v, (int, float))
               and not isinstance(v, bool)][-window:]
    out["window_n"] = len(history)
    if latest is None:
        out["reason"] = "latest_null"
        return out
    if len(history) < min_runs:
        out["reason"] = f"window_n={len(history)}<min_runs={min_runs}"
        return out
    mom = Moments(history)
    mean, std = mom.mean, mom.std
    out["mean"] = round(mean, 9)
    out["std"] = round(std, 9)
    if mean == 0:
        out["reason"] = "zero_window_mean"
        return out
    tol = max(rel_floor, sigma * std / abs(mean))
    out["tolerance"] = round(tol, 6)
    delta = (latest - mean) / abs(mean)
    if not higher_is_better:
        delta = -delta
    out["delta"] = round(delta, 6)
    out["verdict"] = (VERDICT_FLAT if abs(delta) <= tol
                      else VERDICT_IMPROVEMENT if delta > 0
                      else VERDICT_REGRESSION)
    return out


def trend_report(entries, *,
                 window: int = DEFAULT_WINDOW,
                 min_runs: int = DEFAULT_MIN_RUNS,
                 sigma: float = DEFAULT_SIGMA,
                 rel_floor: float = DEFAULT_REL_FLOOR) -> dict:
    """The full N-run trend view over deduplicated ledger entries.

    Returns ``{"params", "rows": [...], "counts": {verdict: n},
    "regressions": [series_keys]}``; one row per (measurement,
    platform) series carrying the window facts and verdict, drift
    series (``fault_rate``/``slo_burn``) judged by the same machinery
    and listed under the same verdict counts."""
    series = collect_series(entries)
    rows = []
    counts = {v: 0 for v in VERDICTS}
    for key in sorted(series):
        s = series[key]
        values = [p["value"] for p in s["points"]]
        j = judge_series(values, higher_is_better=s["higher_is_better"],
                         window=window, min_runs=min_runs, sigma=sigma,
                         rel_floor=rel_floor)
        row = {"series": key, "name": s["name"],
               "platform": s["platform"], "family": s["family"],
               "runs": len(s["points"]),
               "latest_run": (s["points"][-1]["run_id"]
                              if s["points"] else None), **j}
        counts[row["verdict"]] += 1
        rows.append(row)
    return {
        "params": {"window": window, "min_runs": min_runs,
                   "sigma": sigma, "rel_floor": rel_floor},
        "rows": rows,
        "counts": counts,
        "regressions": [r["series"] for r in rows
                        if r["verdict"] == VERDICT_REGRESSION],
    }


def exit_code(report: dict) -> int:
    """0 = no regression verdicts (flat / improved / insufficient-data
    all pass — compare.py's exit contract); 1 = at least one."""
    return 1 if report["counts"][VERDICT_REGRESSION] else 0


def format_trend(report: dict) -> str:
    """Human rendering: one line per series — latest vs window mean,
    the noise band applied, and the verdict."""
    p = report["params"]
    lines = [f"trend (window={p['window']}, min_runs={p['min_runs']}, "
             f"sigma={p['sigma']}, floor=±{100 * p['rel_floor']:.0f}%)"]
    width = max((len(r["series"]) for r in report["rows"]), default=6)

    def num(v):
        return "—" if v is None else f"{v:.6g}"

    for r in report["rows"]:
        band = (f" ±{100 * r['tolerance']:.1f}%"
                if r.get("tolerance") is not None else "")
        delta = (f"  {100 * r['delta']:+.1f}%"
                 if r.get("delta") is not None else "")
        reason = f"  ({r['reason']})" if r.get("reason") else ""
        lines.append(
            f"  {r['series']:<{width}}  {num(r.get('mean')):>12}{band} "
            f"-> {num(r.get('latest')):>12}  "
            f"[n={r['window_n']}] {r['verdict']}{delta}{reason}")
    c = report["counts"]
    lines.append("verdicts: " + "  ".join(
        f"{k}={c[k]}" for k in VERDICTS if c[k]))
    if not report["rows"]:
        lines.append("no series found in the ledger")
    return "\n".join(lines)


__all__ = ["DEFAULT_MIN_RUNS", "DEFAULT_REL_FLOOR", "DEFAULT_SIGMA",
           "DEFAULT_WINDOW", "Moments", "VERDICTS", "VERDICT_FLAT",
           "VERDICT_IMPROVEMENT", "VERDICT_INSUFFICIENT",
           "VERDICT_REGRESSION", "collect_series", "exit_code",
           "format_trend", "judge_series", "trend_report"]
