"""Wall-clock phase attribution: where a run's time actually went.

Five rounds of the headline bench died on the supervisor deadline at the
4096 ``abft_kernel_huge`` stage, and the PR-4/5 timelines say *where*
the wall went but not *why*: "almost certainly XLA compile" stayed a
guess because no layer rolled the streamed spans up into compile vs
execute vs everything-else fractions. This module is that rollup. It
consumes a :func:`ft_sgemm_tpu.telemetry.timeline.summarize_timeline`
summary — whose stage spans now carry the ``compile_seconds`` /
``execute_seconds`` split that ``utils.timing.bench_seconds_per_call``
measures via the explicit ``lower()``/``.compile()`` separation — and
attributes every attributed second to one of the phase buckets:

    import        the jax import itself (``import_jax`` compile spans)
    backend_init  device discovery / PJRT plugin init (the tunnel killer)
    compile       lower + XLA/Mosaic compile wall (incl. cache retrieval)
    tune          autotuner search spans
    transfer      host->device input staging (``device_put_inputs``)
    execute       measured device execution
    other         wall the spans don't explain (scheduling, emit, gaps)

Fractions are guaranteed to sum to <= 1: unattributed wall lands in the
explicit ``other`` bucket, and if spans overlap (double-booked wall) the
denominator grows to the attributed total instead of letting a fraction
exceed 1. Surfaced in ``cli timeline --phases``, the RunReport "Wall
attribution" section, and — when telemetry is enabled — ``wall.*``
registry series.

Pure stdlib, no jax: readers and renderers (including the jax-free bench
supervisor's tooling) can import this from any process.
"""

from __future__ import annotations

from typing import Optional

PHASES = ("import", "backend_init", "compile", "tune", "transfer",
          "execute", "other")

# Span names (of kind="compile") that are really their own phase: the
# bench worker streams the jax import and the backend probe as compile
# spans so they land on the timeline even when no kernel compiles.
_COMPILE_NAME_PHASES = {
    "import_jax": "import",
    "backend_init": "backend_init",
    # Cache setup is bookkeeping, not XLA compile wall.
    "compile_cache_setup": "other",
}

# Stage names that are pure host->device staging, not measurement.
_TRANSFER_STAGES = ("device_put_inputs",)


def span_phase_seconds(span: dict) -> dict:
    """One completed span -> ``{phase: seconds}``.

    Envelope spans (``kind="attempt"``) attribute nothing — they bracket
    the leaf spans that do. A stage span with a recorded
    compile/execute split is decomposed (clamped so the parts never
    exceed the span); one without a split is all ``execute`` (it was
    measured device work as far as the timeline knows).
    """
    kind = span.get("kind")
    name = span.get("name") or ""
    sec = span.get("seconds")
    if not isinstance(sec, (int, float)) or sec <= 0:
        return {}
    if kind == "attempt":
        return {}
    if kind == "compile":
        return {_COMPILE_NAME_PHASES.get(name, "compile"): float(sec)}
    if kind == "tune" or name.startswith("tune"):
        return {"tune": float(sec)}
    if kind == "stage":
        if name in _TRANSFER_STAGES:
            return {"transfer": float(sec)}
        comp = span.get("compile_seconds")
        if isinstance(comp, (int, float)):
            comp = min(max(float(comp), 0.0), float(sec))
            lower = span.get("lower_seconds")
            if isinstance(lower, (int, float)):
                # Tracing/lowering is compile-side wall too.
                comp = min(comp + max(float(lower), 0.0), float(sec))
            ex = span.get("execute_seconds")
            if isinstance(ex, (int, float)):
                ex = min(max(float(ex), 0.0), float(sec) - comp)
            else:
                ex = float(sec) - comp
            out = {"compile": comp, "execute": ex}
            rest = float(sec) - comp - ex
            if rest > 1e-9:
                out["other"] = rest
            return out
        return {"execute": float(sec)}
    return {"other": float(sec)}


def _drop_double_counted(spans: list) -> list:
    """Filter spans that envelop other spans in the list.

    The bench worker nests each headline-ladder rung span
    (``ft_headline[...]``) inside the outer ``ft_headline`` span;
    attributing both would double-book the rung wall. When rung spans
    are present the envelope is dropped and the rungs attribute.
    """
    has_rungs = any(isinstance(s.get("name"), str)
                    and s["name"].startswith("ft_headline[")
                    for s in spans)
    if not has_rungs:
        return spans
    return [s for s in spans if s.get("name") != "ft_headline"]


def attribute_wall(summary: dict,
                   wall_seconds: Optional[float] = None) -> dict:
    """Roll a timeline summary up into per-phase seconds and fractions.

    Returns::

        {"wall_seconds": float|None,
         "seconds":   {phase: float},   # every phase present, 0.0 incl.
         "fractions": {phase: float}}   # sum <= 1.0 by construction

    ``wall_seconds`` overrides the summary's own ``wall_seconds`` (e.g.
    a supervisor that knows the true run wall including pre-import
    time). Unattributed wall is the explicit ``other`` bucket; if the
    spans overlap past the wall (double-booked time), the attributed
    total becomes the denominator so no fraction can exceed 1.
    """
    spans = _drop_double_counted(list(summary.get("spans") or []))
    seconds = {p: 0.0 for p in PHASES}
    for span in spans:
        for phase, sec in span_phase_seconds(span).items():
            seconds[phase] += sec
    attributed = sum(seconds.values())
    wall = wall_seconds if wall_seconds is not None \
        else summary.get("wall_seconds")
    if isinstance(wall, (int, float)) and wall > 0:
        gap = float(wall) - attributed
        if gap > 0:
            seconds["other"] += gap
            denom = float(wall)
        else:
            denom = attributed  # overlapping spans: never report > 100%
    else:
        wall = attributed if attributed > 0 else None
        denom = attributed
    # Floor (not round) to 4 places: independently ROUNDING each phase
    # can push the reported sum to 1.0001, breaking the sum<=1 contract
    # the tests pin; flooring can only lose <=1e-4 per phase.
    fractions = {p: (int(seconds[p] / denom * 10000) / 10000.0
                     if denom else 0.0)
                 for p in PHASES}
    return {
        "wall_seconds": round(float(wall), 3) if wall else None,
        "seconds": {p: round(v, 3) for p, v in seconds.items()},
        "fractions": fractions,
    }


def format_wall(attribution: dict) -> str:
    """Human rendering: one line per phase, largest-share first."""
    wall = attribution.get("wall_seconds")
    lines = ["wall attribution"
             + (f" ({wall:.1f}s wall)" if isinstance(wall, (int, float))
                else "")]
    seconds = attribution.get("seconds") or {}
    fractions = attribution.get("fractions") or {}
    for phase in sorted(PHASES, key=lambda p: -seconds.get(p, 0.0)):
        sec = seconds.get(phase, 0.0)
        if sec <= 0:
            continue
        frac = fractions.get(phase, 0.0)
        lines.append(f"  {phase:<12s} {100 * frac:5.1f}%  {sec:8.2f}s")
    if len(lines) == 1:
        lines.append("  (no attributable spans)")
    return "\n".join(lines)


def record_wall(attribution: dict, registry=None) -> None:
    """Mirror one attribution into the telemetry registry as ``wall.*``
    gauges (``wall.<phase>_seconds`` / ``wall.<phase>_fraction``), the
    subsystem's usual explicit-registry-or-enabled convention. No-op —
    never an exception — when telemetry is off and no registry given."""
    try:
        if registry is None:
            from ft_sgemm_tpu import telemetry

            if not telemetry.enabled():
                return
            registry = telemetry.get_registry()
        for phase in PHASES:
            sec = (attribution.get("seconds") or {}).get(phase)
            frac = (attribution.get("fractions") or {}).get(phase)
            if isinstance(sec, (int, float)):
                registry.gauge(f"wall.{phase}_seconds").set(float(sec))
            if isinstance(frac, (int, float)):
                registry.gauge(f"wall.{phase}_fraction").set(float(frac))
        wall = attribution.get("wall_seconds")
        if isinstance(wall, (int, float)):
            registry.gauge("wall.total_seconds").set(float(wall))
    except Exception:  # noqa: BLE001 — observability never kills a run
        pass


__all__ = ["PHASES", "attribute_wall", "format_wall", "record_wall",
           "span_phase_seconds"]
