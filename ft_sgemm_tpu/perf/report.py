"""RunReport: the self-describing manifest a bench artifact embeds.

A GFLOPS number without its context is unreviewable: which chip, which
jax, which code revision, did the tuner cache serve or miss, did any
fault go uncorrectable, and how close did each stage run to the
hardware roofline. :class:`RunReport` packages exactly that — an
environment manifest plus per-stage roofline rows
(:func:`~ft_sgemm_tpu.perf.roofline.roofline_summary`) — serializes to
JSON (round-trippable, schema-tagged) and renders to markdown for humans
(``python -m ft_sgemm_tpu.cli report ARTIFACT.json``).

:func:`build_manifest` degrades gracefully fact by fact: no git, no jax,
no telemetry — each contributes ``None`` rather than an exception, so a
manifest is constructible from any process state (including the bench
supervisor, which never imports jax: every jax touch here is lazy and
guarded).
"""

from __future__ import annotations

import dataclasses
import json
import os
import platform as _platform
import subprocess
import time
from typing import List, Optional

SCHEMA_VERSION = 1


def _git_rev(cwd: Optional[str] = None) -> Optional[str]:
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=cwd or os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))),
            capture_output=True, text=True, timeout=10)
        rev = out.stdout.strip()
        if out.returncode != 0 or not rev:
            return None
        dirty = subprocess.run(
            ["git", "status", "--porcelain"],
            cwd=cwd or None, capture_output=True, text=True, timeout=10)
        if dirty.returncode == 0 and dirty.stdout.strip():
            rev += "-dirty"
        return rev
    except Exception:  # noqa: BLE001 — no git is a valid environment
        return None


def _jax_facts() -> dict:
    facts = {"jax_version": None, "jaxlib_version": None,
             "backend": None, "device_kind": None, "num_devices": None}
    try:
        import jax

        facts["jax_version"] = jax.__version__
    except Exception:  # noqa: BLE001
        return facts
    try:
        import jaxlib

        facts["jaxlib_version"] = jaxlib.__version__
    except Exception:  # noqa: BLE001
        pass
    try:
        devs = jax.devices()
        facts["backend"] = jax.default_backend()
        facts["device_kind"] = getattr(devs[0], "device_kind",
                                       devs[0].platform)
        facts["num_devices"] = len(devs)
    except RuntimeError:
        # Backend init failure: version facts stand, device facts are
        # honestly absent (the bench fallback path records its own).
        pass
    return facts


def _tuner_stats() -> Optional[dict]:
    try:
        from ft_sgemm_tpu import tuner

        return dict(tuner.lookup_stats())
    except Exception:  # noqa: BLE001
        return None


def _fault_counters() -> Optional[dict]:
    try:
        from ft_sgemm_tpu import telemetry

        reg = telemetry.get_registry()
        return {"calls": reg.total("ft_calls"),
                "detections": reg.total("ft_detections"),
                "corrected": reg.total("ft_corrected"),
                "uncorrectable": reg.total("ft_uncorrectable")}
    except Exception:  # noqa: BLE001
        return None


def build_manifest(*, device_kind: Optional[str] = None,
                   platform: Optional[str] = None,
                   extra: Optional[dict] = None,
                   probe_jax: bool = True) -> dict:
    """Collect the run's environment facts, each one guarded.

    ``device_kind``/``platform`` override the live-probed values (the
    bench supervisor passes what the worker recorded; ``probe_jax=False``
    skips the live probe entirely for jax-free processes).
    """
    facts = _jax_facts() if probe_jax else {
        "jax_version": None, "jaxlib_version": None, "backend": None,
        "device_kind": None, "num_devices": None}
    if device_kind is not None:
        facts["device_kind"] = device_kind
    if platform is not None:
        facts["backend"] = platform
    manifest = {
        "schema": SCHEMA_VERSION,
        "created_unix": time.time(),
        "host_platform": _platform.platform(),
        "python_version": _platform.python_version(),
        "git_rev": _git_rev(),
        **facts,
        "tuner_cache": _tuner_stats(),
        "fault_counters": _fault_counters(),
    }
    if extra:
        manifest.update(extra)
    return manifest


def stage_row(name: str, seconds: Optional[float], *, m: int, n: int,
              k: int, in_itemsize: int = 4, dtype: str = "float32",
              block=None, strategy: Optional[str] = None,
              encode: str = "vpu", check_every=None,
              multifault: bool = False,
              device_kind: Optional[str] = None) -> dict:
    """One measured stage -> one roofline row.

    Resolves the user-facing ``(strategy, encode)`` pair to the kernel
    body that actually ran (``resolve_kernel_strategy`` — weighted+mxu is
    the fused body) so the cost decomposition matches the executed
    kernel. Imports the ops layer lazily: only callers that BUILD rows
    need jax; readers/renderers never do.
    """
    from ft_sgemm_tpu.ops.common import gemm_cost_breakdown
    from ft_sgemm_tpu.perf.roofline import roofline_summary

    kernel_strategy = None
    if strategy is not None:
        from ft_sgemm_tpu.ops.ft_sgemm import resolve_kernel_strategy

        kernel_strategy = resolve_kernel_strategy(strategy, encode)
    parts = gemm_cost_breakdown(m, n, k, in_itemsize, block=block,
                                strategy=kernel_strategy,
                                multifault=multifault,
                                check_every=check_every)
    row = roofline_summary(
        flops=(parts["flops_base"] + parts["flops_encode"]
               + parts["flops_check"]),
        bytes_accessed=(parts["bytes_base"] + parts["bytes_encode"]
                        + parts["bytes_check"]),
        seconds=seconds, device_kind=device_kind, dtype=dtype,
        breakdown=parts, name=name)
    row["problem"] = [int(m), int(n), int(k)]
    if strategy is not None:
        row["strategy"] = strategy
        row["encode"] = encode
    return row


@dataclasses.dataclass
class RunReport:
    """The manifest + per-stage roofline rows of one bench run.

    ``timeline`` optionally carries the run's wall-clock shape — the
    :func:`ft_sgemm_tpu.telemetry.timeline.summarize_timeline` dict of
    the streamed span log (per-stage wall time, in-flight work at kill
    time, heartbeat health) — so a report renders WHERE a run's time
    went, not just how fast each stage ran once measured. ``wall`` is
    the phase rollup of that same timeline
    (:func:`ft_sgemm_tpu.perf.wallclock.attribute_wall`): the
    import/backend_init/compile/tune/transfer/execute/other fractions
    the "Wall attribution" section renders. ``slo`` is a serving run's
    final SLO/error-budget + device-health snapshot
    (:meth:`ft_sgemm_tpu.telemetry.monitor.Monitor.snapshot`) — the
    "SLO" markdown section.
    """

    manifest: dict
    stages: List[dict] = dataclasses.field(default_factory=list)
    schema: int = SCHEMA_VERSION
    timeline: Optional[dict] = None
    wall: Optional[dict] = None
    slo: Optional[dict] = None
    # A serving run's cost-plane roll-up (perf/economics.py
    # ``CostLedger.snapshot()``) — the "Cost economics" section.
    economics: Optional[dict] = None

    def to_dict(self) -> dict:
        d = {"schema": self.schema, "manifest": self.manifest,
             "stages": self.stages}
        if self.timeline is not None:
            d["timeline"] = self.timeline
        if self.wall is not None:
            d["wall"] = self.wall
        if self.slo is not None:
            d["slo"] = self.slo
        if self.economics is not None:
            d["economics"] = self.economics
        return d

    def to_json(self, indent: Optional[int] = None) -> str:
        return json.dumps(self.to_dict(), indent=indent, sort_keys=True)

    @staticmethod
    def from_dict(d: dict) -> "RunReport":
        if not isinstance(d, dict) or "manifest" not in d:
            raise ValueError("not a RunReport dict (no 'manifest')")
        return RunReport(manifest=dict(d["manifest"]),
                         stages=list(d.get("stages") or []),
                         schema=int(d.get("schema", SCHEMA_VERSION)),
                         timeline=d.get("timeline"),
                         wall=d.get("wall"),
                         slo=d.get("slo"),
                         economics=d.get("economics"))

    @staticmethod
    def from_json(text: str) -> "RunReport":
        return RunReport.from_dict(json.loads(text))

    def to_markdown(self) -> str:
        """Human rendering: manifest facts, then the roofline table."""
        md = ["# Run report", "", "## Environment", ""]
        order = ("device_kind", "backend", "num_devices", "jax_version",
                 "jaxlib_version", "git_rev", "python_version",
                 "host_platform", "platform_requested", "platform_used",
                 "fallback_reason")
        seen = set(order)
        for key in order:
            if self.manifest.get(key) is not None:
                md.append(f"- **{key}**: {self.manifest[key]}")
        for key in sorted(self.manifest):
            v = self.manifest[key]
            if key in seen or key in ("schema", "stages") or v is None:
                continue
            if isinstance(v, dict):
                inner = ", ".join(f"{ik}={iv}" for ik, iv in
                                  sorted(v.items()))
                md.append(f"- **{key}**: {inner}")
            else:
                md.append(f"- **{key}**: {v}")
        if self.stages:
            md += ["", "## Roofline", ""]
            md.append("| stage | seconds | GFLOP/s | AI (flops/B) | "
                      "% peak compute | % peak HBM | bound | ABFT "
                      "overhead |")
            md.append("|---|---|---|---|---|---|---|---|")
            for row in self.stages:
                est = "~" if row.get("spec_estimated") else ""

                def pct(v, est=est):
                    return "—" if v is None else f"{est}{100 * v:.1f}%"

                def num(v, fmt="{:.4g}"):
                    return "—" if v is None else fmt.format(v)

                md.append(
                    "| {name} | {sec} | {gf} | {ai} | {pc} | {pb} | {bd} "
                    "| {ov} |".format(
                        name=row.get("name") or "?",
                        sec=num(row.get("seconds")),
                        gf=num(row.get("gflops"), "{:.1f}"),
                        ai=num(row.get("arithmetic_intensity"), "{:.1f}"),
                        pc=pct(row.get("pct_peak_compute")),
                        pb=pct(row.get("pct_peak_bandwidth")),
                        bd=row.get("bound") or "—",
                        ov=pct(row.get("abft_fraction"), est="")))
            dev = self.stages[0].get("device")
            if dev:
                note = (" (estimated placeholder spec)"
                        if self.stages[0].get("spec_estimated") else "")
                md.append("")
                md.append(f"Peaks from the `{dev}` spec entry{note}; "
                          "`AI` is arithmetic intensity, `ABFT overhead` "
                          "the checksum encode+check share of the "
                          "stage's FLOPs.")
        slo = self.slo
        if slo:
            md += ["", "## SLO", ""]
            md.append(f"- **status**: {slo.get('status', '—')}"
                      + (" (" + "; ".join(slo["reasons"]) + ")"
                         if slo.get("reasons") else ""))
            for key, label in (
                    ("budget_remaining", "error budget remaining"),
                    ("burn_rate", "burn rate"),
                    ("goodput_ratio", "goodput ratio"),
                    ("observed_p99_seconds", "observed p99 (s)"),
                    ("window_requests", "window requests"),
                    ("violations", "violations"),
                    ("device_health_min", "device health min")):
                v = slo.get(key)
                if v is not None:
                    md.append(f"- **{label}**: {v}")
            obj = slo.get("objectives") or {}
            if obj:
                md.append("- **objectives**: " + ", ".join(
                    f"{k}={v}" for k, v in sorted(obj.items())))
            dh = slo.get("device_health") or {}
            if dh:
                md += ["", "| device | health |", "|---|---|"]
                for dev in sorted(dh, key=lambda d: dh[d]):
                    md.append(f"| {dev} | {dh[dev]:.3f} |")
        econ = self.economics
        if econ:
            md += ["", "## Cost economics", ""]
            uff = econ.get("useful_flops_fraction")
            if uff is not None:
                md.append(f"- **useful flops fraction**: {uff}")
            for key, label in (
                    ("requests", "requests"),
                    ("requests_ok", "requests ok"),
                    ("flops_total", "total flops"),
                    ("tokens_correct", "tokens correct"),
                    ("tokens_correct_per_second_per_device",
                     "tokens-correct/s/device"),
                    ("devices", "devices"),
                    ("wall_seconds", "wall (s)")):
                v = econ.get(key)
                if v is not None:
                    md.append(f"- **{label}**: {v}")
            fracs = {c: v for c, v in
                     (econ.get("overhead_fractions") or {}).items()
                     if v}
            if fracs:
                md += ["", "| overhead cause | fraction of total flops |",
                       "|---|---|"]
                for cause in sorted(fracs, key=lambda c: -fracs[c]):
                    md.append(f"| {cause} | {100 * fracs[cause]:.2f}% |")
        wa = self.wall
        if wa and wa.get("fractions"):
            md += ["", "## Wall attribution", ""]
            wall = wa.get("wall_seconds")
            if wall is not None:
                md.append(f"- **wall**: {wall:.1f}s")
            md.append("")
            md.append("| phase | seconds | fraction |")
            md.append("|---|---|---|")
            secs = wa.get("seconds") or {}
            order = sorted(wa["fractions"],
                           key=lambda p: -(secs.get(p) or 0.0))
            for phase in order:
                sec = secs.get(phase)
                frac = wa["fractions"].get(phase)
                if not sec and not frac:
                    continue
                md.append(
                    f"| {phase} | "
                    + (f"{sec:.2f}" if isinstance(sec, (int, float))
                       else "—")
                    + " | "
                    + (f"{100 * frac:.1f}%"
                       if isinstance(frac, (int, float)) else "—")
                    + " |")
        tl = self.timeline
        if tl and (tl.get("spans") or tl.get("in_flight")):
            md += ["", "## Timeline", ""]
            wall = tl.get("wall_seconds")
            if wall is not None:
                md.append(f"- **wall**: {wall:.1f}s over "
                          f"{len(tl.get('spans') or [])} completed spans")
            if tl.get("killed_at_stage"):
                md.append(f"- **killed during**: {tl['killed_at_stage']}")
            if tl.get("heartbeats"):
                gap = tl.get("max_heartbeat_gap")
                md.append(f"- **heartbeats**: {tl['heartbeats']}"
                          + (f" (max gap {gap:.1f}s)"
                             if gap is not None else ""))
            md.append("")
            md.append("| span | kind | seconds | status |")
            md.append("|---|---|---|---|")
            for s in tl.get("spans") or []:
                sec = s.get("seconds")
                md.append(
                    f"| {s.get('name')} | {s.get('kind')} | "
                    + (f"{sec:.2f}" if isinstance(sec, (int, float))
                       else "—")
                    + f" | {s.get('status') or '—'} |")
            for s in tl.get("in_flight") or []:
                md.append(f"| {s.get('name')} | {s.get('kind')} | — | "
                          "in flight |")
        return "\n".join(md)


def from_artifact(artifact: dict) -> Optional[RunReport]:
    """The RunReport embedded in a bench artifact (under
    ``context.run_report``), or None."""
    try:
        d = artifact.get("context", {}).get("run_report")
        return None if d is None else RunReport.from_dict(d)
    except (AttributeError, ValueError, TypeError):
        return None


__all__ = ["RunReport", "SCHEMA_VERSION", "build_manifest",
           "from_artifact", "stage_row"]
