"""Compiled-artifact introspection: what XLA actually built.

Measured GFLOPS say how fast a kernel ran; the compiled artifact says
what the compiler did to it — how long compilation took, what XLA's own
cost model thinks the executable costs, how much device memory it
reserves, and how many ``dot``/``fusion``/``custom-call`` ops survived
optimization (the MXU-encode work of PR 3 is pinned to "exactly one
dot_general" at the jaxpr level; this module gives the same visibility
post-XLA). One :func:`introspect_jitted` call lowers + compiles the
callable once and returns a plain dict; when the telemetry subsystem is
enabled the numbers also land in the PR-1 metrics registry as
``compile.*`` and ``hlo.*`` gauge series.

Both ``cost_analysis()`` and ``memory_analysis()`` are best-effort per
backend (the CPU backend of some jaxlib builds returns nothing, TPU-ish
backends raise ``NotImplementedError`` through a tunnel): every probe is
guarded, a missing analysis is reported by name under ``unavailable``,
and the rest of the dict still fills in — graceful degradation, never an
exception out of an observability path.

jax is imported lazily inside the functions so merely importing
:mod:`ft_sgemm_tpu.perf` stays jax-free (the bench supervisor's
constraint).
"""

from __future__ import annotations

import re
import time
from typing import Optional

# cost_analysis returns a large property map on some backends; only the
# stable, scalar, cross-backend-meaningful keys are kept.
_COST_KEYS = ("flops", "transcendentals", "bytes accessed",
              "optimal_seconds", "utilization operand 0 {}",
              "utilization operand 1 {}")

_MEM_ATTRS = ("generated_code_size_in_bytes", "argument_size_in_bytes",
              "output_size_in_bytes", "temp_size_in_bytes",
              "alias_size_in_bytes", "host_generated_code_size_in_bytes",
              "host_argument_size_in_bytes", "host_output_size_in_bytes",
              "host_temp_size_in_bytes")


def _normalize_cost(cost) -> Optional[dict]:
    """cost_analysis() shapes vary by jax version: a dict, a list of
    per-computation dicts, or None. Normalize to one flat float dict."""
    if cost is None:
        return None
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else None
        if cost is None:
            return None
    if not isinstance(cost, dict):
        return None
    out = {}
    for key in _COST_KEYS:
        v = cost.get(key)
        if isinstance(v, (int, float)):
            out[key] = float(v)
    return out or None


def _normalize_memory(mem) -> Optional[dict]:
    if mem is None:
        return None
    out = {}
    for attr in _MEM_ATTRS:
        v = getattr(mem, attr, None)
        if isinstance(v, (int, float)):
            out[attr] = int(v)
    return out or None


def hlo_op_counts(hlo_text: str) -> dict:
    """Optimized-HLO op census: the fusion/dot/custom-call shape of the
    executable. Counts instruction definitions (``= <shape> op(...)``),
    not free-text mentions."""
    def count(op):
        return len(re.findall(rf"= \S+ {op}\(", hlo_text))

    return {
        "dot_general": count("dot") + count("dot_general"),
        "fusion": count("fusion"),
        "custom_call": count("custom-call"),
        "while": count("while"),
        "all_reduce": count("all-reduce"),
    }


def introspect_jitted(fn, *args, label: str = "jit",
                      registry=None, **jit_kwargs) -> dict:
    """Lower + compile ``fn(*args)`` once and report the artifact's facts.

    ``fn`` may be a plain callable (jitted here) or anything with a
    ``.lower(*args)`` (an existing ``jax.jit`` wrapper). ``args`` may be
    real arrays or ``jax.ShapeDtypeStruct``s — nothing is executed, so
    the probe costs one compile and no device run.

    Returns ``{"label", "lower_seconds", "compile_seconds",
    "cost_analysis", "memory_analysis", "hlo_counts", "unavailable"}``
    where each analysis is None (and named in ``unavailable`` with the
    reason) when the backend does not provide it. When ``registry`` is
    given — or telemetry is enabled — the scalars are mirrored into it
    as ``compile.*`` / ``hlo.*`` series labeled ``stage=<label>``.
    """
    import jax

    out = {
        "label": label,
        "lower_seconds": None,
        "compile_seconds": None,
        "cost_analysis": None,
        "memory_analysis": None,
        "hlo_counts": None,
        "unavailable": {},
    }

    jitted = fn if hasattr(fn, "lower") else jax.jit(fn, **jit_kwargs)
    try:
        t0 = time.perf_counter()
        lowered = jitted.lower(*args)
        out["lower_seconds"] = time.perf_counter() - t0
    except Exception as e:  # noqa: BLE001 — observability must not raise
        out["unavailable"]["lower"] = f"{type(e).__name__}: {e}"
        return out
    try:
        t0 = time.perf_counter()
        compiled = lowered.compile()
        out["compile_seconds"] = time.perf_counter() - t0
    except Exception as e:  # noqa: BLE001
        out["unavailable"]["compile"] = f"{type(e).__name__}: {e}"
        return out

    for probe, normalize in (("cost_analysis", _normalize_cost),
                             ("memory_analysis", _normalize_memory)):
        try:
            out[probe] = normalize(getattr(compiled, probe)())
            if out[probe] is None:
                out["unavailable"][probe] = "backend returned no data"
        except Exception as e:  # noqa: BLE001 — per-backend best effort
            out["unavailable"][probe] = f"{type(e).__name__}: {e}"
    try:
        out["hlo_counts"] = hlo_op_counts(compiled.as_text())
    except Exception as e:  # noqa: BLE001
        out["unavailable"]["hlo_text"] = f"{type(e).__name__}: {e}"

    _record(out, registry)
    return out


def _record(result: dict, registry) -> None:
    """Mirror one introspection into the telemetry registry (explicit
    registry, or the active one when telemetry is enabled; otherwise a
    no-op — the subsystem's zero-overhead-off convention)."""
    if registry is None:
        from ft_sgemm_tpu import telemetry

        if not telemetry.enabled():
            return
        registry = telemetry.get_registry()
    label = result.get("label") or "jit"
    for key in ("lower_seconds", "compile_seconds"):
        v = result.get(key)
        if v is not None:
            registry.gauge(f"compile.{key}", stage=label).set(v)
    cost = result.get("cost_analysis") or {}
    for key, series in (("flops", "hlo.flops"),
                        ("bytes accessed", "hlo.bytes_accessed")):
        if key in cost:
            registry.gauge(series, stage=label).set(cost[key])
    mem = result.get("memory_analysis") or {}
    for attr in ("generated_code_size_in_bytes", "temp_size_in_bytes",
                 "argument_size_in_bytes", "output_size_in_bytes"):
        if attr in mem:
            registry.gauge(f"hlo.{attr}", stage=label).set(mem[attr])
    counts = result.get("hlo_counts") or {}
    for op, v in counts.items():
        registry.gauge(f"hlo.{op}_count", stage=label).set(v)


__all__ = ["hlo_op_counts", "introspect_jitted"]
